"""Agreement-as-a-service: the overload-safe continuous-batched serving
front-end (ISSUE 10 tentpole).

The reference program is one caller talking to one REPL; our
``Cluster``/``JaxBackend`` inherited that shape — one campaign owns the
process.  This module is the long-lived layer that lets THOUSANDS of
concurrent callers share one process safely:

- **Continuous batching.**  Concurrent ``actual-order`` /
  ``run-rounds`` / ``scenario`` requests coalesce into the engine's
  already-padded batch dimension (the bucketed-capacity discipline that
  keeps ``sweep10k_signed`` recompile-free: rosters pad to power-of-two
  capacities, cohorts pad to power-of-two batch slots).  The engine
  entry is ``parallel.pipeline.coalesced_sweep`` — per-SLOT key
  schedules make every batched result BIT-EXACT with the same request
  run alone at equal padded capacity (the parity test is the heart of
  the PR; the coalescing is pure throughput, never a semantic change).
- **Deadline budgets.**  Every request carries a deadline; an expired
  request is cancelled BEFORE dispatch (a :class:`DeadlineExceeded`
  ticket and a ``request`` record with ``status: "expired"``), never
  after — once a cohort's carry is donated the batch completes and
  late results are still delivered (cancelling mid-donation would
  poison the cohort's shared buffers for everyone else in it).
- **Admission control + backpressure.**  The queue is BOUNDED
  (``max_queue``); an admission that cannot be honored raises
  :class:`Overloaded` with a ``retry_after_s`` hint (queue depth x the
  observed per-batch service time) instead of growing the queue — the
  service's memory is O(max_queue), whatever the fleet does.  Pressure
  is read off the signals ``obs/health.py`` already samples from the
  engine's own instruments: depth-occupancy (device saturation) and
  retire-lag p99 (service quality), plus queue occupancy.
- **Load shedding tiers** (:func:`shed_tier`): under pressure the
  service FIRST halves the coalescing window (tier 1 — dispatch
  sooner, trade batching efficiency for latency), THEN sheds
  batch-coalescable interactive work (tier 2 — ``actual-order`` /
  ``run-rounds`` rejections; long ``scenario`` campaigns, which cannot
  cheaply be re-issued, keep admitting), and only at tier 3 rejects
  everything.  Tier transitions emit ``shed`` records and the
  ``serve_shed_tier`` gauge.
- **Per-request fault isolation.**  Each coalesced batch dispatches
  through the same execution seam the supervisor uses: transient
  faults retry in place (backoff + deterministic jitter, shared with
  ``runtime/supervisor.py``); a dispatch that exhausts retries fails
  ONLY the requests in that batch slot's cohort — classified via
  ``supervisor.classify_fault`` (one fault taxonomy,
  ``supervisor.fault_attribution``) — while the dispatcher thread
  keeps serving the next cohort.

HOST-TIER BY LINT CONTRACT (ba-lint BA301, mutation-checked like obs):
this module's MODULE-LEVEL import closure never reaches
``ba_tpu.core``/``ba_tpu.ops`` — admission control, fault-plan
validation and client shaping run on hosts without jax; the engine is
reached lazily from the dispatcher thread (``_execute``), exactly the
``runtime/backends.py`` discipline.

- **Warm serving** (ISSUE 11).  ``warm=True`` (``BA_TPU_WARM=1``)
  launches a background AOT warmup pass at ``open()``
  (``runtime/warmup.py``): the cross-run ledger's signature set plus
  the cohort-key bucket lattice compile into the persistent executable
  cache (``obs/aotcache.py``, ``BA_TPU_AOT_CACHE``) off the request
  path, health-gated so warmup never sheds live traffic.  The
  dispatcher consults the cache before every cohort dispatch; a warm
  service's ``serve_compile_on_request_path_total`` stays 0 after the
  :meth:`AgreementService.warm_barrier` — the measured acceptance
  boolean — while an unwarmed cohort still serves via compile-on-miss
  (counted in ``serve_warmup_miss_total``).

- **SLO engine** (ISSUE 17).  Every terminal ``request`` record now
  carries the full lifecycle decomposition — ``queue_s`` (admitted →
  popped), ``coalesce_s`` (popped → dispatched), ``compile_s`` /
  ``dispatch_s`` (the engine's own measured walls), ``retire_lag_s``
  (retire fetch + delivery) — telescoping EXACTLY to ``wall_s``, plus
  the request's ``tenant`` label and ``cohort`` string (tenants are
  ACCOUNTING, never isolation: the cohort key is unchanged, so tenants
  coalesce together).  With a policy configured (``BA_TPU_SLO``), the
  service installs an ``obs/slo.py`` engine: request/admission records
  fold into per-(cohort, tenant) phase histograms and per-objective
  burn windows, and ``slo_report`` / ``slo_alert`` /
  ``autoscale_signal`` records ride the pressure sampler's cadence —
  the shed ladder reads the ``health_slo_burn`` gauge as a
  first-class pressure signal (``burn_soft`` / ``burn_hard`` dials).

Environment: ``BA_TPU_SERVE_BATCH`` / ``BA_TPU_SERVE_QUEUE`` /
``BA_TPU_SERVE_WINDOW_S`` / ``BA_TPU_SERVE_DEADLINE_S`` /
``BA_TPU_SERVE_RETRIES`` / ``BA_TPU_WARM`` / ``BA_TPU_SLO`` override
:meth:`ServeConfig.from_env`; ``BA_TPU_AOT_CACHE`` places (or
disables) the executable-cache directory.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import threading
import time

import numpy as np

from ba_tpu import obs
from ba_tpu.scenario.compile import compile_scenario, empty_block
from ba_tpu.utils import metrics as _metrics

# NOTE: runtime.supervisor (classification/backoff) and the engine
# (parallel.pipeline) are imported LAZILY from the dispatcher path —
# the supervisor's own lazy engine seam makes its import-graph closure
# reach the jitted trees, and this module's import-time closure is
# host-tier by lint contract (BA301, module docstring).

REQUEST_KINDS = ("actual-order", "run-rounds", "scenario")
ORDERS = ("attack", "retreat")
# Engine request tokens (ISSUE 13) — the jax-free spelling of
# parallel.pipeline's request set (this module must validate admissions
# without touching the engine; the equality is test-pinned).
ENGINE_TOKENS = ("xla", "pallas", "interpret", "auto")
# Admission outcomes the `admission` record's `reason` field may carry.
REJECT_REASONS = ("queue_full", "shed_interactive", "shed_all")

# ISSUE 17: the documented retry-after hint for a COLD service — no
# batch has completed yet, so there is no observed service rate to
# scale queue depth by.  0.1 s is one order above the default coalesce
# window and well under any deadline budget: a cold fleet retries
# promptly without hammering, instead of the old degenerate
# max(coalesce_window_s, 1 ms) hint that told a 64-deep queue to retry
# in 5 ms.
COLD_RETRY_AFTER_S = 0.1


class ServeError(RuntimeError):
    """The service could not accept or complete a request."""


class Overloaded(ServeError):
    """Admission refused: bounded queue full or load-shed.  Carries the
    backpressure contract — ``retry_after_s`` (the observed-service-rate
    hint), ``tier`` and ``reason`` — so a client can retry sanely
    instead of hammering."""

    def __init__(self, message, *, retry_after_s, tier, reason):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.tier = tier
        self.reason = reason


class DeadlineExceeded(ServeError):
    """The request's deadline budget expired before its cohort
    dispatched (expiry is always pre-dispatch — see module docstring)."""


class RequestFailed(ServeError):
    """The request's COHORT dispatch exhausted its retries; ``fault``
    is the ``supervisor.classify_fault`` classification."""

    def __init__(self, message, *, fault):
        super().__init__(message)
        self.fault = fault


def _capacity(n: int) -> int:
    """Power-of-two roster capacity, floor 4 — the exact bucketing
    ``runtime.backends.JaxBackend`` pads interactive rosters with, so
    serve cohorts reuse the same compiled specializations."""
    cap = 4
    while cap < n:
        cap *= 2
    return cap


def _batch_bucket(n: int) -> int:
    """Power-of-two batch-slot bucket: cohorts of 3 and 4 share one
    compiled batch=4 program instead of specializing per arrival
    count."""
    b = 1
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving dials.  ``from_env`` overlays the ``BA_TPU_SERVE_*``
    variables; everything validates eagerly."""

    max_batch: int = 8             # coalesced requests per dispatch
    max_queue: int = 64            # bounded admission queue
    coalesce_window_s: float = 0.005  # wait-for-cohort window (tier 0)
    default_deadline_s: float | None = 30.0  # None = no deadline
    queue_soft_frac: float = 0.5   # tier 1 queue-occupancy threshold
    queue_hard_frac: float = 0.875  # tier 2 queue-occupancy threshold
    lag_soft_s: float = 1.0        # tier 1 retire-lag p99 threshold
    lag_hard_s: float = 5.0        # tier 2 retire-lag p99 threshold
    depth: int = 2                 # engine dispatch depth per cohort
    rounds_per_dispatch: int = 8   # engine scan length per dispatch
    m: int = 1                     # recursion depth served
    max_retries: int | None = None  # None: BA_TPU_SERVE_RETRIES >
    #                                 BA_TPU_MAX_RETRIES > 3
    dispatch_timeout_s: float | None = None  # cohort watchdog; None =
    #                                 supervisor.derive_timeout_s
    #                                 (BA_TPU_SUPERVISE_TIMEOUT_S pin,
    #                                 30 s floor)
    warm: bool = False             # ISSUE 11: background AOT warmup at
    #                                 open() + warm executable dispatch
    warm_capacities: tuple = (4,)  # capacity buckets the lattice warms
    warm_rounds: int | None = None  # expected request rounds (warms the
    #                                 ragged remainder window too)
    warm_scenarios: bool = True    # also warm scenario-cohort
    #                                 specializations (kind="scenario"
    #                                 is first-class traffic; False
    #                                 halves warmup wall when the fleet
    #                                 is known interactive-only)
    warm_signed: bool = True       # ISSUE 14: also warm SIGNED-cohort
    #                                 specializations (the lattice's
    #                                 signed axis) — a fleet including
    #                                 signed cohorts keeps
    #                                 serve_compile_on_request_path_total
    #                                 at 0 after the warm barrier; False
    #                                 trims warmup wall for fleets that
    #                                 never sign
    warm_ms: tuple | None = None   # ISSUE 14: m values the lattice
    #                                 warms (None = just the config's
    #                                 `m` dial).  Per-request m joined
    #                                 the cohort key, so a fleet that
    #                                 serves m=2 EIG cohorts lists it
    #                                 here or pays one counted
    #                                 compile-on-miss per unwarmed m
    aot_cache: str | None = None   # executable-cache dir; None = the
    #                                 BA_TPU_AOT_CACHE / default dir
    engine: str = "xla"            # ISSUE 13: the service's default
    #                                 megastep engine (requests may
    #                                 override per-request); resolved
    #                                 by the engine-select seam at
    #                                 dispatch time, part of the
    #                                 cohort key so engines never
    #                                 share a batch
    slo: object = None             # ISSUE 17: SLO policy — None = no
    #                                 engine; True = obs.slo default
    #                                 policy; a path string loads a
    #                                 policy JSON; an SLOPolicy is used
    #                                 as-is (resolved at service init)
    burn_soft: float = 1.0         # tier-1 health_slo_burn threshold
    burn_hard: float = 8.0         # tier-2 health_slo_burn threshold

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch={self.max_batch} must be >= 1")
        if self.max_queue < 1:
            raise ValueError(f"max_queue={self.max_queue} must be >= 1")
        if self.coalesce_window_s < 0:
            raise ValueError(
                f"coalesce_window_s={self.coalesce_window_s} must be >= 0"
            )
        if self.default_deadline_s is not None and (
            self.default_deadline_s < 0
        ):
            raise ValueError(
                f"default_deadline_s={self.default_deadline_s} "
                f"must be >= 0"
            )
        if not 0 < self.queue_soft_frac <= self.queue_hard_frac <= 1.0:
            raise ValueError(
                f"need 0 < queue_soft_frac <= queue_hard_frac <= 1, got "
                f"{self.queue_soft_frac}/{self.queue_hard_frac}"
            )
        if not 0 < self.lag_soft_s <= self.lag_hard_s:
            raise ValueError(
                f"need 0 < lag_soft_s <= lag_hard_s, got "
                f"{self.lag_soft_s}/{self.lag_hard_s}"
            )
        if self.dispatch_timeout_s is not None and (
            self.dispatch_timeout_s <= 0
        ):
            raise ValueError(
                f"dispatch_timeout_s={self.dispatch_timeout_s} "
                f"must be > 0"
            )
        if self.warm_rounds is not None and self.warm_rounds < 1:
            raise ValueError(
                f"warm_rounds={self.warm_rounds} must be >= 1"
            )
        for cap in self.warm_capacities:
            if not isinstance(cap, int) or isinstance(cap, bool) or cap < 1:
                raise ValueError(
                    f"warm_capacities entry {cap!r} must be an int >= 1"
                )
        if self.warm_ms is not None:
            for mv in self.warm_ms:
                if not isinstance(mv, int) or isinstance(mv, bool) or (
                    mv < 1
                ):
                    raise ValueError(
                        f"warm_ms entry {mv!r} must be an int >= 1"
                    )
        if self.engine not in ENGINE_TOKENS:
            raise ValueError(
                f"engine={self.engine!r} not in {ENGINE_TOKENS}"
            )
        if self.slo is not None and not isinstance(self.slo, (bool, str)):
            # Anything else must quack like a policy (obs.slo.SLOPolicy
            # — checked structurally so this module stays import-light).
            if not hasattr(self.slo, "objectives"):
                raise ValueError(
                    f"slo={self.slo!r} must be None, a bool, a policy "
                    f"path, or an obs.slo.SLOPolicy"
                )
        if not 0 < self.burn_soft <= self.burn_hard:
            raise ValueError(
                f"need 0 < burn_soft <= burn_hard, got "
                f"{self.burn_soft}/{self.burn_hard}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        env = {}
        if "BA_TPU_SERVE_BATCH" in os.environ:
            env["max_batch"] = int(os.environ["BA_TPU_SERVE_BATCH"])
        if "BA_TPU_SERVE_QUEUE" in os.environ:
            env["max_queue"] = int(os.environ["BA_TPU_SERVE_QUEUE"])
        if "BA_TPU_SERVE_WINDOW_S" in os.environ:
            env["coalesce_window_s"] = float(
                os.environ["BA_TPU_SERVE_WINDOW_S"]
            )
        if "BA_TPU_SERVE_DEADLINE_S" in os.environ:
            raw = os.environ["BA_TPU_SERVE_DEADLINE_S"]
            env["default_deadline_s"] = None if raw == "" else float(raw)
        if "BA_TPU_WARM" in os.environ:
            env["warm"] = os.environ["BA_TPU_WARM"] not in ("", "0")
        if os.environ.get("BA_TPU_ENGINE"):
            env["engine"] = os.environ["BA_TPU_ENGINE"]
        if "BA_TPU_SLO" in os.environ:
            raw = os.environ["BA_TPU_SLO"]
            # "" / "0" off, "1" default policy, anything else a path.
            env["slo"] = (
                None if raw in ("", "0") else True if raw == "1" else raw
            )
        env.update(overrides)
        return cls(**env)

    def resolved_max_retries(self) -> int:
        if self.max_retries is not None:
            return self.max_retries
        return int(
            os.environ.get(
                "BA_TPU_SERVE_RETRIES",
                os.environ.get("BA_TPU_MAX_RETRIES", 3),
            )
        )


def shed_tier(
    queue_frac, lag_p99_s, occupancy, config: ServeConfig, burn=None
) -> int:
    """The load-shedding tier from the pressure signals (pure, pinned
    by unit tests):

    - tier 3 — queue full: reject everything;
    - tier 2 — queue past ``queue_hard_frac``, retire-lag p99 past
      ``lag_hard_s`` (inf — the overflow bucket — counts), or the SLO
      gate burn rate (ISSUE 17: the ``health_slo_burn`` gauge an
      installed ``obs/slo.py`` engine maintains) past ``burn_hard``:
      shed interactive work, keep admitting campaigns;
    - tier 1 — queue past ``queue_soft_frac``, lag past ``lag_soft_s``,
      burn past ``burn_soft``, or the engine's depth-occupancy at/over
      the configured depth (every pipeline slot full — the device is
      saturated): halve the coalescing window, admit everything;
    - tier 0 — healthy.

    ``lag_p99_s``/``occupancy``/``burn`` are sampled signals and may be
    None (no window yet, no SLO engine) — absent signals never raise
    the tier.
    """
    if queue_frac >= 1.0:
        return 3
    lag_hard = lag_p99_s is not None and lag_p99_s >= config.lag_hard_s
    burn_hard = burn is not None and burn >= config.burn_hard
    if queue_frac >= config.queue_hard_frac or lag_hard or burn_hard:
        return 2
    lag_soft = lag_p99_s is not None and lag_p99_s >= config.lag_soft_s
    burn_soft = burn is not None and burn >= config.burn_soft
    saturated = occupancy is not None and occupancy >= config.depth
    if (
        queue_frac >= config.queue_soft_frac
        or lag_soft
        or burn_soft
        or saturated
    ):
        return 1
    return 0


@dataclasses.dataclass(frozen=True)
class AgreementRequest:
    """One caller's request: its OWN simulated cluster (n generals with
    ids 1..n, ``faulty`` roster indices, leader = lowest id), order,
    seed and round count — the service is stateless per request.
    ``spec`` (a ``ba_tpu.scenario.spec.Scenario``) is required for
    ``kind="scenario"`` and supplies the round count there."""

    kind: str = "actual-order"
    order: str = "attack"
    n: int = 4
    faulty: tuple = ()
    seed: int = 0
    rounds: int = 1
    spec: object = None
    # ISSUE 13: per-request megastep engine override (None = the
    # service's configured default).  Joins the cohort key — an engine
    # request never coalesces into another engine's batch.
    engine: str | None = None
    # ISSUE 14: per-request protocol dials, both cohort-key members so
    # one front-end serves oral, signed and mixed-depth traffic
    # CONCURRENTLY without ever coalescing across protocols.  ``m`` is
    # the recursion/relay depth (None = the service's single ``m``
    # dial, the PR 10 behavior); ``signed=True`` runs the request
    # through the signed SM(m) lane (sign-ahead tables + the signed
    # coalesced megastep).
    m: int | None = None
    signed: bool = False
    # ISSUE 17: optional accounting label.  DELIBERATELY not a cohort
    # key member — tenants coalesce together (the label attributes
    # spend, it never isolates); the SLO engine accounts per
    # (cohort, tenant) from the request records.
    tenant: str | None = None
    # ISSUE 19: optional W3C traceparent injected by an external caller
    # — the request's span tree parents under the caller's span.  Not a
    # cohort key member (causality never changes coalescing); malformed
    # values degrade to a fresh root trace, never an error.
    traceparent: str | None = None


def validate_request(req: AgreementRequest) -> AgreementRequest:
    """Eager request validation (raises ValueError before admission —
    a malformed request must never reach the dispatcher thread)."""
    if req.kind not in REQUEST_KINDS:
        raise ValueError(
            f"kind {req.kind!r} not in {REQUEST_KINDS}"
        )
    if req.order not in ORDERS:
        raise ValueError(f"order {req.order!r} not in {ORDERS}")
    if req.n < 1:
        raise ValueError(f"n={req.n} must be >= 1")
    for i in req.faulty:
        if not isinstance(i, int) or isinstance(i, bool) or not (
            0 <= i < req.n
        ):
            raise ValueError(
                f"faulty index {i!r} outside roster [0, {req.n})"
            )
    if req.engine is not None and req.engine not in ENGINE_TOKENS:
        raise ValueError(
            f"engine={req.engine!r} not in {ENGINE_TOKENS}"
        )
    if req.m is not None and (
        not isinstance(req.m, int) or isinstance(req.m, bool) or req.m < 1
    ):
        raise ValueError(f"m={req.m!r} must be an int >= 1 (or None)")
    if req.tenant is not None and (
        not isinstance(req.tenant, str) or not req.tenant
    ):
        raise ValueError(
            f"tenant={req.tenant!r} must be None or a non-empty string"
        )
    if req.traceparent is not None and not isinstance(req.traceparent, str):
        # Shape-check only: a WELL-TYPED but malformed traceparent is
        # external input and degrades to untraced (obs.trace contract),
        # but a non-string is a caller bug worth failing eagerly.
        raise ValueError(
            f"traceparent={req.traceparent!r} must be None or a string"
        )
    if req.kind == "scenario":
        if req.spec is None:
            raise ValueError("kind='scenario' needs a spec")
        if req.signed:
            raise ValueError(
                "signed requests cannot carry a scenario (the signed "
                "megastep has no mutating-round form)"
            )
    elif req.spec is not None:
        raise ValueError(f"kind={req.kind!r} does not take a spec")
    if req.kind == "actual-order" and req.rounds != 1:
        raise ValueError(
            f"actual-order is one round; rounds={req.rounds} "
            f"(use kind='run-rounds')"
        )
    if req.rounds < 1:
        raise ValueError(f"rounds={req.rounds} must be >= 1")
    return req


def request_rounds(req: AgreementRequest) -> int:
    return req.spec.rounds if req.kind == "scenario" else req.rounds


def cohort_key(
    req: AgreementRequest,
    default_engine: str = "xla",
    default_m: int = 1,
) -> tuple:
    """Requests sharing this key coalesce into one batch: same compiled
    specialization (round count, padded capacity, scenario-ness, the
    effective engine request — ISSUE 13 — and, ISSUE 14, the PROTOCOL:
    the effective recursion/relay depth ``m`` and the ``signed`` flag,
    so signed and m>=2 EIG cohorts coalesce separately but serve
    concurrently; the dispatcher passes its config's defaults) —
    orders, seeds, fault patterns and event planes are per-slot DATA."""
    return (
        req.kind == "scenario", request_rounds(req), _capacity(req.n),
        req.engine or default_engine,
        default_m if req.m is None else req.m,
        bool(req.signed),
    )


def cohort_label(key: tuple) -> str:
    """The cohort key's compact record-field spelling (ISSUE 17):
    ``{scenario|plain}.r<rounds>.c<capacity>.<engine>.m<m>[.signed]``
    — a stable string the SLO engine / report tooling group on, so the
    JSONL stream never carries raw tuples."""
    is_scenario, rounds, cap, engine, m, signed = key
    label = (
        f"{'scenario' if is_scenario else 'plain'}"
        f".r{rounds}.c{cap}.{engine}.m{m}"
    )
    return label + ".signed" if signed else label


class Ticket:
    """The caller's handle on a submitted request (a tiny future):
    ``result(timeout=None)`` blocks for the terminal state and returns
    the result dict or raises the failure (:class:`DeadlineExceeded`,
    :class:`RequestFailed`, :class:`ServeError`)."""

    def __init__(self, request, req_id, deadline_t):
        self.request = request
        self.id = req_id
        self.deadline_t = deadline_t  # perf_counter deadline or None
        # Lifecycle marks (ISSUE 17): admitted → popped (left the queue
        # into a cohort, or expired at pop) → dispatched (cohort batch
        # handed to the engine) → retired (engine returned) →
        # delivered (the record-emission instant).  The request
        # record's phase decomposition telescopes over these.
        self.enqueued_t = time.perf_counter()
        self.popped_t = None
        self.dispatched_t = None
        self.retired_t = None
        # Causal root (ISSUE 19): every admitted request owns one span —
        # the root of its cross-process tree.  Parent priority: the
        # request's own traceparent field, else BA_TPU_TRACE_CONTEXT,
        # else a fresh root trace.  Created at admission (caller's
        # thread) so the id exists before any dispatcher work can
        # reference it in a fan-in edge.
        self._trace = obs.trace.new_context(
            request.traceparent
            or os.environ.get(obs.trace.TRACE_CONTEXT_ENV)
            or None
        )
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._block = None  # compiled per-slot scenario planes

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _resolve(self, result) -> None:
        self._result = result
        self._event.set()

    def _fail(self, error) -> None:
        self._error = error
        self._event.set()


class AgreementService:
    """The long-lived, thread-safe serving front-end (module docstring
    for the architecture).  Lifecycle::

        svc = AgreementService()        # or (ServeConfig(...), plan)
        svc.start()
        ticket = svc.submit(AgreementRequest(kind="run-rounds",
                                             n=4, rounds=32, seed=7))
        out = ticket.result(timeout=60)
        svc.stop()

    ``fault_plan`` (a ``runtime.chaos.FaultPlan`` or live
    ``ChaosInjector``) injects engine-phase faults into every cohort
    dispatch for drills — the same plans the supervisor drills with.
    ``open()`` alone (admission without the dispatcher thread) is the
    deterministic-overload drill hook the tests and the schema check
    use: submissions queue/reject exactly as in production, and a later
    ``start()`` drains them.
    """

    def __init__(self, config: ServeConfig | None = None, fault_plan=None,
                 registry=None):
        self._cfg = config or ServeConfig.from_env()
        self._reg = registry if registry is not None else (
            obs.default_registry()
        )
        self._cond = threading.Condition()
        # Tier/wedge state is written from BOTH the dispatcher thread
        # (_refresh_tier decay, post-dispatch wedge clear) and the
        # watchdog Timer thread (_declare_wedged) — a dedicated lock,
        # NOT self._cond, so the watchdog never contends with queue
        # signalling (BA501).
        self._tier_lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._open = False
        self._drain = True
        self._thread = None
        self._tier = 0
        self._window_s = self._cfg.coalesce_window_s
        self._batch_s = None  # EWMA of cohort dispatch wall time
        self._ids = itertools.count(1)
        # The pressure sampler reads the PROCESS-GLOBAL registry, not
        # self._reg: the engine records its pipeline_* instruments
        # (depth occupancy, retire lag) into obs.default_registry()
        # whatever registry the service's own serve_* family lives in
        # — sampling self._reg would leave the lag/occupancy shed
        # signals permanently None for any service constructed with a
        # custom registry (engine pressure is process-global by
        # design; serve bookkeeping is what registry= isolates).
        self._sampler = obs.health.HealthSampler()
        # SLO engine (ISSUE 17): resolved EAGERLY — a bad policy path
        # or document fails at construction, not mid-traffic — and
        # installed process-wide at open() (the health sampler's hook
        # target; reports ride the pressure-sampling cadence).
        self._slo = None
        if self._cfg.slo:
            policy = self._cfg.slo
            if policy is True:
                policy = obs.slo.default_policy()
            elif isinstance(policy, str):
                policy = obs.slo.SLOPolicy.load(policy)
            self._slo = obs.slo.SLOEngine(policy, registry=self._reg)
        from ba_tpu.runtime.supervisor import (
            SupervisorConfig,
            derive_timeout_s,
        )

        self._sup_cfg = SupervisorConfig()
        self._max_retries = self._cfg.resolved_max_retries()
        # Cohort watchdog (PR 7's timeout machinery reused): an
        # in-process hung dispatch is not interruptible — the watchdog
        # OBSERVES and applies BACKPRESSURE (tier 3, explicit
        # rejections with the wedge named) so a wedged engine reads as
        # an overloaded service, never a silently growing queue of
        # forever-blocked tickets.  Recovery from a true wedge is
        # process replacement, exactly as for supervised campaigns.
        self._dispatch_timeout_s = (
            self._cfg.dispatch_timeout_s
            if self._cfg.dispatch_timeout_s is not None
            else derive_timeout_s(self._sup_cfg)
        )
        self._wedged = False
        self._stalls_c = self._reg.counter("serve_stalls_total")
        # Warm-serving stack (ISSUE 11): the executable cache the
        # dispatcher consults before every cohort dispatch, and the
        # background warmup runner open() starts.  The cache exists
        # whenever warmup is on OR an explicit cache dir is configured
        # (BA_TPU_AOT_CACHE / aot_cache) — a cold-configured service
        # keeps the exact pre-ISSUE-11 dispatch path.
        self._exec_cache = None
        self._warmup = None
        cache_env = os.environ.get(obs.aotcache.CACHE_ENV, "")
        if self._cfg.warm or self._cfg.aot_cache or cache_env not in (
            "", "0"
        ):
            self._exec_cache = obs.aotcache.ExecutableCache(
                directory=self._cfg.aot_cache
            )
        self._compile_rp_c = self._reg.counter(
            "serve_compile_on_request_path_total"
        )
        self._warm_miss_c = self._reg.counter("serve_warmup_miss_total")
        # Instance-local tallies for stats(): registry counters are
        # shared by every service on the registry (the documented
        # one-process roster+service mode), and "did THIS service
        # compile on its request path" must not blend another
        # service's history in.
        self._rpc_n = 0
        self._warm_miss_n = 0
        injector = fault_plan
        if injector is not None and not hasattr(injector, "fire"):
            from ba_tpu.runtime.chaos import ChaosInjector

            injector = ChaosInjector(injector)
        self._injector = injector
        # serve_* instrument family (the `serve_` PREFIX rule is
        # registry-asserted, like `_per_shard` — DESIGN §8).
        self._admitted_c = self._reg.counter("serve_admitted_total")
        self._completed_c = self._reg.counter("serve_completed_total")
        self._rejected_c = self._reg.counter("serve_rejected_total")
        self._expired_c = self._reg.counter("serve_expired_total")
        self._failed_c = self._reg.counter("serve_failed_total")
        self._retries_c = self._reg.counter("serve_retries_total")
        self._batches_c = self._reg.counter("serve_batches_total")
        self._slots_h = self._reg.histogram(
            "serve_batch_slots", base=1.0, n_buckets=12
        )
        self._wait_h = self._reg.histogram("serve_queue_wait_s")
        self._latency_h = self._reg.histogram("serve_request_latency_s")
        self._reg.gauge("serve_queue_depth").set(0)
        self._reg.gauge("serve_shed_tier").set(0)
        self._reg.gauge("serve_window_s").set(self._window_s)

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> None:
        """Open ADMISSION without the dispatcher (see class docstring).
        With ``warm`` configured (ISSUE 11) this also launches the
        background warmup runner — admission never waits on it; callers
        that want the warm guarantee block on :meth:`warm_barrier`."""
        with self._cond:
            self._open = True
        self._sampler.prime()
        if self._slo is not None:
            obs.slo.install(self._slo)
        # Host-crypto pool lifecycle (ISSUE 16): the SERVICE owns the
        # process-default signing/verify pool — spawn it at open (per
        # BA_TPU_SIGN_POOL; a 0 derivation is the in-process path and
        # spawns nothing), drain it at stop.  Jax-free host tier.
        from ba_tpu.crypto import pool as _sign_pool_mod

        pool = _sign_pool_mod.default_pool()
        self._reg.gauge("serve_sign_pool_workers").set(
            pool.workers if pool is not None else 0
        )
        if self._cfg.warm and self._warmup is None:
            from ba_tpu.runtime import warmup as warmup_mod

            self._warmup = warmup_mod.WarmupRunner(
                self._exec_cache,
                warmup_mod.service_plan(self._cfg),
                # Health gate: the shed-tier view (derived from the
                # obs/health pressure sampler) — warmup compiles only
                # while the service reads healthy, so it can never be
                # the thing that sheds live traffic.
                gate=lambda: self._tier == 0 and not self._wedged,
                registry=self._reg,
                # Warm path pre-populates the signature-table cache
                # (ISSUE 16): signed cohorts after the warm barrier
                # probe, they don't sign.
                prime=warmup_mod.sign_cache_primer(self._cfg),
            )
            self._warmup.start()

    def warm_barrier(self, timeout: float | None = None) -> bool:
        """Block until the warmup pass attempted every planned
        signature (True; False on timeout).  A service without warmup
        is trivially warm."""
        if self._warmup is None:
            return True
        return self._warmup.wait(timeout)

    def start(self) -> None:
        self.open()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ba-tpu-serve", daemon=True
            )
            self._thread.start()

    def stop(self, drain: bool = True, timeout: float | None = None) -> None:
        """Close admission; with ``drain`` (default) the dispatcher
        finishes the queued work first, otherwise queued tickets fail
        with :class:`ServeError`."""
        with self._cond:
            self._open = False
            self._drain = drain
            self._cond.notify_all()
        if self._warmup is not None:
            # Wind the background compiler down with the service; the
            # daemon thread finishes its in-flight compile and exits.
            self._warmup.stop()
        if self._thread is not None:
            self._thread.join(timeout)
        # The other half of the pool lifecycle the service owns
        # (ISSUE 16): drain the signing/verify workers.  The signature
        # cache keeps its warm entries — it is memory, not processes.
        from ba_tpu.crypto import pool as _sign_pool_mod

        _sign_pool_mod.close_default_pool()
        # Whatever is left (no dispatcher ever ran, or drain=False):
        # fail loudly rather than leaving callers blocked forever.
        leftovers = []
        with self._cond:
            while self._queue:
                leftovers.append(self._queue.popleft())
            self._gauge_queue_locked()
        for t in leftovers:
            # Counted as failures so stats()/the REPL line and the
            # emitted request records stay joinable on one tally.
            self._failed_c.inc()
            t._fail(ServeError("service stopped before dispatch"))
            self._emit_request(t, status="failed", fault=None)
        if self._slo is not None:
            # One final forced report (the leftovers above folded in),
            # then uninstall — a stopped service must not leave its
            # engine wired to the process-wide sampler hook.
            self._slo.maybe_report(force=True)
            if obs.slo.installed() is self._slo:
                obs.slo.install(None)

    def handoff(self, timeout: float | None = None) -> list:
        """The fleet drain hook (ISSUE 20): close admission, let the
        in-flight cohort retire normally, then DETACH the queued-but-
        never-dispatched tickets — failed with a re-homable
        :class:`ServeError` so no caller ever hangs, but NOT counted as
        failures and with NO terminal ``request`` record emitted: a
        drain is a move, not an outcome, and the replica that finally
        dispatches the request owns its one terminal record (the
        router's :class:`~ba_tpu.fleet.router.RoutedTicket` catches
        exactly this error and re-submits on a surviving replica).

        Returns the detached tickets (fleet accounting).  Unlike
        :meth:`stop` this leaves the process-shared resources — the
        signing pool, the SLO hook — alone: other replicas in the
        process are still serving on them."""
        leftovers = []
        with self._cond:
            self._open = False
            self._drain = False
            while self._queue:
                leftovers.append(self._queue.popleft())
            self._gauge_queue_locked()
            self._cond.notify_all()
        if self._warmup is not None:
            self._warmup.stop()
        if self._thread is not None:
            self._thread.join(timeout)
        for t in leftovers:
            t._fail(ServeError(
                f"request {t.id} re-homed: replica draining"
            ))
        obs.instant("serve_handoff", rehomed=len(leftovers))
        return leftovers

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- admission ----------------------------------------------------------

    def submit(
        self, request: AgreementRequest, deadline_s=...,
    ) -> Ticket:
        """Admit one request (or raise): eager validation, bounded-queue
        + shed-tier admission, deadline stamping.  ``deadline_s``
        defaults to the config's budget; ``None`` disables the deadline
        for this request."""
        validate_request(request)
        if deadline_s is ...:
            deadline_s = self._cfg.default_deadline_s
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s={deadline_s} must be >= 0")

        def check(depth, tier):
            # ONE spelling of the admission ladder, used twice (see
            # below): queue bound, then shed tiers — interactive work
            # sheds BEFORE long campaigns (an interactive caller
            # retries cheaply, a campaign re-issue re-pays its spec).
            if depth >= self._cfg.max_queue:
                return ("queue_full", depth, tier)
            if tier >= 3:
                return ("shed_all", depth, tier)
            if tier >= 2 and request.kind != "scenario":
                return ("shed_interactive", depth, tier)
            return None

        with self._cond:
            if not self._open:
                raise ServeError(
                    "service is not accepting requests (call start())"
                )
            # Pre-compile admission probe: an overloaded service must
            # reject in O(1), not after paying a full per-request
            # scenario lowering it is about to throw away.
            reject = check(len(self._queue), self._tier)
        block = None
        if reject is None and request.kind == "scenario":
            # Compile in the CALLER's thread, before enqueue: spec
            # errors (unknown ids, bad strategies) belong to the caller
            # eagerly, and the dispatcher must never pay per-request
            # lowering inside the coalescing window.
            cap = _capacity(request.n)
            block = compile_scenario(
                request.spec, batch=1, capacity=cap,
                ids=np.arange(1, cap + 1, dtype=np.int64),
            )
        with self._cond:
            if not self._open:
                raise ServeError(
                    "service is not accepting requests (call start())"
                )
            depth = len(self._queue)
            tier = self._tier
            if reject is None:
                # Re-check under the lock: the queue/tier may have
                # moved while the spec compiled.
                reject = check(depth, tier)
            if reject is None:
                ticket = Ticket(
                    request,
                    next(self._ids),
                    None
                    if deadline_s is None
                    else time.perf_counter() + deadline_s,
                )
                ticket._block = block
                self._queue.append(ticket)
                self._gauge_queue_locked()
                self._cond.notify_all()
        if reject is not None:
            reason, depth, tier = reject
            retry_after = self._retry_after(depth)
            self._rejected_c.inc()
            rec = {
                "event": "admission",
                "v": _metrics.SCHEMA_VERSION,
                "decision": "reject",
                "reason": reason,
                "kind": request.kind,
                # Accounting labels (ISSUE 17): a rejection is
                # attributable to its tenant/cohort like any terminal
                # outcome — rejected work burns error budget too.
                "tenant": request.tenant,
                "cohort": cohort_label(
                    cohort_key(request, self._cfg.engine, self._cfg.m)
                ),
                "tier": tier,
                "queue_depth": depth,
                "queue_limit": self._cfg.max_queue,
                "retry_after_s": retry_after,
            }
            _metrics.emit(rec)
            if self._slo is not None:
                self._slo.fold(rec)
            obs.instant(
                "serve_reject", reason=reason, tier=tier, queue=depth
            )
            raise Overloaded(
                f"overloaded ({reason}): queue {depth}/"
                f"{self._cfg.max_queue}, shed tier {tier} — retry in "
                f"~{retry_after}s",
                retry_after_s=retry_after,
                tier=tier,
                reason=reason,
            )
        self._admitted_c.inc()
        return ticket

    def _retry_after(self, queue_depth: int) -> float:
        # Cold service (no batch observed yet): the documented default,
        # not a degenerate coalesce-window hint (ISSUE 17 satellite).
        per_batch = (
            self._batch_s
            if self._batch_s is not None
            else COLD_RETRY_AFTER_S
        )
        batches_ahead = max(
            1, -(-max(1, queue_depth) // self._cfg.max_batch)
        )
        return round(max(self._window_s, batches_ahead * per_batch), 4)

    def _gauge_queue_locked(self) -> None:
        self._reg.gauge("serve_queue_depth").set(len(self._queue))

    # -- the dispatcher thread ----------------------------------------------

    def _run(self) -> None:
        # Tier refresh rides every loop iteration — INCLUDING idle ones
        # (the cohort wait below is bounded): a service that shed its
        # way to tier 3 under a storm must decay back down once the
        # queue drains, or rejection would outlive the overload.
        while True:
            self._refresh_tier()
            cohort = self._next_cohort()
            if cohort is None:
                break
            if cohort:
                self._dispatch_cohort(cohort)

    def _next_cohort(self):
        """Pop one coalescable cohort (None = shut down, [] = nothing
        dispatchable this round — idle tick or expired-only).  Expiry
        is checked at pop AND immediately before returning — a request
        is cancelled before dispatch or not at all."""
        expired = []
        cohort = []
        with self._cond:
            if self._open and not self._queue:
                # Bounded idle wait, not a loop: the caller's loop must
                # keep ticking the tier refresh while idle.
                self._cond.wait(0.05)
            if not self._open and (not self._drain or not self._queue):
                return None
            if not self._queue:
                return []
            now = time.perf_counter()
            head = None
            while self._queue:
                t = self._queue.popleft()
                # The pop mark (ISSUE 17): the instant the ticket left
                # the queue for good — into a cohort or into expiry.
                # Tickets parked on `keep` below re-queue unstamped;
                # their queue phase is still running.
                t.popped_t = now
                if t.deadline_t is not None and now >= t.deadline_t:
                    expired.append(t)
                    continue
                head = t
                break
            if head is not None:
                ckey = cohort_key(
                    head.request, self._cfg.engine, self._cfg.m
                )
                cohort = [head]
                window_end = time.perf_counter() + self._window_s
                while len(cohort) < self._cfg.max_batch:
                    keep: collections.deque = collections.deque()
                    now = time.perf_counter()
                    while self._queue:
                        t = self._queue.popleft()
                        if (
                            t.deadline_t is not None
                            and now >= t.deadline_t
                        ):
                            t.popped_t = now
                            expired.append(t)
                        elif (
                            len(cohort) < self._cfg.max_batch
                            and cohort_key(
                                t.request, self._cfg.engine, self._cfg.m
                            )
                            == ckey
                        ):
                            t.popped_t = now
                            cohort.append(t)
                        else:
                            keep.append(t)
                    self._queue = keep
                    if len(cohort) >= self._cfg.max_batch:
                        break
                    remaining = window_end - time.perf_counter()
                    if remaining <= 0 or not self._open:
                        break
                    self._cond.wait(remaining)
            self._gauge_queue_locked()
        for t in expired:
            self._expire(t)
        live = []
        now = time.perf_counter()
        for t in cohort:
            if t.deadline_t is not None and now >= t.deadline_t:
                self._expire(t)
            else:
                live.append(t)
        return live

    def _expire(self, ticket: Ticket) -> None:
        self._expired_c.inc()
        ticket._fail(
            DeadlineExceeded(
                f"request {ticket.id} expired after "
                f"{time.perf_counter() - ticket.enqueued_t:.3f}s in "
                f"queue (cancelled before dispatch)"
            )
        )
        self._emit_request(ticket, status="expired", fault=None)

    def _refresh_tier(self) -> None:
        """One health sample (lock-free registry reads — the same
        depth-occupancy / retire-lag signals ``stats --live`` renders)
        -> shed tier -> coalescing window; a transition emits one
        ``shed`` record.  A WEDGED dispatcher (watchdog fired, dispatch
        still out) holds tier 3 — decay resumes once the dispatch
        returns."""
        if self._wedged:
            return
        with self._cond:
            depth = len(self._queue)
        frac = depth / self._cfg.max_queue
        if self._slo is not None:
            # Stamp queue pressure BEFORE sampling: sample() fires the
            # installed engine's maybe_report, and the autoscale_signal
            # it emits folds this very reading in (GIL-atomic write).
            self._slo.queue_frac = frac
        snap = self._sampler.sample()
        # The SLO gate burn as a pressure signal (ISSUE 17): lock-free
        # gauge read, None when no engine ever reported — absent
        # signals never raise the tier (shed_tier docstring).
        burn_inst = self._reg.get("health_slo_burn")
        burn = burn_inst.value if burn_inst is not None else None
        tier = shed_tier(
            frac,
            snap.get("retire_lag_p99_s"),
            snap.get("depth_occupancy"),
            self._cfg,
            burn=burn,
        )
        if tier != self._tier:
            self._transition_tier(tier, depth, snap=snap, frac=frac)

    def _transition_tier(self, tier, depth, snap=None, frac=None,
                         reason=None) -> None:
        """Apply + record one shed-tier transition (the dispatcher's
        refresh path AND the watchdog's wedge path — one spelling of
        the window/gauge/record bookkeeping)."""
        with self._tier_lock:
            prev, self._tier = self._tier, tier
            # Halve the window per tier under pressure BEFORE any
            # rejection tier bites (tiers 2/3 keep the halved window
            # for whatever still admits).
            self._window_s = self._cfg.coalesce_window_s * (
                0.5 ** min(tier, 2)
            )
        self._reg.gauge("serve_shed_tier").set(tier)
        self._reg.gauge("serve_window_s").set(self._window_s)
        lag = (snap or {}).get("retire_lag_p99_s")
        _metrics.emit(
            {
                "event": "shed",
                "v": _metrics.SCHEMA_VERSION,
                "tier": tier,
                "prev_tier": prev,
                "window_s": round(self._window_s, 6),
                "queue_depth": depth,
                "queue_frac": round(
                    frac if frac is not None
                    else depth / self._cfg.max_queue, 4
                ),
                "retire_lag_p99_s": (
                    None if lag == float("inf") else lag
                ),
                "depth_occupancy": (snap or {}).get("depth_occupancy"),
                **({"reason": reason} if reason else {}),
            }
        )
        obs.instant("serve_shed", tier=tier, prev=prev, queue=depth)

    def _declare_wedged(self, slots, lo_rounds) -> None:
        # Timer-thread path (the PR 7 watchdog pattern): the cohort's
        # dispatch has run past dispatch_timeout_s.  An in-process hung
        # dispatch cannot be interrupted — observe (counter + instant)
        # and apply BACKPRESSURE: tier 3 holds until the dispatch
        # returns, so new submissions reject explicitly instead of
        # queueing behind a wedge forever.
        with self._tier_lock:
            self._wedged = True
        self._stalls_c.inc()
        obs.instant(
            "serve_dispatch_stalled", slots=slots, rounds=lo_rounds,
            timeout_s=self._dispatch_timeout_s,
        )
        with self._cond:
            depth = len(self._queue)
        if self._tier != 3:
            self._transition_tier(3, depth, reason="dispatcher_stalled")

    # -- cohort dispatch ----------------------------------------------------

    def _seam(self, call, phase, d, lo, hi):
        """The cohort's execution seam: chaos injection (drills) +
        in-place transient retry with the supervisor's backoff/jitter.
        Anything that escapes fails the COHORT (caught one frame up),
        never the service."""
        from ba_tpu.runtime.supervisor import (
            TRANSIENT,
            backoff_s,
            classify_fault,
        )

        wrapped = (
            call
            if self._injector is None
            else lambda: self._injector.fire(call, phase, lo, hi)
        )
        tries = 0
        while True:
            try:
                return wrapped()
            except Exception as e:
                if (
                    classify_fault(e) != TRANSIENT
                    or tries >= self._max_retries
                ):
                    raise
                tries += 1
                self._retries_c.inc()
                time.sleep(
                    backoff_s(self._sup_cfg, tries, f"serve:{phase}:{lo}")
                )

    def _dispatch_cohort(self, live) -> None:
        from ba_tpu.runtime.supervisor import fault_attribution

        t0 = time.perf_counter()
        for t in live:
            t.dispatched_t = t0
            self._wait_h.record(t0 - t.enqueued_t)
        rounds = request_rounds(live[0].request)
        # The coalesced-batch fan-in node (ISSUE 19): many request roots
        # converge on ONE shared engine dispatch, so the batch span is a
        # child of the FIRST member's trace and carries every member's
        # root span id as a ``fan_in`` edge — obs/fleet grafts the shared
        # subtree under each other member's root from those edges.  The
        # scope makes every record the engine emits during this dispatch
        # (flight spans, sign staging, pool tasks) parent under it.
        batch_ctx = obs.trace.child_context(live[0]._trace)
        fan_in = [t._trace[1] for t in live]
        watchdog = threading.Timer(
            self._dispatch_timeout_s, self._declare_wedged,
            args=(len(live), rounds),
        )
        watchdog.daemon = True
        watchdog.start()
        try:
            try:
                with obs.trace.scope(batch_ctx):
                    results, run_id, phases = self._execute(live)
            except Exception as e:  # per-cohort fault isolation
                att = fault_attribution(e)
                self._failed_c.inc(len(live))
                obs.instant(
                    "serve_cohort_failed", fault=att["fault"],
                    slots=len(live),
                )
                obs.trace.emit_trace_span(
                    "serve_batch", batch_ctx, t0,
                    time.perf_counter() - t0, fan_in=fan_in,
                    slots=len(live), status="failed",
                )
                for t in live:
                    t._fail(
                        RequestFailed(
                            f"cohort of {len(live)} failed "
                            f"({att['fault']}): {att['error']}",
                            fault=att["fault"],
                        )
                    )
                    self._emit_request(
                        t, status="failed", fault=att["fault"]
                    )
                return
        finally:
            # Whether the dispatch returned, failed, or ran past the
            # watchdog (which can only observe — see _declare_wedged):
            # the wedge is over once control is back here, and the
            # next _refresh_tier decays the forced tier 3 normally.
            watchdog.cancel()
            with self._tier_lock:
                self._wedged = False
        t_retired = time.perf_counter()
        for t in live:
            t.retired_t = t_retired
        wall = t_retired - t0
        obs.trace.emit_trace_span(
            "serve_batch", batch_ctx, t0, wall, fan_in=fan_in,
            slots=len(live), status="ok",
        )
        self._batch_s = (
            wall
            if self._batch_s is None
            else 0.5 * self._batch_s + 0.5 * wall
        )
        self._batches_c.inc()
        self._slots_h.record(len(live))
        self._completed_c.inc(len(live))
        for t, result in zip(live, results):
            t._resolve(result)
            self._latency_h.record(time.perf_counter() - t.enqueued_t)
            self._emit_request(
                t, status="ok", fault=None,
                batch=len(live), slot=result["slot"], run_id=run_id,
                phases=phases,
            )

    def _execute(self, live):
        """Stage + dispatch one coalesced batch (the dispatcher
        thread's only engine contact — jax imports live HERE, keeping
        the module import host-tier)."""
        import jax.random as jr

        from ba_tpu.core.state import SimState
        from ba_tpu.core.types import (
            ATTACK,
            COMMAND_DTYPE,
            RETREAT,
            command_from_name,
        )
        from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy

        import jax.numpy as jnp

        is_scenario, rounds, cap, engine, m, signed = cohort_key(
            live[0].request, self._cfg.engine, self._cfg.m
        )
        n_live = len(live)
        B = min(_batch_bucket(n_live), _batch_bucket(self._cfg.max_batch))
        # Filler slots replicate slot 0 under a fixed key: independent
        # lanes, results discarded — padding is pure shape discipline.
        reqs = [t.request for t in live] + [live[0].request] * (B - n_live)
        order = np.zeros(B, np.int8)
        leader = np.zeros(B, np.int32)
        faulty = np.zeros((B, cap), np.bool_)
        alive = np.zeros((B, cap), np.bool_)
        ids = np.tile(np.arange(1, cap + 1, dtype=np.int32), (B, 1))
        for b, req in enumerate(reqs):
            order[b] = command_from_name(req.order)
            alive[b, : req.n] = True
            for i in req.faulty:
                faulty[b, i] = True
        # fresh_copy is LOAD-BEARING (the backends.py lesson): the
        # numpy staging above may be zero-copied by jnp.asarray on CPU,
        # and the engine donates this state.
        state = fresh_copy(
            SimState(
                order=jnp.asarray(order.astype(COMMAND_DTYPE)),
                leader=jnp.asarray(leader),
                faulty=jnp.asarray(faulty),
                alive=jnp.asarray(alive),
                ids=jnp.asarray(ids),
            )
        )
        keys = [jr.key(req.seed) for req in reqs[:n_live]]
        keys += [jr.key(0)] * (B - n_live)
        planes = None
        if is_scenario:
            blocks = [t._block for t in live]
            fill = empty_block(rounds, B - n_live, cap) if B > n_live else None
            planes = {
                name: np.concatenate(
                    [getattr(b, name) for b in blocks]
                    + ([getattr(fill, name)] if fill is not None else []),
                    axis=1,
                )
                for name in ("kill", "revive", "set_faulty", "set_strategy")
            }
        out = coalesced_sweep(
            keys,
            state,
            rounds,
            m=m,
            depth=self._cfg.depth,
            rounds_per_dispatch=self._cfg.rounds_per_dispatch,
            scenario=planes,
            signed=signed,
            exec_seam=self._seam,
            executables=self._exec_cache,
            engine=engine,
        )
        # Warm-serving accounting (ISSUE 11): every dispatch window that
        # compiled ON the request path is a counted event — the "warm
        # service never compiles on the request path" acceptance boolean
        # is `serve_compile_on_request_path_total == 0` after the warm
        # barrier, measured, not hoped.  With the cache active the same
        # count is the compile-on-miss fallback tally (an unwarmed
        # cohort's first request still served — it just paid a compile).
        rpc = out["stats"].get("request_path_compiles", 0)
        if rpc:
            self._compile_rp_c.inc(rpc)
            self._rpc_n += rpc
            if self._exec_cache is not None:
                self._warm_miss_c.inc(rpc)
                self._warm_miss_n += rpc
        results = []
        for i, t in enumerate(live):
            dec = out["decisions"][:, i]
            n_attack = int((dec == ATTACK).sum())
            n_retreat = int((dec == RETREAT).sum())
            result = {
                "kind": t.request.kind,
                "rounds": rounds,
                "decisions": [int(v) for v in dec],
                "counts": {
                    "attack": n_attack,
                    "retreat": n_retreat,
                    "undefined": rounds - n_attack - n_retreat,
                },
                "majorities": [
                    int(v) for v in out["majorities"][i, : t.request.n]
                ],
                "counters": {
                    name: int(v)
                    for name, v in zip(
                        out["counter_names"], out["counters"][i]
                    )
                },
                "batch": n_live,
                "slot": i,
                "run_id": out["stats"]["run_id"],
            }
            if is_scenario:
                result["leaders"] = [int(v) for v in out["leaders"][:, i]]
            results.append(result)
        # Engine-side phase walls for the SLO attribution join
        # (ISSUE 17): every request in the cohort EXPERIENCED the whole
        # batch's compile and retire-fetch time — attribution reports
        # latency as felt, it does not cost-split across slots.
        phases = {
            "compile_s": out["stats"].get("compile_s", 0.0),
            "retire_fetch_s": out["stats"].get("retire_fetch_s", 0.0),
        }
        return results, out["stats"]["run_id"], phases

    # -- records / stats ----------------------------------------------------

    def _emit_request(self, ticket, *, status, fault, batch=None,
                      slot=None, run_id=None, phases=None) -> None:
        # Phase decomposition (ISSUE 17): consecutive perf_counter
        # marks telescope, so for an ok row
        #   queue_s + coalesce_s + compile_s + dispatch_s + retire_lag_s
        # sums EXACTLY to wall_s (modulo 6-dp rounding) — the pinned
        # attribution invariant.  Non-ok rows carry whatever phases
        # they reached (number-or-null, same keys) so failures are
        # attributable too, never just ok rows.
        now = time.perf_counter()
        admitted = ticket.enqueued_t
        popped = ticket.popped_t
        dispatched = ticket.dispatched_t
        retired = ticket.retired_t
        queue_s = (popped if popped is not None else now) - admitted
        coalesce_s = compile_s = dispatch_s = retire_lag_s = None
        if popped is not None and dispatched is not None:
            coalesce_s = dispatched - popped
        if status == "ok" and dispatched is not None and retired is not None:
            compile_s = (phases or {}).get("compile_s", 0.0)
            fetch_s = (phases or {}).get("retire_fetch_s", 0.0)
            # dispatch_s is the residual of the engine span: batch
            # staging + device execution, with the measured compile and
            # retire-fetch walls attributed to their own phases.
            dispatch_s = max(
                0.0, (retired - dispatched) - compile_s - fetch_s
            )
            retire_lag_s = fetch_s + (now - retired)
        elif status == "failed" and dispatched is not None:
            # A failed cohort's engine span is all dispatch — there is
            # no retire mark to split against.
            dispatch_s = now - dispatched
        rec = {
            "event": "request",
            "v": _metrics.SCHEMA_VERSION,
            "id": ticket.id,
            "kind": ticket.request.kind,
            "status": status,
            "rounds": request_rounds(ticket.request),
            "tenant": ticket.request.tenant,
            "cohort": cohort_label(
                cohort_key(ticket.request, self._cfg.engine, self._cfg.m)
            ),
            "queue_s": round(queue_s, 6),
            "coalesce_s": (
                None if coalesce_s is None else round(coalesce_s, 6)
            ),
            "compile_s": (
                None if compile_s is None else round(compile_s, 6)
            ),
            "dispatch_s": (
                None if dispatch_s is None else round(dispatch_s, 6)
            ),
            "retire_lag_s": (
                None if retire_lag_s is None else round(retire_lag_s, 6)
            ),
            "wall_s": round(now - admitted, 6),
        }
        if fault is not None:
            rec["fault"] = fault
        if batch is not None:
            rec["batch"] = batch
        if slot is not None:
            rec["slot"] = slot
        if run_id is not None:
            rec["run_id"] = run_id
        # ISSUE 19: the request record IS the tree root — stamp its own
        # span explicitly (the dispatcher thread's ambient context, if
        # any, belongs to a batch, not to this ticket).
        tctx = ticket._trace
        rec["trace_id"], rec["span_id"] = tctx[0], tctx[1]
        if tctx[2] is not None:
            rec["parent_id"] = tctx[2]
        _metrics.emit(rec)
        if self._slo is not None:
            self._slo.fold(rec)

    def stats(self) -> dict:
        with self._cond:
            depth = len(self._queue)
        out = {
            "open": self._open,
            "running": self.running(),
            "tier": self._tier,
            "window_s": round(self._window_s, 6),
            "queue_depth": depth,
            "queue_limit": self._cfg.max_queue,
            "max_batch": self._cfg.max_batch,
            "admitted": self._admitted_c.value,
            "completed": self._completed_c.value,
            "rejected": self._rejected_c.value,
            "expired": self._expired_c.value,
            "failed": self._failed_c.value,
            "retries": self._retries_c.value,
            "stalls": self._stalls_c.value,
            "batches": self._batches_c.value,
            "batch_s_ewma": (
                round(self._batch_s, 6) if self._batch_s else None
            ),
            "injected": (
                len(self._injector.fired)
                if self._injector is not None
                else 0
            ),
            "compiles_on_request_path": self._rpc_n,
            "warm": self._cfg.warm,
            # ISSUE 17: whether an SLO engine is wired, and how many
            # reports it has emitted (0 until the sampler cadence hits).
            "slo": self._slo is not None,
            "slo_reports": (
                self._slo.reports if self._slo is not None else 0
            ),
            # ISSUE 13: the configured default engine dial (per-request
            # overrides ride the cohort key; what actually RAN is the
            # engine's own pipeline_engine gauge + stats).
            "engine": self._cfg.engine,
        }
        if self._warmup is not None:
            prog = self._warmup.progress()
            out.update(
                warmup_planned=prog["planned"],
                warmup_warmed=prog["warmed"],
                warmup_pending=prog["pending"],
                warmup_errors=prog["errors"],
                warmup_done=prog["done"],
                warmup_misses=self._warm_miss_n,
            )
        return out
