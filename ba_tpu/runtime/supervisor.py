"""Resilient execution supervisor: drive long campaigns to completion
through real and injected faults (ISSUE 7 tentpole).

PR 6 made the pipelined engine's donated carry durable
(``CarryCheckpoint`` + bit-exact ``resume=``), but nothing USED that
durability to survive a failure: a raised XLA error, a hung dispatch, a
preempted process or a rotten ``.npz`` killed the run and the operator
restarted by hand.  This module is the missing runtime — the real-world
counterpart of the paper's simulated failure-detection layer (TCP-ping
of generals), applied to our own execution engine.  Four pillars:

1. **Fault detection.**  A wall-clock watchdog on the depth-delayed
   retire (``pipeline_sweep(retire_timeout_s=...)``): a dispatch whose
   retire fetch exceeds ``timeout_s`` is declared STALLED.  The timeout
   derives from the engine's own observed dispatch-latency histogram
   (``pipeline_dispatch_latency_s``: ``multiplier x`` the worst observed
   latency, floored) unless ``BA_TPU_SUPERVISE_TIMEOUT_S`` or
   ``SupervisorConfig.timeout_s`` pins it.  Raised errors classify into
   **transient** (retry in place), **fatal** (resume from checkpoint)
   and **oom** (degrade, then retry) via :func:`classify_fault` — duck
   typing on the ``ba_tpu_fault`` marker chaos-injected faults carry,
   plus message-marker tables for real XLA errors.

2. **Retry with exponential backoff + deterministic jitter.**  The
   supervisor installs itself into the engine's execution seam
   (``exec_seam``): a transient fault raised at a dispatch or retire is
   retried IN PLACE up to ``max_retries`` times (``BA_TPU_MAX_RETRIES``)
   with :func:`backoff_s` delays — deterministic jitter (a hash of
   seed/site/attempt, no global RNG), so reruns are reproducible and a
   fleet of supervisors never thunders in phase.  In-place retry is
   bit-exact because injected faults fire BEFORE the jitted call
   consumes the donated carry, and the engine re-stages event chunks
   from the host-resident sparse block on the retried call.

3. **Automatic recovery.**  An error that escapes the seam (a fatal
   fault, exhausted retries, a killed-and-restarted process) resumes
   from the NEWEST VALID checkpoint (``snapshot.newest_valid_checkpoint``
   — corrupt files quarantine to ``<path>.corrupt`` and the scan falls
   back) through the engine's existing ``resume=`` path, re-lowering the
   remaining sparse window.  Completed per-round rows are collected via
   the engine's ``on_rows`` hook and persisted as ``<ckpt>.rows.npz``
   DELTA sidecars next to the checkpoints (each carries only the rounds
   since the previous checkpoint — O(R) total sidecar I/O — and
   recovery merges the family's chain), so the assembled campaign
   result is bit-identical to an uninterrupted run even across a
   process boundary (the parity tests pin decisions, leaders and every
   counter block).
   Each recovery emits a versioned ``{"event": "recovery", "v": 1}``
   record, a ``recovery`` span/instant and the
   ``supervisor_recoveries_total`` counter.

4. **Graceful degradation.**  A device OOM halves ``depth`` first (fewer
   in-flight carries), then ``rounds_per_dispatch`` (smaller per-dispatch
   working set), and retries — both are pure scheduling dials, so the
   degraded campaign stays bit-exact; the downgrade is recorded
   (``supervisor_degrades_total`` + a ``recovery`` record with
   ``"action": "degrade"``).  The batch is deliberately NOT halved:
   that would change the computed campaign, not its schedule.

**Poison quarantine.**  A campaign window whose replay keeps failing
(``poison_threshold`` times at the same round cursor) is not a fault to
retry forever: the supervisor raises :class:`PoisonousWindow` carrying a
minimal reproducer (window bounds, engine dials, newest checkpoint to
resume from) and writes it as ``poison_<round>.json`` next to the
checkpoints.

Everything here is HOST-side orchestration: the engine's no-blocking
dispatch-count proof re-runs under full supervision (watchdog + seam +
rows collection live) with an unchanged schedule — supervision adds
classification and bookkeeping to failures, never synchronization to
success.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

import numpy as np

from ba_tpu import obs
from ba_tpu.utils import metrics as _metrics
from ba_tpu.utils import snapshot as _snapshot

TRANSIENT = "transient"
FATAL = "fatal"
OOM = "oom"

# Message markers for REAL runtime errors (chaos-injected ones carry the
# ba_tpu_fault attribute instead).  OOM first: an allocator failure
# often travels inside an ABORTED/INTERNAL envelope, and the resource
# marker is the more specific signal.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "Allocation failure",
)
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "Connection reset",
    "Socket closed",
    "failed to connect",
)

ROWS_SIDECAR_FORMAT = "ba_tpu.rows_sidecar"
ROWS_SIDECAR_VERSION = 1
# Engine ys-stream name -> assembled result key.
_STREAM_RESULT_KEYS = {
    "histograms": "histograms",
    "leaders": "leaders",
    "counter_rows": "counters_per_round",
    "decisions": "decisions",
}


class SupervisorError(RuntimeError):
    """The supervisor gave up (retry/recovery/degrade budgets exhausted)."""


class PoisonousWindow(SupervisorError):
    """The same campaign window failed ``poison_threshold`` times —
    quarantined with a minimal reproducer (``.reproducer``)."""

    def __init__(self, message: str, reproducer: dict):
        super().__init__(message)
        self.reproducer = reproducer


def classify_fault(exc: BaseException) -> str:
    """``transient`` | ``fatal`` | ``oom`` for a raised execution error.

    Precedence: the ``ba_tpu_fault`` duck-type marker (chaos-injected
    faults, or any caller-defined error that wants a classification),
    then OOM message markers, then transient message markers; everything
    unrecognized is FATAL — the safe default, because fatal recovery
    resumes from a checkpoint while a misclassified transient would
    retry a poisoned operation in place.
    """
    marker = getattr(exc, "ba_tpu_fault", None)
    if marker in (TRANSIENT, FATAL, OOM):
        return marker
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in OOM_MARKERS):
        return OOM
    if any(m in text for m in TRANSIENT_MARKERS):
        return TRANSIENT
    return FATAL


def fault_attribution(exc: BaseException) -> dict:
    """``{"fault": <classification>, "error": "<Type>: <msg>"}`` — the
    ONE spelling of fault attribution (ISSUE 10): the supervisor's
    ``recovery``/quarantine records and the serving front-end's
    per-cohort request failures (``runtime/serve.py``) attribute a
    raised execution error identically, so an operator joining
    ``recovery`` rows against ``request`` rows reads one taxonomy.
    The error text truncates at 200 chars like every record that
    carries one."""
    return {
        "fault": classify_fault(exc),
        "error": f"{type(exc).__name__}: {exc}"[:200],
    }


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    """Supervision dials.  ``None`` fields resolve from the environment
    at run time (``BA_TPU_MAX_RETRIES``, ``BA_TPU_SUPERVISE_TIMEOUT_S``)
    so a deployed campaign is tunable without code changes."""

    max_retries: int | None = None       # in-place transient retries/site
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter_frac: float = 0.25
    seed: int = 0                        # jitter determinism
    timeout_s: float | None = None       # retire watchdog; None = derive
    timeout_multiplier: float = 16.0
    timeout_floor_s: float = 30.0
    max_recoveries: int = 8              # checkpoint resumes per campaign
    max_degrades: int = 2                # OOM halvings per campaign
    poison_threshold: int = 3            # same-window failures -> quarantine

    def resolved_max_retries(self) -> int:
        if self.max_retries is not None:
            return self.max_retries
        return int(os.environ.get("BA_TPU_MAX_RETRIES", 3))


def backoff_s(cfg: SupervisorConfig, attempt: int, token: str) -> float:
    """Exponential backoff with DETERMINISTIC jitter.

    ``attempt`` >= 1; ``token`` names the retry site (phase + round
    window), so two sites at the same attempt draw different jitter
    while the same (seed, token, attempt) always draws the same delay —
    reproducible supervision, no global RNG state touched.
    """
    if attempt < 1:
        raise ValueError(f"attempt={attempt} must be >= 1")
    raw = min(
        cfg.backoff_base_s * cfg.backoff_factor ** (attempt - 1),
        cfg.backoff_max_s,
    )
    digest = hashlib.sha256(
        f"{cfg.seed}:{token}:{attempt}".encode()
    ).digest()
    u = int.from_bytes(digest[:8], "big") / 2.0**63 - 1.0  # [-1, 1)
    return max(0.0, raw * (1.0 + cfg.jitter_frac * u))


def derive_timeout_s(cfg: SupervisorConfig, registry=None) -> float:
    """The retire watchdog timeout: config pin > env pin > derived.

    Derivation reads the engine's own ``pipeline_dispatch_latency_s``
    histogram — ``timeout_multiplier x`` the WORST latency this process
    has observed, floored at ``timeout_floor_s`` (a fresh process with
    an empty histogram gets the floor; the first dispatches calibrate
    the next campaign's timeout for free).
    """
    if cfg.timeout_s is not None:
        return float(cfg.timeout_s)
    env = os.environ.get("BA_TPU_SUPERVISE_TIMEOUT_S")
    if env:
        return float(env)
    reg = registry if registry is not None else obs.default_registry()
    snap = reg.snapshot().get("pipeline_dispatch_latency_s")
    if snap and snap.get("count") and snap.get("max"):
        return max(cfg.timeout_floor_s, cfg.timeout_multiplier * snap["max"])
    return cfg.timeout_floor_s


def _stream_names(scenario, collect_decisions, with_counters):
    """The engine's retire-``ys`` stream layout, by name — must mirror
    ``pipeline_megastep``/``scenario_megastep`` output order exactly."""
    if scenario:
        names = ["histograms", "leaders", "counter_rows"]
        if collect_decisions:
            names.append("decisions")
        return names
    names = ["histograms"]
    if collect_decisions:
        names.append("decisions")
    if with_counters:
        names.append("counter_rows")
    return names


def _rows_sidecar_path(ckpt_path: str) -> str:
    return ckpt_path + ".rows.npz"


def _write_rows_sidecar(path, streams, start, upto, names) -> None:
    """Persist the campaign history rows [start, upto) next to a
    checkpoint (atomic, versioned like every durable shape in the repo).
    ``streams`` is one stacked ``[upto - start, ...]`` array per name.

    On a ``{round}``-templated checkpoint family each sidecar is a
    DELTA — only the rows since the previous checkpoint — so the
    per-campaign sidecar I/O is O(R), not O(R^2/checkpoint_every);
    recovery merges the family's chain back into the full history.
    Sidecars are derived data, so the write skips the fsync the carry
    checkpoint pays (``durable=False``): a garbled one fails its own
    schema check and costs assembled history, never the resume.
    """
    arrays = dict(zip(names, streams))
    meta = {
        "format": ROWS_SIDECAR_FORMAT,
        "v": ROWS_SIDECAR_VERSION,
        "start": start,
        "round": upto,
        "streams": list(names),
    }

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(fh, __meta__=np.asarray(json.dumps(meta)), **arrays)

    _snapshot._atomic_write(path, write, durable=False)


def _read_rows_sidecar(path, names):
    """-> (start, upto, [stream arrays]) or None when missing or
    unusable — a sidecar is DERIVED data: a broken one costs the
    campaign prefix in the assembled result, never the resume itself."""
    try:
        with np.load(path, allow_pickle=False) as data:
            fields = {k: data[k] for k in data.files}
        meta = json.loads(str(fields.pop("__meta__")))
    except Exception:
        return None
    if (
        meta.get("format") != ROWS_SIDECAR_FORMAT
        or meta.get("v") != ROWS_SIDECAR_VERSION
        or meta.get("streams") != list(names)
    ):
        return None
    start, upto = meta.get("start"), meta.get("round")
    if not (isinstance(start, int) and isinstance(upto, int)):
        return None
    if any(
        n not in fields or len(fields[n]) != upto - start for n in names
    ):
        return None
    return start, upto, [fields[n] for n in names]


# The collected campaign history is BLOCK-structured, exactly as the
# engine retires it: ``blocks[lo] = (hi, [stream arrays])`` for each
# dispatch window [lo, hi) — zero copies on the hot path (the arrays
# are the retire fetch's own host blocks), and sidecar/stitch work is
# array concatenation, not per-round Python.  Replayed windows after a
# recovery land on the same lo grid (resume points are dispatch
# boundaries) and overwrite with bit-identical data; after an OOM
# degrade the grid is finer, and the coverage walk below simply chains
# the finer blocks.


def _block_cover(blocks, start, end):
    """Contiguous block chain covering [start, end), as
    ``[(lo, hi, streams)]``, or None when there is a gap."""
    out, pos = [], start
    while pos < end:
        blk = blocks.get(pos)
        if blk is None or blk[0] <= pos:
            return None
        out.append((pos, blk[0], blk[1]))
        pos = blk[0]
    return out


def _slice_cover(cover, start, end, n_streams):
    """One stacked [end - start, ...] array per stream out of a block
    chain (views where a single block suffices)."""
    parts = [[] for _ in range(n_streams)]
    for lo, hi, streams in cover:
        s, e = max(start, lo), min(end, hi)
        if s >= e:
            continue
        for i in range(n_streams):
            parts[i].append(streams[i][s - lo:e - lo])
    return [
        p[0] if len(p) == 1 else np.concatenate(p) for p in parts
    ]


def _campaign_fingerprint(key, rounds, scenario):
    """sha256 identity of THIS campaign (key material + rounds +
    compiled scenario content), stamped into every checkpoint the
    supervised run writes (``campaign_sha256`` in ``__meta__``) and
    verified by ``resume="auto"``: a checkpoint family left behind by a
    DIFFERENT campaign at the same path must refuse loudly instead of
    silently splicing someone else's carry into this run.  ``None``
    when the key is unavailable (explicit-resume entry): stamping and
    verification both skip, exactly like pre-digest checkpoints.
    """
    if key is None:
        return None
    import jax

    h = hashlib.sha256()
    h.update(str(int(rounds)).encode())
    try:
        key_bytes = np.asarray(jax.random.key_data(key)).tobytes()
    except TypeError:
        key_bytes = np.asarray(key).tobytes()
    h.update(key_bytes)
    if scenario is None:
        h.update(b"plain-sweep")
    elif hasattr(scenario, "to_doc"):
        h.update(
            json.dumps(scenario.to_doc(), sort_keys=True).encode()
        )
    else:
        for name in ("kill", "revive", "set_faulty", "set_strategy"):
            h.update(np.asarray(getattr(scenario, name)).tobytes())
    return h.hexdigest()


def _read_rows_chain(ckpt_template, names):
    """Merge every delta sidecar of a ``{round}``-templated checkpoint
    family into a blocks dict.  Unreadable or schema-drifted deltas are
    skipped (derived data); the caller checks contiguous coverage
    before trusting the merged history.

    Scans the SIDECAR files themselves (``<tmpl>.rows.npz`` is itself a
    ``{round}``-templated family), not the surviving checkpoints: under
    ``checkpoint_keep_last`` retention the supervisor prunes old CARRY
    checkpoints but keeps their sidecars — the sidecars are the
    campaign history, O(R) total by design — so a successor can still
    assemble the full result even when the kill landed many checkpoint
    intervals in."""
    blocks = {}
    for _, path in _snapshot.checkpoint_paths(
        _rows_sidecar_path(ckpt_template)
    ):
        side = _read_rows_sidecar(path, names)
        if side is not None:
            blocks[side[0]] = (side[1], side[2])
    return blocks


def supervised_sweep(  # ba-lint: donates(state)
    key,
    state,
    rounds: int | None = None,
    *,
    scenario=None,
    resume="auto",
    **kwargs,
):
    """Run a campaign under supervision, inside ONE flight-recorder run
    scope (ISSUE 9).

    The thin public layer over :func:`_supervised_sweep_impl` (which
    documents the supervision surface — chaos plans, SupervisorConfig,
    resume="auto", recovery/degrade/poison semantics): it resolves the
    campaign's run_id BEFORE the first attempt — ``BA_TPU_RUN_ID`` >
    an active scope > the resume checkpoint's stored id > a sha256
    over the same (key, rounds, scenario) identity the campaign
    fingerprint hashes — and holds the scope across EVERY attempt, so
    retries, recoveries and the records they emit all correlate to one
    run (and a killed process's successor, re-deriving the same id,
    joins its predecessor's ledger).  The scope owner emits the
    assembled ``flight_summary`` at the end; the id rides
    ``result["supervisor"]["run_id"]``.
    """
    n_rounds = rounds
    if n_rounds is None and scenario is not None:
        n_rounds = scenario.rounds
    inherited = None
    if key is None and resume is not None and resume != "auto":
        # Explicit-resume entry: the checkpoint header is the only
        # identity we have — adopt its run_id (an unreadable/pre-
        # recorder checkpoint just falls through to derivation; the
        # impl will surface the real error).
        if isinstance(resume, str):
            try:
                inherited = _snapshot.validate_carry_checkpoint(
                    resume
                ).get("run_id")
            except (OSError, ValueError):
                inherited = None
        else:
            inherited = getattr(resume, "run_id", None)

    def _identity_material():
        # Deferred: the fingerprint hashes the full scenario content —
        # wasted when BA_TPU_RUN_ID / an outer scope decides the id.
        fingerprint = (
            _campaign_fingerprint(key, n_rounds, scenario)
            if key is not None and n_rounds is not None
            else None
        )
        return ("supervised", fingerprint or "", n_rounds)

    rid = obs.flight.resolve_run_id(
        inherited=inherited, material_fn=_identity_material
    )
    with obs.flight.run_scope(rid) as scope:
        result = _supervised_sweep_impl(
            key, state, rounds, scenario=scenario, resume=resume, **kwargs
        )
        result["supervisor"]["run_id"] = scope.run_id
        if scope.owner:
            obs.flight.emit_flight_summary(run_id=scope.run_id)
    return result


def _supervised_sweep_impl(  # ba-lint: donates(state)
    key,
    state,
    rounds: int | None = None,
    *,
    scenario=None,
    chaos=None,
    config: SupervisorConfig | None = None,
    collect_decisions: bool = False,
    with_counters: bool = False,
    depth: int = 2,
    rounds_per_dispatch: int = 1,
    checkpoint_every: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_keep_last: int | None = None,
    on_checkpoint=None,
    resume="auto",
    **engine_kwargs,
):
    """Run ``pipeline_sweep``/``scenario_sweep`` under supervision.

    Same surface as :func:`ba_tpu.parallel.pipeline.pipeline_sweep`
    (``rounds`` defaults to ``scenario.rounds``; every engine dial
    passes through) plus:

    - ``chaos`` — a :class:`ba_tpu.runtime.chaos.FaultPlan` (or a live
      ``ChaosInjector``) whose faults fire deterministically from the
      execution seam and checkpoint hook;
    - ``config`` — a :class:`SupervisorConfig`;
    - ``resume="auto"`` — scan ``checkpoint_path`` for the newest VALID
      checkpoint before starting (quarantining corrupt ones) and
      continue from it: a killed process's successor picks the campaign
      up by rerunning the same call.  ``resume=None`` forces a fresh
      start; an explicit checkpoint/path behaves like the engine's
      ``resume=``.

    Returns the engine's result dict with per-round arrays stitched
    across every attempt (bit-identical to an uninterrupted run when
    the campaign history is complete) plus a ``"supervisor"`` stats
    block (attempts, retries, recoveries, degrades, stalls, lost
    rounds, injected faults, resolved timeout).

    MESH (ISSUE 8): ``mesh=`` passes through to the engine like any
    other dial, and recovery works unchanged — checkpoints are
    device-count-free (gather-on-write), every resume re-splits the
    carry for the attempt's mesh (reshard-on-read), and the rows
    history the supervisor persists is already host-tree-reduced to
    canonical shapes, so the stitched result is bit-identical at any
    device count (pinned by the mesh fatal-recovery test).

    DONATION: ``state`` is copied up front (the supervisor may need to
    restart from round 0), so unlike the raw engine the caller's state
    stays live — but callers should not rely on that divergence.
    """
    from ba_tpu.parallel.pipeline import fresh_copy, pipeline_sweep

    cfg = config or SupervisorConfig()
    if rounds is None:
        if scenario is None:
            raise ValueError("rounds is required without a scenario block")
        rounds = scenario.rounds
    for k in ("exec_seam", "on_rows", "retire_timeout_s", "on_stall",
              "checkpoint_meta"):
        if k in engine_kwargs:
            raise ValueError(f"{k} is owned by the supervisor")
    if scenario is not None:
        with_counters = True
    names = _stream_names(
        scenario is not None, collect_decisions, with_counters
    )
    if checkpoint_keep_last is not None:
        # Mirror the engine's eager validation: the supervisor owns
        # retention (sidecar-preserving — see chained_on_checkpoint), so
        # the engine never sees checkpoint_keep_last and would not
        # reject a bad combination for us.
        if checkpoint_keep_last < 1:
            raise ValueError(
                f"checkpoint_keep_last={checkpoint_keep_last} must be >= 1"
            )
        if checkpoint_every is None:
            raise ValueError("checkpoint_keep_last needs checkpoint_every")
        if "{round}" in os.path.dirname(checkpoint_path or ""):
            raise ValueError(
                "checkpoint_path cannot carry the {round} slot in its "
                "directory component (retention scans one directory)"
            )
        if "{round}" not in os.path.basename(checkpoint_path or ""):
            raise ValueError(
                "checkpoint_keep_last needs a {round}-templated "
                "checkpoint FILENAME (the directory component cannot "
                "carry the slot)"
            )

    injector = chaos
    if injector is not None and not hasattr(injector, "fire"):
        from ba_tpu.runtime.chaos import ChaosInjector

        injector = ChaosInjector(injector)

    max_retries = cfg.resolved_max_retries()
    timeout_s = derive_timeout_s(cfg)
    if timeout_s <= 0:
        # Eagerly, with the knob named: the engine's own rejection would
        # otherwise surface from inside the first attempt.  There is no
        # "disable the watchdog" spelling — supervision without stall
        # detection is half a supervisor; raise the floor instead.
        raise ValueError(
            f"supervise timeout {timeout_s} must be > 0 "
            f"(SupervisorConfig.timeout_s / BA_TPU_SUPERVISE_TIMEOUT_S)"
        )
    reg = obs.default_registry()
    faults_c = reg.counter("supervisor_faults_total")
    retries_c = reg.counter("supervisor_retries_total")
    recoveries_c = reg.counter("supervisor_recoveries_total")
    degrades_c = reg.counter("supervisor_degrades_total")
    stalls_c = reg.counter("supervisor_stalls_total")
    quarantine_c = reg.counter("supervisor_quarantined_total")

    # The supervisor may restart from scratch after a pre-checkpoint
    # fatal; the engine donates its input state, so keep a master copy.
    master_state = fresh_copy(state) if state is not None else None

    blocks: dict = {}        # lo -> (hi, [stream arrays]) per retire
    history_start = 0        # first round the collected history covers
    sidecar_upto = 0         # rows persisted to delta sidecars so far
    n_retries = 0
    n_stalls = 0
    n_checkpoints_total = 0
    n_recoveries = 0
    n_degrades = 0
    lost_rounds_total = 0
    window_failures: dict = {}
    cur_depth = depth
    cur_rpd = rounds_per_dispatch

    fingerprint = _campaign_fingerprint(key, rounds, scenario)

    def accept_meta(meta):
        # Campaign-identity filter for every checkpoint scan: only OUR
        # family members (or unstamped pre-fingerprint ones) may seed a
        # resume — a foreign campaign's carry at the same path is
        # stepped over, never spliced in and never quarantined.
        return fingerprint is None or meta.get("campaign_sha256") in (
            None, fingerprint,
        )

    resume_arg = None
    # Causal continuity (ISSUE 19): the checkpoint header's traceparent
    # — the writer's trace position at write time — re-parents every
    # resumed attempt's spans under the pre-crash span, so the merged
    # fleet tree stays fully parented across process deaths.
    resume_tp = None
    if resume == "auto":
        if checkpoint_path is not None:
            # below=rounds: a COMPLETED campaign's final checkpoint is
            # valid but not resumable (the engine refuses a cursor at
            # the campaign end) — rerunning the same call must replay
            # the last window from the previous checkpoint, not poison
            # itself retrying the final one.
            found = _snapshot.newest_valid_checkpoint(
                checkpoint_path, below=rounds, accept=accept_meta
            )
            if found is None and fingerprint is not None:
                # Nothing of OURS — but if a foreign family holds the
                # path, starting fresh would interleave two campaigns'
                # checkpoints at one template: refuse loudly (this is
                # the path-collision operator error, caught before any
                # work runs).
                foreign = _snapshot.newest_valid_checkpoint(
                    checkpoint_path, quarantine=False, below=rounds
                )
                if foreign is not None:
                    stored = foreign[1].get("campaign_sha256")
                    obs.trace.flush_export()
                    raise SupervisorError(
                        f"checkpoint family at {checkpoint_path!r} "
                        f"belongs to a DIFFERENT campaign (stored "
                        f"fingerprint {(stored or '?')[:12]}..., this "
                        f"campaign {fingerprint[:12]}...) — resuming "
                        f"would silently splice its carry into this "
                        f"run; pass a fresh checkpoint_path (or "
                        f"resume=None to overwrite the family "
                        f"knowingly)"
                    )
            if found is not None:
                resume_arg = found[0]
                resume_tp = found[1].get("traceparent")
                r0 = found[1]["round"]
                if "{round}" in checkpoint_path:
                    blocks.update(_read_rows_chain(checkpoint_path, names))
                else:
                    side = _read_rows_sidecar(
                        _rows_sidecar_path(found[0]), names
                    )
                    if side is not None:
                        blocks[side[0]] = (side[1], side[2])
                if _block_cover(blocks, 0, r0) is None:
                    # No usable history: the assembled result can only
                    # cover the tail.  Resume anyway — cumulative
                    # counters ride the carry, so campaign TOTALS stay
                    # exact regardless.
                    history_start = r0
                sidecar_upto = r0
                # Flight-recorder edge (ISSUE 9): an auto-resume entry
                # IS a recovery — the predecessor process died between
                # this checkpoint and campaign end (or completed, and
                # the rerun replays the final window), and nobody else
                # records the cross-process seam.  One `recovery`
                # record stitches the two processes' ledgers; the
                # supervisor's recovery BUDGET is untouched (nothing
                # failed in THIS process).
                obs.instant(
                    "recovery", fault=FATAL, action="resume", attempt=0,
                    from_round=r0, lost_rounds=0,
                )
                _metrics.emit(
                    {
                        "event": "recovery",
                        "v": _metrics.SCHEMA_VERSION,
                        "fault": FATAL,
                        "action": "resume",
                        "attempt": 0,
                        "from_round": r0,
                        "lost_rounds": 0,
                        "error": (
                            "auto-resume: prior process left a valid "
                            "checkpoint family"
                        ),
                    }
                )
    elif resume is not None:
        resume_arg = resume
        if isinstance(resume, str):
            meta = _snapshot.validate_carry_checkpoint(resume)
            r0 = meta["round"]
            resume_tp = meta.get("traceparent")
        else:
            r0 = resume.round
        history_start = r0
        sidecar_upto = r0

    def on_stall_cb(d, t):
        nonlocal n_stalls
        n_stalls += 1
        stalls_c.inc()

    def on_rows_cb(d, lo, hi, host_ys):
        # Zero-copy: the retire fetch's own host blocks, keyed by their
        # round window.  Replays after a recovery land on the same lo
        # grid and overwrite with bit-identical data.
        blocks[lo] = (hi, list(host_ys))

    def seam(call, phase, d, lo, hi):
        # Pillar 2: in-place transient retry with backoff + jitter.
        # Injected faults raise BEFORE the wrapped operation consumes
        # anything, so re-running the same zero-arg call is bit-exact;
        # a real post-donation failure raises use-after-donate on the
        # retry and escalates to recovery via classification (fatal).
        nonlocal n_retries
        wrapped = (
            call if injector is None
            else lambda: injector.fire(call, phase, lo, hi)
        )
        tries = 0
        while True:
            try:
                return wrapped()
            except Exception as e:
                if classify_fault(e) != TRANSIENT or tries >= max_retries:
                    raise
                tries += 1
                n_retries += 1
                retries_c.inc()
                delay = backoff_s(cfg, tries, f"{phase}:{lo}")
                obs.instant(
                    "supervisor_retry", phase=phase, dispatch=d, lo=lo,
                    attempt=tries, delay_s=round(delay, 4),
                )
                time.sleep(delay)

    def chained_on_checkpoint(round_cursor, path):
        # Rows first (the engine delivered this retire's rows before
        # firing the checkpoint hook), then chaos corruption (it must
        # damage the REAL file, after the sidecar exists), then the
        # caller's hook.  Templated families persist DELTAS (O(R) total
        # sidecar I/O; recovery merges the chain); a single-file family
        # has nowhere to chain, so it rewrites the full prefix.
        nonlocal sidecar_upto, n_checkpoints_total
        n_checkpoints_total += 1
        if "{round}" in (checkpoint_path or ""):
            lo = min(sidecar_upto, round_cursor)
            cover = _block_cover(blocks, lo, round_cursor)
            if round_cursor > lo and cover is not None:
                _write_rows_sidecar(
                    _rows_sidecar_path(path),
                    _slice_cover(cover, lo, round_cursor, len(names)),
                    lo, round_cursor, names,
                )
                sidecar_upto = max(sidecar_upto, round_cursor)
        else:
            cover = _block_cover(blocks, history_start, round_cursor)
            if cover is not None:
                _write_rows_sidecar(
                    _rows_sidecar_path(path),
                    _slice_cover(
                        cover, history_start, round_cursor, len(names)
                    ),
                    history_start, round_cursor, names,
                )
        if checkpoint_keep_last is not None:
            # Supervisor-owned retention: prune old CARRY checkpoints
            # only (companions=False) — their rows sidecars stay, so a
            # cross-process successor can assemble the FULL history even
            # when the kill landed more than keep_last checkpoint
            # intervals into the campaign.
            _snapshot.prune_checkpoints(
                checkpoint_path, checkpoint_keep_last, companions=False
            )
        if injector is not None:
            injector.after_checkpoint(round_cursor, path)
        if on_checkpoint is not None:
            on_checkpoint(round_cursor, path)

    def completed_round():
        # The campaign's high-water mark: bit-exact replay makes this
        # stable across attempts, which is what keys poison detection.
        done = history_start
        while True:
            blk = blocks.get(done)
            if blk is None or blk[0] <= done:
                return done
            done = blk[0]

    attempt = 0
    while True:
        attempt += 1
        # A resumed attempt takes its strategy plane from the carry
        # (bit-exact continuation); forwarding the caller's t=0 plane
        # alongside is an engine-level ValueError that would otherwise
        # masquerade as an unrecoverable fatal in the recovery loop.
        attempt_kwargs = engine_kwargs
        if resume_arg is not None and "initial_strategy" in engine_kwargs:
            attempt_kwargs = {
                k: v for k, v in engine_kwargs.items()
                if k != "initial_strategy"
            }
        try:
            # inject_scope: a resumed attempt adopts the checkpoint
            # header's traceparent (its spans parent under the
            # pre-crash position); a fresh attempt falls back to
            # BA_TPU_TRACE_CONTEXT, else runs untraced.  mark: the
            # adopted attempt root materializes as a record up front,
            # so even an attempt that dies mid-flight leaves the span
            # its windows parent under in-stream.
            with obs.trace.inject_scope(
                resume_tp, mark="supervised_attempt"
            ), obs.span(
                "supervised_attempt", attempt=attempt,
                start=0 if resume_arg is None else -1,
            ):
                res = pipeline_sweep(
                    None if resume_arg is not None else key,
                    None
                    if resume_arg is not None
                    else (
                        fresh_copy(master_state)
                        if master_state is not None
                        else None
                    ),
                    rounds,
                    scenario=scenario,
                    collect_decisions=collect_decisions,
                    with_counters=with_counters,
                    depth=cur_depth,
                    rounds_per_dispatch=cur_rpd,
                    checkpoint_every=checkpoint_every,
                    checkpoint_path=checkpoint_path,
                    # Retention is supervisor-owned (sidecar-preserving;
                    # see chained_on_checkpoint), never the engine's.
                    checkpoint_keep_last=None,
                    checkpoint_meta=(
                        {"campaign_sha256": fingerprint}
                        if checkpoint_every is not None
                        and fingerprint is not None
                        else None
                    ),
                    on_checkpoint=(
                        chained_on_checkpoint
                        if checkpoint_every is not None
                        else None
                    ),
                    exec_seam=seam,
                    retire_timeout_s=timeout_s,
                    on_stall=on_stall_cb,
                    on_rows=on_rows_cb,
                    resume=resume_arg,
                    **attempt_kwargs,
                )
            break
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            if isinstance(e, ValueError) and not hasattr(e, "ba_tpu_fault"):
                # Engine/parameter validation is DETERMINISTIC: a
                # ValueError (without a chaos classification marker)
                # raises the same way on every attempt — recovering
                # through it would burn the poison budget re-running
                # the campaign from scratch and then misreport a
                # one-line config error as a PoisonousWindow.
                obs.trace.flush_export()
                raise
            attribution = fault_attribution(e)
            kind = attribution["fault"]
            faults_c.inc()
            fail_round = completed_round()
            window_failures[fail_round] = (
                window_failures.get(fail_round, 0) + 1
            )
            if window_failures[fail_round] >= cfg.poison_threshold:
                _quarantine_window(
                    e, kind, fail_round, rounds, cur_depth, cur_rpd,
                    checkpoint_path, window_failures[fail_round],
                    quarantine_c, accept_meta,
                )
            action = "resume"
            if kind == OOM and n_degrades < cfg.max_degrades:
                # Pillar 4: degrade the SCHEDULE, never the batch —
                # depth first (fewer donated carries in flight), then
                # the per-dispatch round count (smaller working set).
                action = "degrade"
                n_degrades += 1
                degrades_c.inc()
                if cur_depth > 1:
                    cur_depth = max(1, cur_depth // 2)
                else:
                    cur_rpd = max(1, cur_rpd // 2)
            elif n_recoveries >= cfg.max_recoveries:
                # Fatal path (ISSUE 19 satellite): export the Chrome
                # trace NOW — the atexit hook alone loses the buffer
                # when an embedding hard-exits, and a crashed
                # campaign's trace is exactly the one worth keeping.
                obs.trace.flush_export()
                raise SupervisorError(
                    f"recovery budget exhausted after {n_recoveries} "
                    f"resume(s); last fault: {type(e).__name__}: {e}"
                ) from e
            else:
                n_recoveries += 1
                recoveries_c.inc()

            # Pillar 3: reload the newest checkpoint that still
            # validates (corrupt ones quarantine to .corrupt and the
            # scan falls back; below=rounds — a final-cursor checkpoint
            # cannot seed a resume), or restart from round 0 when none
            # survives.
            resume_arg = None
            resume_tp = None
            from_round = 0
            if checkpoint_path is not None:
                found = _snapshot.newest_valid_checkpoint(
                    checkpoint_path, below=rounds, accept=accept_meta
                )
                if found is not None:
                    resume_arg = found[0]
                    resume_tp = found[1].get("traceparent")
                    from_round = found[1]["round"]
            if resume_arg is None:
                # From-scratch restart: the fresh run re-covers
                # [0, from_round) too, so the collected history starts
                # at 0 again even if the original resume had no usable
                # sidecar chain (history_start > 0 would silently
                # truncate the full result the restart computes).
                history_start = 0
            if resume_arg is None and master_state is None:
                # Entered via explicit resume= (key/state None, per the
                # engine contract) and no checkpoint survived the scan:
                # a from-scratch restart has nothing to start FROM, and
                # letting the engine crash on state=None would bury the
                # real fault under a TypeError.
                obs.trace.flush_export()
                raise SupervisorError(
                    f"cannot recover: no valid checkpoint at "
                    f"{checkpoint_path!r} and no initial state to "
                    f"restart from (the campaign was entered via an "
                    f"explicit resume=); last fault: "
                    f"{type(e).__name__}: {e}"
                ) from e
            # Re-cover the delta-sidecar chain from the resume point: a
            # quarantined checkpoint took its sidecar with it, and the
            # replayed attempt must re-write those deltas (from the
            # in-memory rows, bit-exact) or a LATER cross-process resume
            # would find a hole in the chain.
            sidecar_upto = min(sidecar_upto, from_round)
            lost = max(0, fail_round - from_round)
            lost_rounds_total += lost
            obs.instant(
                "recovery", fault=kind, action=action, attempt=attempt,
                from_round=from_round, lost_rounds=lost,
            )
            _metrics.emit(
                {
                    "event": "recovery",
                    "v": _metrics.SCHEMA_VERSION,
                    "fault": kind,
                    "action": action,
                    "attempt": attempt,
                    "from_round": from_round,
                    "lost_rounds": lost,
                    "error": attribution["error"],
                }
            )
            if kind in (TRANSIENT, OOM):
                time.sleep(
                    backoff_s(cfg, attempt, f"recover:{from_round}")
                )
            # A from-scratch restart re-covers [0, from_round) too;
            # rows are replayed bit-exactly either way.

    result = dict(res)
    if checkpoint_every is not None or n_stalls:
        # The engine's stats block describes the FINAL attempt only (a
        # failed attempt's stats die with its exception); checkpoints
        # and stalls are tracked supervisor-side across every attempt —
        # an operator auditing durability cadence must see all writes,
        # not the last attempt's share.
        result["stats"] = dict(
            res["stats"],
            checkpoints=n_checkpoints_total,
            stalls=n_stalls,
        )
    done = completed_round()
    cover = _block_cover(blocks, history_start, rounds)
    if cover is not None:
        stacked = _slice_cover(cover, history_start, rounds, len(names))
        for i, name in enumerate(names):
            result[_STREAM_RESULT_KEYS[name]] = stacked[i]
    result["supervisor"] = {
        "attempts": attempt,
        "retries": n_retries,
        "recoveries": n_recoveries,
        "degrades": n_degrades,
        "stalls": n_stalls,
        "lost_rounds": lost_rounds_total,
        "timeout_s": round(timeout_s, 6),
        "depth": cur_depth,
        "rounds_per_dispatch": cur_rpd,
        "history_start": history_start,
        "history_rounds": done - history_start,
        "injected": len(injector.fired) if injector is not None else 0,
    }
    return result


def _quarantine_window(
    exc, kind, fail_round, rounds, depth, rpd, checkpoint_path, failures,
    quarantine_c, accept_meta,
):
    """Give up on a poisoned window: build + persist the minimal
    reproducer and raise :class:`PoisonousWindow`."""
    quarantine_c.inc()
    # Same filters as every resume scan: the reproducer's hint must
    # name a checkpoint the supervisor itself would resume from — not a
    # foreign campaign's member or the unresumable final cursor.
    newest = (
        _snapshot.newest_valid_checkpoint(
            checkpoint_path, below=rounds, accept=accept_meta
        )
        if checkpoint_path is not None
        else None
    )
    reproducer = {
        "window": [fail_round, min(rounds, fail_round + rpd)],
        "rounds": rounds,
        "depth": depth,
        "rounds_per_dispatch": rpd,
        "failures": failures,
        "fault": kind,
        "error": fault_attribution(exc)["error"],
        "resume": newest[0] if newest is not None else None,
        "hint": (
            "re-run pipeline_sweep(resume=<resume>, "
            "rounds_per_dispatch=1, depth=1) to replay the window "
            "dispatch-by-dispatch"
        ),
    }
    if checkpoint_path is not None:
        target = os.path.join(
            os.path.dirname(checkpoint_path) or ".",
            f"poison_{fail_round}.json",
        )
        try:
            with open(target, "w") as fh:
                json.dump(reproducer, fh, indent=2)
            reproducer["reproducer_path"] = target
        except OSError:
            pass
    obs.instant("poison_quarantine", round=fail_round, failures=failures)
    _metrics.emit(
        {
            "event": "recovery",
            "v": _metrics.SCHEMA_VERSION,
            "fault": kind,
            "action": "quarantine",
            "attempt": failures,
            "from_round": fail_round,
            "lost_rounds": 0,
            "error": reproducer["error"],
        }
    )
    # Fatal path (ISSUE 19 satellite): a poisoned campaign is exactly
    # the one someone diagnoses FROM the trace — export before raising,
    # not at a process exit that may never run the atexit hooks.
    obs.trace.flush_export()
    raise PoisonousWindow(
        f"campaign window starting at round {fail_round} failed "
        f"{failures} time(s) — quarantined; minimal reproducer: "
        f"{json.dumps(reproducer, sort_keys=True)}",
        reproducer,
    ) from exc
