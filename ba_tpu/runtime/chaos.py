"""Host-side fault injection: deterministic chaos for the supervised
engine (ISSUE 7).

The paper's failure model is simulated-world (a traitor lies, a general
dies on command); the EXECUTION layer's failure model — a raised XLA
error, a hung dispatch, a preempted process, a rotten checkpoint — had
no counterpart until the execution supervisor
(``ba_tpu.runtime.supervisor``).  This module is the supervisor's proof
harness: a :class:`FaultPlan` is plain data (JSON round-trip, eagerly
validated, exactly the scenario-spec pattern) naming faults at chosen
ROUNDS, and a :class:`ChaosInjector` fires them deterministically from
the engine's execution seam (``pipeline_sweep(exec_seam=...)``) and
checkpoint hook:

- ``transient`` / ``fatal`` / ``oom`` — raise a marked exception
  (:class:`InjectedTransient` / :class:`InjectedFatal` /
  :class:`InjectedOOM`) before the wrapped operation runs, so the
  donated carry is NEVER consumed by an injected failure and an
  in-place retry is bit-exact;
- ``stall`` — sleep ``seconds`` inside the watchdogged region (at the
  ``retire`` phase this sits inside the engine's retire-timeout timer,
  so an injected stall trips the real watchdog);
- ``kill`` — ``SIGKILL`` this process mid-campaign: the real
  preemption, used by the subprocess recovery tests and the
  ``resilience`` bench;
- ``corrupt`` — damage the just-written checkpoint file (``flip`` bytes
  mid-file or ``truncate`` it), exercising digest verification and
  quarantine fallback;
- ``slow_client`` / ``abandon`` / ``deadline_storm`` (ISSUE 10) —
  CLIENT-tier faults, fired at the ``client`` phase by a serving load
  harness via :meth:`ChaosInjector.client_faults` and keyed by request
  ordinal: they shape the synthetic callers of the agreement service
  (``runtime/serve.py``) — late arrivals, never-read tickets, a fleet
  flipping to near-zero deadlines — so the overload-survival drills
  are as declarative and reproducible as the engine-fault ones.

Faults are keyed by ROUND, not dispatch index: dispatch numbering
restarts at 0 on every supervised resume, while the round cursor is the
campaign's stable coordinate — a ``times: 1`` fault fired before a
recovery stays fired after it (one injector instance spans the whole
supervised run).

Jax-free by construction (stdlib + the host-side obs/metrics layers):
``python -m ba_tpu.runtime.chaos plan.json ...`` validates committed
plans in milliseconds, exactly like ``python -m ba_tpu.scenario`` does
for campaign specs, and ``scripts/ci.sh`` runs it as the chaos smoke
stage.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time

from ba_tpu import obs
from ba_tpu.utils import metrics as _metrics

# Client-tier kinds (ISSUE 10): faults of the CALLERS, not the engine —
# they fire at the "client" phase, consumed by a serving-load harness
# (bench.py's `serving` config, tests/test_serve.py) shaping synthetic
# clients against the agreement service (runtime/serve.py), keyed by
# REQUEST ORDINAL instead of campaign round:
#
# - ``slow_client`` — the client sleeps ``seconds`` before submitting
#   (a stalled upstream: requests arrive late and bunch up);
# - ``abandon`` — the client submits and never reads its ticket (the
#   service must complete/expire it without anyone waiting);
# - ``deadline_storm`` — the client fleet switches to near-zero
#   deadline budgets from this ordinal on (every coalesced batch then
#   races admission-time expiry — the overload-survival drill).
CLIENT_FAULT_KINDS = ("slow_client", "abandon", "deadline_storm")
FAULT_KINDS = (
    "transient", "fatal", "oom", "stall", "kill", "corrupt"
) + CLIENT_FAULT_KINDS
# corrupt fires from the checkpoint hook, client kinds from the serving
# load harness, everything else from the execution seam's
# dispatch/retire phases.
FAULT_PHASES = ("dispatch", "retire", "checkpoint", "client")


class FaultPlanError(ValueError):
    """A malformed fault plan (bad kind/phase/fields) — eagerly raised
    at ``from_dict`` time, never mid-campaign."""


class InjectedFault(RuntimeError):
    """Base of every chaos-raised error; ``ba_tpu_fault`` is the
    classification marker ``supervisor.classify_fault`` reads (duck
    typing, so the supervisor never imports this module)."""

    ba_tpu_fault = "fatal"


class InjectedTransient(InjectedFault):
    ba_tpu_fault = "transient"


class InjectedFatal(InjectedFault):
    ba_tpu_fault = "fatal"


class InjectedOOM(InjectedFault):
    """Message mimics the XLA allocator's phrasing so the string-marker
    classification path is exercised too, not just the duck-typed one."""

    ba_tpu_fault = "oom"


_RAISES = {
    "transient": InjectedTransient,
    "fatal": InjectedFatal,
    "oom": InjectedOOM,
}


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned fault.  ``round`` is the campaign round it keys on —
    or, for client-tier kinds (``phase == "client"``), the REQUEST
    ORDINAL in the load harness's submission sequence.  ``times`` is
    how often it fires (-1 = unlimited — the poison-window tests);
    ``seconds`` is the stall/slow-client length; ``mode`` the
    corruption style."""

    round: int
    kind: str
    phase: str = "dispatch"
    times: int = 1
    seconds: float = 0.0
    mode: str = "flip"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    name: str
    faults: tuple


def from_dict(doc: dict) -> FaultPlan:
    """Parse + eagerly validate a fault-plan document."""
    if not isinstance(doc, dict):
        raise FaultPlanError(f"fault plan must be an object, got {type(doc)}")
    unknown = set(doc) - {"name", "faults"}
    if unknown:
        raise FaultPlanError(f"unknown fault plan key(s) {sorted(unknown)}")
    name = doc.get("name")
    if not isinstance(name, str) or not name:
        raise FaultPlanError(f"fault plan needs a non-empty name, got {name!r}")
    raw = doc.get("faults")
    if not isinstance(raw, list):
        raise FaultPlanError(f"faults must be a list, got {type(raw)}")
    faults = []
    for i, f in enumerate(raw):
        if not isinstance(f, dict):
            raise FaultPlanError(f"faults[{i}] must be an object")
        unknown = set(f) - {"round", "kind", "phase", "times", "seconds",
                            "mode"}
        if unknown:
            raise FaultPlanError(
                f"faults[{i}]: unknown key(s) {sorted(unknown)}"
            )
        kind = f.get("kind")
        if kind not in FAULT_KINDS:
            raise FaultPlanError(
                f"faults[{i}]: kind {kind!r} not in {FAULT_KINDS}"
            )
        rnd = f.get("round")
        if not isinstance(rnd, int) or isinstance(rnd, bool) or rnd < 0:
            raise FaultPlanError(f"faults[{i}]: bad round {rnd!r}")
        phase = f.get("phase", _default_phase(kind))
        if phase not in FAULT_PHASES:
            raise FaultPlanError(
                f"faults[{i}]: phase {phase!r} not in {FAULT_PHASES}"
            )
        if phase != _default_phase(kind) and not (
            kind not in ("corrupt",) + CLIENT_FAULT_KINDS
            and phase in ("dispatch", "retire")
        ):
            raise FaultPlanError(
                f"faults[{i}]: kind {kind!r} cannot fire at phase {phase!r} "
                f"(corrupt fires at 'checkpoint', client kinds "
                f"{CLIENT_FAULT_KINDS} at 'client', everything else at "
                f"'dispatch'/'retire')"
            )
        times = f.get("times", 1)
        if not isinstance(times, int) or isinstance(times, bool) or (
            times < 1 and times != -1
        ):
            raise FaultPlanError(
                f"faults[{i}]: times must be >= 1 or -1 (unlimited), "
                f"got {times!r}"
            )
        seconds = f.get("seconds", 0.0)
        if not isinstance(seconds, (int, float)) or isinstance(
            seconds, bool
        ) or seconds < 0:
            raise FaultPlanError(f"faults[{i}]: bad seconds {seconds!r}")
        if (kind in ("stall", "slow_client")) != (seconds > 0):
            raise FaultPlanError(
                f"faults[{i}]: seconds is the stall/delay length — "
                f"required > 0 for kinds 'stall'/'slow_client', "
                f"meaningless otherwise"
            )
        mode = f.get("mode", "flip")
        if mode not in ("flip", "truncate"):
            raise FaultPlanError(
                f"faults[{i}]: corrupt mode {mode!r} not in "
                f"('flip', 'truncate')"
            )
        faults.append(
            Fault(round=rnd, kind=kind, phase=phase, times=times,
                  seconds=float(seconds), mode=mode)
        )
    return FaultPlan(name=name, faults=tuple(faults))


def _default_phase(kind) -> str:
    if kind == "corrupt":
        return "checkpoint"
    if kind in CLIENT_FAULT_KINDS:
        return "client"
    return "dispatch"


def to_dict(plan: FaultPlan) -> dict:
    """The exact inverse of :func:`from_dict` (round-trip pinned by the
    CLI and tests): defaulted fields are omitted, so a loaded-and-saved
    plan is byte-stable."""
    faults = []
    for f in plan.faults:
        d = {"round": f.round, "kind": f.kind}
        if f.phase != _default_phase(f.kind):
            d["phase"] = f.phase
        if f.times != 1:
            d["times"] = f.times
        if f.kind in ("stall", "slow_client"):
            d["seconds"] = f.seconds
        if f.kind == "corrupt" and f.mode != "flip":
            d["mode"] = f.mode
        faults.append(d)
    return {"name": plan.name, "faults": faults}


def load(path: str) -> FaultPlan:
    with open(path) as fh:
        return from_dict(json.load(fh))


def save(path: str, plan: FaultPlan) -> None:
    with open(path, "w") as fh:
        json.dump(to_dict(plan), fh, indent=2)
        fh.write("\n")


def corrupt_file(path: str, mode: str = "flip") -> None:
    """Deterministically damage ``path``: ``flip`` inverts 64 bytes at
    the middle of the file (data-region damage the content digest
    catches even when the zip directory survives); ``truncate`` keeps
    the first half (torn-file damage the zip reader catches)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as fh:
            fh.truncate(max(1, size // 2))
        return
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        chunk = fh.read(min(64, max(1, size - size // 2)))
        fh.seek(size // 2)
        fh.write(bytes(b ^ 0xFF for b in chunk))


class ChaosInjector:
    """Fires a plan's faults from the engine's execution seam.

    One injector instance spans one supervised campaign INCLUDING its
    recoveries: consumed ``times`` stay consumed across engine restarts,
    which is what makes "inject one fatal fault, recover, complete"
    deterministic.  ``fired`` records every injection (kind, round,
    phase) for tests and the supervisor's stats block.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._remaining = [f.times for f in plan.faults]
        self.fired = []

    def _consume(self, i, fault, lo, hi):
        if self._remaining[i] > 0:
            self._remaining[i] -= 1
        self.fired.append(
            {"kind": fault.kind, "phase": fault.phase, "round": fault.round,
             "window": [lo, hi]}
        )
        obs.instant(
            "fault_injected", kind=fault.kind, phase=fault.phase,
            round=fault.round, lo=lo, hi=hi,
        )
        obs.default_registry().counter("chaos_injected_total").inc()
        _metrics.emit(
            {
                "event": "fault_injected",
                "v": _metrics.SCHEMA_VERSION,
                "plan": self.plan.name,
                "kind": fault.kind,
                "phase": fault.phase,
                "round": fault.round,
            }
        )

    def fire(self, call, phase, lo, hi):
        """The seam body: inject any due faults for rounds ``[lo, hi)``
        at ``phase``, then run the real operation.

        Raising kinds fire BEFORE ``call`` so the donated carry is never
        consumed by an injected failure — the supervisor's in-place
        retry of the same zero-arg ``call`` is then bit-exact.
        """
        for i, f in enumerate(self.plan.faults):
            if f.phase != phase or not lo <= f.round < hi:
                continue
            if self._remaining[i] == 0:
                continue
            self._consume(i, f, lo, hi)
            if f.kind == "stall":
                time.sleep(f.seconds)
                continue
            if f.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            raise _RAISES[f.kind](
                f"injected {f.kind} fault at rounds [{lo}, {hi}) "
                f"(plan {self.plan.name!r}"
                + (", RESOURCE_EXHAUSTED: Out of memory)"
                   if f.kind == "oom" else ")")
            )
        return call()

    def client_faults(self, ordinal: int):
        """Client-tier faults due at request ``ordinal`` (ISSUE 10).

        Consumed by the serving LOAD HARNESS (bench.py ``serving``,
        tests/test_serve.py) shaping synthetic clients — the service
        itself never reads these: a real client's slowness or
        abandonment happens outside the process.  Returns the fired
        :class:`Fault` list (``times`` consumed, ``fault_injected``
        records emitted with ``phase: "client"``); the caller applies
        the semantics — sleep ``seconds`` for ``slow_client``, drop the
        ticket for ``abandon``, switch to near-zero deadlines from here
        on for ``deadline_storm``.

        Matching is by EXACT ordinal, so in a harness that draws each
        ordinal once, ``times > 1`` never fires more than once — plan
        one fault entry per ordinal to inject repeatedly (``times``
        matters only when a harness re-queries an ordinal, e.g. one
        submission retried after a rejection).
        """
        fired = []
        for i, f in enumerate(self.plan.faults):
            if f.phase != "client" or f.round != ordinal:
                continue
            if self._remaining[i] == 0:
                continue
            self._consume(i, f, ordinal, ordinal + 1)
            fired.append(f)
        return fired

    def after_checkpoint(self, round_cursor, path):
        """The checkpoint hook: corrupt a just-written checkpoint whose
        round window reached the fault's round."""
        for i, f in enumerate(self.plan.faults):
            if f.kind != "corrupt" or self._remaining[i] == 0:
                continue
            if round_cursor < f.round:
                continue
            self._consume(i, f, round_cursor, round_cursor)
            corrupt_file(path, f.mode)


def _check_plan(path: str) -> str:
    plan = load(path)
    doc = to_dict(plan)
    if to_dict(from_dict(json.loads(json.dumps(doc)))) != doc:
        raise FaultPlanError("to_dict/from_dict round-trip drifted")
    kinds = {}
    for f in plan.faults:
        kinds[f.kind] = kinds.get(f.kind, 0) + 1
    summary = ", ".join(f"{k}x{v}" for k, v in sorted(kinds.items()))
    return (
        f"{path}: OK — {plan.name!r}, {len(plan.faults)} fault(s)"
        + (f" ({summary})" if summary else "")
    )


def main(argv) -> int:
    if not argv:
        print(
            "usage: python -m ba_tpu.runtime.chaos <plan.json> ...",
            file=sys.stderr,
        )
        return 2
    for path in argv:
        try:
            print(_check_plan(path))
        except (OSError, ValueError) as e:  # FaultPlanError is a ValueError
            print(f"{path}: FAIL — {e}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
