"""The interactive REPL: six commands, byte-identical output.

Command surface and exact output formats follow SURVEY.md section 3.1
(reference ba.py:354-445):

- ``actual-order <cmd>`` — run one agreement round, print every general's
  line ``G{id}, {primary|secondary}, majority={m}, state={F|NF}`` then the
  ``Execute order: ...`` quorum line (ba.py:383-399, 237-255).
- ``g-state`` / ``g-state <id> <faulty|non-faulty>`` — show / set fault
  flags (ba.py:401-413); with three tokens the role column is omitted and
  any third token other than "faulty" means non-faulty.
- ``g-kill <id>`` — remove a general (ba.py:415-425).
- ``g-add <n>`` — spawn n more (ba.py:427-437).
- ``List`` — ``P{id}, {True|False}`` primary flags (ba.py:439-445).
- ``Exit`` — leave the loop (ba.py:373-374).

Framework extensions (no reference analogue; the six reference commands
stay byte-identical):

- ``run-rounds <cmd> <R>`` — R agreement rounds in one pipelined device
  run (the last round's block in ``actual-order`` format, plus a
  ``Rounds: ...`` decision tally).
- ``scenario <file>`` — run a declarative scenario campaign
  (``ba_tpu.scenario`` JSON spec: kills, revivals, fault flips, adversary
  strategies, per round) through the pipelined mutating engine; prints
  the decision tally and the on-device scenario counters (incl. IC1/IC2
  verdicts), then leaves the roster in the campaign's final state — the
  whole ``g-kill``/``g-state`` session the spec encodes, as one device
  run.  ``scenario <file> <ckpt-path> <every>`` checkpoints the carry;
  a trailing ``supervise`` token runs the campaign under the resilient
  execution supervisor (``runtime/supervisor.py``: watchdog, transient
  retry, automatic checkpoint recovery) and prints its stats line.  A
  ``mesh=N`` token (ISSUE 8) routes the campaign through the engine's
  mesh-sharded scan core on an N×1 device mesh — the interactive batch
  is 1, so only ``mesh=1`` runs (a larger N prints the engine's clear
  one-line error naming the mismatch, as does asking for more devices
  than exist); batched multi-chip campaigns use
  ``parallel.pipeline.scenario_sweep(mesh=)`` from library code.
- ``search`` (ISSUE 15) — run an adversary hunt sized to this cluster
  (``ba_tpu.search``): sample populations of candidate campaigns,
  evaluate them batched campaign-per-instance through the coalesced
  engine, collect IC1/IC2/quorum violations and shrink them to minimal
  reproducers.  ``search gens=N objective=ic|ic1|ic2|quorum|havoc
  export=DIR stop=N space=FILE`` — ``export=`` writes the minimized
  findings as ordinary provenance-stamped scenario JSON specs (the
  ``scenario`` command replays them), ``space=`` loads an explicit
  search-space JSON.
- ``serve start|stat|stop`` (ISSUE 10) — control a local
  agreement-as-a-service front-end (``runtime/serve.py``): ``start``
  spawns the continuous-batching dispatcher (``serve start queue=N
  window=S batch=N warm=0|1`` override the ``BA_TPU_SERVE_*`` /
  ``BA_TPU_WARM`` defaults; ``warm=1`` (ISSUE 11) launches the
  background AOT warmup pass so dispatches hit precompiled
  executables), ``stat`` prints the service's live stats block (tier,
  queue depth, admitted/completed/rejected/expired/failed tallies,
  plus — warm — warmup signatures warmed/pending and the
  compile-on-miss count), ``stop`` drains and prints the final
  tallies.  Library/bench clients submit via
  ``serve.AgreementService`` — the REPL command exists so one process
  can host the roster AND the service.
- ``fleet start|stat|drain|stop`` (ISSUE 20) — control a local
  replicated serving fleet (``ba_tpu.fleet``): ``start`` boots N
  warm-gated ``AgreementService`` replicas behind a consistent-hash
  router (``fleet start replicas=N root=DIR hops=N vnodes=N queue=N
  window=S batch=N warm=0|1`` override the ``BA_TPU_FLEET_*`` /
  ``BA_TPU_SERVE_*`` defaults), ``stat`` prints router tallies plus one
  lock-free health line per replica, ``drain <replica>`` serve-drains
  one replica and live-migrates its in-flight campaigns to a survivor,
  ``stop`` drains the whole fleet.  Library/bench clients route via
  ``fleet.FleetRouter`` — the REPL command exists so one process can
  host the roster AND the fleet.
- ``stats`` — dump the observability registry (``ba_tpu.obs``) as
  Prometheus-style text: round wall-time histogram, pipeline dispatch /
  retire latencies and depth occupancy, election and failover counters.
  Prints nothing before the first instrumented operation.
  ``stats --live`` (ISSUE 9) renders one health sample instead
  (``obs/health.py``): rounds/s, depth occupancy, retire-lag p50/p99,
  watchdog margin, per-shard imbalance — rates measured since the
  previous ``stats --live`` call, lock-free reads only.
  ``stats --fleet`` (ISSUE 19) prints one fleet rollup line instead,
  merged on demand from the sharded sink directory
  (``BA_TPU_METRICS=dir/`` mode) — replicas, cohorts, requests, pool
  tasks, traces, p99 wall, worst burn.  Lock-free like ``--live``:
  every process appends to its own shard, the reader never contends.

Divergences (all guarded crashes in the reference, documented in SURVEY.md
section 3.3): unknown ids and an empty cluster are ignored instead of
raising (Q4), and ``actual-order`` immediately after killing the leader
cannot hit a not-yet-reelected assert (Q5) because election here is
event-driven.  Q6 (a general added mid-round never sees that round's
command, ba.py:53-57) is unrepresentable here: rounds are atomic device
programs, so membership can only change between rounds — a joiner simply
votes from the next ``actual-order`` on.
"""

from __future__ import annotations

from ba_tpu import obs
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.runtime.supervisor import SupervisorError
from ba_tpu.scenario import spec as scenario_spec


def _fmt_state(faulty: bool) -> str:
    return "F" if faulty else "NF"


def quorum_line(res) -> str:
    """The ``Execute order: ...`` line, exactly as ba.py:237-255 builds it."""
    quorum_text = f"{res.needed} out of {res.total} quorum suggests"
    quorum_fail = f"{res.n_undefined} out of {res.total} quorum not consistent"
    faulty_text = "Non-faulty nodes in the system"
    if res.nr_faulty > 0:
        faulty_text = f"{res.nr_faulty} faulty node(s) in the system"
    if res.decision == "retreat":
        decision = f"retreat! {faulty_text} - {quorum_text} retreat"
    elif res.decision == "attack":
        decision = f"attack! {faulty_text} - {quorum_text} attack"
    else:
        decision = (
            "cannot be determined - not enough generals in the system! "
            f"{faulty_text} - {quorum_fail}"
        )
    return f"Execute order: {decision}"


def handle_command(cluster: Cluster, line: str, out) -> bool:
    """Dispatch one REPL line.  Returns False when the loop should stop."""
    cmd = line.split(" ")
    with obs.span("repl_command", command=cmd[0]):
        return _dispatch(cluster, cmd, out)


def _dispatch(cluster: Cluster, cmd: list, out) -> bool:
    command = cmd[0]

    if command == "Exit":
        return False

    elif command == "actual-order":
        if len(cmd) == 1:
            return True
        res = cluster.actual_order(cmd[1])
        if res is None:
            return True
        for gid, is_primary, maj, faulty in res.per_general:
            status = "primary" if is_primary else "secondary"
            out(f"G{gid}, {status}, majority={maj}, state={_fmt_state(faulty)}")
        out(quorum_line(res))

    elif command == "run-rounds":
        # Framework extension (no reference analogue): R agreement rounds
        # in one pipelined device run (cluster.actual_order_rounds — the
        # depth-k engine with metrics overlapping device compute).  Prints
        # the LAST round's per-general block + quorum line in the
        # actual-order format, then a decision tally over all R rounds.
        if len(cmd) < 3:
            return True
        try:
            rounds = int(cmd[2])
        except ValueError:
            return True
        if rounds < 1:
            return True
        ran = cluster.actual_order_rounds(cmd[1], rounds)
        if ran is None:
            return True
        res, counts, _stats = ran
        for gid, is_primary, maj, faulty in res.per_general:
            status = "primary" if is_primary else "secondary"
            out(f"G{gid}, {status}, majority={maj}, state={_fmt_state(faulty)}")
        out(quorum_line(res))
        out(
            f"Rounds: {rounds} - attack={counts['attack']}, "
            f"retreat={counts['retreat']}, undefined={counts['undefined']}"
        )
        if _stats and _stats.get("signed"):
            # Signed lane evidence (ISSUE 14): the sign-ahead host lane
            # ran — one additive line so an interactive signed session
            # can see its overlap wall without opening the metrics
            # stream.
            out(
                f"Signed lane: sign_ahead_s="
                f"{_stats.get('sign_ahead_s')}, dispatches="
                f"{_stats.get('dispatches')}"
            )

    elif command == "scenario":
        # Framework extension (additive, like run-rounds): a whole
        # declarative campaign — membership churn, fault injection,
        # adversary strategies — as one pipelined device run.  Spec
        # problems print a one-line error; an incapable backend
        # (PyBackend, signed) is silently ignored like other guarded
        # divergences.  `scenario <file> <ckpt-path> <every>` (ISSUE 6)
        # additionally serializes the campaign's carry every <every>
        # rounds to <ckpt-path> (a literal {round} in the path keeps
        # every checkpoint; otherwise the latest wins), so a long
        # campaign survives the REPL process and resumes bit-exactly.
        # The reference-exact `line.split(" ")` keeps empty tokens, so a
        # trailing space would otherwise read as an (empty) checkpoint
        # path and abort the command — drop them here, locally.  A
        # trailing `supervise` token (ISSUE 7) runs the campaign under
        # the resilient execution supervisor (watchdog, transient retry,
        # automatic checkpoint recovery).  A `mesh=N` token (ISSUE 8)
        # routes through the mesh-sharded scan core; every mesh problem
        # (more devices than exist, a data axis the B=1 batch cannot
        # split) surfaces as one error line, never a traceback.
        args = [t for t in cmd[1:] if t]
        engine = None
        for tok in list(args):
            # ISSUE 13: `engine=xla|pallas|interpret|auto` routes the
            # campaign through the engine-select seam; an unsupported
            # request surfaces as the engine's one-line eager error
            # below, never a traceback.
            if tok.startswith("engine="):
                engine = tok[len("engine="):]
                if not engine:
                    out("scenario error: engine= wants one of "
                        "xla|pallas|interpret|auto")
                    return True
                args.remove(tok)
        mesh_n = None
        for tok in list(args):
            if tok.startswith("mesh="):
                try:
                    mesh_n = int(tok[len("mesh="):])
                except ValueError:
                    out(f"scenario error: mesh= wants a device count, "
                        f"got {tok[len('mesh='):]!r}")
                    return True
                if mesh_n < 1:
                    out(f"scenario error: mesh= must be >= 1, "
                        f"got {mesh_n}")
                    return True
                args.remove(tok)
        supervise = False
        if args and args[-1] == "supervise":
            supervise = True
            args = args[:-1]
        if not args:
            return True
        ck_path = ck_every = None
        if len(args) == 2:
            # A path without <every> would silently run uncheckpointed —
            # and the user would only find out at resume time.
            out("scenario error: checkpoint path given without <every> "
                "(usage: scenario <file> [<ckpt-path> <every>] "
                "[supervise] [mesh=N] [engine=...])")
            return True
        if len(args) > 3:
            # Like the path-without-<every> case: extra tokens mean the
            # user expected something this command does not do — refuse
            # loudly rather than silently dropping them.
            out("scenario error: too many arguments "
                "(usage: scenario <file> [<ckpt-path> <every>] "
                "[supervise] [mesh=N] [engine=...])")
            return True
        if len(args) == 3:
            ck_path = args[1]
            try:
                ck_every = int(args[2])
            except ValueError:
                out(f"scenario error: <every> must be an integer, "
                    f"got {args[2]!r}")
                return True
            if ck_every < 1:
                out(f"scenario error: <every> must be >= 1, got {ck_every}")
                return True
        try:
            spec = scenario_spec.load(args[0])
        except (OSError, ValueError) as e:
            out(f"scenario error: {e}")
            return True
        try:
            mesh = None
            if mesh_n is not None:
                # Lazy: make_mesh imports jax, and the PyBackend REPL
                # must keep running without it; its clear oversized-
                # request ValueError prints below as one line.
                from ba_tpu.parallel.mesh import make_mesh

                mesh = make_mesh((mesh_n, 1), ("data", "node"))
            ran = cluster.run_scenario(
                spec, checkpoint_every=ck_every, checkpoint_path=ck_path,
                supervise=supervise, mesh=mesh, engine=engine,
            )
        except (OSError, ValueError, ImportError, SupervisorError) as e:
            # ImportError: `mesh=N` on a jax-less install (PyBackend
            # REPL) — the lazy make_mesh import is the first jax touch,
            # and it must cost one error line, not the REPL.
            # ValueError: e.g. the spec names ids not in the roster.
            # OSError: an unwritable checkpoint path surfaces from the
            # engine's mid-campaign write — one error line, not a dead
            # REPL (and a dead campaign carry with it).
            # SupervisorError: a supervised campaign exhausted its
            # retry/recovery budgets (or quarantined a poisoned window)
            # — the diagnosis IS the message.
            out(f"scenario error: {e}")
            return True
        if ran is None:
            return True
        counts, res = ran
        out(
            f"Scenario {spec.name}: {spec.rounds} rounds - "
            f"attack={counts['attack']}, retreat={counts['retreat']}, "
            f"undefined={counts['undefined']}"
        )
        out(
            "Scenario counters: "
            + ", ".join(f"{k}={v}" for k, v in res["counters"].items())
        )
        if ck_path is not None:
            out(
                f"Scenario checkpoints: "
                f"{res['stats'].get('checkpoints', 0)} -> {ck_path}"
            )
        if supervise:
            sup = res["stats"]["supervisor"]
            out(
                f"Scenario supervisor: attempts={sup['attempts']}, "
                f"retries={sup['retries']}, "
                f"recoveries={sup['recoveries']}, stalls={sup['stalls']}"
            )

    elif command == "search":
        # Framework extension (additive, ISSUE 15): an adversary hunt
        # sized to this cluster — sample populations of candidate
        # campaigns, evaluate them batched (campaign-per-instance),
        # collect IC1/IC2/quorum violations and shrink them to minimal
        # reproducers.  Tokens: `gens=N` generations, `objective=NAME`
        # (ic|ic1|ic2|quorum|havoc), `export=DIR` writes the minimized
        # reproducers as ordinary scenario JSON specs, `stop=N` ends
        # early after N findings, `space=FILE` loads an explicit
        # search-space JSON instead of the roster-shaped default.  An
        # incapable backend (PyBackend, signed) is silently ignored
        # like other guarded divergences; every config problem prints
        # one error line, never a traceback.
        args = [t for t in cmd[1:] if t]
        kwargs = {}
        space = None
        ok = True
        for tok in args:
            key, sep, value = tok.partition("=")
            if not sep or not value:
                out(f"search error: unknown token {tok!r} (usage: search "
                    f"[gens=N] [objective=NAME] [export=DIR] [stop=N] "
                    f"[space=FILE])")
                ok = False
                break
            if key in ("gens", "stop"):
                try:
                    n = int(value)
                except ValueError:
                    out(f"search error: {key}= wants an integer, "
                        f"got {value!r}")
                    ok = False
                    break
                if n < 1:
                    out(f"search error: {key}= must be >= 1, got {n}")
                    ok = False
                    break
                kwargs["generations" if key == "gens" else "stop_after"] = n
            elif key == "objective":
                kwargs["objective"] = value
            elif key == "export":
                kwargs["export_dir"] = value
            elif key == "space":
                try:
                    import json as _json

                    from ba_tpu.search.generate import space_from_dict

                    with open(value) as fh:
                        space = space_from_dict(_json.load(fh))
                except (OSError, ValueError) as e:
                    out(f"search error: {e}")
                    ok = False
                    break
            else:
                out(f"search error: unknown token {tok!r} (usage: search "
                    f"[gens=N] [objective=NAME] [export=DIR] [stop=N] "
                    f"[space=FILE])")
                ok = False
                break
        if not ok:
            return True
        try:
            res = cluster.run_search(space=space, **kwargs)
        except (OSError, ValueError, ImportError) as e:
            # ValueError: ScenarioError-grade config problems (unknown
            # objective, bad space).  OSError: an unwritable export /
            # checkpoint target.  ImportError: a jax-less install — one
            # error line, not a dead REPL.
            out(f"search error: {e}")
            return True
        if res is None:
            return True
        stats = res["stats"]
        out(
            f"Search: generations={stats['generations_run']}, "
            f"campaigns={stats['campaigns']}, "
            f"objective={stats['objective']}"
        )
        shrunk = res["minimized"]
        shrink_note = (
            " ({} minimized, events {})".format(
                len(shrunk),
                ", ".join(
                    f"{m['events_before']}->{m['events_after']}"
                    for m in shrunk
                ),
            )
            if shrunk
            else ""
        )
        out(
            f"Search found: {stats['found']} violating campaign(s), "
            f"best score {stats['best_score']}{shrink_note}"
        )
        if res["exported"]:
            out("Search exported: " + ", ".join(res["exported"]))

    elif command == "serve":
        # Framework extension (additive, ISSUE 10): start/stat/stop a
        # local agreement-as-a-service front-end.  The service module
        # is host-tier (importing it never touches jax — lint-pinned),
        # so the command works on the PyBackend REPL too; the first
        # DISPATCH on a jax-less install fails that request's cohort
        # with a classified error, never the REPL.
        args = [t for t in cmd[1:] if t]
        if not args or args[0] not in ("start", "stat", "stop"):
            out("serve error: usage: serve start [queue=N] [window=S] "
                "[batch=N] [warm=0|1] | serve stat | serve stop")
            return True
        from ba_tpu.runtime import serve as serve_mod

        svc = getattr(cluster, "_serve_service", None)
        if args[0] == "start":
            if svc is not None and svc.running():
                out("serve error: already running (serve stop first)")
                return True
            overrides = {}
            # warm= casts through int so `warm=yes` is a one-line error
            # like every other malformed option, then lands as a bool.
            names = {"queue": ("max_queue", int),
                     "window": ("coalesce_window_s", float),
                     "batch": ("max_batch", int),
                     "warm": ("warm", int),
                     "engine": ("engine", str)}
            for tok in args[1:]:
                key, sep, val = tok.partition("=")
                if not sep or key not in names:
                    out(f"serve error: unknown option {tok!r} (usage: "
                        f"serve start [queue=N] [window=S] [batch=N] "
                        f"[warm=0|1] [engine=xla|pallas|interpret|auto])")
                    return True
                field, cast = names[key]
                try:
                    overrides[field] = cast(val)
                except ValueError:
                    out(f"serve error: {key}= wants a {cast.__name__}, "
                        f"got {val!r}")
                    return True
            if "warm" in overrides:
                overrides["warm"] = bool(overrides["warm"])
            try:
                cfg = serve_mod.ServeConfig.from_env(**overrides)
            except ValueError as e:
                out(f"serve error: {e}")
                return True
            svc = serve_mod.AgreementService(
                cfg, registry=obs.default_registry()
            )
            svc.start()
            cluster._serve_service = svc
            out(f"serve: started (queue={cfg.max_queue}, "
                f"window={cfg.coalesce_window_s}s, "
                f"batch={cfg.max_batch}"
                + (", warm" if cfg.warm else "") + ")")
        elif svc is None:
            out("serve error: not running (serve start first)")
        elif args[0] == "stat":
            for k, v in svc.stats().items():
                out(f"serve_{k} {v}")
        else:  # stop
            svc.stop()
            cluster._serve_service = None
            st = svc.stats()
            out(f"serve: stopped — admitted={st['admitted']}, "
                f"completed={st['completed']}, "
                f"rejected={st['rejected']}, expired={st['expired']}, "
                f"failed={st['failed']}")

    elif command == "fleet":
        # Framework extension (additive, ISSUE 20): control a local
        # replicated serving fleet (``ba_tpu.fleet``).  Host-tier like
        # `serve` — importing the fleet tier never touches jax
        # (lint-pinned), so the command works on the PyBackend REPL.
        args = [t for t in cmd[1:] if t]
        if not args or args[0] not in ("start", "stat", "drain", "stop"):
            out("fleet error: usage: fleet start [replicas=N] [root=DIR] "
                "[hops=N] [vnodes=N] [queue=N] [window=S] [warm=0|1] | "
                "fleet stat | fleet drain <replica> | fleet stop")
            return True
        from ba_tpu import fleet as fleet_mod
        from ba_tpu.runtime import serve as serve_mod

        mgr = getattr(cluster, "_fleet_manager", None)
        if args[0] == "start":
            if mgr is not None:
                out("fleet error: already running (fleet stop first)")
                return True
            fleet_over, serve_over = {}, {}
            names = {"replicas": (fleet_over, "replicas", int),
                     "root": (fleet_over, "root", str),
                     "hops": (fleet_over, "max_hops", int),
                     "vnodes": (fleet_over, "vnodes", int),
                     "queue": (serve_over, "max_queue", int),
                     "window": (serve_over, "coalesce_window_s", float),
                     "batch": (serve_over, "max_batch", int),
                     "warm": (serve_over, "warm", int)}
            for tok in args[1:]:
                key, sep, val = tok.partition("=")
                if not sep or key not in names:
                    out(f"fleet error: unknown option {tok!r} (usage: "
                        f"fleet start [replicas=N] [root=DIR] [hops=N] "
                        f"[vnodes=N] [queue=N] [window=S] [batch=N] "
                        f"[warm=0|1])")
                    return True
                target, field, cast = names[key]
                try:
                    target[field] = cast(val)
                except ValueError:
                    out(f"fleet error: {key}= wants a {cast.__name__}, "
                        f"got {val!r}")
                    return True
            if "warm" in serve_over:
                serve_over["warm"] = bool(serve_over["warm"])
            try:
                fcfg = fleet_mod.FleetConfig.from_env(**fleet_over)
                scfg = serve_mod.ServeConfig.from_env(**serve_over)
            except ValueError as e:
                out(f"fleet error: {e}")
                return True
            mgr = fleet_mod.ReplicaManager(fcfg, serve_config=scfg)
            try:
                mgr.start()
            except serve_mod.ServeError as e:
                mgr.stop()
                out(f"fleet error: {e}")
                return True
            cluster._fleet_manager = mgr
            cluster._fleet_router = fleet_mod.FleetRouter(mgr)
            out(f"fleet: started {len(mgr.ready())} replica(s) "
                f"(hops={fcfg.max_hops}, vnodes={fcfg.vnodes}"
                + (f", root={fcfg.root}" if fcfg.root else "")
                + (", warm" if scfg.warm else "") + ")")
        elif mgr is None:
            out("fleet error: not running (fleet start first)")
        elif args[0] == "stat":
            router = cluster._fleet_router
            st = router.stats()
            out(f"fleet_routes {st['routes']}")
            out(f"fleet_reroutes {st['reroutes']}")
            out(f"fleet_ready {st['ready']}")
            for h in st["replicas"]:
                out(f"fleet_replica {h['replica']} state={h['state']} "
                    f"queue={h['queue_depth']} tier={h['tier']} "
                    f"admitted={h['admitted']} rejected={h['rejected']}")
        elif args[0] == "drain":
            if len(args) != 2:
                out("fleet error: usage: fleet drain <replica>")
                return True
            try:
                adopted = mgr.drain(args[1])
            except (KeyError, serve_mod.ServeError) as e:
                out(f"fleet error: {e}")
                return True
            out(f"fleet: drained {args[1]} — "
                f"{len(adopted)} campaign(s) migrated, "
                f"{len(mgr.ready())} replica(s) still serving")
        else:  # stop
            mgr.stop()
            st = cluster._fleet_router.stats()
            cluster._fleet_manager = None
            cluster._fleet_router = None
            out(f"fleet: stopped — routes={st['routes']}, "
                f"reroutes={st['reroutes']}")

    elif command == "g-state":
        if len(cmd) == 3:
            try:
                gid = int(cmd[1])
            except ValueError:
                return True
            # Any third token other than "faulty" means non-faulty
            # (ba.py:407).
            if not cluster.set_faulty(gid, cmd[2] == "faulty"):
                return True
        for g in cluster.generals:
            primarity = ", primary" if g.id == cluster.leader_id else ", secondary"
            primarity = primarity if len(cmd) != 3 else ""
            out(f"G{g.id}{primarity}, state={_fmt_state(g.faulty)}")

    elif command == "g-kill":
        if len(cmd) == 1:
            return True
        try:
            gid = int(cmd[1])
        except ValueError:
            return True
        cluster.kill(gid)

    elif command == "g-add":
        if len(cmd) == 1:
            return True
        try:
            count = int(cmd[1])
        except ValueError:
            return True
        cluster.add(count)

    elif command == "List":
        for g in cluster.generals:
            out(f"P{g.id}, {g.id == cluster.leader_id}")

    elif command == "stats":
        # Framework extension (additive, like run-rounds): the obs
        # registry as Prometheus-style text exposition.  Empty registry
        # prints nothing — the reference command surface is untouched.
        # `stats --live` (ISSUE 9) renders one health sample instead:
        # the derived live view (rounds/s, depth occupancy, retire-lag
        # p50/p99, watchdog margin, per-shard imbalance) from the
        # process-wide sampler — rates are measured since the PREVIOUS
        # `stats --live` call.  Lock-free reads; also writes the
        # health_* gauges, so plain `stats` carries the family too.
        if "--live" in cmd[1:]:
            snap = obs.health.default_sampler().sample()
            for k, v in snap.items():
                if v is None:
                    continue
                out(f"{k} {'+Inf' if v == float('inf') else v}")
            # One SLO line (ISSUE 17): worst gate burn + worst cohort
            # p99 attribution, read lock-free from the installed
            # engine's last report (GIL-atomic attribute read) — no
            # engine or no report yet prints nothing; errors are one
            # line, PyBackend-safe (everything here is host-tier).
            try:
                eng = obs.slo.installed()
                worst = eng.last_worst if eng is not None else None
                if worst is not None:
                    out(
                        f"slo_worst burn={worst['burn']} "
                        f"cohort={worst['cohort']} "
                        f"tenant={worst['tenant']} "
                        f"p99_s={worst['p99_s']} "
                        f"phase={worst['phase']}"
                    )
            except Exception as e:
                out(f"slo_worst error: {e}")
            return True
        # `stats --fleet` (ISSUE 19): one fleet rollup line from the
        # sharded sink directory — merge-on-demand from the shards on
        # disk (each process appends to its OWN shard, so reading here
        # takes no lock anywhere; the writers never contend with us).
        # No dir-mode sink prints one explanatory line; errors are one
        # line, like the SLO view above.
        if "--fleet" in cmd[1:]:
            try:
                from ba_tpu.utils import metrics as _metrics

                target = _metrics.default_sink().target
                if not _metrics.is_dir_target(target):
                    out("fleet (no sharded sink — set BA_TPU_METRICS "
                        "to a directory)")
                    return True
                from ba_tpu.obs import fleet as _fleet

                out(_fleet.summary_line(
                    _fleet.fleet_summary(_fleet.merge_shards(target))
                ))
            except Exception as e:
                out(f"fleet error: {e}")
            return True
        for ln in obs.default_registry().prometheus_text().splitlines():
            out(ln)

    return True


def run_repl(cluster: Cluster, stdin, out) -> None:
    for line in stdin:
        if not handle_command(cluster, line.rstrip("\n"), out):
            break
