"""Host-side cluster: membership registry, election-for-life, fault flags.

This is the stateful shell around the pure consensus core — the TPU-native
replacement for the reference's thread-per-general runtime (ba.py:66-122,
344-351).  Threads, sockets and 0.1 s polling loops disappear; their
*semantics* stay:

- Generals get ascending ids from 1 and "ports" from 18812 (ba.py:344-351) —
  ports are vestigial here (no TCP) but kept so `List`/diagnostics match.
- Election is for life, by lowest id among the living (ba.py:124-157): the
  leader only changes when the current one is killed, which the reference
  detects by a 0.1 s TCP ping (ba.py:306-314) and we detect by an event-driven
  ``tick()`` after every membership change — same converged outcome, no race
  window (the reference's Q5 assert-crash cannot happen here).
- New generals adopt the existing leader (discovery, ba.py:86-102) and never
  trigger an election while one is alive.
- Killed generals leave the roster (ba.py:415-425); their slots stay in the
  core's ``alive`` mask so tensor shapes remain static between recompiles.
"""

from __future__ import annotations

import dataclasses
import time

from ba_tpu import obs
from ba_tpu.core.quorum import quorum_threshold_py
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED, COMMAND_NAMES, command_from_name
from ba_tpu.utils import metrics

BASE_PORT = 18812  # rpyc's default port, kept for display parity (ba.py:355)


@dataclasses.dataclass
class General:
    """Roster entry — the host-visible face of one general."""

    id: int
    port: int
    faulty: bool = False
    alive: bool = True


@dataclasses.dataclass
class RoundResult:
    """Everything ``actual-order`` needs to print (ba.py:383-399)."""

    per_general: list  # (id, is_primary, majority_str, faulty)
    nr_faulty: int
    n_attack: int
    n_retreat: int
    n_undefined: int
    needed: int
    total: int
    decision: str  # "attack" | "retreat" | "undefined"


class Cluster:
    """B=1 interactive cluster with elastic membership.

    ``backend`` provides ``run_round(generals, leader_idx, order_code, seed)
    -> list[int]`` returning each roster general's majority code; the JAX
    backend batches this same function over thousands of clusters in the
    sweep API (ba_tpu.parallel).
    """

    def __init__(self, n: int, backend, seed: int = 0):
        self.backend = backend
        self.seed = seed
        self._round = 0
        self.generals: list[General] = []
        self._next_id = 1
        self.leader_id: int | None = None
        self.add(n)

    # -- membership ---------------------------------------------------------

    def add(self, count: int) -> None:
        """Spawn ``count`` generals with the next ids/ports (ba.py:427-437).

        Joiners discover the current leader and do not disturb it
        (ba.py:86-102); if the cluster had no leader a tick elects one.
        """
        for _ in range(count):
            gid = self._next_id
            self._next_id += 1
            self.generals.append(General(id=gid, port=BASE_PORT + gid - 1))
        self.tick()

    def kill(self, gid: int) -> bool:
        """Kill by id (ba.py:415-425). Returns False if no such general."""
        g = self.find(gid)
        if g is None or not g.alive:
            return False
        g.alive = False
        was_leader = gid == self.leader_id
        # Failover transition marker: an instant span + counter, NOT a
        # metrics.emit — the JSONL stream stays one-record-per-round so
        # existing consumers' line counts hold.
        obs.instant("failover_kill", gid=gid, was_leader=was_leader)
        obs.default_registry().counter("failover_kills_total").inc()
        self.generals = [x for x in self.generals if x.alive]
        self.tick()
        return True

    def set_faulty(self, gid: int, faulty: bool) -> bool:
        """Live fault injection (``g-state <id> faulty``, ba.py:401-407)."""
        g = self.find(gid)
        if g is None:
            return False
        g.faulty = faulty
        return True

    def find(self, gid: int):
        for g in self.generals:
            if g.id == gid:
                return g
        return None

    def tick(self) -> None:
        """Failure detection + election, event-driven.

        The reference's per-general 0.1 s ping loop (ba.py:306-314) exists to
        notice a dead leader and re-elect; with a host-side registry the same
        transition is a lookup.  Election is for life (ba.py:124-125): a
        living leader is never displaced.
        """
        prev = self.leader_id
        alive = [g for g in self.generals if g.alive]
        if not alive:
            self.leader_id = None
        elif self.leader_id is None or self.find(self.leader_id) is None:
            self.leader_id = min(g.id for g in alive)
        if self.leader_id != prev and self.leader_id is not None:
            # Count ELECTIONS only: a cluster draining to leaderless is a
            # transition but nobody was elected.
            obs.instant("election", leader_id=self.leader_id, prev=prev)
            obs.default_registry().counter("elections_total").inc()

    @property
    def leader(self):
        return self.find(self.leader_id) if self.leader_id is not None else None

    # -- the agreement round ------------------------------------------------

    def actual_order(self, command: str) -> RoundResult | None:
        """One full agreement round: the ``actual-order`` hot path.

        Round semantics live in the backend (tensorised in ba_tpu.core); this
        method reproduces the REPL-level bookkeeping of ba.py:376-399 +
        ba.py:197-255: per-general majorities, the faulty count, and the
        majority-of-majorities quorum.

        String-parity quirk: the reference ships the raw command string, so
        the *leader's* reported majority is that raw string even when it is
        neither "attack" nor "retreat" (ba.py:284-285) — and the quorum then
        buckets it as n_undefined (ba.py:208-215).  Lieutenants only ever see
        attack/retreat (anything non-"attack" tallies as retreat,
        ba.py:163-167).
        """
        if not self.generals:
            return None  # the reference would crash here (SURVEY.md Q4)
        self.tick()
        order_code = command_from_name(command)
        leader_idx = next(
            i for i, g in enumerate(self.generals) if g.id == self.leader_id
        )
        with obs.timed_span(
            "agreement_round", "round_wall_s",
            round=self._round, n=len(self.generals),
        ) as timed:
            majorities = self.backend.run_round(
                self.generals, leader_idx, order_code, self._round_seed()
            )
        round_elapsed = timed.elapsed_s
        round_idx = self._round
        self._round += 1

        res = self._tally(command, leader_idx, majorities)
        metrics.emit(
            {
                "event": "agreement_round",
                "round": round_idx,
                "n": len(self.generals),
                "leader_id": self.leader_id,
                "order": command,
                "decision": res.decision,
                "n_attack": res.n_attack,
                "n_retreat": res.n_retreat,
                "n_undefined": res.n_undefined,
                "needed": res.needed,
                "total": res.total,
                "nr_faulty": res.nr_faulty,
                "round_elapsed_s": round(round_elapsed, 6),
            }
        )
        return res

    def actual_order_rounds(self, command: str, rounds: int):
        """``rounds`` agreement rounds in one pipelined device run.

        The multi-round form of ``actual_order``: backends exposing
        ``run_rounds`` (the JAX path, oral messages) execute all R rounds
        through the pipelined sweep engine — on-device key schedule,
        donated buffers, depth-k dispatches in flight — with metrics
        emission riding the engine's ``host_work`` hook so the JSON lines
        are written while the device is still computing later rounds.
        Backends without it (the Python oracle; the signed path, which
        host-signs between device programs) fall back to R sequential
        ``actual_order`` calls.

        Returns ``(last RoundResult, counts, stats)``: the final round's
        full result (what ``run-rounds`` prints as the per-general
        block), a ``{"attack": a, "retreat": r, "undefined": u}`` count of
        the R per-round quorum decisions, and the engine's dispatch stats
        (None on the fallback path).  None when the cluster is empty.
        """
        if rounds < 1:
            raise ValueError(f"rounds={rounds} must be >= 1")
        if not self.generals:
            return None  # the reference would crash here (SURVEY.md Q4)
        self.tick()
        order_code = command_from_name(command)
        leader_idx = next(
            i for i, g in enumerate(self.generals) if g.id == self.leader_id
        )
        run_rounds = getattr(self.backend, "run_rounds", None)
        if command not in ("attack", "retreat"):
            # Non-canonical orders hit the leader raw-string parity quirk
            # (ba.py:284-285: the leader's majority is the raw string,
            # bucketed as undefined) which the device quorum cannot see —
            # take the sequential path so both outputs stay quirk-exact.
            run_rounds = None
        pipelined = None
        round_base = self._round
        t0 = time.perf_counter()
        if run_rounds is not None:

            def host_work(dispatch):
                # Runs between dispatches while the device is busy: the
                # overlap model's host lane (utils/metrics.py sink).
                metrics.emit(
                    {
                        "event": "pipeline_dispatch",
                        "dispatch": dispatch,
                        "round_base": round_base,
                        "n": len(self.generals),
                        "order": command,
                    }
                )

            with obs.span(
                "agreement_rounds", rounds=rounds, n=len(self.generals)
            ):
                pipelined = run_rounds(
                    self.generals,
                    leader_idx,
                    order_code,
                    self._round_seed(),
                    rounds,
                    host_work=host_work,
                )
        if pipelined is None:
            res = None
            counts = {"attack": 0, "retreat": 0, "undefined": 0}
            for _ in range(rounds):
                res = self.actual_order(command)
                counts[res.decision] += 1
            return res, counts, None
        majorities, decisions, stats = pipelined
        elapsed = time.perf_counter() - t0
        self._round += rounds
        res = self._tally(command, leader_idx, majorities)
        names = {ATTACK: "attack", RETREAT: "retreat"}
        counts = {"attack": 0, "retreat": 0, "undefined": 0}
        for d in decisions:
            counts[names.get(d, "undefined")] += 1
        metrics.emit(
            {
                "event": "agreement_rounds_pipelined",
                # The engine's run scope closed when run_rounds
                # returned; re-attach its id so the summary record
                # joins the same flight (ISSUE 9).  Conditional: a
                # present-but-None key would defeat the sink's own
                # setdefault stamping.
                **(
                    {"run_id": stats["run_id"]}
                    if stats.get("run_id")
                    else {}
                ),
                "round_base": round_base,
                "rounds": rounds,
                "n": len(self.generals),
                "leader_id": self.leader_id,
                "order": command,
                "decision_counts": counts,
                "dispatches": stats["dispatches"],
                "depth": stats["depth"],
                # On-device agreement counters (quorum failures,
                # unanimous rounds, equivocation observed), drained at
                # the engine's retire points — pure data, no extra sync.
                "counters": stats.get("counters"),
                "elapsed_s": round(elapsed, 6),
            }
        )
        return res, counts, stats

    def run_scenario(
        self,
        spec,
        checkpoint_every=None,
        checkpoint_path=None,
        checkpoint_keep_last=None,
        supervise=False,
        fault_plan=None,
        mesh=None,
        health_every=None,
        engine=None,
    ):
        """Run a declarative scenario campaign (ba_tpu.scenario) on this
        cluster: the whole ``g-kill``/``g-add``/``g-state`` REPL session
        the spec encodes, executed as ONE pipelined device run.

        ``checkpoint_every``/``checkpoint_path`` (ISSUE 6) thread into
        the engine's carry checkpoints: every N rounds the campaign's
        donated carry serializes to the repo's single checkpoint format
        (``utils/snapshot.py``), so a long-lived campaign survives its
        process and resumes bit-exactly
        (``pipeline_sweep(resume=path)``).  ``supervise=True`` (ISSUE 7)
        runs the campaign under the resilient execution supervisor —
        watchdogged retires, transient retry, automatic checkpoint
        recovery, OOM degradation — and ``fault_plan`` injects
        deterministic chaos faults for drills (requires supervision);
        the supervisor's stats block lands in the ``scenario_campaign``
        record.  ``mesh`` (ISSUE 8) threads into the engine's sharded
        scan core — the interactive batch is 1, so only a data-axis-1
        mesh runs (a larger one raises the engine's clear divisibility
        error; batched multi-chip campaigns call
        ``parallel.pipeline.scenario_sweep(mesh=)`` directly).
        ``health_every`` (ISSUE 9) threads into the engine's live
        health sampler: one ``health_snapshot`` per N dispatches from
        the host_work overlap slot, zero added synchronization.
        ``engine`` (ISSUE 13) picks the megastep implementation
        (``xla`` / ``pallas`` / ``interpret`` / ``auto`` — the
        engine-select seam in ``parallel/pipeline.py``); unsupported
        requests surface the seam's one-line eager ValueError.

        The backend (``run_scenario``) compiles the spec against the
        current roster and drives the mutating megastep; afterwards the
        host roster adopts the campaign's FINAL state — generals dead at
        the end leave the roster (exactly what a ``g-kill`` would have
        done), fault flags follow the last ``set_faulty``, and the
        leader is the scenario's final elected leader ("election is for
        life" holds across the boundary: a revived lower id does not
        displace it).  Returns ``(counts, result)`` — the per-round
        decision tally plus the backend's result dict (counters incl.
        IC1/IC2 verdicts, stats) — or None when the cluster is empty or
        the backend cannot run scenarios (PyBackend, signed paths).
        """
        if not self.generals:
            return None  # the reference would crash here (SURVEY.md Q4)
        self.tick()
        run = getattr(self.backend, "run_scenario", None)
        if run is None:
            return None
        order_code = command_from_name(spec.order)
        leader_idx = next(
            i for i, g in enumerate(self.generals) if g.id == self.leader_id
        )
        obs.instant("scenario_repl", scenario=spec.name, rounds=spec.rounds)
        with obs.span(
            "scenario_campaign", rounds=spec.rounds, n=len(self.generals)
        ):
            res = run(
                self.generals, leader_idx, order_code, self._round_seed(),
                spec,
                checkpoint_every=checkpoint_every,
                checkpoint_path=checkpoint_path,
                checkpoint_keep_last=checkpoint_keep_last,
                supervise=supervise,
                fault_plan=fault_plan,
                mesh=mesh,
                health_every=health_every,
                engine=engine,
            )
        if res is None:
            return None
        self._round += spec.rounds
        roster = list(self.generals)
        for g, alive, faulty in zip(roster, res["alive"], res["faulty"]):
            g.faulty = faulty
            g.alive = alive
        dead = [g.id for g in roster if not g.alive]
        self.generals = [g for g in roster if g.alive]
        # The scenario's final leader is authoritative (election is for
        # life, on device as on host); tick() only covers the corner
        # where the campaign left the cluster leaderless.
        prev = self.leader_id
        last_leader = res["leaders"][-1]
        if (
            0 <= last_leader < len(roster)
            and roster[last_leader].alive
        ):
            self.leader_id = roster[last_leader].id
        else:
            self.leader_id = None
        if self.leader_id != prev and self.leader_id is not None:
            obs.instant("election", leader_id=self.leader_id, prev=prev)
            obs.default_registry().counter("elections_total").inc()
        self.tick()
        names = {ATTACK: "attack", RETREAT: "retreat"}
        counts = {"attack": 0, "retreat": 0, "undefined": 0}
        for d in res["decisions"]:
            counts[names.get(d, "undefined")] += 1
        metrics.emit(
            {
                "event": "scenario_campaign",
                # Re-attach the campaign's run id (the engine's scope
                # closed when the backend returned) so this summary
                # record joins the same flight (ISSUE 9); conditional
                # so a backend without one never emits run_id: null.
                **(
                    {"run_id": res["stats"]["run_id"]}
                    if res["stats"].get("run_id")
                    else {}
                ),
                "name": spec.name,
                "rounds": spec.rounds,
                "order": spec.order,
                "decision_counts": counts,
                "counters": res["counters"],
                "killed": dead,
                "leader_id": self.leader_id,
                "n": len(self.generals),
                "dispatches": res["stats"]["dispatches"],
                "checkpoints": res["stats"].get("checkpoints", 0),
                # Present only on supervised campaigns: the supervisor's
                # attempts/retries/recoveries/degrades/stalls block.
                **(
                    {"supervisor": res["stats"]["supervisor"]}
                    if "supervisor" in res["stats"]
                    else {}
                ),
            }
        )
        return counts, res

    def run_search(self, space=None, **kwargs):
        """Run an adversary hunt (``ba_tpu.search``, ISSUE 15) sized to
        this cluster's padded capacity: sample populations of candidate
        campaigns, evaluate them batched through the coalesced engine,
        collect objective violations, and (by default) shrink them to
        minimal reproducers.

        The hunt never touches the roster — candidates run from the
        canonical all-honest state, so this is "what adversary would
        break a cluster shaped like mine", not a mutation of the live
        session.  ``space``/``kwargs`` thread into the backend's
        ``run_search`` (and from there ``ba_tpu.search.loop.hunt``).
        Returns the hunt's result dict, or None when the cluster is
        empty or the backend cannot search (PyBackend, signed paths).
        """
        if not self.generals:
            return None  # the reference would crash here (SURVEY.md Q4)
        run = getattr(self.backend, "run_search", None)
        if run is None:
            return None
        obs.instant("search_repl", n=len(self.generals))
        with obs.span("search_hunt", n=len(self.generals)):
            res = run(self.generals, self._round_seed(), space=space, **kwargs)
        if res is None:
            return None
        metrics.emit(
            {
                "event": "search_campaign",
                # Re-attach the hunt's run id (the engine's scope closed
                # when the backend returned) so this summary record
                # joins the same flight — the scenario_campaign pattern.
                **(
                    {"run_id": res["stats"]["run_id"]}
                    if res["stats"].get("run_id")
                    else {}
                ),
                "objective": res["stats"]["objective"],
                "generations": res["stats"]["generations_run"],
                "campaigns": res["stats"]["campaigns"],
                "found": res["stats"]["found"],
                "minimized": res["stats"]["minimized"],
                "best_score": res["stats"]["best_score"],
                "n": len(self.generals),
            }
        )
        return res

    def _tally(self, command: str, leader_idx: int, majorities) -> RoundResult:
        """REPL-level bookkeeping for one round's majorities (ba.py:383-399
        + 197-255), shared by the per-round and pipelined paths."""
        per_general = []
        n_attack = n_retreat = n_undefined = 0
        nr_faulty = 0
        for i, g in enumerate(self.generals):
            is_primary = i == leader_idx
            if is_primary:
                maj_str = command  # raw string passthrough (ba.py:284-285)
                bucket = {"attack": ATTACK, "retreat": RETREAT}.get(command, UNDEFINED)
            else:
                maj_str = COMMAND_NAMES[majorities[i]]
                bucket = majorities[i]
            if bucket == ATTACK:
                n_attack += 1
            elif bucket == RETREAT:
                n_retreat += 1
            else:
                n_undefined += 1
            if g.faulty:
                nr_faulty += 1
            per_general.append((g.id, is_primary, maj_str, g.faulty))

        total = n_attack + n_retreat + n_undefined
        needed = quorum_threshold_py(total)
        if needed <= n_retreat:  # retreat first: ties prefer retreat (Q7)
            decision = "retreat"
        elif needed <= n_attack:
            decision = "attack"
        else:
            decision = "undefined"
        return RoundResult(
            per_general=per_general,
            nr_faulty=nr_faulty,
            n_attack=n_attack,
            n_retreat=n_retreat,
            n_undefined=n_undefined,
            needed=needed,
            total=total,
            decision=decision,
        )

    def _round_seed(self) -> int:
        return (self.seed << 20) ^ self._round
