"""CLI entry point: ``python -m ba_tpu.runtime.main N [--backend ...]``.

Launch-compatible with the reference's one-positional-arg contract
(Generals_Byzantine_program.sh:1 -> ba.py:12) and extends it with the
framework flags promised by BASELINE.json's north star: ``--backend=tpu``
swaps the sequential Python loop for the JAX path.
"""

from __future__ import annotations

import argparse
import sys


def build_cluster(argv=None):
    parser = argparse.ArgumentParser(
        prog="ba-tpu",
        description="TPU-native Byzantine Generals simulator",
    )
    parser.add_argument("n", type=int, help="initial number of generals")
    parser.add_argument(
        "--backend",
        choices=["tpu", "py"],
        default="tpu",
        help="tpu: batched JAX core; py: sequential Python oracle",
    )
    parser.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (e.g. cpu) for the tpu backend",
    )
    parser.add_argument("--seed", type=int, default=0, help="fault-coin RNG seed")
    parser.add_argument(
        "-m",
        type=int,
        default=1,
        dest="m",
        help="OM recursion depth (1 = the reference's protocol)",
    )
    parser.add_argument(
        "--protocol",
        choices=["om", "sm"],
        default="om",
        help="om: oral messages (reference semantics); sm: signed messages",
    )
    parser.add_argument(
        "--signed",
        action="store_true",
        help="sm only: real Ed25519 sign/verify per round (host sign, "
        "batched device verify)",
    )
    parser.add_argument(
        "--state",
        default=None,
        metavar="FILE",
        help="checkpoint file: restored at startup when it exists, saved "
        "on Exit (the reference loses all state on exit; SURVEY.md sec. 6)",
    )
    args = parser.parse_args(argv)

    from ba_tpu.runtime.cluster import Cluster

    if args.backend == "py":
        if args.protocol != "om" or args.signed:
            parser.error(
                "--protocol sm/--signed require --backend tpu "
                "(the py oracle only implements unsigned oral messages)"
            )
        from ba_tpu.runtime.backends import PyBackend

        backend = PyBackend()
    else:
        from ba_tpu.runtime.backends import JaxBackend

        backend = JaxBackend(
            platform=args.platform,
            m=args.m,
            protocol=args.protocol,
            signed=args.signed,
        )
    cluster = Cluster(args.n, backend, seed=args.seed)
    if args.state:
        import os

        if os.path.exists(args.state):
            from ba_tpu.utils.snapshot import restore_cluster

            restore_cluster(args.state, cluster)
    return cluster, args.state


def main(argv=None) -> int:
    cluster, state_path = build_cluster(argv)
    from ba_tpu.runtime.repl import run_repl

    try:
        run_repl(cluster, sys.stdin, print)
    finally:
        # Save even on abnormal exit (Ctrl-C, backend error): surviving
        # crashes is the point of checkpointing (ba_tpu.utils.snapshot).
        if state_path:
            from ba_tpu.utils.snapshot import save_cluster

            save_cluster(state_path, cluster)
    return 0


if __name__ == "__main__":
    sys.exit(main())
