"""AOT specialization warmup: compile the serving path's executables
BEFORE traffic arrives (ISSUE 11 tentpole, piece 2).

The executable cache (``obs/aotcache.py``) can make any megastep
specialization warm — this module decides WHICH, and WHEN:

- **What** (:func:`service_plan`): the union of two sets, deduped —

  1. the **cross-run axes ledger**'s signature set
     (``obs/instrument.ledger_signatures``): every compile signature
     real traffic reached in previous processes, filtered to rows this
     toolchain can reproduce (the env axes ARE part of the signature —
     a stale-jaxlib row is unreproducible by construction) and to fns
     with registered builders (``parallel.pipeline.AOT_SPECS``);
  2. the **cohort-key bucket lattice** (:func:`bucket_lattice`): the
     serving front-end buckets rosters to power-of-two capacities and
     cohorts to power-of-two batch slots (``runtime/serve.py``), and
     every cohort dispatches in ``rounds_per_dispatch`` windows — so
     the reachable specialization space is finite and enumerable even
     on a first-ever boot with an empty ledger.

- **When**: in a BACKGROUND daemon thread (:class:`WarmupRunner`),
  started by ``AgreementService.open()`` — admission and dispatch never
  wait on it.  An unwarmed cohort's first request still works: the
  engine compiles on miss exactly as before, and the service counts it
  (``serve_compile_on_request_path_total``).  The runner is
  **health-gated**: before each compile it polls its ``gate()``
  (the service passes its shed-tier view, itself derived from the
  ``obs/health.py`` sampler; standalone callers can use
  :func:`health_gate`) and PAUSES while the gate reads pressure — a
  warmup must never shed or delay live traffic, which the
  warmup-never-sheds test pins.

Every signature emits one ``{"event": "warmup", "v": 1}`` record
(phases ``start`` / ``signature`` / ``done``), stamped with a
deterministic per-pass ``run_id`` (sha over the plan), and the
``serve_warmup_*`` instrument family tracks progress (the REPL's
``serve stat`` prints it).

HOST-TIER BY LINT CONTRACT (ba-lint BA301, mutation-checked like
serve): this module's MODULE-LEVEL import closure never reaches
``ba_tpu.core``/``ba_tpu.ops`` — plan construction runs jax-free; the
builders (which need the jitted trees) are imported lazily from the
runner thread.

``BA_TPU_WARM=1`` turns the service's warmup on
(``ServeConfig.from_env``); ``BA_TPU_AOT_CACHE`` places (or disables)
the persistent entry directory (``obs/aotcache.py``).
"""

from __future__ import annotations

import itertools
import json
import threading
import time

from ba_tpu import obs
from ba_tpu.utils import metrics as _metrics

WARM_ENV = "BA_TPU_WARM"

# The fns the warmup pass knows how to rebuild from a ledger row — the
# keys of ``parallel.pipeline.AOT_SPECS``, spelled here so plan
# construction stays jax-free (a drifted name simply never matches a
# ledger row; the builder lookup below would raise loudly on a plan
# that names an unknown fn).
WARM_FNS = (
    "coalesced_megastep",
    "pipeline_megastep",
    "scenario_megastep",
    "signed_megastep",
)


def builder_for(fn: str):
    """The axes -> (jitted, abstract args, kwargs) builder for ``fn``
    (lazy: the builders live with the jitted trees in
    ``parallel/pipeline.py``)."""
    if fn not in WARM_FNS:
        raise ValueError(f"no AOT builder for fn {fn!r} (know {WARM_FNS})")

    def build(axes: dict):
        from ba_tpu.parallel import pipeline

        return pipeline.AOT_SPECS[fn](axes)

    return build


def _axes_key(fn: str, axes: dict) -> str:
    return fn + ":" + json.dumps(axes, sort_keys=True, default=str)


def bucket_lattice(
    max_batch: int,
    rounds_per_dispatch: int,
    *,
    capacities=(4,),
    rounds: int | None = None,
    m: int = 1,
    scenarios=(False,),
    engines=("xla",),
    signeds=(False,),
    ms=None,
) -> list:
    """The serving dispatcher's reachable coalesced specializations:
    ``(fn, axes)`` pairs over every power-of-two batch bucket up to the
    config's bucketed ``max_batch``, each capacity bucket, and each
    dispatch-window size.

    Windows are ``rounds_per_dispatch`` plus — when ``rounds`` names the
    expected request length — the clipped first window and the ragged
    remainder (``rounds % rounds_per_dispatch``), the exact chunking
    ``coalesced_sweep`` performs.  Without a ``rounds`` hint only the
    steady-state window warms; a cohort with a ragged tail then pays one
    counted compile-on-miss for its remainder window.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch={max_batch} must be >= 1")
    if rounds_per_dispatch < 1:
        raise ValueError(
            f"rounds_per_dispatch={rounds_per_dispatch} must be >= 1"
        )
    buckets = [1]
    while buckets[-1] < max_batch:
        buckets.append(buckets[-1] * 2)
    windows = {rounds_per_dispatch}
    if rounds is not None:
        if rounds < 1:
            raise ValueError(f"rounds={rounds} must be >= 1")
        windows.add(min(rounds, rounds_per_dispatch))
        if rounds % rounds_per_dispatch:
            windows.add(rounds % rounds_per_dispatch)
    # The m axis (ISSUE 14): requests may carry their own recursion /
    # relay depth into the cohort key, so the lattice enumerates every
    # m the operator expects to serve (default: the config's single
    # dial).  A request with an UNWARMED m still serves — it pays one
    # counted compile-on-miss, exactly like an unwarmed window.
    m_values = []
    for mv in (ms if ms is not None else (m,)):
        if not isinstance(mv, int) or isinstance(mv, bool) or mv < 1:
            raise ValueError(f"m value {mv!r} must be an int >= 1")
        if mv not in m_values:
            m_values.append(mv)
    for cap in capacities:
        if cap < 1:
            raise ValueError(f"capacity {cap} must be >= 1")
    plan = []
    for signed in signeds:
        # Signed cohorts (ISSUE 14) exist only on the XLA core and
        # never carry scenario planes — the lattice mirrors the
        # dispatch loop's reachable combinations exactly, not the
        # cross product.
        combos = itertools.product(
            engines if not signed else ("xla",),
            scenarios if not signed else (False,),
            capacities,
            m_values,
            buckets,
            sorted(windows),
        )
        for engine, scenario, cap, mv, batch, window in combos:
            plan.append(
                (
                    "coalesced_megastep",
                    {
                        "batch": batch,
                        "capacity": cap,
                        "rounds": window,
                        "m": mv,
                        "max_liars": None,
                        # Literal 1 = coalesced_sweep's unroll default
                        # (serve never overrides it); if serving ever
                        # grows an unroll dial this must track
                        # min(unroll, window) or warm lookups silently
                        # stop matching.
                        "unroll": 1,
                        "scenario": bool(scenario),
                        # ISSUE 14: protocol axes — a warm lookup
                        # without them would never match the dispatch
                        # loop's uniform coalesced signature.
                        "signed": bool(signed),
                        "collapsed": False,
                        # ISSUE 13: the engine is a compile axis — a
                        # warm lookup without it would never match the
                        # dispatch loop's signature.
                        "engine": engine,
                    },
                )
            )
    return plan


def ledger_replay_set(fns=WARM_FNS) -> list:
    """Warmable ``(fn, axes)`` pairs out of the cross-run axes ledger:
    rows of known megastep fns whose env axes match THIS process's
    toolchain (a mismatched row cannot be reproduced — the versions are
    part of the signature), with the env axes and the ``run_id``
    provenance rider stripped back off into the engine's axes dict.
    Sharded rows (``data > 1``) are skipped: a sharded executable has no
    portable serialized form (``pipeline_aot_spec`` documents it).
    Empty when no ledger is configured."""
    from ba_tpu.obs import instrument

    env = instrument.ledger_env_axes()
    out = []
    for fn, sigs in instrument.ledger_signatures().items():
        if fn not in fns:
            continue
        for sig in sigs:
            core = {k: v for k, v in sig.items() if k != "run_id"}
            if env and any(core.get(k) != v for k, v in env.items()):
                continue
            axes = {k: v for k, v in core.items() if k not in env}
            if axes.get("data", 1) != 1:
                continue
            # Pre-ISSUE-13 ledger rows carry no engine axis: they were
            # XLA-core compiles, so upgrading them in place keeps a
            # pre-upgrade ledger warming the post-upgrade dispatch
            # signatures instead of going uniformly cold.
            axes.setdefault("engine", "xla")
            if axes["engine"] not in ("xla", "pallas", "interpret"):
                continue
            # Pre-ISSUE-14 rows carry no protocol axes: they were oral
            # compiles — same in-place upgrade (`collapsed` exists only
            # on the coalesced/signed signatures).
            axes.setdefault("signed", False)
            if fn in ("coalesced_megastep", "signed_megastep"):
                axes.setdefault("collapsed", False)
            out.append((fn, axes))
    return out


def plan_engines(config) -> tuple:
    """The engine axis values this service's dispatch loop can produce
    (ISSUE 13): the XLA core always (the fallback every request can
    land on), plus the RESOLVED kernel engine when the config asks for
    one — so a ``BA_TPU_ENGINE=pallas`` service warms BOTH engines'
    signatures.  Resolution needs the platform, hence the
    function-local engine import — the default "xla" path stays
    jax-free (plan construction's contract).  An unsupported kernel
    request warms only the XLA core: its cohorts will error at
    dispatch, and warming the error is not a thing."""
    requested = getattr(config, "engine", "xla") or "xla"
    if requested == "xla":
        return ("xla",)
    from ba_tpu.parallel.pipeline import resolve_engine

    try:
        resolved, _ = resolve_engine(requested, m=getattr(config, "m", 1))
    except ValueError:
        return ("xla",)
    if resolved == "xla":
        return ("xla",)
    return ("xla", resolved)


def service_plan(config) -> list:
    """The ``AgreementService`` warmup plan: ledger replay ∪ cohort
    lattice, deduped in that order (real traffic's signatures first —
    they are the ones most likely to be asked for again).  The lattice
    covers BOTH scenario-nesses by default (``kind="scenario"`` is
    first-class traffic — the shed ladder even privileges it);
    ``warm_scenarios=False`` halves the pass for interactive-only
    fleets."""
    plan = ledger_replay_set()
    plan += bucket_lattice(
        config.max_batch,
        config.rounds_per_dispatch,
        capacities=config.warm_capacities,
        rounds=config.warm_rounds,
        m=config.m,
        scenarios=(False, True) if config.warm_scenarios else (False,),
        engines=plan_engines(config),
        signeds=(
            (False, True)
            if getattr(config, "warm_signed", False)
            else (False,)
        ),
        # The config's own m dial is ALWAYS warm (it is every
        # m=None request's effective depth); warm_ms adds the other
        # depths the fleet's per-request overrides will ask for.
        ms=(config.m,) + tuple(getattr(config, "warm_ms", None) or ()),
    )
    seen: set = set()
    deduped = []
    for fn, axes in plan:
        key = _axes_key(fn, axes)
        if key not in seen:
            seen.add(key)
            deduped.append((fn, axes))
    return deduped


def sign_cache_primer(config):
    """The warm path's signature-table cache hook (ISSUE 16): a thunk
    that stages the service's expected signed round range through a
    batch-1 ``SignAheadLane`` under the shared sign seed, populating
    the process-default :class:`ba_tpu.crypto.pool.SigTableCache` with
    exactly the per-round entries every serving signed cohort
    (``coalesced_sweep(signed=True)``) will probe.  None when the
    config doesn't warm signed cohorts, or the cache is disabled —
    the runner then skips priming entirely.

    Per-ROUND cache granularity makes the hint forgiving: priming
    rounds ``[0, R)`` warms every request of R or fewer rounds, and a
    longer request simply misses on its tail rounds.
    """
    if not getattr(config, "warm_signed", False):
        return None
    rounds = getattr(config, "warm_rounds", None) or getattr(
        config, "rounds_per_dispatch", 1
    )

    def prime() -> int:
        from ba_tpu.crypto import pool as pool_mod

        if pool_mod.default_cache() is None:
            return 0
        from ba_tpu.parallel.signing import SignAheadLane

        SignAheadLane(1, seed=0).stage(0, rounds)
        return rounds

    return prime


def health_gate(max_occupancy: float | None = None, registry=None):
    """A standalone warmup gate off the live health view
    (``obs/health.py``): True while the engine's depth-occupancy window
    reads idle (None) or below ``max_occupancy`` (default 1.0 — any
    steadily-occupied pipeline defers warmup).  The serving front-end
    uses its shed-tier view instead (same sampler underneath); this
    exists for campaign-side callers warming ``pipeline_sweep``
    specializations next to live work."""
    limit = 1.0 if max_occupancy is None else max_occupancy
    sampler = obs.health.HealthSampler(registry)
    sampler.prime()

    def gate() -> bool:
        occ = sampler.sample().get("depth_occupancy")
        return occ is None or occ < limit

    return gate


class WarmupRunner:
    """The background warmup thread: replay ``plan`` (``(fn, axes)``
    pairs) through ``cache.ensure``, health-gated, observable.

    - ``gate()`` (optional): polled before each compile; False pauses
      (``pause_s`` between polls) until it reads True or the runner is
      stopped — live traffic always wins the processor.
    - :meth:`wait` is the WARM BARRIER: block until every planned
      signature was attempted (warmed or errored).
    - Per-signature failures are counted and emitted, never raised: a
      builder a future axes shape confuses must cost one cold compile
      later, not the warmup pass.
    """

    def __init__(
        self,
        cache,
        plan,
        *,
        gate=None,
        registry=None,
        run_id: str | None = None,
        pause_s: float = 0.02,
        prime=None,
    ):
        self._cache = cache
        self._plan = list(plan)
        self._gate = gate
        self._pause_s = pause_s
        # Optional host-side primer (ISSUE 16: the signature-table
        # cache, see :func:`sign_cache_primer`) run on the runner
        # thread before the compile plan — same never-raise contract
        # as a plan signature.
        self._prime = prime
        self._reg = registry if registry is not None else (
            obs.default_registry()
        )
        self._stop = threading.Event()
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        self.warmed = 0
        self.errors = 0
        self.loaded = 0
        self.compiled = 0
        # Deterministic per-pass id (the plan IS the identity): warmup
        # records of the same service config correlate across restarts.
        self.run_id = run_id or obs.flight.derive_run_id(
            "warmup", *[_axes_key(fn, axes) for fn, axes in self._plan]
        )
        # serve_ prefix per the registry's service-metric rule: these
        # ARE the serving dashboard's warmup block.
        self._reg.gauge("serve_warmup_signatures").set(len(self._plan))
        self._reg.gauge("serve_warmup_pending").set(len(self._plan))
        self._warmed_c = self._reg.counter("serve_warmup_warmed_total")
        self._errors_c = self._reg.counter("serve_warmup_errors_total")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ba-tpu-warmup", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        """Ask the runner to wind down (it finishes the in-flight
        compile — an XLA compile is not interruptible — then exits)."""
        self._stop.set()

    def wait(self, timeout: float | None = None) -> bool:
        """The warm barrier: True once every planned signature was
        attempted (False on timeout)."""
        return self._done.wait(timeout)

    def done(self) -> bool:
        return self._done.is_set()

    def ok(self) -> bool:
        """The ring-entry gate (ISSUE 20): the pass finished AND every
        planned signature warmed — zero errors.  A fleet replica joins
        the ring only when this reads True, so a half-warmed replica
        can never leak request-path compiles into a warm fleet."""
        return self._done.is_set() and self.errors == 0

    def progress(self) -> dict:
        return {
            "planned": len(self._plan),
            "warmed": self.warmed,
            "pending": len(self._plan) - self.warmed - self.errors,
            "errors": self.errors,
            "loaded": self.loaded,
            "compiled": self.compiled,
            "done": self.done(),
        }

    # -- the runner thread ---------------------------------------------------

    def _emit(self, phase: str, **fields) -> None:
        _metrics.emit(
            {
                "event": "warmup",
                "v": _metrics.SCHEMA_VERSION,
                "phase": phase,
                "run_id": self.run_id,
                **fields,
            }
        )

    def _run(self) -> None:
        t0 = time.perf_counter()
        self._emit("start", planned=len(self._plan))
        obs.instant("warmup_start", planned=len(self._plan))
        if self._prime is not None and not self._stop.is_set():
            # Pre-populate the signature-table cache (ISSUE 16): the
            # first signed cohort after the warm barrier then pays
            # lookups, not host crypto.  Counted as an error on
            # failure, never raised — the warmup-pass discipline.
            try:
                primed = self._prime()
            except Exception as e:
                self.errors += 1
                self._errors_c.inc()
                self._emit(
                    "signature", fn="sign_cache_prime", status="error",
                    error=f"{type(e).__name__}: {e}",
                )
            else:
                self._reg.gauge("serve_warmup_sign_cache_rounds").set(
                    int(primed or 0)
                )
        for fn, axes in self._plan:
            if self._stop.is_set():
                break
            # The health gate: pause (never abandon) while live traffic
            # holds pressure — tier decay or an idle queue resumes us.
            while self._gate is not None and not self._gate():
                if self._stop.wait(self._pause_s):
                    break
            if self._stop.is_set():
                break
            try:
                info = self._cache.ensure(fn, axes, builder_for(fn))
            except Exception as e:
                self.errors += 1
                self._errors_c.inc()
                self._emit(
                    "signature", fn=fn, axes=dict(axes), status="error",
                    error=f"{type(e).__name__}: {e}",
                )
            else:
                self.warmed += 1
                self._warmed_c.inc()
                if info["status"] == "loaded":
                    self.loaded += 1
                elif info["status"] == "compiled":
                    self.compiled += 1
                self._emit(
                    "signature", fn=fn, axes=dict(axes),
                    status=info["status"],
                    wall_s=round(info.get("wall_s", 0.0), 6),
                )
            self._reg.gauge("serve_warmup_pending").set(
                len(self._plan) - self.warmed - self.errors
            )
        self._emit(
            "done",
            planned=len(self._plan),
            warmed=self.warmed,
            loaded=self.loaded,
            compiled=self.compiled,
            errors=self.errors,
            stopped=self._stop.is_set(),
            wall_s=round(time.perf_counter() - t0, 6),
        )
        obs.instant(
            "warmup_done", warmed=self.warmed, errors=self.errors
        )
        self._done.set()
