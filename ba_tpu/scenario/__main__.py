"""``python -m ba_tpu.scenario <spec.json> ...`` — the CI spec validator.

For every path: load + eagerly validate the spec, round-trip it through
``to_dict``/``from_dict`` (byte-stable grammar), and lower it through
the compiler at a probe shape (batch 2, capacity = the largest general
id the events name, floor 4) so every event's ids/instances/values are
proven loweable.  Exits non-zero with the offending path on the first
failure.  Jax-free by construction (spec + compiler are numpy/stdlib
only) — the same property ba-lint relies on, so this stage costs
milliseconds in ``scripts/ci.sh``.
"""

from __future__ import annotations

import sys

from ba_tpu.scenario.compile import compile_scenario
from ba_tpu.scenario.spec import ScenarioError, from_dict, load, to_dict


def main(argv) -> int:
    if not argv:
        print("usage: python -m ba_tpu.scenario <spec.json> ...",
              file=sys.stderr)
        return 2
    for path in argv:
        try:
            spec = load(path)
            doc = to_dict(spec)
            if to_dict(from_dict(doc)) != doc:
                raise ScenarioError("to_dict/from_dict round-trip drifted")
            capacity = max(
                [4] + [gid for ev in spec.events for gid in ev.ids]
            )
            block = compile_scenario(spec, batch=2, capacity=capacity)
            mutations = int(
                block.kill.sum()
                + block.revive.sum()
                + (block.set_faulty >= 0).sum()
                + (block.set_strategy >= 0).sum()
            )
            print(
                f"{path}: OK — {spec.name!r}, {spec.rounds} round(s), "
                f"{len(spec.events)} event(s), {mutations} mutated "
                f"cell(s) at probe capacity {capacity}"
            )
        except (OSError, ScenarioError) as e:
            print(f"{path}: FAIL — {e}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
