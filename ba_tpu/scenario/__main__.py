"""``python -m ba_tpu.scenario <spec.json|ckpt.npz> ...`` — the CI
validator for campaign specs AND carry checkpoints.

For every ``.json`` path: load + eagerly validate the spec, round-trip
it through ``to_dict``/``from_dict`` (byte-stable grammar), lower it
through BOTH compilers at a probe shape (batch 2, capacity = the
largest general id the events name, floor 4) — proving every event's
ids/instances/values loweable — and check the SPARSE lowering (ISSUE
6): its JSON encoding round-trips exactly
(``SparseScenarioBlock.to_doc``/``from_doc``) and the chunks it
materializes are bit-identical to the dense planes — every chunk on
small specs, every event-bearing chunk plus a spread of empty ones
(the shared-zero fast path) on long campaigns, keeping this stage
O(events) rather than O(rounds).

For every ``.npz`` path: schema-check it as a carry checkpoint
(``utils/snapshot.validate_carry_checkpoint`` — format/version header,
required carry arrays, round-cursor/KeySchedule-counter agreement,
counter/strategy shape consistency).

Exits non-zero with the offending path on the first failure.  Jax-free
by construction (spec + compiler + checkpoint reader are numpy/stdlib
only) — the same property ba-lint relies on, so this stage costs
milliseconds in ``scripts/ci.sh``.
"""

from __future__ import annotations

import json
import sys

import numpy as np

from ba_tpu.scenario.compile import SparseScenarioBlock, compile_scenario
from ba_tpu.scenario.spec import (
    ScenarioError,
    event_rounds,
    from_dict,
    load,
    to_dict,
)
from ba_tpu.utils.snapshot import validate_carry_checkpoint


def _check_spec(path: str) -> str:
    spec = load(path)
    doc = to_dict(spec)
    if to_dict(from_dict(doc)) != doc:
        raise ScenarioError("to_dict/from_dict round-trip drifted")
    capacity = max([4] + [gid for ev in spec.events for gid in ev.ids])
    sparse = compile_scenario(spec, batch=2, capacity=capacity, sparse=True)
    # The dense reference lowering is O(rounds * capacity) host memory
    # even at probe batch 2 — fine for every committed spec, but a
    # million-round campaign naming a four-digit general id would need
    # gigabytes here.  Above the cap the dense side of the parity check
    # is skipped (the sparse round-trip and chunk/bounds validation
    # still run); the output line says which mode ran.
    dense_cells = spec.rounds * 2 * capacity * 4
    block = (
        compile_scenario(spec, batch=2, capacity=capacity)
        if dense_cells <= 64_000_000
        else None
    )
    # Sparse encoding round-trip: exact through its own JSON grammar.
    sdoc = sparse.to_doc()
    if SparseScenarioBlock.from_doc(
        json.loads(json.dumps(sdoc))
    ).to_doc() != sdoc:
        raise ScenarioError("sparse to_doc/from_doc round-trip drifted")
    # Sparse-vs-dense lowering parity, chunk by chunk (window 3 exercises
    # ragged tails and — on eventless stretches — the shared zero chunk).
    # The checked-window set is bounded by O(events), not O(rounds): on a
    # long pure-agreement stretch every window is the SAME shared zero
    # chunk, so sweeping all of a million-round campaign would cost
    # minutes while proving nothing new.  Small specs check every
    # window; large ones check every event-bearing window plus a spread
    # of empty ones (first/last included) to keep the zero-chunk fast
    # path pinned.
    step = 3
    n_windows = (spec.rounds + step - 1) // step
    if n_windows <= 512:
        windows = range(n_windows)
    else:
        picked = {r // step for r in sparse.event_rounds}
        picked.update((0, n_windows - 1))
        picked.update(range(0, n_windows, n_windows // 8))
        windows = sorted(picked)
    for w in windows if block is not None else ():
        lo = w * step
        hi = min(lo + step, spec.rounds)
        dense_chunk = block.chunk(lo, hi)
        sparse_chunk = sparse.chunk(lo, hi)
        for name, plane in dense_chunk.items():
            if not np.array_equal(plane, sparse_chunk[name]):
                raise ScenarioError(
                    f"sparse lowering diverges from dense at rounds "
                    f"[{lo}, {hi}) plane {name!r}"
                )
    if block is not None:
        mutations = int(
            block.kill.sum()
            + block.revive.sum()
            + (block.set_faulty >= 0).sum()
            + (block.set_strategy >= 0).sum()
        )
        parity = f"{mutations} mutated cell(s), sparse parity clean"
    else:
        # Exercise the sparse chunk path (bounds, event replay, the
        # shared zero chunk) even when the dense reference is skipped.
        for w in windows:
            sparse.chunk(w * step, min(w * step + step, spec.rounds))
        parity = (
            f"dense parity probe skipped ({dense_cells / 1e6:.0f}M cells)"
        )
    sparsity = len(event_rounds(spec)) / spec.rounds
    return (
        f"{path}: OK — {spec.name!r}, {spec.rounds} round(s), "
        f"{len(spec.events)} event(s) at probe capacity {capacity}, "
        f"{parity} ({sparsity:.0%} of rounds carry events)"
    )


def _check_checkpoint(path: str) -> str:
    meta = validate_carry_checkpoint(path)
    kind = "scenario" if meta.get("scenario") else "plain"
    return (
        f"{path}: OK — carry checkpoint v{meta['v']} ({kind}), "
        f"round {meta['round']}"
        + (
            f" of {meta['rounds_total']}"
            if meta.get("rounds_total") is not None
            else ""
        )
    )


def main(argv) -> int:
    if not argv:
        print(
            "usage: python -m ba_tpu.scenario <spec.json|ckpt.npz> ...",
            file=sys.stderr,
        )
        return 2
    for path in argv:
        try:
            if path.endswith(".npz"):
                print(_check_checkpoint(path))
            else:
                print(_check_spec(path))
        except (OSError, ValueError) as e:  # ScenarioError is a ValueError
            print(f"{path}: FAIL — {e}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
