"""Vectorized adversary strategies: what a faulty general's lies ARE.

The reference's only Byzantine behaviour is the per-call fair coin
(``random.randint(0, 1)``, ba.py:44-49) — the WEAKEST adversary in the
Lamport/Shostak/Pease model, whose impossibility arguments (and every
BFT evaluation since, PBFT-style colluding traitors included) are
driven by *coordinated* strategies.  This module upgrades the fault
model: each general carries an int8 strategy id, and the send paths of
``core/om.py`` / ``core/eig.py`` / ``core/sm.py`` transform their
existing coin tensors through one branch-free select — vmap/scan stay
fused, and the RANDOM row is the identity on the coins, which is what
keeps the legacy paths bit-exact (tests/test_scenario.py pins it).

Strategy table (ids are positions in ``spec.STRATEGY_NAMES`` — one
source of truth, asserted in tests):

- ``RANDOM``          — the reference adversary: an independent fair
  coin per message.  Bit-exact with the pre-strategy code under the
  same keys (the coins are drawn identically and selected unchanged).
- ``COLLUDE_ATTACK`` / ``COLLUDE_RETREAT`` — the coalition pushes one
  value to everyone (oral paths lie with that value; signed paths
  forward only that value and withhold the other).
- ``SILENT``          — withholding: oral paths answer ``UNDEFINED``
  (counted by no tally, exactly like the reference's dead-peer
  ``try/except`` vanishing, ba.py:185-186 — the on-the-wire UNDEFINED
  is a framework extension modelling a dropped reply); signed paths
  never forward (the ``sm.py`` withhold schedule generalized).
- ``ADAPTIVE_SPLIT``  — maximize disagreement: send ATTACK to
  even-indexed receivers and RETREAT to odd (the classic
  split-the-vote adversary; deterministic, coin-free).

Strategy only matters where the sender is already faulty: every caller
applies these values under its existing ``faulty`` masks, so honest
generals never lie regardless of their strategy id — and a faulty
general still *tallies* honestly (SURVEY.md Q3 is untouched).

Import discipline: this module imports ONLY jax — never ``ba_tpu.core``
(the core send paths import it, and a back-edge would cycle through the
package inits).  The command codes are therefore pinned locally;
tests assert they match ``core.types``.
"""

from __future__ import annotations

import jax.numpy as jnp

# Mirrors core.types (RETREAT/ATTACK/UNDEFINED) — pinned by
# tests/test_scenario.py; see the import-discipline note above.
_RETREAT = 0
_ATTACK = 1
_UNDEFINED = 2

# Ids are positions in ba_tpu.scenario.spec.STRATEGY_NAMES.
RANDOM = 0
COLLUDE_ATTACK = 1
COLLUDE_RETREAT = 2
SILENT = 3
ADAPTIVE_SPLIT = 4

STRATEGY_DTYPE = jnp.int8


def lie_values(strategy, coins, receiver_index) -> jnp.ndarray:
    """Per-message lie values for ORAL sends (OM answer cubes, EIG relay
    levels, round-1 equivocation).

    ``strategy`` int8 (the SENDER's id) and ``receiver_index`` int32
    broadcast against ``coins`` — int8 fair coins in {RETREAT, ATTACK},
    the RANDOM stream the caller already draws.  Returns values in
    {RETREAT, ATTACK, UNDEFINED}; the caller applies them under its
    ``faulty`` masks exactly where the raw coins used to go.  All-RANDOM
    strategies return ``coins`` unchanged (bit-exact legacy parity).

    Every constant is staged in ``coins.dtype`` up front: a python-int
    constant in a ``where`` silently promotes the whole select chain to
    int32, and the resulting per-element int8<->int32 converts in the
    send-cube's innermost loop cost ~3x wall clock on the CPU backend
    (measured while landing ISSUE 5) against +40% nominal flops.
    """
    attack = jnp.asarray(_ATTACK, coins.dtype)
    retreat = jnp.asarray(_RETREAT, coins.dtype)
    undefined = jnp.asarray(_UNDEFINED, coins.dtype)
    split = jnp.where((receiver_index & 1) == 0, attack, retreat)
    v = coins
    v = jnp.where(strategy == COLLUDE_ATTACK, attack, v)
    v = jnp.where(strategy == COLLUDE_RETREAT, retreat, v)
    v = jnp.where(strategy == SILENT, undefined, v)
    v = jnp.where(strategy == ADAPTIVE_SPLIT, split, v)
    return v


def send_gate(strategy, coins, receiver_index, value_index) -> jnp.ndarray:
    """Per-message forward gates for SIGNED sends (the SM relay cube).

    In SM(m) a faulty general cannot forge — signatures reduce its
    powers to selective withholding (core/sm.py's adversary), so a
    strategy lowers to a bool gate over the ``[.., receiver, sender,
    value]`` send cube: ``coins`` is the RANDOM gate stream (fair bool
    coins, drawn by the caller as today), ``value_index`` indexes the
    2-wide V-set axis (0=RETREAT, 1=ATTACK).  Colluders forward only
    the coalition value, SILENT never forwards, ADAPTIVE_SPLIT routes
    ATTACK to even receivers and RETREAT to odd.  All-RANDOM returns
    ``coins`` unchanged.  The chain-length soundness bound and the
    "sender must hold the value" mask stay with the caller — a gate can
    only restrict what the exact model already allowed.
    """
    is_attack = value_index == 1
    split = (receiver_index % 2 == 0) == is_attack
    g = coins
    g = jnp.where(strategy == COLLUDE_ATTACK, is_attack, g)
    g = jnp.where(strategy == COLLUDE_RETREAT, ~is_attack, g)
    g = jnp.where(strategy == SILENT, False, g)
    g = jnp.where(strategy == ADAPTIVE_SPLIT, split, g)
    return g
