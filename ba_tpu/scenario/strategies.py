"""Vectorized adversary strategies: what a faulty general's lies ARE.

The reference's only Byzantine behaviour is the per-call fair coin
(``random.randint(0, 1)``, ba.py:44-49) — the WEAKEST adversary in the
Lamport/Shostak/Pease model, whose impossibility arguments (and every
BFT evaluation since, PBFT-style colluding traitors included) are
driven by *coordinated* strategies.  This module upgrades the fault
model: each general carries an int8 strategy id, and the send paths of
``core/om.py`` / ``core/eig.py`` / ``core/sm.py`` transform their
existing coin tensors through branch-free arithmetic — vmap/scan stay
fused, and the RANDOM row is the identity on the coins, which is what
keeps the legacy paths bit-exact (tests/test_scenario.py pins it).

Strategy table (ids are positions in ``spec.STRATEGY_NAMES`` — one
source of truth, asserted in tests):

- ``RANDOM``          — the reference adversary: an independent fair
  coin per message.  Bit-exact with the pre-strategy code under the
  same keys (the coins are drawn identically and selected unchanged).
- ``COLLUDE_ATTACK`` / ``COLLUDE_RETREAT`` — the coalition pushes one
  value to everyone (oral paths lie with that value; signed paths
  forward only that value and withhold the other).
- ``SILENT``          — withholding: oral paths answer ``UNDEFINED``
  (counted by no tally, exactly like the reference's dead-peer
  ``try/except`` vanishing, ba.py:185-186 — the on-the-wire UNDEFINED
  is a framework extension modelling a dropped reply); signed paths
  never forward (the ``sm.py`` withhold schedule generalized).
- ``ADAPTIVE_SPLIT``  — maximize disagreement: send ATTACK to
  even-indexed receivers and RETREAT to odd (the classic
  split-the-vote adversary; deterministic, coin-free).

Strategy only matters where the sender is already faulty: every caller
applies these values under its existing ``faulty`` masks, so honest
generals never lie regardless of their strategy id — and a faulty
general still *tallies* honestly (SURVEY.md Q3 is untouched).

FORMULATION (ISSUE 13): the original implementation was a chain of
nested ``jnp.where`` selects — one per strategy row, each depending on
the previous — which XLA-CPU lowers as a serial select chain it cannot
vectorize across (the measured ~3x strategy-select pathology the
ROADMAP carried since ISSUE 5).  The current form is a precomputed
**lie table** (:func:`lie_table`): the per-strategy value planes build
ONCE at strategy shape (one-hot masks into multiply-adds — tiny), and
the cube-shaped send path pays exactly TWO selects (receiver-parity
pick, then known-row vs coin) instead of the four-deep chain over the
full answer cube.  The Pallas megastep kernel
(``ops/scenario_step.py``) evaluates the SAME table in-kernel — one
formulation, two engines.  The legacy select
chains are kept verbatim (:func:`lie_values_chain`,
:func:`send_gate_chain`) as the A/B baseline and parity oracle
(``bench.py megastep_ab`` dispatches on ``BA_TPU_STRATEGY_CHAIN`` /
:func:`chain_impl`); both formulations are bit-identical for coins in
{0, 1} and any int8 strategy id, which tests/test_megastep.py pins.

Import discipline: this module imports ONLY jax — never ``ba_tpu.core``
(the core send paths import it, and a back-edge would cycle through the
package inits).  The command codes are therefore pinned locally;
tests assert they match ``core.types``.
"""

from __future__ import annotations

import contextlib
import os

import jax.numpy as jnp

# Mirrors core.types (RETREAT/ATTACK/UNDEFINED) — pinned by
# tests/test_scenario.py; see the import-discipline note above.
_RETREAT = 0
_ATTACK = 1
_UNDEFINED = 2

# Ids are positions in ba_tpu.scenario.spec.STRATEGY_NAMES.
RANDOM = 0
COLLUDE_ATTACK = 1
COLLUDE_RETREAT = 2
SILENT = 3
ADAPTIVE_SPLIT = 4

STRATEGY_DTYPE = jnp.int8

# Trace-time implementation dial for the megastep_ab bench: "chain"
# re-traces the legacy nested-select formulation so the branch-free
# rewrite can be A/B-measured in one process (the bench clears the
# megastep jit caches between legs — a live program never re-traces on
# a flag flip alone).  Read at TRACE time; anything but "chain" is the
# branch-free table.
_IMPL_ENV = "BA_TPU_STRATEGY_CHAIN"
_impl_chain = os.environ.get(_IMPL_ENV, "") == "1"


@contextlib.contextmanager
def chain_impl(enabled: bool = True):
    """Trace the LEGACY strategy formulation inside this context: the
    nested select chains here AND the per-instance vmapped round in
    ``parallel.sweep.agreement_step`` (the pre-ISSUE-13 structure the
    two read together).  Bench A/B only — callers must clear the
    affected jit caches so the flag is seen at trace time."""
    global _impl_chain
    prev = _impl_chain
    _impl_chain = enabled
    try:
        yield
    finally:
        _impl_chain = prev


def lie_table(strategy, dtype):
    """The precomputed lie table at STRATEGY shape: ``(known, even_v,
    odd_v)``.

    ``known`` (bool) marks ids with a deterministic table row; the two
    value planes are what such a sender says to even- and odd-indexed
    receivers (receiver parity is ADAPTIVE_SPLIT's only receiver
    dependence — every other row is receiver-free):

    ========================  ======  =======  =======
    strategy                  known   even_v   odd_v
    ========================  ======  =======  =======
    RANDOM / unknown ids      False   (coin)   (coin)
    COLLUDE_ATTACK            True    ATTACK   ATTACK
    COLLUDE_RETREAT           True    RETREAT  RETREAT
    SILENT                    True    UNDEF    UNDEF
    ADAPTIVE_SPLIT            True    ATTACK   RETREAT
    ========================  ======  =======  =======

    The table is built ONCE at the (small) strategy shape — one-hot
    masks into multiply-adds, no cube-sized work — so the cube-shaped
    caller pays exactly TWO selects (parity pick + known/coin pick)
    where the legacy formulation paid a four-deep select chain over the
    full answer cube.  Unknown ids read ``known = False`` — the chain's
    fall-through to the coin.  Shared verbatim by the XLA send paths
    and the Pallas megastep kernel (``ops/scenario_step.py``), which
    evaluates the same table in int32 lanes.
    """
    m1 = (strategy == COLLUDE_ATTACK).astype(dtype)
    m3 = (strategy == SILENT).astype(dtype)
    m4 = (strategy == ADAPTIVE_SPLIT).astype(dtype)
    known = (
        m1 + (strategy == COLLUDE_RETREAT).astype(dtype) + m3 + m4
    ) > 0
    # COLLUDE_RETREAT's value rows are RETREAT == 0: the row exists
    # only through `known` (both planes already default to 0).
    even_v = (
        m1 * jnp.asarray(_ATTACK, dtype)
        + m3 * jnp.asarray(_UNDEFINED, dtype)
        + m4 * jnp.asarray(_ATTACK, dtype)
    )
    odd_v = even_v - m4 * jnp.asarray(_ATTACK - _RETREAT, dtype)
    return known, even_v, odd_v


def lie_values(strategy, coins, receiver_index) -> jnp.ndarray:
    """Per-message lie values for ORAL sends (OM answer cubes, EIG relay
    levels, round-1 equivocation).

    ``strategy`` int8 (the SENDER's id) and ``receiver_index`` int32
    broadcast against ``coins`` — int8 fair coins in {RETREAT, ATTACK},
    the RANDOM stream the caller already draws.  Returns values in
    {RETREAT, ATTACK, UNDEFINED}; the caller applies them under its
    ``faulty`` masks exactly where the raw coins used to go.  All-RANDOM
    strategies return ``coins`` unchanged (bit-exact legacy parity).

    Two cube-sized selects over the precomputed :func:`lie_table` —
    the branch-free replacement for the legacy four-deep select chain
    (``lie_values_chain``); bit-identical for coins in {0, 1} and any
    int8 strategy id (test-pinned).

    Every constant is staged in ``coins.dtype`` up front: a python-int
    constant in this arithmetic silently promotes the whole expression
    to int32, and the resulting per-element int8<->int32 converts in the
    send-cube's innermost loop cost ~3x wall clock on the CPU backend
    (measured while landing ISSUE 5) against +40% nominal flops.
    """
    if _impl_chain:
        return lie_values_chain(strategy, coins, receiver_index)
    known, even_v, odd_v = lie_table(strategy, coins.dtype)
    table_v = jnp.where((receiver_index & 1) == 0, even_v, odd_v)
    return jnp.where(known, table_v, coins)


def send_gate(strategy, coins, receiver_index, value_index) -> jnp.ndarray:
    """Per-message forward gates for SIGNED sends (the SM relay cube).

    In SM(m) a faulty general cannot forge — signatures reduce its
    powers to selective withholding (core/sm.py's adversary), so a
    strategy lowers to a bool gate over the ``[.., receiver, sender,
    value]`` send cube: ``coins`` is the RANDOM gate stream (fair bool
    coins, drawn by the caller as today), ``value_index`` indexes the
    2-wide V-set axis (0=RETREAT, 1=ATTACK).  Colluders forward only
    the coalition value, SILENT never forwards, ADAPTIVE_SPLIT routes
    ATTACK to even receivers and RETREAT to odd.  All-RANDOM returns
    ``coins`` unchanged.  The chain-length soundness bound and the
    "sender must hold the value" mask stay with the caller — a gate can
    only restrict what the exact model already allowed.

    Branch-free like :func:`lie_values`: disjoint strategy masks turn
    the select chain into one AND/OR tree (SILENT contributes nothing —
    its gate is constant False, expressed by masking the coin off
    through ``known`` without adding a term).
    """
    if _impl_chain:
        return send_gate_chain(strategy, coins, receiver_index, value_index)
    is_attack = value_index == 1
    m1 = strategy == COLLUDE_ATTACK
    m2 = strategy == COLLUDE_RETREAT
    m3 = strategy == SILENT
    m4 = strategy == ADAPTIVE_SPLIT
    known = m1 | m2 | m3 | m4
    split = (receiver_index % 2 == 0) == is_attack
    return (
        (coins & ~known)
        | (m1 & is_attack)
        | (m2 & ~is_attack)
        | (m4 & split)
    )


# -- legacy select-chain formulation ------------------------------------------
#
# The pre-ISSUE-13 implementations, kept verbatim: the megastep_ab
# bench's baseline leg (what the strategy cost looked like before the
# rewrite) and the parity oracle the branch-free table is pinned
# against.  Semantically identical by construction — same fall-through
# for unknown ids, same value set — never called on a hot path unless
# BA_TPU_STRATEGY_CHAIN=1 / chain_impl() re-traces it deliberately.


def lie_values_chain(strategy, coins, receiver_index) -> jnp.ndarray:
    """The nested-select formulation of :func:`lie_values` (A/B
    baseline; bit-identical outputs)."""
    attack = jnp.asarray(_ATTACK, coins.dtype)
    retreat = jnp.asarray(_RETREAT, coins.dtype)
    undefined = jnp.asarray(_UNDEFINED, coins.dtype)
    split = jnp.where((receiver_index & 1) == 0, attack, retreat)
    v = coins
    v = jnp.where(strategy == COLLUDE_ATTACK, attack, v)
    v = jnp.where(strategy == COLLUDE_RETREAT, retreat, v)
    v = jnp.where(strategy == SILENT, undefined, v)
    v = jnp.where(strategy == ADAPTIVE_SPLIT, split, v)
    return v


def send_gate_chain(strategy, coins, receiver_index, value_index) -> jnp.ndarray:
    """The nested-select formulation of :func:`send_gate` (A/B
    baseline; bit-identical outputs)."""
    is_attack = value_index == 1
    split = (receiver_index % 2 == 0) == is_attack
    g = coins
    g = jnp.where(strategy == COLLUDE_ATTACK, is_attack, g)
    g = jnp.where(strategy == COLLUDE_RETREAT, ~is_attack, g)
    g = jnp.where(strategy == SILENT, False, g)
    g = jnp.where(strategy == ADAPTIVE_SPLIT, split, g)
    return g
