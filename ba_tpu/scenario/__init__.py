"""ba_tpu.scenario — declarative adversary & membership campaigns.

Three parts (docs/DESIGN.md §9):

- **spec** (``scenario/spec.py``): scenarios are plain data — rounds ×
  events (``kill`` / ``revive`` / ``set_faulty`` / ``set_strategy``,
  with per-instance batch masks) — validated eagerly on host and
  round-tripping through JSON (``python -m ba_tpu.scenario`` is the CI
  validator).
- **compiler** (``scenario/compile.py``): lowers a spec to dense packed
  ``[R, B, n]`` planes (:class:`~ba_tpu.scenario.compile.ScenarioBlock`)
  — no Python in the hot loop.
- **strategies** (``scenario/strategies.py``): the vectorized adversary
  engine — per-general strategy ids select among branch-free behaviours
  (RANDOM / COLLUDE_ATTACK / COLLUDE_RETREAT / SILENT / ADAPTIVE_SPLIT)
  inside the send paths of ``core/om.py``/``core/eig.py``/``core/sm.py``.

The execution engine lives with the pipeline it rides:
``ba_tpu.parallel.pipeline.scenario_sweep`` (re-exported here lazily)
runs a compiled block through the donated, depth-k pipelined megastep —
kills, lowest-alive-id re-election, strategy-aware agreement, and
IC1/IC2 verdicts folding into the on-device counter block, all inside
``lax.scan``.

Import discipline: this ``__init__`` eagerly imports only the jax-free
spec + compiler layers (CI validates specs without an accelerator
stack); ``strategies`` (jax) and ``scenario_sweep`` (the engine) load
on attribute access.  ``core/om.py`` etc. import
``ba_tpu.scenario.strategies`` directly, which keeps the package init
off the jitted tree's import hot path.
"""

from ba_tpu.scenario.spec import (
    EVENT_KINDS,
    STRATEGY_NAMES,
    Event,
    Scenario,
    ScenarioError,
    event_rounds,
    from_dict,
    load,
    save,
    strategy_id,
    to_dict,
    validate,
)
from ba_tpu.scenario.compile import (
    ScenarioBlock,
    SparseScenarioBlock,
    as_dense,
    block_from_kills,
    compile_scenario,
    empty_block,
    zero_chunk,
)

__all__ = [
    "EVENT_KINDS",
    "STRATEGY_NAMES",
    "Event",
    "Scenario",
    "ScenarioBlock",
    "ScenarioError",
    "SparseScenarioBlock",
    "as_dense",
    "block_from_kills",
    "compile_scenario",
    "empty_block",
    "event_rounds",
    "from_dict",
    "load",
    "save",
    "scenario_sweep",
    "strategies",
    "strategy_id",
    "to_dict",
    "validate",
    "zero_chunk",
]


def __getattr__(name):
    # Lazy: `strategies` pulls jax, `scenario_sweep` pulls the whole
    # parallel engine — neither belongs in the jax-free spec/compile
    # path CI uses to validate committed scenario files.
    if name == "strategies":
        from ba_tpu.scenario import strategies

        return strategies
    if name == "scenario_sweep":
        from ba_tpu.parallel.pipeline import scenario_sweep

        return scenario_sweep
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
