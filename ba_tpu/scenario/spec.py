"""Declarative scenario specs: adversary & membership campaigns as data.

The reference drives every fault interactively — ``g-kill``, ``g-add``,
``g-state <id> faulty`` one REPL line at a time (ba.py:401-437) — and
its only adversary is the per-call fair coin (ba.py:44-49).  A
:class:`Scenario` captures a whole campaign declaratively: R rounds and
a list of :class:`Event`\\ s that fire BEFORE a given round, each naming
general ids (1-based, the reference's numbering) and optionally a
subset of batch instances.  Scenarios are plain data — JSON in, JSON
out, validated eagerly on host — and are lowered by
``ba_tpu.scenario.compile`` to dense per-round device planes, so no
Python ever runs inside the compiled round loop.

Event kinds (the REPL commands generalized, docs/COVERAGE.md maps them
row by row):

- ``kill``         — crash fault (``g-kill``): the named generals leave
  the alive mask before the round.
- ``revive``       — the capacity-preserving ``g-add`` analogue: a slot
  re-enters the alive mask (shapes stay static under jit, so
  membership growth is modelled inside the fixed capacity).  A living
  leader is never displaced by a revived lower id ("election is for
  life", ba.py:124-125).
- ``set_faulty``   — ``g-state <id> faulty|non-faulty`` (``value``:
  true/false).
- ``set_strategy`` — the adversary upgrade the reference never had:
  assign one of the vectorized strategies
  (``ba_tpu.scenario.strategies``) to the named generals (``value``:
  a :data:`STRATEGY_NAMES` entry).  Strategy only matters while the
  general is faulty — honest generals never lie regardless of id.

This module imports nothing heavier than the stdlib: spec validation
and (de)serialization run jax-free, which is what lets ``python -m
ba_tpu.scenario`` round-trip the committed spec files in CI for free.

JSON grammar (one object per event; exactly one kind key)::

    {"name": "cascading-failover", "rounds": 6, "order": "attack",
     "events": [
       {"round": 1, "kill": [1]},
       {"round": 2, "set_faulty": [4], "value": true},
       {"round": 3, "set_strategy": [4], "value": "collude_retreat",
        "instances": [0, 1]}]}

An optional top-level ``"provenance"`` object (any JSON-able dict,
round-tripped verbatim) records where a spec came from — the adversary
search engine (``ba_tpu.search``, ISSUE 15) stamps its replay recipe
there on every exported minimal reproducer.  The compiler never reads
it.
"""

from __future__ import annotations

import dataclasses
import json

EVENT_KINDS = ("kill", "revive", "set_faulty", "set_strategy")

# Strategy id table (single source of truth; ``strategies.py`` pins its
# jnp-side constants to these positions and a test asserts the match).
STRATEGY_NAMES = (
    "random",
    "collude_attack",
    "collude_retreat",
    "silent",
    "adaptive_split",
)

ORDERS = ("attack", "retreat")


class ScenarioError(ValueError):
    """Raised by eager host-side validation — never from device code."""


def strategy_id(name: str) -> int:
    """Strategy name -> int8 id (the value ``set_strategy`` lowers to)."""
    try:
        return STRATEGY_NAMES.index(name)
    except ValueError:
        raise ScenarioError(
            f"unknown strategy {name!r}; one of {STRATEGY_NAMES}"
        ) from None


@dataclasses.dataclass(frozen=True)
class Event:
    """One membership/fault/strategy mutation, applied BEFORE ``round``.

    ``ids`` are general ids (1-based); ``instances`` limits the event to
    a subset of batch instances (None = every instance).  ``value`` is
    kind-specific: kill/revive take none, ``set_faulty`` a bool,
    ``set_strategy`` a :data:`STRATEGY_NAMES` entry.
    """

    round: int
    kind: str
    ids: tuple
    value: object = None
    instances: tuple | None = None


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A validated campaign: R rounds of ``order`` under ``events``.

    ``provenance`` (ISSUE 15) is an optional JSON-able dict of
    where-this-spec-came-from metadata — the adversary search engine
    stamps ``{"search": {seed, uid, generation, objective, score,
    counters}}`` on every exported minimal reproducer so a found spec
    carries its own replay recipe.  Purely descriptive: the compiler
    never reads it, and a spec without one is unchanged.
    """

    name: str
    rounds: int
    events: tuple
    order: str = "attack"
    provenance: dict | None = None


def validate(spec: Scenario) -> Scenario:
    """Eager host-side validation; returns ``spec`` for chaining.

    Everything that could silently mis-lower raises here, before any
    array is built: unknown kinds/strategies/orders, out-of-range
    rounds, malformed id/instance lists, kind/value mismatches, and a
    kill+revive of the same general in the same round (ambiguous — the
    compiler applies kills before revives, which would silently resolve
    the conflict toward revive).
    """
    if not isinstance(spec.name, str) or not spec.name:
        raise ScenarioError("scenario name must be a non-empty string")
    if not isinstance(spec.rounds, int) or spec.rounds < 1:
        raise ScenarioError(f"rounds={spec.rounds!r} must be an int >= 1")
    if spec.order not in ORDERS:
        raise ScenarioError(
            f"order={spec.order!r} must be one of {ORDERS} "
            "(non-canonical orders are a leader raw-string REPL quirk, "
            "not a campaign input)"
        )
    if spec.provenance is not None:
        if not isinstance(spec.provenance, dict):
            raise ScenarioError(
                f"provenance must be an object, got {spec.provenance!r}"
            )
        try:
            json.dumps(spec.provenance)
        except (TypeError, ValueError) as e:
            # A non-JSON-able provenance would only fail at save() time,
            # deep inside a search export — the eager-validation rule.
            raise ScenarioError(
                f"provenance must be JSON-serializable: {e}"
            ) from None
    killed_revived = {}
    for ev in spec.events:
        if ev.kind not in EVENT_KINDS:
            raise ScenarioError(
                f"unknown event kind {ev.kind!r}; one of {EVENT_KINDS}"
            )
        if not isinstance(ev.round, int) or not 0 <= ev.round < spec.rounds:
            raise ScenarioError(
                f"event round {ev.round!r} outside [0, {spec.rounds})"
            )
        if not ev.ids or not all(
            isinstance(i, int) and i >= 1 for i in ev.ids
        ):
            raise ScenarioError(
                f"{ev.kind} event needs a non-empty list of 1-based "
                f"general ids, got {ev.ids!r}"
            )
        if len(set(ev.ids)) != len(ev.ids):
            raise ScenarioError(f"duplicate ids in {ev.kind} event: {ev.ids}")
        if ev.instances is not None:
            if not ev.instances or not all(
                isinstance(i, int) and i >= 0 for i in ev.instances
            ):
                raise ScenarioError(
                    f"instances must be a non-empty list of batch indices, "
                    f"got {ev.instances!r}"
                )
            if len(set(ev.instances)) != len(ev.instances):
                raise ScenarioError(
                    f"duplicate instances in {ev.kind} event: {ev.instances}"
                )
        if ev.kind in ("kill", "revive"):
            if ev.value is not None:
                raise ScenarioError(f"{ev.kind} events take no value")
            for gid in ev.ids:
                other = killed_revived.setdefault((ev.round, gid), ev.kind)
                if other != ev.kind:
                    raise ScenarioError(
                        f"general {gid} both killed and revived before "
                        f"round {ev.round}"
                    )
        elif ev.kind == "set_faulty":
            if not isinstance(ev.value, bool):
                raise ScenarioError(
                    f"set_faulty value must be true/false, got {ev.value!r}"
                )
        elif ev.kind == "set_strategy":
            if not isinstance(ev.value, str):
                raise ScenarioError(
                    f"set_strategy value must be a strategy name, "
                    f"got {ev.value!r}"
                )
            strategy_id(ev.value)  # raises on unknown names
    return spec


def event_rounds(spec: Scenario) -> tuple:
    """Sorted distinct rounds carrying at least one event.

    The campaign's sparsity profile: the streaming engine (ISSUE 6)
    stages only chunks that intersect these rounds — everything else is
    the shared zero chunk, uploaded once.  ``python -m ba_tpu.scenario``
    reports ``len(event_rounds) / rounds`` so a spec author can see what
    fraction of a long campaign actually mutates.
    """
    return tuple(sorted({ev.round for ev in spec.events}))


# -- (de)serialization --------------------------------------------------------


def to_dict(spec: Scenario) -> dict:
    """The JSON-grammar form (stable key order, round-trips exactly)."""
    events = []
    for ev in spec.events:
        d = {"round": ev.round, ev.kind: list(ev.ids)}
        if ev.value is not None:
            d["value"] = ev.value
        if ev.instances is not None:
            d["instances"] = list(ev.instances)
        events.append(d)
    doc = {
        "name": spec.name,
        "rounds": spec.rounds,
        "order": spec.order,
        "events": events,
    }
    if spec.provenance is not None:
        doc["provenance"] = spec.provenance
    return doc


def from_dict(doc: dict) -> Scenario:
    """Parse + validate the JSON-grammar form; strict about keys."""
    if not isinstance(doc, dict):
        raise ScenarioError(f"scenario document must be an object, got {doc!r}")
    unknown = set(doc) - {"name", "rounds", "order", "events", "provenance"}
    if unknown:
        raise ScenarioError(f"unknown scenario keys: {sorted(unknown)}")
    events = []
    for i, d in enumerate(doc.get("events", [])):
        if not isinstance(d, dict):
            raise ScenarioError(f"event #{i} must be an object, got {d!r}")
        kinds = [k for k in EVENT_KINDS if k in d]
        if len(kinds) != 1:
            raise ScenarioError(
                f"event #{i} must carry exactly one of {EVENT_KINDS}, "
                f"got {sorted(d)}"
            )
        extra = set(d) - {"round", "value", "instances", kinds[0]}
        if extra:
            raise ScenarioError(f"event #{i} unknown keys: {sorted(extra)}")
        ids = d[kinds[0]]
        if not isinstance(ids, list):
            raise ScenarioError(f"event #{i} ids must be a list, got {ids!r}")
        inst = d.get("instances")
        events.append(
            Event(
                round=d.get("round", 0),
                kind=kinds[0],
                ids=tuple(ids),
                value=d.get("value"),
                instances=None if inst is None else tuple(inst),
            )
        )
    return validate(
        Scenario(
            name=doc.get("name", ""),
            rounds=doc.get("rounds", 0),
            events=tuple(events),
            order=doc.get("order", "attack"),
            provenance=doc.get("provenance"),
        )
    )


def load(path: str) -> Scenario:
    """Load + validate a JSON spec file."""
    with open(path) as fh:
        try:
            doc = json.load(fh)
        except json.JSONDecodeError as e:
            raise ScenarioError(f"{path}: not valid JSON ({e})") from None
    return from_dict(doc)


def save(path: str, spec: Scenario) -> None:
    with open(path, "w") as fh:
        json.dump(to_dict(validate(spec)), fh, indent=1)
        fh.write("\n")
