"""Scenario compiler: spec events -> per-round device planes, dense or
sparse.

The lowering contract of the scenario engine (docs/DESIGN.md §9): a
validated :class:`~ba_tpu.scenario.spec.Scenario` compiles ONCE, on
host, and from then on the campaign is pure data riding the pipelined
megastep's scan (``parallel/pipeline.py``).  No Python callback, dict
lookup, or event list survives into the hot loop; the only per-dispatch
host work is materializing the next chunk of rounds (``chunk``), which
feeds an async upload, not a sync.

Two lowerings, bit-exact with each other (the parity tests pin it):

- **dense** (:class:`ScenarioBlock`): four ``[R, B, n]`` planes — the
  original ISSUE 5 form.  Host memory is O(R); fine for short
  campaigns, the only option when the caller already has per-round
  arrays (``block_from_kills``).
- **sparse** (:class:`SparseScenarioBlock`, ISSUE 6): events stay
  round-indexed on the host — O(events) memory, so R is unbounded —
  and ``chunk(lo, hi)`` materializes only the ``[hi-lo, B, n]`` planes
  one dispatch consumes.  A chunk with no events short-circuits to a
  SHARED read-only zero chunk (module-level cache), which the engine
  recognizes to skip re-uploading pure-agreement stretches.

Plane encodings (one row per round, applied BEFORE that round runs):

- ``kill`` / ``revive`` ``[R, B, n]`` bool — alive-mask deltas
  (``alive = (alive & ~kill) | revive``; validation rejects a same-round
  kill+revive of one general, so the order cannot silently matter);
- ``set_faulty`` ``[R, B, n]`` int8 — ``-1`` keep, ``0`` clear, ``1``
  set (the ``g-state`` tri-state: most cells are "keep");
- ``set_strategy`` ``[R, B, n]`` int8 — ``-1`` keep, else a strategy id
  (``spec.STRATEGY_NAMES`` position).

Like ``spec.py`` this module is numpy-only (no jax): CI round-trips the
committed spec files through both lowerings without touching an
accelerator stack.
"""

from __future__ import annotations

import bisect
import dataclasses
import functools
import threading

import numpy as np

from ba_tpu.scenario.spec import (
    STRATEGY_NAMES,
    Scenario,
    ScenarioError,
    strategy_id,
    validate,
)

KEEP = -1  # "no change" cell in the set_faulty / set_strategy planes


def _is_int(value) -> bool:
    """A real int (bool excluded) — the only type safe to index planes
    with; JSON happily delivers 5.0 or "5" where a round belongs."""
    return isinstance(value, int) and not isinstance(value, bool)

PLANE_NAMES = ("kill", "revive", "set_faulty", "set_strategy")

SPARSE_FORMAT = "ba_tpu.sparse_scenario"
SPARSE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ScenarioBlock:
    """Compiled campaign: four dense ``[R, B, n]`` planes (see module
    docstring for the cell encodings).  Plain data — numpy out of the
    compiler, device arrays once the engine has staged chunks."""

    kill: np.ndarray
    revive: np.ndarray
    set_faulty: np.ndarray
    set_strategy: np.ndarray

    def __post_init__(self):
        shape = np.shape(self.kill)
        if len(shape) != 3:
            raise ScenarioError(
                f"scenario planes must be [R, B, n], got {shape}"
            )
        for name in ("revive", "set_faulty", "set_strategy"):
            got = np.shape(getattr(self, name))
            if got != shape:
                raise ScenarioError(
                    f"plane shape mismatch: kill {shape} vs {name} {got}"
                )

    @property
    def rounds(self) -> int:
        return int(np.shape(self.kill)[0])

    @property
    def batch(self) -> int:
        return int(np.shape(self.kill)[1])

    @property
    def n(self) -> int:
        return int(np.shape(self.kill)[2])

    def chunk(self, lo: int, hi: int) -> dict:
        """Rounds ``[lo, hi)`` as a dict of planes — what one pipelined
        dispatch consumes (the megastep's scan ``xs``)."""
        return {
            "kill": self.kill[lo:hi],
            "revive": self.revive[lo:hi],
            "set_faulty": self.set_faulty[lo:hi],
            "set_strategy": self.set_strategy[lo:hi],
        }

    @functools.cached_property
    def _round_has_event(self) -> np.ndarray:
        """``[R]`` bool, True where any plane cell departs from no-op —
        one pass over the planes at first use so the engine's per-
        dispatch emptiness probe is O(chunk) bits, not an O(chunk*B*n)
        rescan of all four planes (with two chunk-sized temporaries) on
        the staging path's critical section."""
        return (
            self.kill.any(axis=(1, 2))
            | self.revive.any(axis=(1, 2))
            | (self.set_faulty != KEEP).any(axis=(1, 2))
            | (self.set_strategy != KEEP).any(axis=(1, 2))
        )

    def chunk_is_empty(self, lo: int, hi: int) -> bool:
        """True when no event touches rounds ``[lo, hi)`` — the engine's
        cue to reuse its staged zero chunk instead of uploading again."""
        return not self._round_has_event[lo:hi].any()

def _fresh_planes(shape) -> dict:
    """One zero-initialized plane set — THE definition of "no event",
    shared by the dense compiler's base block, sparse chunk
    materialization and the zero-chunk cache so a new plane or dtype
    change cannot drift between the lowerings."""
    return {
        "kill": np.zeros(shape, bool),
        "revive": np.zeros(shape, bool),
        "set_faulty": np.full(shape, KEEP, np.int8),
        "set_strategy": np.full(shape, KEEP, np.int8),
    }


def empty_block(rounds: int, batch: int, capacity: int) -> ScenarioBlock:
    """The no-op campaign: ``rounds`` rounds, nothing mutates.

    ``pipeline_sweep`` without a scenario IS this block (the parity test
    pins bit-exactness), so the empty block exists mostly for tests and
    as the base the compiler writes events into.
    """
    if rounds < 1:
        raise ScenarioError(f"rounds={rounds} must be >= 1")
    if batch < 1 or capacity < 1:
        raise ScenarioError(
            f"batch={batch} / capacity={capacity} must be >= 1"
        )
    return ScenarioBlock(**_fresh_planes((rounds, batch, capacity)))


def block_from_kills(kill_schedule) -> ScenarioBlock:
    """A kill-only block from a dense ``[R, B, n]`` bool schedule — the
    exact input ``failover_sweep`` has always taken, so the old engine's
    call sites lower onto the scenario engine unchanged."""
    kills = np.asarray(kill_schedule, bool)
    if kills.ndim != 3:
        raise ScenarioError(
            f"kill schedule must be [R, B, n], got shape {kills.shape}"
        )
    block = empty_block(*kills.shape)
    return dataclasses.replace(block, kill=kills)


def _resolve_events(spec: Scenario, batch: int, capacity: int, ids=None):
    """Spec events -> ``(round, kind, instances|None, slots, value)``
    tuples in spec order — the roster-resolved, lowering-agnostic form
    both the dense and the sparse compiler consume (ONE resolution
    implementation, so the two lowerings cannot drift).

    ``instances`` is ``None`` for every-instance events (kept symbolic so
    the sparse encoding stays O(events), not O(events * batch));
    ``value`` is ``None`` for kill/revive, ``0``/``1`` for set_faulty,
    the strategy id for set_strategy.  Unknown ids and out-of-range
    instances raise here — eagerly, on host — rather than silently
    masking to nothing on device.
    """
    if ids is None:
        ids = np.arange(1, capacity + 1)
    else:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.shape[0] != capacity:
            raise ScenarioError(
                f"ids has {ids.shape[0]} entries for capacity {capacity}"
            )
    slot_of = {}
    for slot, gid in enumerate(ids.tolist()):
        if gid > 0 and gid not in slot_of:  # 0 = unoccupied padding slot
            slot_of[gid] = slot

    resolved = []
    for ev in spec.events:
        try:
            slots = tuple(slot_of[gid] for gid in ev.ids)
        except KeyError as e:
            raise ScenarioError(
                f"{ev.kind} event names general id {e.args[0]} which is "
                f"not in the roster (ids {sorted(slot_of)})"
            ) from None
        if ev.instances is None:
            rows = None
        else:
            rows = tuple(int(i) for i in ev.instances)
            if max(rows) >= batch:
                raise ScenarioError(
                    f"{ev.kind} event instance {max(rows)} outside "
                    f"batch {batch}"
                )
        if ev.kind in ("kill", "revive"):
            value = None
        elif ev.kind == "set_faulty":
            value = 1 if ev.value else 0
        else:  # set_strategy (validate() rejected everything else)
            value = strategy_id(ev.value)
        resolved.append((ev.round, ev.kind, rows, slots, value))
    return tuple(resolved)


def _apply_event(planes: dict, r: int, kind, rows, slots, value, batch):
    """Write one resolved event into a chunk's plane rows (shared by the
    dense compiler and sparse chunk materialization — identical writes,
    identical order, hence the bit-exact parity)."""
    cells = np.ix_(
        np.arange(batch) if rows is None else np.asarray(rows, np.int64),
        np.asarray(slots, np.int64),
    )
    if kind == "kill":
        planes["kill"][r][cells] = True
    elif kind == "revive":
        planes["revive"][r][cells] = True
    elif kind == "set_faulty":
        planes["set_faulty"][r][cells] = value
    else:
        planes["set_strategy"][r][cells] = value


# Shared zero chunks: one read-only materialization per (rounds, B, n)
# shape, handed out to EVERY empty chunk request — the host half of the
# engine's "pure-agreement stretches upload nothing new" fast path (the
# device half is the engine's staged-zero-chunk cache).  Read-only so a
# caller scribbling on a shared chunk fails loudly instead of corrupting
# every later empty chunk.
_zero_lock = threading.Lock()
_zero_chunks: dict = {}
_ZERO_CHUNK_CACHE_MAX = 8


def zero_chunk(rounds: int, batch: int, capacity: int) -> dict:
    """The shared no-event chunk for this shape (read-only planes).

    The cache is bounded: a long-lived process (REPL, serving layer)
    cycling through campaign shapes must not pin one chunk-sized zero
    set per shape forever — at the production chunk that is hundreds of
    host MB per entry.  Oldest entries are dropped FIFO (rebuilding a
    zero chunk is one memset; handed-out chunks stay valid, they just
    stop being shared)."""
    key = (rounds, batch, capacity)
    with _zero_lock:
        chunk = _zero_chunks.get(key)
        if chunk is None:
            chunk = _fresh_planes(key)
            for plane in chunk.values():
                plane.setflags(write=False)
            while len(_zero_chunks) >= _ZERO_CHUNK_CACHE_MAX:
                _zero_chunks.pop(next(iter(_zero_chunks)))
            _zero_chunks[key] = chunk
    return chunk


@dataclasses.dataclass(frozen=True)
class SparseScenarioBlock:
    """Sparse-lowered campaign: events stay round-indexed on the host.

    Host memory is O(len(events)) — independent of ``rounds`` — which is
    what makes million-round campaigns representable at all (a dense
    ``[R, B, n]`` block at R = 1e6, B = 2048, n = 64 would need ~0.5 TB).
    ``chunk(lo, hi)`` materializes the dense ``[hi-lo, B, n]`` planes one
    pipelined dispatch consumes, bit-exact with the dense lowering's
    slice of the same window (``tests/test_scenario.py`` pins it per
    chunk, including the empty-chunk fast path, which returns the
    SHARED read-only :func:`zero_chunk`).

    ``events`` holds :func:`_resolve_events` tuples in spec order —
    plain ints/tuples, which is what keeps the JSON encoding
    (:meth:`to_doc`/:meth:`from_doc`) exact.
    """

    rounds: int
    batch: int
    capacity: int
    events: tuple = ()

    def __post_init__(self):
        # Type checks before bounds checks: these fields index numpy
        # planes later, and a float/str that limps through a `<` compare
        # here (5.0 < rounds is True) would crash mid-campaign inside
        # the staging hot loop — or, for strings, escape as a TypeError
        # the jax-free CLI's ScenarioError handling never sees.
        for name in ("rounds", "batch", "capacity"):
            if not _is_int(getattr(self, name)):
                raise ScenarioError(
                    f"{name}={getattr(self, name)!r} must be an int"
                )
        if self.rounds < 1:
            raise ScenarioError(f"rounds={self.rounds} must be >= 1")
        if self.batch < 1 or self.capacity < 1:
            raise ScenarioError(
                f"batch={self.batch} / capacity={self.capacity} must be >= 1"
            )
        for r, kind, rows, slots, value in self.events:
            if not _is_int(r):
                raise ScenarioError(
                    f"sparse event round {r!r} must be an int"
                )
            if not 0 <= r < self.rounds:
                raise ScenarioError(
                    f"sparse event round {r} outside [0, {self.rounds})"
                )
            if kind not in PLANE_NAMES:
                raise ScenarioError(f"unknown sparse event kind {kind!r}")
            # Bounds here, not at chunk() time: a from_doc-built block
            # must fail at construction, never mid-campaign inside the
            # staging hot loop — and negative indices would silently
            # wrap to the wrong general/instance.
            for slot in slots:
                if not _is_int(slot) or not 0 <= slot < self.capacity:
                    raise ScenarioError(
                        f"sparse {kind} event slot {slot!r} outside "
                        f"[0, {self.capacity})"
                    )
            if rows is not None:
                for row in rows:
                    if not _is_int(row) or not 0 <= row < self.batch:
                        raise ScenarioError(
                            f"sparse {kind} event instance {row!r} outside "
                            f"[0, {self.batch})"
                        )
            # Values too — the resolved contract (_resolve_events):
            # kill/revive carry None, set_faulty 0/1, set_strategy a
            # strategy id.  A hand-edited doc with the SPEC grammar's
            # string form ("silent") or an out-of-table id would
            # otherwise limp through from_doc and blow up inside
            # _apply_event's int8 plane write mid-campaign — or, for a
            # set_faulty value like 3, be written silently into the
            # tri-state plane.
            if kind in ("kill", "revive"):
                if value is not None:
                    raise ScenarioError(
                        f"sparse {kind} event value must be null, "
                        f"got {value!r}"
                    )
            elif kind == "set_faulty":
                if not _is_int(value) or value not in (0, 1):
                    raise ScenarioError(
                        f"sparse set_faulty event value {value!r} must "
                        f"be 0 or 1"
                    )
            elif not _is_int(value) or not 0 <= value < len(STRATEGY_NAMES):
                raise ScenarioError(
                    f"sparse set_strategy event value {value!r} outside "
                    f"the strategy table [0, {len(STRATEGY_NAMES)})"
                )

    @property
    def n(self) -> int:
        return self.capacity

    @functools.cached_property
    def event_rounds(self) -> tuple:
        """Sorted distinct rounds carrying at least one event."""
        return tuple(sorted({ev[0] for ev in self.events}))

    @functools.cached_property
    def _by_round(self) -> dict:
        by = {}
        for ev in self.events:
            by.setdefault(ev[0], []).append(ev)
        return by

    def chunk_is_empty(self, lo: int, hi: int) -> bool:
        """True when no event touches rounds ``[lo, hi)`` — an O(log E)
        bisect over the sorted event rounds, never an array scan."""
        i = bisect.bisect_left(self.event_rounds, lo)
        return i >= len(self.event_rounds) or self.event_rounds[i] >= hi

    def chunk_nbytes(self, lo: int, hi: int) -> int:
        return (hi - lo) * self.batch * self.capacity * len(PLANE_NAMES)

    def chunk(self, lo: int, hi: int) -> dict:
        """Materialize rounds ``[lo, hi)`` as dense planes.

        Empty windows return the SHARED read-only zero chunk (no
        allocation); event windows allocate fresh planes and replay the
        window's events in spec order — the same writes the dense
        compiler performed, hence bit-exact.
        """
        if not 0 <= lo < hi <= self.rounds:
            raise ScenarioError(
                f"chunk [{lo}, {hi}) outside campaign [0, {self.rounds})"
            )
        if self.chunk_is_empty(lo, hi):
            return zero_chunk(hi - lo, self.batch, self.capacity)
        planes = _fresh_planes((hi - lo, self.batch, self.capacity))
        for r in self.event_rounds[
            bisect.bisect_left(self.event_rounds, lo):
        ]:
            if r >= hi:
                break
            for _, kind, rows, slots, value in self._by_round[r]:
                _apply_event(
                    planes, r - lo, kind, rows, slots, value, self.batch
                )
        return planes

    # -- JSON encoding (the CI validator round-trips it jax-free) -----------

    def to_doc(self) -> dict:
        """The versioned JSON form of the sparse encoding (exact
        round-trip through :meth:`from_doc`; ``python -m
        ba_tpu.scenario`` CI-validates it for every committed spec)."""
        return {
            "format": SPARSE_FORMAT,
            "v": SPARSE_VERSION,
            "rounds": self.rounds,
            "batch": self.batch,
            "capacity": self.capacity,
            "events": [
                {
                    "round": r,
                    "kind": kind,
                    "instances": None if rows is None else list(rows),
                    "slots": list(slots),
                    "value": value,
                }
                for r, kind, rows, slots, value in self.events
            ],
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "SparseScenarioBlock":
        if not isinstance(doc, dict) or doc.get("format") != SPARSE_FORMAT:
            raise ScenarioError(
                f"not a sparse scenario document: {doc!r:.120}"
            )
        if doc.get("v") != SPARSE_VERSION:
            raise ScenarioError(
                f"unknown sparse scenario version {doc.get('v')!r}"
            )
        events = []
        for i, d in enumerate(doc.get("events", [])):
            try:
                rows = d["instances"]
                events.append(
                    (
                        d["round"],
                        d["kind"],
                        None if rows is None else tuple(rows),
                        tuple(d["slots"]),
                        d["value"],
                    )
                )
            except (KeyError, TypeError) as e:
                raise ScenarioError(
                    f"sparse event #{i} malformed: {e}"
                ) from None
        return cls(
            rounds=doc.get("rounds", 0),
            batch=doc.get("batch", 0),
            capacity=doc.get("capacity", 0),
            events=tuple(events),
        )


def as_dense(block: SparseScenarioBlock) -> ScenarioBlock:
    """Materialize a sparse block fully — the parity tests' bridge (and
    the escape hatch for call sites that still want dense arrays).
    O(R) memory: exactly what the sparse form exists to avoid, so keep
    it out of long-campaign paths.  Always fresh writable planes — an
    event-free block must not hand out the shared read-only zero chunk
    the way :meth:`SparseScenarioBlock.chunk` deliberately does."""
    planes = _fresh_planes((block.rounds, block.batch, block.capacity))
    for r, kind, rows, slots, value in block.events:
        _apply_event(planes, r, kind, rows, slots, value, block.batch)
    return ScenarioBlock(**planes)


def compile_scenario(
    spec: Scenario,
    batch: int,
    capacity: int,
    ids=None,
    sparse: bool = False,
):
    """Lower a validated spec for a ``[batch, capacity]`` state.

    ``sparse=False`` (default) returns the dense :class:`ScenarioBlock`
    — O(R) host memory, the ISSUE 5 form.  ``sparse=True`` returns a
    :class:`SparseScenarioBlock` — O(events) memory, the streaming form
    long campaigns need; both lower bit-exactly (shared event
    resolution, shared plane writes).

    ``ids`` maps slots to general ids (default ``1..capacity``, the
    ascending spawn order of ba.py:344-351 that ``make_state`` /
    ``make_sweep_state`` use); the interactive backend passes its roster
    ids so REPL scenarios address the same generals ``g-kill`` would.
    Unknown ids and out-of-range instances raise here — eagerly, on
    host — rather than silently masking to nothing on device.
    """
    validate(spec)
    block = SparseScenarioBlock(
        rounds=spec.rounds, batch=batch, capacity=capacity,
        events=_resolve_events(spec, batch, capacity, ids),
    )
    # Dense is DEFINED as the sparse form fully materialized — one
    # lowering implementation, so the parity the tests pin is structural.
    return block if sparse else as_dense(block)
