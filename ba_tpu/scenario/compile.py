"""Scenario compiler: spec events -> dense per-round device planes.

The lowering contract of the scenario engine (docs/DESIGN.md §9): a
validated :class:`~ba_tpu.scenario.spec.Scenario` compiles ONCE, on
host, into a :class:`ScenarioBlock` of dense ``[R, B, n]`` planes —
packed bool/int8, numpy — and from then on the campaign is pure data
riding the pipelined megastep's scan (``parallel/pipeline.py``).  No
Python callback, dict lookup, or event list survives into the hot loop;
the only per-dispatch host work is slicing the next chunk of rounds off
these arrays (``chunk``), which is an async upload, not a sync.

Plane encodings (one row per round, applied BEFORE that round runs):

- ``kill`` / ``revive`` ``[R, B, n]`` bool — alive-mask deltas
  (``alive = (alive & ~kill) | revive``; validation rejects a same-round
  kill+revive of one general, so the order cannot silently matter);
- ``set_faulty`` ``[R, B, n]`` int8 — ``-1`` keep, ``0`` clear, ``1``
  set (the ``g-state`` tri-state: most cells are "keep");
- ``set_strategy`` ``[R, B, n]`` int8 — ``-1`` keep, else a strategy id
  (``spec.STRATEGY_NAMES`` position).

Like ``spec.py`` this module is numpy-only (no jax): CI round-trips the
committed spec files through the compiler without touching an
accelerator stack.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ba_tpu.scenario.spec import Scenario, ScenarioError, strategy_id, validate

KEEP = -1  # "no change" cell in the set_faulty / set_strategy planes


@dataclasses.dataclass(frozen=True)
class ScenarioBlock:
    """Compiled campaign: four dense ``[R, B, n]`` planes (see module
    docstring for the cell encodings).  Plain data — numpy out of the
    compiler, device arrays once the engine has staged chunks."""

    kill: np.ndarray
    revive: np.ndarray
    set_faulty: np.ndarray
    set_strategy: np.ndarray

    def __post_init__(self):
        shape = np.shape(self.kill)
        if len(shape) != 3:
            raise ScenarioError(
                f"scenario planes must be [R, B, n], got {shape}"
            )
        for name in ("revive", "set_faulty", "set_strategy"):
            got = np.shape(getattr(self, name))
            if got != shape:
                raise ScenarioError(
                    f"plane shape mismatch: kill {shape} vs {name} {got}"
                )

    @property
    def rounds(self) -> int:
        return int(np.shape(self.kill)[0])

    @property
    def batch(self) -> int:
        return int(np.shape(self.kill)[1])

    @property
    def n(self) -> int:
        return int(np.shape(self.kill)[2])

    def chunk(self, lo: int, hi: int) -> dict:
        """Rounds ``[lo, hi)`` as a dict of planes — what one pipelined
        dispatch consumes (the engine donates these to the megastep)."""
        return {
            "kill": self.kill[lo:hi],
            "revive": self.revive[lo:hi],
            "set_faulty": self.set_faulty[lo:hi],
            "set_strategy": self.set_strategy[lo:hi],
        }


def empty_block(rounds: int, batch: int, capacity: int) -> ScenarioBlock:
    """The no-op campaign: ``rounds`` rounds, nothing mutates.

    ``pipeline_sweep`` without a scenario IS this block (the parity test
    pins bit-exactness), so the empty block exists mostly for tests and
    as the base the compiler writes events into.
    """
    if rounds < 1:
        raise ScenarioError(f"rounds={rounds} must be >= 1")
    if batch < 1 or capacity < 1:
        raise ScenarioError(
            f"batch={batch} / capacity={capacity} must be >= 1"
        )
    shape = (rounds, batch, capacity)
    return ScenarioBlock(
        kill=np.zeros(shape, bool),
        revive=np.zeros(shape, bool),
        set_faulty=np.full(shape, KEEP, np.int8),
        set_strategy=np.full(shape, KEEP, np.int8),
    )


def block_from_kills(kill_schedule) -> ScenarioBlock:
    """A kill-only block from a dense ``[R, B, n]`` bool schedule — the
    exact input ``failover_sweep`` has always taken, so the old engine's
    call sites lower onto the scenario engine unchanged."""
    kills = np.asarray(kill_schedule, bool)
    if kills.ndim != 3:
        raise ScenarioError(
            f"kill schedule must be [R, B, n], got shape {kills.shape}"
        )
    block = empty_block(*kills.shape)
    return dataclasses.replace(block, kill=kills)


def compile_scenario(
    spec: Scenario,
    batch: int,
    capacity: int,
    ids=None,
) -> ScenarioBlock:
    """Lower a validated spec to dense planes for a ``[batch, capacity]``
    state.

    ``ids`` maps slots to general ids (default ``1..capacity``, the
    ascending spawn order of ba.py:344-351 that ``make_state`` /
    ``make_sweep_state`` use); the interactive backend passes its roster
    ids so REPL scenarios address the same generals ``g-kill`` would.
    Unknown ids and out-of-range instances raise here — eagerly, on
    host — rather than silently masking to nothing on device.
    """
    validate(spec)
    block = empty_block(spec.rounds, batch, capacity)
    if ids is None:
        ids = np.arange(1, capacity + 1)
    else:
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.shape[0] != capacity:
            raise ScenarioError(
                f"ids has {ids.shape[0]} entries for capacity {capacity}"
            )
    slot_of = {}
    for slot, gid in enumerate(ids.tolist()):
        if gid > 0 and gid not in slot_of:  # 0 = unoccupied padding slot
            slot_of[gid] = slot

    for ev in spec.events:
        try:
            slots = [slot_of[gid] for gid in ev.ids]
        except KeyError as e:
            raise ScenarioError(
                f"{ev.kind} event names general id {e.args[0]} which is "
                f"not in the roster (ids {sorted(slot_of)})"
            ) from None
        if ev.instances is None:
            rows = np.arange(batch)
        else:
            rows = np.asarray(ev.instances, np.int64)
            if (rows >= batch).any():
                raise ScenarioError(
                    f"{ev.kind} event instance {int(rows.max())} outside "
                    f"batch {batch}"
                )
        cells = np.ix_(rows, np.asarray(slots, np.int64))
        if ev.kind == "kill":
            block.kill[ev.round][cells] = True
        elif ev.kind == "revive":
            block.revive[ev.round][cells] = True
        elif ev.kind == "set_faulty":
            block.set_faulty[ev.round][cells] = 1 if ev.value else 0
        else:  # set_strategy (validate() rejected everything else)
            block.set_strategy[ev.round][cells] = strategy_id(ev.value)
    return block
