"""ba-lint driver: file discovery, the two-phase run, output, exit code.

``python -m ba_tpu.analysis [paths] [--format human|json]
[--rules ...] [--sarif OUT.sarif]``

Phase one parses every ``.py`` under the given paths into
:class:`~ba_tpu.analysis.project.ModuleInfo`; phase two builds the
:class:`~ba_tpu.analysis.project.Project` (import graph + donation
registry) and runs every selected rule over every module.  Findings are
filtered through the per-file suppression index, sorted by location,
and rendered human-readable or as one JSON object (schema below, which
``scripts/ci.sh`` validates the way it validates the metrics JSONL).

Exit code: 1 if any unsuppressed ERROR-severity finding (including
syntax errors, reported as ``BA900``), else 0.  Warnings print and
count but never fail the run.

JSON schema (version 1)::

    {"version": 1, "tool": "ba-lint", "files_scanned": N,
     "rules": ["BA101", ...],
     "findings":   [{"code", "severity", "path", "line", "col",
                     "message"}, ...],
     "suppressed": [...same shape...],
     "counts": {"error": E, "warning": W, "suppressed": S},
     "exit": 0 | 1}
"""

from __future__ import annotations

import argparse
import json
import os

from ba_tpu.analysis.base import ERROR, Finding, all_rules
from ba_tpu.analysis.project import ModuleInfo, Project

JSON_SCHEMA_VERSION = 1
PARSE_ERROR_CODE = "BA900"

_SKIP_DIRS = {"__pycache__", ".git"}


def discover(paths, exclude=()) -> list:
    """``(abs_path, display_path)`` for every ``.py`` under ``paths``.

    ``exclude`` entries are file-or-directory path prefixes (resolved
    absolute, so ``tests/fixtures/ba_lint`` works from the repo root):
    anything at or under one is skipped — the CI spelling for "lint
    ``tests/`` but not the deliberately-violating lint fixtures".
    """
    out = []
    seen = set()
    excluded = tuple(os.path.abspath(e) for e in exclude)

    def is_excluded(ap: str) -> bool:
        return any(
            ap == e or ap.startswith(e + os.sep) for e in excluded
        )

    def add(p: str) -> None:
        ap = os.path.abspath(p)
        if ap in seen or is_excluded(ap):
            return
        seen.add(ap)
        rel = os.path.relpath(ap)
        out.append((ap, rel if not rel.startswith("..") else ap))

    for path in paths:
        if os.path.isfile(path):
            add(path)
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no such file or directory: {path}")
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d
                for d in dirs
                if d not in _SKIP_DIRS
                and not d.startswith(".")
                and not is_excluded(os.path.abspath(os.path.join(root, d)))
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    add(os.path.join(root, f))
    return sorted(out, key=lambda t: t[1])


def run_paths(paths, rule_codes=None, exclude=()):
    """Analyze ``paths``; returns ``(findings, suppressed, files_scanned)``.

    ``findings``/``suppressed`` are location-sorted :class:`Finding`
    lists; ``rule_codes`` (e.g. ``{"BA101"}``) restricts the rule set;
    ``exclude`` prunes path prefixes from discovery (see
    :func:`discover`).
    """
    rules = [
        r
        for r in all_rules()
        if rule_codes is None or r.code in rule_codes
    ]
    modules = []
    findings = []
    for ap, disp in discover(paths, exclude):
        with open(ap, encoding="utf-8") as fh:
            source = fh.read()
        try:
            modules.append(ModuleInfo.parse(ap, disp, source))
        except SyntaxError as exc:
            findings.append(
                Finding(
                    code=PARSE_ERROR_CODE,
                    severity=ERROR,
                    path=disp,
                    line=exc.lineno or 1,
                    col=(exc.offset or 1) - 1,
                    message=f"syntax error: {exc.msg}",
                )
            )
    project = Project(modules)
    for mod in modules:
        for rule in rules:
            findings.extend(rule.check_module(mod, project))

    by_path = {m.display_path: m for m in modules}
    active, suppressed = [], []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressions.is_suppressed(
            f.code, f.line
        ):
            suppressed.append(f)
        else:
            active.append(f)
    key = lambda f: (f.path, f.line, f.col, f.code)  # noqa: E731
    return sorted(active, key=key), sorted(suppressed, key=key), len(
        modules
    )


def _to_json(active, suppressed, files, rules) -> dict:
    errors = sum(1 for f in active if f.severity == ERROR)
    warnings = len(active) - errors
    return {
        "version": JSON_SCHEMA_VERSION,
        "tool": "ba-lint",
        "files_scanned": files,
        "rules": [r.code for r in rules],
        "findings": [f.to_json() for f in active],
        "suppressed": [f.to_json() for f in suppressed],
        "counts": {
            "error": errors,
            "warning": warnings,
            "suppressed": len(suppressed),
        },
        "exit": 1 if errors else 0,
    }


def _to_sarif(active, suppressed, rules) -> dict:
    """SARIF 2.1.0 (the static-analysis interchange format CI code
    scanners ingest): one run, one ``result`` per finding — suppressed
    findings are carried too, marked ``suppressions: [{"kind":
    "inSource"}]``, so a waiver shows up in review instead of
    vanishing.  ``level`` maps error→error, warning→warning.  The
    rules array covers every SELECTED rule plus any extra code present
    in the results (BA900 parse errors have no Rule object)."""
    descriptors = {
        r.code: {
            "id": r.code,
            "name": r.name,
            "defaultConfiguration": {
                "level": "error" if r.severity == ERROR else "warning"
            },
        }
        for r in rules
    }
    for f in list(active) + list(suppressed):
        descriptors.setdefault(
            f.code,
            {
                "id": f.code,
                "name": "parse-error"
                if f.code == PARSE_ERROR_CODE
                else f.code,
                "defaultConfiguration": {"level": "error"},
            },
        )

    def result(f: Finding, in_source_suppressed: bool) -> dict:
        out = {
            "ruleId": f.code,
            "level": "error" if f.severity == ERROR else "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/")
                        },
                        "region": {
                            "startLine": f.line,
                            # SARIF columns are 1-based; Finding.col
                            # is the 0-based ast col_offset.
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if in_source_suppressed:
            out["suppressions"] = [{"kind": "inSource"}]
        return out

    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ba-lint",
                        "informationUri": (
                            "https://github.com/ba-tpu/ba-tpu"
                        ),
                        "rules": [
                            descriptors[c] for c in sorted(descriptors)
                        ],
                    }
                },
                "results": [result(f, False) for f in active]
                + [result(f, True) for f in suppressed],
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ba_tpu.analysis",
        description=(
            "ba-lint: AST-based JAX-safety analyzer (host-sync, "
            "donation, key-linearity, obs-purity; zero deps, never "
            "imports jax)"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["."],
        help="files or directories to analyze (default: .)",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json"),
        default="human",
        help="output format (json: one schema-versioned object)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule codes to run (default: all)",
    )
    parser.add_argument(
        "--exclude",
        action="append",
        default=[],
        metavar="PATH",
        help="path prefix to skip (repeatable) — e.g. "
             "--exclude tests/fixtures/ba_lint keeps the deliberately-"
             "violating fixtures out of a tests/ lint run",
    )
    parser.add_argument(
        "--sarif",
        metavar="OUT.sarif",
        help="ALSO write findings as SARIF 2.1.0 to this path "
             "(composes with either --format; suppressed findings "
             "are included, marked suppressions=inSource)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.code}  {r.severity:7s}  {r.name}")
        return 0
    selected = None
    if args.rules:
        selected = {c.strip().upper() for c in args.rules.split(",")}
        known = {r.code for r in rules}
        bad = selected - known
        if bad:
            parser.error(
                f"unknown rule code(s): {', '.join(sorted(bad))} "
                f"(known: {', '.join(sorted(known))})"
            )
    try:
        active, suppressed, files = run_paths(
            args.paths, selected, exclude=args.exclude
        )
    except FileNotFoundError as exc:
        parser.error(str(exc))

    run_rules = [r for r in rules if selected is None or r.code in selected]
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(
                _to_sarif(active, suppressed, run_rules), fh, indent=2
            )
            fh.write("\n")
    if args.format == "json":
        print(json.dumps(_to_json(active, suppressed, files, run_rules)))
    else:
        for f in active:
            print(f.render())
        errors = sum(1 for f in active if f.severity == ERROR)
        warnings = len(active) - errors
        print(
            f"ba-lint: {errors} error(s), {warnings} warning(s)"
            f" ({len(suppressed)} suppressed) across {files} file(s)"
        )
    return 1 if any(f.severity == ERROR for f in active) else 0
