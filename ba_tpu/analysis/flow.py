"""A small intra-function dataflow walker shared by BA201 and BA202.

Both rules are *must*-analyses over local names ("this name is
definitely donated/consumed here"), so they share one statement-ordered
event walk with false-positive-safe branch handling:

- Within a simple statement, events fire in evaluation order: the
  right-hand side of an assignment before its targets (so
  ``state = f(state)`` reads the old binding, then clears it), loads of
  a call's arguments before the call itself.
- ``if``/``try`` branches run on copies and merge by INTERSECTION — a
  fact must hold on every path to survive the join, so a donate inside
  one branch never poisons the fall-through path.
- Loop bodies run TWICE: the second pass re-enters with the first
  pass's exit state, which is what catches loop-carried bugs (donate at
  the bottom of the body, read at the top of the next iteration) without
  a fixpoint engine.  Rules de-duplicate findings by location, so the
  double walk never double-reports.
- ``lambda`` bodies and nested ``def``/``class`` are opaque: they
  execute later (or never), so their reads prove nothing about the
  enclosing function's statement order.  Nested functions are analyzed
  as their own scopes by the rule driver.

A rule implements :class:`FlowHandler` (``on_load`` / ``on_store`` /
``on_call``) over its own :class:`FlowState` subclass (``copy`` /
``merge``).
"""

from __future__ import annotations

import ast


class FlowState:
    """Rule-owned mutable state threaded through the walk."""

    def copy(self) -> "FlowState":
        raise NotImplementedError

    def merge(self, others: list) -> None:
        """Intersection-join ``others`` (branch exit states) into self."""
        raise NotImplementedError


class FlowHandler:
    """Event callbacks; rules collect findings on themselves."""

    def on_load(self, name_node: ast.Name, state: FlowState) -> None:
        pass

    def on_store(self, name: str, state: FlowState) -> None:
        pass

    def on_call(self, call: ast.Call, state: FlowState) -> None:
        pass


def walk_expr(node, handler: FlowHandler, state: FlowState) -> None:
    if node is None or isinstance(node, ast.Lambda):
        return
    if isinstance(node, ast.Call):
        walk_expr(node.func, handler, state)
        for a in node.args:
            walk_expr(a, handler, state)
        for kw in node.keywords:
            walk_expr(kw.value, handler, state)
        handler.on_call(node, state)
        return
    if isinstance(node, ast.Name):
        if isinstance(node.ctx, ast.Load):
            handler.on_load(node, state)
        else:
            handler.on_store(node.id, state)
        return
    if isinstance(node, ast.IfExp):
        walk_expr(node.test, handler, state)
        branches = []
        for side in (node.body, node.orelse):
            s = state.copy()
            walk_expr(side, handler, s)
            branches.append(s)
        state.merge(branches)
        return
    if isinstance(node, ast.BoolOp):
        # Short-circuit: operands after the first may never evaluate,
        # so each runs on a copy and joins by intersection — a donate
        # behind `flag and f(state)` must not poison the fall-through.
        walk_expr(node.values[0], handler, state)
        branches = [state.copy()]
        for value in node.values[1:]:
            s = state.copy()
            walk_expr(value, handler, s)
            branches.append(s)
        state.merge(branches)
        return
    for child in ast.iter_child_nodes(node):
        walk_expr(child, handler, state)


_MATCH = getattr(ast, "Match", None)


def _walk_pattern(pattern, handler: FlowHandler, state: FlowState) -> None:
    """Events for a match-case pattern: value/key expressions load,
    capture names (``case x``, ``case [*xs]``, ``case {**rest}``)
    store."""
    for node in ast.walk(pattern):
        if isinstance(node, ast.MatchValue):
            walk_expr(node.value, handler, state)
        elif isinstance(node, ast.MatchAs) and node.name:
            handler.on_store(node.name, state)
        elif isinstance(node, ast.MatchStar) and node.name:
            handler.on_store(node.name, state)
        elif isinstance(node, ast.MatchMapping):
            for key in node.keys:
                walk_expr(key, handler, state)
            if node.rest:
                handler.on_store(node.rest, state)
        elif isinstance(node, ast.MatchClass):
            walk_expr(node.cls, handler, state)


def _walk_loop(iter_events, body, orelse, handler, state) -> None:
    """Shared For/While shape: 0-iteration path merges with the
    double-walked body path."""
    zero_iter = state.copy()
    looped = state.copy()
    for _ in range(2):
        iter_events(looped)
        walk_body(body, handler, looped)
    # merge() computes the intersection of the given branch states, so
    # the 0-iteration path rides along explicitly.
    state.merge([zero_iter, looped])
    walk_body(orelse, handler, state)


def walk_stmt(stmt, handler: FlowHandler, state: FlowState) -> None:
    if isinstance(
        stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        for dec in stmt.decorator_list:
            walk_expr(dec, handler, state)
        # The def itself binds a name; its body is a separate scope.
        handler.on_store(stmt.name, state)
        return
    if isinstance(stmt, ast.If):
        walk_expr(stmt.test, handler, state)
        branches = []
        for body in (stmt.body, stmt.orelse):
            s = state.copy()
            walk_body(body, handler, s)
            branches.append(s)
        state.merge(branches)
        return
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        walk_expr(stmt.iter, handler, state)

        def events(s, _t=stmt.target):
            walk_expr(_t, handler, s)

        _walk_loop(events, stmt.body, stmt.orelse, handler, state)
        return
    if isinstance(stmt, ast.While):

        def events(s, _t=stmt.test):
            walk_expr(_t, handler, s)

        _walk_loop(events, stmt.body, stmt.orelse, handler, state)
        return
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            walk_expr(item.context_expr, handler, state)
            walk_expr(item.optional_vars, handler, state)
        walk_body(stmt.body, handler, state)
        return
    if _MATCH is not None and isinstance(stmt, _MATCH):
        walk_expr(stmt.subject, handler, state)
        # Arms are branches like `if`/`elif`: each runs on a copy
        # (capture patterns bind names, guards and bodies see them),
        # and the join keeps a no-arm-taken copy — `match` need not be
        # exhaustive.
        branches = [state.copy()]
        for case in stmt.cases:
            s = state.copy()
            _walk_pattern(case.pattern, handler, s)
            walk_expr(case.guard, handler, s)
            walk_body(case.body, handler, s)
            branches.append(s)
        state.merge(branches)
        return
    if isinstance(stmt, ast.Try):
        normal = state.copy()
        walk_body(stmt.body, handler, normal)
        walk_body(stmt.orelse, handler, normal)
        branches = [normal]
        for h in stmt.handlers:
            s = state.copy()
            if h.name:
                handler.on_store(h.name, s)
            walk_body(h.body, handler, s)
            branches.append(s)
        state.merge(branches)
        walk_body(stmt.finalbody, handler, state)
        return
    if isinstance(stmt, ast.Assign):
        walk_expr(stmt.value, handler, state)
        for t in stmt.targets:
            walk_expr(t, handler, state)
        return
    if isinstance(stmt, ast.AnnAssign):
        walk_expr(stmt.value, handler, state)
        walk_expr(stmt.target, handler, state)
        return
    if isinstance(stmt, ast.AugAssign):
        if isinstance(stmt.target, ast.Name):
            handler.on_load(stmt.target, state)
            walk_expr(stmt.value, handler, state)
            handler.on_store(stmt.target.id, state)
        else:
            walk_expr(stmt.target, handler, state)
            walk_expr(stmt.value, handler, state)
        return
    if isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                handler.on_store(t.id, state)
            else:
                walk_expr(t, handler, state)
        return
    # Expr / Return / Raise / Assert / Global / Import / pass ...: walk
    # whatever expressions hang off the node, in field order.
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.expr):
            walk_expr(child, handler, state)


def walk_body(stmts, handler: FlowHandler, state: FlowState) -> None:
    for stmt in stmts:
        walk_stmt(stmt, handler, state)


def function_scopes(tree: ast.Module):
    """Every analyzable scope: the module body plus each (nested) def.

    Yields ``(scope_node, body)``; rules run their flow walk once per
    scope with fresh state, which is how lambda/def opacity in the walk
    stays sound — inner defs get their own pass.
    """
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body
