"""BA6xx contracts: record schemas, metric naming, env registry
(ISSUE 18).

All three rules consume the declared registries in
``ba_tpu.analysis.contracts`` — the SAME tables
``scripts/check_metrics_schema.py`` validates real JSONL streams
against, so the static and dynamic checkers cannot drift.

- **BA601 record-schema**: every statically-recognizable emit site — a
  dict literal carrying a constant ``"event"`` key that either spells
  ``"v"`` literally or is passed directly to an ``.emit(...)`` call —
  is checked against :data:`contracts.RECORD_FAMILIES`: unknown
  families flag (a typo'd event name silently creates an orphan stream
  no dashboard reads), and sites without a ``**spread`` must spell
  every required key literally.
- **BA602 metric-naming**: the ``serve_`` prefix and ``_per_shard``
  suffix rules, applied at every ``counter``/``gauge``/``histogram``
  construction site with a literal name — the static mirror of the
  runtime assertions in ``obs/registry.MetricsRegistry._get`` (which
  stay, as defense-in-depth; this rule fails the commit before the
  assert can fail a run).
- **BA603 env-registry**: every ``BA_TPU_*`` environment read
  (``os.environ.get``/``os.getenv``/subscript/``in os.environ``,
  including reads through module-level name constants like
  ``WARM_ENV = "BA_TPU_WARM"``, alias-resolved cross-module) is diffed
  against the README env table (:data:`contracts.ENV_DOCUMENTED`):
  used-but-undocumented flags at the read site; documented-but-unused
  flags at the ``ba_tpu`` package root — but ONLY when the analyzed
  set spans the whole repo (``ba_tpu/ examples/ bench.py tests/
  scripts/``), so partial runs never false-positive on rows whose
  reader lives outside the set.
"""

from __future__ import annotations

import ast

from ba_tpu.analysis import contracts
from ba_tpu.analysis.base import Rule, register

ENV_PREFIX = "BA_TPU_"

# Reads (flaggable when undocumented, count as usage).
_ENV_READ_FUNCS = {
    "os.environ.get",
    "os.getenv",
    "os.environ.setdefault",
}
# Writes/clears (count as usage only — tests legitimately set and pop
# synthetic names; documentation governs what code READS).
_ENV_WRITE_FUNCS = {"os.environ.pop"}
_MONKEYPATCH_FUNCS = {"setenv", "delenv"}


def _dict_literal_keys(node: ast.Dict):
    keys = set()
    spread = False
    for k in node.keys:
        if k is None:
            spread = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
    return keys, spread


def _event_value(node: ast.Dict):
    for k, v in zip(node.keys, node.values):
        if (
            isinstance(k, ast.Constant)
            and k.value == "event"
            and isinstance(v, ast.Constant)
            and isinstance(v.value, str)
        ):
            return v.value
    return None


@register
class RecordSchema(Rule):
    code = "BA601"
    name = "record-schema"
    severity = "error"

    def check_module(self, mod, project):
        emit_args = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                is_emit = (
                    isinstance(fn, ast.Attribute) and fn.attr == "emit"
                ) or (isinstance(fn, ast.Name) and fn.id == "emit")
                if is_emit:
                    for arg in node.args:
                        if isinstance(arg, ast.Dict):
                            emit_args.add(id(arg))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys, spread = _dict_literal_keys(node)
            if "event" not in keys:
                continue
            if "v" not in keys and id(node) not in emit_args:
                # A dict that names an event but neither versions
                # itself nor flows into an emit() is a payload/filter,
                # not an emit site.
                continue
            event = _event_value(node)
            if event is None:
                continue  # dynamic event name; not statically checkable
            spec = contracts.RECORD_FAMILIES.get(event)
            if spec is None:
                yield self.finding(
                    mod,
                    node,
                    f"unknown record family {event!r} — not in "
                    f"analysis/contracts.RECORD_FAMILIES; a typo'd "
                    f"event name creates an orphan JSONL stream no "
                    f"consumer reads (register the family or fix the "
                    f"name)",
                )
                continue
            if spread:
                continue  # keys may arrive through the **spread
            missing = [k for k in spec["required"] if k not in keys]
            if missing:
                yield self.finding(
                    mod,
                    node,
                    f"record family {event!r} emit site missing "
                    f"required key(s) {', '.join(sorted(missing))} — "
                    f"contracts.RECORD_FAMILIES declares them; "
                    f"consumers (scripts/check_metrics_schema.py, "
                    f"dashboards) key on every one",
                )


@register
class MetricNaming(Rule):
    code = "BA602"
    name = "metric-naming"
    severity = "error"

    _CTORS = {"counter", "gauge", "histogram"}

    def check_module(self, mod, project):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute) and fn.attr in self._CTORS
            ):
                continue
            if not node.args:
                continue
            name_arg = node.args[0]
            if not (
                isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
            ):
                continue
            reason = contracts.metric_name_violation(name_arg.value)
            if reason:
                yield self.finding(mod, name_arg, reason)


def _env_name(expr, mod, project):
    """Resolve an env-name expression to its literal value: a string
    constant, a module-level name constant (``WARM_ENV``), or an
    alias-resolved cross-module attribute (``aotcache.CACHE_ENV``)."""
    if isinstance(expr, ast.Constant):
        return expr.value if isinstance(expr.value, str) else None
    table = project.env_constants()
    dotted = mod.imports.resolve(expr)
    if dotted and dotted in table:
        return table[dotted]
    if isinstance(expr, ast.Name):
        return table.get(f"{mod.modname}.{expr.id}")
    return None


def _env_accesses(mod, project):
    """Yield ``(name, node, is_read)`` for every resolvable ``BA_TPU_*``
    environment access in the module."""
    for node in ast.walk(mod.tree):
        name_expr = None
        is_read = True
        if isinstance(node, ast.Call):
            fn = mod.imports.resolve(node.func)
            if fn in _ENV_READ_FUNCS and node.args:
                name_expr = node.args[0]
            elif fn in _ENV_WRITE_FUNCS and node.args:
                name_expr, is_read = node.args[0], False
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MONKEYPATCH_FUNCS
                and node.args
            ):
                name_expr, is_read = node.args[0], False
        elif isinstance(node, ast.Subscript):
            if mod.imports.resolve(node.value) == "os.environ":
                name_expr = node.slice
                is_read = isinstance(node.ctx, ast.Load)
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            for i, op in enumerate(node.ops):
                if isinstance(op, (ast.In, ast.NotIn)) and (
                    mod.imports.resolve(operands[i + 1]) == "os.environ"
                ):
                    name = _env_name(operands[i], mod, project)
                    if name and name.startswith(ENV_PREFIX):
                        yield name, node, True
            continue
        if name_expr is None:
            continue
        name = _env_name(name_expr, mod, project)
        if name and name.startswith(ENV_PREFIX):
            yield name, node, is_read


def _project_env_usage(project):
    used = project.__dict__.get("_ba603_usage")
    if used is None:
        used = set()
        for m in project.modules.values():
            for name, _node, _is_read in _env_accesses(m, project):
                used.add(name)
        project.__dict__["_ba603_usage"] = used
    return used


# The analyzed set must span all of these before documented-but-unused
# may fire — a partial run (the acceptance command omits examples/ and
# bench.py) cannot see every reader, so absence is not evidence there.
_FULL_SET_PREFIXES = ("ba_tpu/", "tests/", "scripts/", "examples/")
_FULL_SET_FILES = ("bench.py",)


def _spans_whole_repo(project):
    paths = [m.display_path for m in project.modules.values()]
    for prefix in _FULL_SET_PREFIXES:
        if not any(p.startswith(prefix) for p in paths):
            return False
    for f in _FULL_SET_FILES:
        if not any(p == f or p.endswith("/" + f) for p in paths):
            return False
    return True


@register
class EnvRegistry(Rule):
    code = "BA603"
    name = "env-registry"
    severity = "error"

    def check_module(self, mod, project):
        for name, node, is_read in _env_accesses(mod, project):
            if is_read and not contracts.env_documented(name):
                yield self.finding(
                    mod,
                    node,
                    f"environment variable {name!r} is read here but "
                    f"has no README 'Environment knobs' row — add the "
                    f"row AND the analysis/contracts.ENV_DOCUMENTED "
                    f"entry (tests pin the two equal)",
                )
        # Reverse direction, anchored once at the package root and only
        # when the analyzed set can actually see every reader.
        if mod.modname != "ba_tpu":
            return
        if not _spans_whole_repo(project):
            return
        used = _project_env_usage(project)
        for name in sorted(contracts.ENV_DOCUMENTED):
            if name not in used:
                yield self.finding(
                    mod,
                    mod.tree,
                    f"documented environment variable {name!r} is "
                    f"never read anywhere in the analyzed tree — "
                    f"drop the stale README row (and its "
                    f"contracts.ENV_DOCUMENTED entry) or wire the "
                    f"knob back up",
                )
        for prefix in contracts.ENV_WILDCARDS:
            if not any(u.startswith(prefix) for u in used):
                yield self.finding(
                    mod,
                    mod.tree,
                    f"documented wildcard row {prefix + '*'!r} "
                    f"matches no read anywhere in the analyzed tree",
                )
