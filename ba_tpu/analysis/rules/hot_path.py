"""BA101 host-sync-in-hot-path and BA102 host-key-split-in-pipeline.

The pipelined sweep engine's entire win (BENCH_pipeline_r6.json: 2.72x
over the blocking driver) is that the host NEVER synchronizes inside
the round loop — the only blocking operation is the depth-delayed
retire, and keys derive on device from the ``KeySchedule`` counter.
These two rules are the semantic versions of the PR 1 text greps in
``scripts/ci.sh`` (see the mapping comment there):

- **BA101** bans host-sync idioms in the round-loop modules:
  ``block_until_ready`` anywhere under ``ba_tpu.parallel``; host-numpy
  conversions (``np.asarray``/``np.array``, ALIAS-RESOLVED — ``import
  numpy as jnp_like`` is still numpy, ``jnp.asarray`` is still
  device-side), ``.item()``/``.tolist()`` drains, and
  ``float()``/``int()`` coercions of jax-derived values, each scoped to
  the two round-loop modules (``pipeline``/``sweep`` — ``mesh``/
  ``multihost`` build host-side topology and are the package's
  sanctioned numpy users).
- **BA102** keeps the host out of PRNG derivation in ``pipeline.py``:
  any ``jax.random.split`` (the round keys come from the on-device
  schedule; a split reappearing means the host is back in the per-round
  loop), and ``jax.random.fold_in`` inside a host ``for``/``while``
  body (the sanctioned ``fold_in`` lives in ``round_keys``, trace-time
  under jit, outside any host loop).
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import Rule, register

# ISSUE 13 extended the hot tree beyond parallel/: the Pallas scenario
# megastep (ops/scenario_step.py) IS the dispatch path when the kernel
# engine is selected — its wrappers sit exactly where the XLA megasteps
# do, so the same no-host-sync discipline applies (the other ops/
# kernels are crypto-side and stay out).  ISSUE 15 added the adversary
# search loop (search/loop.py): its generation loop drives the
# coalesced engine's dispatch stream, and a host sync there would
# serialize population evaluation exactly like one in the engine.
# ISSUE 16 added the host-crypto pool (crypto/pool.py): SignAheadLane
# calls it from the engine's overlap slot, so a device sync there
# blocks the dispatch loop exactly like one in the lane — and the
# module is jax-free by contract anyway, so ANY jax touch is a bug.
HOT_TREES = (
    "ba_tpu.parallel.", "ba_tpu.ops.scenario_step", "ba_tpu.search.loop",
    "ba_tpu.crypto.pool",
)
# The round-loop modules: the ones whose steady-state statements run
# once per round / per dispatch.  ISSUE 8 added the mesh scan core
# (parallel/shard.py — the shard_map megasteps and the retire-time
# host reduction both sit on the dispatch path); mesh/multihost stay
# out as the package's sanctioned host-topology numpy users.  ISSUE 13
# added the kernel megastep module (trace-time numpy map construction
# is fine — the banned idioms are the conversion/drain calls).
HOT_CONVERSION_MODULES = {
    "ba_tpu.parallel.pipeline",
    "ba_tpu.parallel.sweep",
    "ba_tpu.parallel.shard",
    "ba_tpu.ops.scenario_step",
    # ISSUE 15: the search loop scores host rows the engine's retire
    # fetches already brought back — a conversion/drain call there
    # means a device value leaked into the scoring path.
    "ba_tpu.search.loop",
}
PIPELINE_MODULE = "ba_tpu.parallel.pipeline"

_NP_CONVERSIONS = {
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
}
_DRAIN_METHODS = {"item", "tolist"}


def _loop_node_ids(tree: ast.AST) -> set:
    """ids of every node lexically inside a host ``for``/``while`` body."""
    inside: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            for sub in node.body + node.orelse:
                for inner in ast.walk(sub):
                    inside.add(id(inner))
    return inside


@register
class HostSyncInHotPath(Rule):
    code = "BA101"
    name = "host-sync-in-hot-path"
    severity = "error"

    def check_module(self, mod, project):
        if not mod.modname.startswith(HOT_TREES):
            return
        seen: set = set()

        def hit(node, msg):
            key = (node.lineno, node.col_offset)
            if key not in seen:
                seen.add(key)
                yield self.finding(mod, node, msg)

        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "block_until_ready"
            ):
                yield from hit(
                    node,
                    "block_until_ready in a parallel round-loop module: "
                    "any host sync serializes host and device — the "
                    "engine's only sync is the depth-delayed retire",
                )
        for node, dotted in mod.imports.resolved_refs(mod.tree):
            if dotted == "jax.block_until_ready":
                yield from hit(
                    node,
                    "block_until_ready in a parallel round-loop module: "
                    "any host sync serializes host and device — the "
                    "engine's only sync is the depth-delayed retire",
                )

        if mod.modname not in HOT_CONVERSION_MODULES:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.imports.resolve(node.func)
            if dotted in _NP_CONVERSIONS:
                yield from hit(
                    node,
                    f"host numpy conversion ({dotted}) on the round path "
                    "drains the dispatch queue through the host "
                    "(device-side jnp is fine; multihost.put_global is "
                    "the sanctioned np user)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _DRAIN_METHODS
            ):
                yield from hit(
                    node,
                    f".{node.func.attr}() in a round-loop module forces a "
                    "device->host transfer per call",
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
                and node.func.id not in mod.imports.bindings
                and any(
                    d == "jax" or d.startswith(("jax.", "jax.numpy"))
                    for a in node.args
                    for _, d in mod.imports.resolved_refs(a)
                )
            ):
                yield from hit(
                    node,
                    f"{node.func.id}() of a jax value in a round-loop "
                    "module blocks on the device result",
                )


@register
class HostKeySplitInPipeline(Rule):
    code = "BA102"
    name = "host-key-split-in-pipeline"
    severity = "error"

    def check_module(self, mod, project):
        if mod.modname != PIPELINE_MODULE:
            return
        in_loop = _loop_node_ids(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.imports.resolve(node.func)
            if dotted == "jax.random.split":
                yield self.finding(
                    mod,
                    node,
                    "host key split in pipeline.py — round keys derive ON "
                    "DEVICE from the KeySchedule counter "
                    "(fold_in(fold_in(base, r), i) inside the compiled "
                    "megastep); a host split puts the host back in the "
                    "per-round loop",
                )
            elif dotted == "jax.random.fold_in" and id(node) in in_loop:
                yield self.finding(
                    mod,
                    node,
                    "host-loop fold_in in pipeline.py — per-round key "
                    "derivation belongs on device (round_keys, under "
                    "jit), not in the host dispatch loop",
                )
