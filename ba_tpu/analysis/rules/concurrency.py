"""BA5xx concurrency: race/lock-discipline rules for the threaded host
tier (ISSUE 18).

The serving stack grew real threads — the serve dispatcher loop,
watchdog ``threading.Timer``\\ s, the warmup daemon, the health
sampler's deliberately lock-free reads — and the invariants that keep
them correct were enforced only by comments.  Four rules make them
machine-checked:

- **BA501 unsynchronized-shared-mutation**: an instance attribute (or
  ``global``) written from more than one *thread context* —
  each discovered thread entry point is one context, the ordinary
  caller-facing API collectively another — must have a COMMON lock
  across every write (lock regions inferred from ``with <lock>``
  blocks, where a lock is anything assigned from
  ``threading.Lock/RLock/Condition``, alias-resolved).  Thread entry
  points are discovered from ``threading.Thread(target=...)``,
  ``threading.Timer(..., callback)`` and the
  ``# ba-lint: thread-entry`` annotation (for indirect dispatch the
  analyzer cannot see).  Writes in ``__init__`` are pre-thread and
  exempt.  Deliberate GIL-atomic single-writer patterns carry named
  inline suppressions.
- **BA502 lock-free-read discipline**: a module declaring
  ``# ba-lint: lockfree`` (obs/health.py's sampler) may only perform
  single-opcode GIL-atomic reads of shared state: no read-modify-write
  on attributes/subscripts, no iteration over non-local containers, no
  lock acquisition at all.
- **BA503 lock-order-cycle**: the project-wide acquired-while-held
  graph (nested ``with`` regions plus one-hop ``self._m()`` calls made
  under a lock) must be acyclic; a cycle is a potential deadlock the
  moment two threads interleave.  Re-acquiring a NON-reentrant
  ``threading.Lock`` already held is reported as a self-cycle.
- **BA504 leaked-timer/daemon-lifecycle**: a ``threading.Timer`` armed
  in a function must be cancelled on ALL exits (a ``try/finally``
  cancel, or — when stored on ``self`` — a cancel somewhere in the
  owning class); a NON-daemon thread stored on ``self`` must be
  ``join()``\\ ed by the class (``stop()``/``close()``), else process
  exit hangs on it.

All pure-ast, zero-dep, never imports jax — the BA101 constraints.
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import Rule, register

LOCK_CTORS = {
    "threading.Lock": False,  # value: reentrant?
    "threading.RLock": True,
    "threading.Condition": True,  # wraps an RLock by default
}
THREAD_CTOR = "threading.Thread"
TIMER_CTOR = "threading.Timer"


def _func_defs(body):
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _self_attr(node):
    """``self.X`` -> ``"X"`` (else None)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _call_thread_targets(call: ast.Call, imports):
    """(kind, callback-ast) for threading.Thread/Timer constructions.

    kind is "thread" or "timer"; callback is the ``target=`` /
    ``function`` argument's AST (None when absent).
    """
    fn = imports.resolve(call.func)
    if fn == THREAD_CTOR:
        for kw in call.keywords:
            if kw.arg == "target":
                return "thread", kw.value
        return "thread", None
    if fn == TIMER_CTOR:
        for kw in call.keywords:
            if kw.arg == "function":
                return "timer", kw.value
        if len(call.args) >= 2:
            return "timer", call.args[1]
        return "timer", None
    return None, None


def _own_nodes(scope):
    """All AST nodes of ``scope`` EXCLUDING subtrees of nested
    function/class definitions — those are separate scopes, visited
    when the walk reaches them as scopes of their own (without this a
    violation inside a closure would be reported once per enclosing
    def)."""
    nested = set()
    for f in ast.walk(scope):
        if f is scope:
            continue
        if isinstance(
            f, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            for sub in ast.walk(f):
                nested.add(id(sub))
    for node in ast.walk(scope):
        if id(node) not in nested:
            yield node


def _kw_daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            )
    return False


class _FuncFacts:
    """Guard-aware facts for ONE function body: attribute/global writes,
    ``self._m()`` calls, and lock acquisitions, each with the set of
    lock guards lexically active at that point.  Nested defs/lambdas
    are opaque (their own scopes)."""

    def __init__(self, func, lock_ids):
        # lock_ids: {guard-key: reentrant?} — "self.X" for instance
        # locks of the enclosing class, bare names for module locks.
        self.writes = []  # (attr, node, frozenset(guards)) for self.X
        self.global_writes = []  # (name, node, frozenset(guards))
        self.self_calls = []  # (method, node, frozenset(guards))
        self.acquires = []  # (guard-key, node, frozenset(held))
        self._locks = lock_ids
        self._globals = {
            n
            for stmt in ast.walk(func)
            if isinstance(stmt, ast.Global)
            for n in stmt.names
        }
        self._walk(func.body, frozenset())

    def _guard_key(self, expr):
        attr = _self_attr(expr)
        if attr is not None:
            key = f"self.{attr}"
            return key if key in self._locks else None
        if isinstance(expr, ast.Name) and expr.id in self._locks:
            return expr.id
        return None

    def _record_targets(self, targets, node, guards):
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
                continue
            if isinstance(t, ast.Starred):
                stack.append(t.value)
                continue
            attr = _self_attr(t)
            if attr is not None:
                self.writes.append((attr, node, guards))
            elif isinstance(t, ast.Name) and t.id in self._globals:
                self.global_writes.append((t.id, node, guards))

    def _walk(self, body, guards):
        for node in body:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue  # opaque nested scope
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                held = set(guards)
                for item in node.items:
                    key = self._guard_key(item.context_expr)
                    if key is not None:
                        self.acquires.append(
                            (key, item.context_expr, frozenset(held))
                        )
                        held.add(key)
                        acquired.append(key)
                self._walk(node.body, frozenset(held))
                continue
            if isinstance(node, ast.Assign):
                self._record_targets(node.targets, node, guards)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None or isinstance(
                    node, ast.AugAssign
                ):
                    self._record_targets([node.target], node, guards)
            # self._m(...) calls (for entry-closure and BA503 one-hop).
            self._scan_calls(node, guards)
            for child_body_attr in ("body", "orelse", "finalbody"):
                child = getattr(node, child_body_attr, None)
                if child and not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self._walk(child, guards)
            if isinstance(node, ast.Try):
                for h in node.handlers:
                    self._walk(h.body, guards)
            if isinstance(node, ast.Match):
                for case in node.cases:
                    self._walk(case.body, guards)

    def _scan_calls(self, stmt, guards):
        # Only the statement's own expressions — child statement lists
        # are walked structurally (so their guard context is right).
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt) or isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    m = _self_attr(sub.func)
                    if m is not None:
                        self.self_calls.append((m, sub, guards))


class _ClassModel:
    """Per-class concurrency facts."""

    def __init__(self, cls: ast.ClassDef, mod):
        self.node = cls
        self.name = cls.name
        self.methods = {f.name: f for f in _func_defs(cls.body)}
        self.locks = {}  # "self.X" -> reentrant?
        for f in self.methods.values():
            for node in ast.walk(f):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    fn = mod.imports.resolve(node.value.func)
                    if fn in LOCK_CTORS:
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                self.locks[f"self.{attr}"] = LOCK_CTORS[
                                    fn
                                ]
        self.facts = {
            name: _FuncFacts(f, self.locks)
            for name, f in self.methods.items()
        }
        # Thread entry points: target=self._m / Timer callbacks named
        # anywhere in the class, plus `# ba-lint: thread-entry`
        # annotations on def lines.
        self.entries = set()
        for f in self.methods.values():
            for node in ast.walk(f):
                if isinstance(node, ast.Call):
                    kind, cb = _call_thread_targets(node, mod.imports)
                    if kind and cb is not None:
                        attr = _self_attr(cb)
                        if attr is not None and attr in self.methods:
                            self.entries.add(attr)
        for name, f in self.methods.items():
            if "thread-entry" in mod.suppressions.annotations.get(
                f.lineno, ()
            ):
                self.entries.add(name)

    def entry_closure(self, entry):
        """Methods reachable from ``entry`` through self-calls, with
        the guard set accumulated along the FIRST discovery path."""
        out = {}
        stack = [(entry, frozenset())]
        while stack:
            name, inherited = stack.pop()
            if name in out or name not in self.facts:
                continue
            out[name] = inherited
            for callee, _node, guards in self.facts[name].self_calls:
                if callee in self.methods and callee not in out:
                    stack.append((callee, inherited | guards))
        return out


def _module_classes(mod):
    memo_key = "_ba5xx_classes"
    cache = mod.__dict__.setdefault(memo_key, None)
    if cache is None:
        cache = [
            _ClassModel(node, mod)
            for node in mod.tree.body
            if isinstance(node, ast.ClassDef)
        ]
        mod.__dict__[memo_key] = cache
    return cache


@register
class UnsynchronizedSharedMutation(Rule):
    code = "BA501"
    name = "unsynchronized-shared-mutation"
    severity = "error"

    def check_module(self, mod, project):
        for cm in _module_classes(mod):
            if not cm.entries:
                continue
            # attr -> {context: [(node, guards)]}
            by_attr: dict = {}
            entry_side = set()
            for entry in sorted(cm.entries):
                closure = cm.entry_closure(entry)
                entry_side |= set(closure)
                for method, inherited in closure.items():
                    for attr, node, guards in cm.facts[method].writes:
                        by_attr.setdefault(attr, {}).setdefault(
                            f"thread:{entry}", []
                        ).append((node, guards | inherited))
            for method, facts in cm.facts.items():
                if method in entry_side or method in (
                    "__init__",
                    "__new__",
                    "__del__",
                ):
                    continue
                for attr, node, guards in facts.writes:
                    by_attr.setdefault(attr, {}).setdefault(
                        "caller", []
                    ).append((node, guards))
            for attr in sorted(by_attr):
                contexts = by_attr[attr]
                if len(contexts) < 2:
                    continue
                all_writes = [
                    w for ws in contexts.values() for w in ws
                ]
                common = frozenset.intersection(
                    *[g for _n, g in all_writes]
                )
                if common:
                    continue
                # Anchor on the first unguarded (or least-guarded)
                # write, deterministic by location.
                anchor = min(
                    all_writes, key=lambda w: (len(w[1]), w[0].lineno)
                )[0]
                ctx_names = ", ".join(sorted(contexts))
                yield self.finding(
                    mod,
                    anchor,
                    f"attribute 'self.{attr}' of {cm.name} is written "
                    f"from multiple thread contexts ({ctx_names}) "
                    f"without a common lock — hold one `with <lock>` "
                    f"region around every write, or suppress with a "
                    f"named waiver if the single-writer/GIL-atomic "
                    f"pattern is deliberate",
                )


@register
class LockFreeReadDiscipline(Rule):
    code = "BA502"
    name = "lockfree-read-discipline"
    severity = "error"

    def check_module(self, mod, project):
        if "lockfree" not in mod.suppressions.file_annotations:
            return
        lock_names = self._module_locks(mod)
        for scope in ast.walk(mod.tree):
            if not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            local = self._local_names(scope)
            for node in _own_nodes(scope):
                if isinstance(node, ast.AugAssign) and isinstance(
                    node.target, (ast.Attribute, ast.Subscript)
                ):
                    yield self.finding(
                        mod,
                        node,
                        "read-modify-write on shared state in a "
                        "`# ba-lint: lockfree` module — `+=` on an "
                        "attribute/item is two interleavable opcodes, "
                        "not a GIL-atomic read",
                    )
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if self._is_lock(item.context_expr, mod,
                                         lock_names):
                            yield self.finding(
                                mod,
                                item.context_expr,
                                "lock acquisition in a "
                                "`# ba-lint: lockfree` module — the "
                                "module declares the no-lock read "
                                "discipline (health sampling must add "
                                "ZERO synchronization); move locked "
                                "work out or drop the declaration",
                            )
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr == "acquire":
                    yield self.finding(
                        mod,
                        node,
                        "explicit .acquire() in a "
                        "`# ba-lint: lockfree` module",
                    )
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    yield from self._check_iter(
                        mod, node.iter, local
                    )
                elif isinstance(
                    node,
                    (ast.ListComp, ast.SetComp, ast.DictComp,
                     ast.GeneratorExp),
                ):
                    for gen in node.generators:
                        yield from self._check_iter(
                            mod, gen.iter, local
                        )

    @staticmethod
    def _module_locks(mod):
        names = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if mod.imports.resolve(node.value.func) in LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
                        attr = _self_attr(t)
                        if attr is not None:
                            names.add(attr)
        return names

    @staticmethod
    def _is_lock(expr, mod, lock_names):
        if isinstance(expr, ast.Name) and expr.id in lock_names:
            return True
        if isinstance(expr, ast.Attribute) and expr.attr in lock_names:
            return True
        return False

    @staticmethod
    def _local_names(scope):
        local = {a.arg for a in scope.args.args}
        local |= {a.arg for a in scope.args.posonlyargs}
        local |= {a.arg for a in scope.args.kwonlyargs}
        # `self`/`cls` receivers are NOT local state: iterating
        # `self.table` walks the shared object, exactly what the
        # lock-free discipline forbids.
        local -= {"self", "cls"}
        for extra in (scope.args.vararg, scope.args.kwarg):
            if extra is not None:
                local.add(extra.arg)
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            local.add(n.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    local.add(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        local.add(n.id)
            elif isinstance(node, ast.comprehension):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        local.add(n.id)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        for n in ast.walk(item.optional_vars):
                            if isinstance(n, ast.Name):
                                local.add(n.id)
        return local

    def _check_iter(self, mod, expr, local):
        root = self._iter_root(expr, local)
        if root is None:
            return
        yield self.finding(
            mod,
            expr,
            f"iteration over non-local container rooted at {root!r} "
            f"in a `# ba-lint: lockfree` module — a concurrent writer "
            f"mutating it mid-iteration raises RuntimeError or tears "
            f"the walk; snapshot into a local (e.g. "
            f"`list(...)` under the writer's lock) first",
        )

    def _iter_root(self, expr, local):
        """The non-local root name a (possibly chained/called) iterable
        reads from, or None when the iterable is provably local."""
        if isinstance(expr, (ast.Constant, ast.Tuple, ast.List,
                             ast.Set, ast.Dict)):
            return None
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name):
                # Builtins over their arguments: range(n),
                # enumerate(x), zip(a, b), sorted(x)...
                for arg in expr.args:
                    root = self._iter_root(arg, local)
                    if root is not None:
                        return root
                return None
            if isinstance(expr.func, ast.Attribute):
                # x.items() / self._d.values(): judge the receiver.
                return self._iter_root(expr.func.value, local)
            return None
        node = expr
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if isinstance(node, ast.Name):
            return None if node.id in local else node.id
        return None


@register
class LockOrderCycle(Rule):
    code = "BA503"
    name = "lock-order-cycle"
    severity = "error"

    def check_module(self, mod, project):
        graph = self._project_graph(project)
        edges, reacquires = graph
        cyclic = self._cyclic_nodes(edges)
        for (a, b), sites in sorted(edges.items()):
            if a in cyclic and b in cyclic and cyclic[a] == cyclic[b]:
                for site_mod, node in sites:
                    if site_mod is mod:
                        members = sorted(
                            k for k, v in cyclic.items()
                            if v == cyclic[a]
                        )
                        yield self.finding(
                            mod,
                            node,
                            f"lock-order cycle: acquiring {b} while "
                            f"holding {a}, but elsewhere the order "
                            f"reverses (cycle members: "
                            f"{', '.join(members)}) — two threads "
                            f"interleaving these regions deadlock; "
                            f"pick ONE global order",
                        )
        for site_mod, node, lock in reacquires:
            if site_mod is mod:
                yield self.finding(
                    mod,
                    node,
                    f"re-acquiring non-reentrant lock {lock} while "
                    f"already holding it — this self-deadlocks the "
                    f"moment the path executes (use RLock, or lift "
                    f"the inner region out)",
                )

    def _project_graph(self, project):
        memo = project.__dict__.get("_ba503_graph")
        if memo is not None:
            return memo
        edges: dict = {}  # (lock_a, lock_b) -> [(mod, node)]
        reacquires = []  # (mod, node, lock)
        for m in project.modules.values():
            mod_locks = {}
            for node in m.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    fn = m.imports.resolve(node.value.func)
                    if fn in LOCK_CTORS:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                mod_locks[t.id] = LOCK_CTORS[fn]
            for cm in _module_classes(m):
                lock_kinds = dict(cm.locks)
                lock_kinds.update(mod_locks)

                def lock_id(key, cls=cm):
                    if key.startswith("self."):
                        return f"{m.modname}.{cls.name}.{key[5:]}"
                    return f"{m.modname}.{key}"

                for mname, facts in cm.facts.items():
                    for key, node, held in facts.acquires:
                        if key in held:
                            if not lock_kinds.get(key, True):
                                reacquires.append(
                                    (m, node, lock_id(key))
                                )
                            continue
                        for h in held:
                            edges.setdefault(
                                (lock_id(h), lock_id(key)), []
                            ).append((m, node))
                    # One-hop: self._m() under a lock, where _m
                    # acquires another lock at its own top level.
                    for callee, node, held in facts.self_calls:
                        if not held or callee not in cm.facts:
                            continue
                        for key, _n, inner_held in cm.facts[
                            callee
                        ].acquires:
                            if inner_held:
                                continue
                            if key in held:
                                if not lock_kinds.get(key, True):
                                    reacquires.append(
                                        (m, node, lock_id(key))
                                    )
                                continue
                            for h in held:
                                edges.setdefault(
                                    (lock_id(h), lock_id(key)), []
                                ).append((m, node))
            # Module-level functions with module locks.
            mod_lock_keys = {k: v for k, v in mod_locks.items()}
            for f in _func_defs(m.tree.body):
                facts = _FuncFacts(f, mod_lock_keys)
                for key, node, held in facts.acquires:
                    if key in held:
                        if not mod_lock_keys.get(key, True):
                            reacquires.append(
                                (m, node, f"{m.modname}.{key}")
                            )
                        continue
                    for h in held:
                        edges.setdefault(
                            (
                                f"{m.modname}.{h}",
                                f"{m.modname}.{key}",
                            ),
                            [],
                        ).append((m, node))
        memo = (edges, reacquires)
        project.__dict__["_ba503_graph"] = memo
        return memo

    @staticmethod
    def _cyclic_nodes(edges):
        """node -> SCC id, for nodes in a multi-node SCC (iterative
        Tarjan over the acquired-while-held digraph)."""
        adj: dict = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: dict = {}
        low: dict = {}
        on_stack: set = set()
        stack: list = []
        sccs: dict = {}
        counter = [0]
        scc_id = [0]

        for start in sorted(adj):
            if start in index:
                continue
            work = [(start, iter(adj[start]))]
            index[start] = low[start] = counter[0]
            counter[0] += 1
            stack.append(start)
            on_stack.add(start)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        for w in comp:
                            sccs[w] = scc_id[0]
                        scc_id[0] += 1
        return sccs


@register
class LeakedTimerLifecycle(Rule):
    code = "BA504"
    name = "leaked-timer-daemon-lifecycle"
    severity = "error"

    def check_module(self, mod, project):
        classes = {cm.name: cm for cm in _module_classes(mod)}
        for scope in ast.walk(mod.tree):
            if not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            owner = self._owning_class(mod, scope, classes)
            yield from self._check_scope(mod, scope, owner)

    @staticmethod
    def _owning_class(mod, scope, classes):
        for cm in classes.values():
            if scope.name in cm.methods and cm.methods[
                scope.name
            ] is scope:
                return cm
        return None

    def _check_scope(self, mod, scope, owner):
        finally_calls = self._finally_method_calls(scope)
        body_calls = self._method_calls(scope)
        for node in _own_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            kind, _cb = _call_thread_targets(node, mod.imports)
            if kind == "timer":
                yield from self._check_timer(
                    mod, scope, node, owner, finally_calls
                )
            elif kind == "thread":
                yield from self._check_thread(
                    mod, scope, node, owner, body_calls
                )

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _finally_method_calls(scope):
        """{(receiver, method)} called from any finally block."""
        out = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) and isinstance(
                            sub.func, ast.Attribute
                        ):
                            recv = sub.func.value
                            if isinstance(recv, ast.Name):
                                out.add((recv.id, sub.func.attr))
                            else:
                                attr = _self_attr(recv)
                                if attr is not None:
                                    out.add(
                                        (f"self.{attr}", sub.func.attr)
                                    )
        return out

    @staticmethod
    def _method_calls(scope):
        out = set()
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ):
                recv = sub.func.value
                if isinstance(recv, ast.Name):
                    out.add((recv.id, sub.func.attr))
                else:
                    attr = _self_attr(recv)
                    if attr is not None:
                        out.add((f"self.{attr}", sub.func.attr))
        return out

    @staticmethod
    def _class_calls(owner, method):
        """{receiver-keys} on which ``method()`` is called anywhere in
        the owning class."""
        out = set()
        if owner is None:
            return out
        for f in owner.methods.values():
            for sub in ast.walk(f):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ) and sub.func.attr == method:
                    attr = _self_attr(sub.func.value)
                    if attr is not None:
                        out.add(f"self.{attr}")
        return out

    def _binding_of(self, scope, call):
        """('local', name) / ('attr', attr) / ('chained', None) /
        (None, None) for how a Thread/Timer construction is bound."""
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        return "local", t.id
                    attr = _self_attr(t)
                    if attr is not None:
                        return "attr", attr
                return "other", None
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.value is call
            ):
                return "chained", node.func.attr
            if isinstance(node, ast.Call) and call in node.args:
                return "escapes", None
            if isinstance(node, (ast.Return, ast.Yield)) and getattr(
                node, "value", None
            ) is call:
                return "escapes", None
        return None, None

    def _check_timer(self, mod, scope, call, owner, finally_calls):
        how, name = self._binding_of(scope, call)
        if how == "chained":
            if name == "start":
                yield self.finding(
                    mod,
                    call,
                    "threading.Timer(...).start() with no binding — "
                    "the timer can never be cancelled; bind it and "
                    "cancel on every exit (try/finally)",
                )
            return
        if how == "escapes" or how == "other":
            return  # lifecycle handed elsewhere; not provable here
        if how == "local":
            started = (name, "start") in self._method_calls(scope)
            if not started:
                return
            if (name, "cancel") in finally_calls:
                return
            yield self.finding(
                mod,
                call,
                f"threading.Timer bound to {name!r} is started but "
                f"not cancelled on all exits — wrap the armed region "
                f"in try/finally with {name}.cancel() in the finally "
                f"(an exception between start() and the hot path "
                f"leaks a live timer that fires into torn state)",
            )
            return
        if how == "attr":
            cancels = self._class_calls(owner, "cancel")
            if f"self.{name}" in cancels:
                return
            yield self.finding(
                mod,
                call,
                f"threading.Timer stored on self.{name} is never "
                f"cancelled anywhere in "
                f"{owner.name if owner else 'this class'} — add a "
                f"cancel on the stop/close path (a live timer "
                f"outliving its owner fires into torn state)",
            )

    def _check_thread(self, mod, scope, call, owner, body_calls):
        if _kw_daemon_true(call):
            return
        how, name = self._binding_of(scope, call)
        if how == "chained":
            return
        if how in ("escapes", "other", None):
            return
        # `t.daemon = True` after construction also counts.
        if how == "local" and self._daemon_assigned(scope, name):
            return
        if how == "attr" and owner is not None and any(
            self._daemon_assigned(f, f"self.{name}")
            for f in owner.methods.values()
        ):
            return
        if how == "local":
            if (name, "start") not in body_calls:
                return
            if (name, "join") in body_calls:
                return
            yield self.finding(
                mod,
                call,
                f"non-daemon thread {name!r} is started but never "
                f"joined in this function — process exit blocks on "
                f"it; join it, or mark it daemon=True if abandoning "
                f"mid-work is safe",
            )
            return
        if how == "attr":
            joins = self._class_calls(owner, "join")
            if f"self.{name}" in joins:
                return
            yield self.finding(
                mod,
                call,
                f"non-daemon thread stored on self.{name} is never "
                f"join()ed anywhere in "
                f"{owner.name if owner else 'this class'} — add a "
                f"join to stop()/close(), or mark it daemon=True",
            )

    @staticmethod
    def _daemon_assigned(scope, key):
        """True when `<key>.daemon = True` appears in ``scope`` (key is
        a bare local name or 'self.attr')."""
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                ):
                    recv = t.value
                    if isinstance(recv, ast.Name) and recv.id == key:
                        return True
                    attr = _self_attr(recv)
                    if attr is not None and f"self.{attr}" == key:
                        return True
        return False
