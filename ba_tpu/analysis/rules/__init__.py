"""Rule plugins.  Importing a rule module registers its rules (the
``@register`` decorator); :func:`load_all` is the one place that lists
them, so adding a rule is one module plus one line here."""


def load_all() -> None:
    from ba_tpu.analysis.rules import (  # noqa: F401
        concurrency,
        contracts_rules,
        dead_imports,
        donation,
        hot_path,
        obs_purity,
        rng,
    )
