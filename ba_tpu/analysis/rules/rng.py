"""BA202 rng-key-reuse.

The silent-correctness bug class the byzantine fault-injection path is
most exposed to: pass the same PRNG key to two sampling calls and the
"random" traitor coins repeat — no crash, no warning, just correlated
faults that quietly break the independence assumptions behind every
histogram test in the suite.  (The engine's whole key discipline —
``fold_in(fold_in(base, round), instance)`` — exists to make reuse
structurally impossible on the hot path; this rule covers everywhere
else.)

Semantics, per function scope over the shared must-flow walk:

- A **sampling** call (``jax.random.normal/bernoulli/randint/...``,
  alias-resolved) with a bare-name key argument CONSUMES that name.
- A second sampling call consuming the same name before it is REBOUND
  is a finding.  Deriving from the key in between
  (``k2 = jr.fold_in(key, 1)``) does NOT clear the mark: keys are
  immutable, so the original name still repeats its stream — only
  rebinding (``key, sub = jr.split(key)``, the canonical idiom)
  decorrelates it.
- Branch joins are intersections (consumed on one path only does not
  poison the other); loop bodies are double-walked, so a
  loop-invariant key consumed every iteration is caught
  (``for i in r: jr.normal(key)`` draws the same numbers each pass).

Only bare ``Name`` keys are tracked: ``jr.normal(jr.fold_in(key, i))``
derives inline and is clean by construction.  Deliberate reuse (A/B
benchmarks replaying identical randomness across two implementations)
is exactly what the line suppression is for::

    out_b = engine_b(jr.uniform(key, ...))  # ba-lint: disable=BA202
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import Rule, register
from ba_tpu.analysis.flow import (
    FlowHandler,
    FlowState,
    function_scopes,
    walk_body,
)

SAMPLING = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical",
    "cauchy", "chisquare", "choice", "dirichlet", "double_sided_maxwell",
    "exponential", "f", "gamma", "generalized_normal", "geometric",
    "gumbel", "laplace", "loggamma", "logistic", "lognormal", "maxwell",
    "multinomial", "multivariate_normal", "normal", "orthogonal",
    "pareto", "permutation", "poisson", "rademacher", "randint",
    "rayleigh", "t", "triangular", "truncated_normal", "uniform", "wald",
    "weibull_min",
}


def _key_arg(call: ast.Call):
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


class _ConsumedState(FlowState):
    def __init__(self, consumed=None):
        # name -> (sampling fn, line of first consumption)
        self.consumed = dict(consumed or {})

    def copy(self):
        return _ConsumedState(self.consumed)

    def merge(self, others):
        if not others:
            self.consumed = {}
            return
        keep = {}
        for name, info in others[0].consumed.items():
            if all(name in o.consumed for o in others):
                keep[name] = self.consumed.get(name, info)
        self.consumed = keep


class _Handler(FlowHandler):
    def __init__(self, rule, mod):
        self.rule = rule
        self.mod = mod
        self.findings = {}

    def on_store(self, name, state):
        state.consumed.pop(name, None)

    def on_call(self, call, state):
        dotted = self.mod.imports.resolve(call.func)
        if not dotted or not dotted.startswith("jax.random."):
            return
        fn = dotted.rsplit(".", 1)[1]
        key = _key_arg(call)
        if fn not in SAMPLING or not isinstance(key, ast.Name):
            # Deriving calls (split/fold_in/clone) deliberately do NOT
            # clear the mark: the immutable original key would still
            # repeat its stream.  Only on_store (rebinding) clears.
            return
        prior = state.consumed.get(key.id)
        if prior is not None:
            prev_fn, prev_line = prior
            loc = (call.lineno, call.col_offset)
            if loc not in self.findings:
                self.findings[loc] = self.rule.finding(
                    self.mod,
                    call,
                    f"key '{key.id}' already consumed by "
                    f"jax.random.{prev_fn} (line {prev_line}) — reuse "
                    "draws identical randomness; split/fold_in first "
                    "(or rebind the name)",
                )
        else:
            state.consumed[key.id] = (fn, call.lineno)


@register
class RngKeyReuse(Rule):
    code = "BA202"
    name = "rng-key-reuse"
    severity = "error"

    def check_module(self, mod, project):
        handler = _Handler(self, mod)
        for _scope, body in function_scopes(mod.tree):
            walk_body(body, handler, _ConsumedState())
        yield from handler.findings.values()
