"""BA201 use-after-donate.

The engine's donation contract (``parallel/pipeline.py``): buffers
passed to a ``donate_argnums`` dispatch are CONSUMED — XLA aliases the
output onto them and jax deletes the handle, so a later read raises (at
best) or silently reads an aliased buffer on backends that defer the
error.  The rule proves at the call site what the runtime only catches
when the path executes: after a statement that passes local name ``x``
at a donated position, any read of ``x`` before a rebinding is a
finding.

Donating callables come from three places, merged in this order:

1. the project-wide registry (``@functools.partial(jax.jit,
   donate_argnums=...)`` decorators and ``g = jax.jit(f,
   donate_argnums=...)`` rebindings, resolved through import aliases so
   cross-module call sites are checked);
2. the ``# ba-lint: donates(name, ...)`` ANNOTATION (ISSUE 5, the
   ROADMAP PR 3 item): a wrapper whose jit lives inside but whose
   documented contract consumes an argument declares it on its own
   ``def`` line::

       def scenario_sweep(  # ba-lint: donates(state)
           key, state, ...

   The comment must sit on the ``def`` line itself (real comment
   placement — a docstring that merely documents the syntax, like this
   one, never registers), and the names must be positional parameters
   of that function.  Parsed here into the same registry the jit
   decorators feed, so call sites in OTHER modules resolve through
   their import aliases identically;
3. the hand-maintained CONVENTION table below — kept as the fallback
   for wrappers that cannot carry the annotation (and as the
   bootstrap the annotation replaced; entries should migrate to
   annotations over time).

Analysis is the shared must-flow walk (``analysis/flow.py``):
evaluation-ordered events, intersection joins at branches (a donate on
one path never poisons the other), and double-walked loop bodies so a
donate at the bottom of a loop body catches the read at the top of the
next iteration.  ``fresh_copy(x)`` BEFORE the donating call is the
sanctioned survival idiom and naturally clean here — only reads AFTER
the donate flag.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize

from ba_tpu.analysis.base import Rule, register
from ba_tpu.analysis.flow import (
    FlowHandler,
    FlowState,
    function_scopes,
    walk_body,
)
from ba_tpu.analysis.project import DonationSpec

# Wrappers that donate by documented contract rather than a visible
# donate_argnums: pipeline_sweep consumes its `state` (arg 1) — the
# first megastep inside it donates it — while `key` survives (the
# schedule copies the key data; make_key_schedule's contract).  Kept as
# the FALLBACK behind the donates annotation (the annotated real
# signatures shadow these entries via the merge order in
# ``_donation_table``); pipeline_sweep itself now carries the
# annotation too, so this table is belt-and-braces.
KNOWN_DONATING = {
    "ba_tpu.parallel.pipeline.pipeline_sweep": DonationSpec(
        frozenset([1]), ("key", "state")
    ),
    # The mesh scan core (ISSUE 8): the sharded megasteps carry real
    # donate_argnums decorators AND def-line annotations; these fallback
    # rows keep cross-module call sites checked even if a refactor drops
    # one of the other two sources.
    "ba_tpu.parallel.shard.sharded_pipeline_megastep": DonationSpec(
        frozenset([0, 1]), ("state", "sched")
    ),
    "ba_tpu.parallel.shard.sharded_scenario_megastep": DonationSpec(
        frozenset([0, 1, 2]), ("state", "sched", "strategy")
    ),
    # The Pallas megastep twins (ISSUE 13) mirror their XLA twins'
    # donation contracts exactly; real donate_argnums decorators and
    # def-line annotations exist there too — same belt-and-braces as
    # the sharded rows above.
    "ba_tpu.ops.scenario_step.pallas_scenario_megastep": DonationSpec(
        frozenset([0, 1, 2]), ("state", "sched", "strategy")
    ),
    "ba_tpu.ops.scenario_step.pallas_pipeline_megastep": DonationSpec(
        frozenset([0, 1]), ("state", "sched")
    ),
    "ba_tpu.ops.scenario_step.pallas_coalesced_megastep": DonationSpec(
        frozenset([0, 1, 2]), ("state", "sched", "strategy")
    ),
    # The signed lane (ISSUE 14): signed megasteps donate (state, sched)
    # like their plain twins — counter block and sign-ahead verdict
    # planes deliberately excluded (no output aliases their shapes).
    # Real donate_argnums decorators and def-line annotations exist
    # there too; same belt-and-braces as the rows above.
    "ba_tpu.parallel.pipeline.signed_megastep": DonationSpec(
        frozenset([0, 1]), ("state", "sched")
    ),
    "ba_tpu.parallel.pipeline.coalesced_signed_megastep": DonationSpec(
        frozenset([0, 1]), ("state", "sched")
    ),
    # The adversary search engine's evaluation seam (ISSUE 15): it
    # hands `state` straight to coalesced_sweep, which consumes it.
    # Carries the def-line annotation too — same belt-and-braces.
    "ba_tpu.search.loop.evaluate_population": DonationSpec(
        frozenset([1]), ("slot_keys", "state", "block")
    ),
}

_DONATES_RE = re.compile(r"#\s*ba-lint:\s*donates\(([^)]*)\)")


def annotated_donations(mod) -> tuple:
    """``({qualified name: DonationSpec}, [(lineno, message)])`` for
    every function in ``mod`` whose ``def`` line carries a ``# ba-lint:
    donates(a, b)`` comment.

    Directives parse from REAL comment tokens (``tokenize``, exactly
    like the suppression index) — a docstring that merely documents the
    syntax never registers — and anchor by line number: the comment
    must sit on the exact line a ``FunctionDef`` starts on (multi-line
    signatures annotate the ``def foo(`` line).  A name that is not a
    positional parameter of its function comes back as an error entry
    (BA201 reports it at the annotation line): a typo'd annotation
    silently protecting nothing is worse than none.
    """
    hits = {}
    try:
        tokens = tokenize.generate_tokens(
            io.StringIO(mod.source).readline
        )
        comments = [
            (tok.start[0], tok.string)
            for tok in tokens
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}, []  # unparseable files already surface as BA900
    for lineno, text in comments:
        m = _DONATES_RE.search(text)
        if m:
            names = tuple(
                s.strip() for s in m.group(1).split(",") if s.strip()
            )
            if names:
                hits[lineno] = names
    if not hits:
        return {}, []
    specs, errors = {}, []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names = hits.pop(node.lineno, None)
        if names is None:
            continue
        params = [
            p.arg for p in node.args.posonlyargs + node.args.args
        ]
        unknown = [nm for nm in names if nm not in params]
        if unknown:
            errors.append(
                (
                    node.lineno,
                    f"donates() annotation names {unknown} which are "
                    f"not positional parameters of {node.name}() "
                    f"(has {params})",
                )
            )
            continue
        specs[f"{mod.modname}.{node.name}"] = DonationSpec(
            frozenset(params.index(nm) for nm in names), tuple(params)
        )
    # Hits left over never matched a def line (e.g. a stray annotation
    # on a call site): also a declaration defect worth surfacing.
    errors.extend(
        (lineno, "donates() annotation is not on a function def line")
        for lineno in sorted(hits)
    )
    return specs, errors


def _donation_table(project) -> tuple:
    """``(merged table, {modname: [(lineno, message)]})``:
    KNOWN_DONATING overlaid by every module's ``donates()`` annotations.
    Built once per Project and memoized on it (rule instances are
    registry singletons; a cross-run cache would go stale)."""
    cached = project.__dict__.get("_ba201_annotations")
    if cached is None:
        table = dict(KNOWN_DONATING)
        bad = {}
        for mod in project.modules.values():
            specs, errors = annotated_donations(mod)
            table.update(specs)
            if errors:
                bad[mod.modname] = errors
        cached = (table, bad)
        project.__dict__["_ba201_annotations"] = cached
    return cached


class _PoisonState(FlowState):
    def __init__(self, poisoned=None):
        # name -> (callee display, donate line)
        self.poisoned = dict(poisoned or {})

    def copy(self):
        return _PoisonState(self.poisoned)

    def merge(self, others):
        if not others:
            self.poisoned = {}
            return
        keep = {}
        for name, info in others[0].poisoned.items():
            if all(name in o.poisoned for o in others):
                keep[name] = self.poisoned.get(name, info)
        self.poisoned = keep


class _Handler(FlowHandler):
    def __init__(self, rule, mod, project, extra):
        self.rule = rule
        self.mod = mod
        self.project = project
        self.extra = extra
        self.findings = {}

    def on_load(self, node, state):
        info = state.poisoned.get(node.id)
        if info is None:
            return
        callee, line = info
        key = (node.lineno, node.col_offset)
        if key not in self.findings:
            self.findings[key] = self.rule.finding(
                self.mod,
                node,
                f"'{node.id}' read after being donated to {callee} "
                f"(line {line}) — donated buffers are deleted by XLA; "
                "thread the returned value, or fresh_copy() before the "
                "dispatch",
            )

    def on_store(self, name, state):
        state.poisoned.pop(name, None)

    def on_call(self, call, state):
        spec = self.project.donation_for(
            self.mod, call.func, self.extra
        )
        if spec is None:
            return
        callee = ast.unparse(call.func)
        for i in spec.positions:
            if i < len(call.args) and isinstance(call.args[i], ast.Name):
                state.poisoned[call.args[i].id] = (callee, call.lineno)
        named = spec.donated_params()
        for kw in call.keywords:
            if kw.arg in named and isinstance(kw.value, ast.Name):
                state.poisoned[kw.value.id] = (callee, call.lineno)


@register
class UseAfterDonate(Rule):
    code = "BA201"
    name = "use-after-donate"
    severity = "error"

    def check_module(self, mod, project):
        table, bad = _donation_table(project)
        for lineno, message in bad.get(mod.modname, ()):
            anchor = ast.Module(body=[], type_ignores=[])
            anchor.lineno, anchor.col_offset = lineno, 0
            yield self.finding(mod, anchor, message)
        handler = _Handler(self, mod, project, table)
        for _scope, body in function_scopes(mod.tree):
            walk_body(body, handler, _PoisonState())
        yield from handler.findings.values()
