"""BA201 use-after-donate.

The engine's donation contract (``parallel/pipeline.py``): buffers
passed to a ``donate_argnums`` dispatch are CONSUMED — XLA aliases the
output onto them and jax deletes the handle, so a later read raises (at
best) or silently reads an aliased buffer on backends that defer the
error.  The rule proves at the call site what the runtime only catches
when the path executes: after a statement that passes local name ``x``
at a donated position, any read of ``x`` before a rebinding is a
finding.

Donating callables come from the project-wide registry
(``@functools.partial(jax.jit, donate_argnums=...)`` decorators and
``g = jax.jit(f, donate_argnums=...)`` rebindings, resolved through
import aliases so cross-module call sites are checked), plus the
CONVENTION table below for wrappers whose jit lives inside but whose
documented contract donates an argument.

Analysis is the shared must-flow walk (``analysis/flow.py``):
evaluation-ordered events, intersection joins at branches (a donate on
one path never poisons the other), and double-walked loop bodies so a
donate at the bottom of a loop body catches the read at the top of the
next iteration.  ``fresh_copy(x)`` BEFORE the donating call is the
sanctioned survival idiom and naturally clean here — only reads AFTER
the donate flag.
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import Rule, register
from ba_tpu.analysis.flow import (
    FlowHandler,
    FlowState,
    function_scopes,
    walk_body,
)
from ba_tpu.analysis.project import DonationSpec

# Wrappers that donate by documented contract rather than a visible
# donate_argnums: pipeline_sweep consumes its `state` (arg 1) — the
# first megastep inside it donates it — while `key` survives (the
# schedule copies the key data; make_key_schedule's contract).
KNOWN_DONATING = {
    "ba_tpu.parallel.pipeline.pipeline_sweep": DonationSpec(
        frozenset([1]), ("key", "state")
    ),
}


class _PoisonState(FlowState):
    def __init__(self, poisoned=None):
        # name -> (callee display, donate line)
        self.poisoned = dict(poisoned or {})

    def copy(self):
        return _PoisonState(self.poisoned)

    def merge(self, others):
        if not others:
            self.poisoned = {}
            return
        keep = {}
        for name, info in others[0].poisoned.items():
            if all(name in o.poisoned for o in others):
                keep[name] = self.poisoned.get(name, info)
        self.poisoned = keep


class _Handler(FlowHandler):
    def __init__(self, rule, mod, project):
        self.rule = rule
        self.mod = mod
        self.project = project
        self.findings = {}

    def on_load(self, node, state):
        info = state.poisoned.get(node.id)
        if info is None:
            return
        callee, line = info
        key = (node.lineno, node.col_offset)
        if key not in self.findings:
            self.findings[key] = self.rule.finding(
                self.mod,
                node,
                f"'{node.id}' read after being donated to {callee} "
                f"(line {line}) — donated buffers are deleted by XLA; "
                "thread the returned value, or fresh_copy() before the "
                "dispatch",
            )

    def on_store(self, name, state):
        state.poisoned.pop(name, None)

    def on_call(self, call, state):
        spec = self.project.donation_for(
            self.mod, call.func, KNOWN_DONATING
        )
        if spec is None:
            return
        callee = ast.unparse(call.func)
        for i in spec.positions:
            if i < len(call.args) and isinstance(call.args[i], ast.Name):
                state.poisoned[call.args[i].id] = (callee, call.lineno)
        named = spec.donated_params()
        for kw in call.keywords:
            if kw.arg in named and isinstance(kw.value, ast.Name):
                state.poisoned[kw.value.id] = (callee, call.lineno)


@register
class UseAfterDonate(Rule):
    code = "BA201"
    name = "use-after-donate"
    severity = "error"

    def check_module(self, mod, project):
        handler = _Handler(self, mod, project)
        for _scope, body in function_scopes(mod.tree):
            walk_body(body, handler, _PoisonState())
        yield from handler.findings.values()
