"""BA401 dead-import (warning severity).

The reference codebase's unused-``datetime``/``wraps`` habit crept into
``ba_tpu`` too (the ISSUE 3 sweep found six of them, since fixed).  A
dead import is noise at best; at worst it is a latent layering leak —
an unused ``from ba_tpu.parallel import ...`` in a core module would
hold an obs-reaching edge open for BA301 the day someone uses it.

A name counts as used when it appears as a ``Name`` load anywhere in
the module (attribute chains count through their base name), or when it
is listed in a string ``__all__`` (re-export — ``parallel/multihost.py``
re-exports ``make_mesh`` this way).  ``__init__.py`` files are skipped
wholesale: their imports ARE their API.  ``from __future__`` and
explicit-intent ``as _`` bindings are exempt.

Warning severity: findings print and count, but never fail the run —
CI keeps the rule on as a ratchet without blocking merges on cleanup.
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import WARNING, Rule, register


def _all_names(tree: ast.Module) -> set:
    """String entries of a top-level ``__all__`` assignment."""
    names: set = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        if not any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in targets
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                names.add(sub.value)
    return names


@register
class DeadImport(Rule):
    code = "BA401"
    name = "dead-import"
    severity = WARNING

    def check_module(self, mod, project):
        if mod.path.endswith("__init__.py"):
            return
        bound = []  # (node, local name, imported thing)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    bound.append((node, local, a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound.append((node, a.asname or a.name, a.name))
        if not bound:
            return
        used = {
            n.id
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.Name)
        }
        used |= _all_names(mod.tree)
        for node, local, imported in bound:
            if local in used or local == "_":
                continue
            yield self.finding(
                mod,
                node,
                f"'{imported}' imported as '{local}' is never used "
                "(add to __all__ if it is a deliberate re-export)",
            )
