"""BA301 obs-purity: the jitted trees never touch the obs layer.

The observability layer (PR 2) is HOST-only by contract: a span or
``metrics.emit`` inside a jitted/scanned body would time tracing
instead of execution, or force a host-callback sync in the middle of
the round loop.  The jitted math lives in ``ba_tpu.core`` and
``ba_tpu.ops``; instrumentation belongs in ``runtime/``, the
``parallel/`` loop drivers, crypto host paths, and ``bench.py``.

Unlike the grep it replaces, this rule works on the real import graph,
alias-resolved:

- a ``core``/``ops`` module importing ``ba_tpu.obs`` under ANY spelling
  (``from ba_tpu import obs as o``, ``from ba_tpu.obs.trace import
  span``...) is flagged at the import;
- so is importing another ``core``/``ops`` module whose own
  closure reaches obs — the finding lands on the edge that lets the
  contamination in, with the path named;
- any alias-resolved attribute reference to ``ba_tpu.obs...`` or
  ``.emit`` on a name bound to ``ba_tpu.utils.metrics`` is flagged at
  the reference.

The closure deliberately follows edges only THROUGH other jitted-tree
modules: importing a host-layer module (``crypto``/``utils``/
``parallel`` helpers, which legitimately instrument their own host
paths — e.g. ``crypto/sha512`` -> ``utils/platform`` ->
``obs.instrument``) is not an obs reference from the jitted tree, and
treating it as one would indict every kernel that consults
``use_pallas`` at trace time.

The SYMMETRIC direction (ISSUE 9): ``ba_tpu.obs`` modules are
HOST-TIER by contract — the flight recorder and health sampler must
stay importable jax-free and must never pull the jitted trees in (an
obs module importing ``ba_tpu.core``/``ba_tpu.ops`` would make every
``import ba_tpu.obs`` pay a core import, and tempt device values into
assembly/sampling paths that run from watchdog threads and atexit
hooks).  Importing through another obs module whose through-obs
closure reaches core/ops is flagged at the edge that lets it in, same
as the forward direction.

The SERVING front-end (ISSUE 10): ``ba_tpu.runtime.serve`` joins the
host-tier scope at MODULE level — its import-time closure must never
reach ``ba_tpu.core``/``ba_tpu.ops`` (admission control, fault-plan
validation and client shaping must run on hosts without jax, and
``import ba_tpu.runtime.serve`` must never pay a jax import).  Unlike
the obs modules, serve's DISPATCHER legitimately drives the engine, so
FUNCTION-LOCAL imports are the sanctioned lazy seam (the
``runtime/backends.py`` discipline) — the check skips imports nested
inside a function body and flags everything at module scope, including
module-level imports whose own closure reaches the jitted trees.

The WARMUP pass (ISSUE 11): ``ba_tpu.runtime.warmup`` joins the same
module-level host-tier scope (plan construction is jax-free; the AOT
builders, which need the jitted trees, load lazily from the runner
thread).

The ADVERSARY SEARCH package (ISSUE 15): every ``ba_tpu.search``
module joins the module-level host-tier scope — the generator,
objective table, minimizer and corpus layers are numpy/stdlib by
contract (the jax-free ``python -m ba_tpu.search`` CLI and the CI
corpus stage depend on it), and the hunt loop reaches the coalesced
engine only through function-body imports, exactly the serve
dispatcher's sanctioned lazy seam.

The FLEET TIER (ISSUE 20): every ``ba_tpu.fleet`` module joins the
module-level host-tier scope — routing, replica state machines,
handoff verification and orphan adoption are numpy/stdlib by contract
(a router host needs no accelerator; checkpoint verification rides the
jax-free ``utils/snapshot`` reader), and only a replica's campaign
lane (``replica._campaign_lane``) reaches the supervised engine,
through the same function-local seam as the serve dispatcher.

The executable cache ``ba_tpu.obs.aotcache`` needs no listing
— it sits inside the obs scope, whose STRICTER rule (even function-local
core/ops imports are findings) already covers it; its specialization
builders therefore live in ``parallel/pipeline.py`` and are passed in.
"""

from __future__ import annotations

import ast

from ba_tpu.analysis.base import Rule, register

SCOPES = ("ba_tpu.core", "ba_tpu.ops")
OBS = "ba_tpu.obs"
SINK = "ba_tpu.utils.metrics"
# Host-tier-at-module-level modules: the serving front-end (ISSUE 10),
# the warmup pass (ISSUE 11), the adversary search package (ISSUE 15),
# and the fleet tier (ISSUE 20) — all must import jax-free (plan
# construction, admission, routing, handoff verification and the
# search CLI's sample/corpus ops run on hosts without jax) and reach
# the engine only through function-local imports (for the fleet: the
# replica's campaign lane, ``replica._campaign_lane``).
HOST_TIER_MODULES = (
    "ba_tpu.runtime.serve",
    "ba_tpu.runtime.warmup",
    "ba_tpu.search",
    "ba_tpu.search.__main__",
    "ba_tpu.search.generate",
    "ba_tpu.search.objective",
    "ba_tpu.search.loop",
    "ba_tpu.search.minimize",
    "ba_tpu.search.corpus",
    "ba_tpu.fleet",
    "ba_tpu.fleet.router",
    "ba_tpu.fleet.replica",
    "ba_tpu.fleet.migrate",
    # The jax-free checkpoint reader (its docstring contract since
    # ISSUE 6; lint-enforced since the fleet tier started verifying
    # handoffs through it): jax appears only inside load functions.
    "ba_tpu.utils.snapshot",
)


def _in_scope(modname: str) -> bool:
    return any(
        modname == s or modname.startswith(s + ".") for s in SCOPES
    )


def _is_obs(target: str) -> bool:
    return target == OBS or target.startswith(OBS + ".")


def _is_jit_tree(target: str) -> bool:
    return _in_scope(target)


def _in_obs_scope(modname: str) -> bool:
    return modname == OBS or modname.startswith(OBS + ".")


@register
class ObsPurity(Rule):
    code = "BA301"
    name = "obs-purity"
    severity = "error"

    def check_module(self, mod, project):
        if _in_obs_scope(mod.modname):
            yield from self._check_host_tier(mod, project)
            return
        if mod.modname in HOST_TIER_MODULES:
            yield from self._check_host_tier(
                mod, project, module_level_only=True
            )
            return
        if not _in_scope(mod.modname):
            return
        # Memoized per Project (rule instances are registry singletons
        # shared across runs; a cross-run memo would go stale).
        memo = project.__dict__.setdefault("_ba301_memo", {})
        seen_lines: set = set()

        def once(node, message):
            if node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                yield self.finding(mod, node, message)

        for node, target in mod.import_records:
            if _is_obs(target):
                yield from once(
                    node,
                    f"jitted-tree module imports {OBS} — observability "
                    "is host-only (a span or emit inside a jitted body "
                    "times tracing, not execution); instrument the "
                    "caller in runtime/ or parallel/ instead",
                )
                continue
            nxt = project.resolve_target_module(target)
            if (
                nxt
                and nxt != mod.modname
                and _in_scope(nxt)
                and project.reaches(nxt, OBS, through=_in_scope, memo=memo)
            ):
                yield from once(
                    node,
                    f"jitted-tree module imports '{target}', whose "
                    f"jitted-tree import closure reaches {OBS} — "
                    "observability is host-only",
                )
        for node, dotted in mod.imports.resolved_refs(mod.tree):
            if _is_obs(dotted):
                yield from once(
                    node,
                    f"reference to {dotted} inside a jitted-tree module "
                    "— observability is host-only",
                )
            elif dotted.startswith(SINK + ".") and dotted.endswith(
                ".emit"
            ):
                yield from once(
                    node,
                    "metrics sink emit inside a jitted-tree module — "
                    "the JSONL sink is host-only; emit from the loop "
                    "driver",
                )

    def _check_host_tier(self, mod, project, module_level_only=False):
        """The reverse scope (ISSUE 9): obs modules never import the
        jitted trees — directly, or through ANY intermediary (unlike
        the forward rule, the closure here is unfiltered: an obs module
        pulling ``ba_tpu.parallel`` in would make ``import ba_tpu.obs``
        pay the core/jax import chain, which is exactly the host-tier
        breach, whoever sits in the middle).

        ``module_level_only`` (ISSUE 10, the serving front-end): only
        imports OUTSIDE any function body count — a function-local
        import is the sanctioned lazy engine seam, paid on the
        dispatcher thread instead of at ``import`` time."""
        seen_lines: set = set()

        def once(node, message):
            if node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                yield self.finding(mod, node, message)

        lazy_spans = ()
        if module_level_only:
            lazy_spans = tuple(
                (f.lineno, f.end_lineno or f.lineno)
                for f in ast.walk(mod.tree)
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            )

        def is_lazy(node) -> bool:
            return any(
                lo <= node.lineno <= hi for lo, hi in lazy_spans
            )

        for node, target in mod.import_records:
            if module_level_only and is_lazy(node):
                continue
            if _is_jit_tree(target):
                yield from once(
                    node,
                    f"host-tier module imports '{target}' — "
                    f"{mod.modname} must stay importable without the "
                    f"jitted trees (ba_tpu.core/ba_tpu.ops); reach "
                    f"them lazily from a function body instead"
                    if module_level_only
                    else f"host-tier obs module imports '{target}' — "
                    f"ba_tpu.obs must stay importable without the "
                    f"jitted trees (ba_tpu.core/ba_tpu.ops); observe "
                    f"their drivers from runtime/ or parallel/ instead",
                )
                continue
            nxt = project.resolve_target_module(target)
            if (
                module_level_only
                and nxt
                and nxt != mod.modname
                and nxt in HOST_TIER_MODULES
            ):
                # A host-tier module importing ANOTHER host-tier module
                # is the fleet tier's composition pattern (router →
                # serve, replica → migrate/snapshot): the target's own
                # module-level closure is enforced at its own entry,
                # and its sanctioned FUNCTION-LOCAL engine seams must
                # not poison importers through the unfiltered reaches
                # walk below (which follows lazy edges by design — the
                # right conservatism for unlisted intermediaries, the
                # wrong one for modules this rule already covers).
                continue
            if (
                nxt
                and nxt != mod.modname
                and any(project.reaches(nxt, scope) for scope in SCOPES)
            ):
                yield from once(
                    node,
                    f"host-tier module imports '{target}', whose "
                    f"import closure reaches the jitted trees "
                    f"(ba_tpu.core/ba_tpu.ops) — "
                    + (
                        f"{mod.modname} is host-tier at module level "
                        f"(lazy function-body imports are the "
                        f"sanctioned engine seam)"
                        if module_level_only
                        else "obs is host-tier"
                    ),
                )
