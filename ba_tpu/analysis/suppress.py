"""Inline suppression comments: ``# ba-lint: disable=BAxxx``.

Two forms, both parsed from REAL comment tokens (``tokenize``, not a
raw-line regex — a docstring that merely *documents* the syntax, like
this one, must never register as a live directive):

- line-scoped — appended to the flagged line::

      out = np.asarray(x)  # ba-lint: disable=BA101

  Multiple codes comma-separate (``disable=BA101,BA202``); ``all``
  silences every rule on the line.
- file-scoped — a comment anywhere in the file on its own line
  (conventionally in the header)::

      # ba-lint: disable-file=BA401

Suppressed findings still count in the JSON summary (``suppressed``
bucket) so a tree accumulating waivers is visible, but they never fail
the run.
"""

from __future__ import annotations

import io
import re
import tokenize

_LINE_RE = re.compile(r"#\s*ba-lint:\s*disable=([A-Za-z0-9,\s]+)")
_FILE_RE = re.compile(r"#\s*ba-lint:\s*disable-file=([A-Za-z0-9,\s]+)")


def _codes(group: str) -> set[str]:
    return {c.strip().upper() for c in group.split(",") if c.strip()}


class SuppressionIndex:
    """Per-file map of suppressed codes by line, plus file-wide codes."""

    def __init__(self, source: str):
        self.by_line: dict[int, set[str]] = {}
        self.file_wide: set[str] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments = [
                (tok.start, tok.string, tok.line)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Unparseable files surface as BA900 findings; suppression
            # directives in them are moot.
            return
        for (lineno, col), text, line in comments:
            m = _FILE_RE.search(text)
            if m:
                # Own-line comments only: a TRAILING disable-file would
                # silently waive a whole file where the author plainly
                # meant one line — ignore it rather than over-apply it.
                if line[:col].strip() == "":
                    self.file_wide |= _codes(m.group(1))
                continue
            m = _LINE_RE.search(text)
            if m:
                self.by_line[lineno] = _codes(m.group(1))

    def is_suppressed(self, code: str, line: int) -> bool:
        for active in (self.file_wide, self.by_line.get(line, ())):
            if code in active or "ALL" in active:
                return True
        return False
