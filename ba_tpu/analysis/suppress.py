"""Inline suppression comments: ``# ba-lint: disable=BAxxx``.

Two forms, both parsed from REAL comment tokens (``tokenize``, not a
raw-line regex — a docstring that merely *documents* the syntax, like
this one, must never register as a live directive):

- line-scoped — appended to the flagged line::

      out = np.asarray(x)  # ba-lint: disable=BA101

  Multiple codes comma-separate (``disable=BA101,BA202``); ``all``
  silences every rule on the line.
- file-scoped — a comment anywhere in the file on its own line
  (conventionally in the header)::

      # ba-lint: disable-file=BA401

Suppressed findings still count in the JSON summary (``suppressed``
bucket) so a tree accumulating waivers is visible, but they never fail
the run.

ISSUE 18 adds declarative ANNOTATIONS on the same comment channel
(parsed from real COMMENT tokens too, so docstrings stay inert):

- ``# ba-lint: thread-entry`` — line-scoped, on a ``def`` line: marks a
  function the concurrency rules must treat as a thread entry point
  even though no ``threading.Thread(target=...)``/``Timer`` call names
  it in the analyzed set (indirect dispatch through a registry,
  callback table, or an external framework);
- ``# ba-lint: lockfree`` — own-line, file-scoped: declares the module
  under the BA502 lock-free read discipline (only single-opcode
  GIL-atomic reads of shared state; no read-modify-write, no iteration
  over shared containers, no lock acquisition).
"""

from __future__ import annotations

import io
import re
import tokenize

_LINE_RE = re.compile(r"#\s*ba-lint:\s*disable=([A-Za-z0-9,\s]+)")
_FILE_RE = re.compile(r"#\s*ba-lint:\s*disable-file=([A-Za-z0-9,\s]+)")
# Declarative annotations (ISSUE 18).  `thread-entry` is line-scoped
# (on the def line); `lockfree` is file-scoped (own-line only, like
# disable-file).
_ANNO_RE = re.compile(r"#\s*ba-lint:\s*(thread-entry|lockfree)\b")


def _codes(group: str) -> set[str]:
    return {c.strip().upper() for c in group.split(",") if c.strip()}


class SuppressionIndex:
    """Per-file map of suppressed codes by line, plus file-wide codes."""

    def __init__(self, source: str):
        self.by_line: dict[int, set[str]] = {}
        self.file_wide: set[str] = set()
        # Annotations: line -> tokens (thread-entry), plus file-wide
        # declarations (lockfree).
        self.annotations: dict[int, set[str]] = {}
        self.file_annotations: set[str] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            comments = [
                (tok.start, tok.string, tok.line)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Unparseable files surface as BA900 findings; suppression
            # directives in them are moot.
            return
        for (lineno, col), text, line in comments:
            m = _FILE_RE.search(text)
            if m:
                # Own-line comments only: a TRAILING disable-file would
                # silently waive a whole file where the author plainly
                # meant one line — ignore it rather than over-apply it.
                if line[:col].strip() == "":
                    self.file_wide |= _codes(m.group(1))
                continue
            m = _LINE_RE.search(text)
            if m:
                self.by_line[lineno] = _codes(m.group(1))
                continue
            m = _ANNO_RE.search(text)
            if m:
                token = m.group(1)
                if token == "lockfree":
                    # Own-line only, mirroring disable-file: a TRAILING
                    # lockfree would put a whole module under the BA502
                    # discipline where the author plainly meant to
                    # annotate one line.
                    if line[:col].strip() == "":
                        self.file_annotations.add(token)
                else:
                    self.annotations.setdefault(lineno, set()).add(token)

    def is_suppressed(self, code: str, line: int) -> bool:
        for active in (self.file_wide, self.by_line.get(line, ())):
            if code in active or "ALL" in active:
                return True
        return False
