"""``python -m ba_tpu.analysis`` — the ba-lint entry point."""

import sys

from ba_tpu.analysis.driver import main

if __name__ == "__main__":
    sys.exit(main())
