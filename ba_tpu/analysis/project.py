"""Whole-run model: parsed modules, the import graph, donation registry.

The analyzer is two-phase.  Phase one parses every file into a
:class:`ModuleInfo` (tree, alias map, suppression index, import
records).  Phase two builds the cross-file facts single rules cannot
see from one tree:

- the **direct-import graph** over the analyzed set, with
  longest-prefix resolution of ``from X import y`` targets (module or
  symbol — both land on the defining module), powering BA301's
  transitive reachability;
- the **donation registry**: every function the analyzed set jits with
  ``donate_argnums``/``donate_argnames`` (the
  ``@functools.partial(jax.jit, donate_argnums=...)`` decorator idiom
  and the ``g = jax.jit(f, donate_argnums=...)`` rebinding idiom),
  keyed by qualified name so BA201 checks call sites in *other*
  modules through their import aliases.

"Direct-import" is a deliberate semantic: the graph follows modules the
code NAMES (what it could call into), not Python's package-``__init__``
load side effects — ``from ba_tpu.parallel.mesh import shard_map``
executes ``ba_tpu/parallel/__init__.py`` at runtime, but gives the
importer no handle on ``ba_tpu.parallel.pipeline``.  The obs-purity
contract is about code reachability, and this is also what keeps the
rule's verdict stable when ``__init__`` re-export lists churn.
"""

from __future__ import annotations

import ast
import dataclasses

from ba_tpu.analysis.resolver import (
    ImportMap,
    iter_import_aliases,
    module_name,
)
from ba_tpu.analysis.suppress import SuppressionIndex


@dataclasses.dataclass
class ModuleInfo:
    path: str
    display_path: str
    modname: str
    source: str
    tree: ast.Module
    imports: ImportMap
    suppressions: SuppressionIndex
    # (ast node, raw dotted target) per imported alias — the node is the
    # finding anchor for import-graph rules.
    import_records: list

    @classmethod
    def parse(cls, path: str, display_path: str, source: str) -> "ModuleInfo":
        tree = ast.parse(source, filename=display_path)
        modname = module_name(path)
        is_package = path.endswith("__init__.py")
        records = [
            (node, edge)
            for node, _local, _binding, edge in iter_import_aliases(
                tree, modname, is_package
            )
        ]
        return cls(
            path=path,
            display_path=display_path,
            modname=modname,
            source=source,
            tree=tree,
            imports=ImportMap(tree, modname, is_package),
            suppressions=SuppressionIndex(source),
            import_records=records,
        )


@dataclasses.dataclass(frozen=True)
class DonationSpec:
    """Donated positions (and param names, for kwarg call sites) of one
    jitted callable."""

    positions: frozenset
    param_names: tuple = ()

    def donated_params(self) -> set:
        named = {
            self.param_names[i]
            for i in self.positions
            if i < len(self.param_names)
        }
        return named


def _const_positions(node: ast.AST) -> frozenset | None:
    """``donate_argnums=`` value -> positions, if statically constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset([node.value])
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
            vals.append(elt.value)
        return frozenset(vals)
    return None


def _const_names(node: ast.AST) -> list | None:
    """``donate_argnames=`` value -> names, if statically constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            vals.append(elt.value)
        return vals
    return None


def _jit_donation(call: ast.Call, imports: ImportMap, params: list):
    """Donated positions from one ``jax.jit(...)``/``partial(jax.jit,
    ...)`` call, or ``None`` when it donates nothing."""
    fn = imports.resolve(call.func)
    inner_args = call.args
    if fn in ("functools.partial", "partial"):
        if not call.args:
            return None
        if imports.resolve(call.args[0]) not in ("jax.jit", "jax.pjit"):
            return None
        inner_args = call.args[1:]
    elif fn not in ("jax.jit", "jax.pjit"):
        return None
    del inner_args  # positional args carry no donation info
    positions: set = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            got = _const_positions(kw.value)
            if got:
                positions |= got
        elif kw.arg == "donate_argnames":
            names = _const_names(kw.value)
            if names:
                positions |= {
                    i for i, p in enumerate(params) if p in names
                }
    return frozenset(positions) if positions else None


def _param_names(fn: ast.AST) -> list:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


class Project:
    """Everything rules may ask about the analyzed set as a whole."""

    def __init__(self, modules: list):
        self.modules = {m.modname: m for m in modules}
        self.donating: dict = {}
        self._reach_memo: dict = {}
        self._env_constants: dict | None = None
        for m in modules:
            self._collect_donations(m)

    # -- module-level string constants ------------------------------------

    def env_constants(self) -> dict:
        """``{modname.CONST: value}`` for every module-level simple
        string-constant assignment in the analyzed set (ISSUE 18).

        The indirection table BA603 resolves env-variable names
        through: ``WARM_ENV = "BA_TPU_WARM"`` in ``runtime/warmup.py``
        registers as ``ba_tpu.runtime.warmup.WARM_ENV``, so both
        ``os.environ.get(WARM_ENV)`` in the defining module and the
        cross-module ``os.environ.get(obs.aotcache.CACHE_ENV)``
        (alias-resolved by the caller's ImportMap) read back the
        literal.  Only top-level ``NAME = "literal"`` forms count —
        conditional or computed names are not static facts.
        """
        if self._env_constants is None:
            table: dict = {}
            for m in self.modules.values():
                for node in m.tree.body:
                    if not (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)
                    ):
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            table[f"{m.modname}.{tgt.id}"] = (
                                node.value.value
                            )
            self._env_constants = table
        return self._env_constants

    # -- donation registry ------------------------------------------------

    def _collect_donations(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = _param_names(node)
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    pos = _jit_donation(dec, mod.imports, params)
                    if pos:
                        self.donating[f"{mod.modname}.{node.name}"] = (
                            DonationSpec(pos, tuple(params))
                        )
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                pos = _jit_donation(node.value, mod.imports, [])
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.donating[f"{mod.modname}.{tgt.id}"] = (
                                DonationSpec(pos)
                            )

    def donation_for(self, mod: ModuleInfo, func: ast.AST, extra=None):
        """The :class:`DonationSpec` a call's func resolves to, if any.

        Resolution order: local name defined in this module, then the
        alias-resolved qualified name (cross-module call sites), then the
        rule-supplied ``extra`` table (convention-donating wrappers).
        """
        candidates = []
        if isinstance(func, ast.Name):
            candidates.append(f"{mod.modname}.{func.id}")
        dotted = mod.imports.resolve(func)
        if dotted:
            candidates.append(dotted)
        for cand in candidates:
            spec = self.donating.get(cand)
            if spec is None and extra:
                spec = extra.get(cand)
            if spec is not None:
                return spec
        return None

    # -- import graph -----------------------------------------------------

    def resolve_target_module(self, target: str) -> str | None:
        """Longest analyzed-module prefix of a raw import target."""
        parts = target.split(".")
        for k in range(len(parts), 0, -1):
            cand = ".".join(parts[:k])
            if cand in self.modules:
                return cand
        return None

    def reaches(
        self, modname: str, prefix: str, through=None, memo=None
    ) -> bool:
        """True when ``modname``'s direct-import closure names a module
        under ``prefix`` (e.g. ``ba_tpu.obs``).

        ``through`` optionally filters which analyzed modules the BFS
        may traverse INTO (BA301 passes its jitted-tree predicate so
        host-layer modules act as boundaries); the start module is
        always examined.  Callers supplying ``through`` must supply
        their own ``memo`` dict — the default memo is only valid for
        the unfiltered closure.

        Iterative BFS over the analyzed set (import cycles are just
        revisits against ``seen`` — a recursive memo would cache wrong
        negatives inside a cycle).
        """
        if memo is None:
            if through is not None:
                raise ValueError("custom `through` needs its own memo")
            memo = self._reach_memo
        key = (modname, prefix)
        if key in memo:
            return memo[key]
        seen = {modname}
        frontier = [modname]
        hit = False
        while frontier and not hit:
            mod = self.modules.get(frontier.pop())
            if mod is None:
                continue
            for _, target in mod.import_records:
                if target == prefix or target.startswith(prefix + "."):
                    hit = True
                    break
                nxt = self.resolve_target_module(target)
                if (
                    nxt
                    and nxt not in seen
                    and (through is None or through(nxt))
                ):
                    seen.add(nxt)
                    frontier.append(nxt)
        memo[key] = hit
        return hit
