"""Alias and import resolution: the part greps fundamentally cannot do.

Two jobs:

- **Module naming** (:func:`module_name`): map a file path to its dotted
  module name by walking up through ``__init__.py`` parents.  Rules
  scope on module names (``ba_tpu.parallel.pipeline``), not raw paths,
  so a CI mutation check running on a tempdir copy of the tree scopes
  identically.
- **Alias maps** (:class:`ImportMap`): for one parsed module, map every
  locally bound name to the canonical dotted thing it refers to —
  ``import numpy as np`` binds ``np -> numpy``; ``from jax.random
  import split as s`` binds ``s -> jax.random.split``; relative imports
  resolve against the module's own package.  :meth:`ImportMap.resolve`
  then canonicalizes an arbitrary ``Name``/``Attribute`` chain:
  ``np.asarray`` -> ``numpy.asarray``, and the adversarial ``import
  numpy as jnp_like; jnp_like.asarray`` -> ``numpy.asarray`` too, which
  is exactly the case the old ``\\bnp\\.`` grep waved through.

The map is flat per file (later bindings shadow earlier ones, matching
runtime rebinding; function-local imports are included).  That loses
per-scope shadowing precision, which no module in this repository relies
on — and a file that aliases one name to two different modules in
different scopes deserves a human reviewer anyway.
"""

from __future__ import annotations

import ast
import os


def module_name(path: str) -> str:
    """Dotted module name for ``path``, by ``__init__.py`` ancestry.

    ``<anything>/ba_tpu/parallel/pipeline.py`` ->
    ``ba_tpu.parallel.pipeline`` wherever the tree sits (the CI mutation
    check analyzes a tempdir copy).  A free-standing file (``bench.py``,
    ``examples/sweep_campaign.py`` — ``examples/`` has no
    ``__init__.py``) is just its stem.
    """
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if parts[0] == "__init__":
        parts = parts[1:] or [os.path.basename(os.path.dirname(path))]
    return ".".join(reversed(parts))


def iter_import_aliases(tree: ast.AST, modname: str, is_package: bool):
    """``(node, local_name, binding_target, edge_target)`` per alias.

    The ONE place relative imports anchor (``project.ModuleInfo`` and
    :class:`ImportMap` both consume this).  ``level=1`` anchors at the
    containing package: the module's parent for a plain module, the
    module ITSELF for a package ``__init__`` (whose dotted name already
    IS the package — the off-by-one a naive ``parts[:-level]`` makes).

    ``binding_target`` is what the local name resolves to for alias
    canonicalization (for un-aliased ``import a.b.c`` the bound name
    ``a`` IS the root package); ``edge_target`` is the full dotted path
    the statement names, for the import graph.  ``local_name`` is
    ``None`` for a ``*`` import (no binding, but the graph edge to the
    source module is real).
    """
    parts = modname.split(".")
    pkg = parts if is_package else parts[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    yield node, a.asname, a.name, a.name
                else:
                    root = a.name.split(".")[0]
                    yield node, root, root, a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                anchor = pkg[: len(pkg) - (node.level - 1)]
                base = ".".join(anchor + ([base] if base else []))
            if base == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    yield node, None, base, base
                else:
                    target = f"{base}.{a.name}" if base else a.name
                    yield node, a.asname or a.name, target, target


class ImportMap:
    """Local name -> canonical dotted target for one module."""

    def __init__(self, tree: ast.AST, modname: str, is_package: bool = False):
        self.modname = modname
        self.bindings: dict[str, str] = {}
        for _node, local, binding, _edge in iter_import_aliases(
            tree, modname, is_package
        ):
            if local is not None:
                self.bindings[local] = binding

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted form of a ``Name``/``Attribute`` chain.

        ``None`` when the chain bottoms out in something that is not a
        plain name (a call result, a subscript...) or in a name this
        module never imported (a local variable, a builtin).
        """
        attrs: list[str] = []
        while isinstance(node, ast.Attribute):
            attrs.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        target = self.bindings.get(node.id)
        if target is None:
            return None
        return ".".join([target] + list(reversed(attrs)))

    def resolved_refs(self, tree: ast.AST):
        """Every resolvable ``Name``/``Attribute`` chain in ``tree``.

        Yields ``(node, dotted)`` for the OUTERMOST node of each chain —
        ``jr.fold_in`` yields once as ``jax.random.fold_in``, not again
        for the inner ``jr``.
        """
        consumed: set[int] = set()
        for node in ast.walk(tree):
            if id(node) in consumed or not isinstance(
                node, (ast.Attribute, ast.Name)
            ):
                continue
            inner = node
            while isinstance(inner, ast.Attribute):
                consumed.add(id(inner.value))
                inner = inner.value
            dotted = self.resolve(node)
            if dotted is not None:
                yield node, dotted
