"""ba-lint — the AST-based JAX-safety analyzer for this repository.

PRs 1-2 established hard contracts that keep the OM(1)/quorum sweep
engine fast and correct: no host sync inside the parallel round loops,
keys derived ON DEVICE from the ``KeySchedule`` counter, donated
``(state, schedule)`` carries never reused after dispatch, and a
host-only observability layer (nothing from ``ba_tpu.obs`` inside the
jitted ``core``/``ops`` trees).  Until this package those contracts were
enforced by text greps in ``scripts/ci.sh`` — blind to import aliases
(``import numpy as jnp_like`` sails through a ``\\bnp\\.`` grep), unable
to tell ``jnp.asarray`` (device-side) from a locally renamed ``numpy``,
and structurally incapable of expressing the donation or RNG-reuse
rules.  ba-lint turns each invariant into a machine-checked semantic
property over real ``ast`` trees and the real import graph.

Zero dependencies beyond the standard library: running the analyzer
never imports jax (or ba_tpu's runtime modules — ``ba_tpu/__init__.py``
is import-free by design, and tests pin that ``jax`` stays out of
``sys.modules``), so it runs on any host in well under the CI budget.

Usage::

    python -m ba_tpu.analysis ba_tpu/ examples/ bench.py
    python -m ba_tpu.analysis --format json --rules BA101,BA301 path/

Rules (docs/DESIGN.md §12 has the full table and rationale):

====== ========================= =========================================
code   name                      invariant
====== ========================= =========================================
BA101  host-sync-in-hot-path     no ``block_until_ready`` / host-numpy
                                 conversions / ``.item()``/``.tolist()``
                                 / ``float()``/``int()`` coercions of
                                 device values in the parallel round-loop
                                 modules
BA102  host-key-split-in-pipeline no ``jax.random.split`` (and no
                                 ``fold_in`` inside host loops) in
                                 ``parallel/pipeline.py`` — keys come
                                 from the on-device ``KeySchedule``
BA201  use-after-donate          an argument donated to a jitted call is
                                 never read again before rebinding
BA202  rng-key-reuse             the same key name is never consumed by
                                 two sampling calls before rebinding
                                 (deriving does not decorrelate the
                                 original key)
BA301  obs-purity                nothing under ``ba_tpu.core`` or
                                 ``ba_tpu.ops`` reaches ``ba_tpu.obs`` or
                                 calls ``metrics.emit`` (direct-import
                                 closure, alias-resolved)
BA401  dead-import               unused imports (warning severity;
                                 ``__all__`` re-exports honored)
====== ========================= =========================================

Suppressions: append ``# ba-lint: disable=BA101`` (comma-separated
codes, or ``all``) to the flagged line, or put
``# ba-lint: disable-file=BAxxx`` on its own line to silence a code for
the whole file.  Suppressed findings are counted but never fail the run.
"""

from ba_tpu.analysis.base import Finding, Rule, all_rules
from ba_tpu.analysis.driver import main, run_paths

__all__ = ["Finding", "Rule", "all_rules", "main", "run_paths"]
