"""The declared contract registry: record schemas, metric-name rules,
and the documented ``BA_TPU_*`` environment surface (ISSUE 18).

ONE table per contract, consumed from BOTH sides so the static and
dynamic checkers can never drift:

- :data:`RECORD_FAMILIES` — every versioned ``{"event": ..., "v": N}``
  JSONL family the repo emits, with the keys each emit site must spell
  literally and the run-scope/CI flags.  The BA601 rule
  (``rules/contracts.py``) checks every statically-extracted emit site
  against it; ``scripts/check_metrics_schema.py`` imports it as the
  source of its end-to-end ``want`` set and generic required-key
  validation.
- :func:`metric_name_violation` — the ``serve_`` prefix and
  ``_per_shard`` suffix naming rules.  Today these are runtime
  assertions in ``obs/registry.MetricsRegistry._get`` (kept, as
  defense-in-depth); BA602 enforces the SAME predicate at every
  counter/gauge/histogram construction site, and the dynamic schema
  checker re-applies it to snapshot records.
- :data:`ENV_DOCUMENTED` / :data:`ENV_WILDCARDS` — the README
  "Environment knobs" table as data.  BA603 diffs every ``BA_TPU_*``
  read in the analyzed tree (including reads through module-level
  name constants, alias-resolved cross-module) against it, both
  directions; ``tests/test_analysis.py`` pins this module against the
  README table itself, so a row added to one without the other fails.

Zero dependencies, stdlib only, importable without jax — this module
is part of the analyzer and shares its constraints.
"""

from __future__ import annotations

# Current JSONL record schema version (mirrors
# ``ba_tpu.utils.metrics.SCHEMA_VERSION``; tests pin the two equal).
SCHEMA_VERSION = 1


def _family(required=(), run_scoped=False, ci=True):
    return {
        "required": tuple(required),
        "run_scoped": run_scoped,
        "ci": ci,
    }


# Every record family the tree emits.  ``required`` lists the keys an
# emit site must spell as LITERAL dict keys (``run_id`` appears only
# for families whose emitters stamp it explicitly — families relying
# on the sink's run-scope stamping are covered by ``run_scoped`` plus
# the dynamic checker).  ``run_scoped`` mirrors
# ``obs/flight.RUN_SCOPED_EVENTS`` (tests pin the two frozensets
# equal).  ``ci`` marks families the end-to-end schema-check session
# must observe (``scripts/check_metrics_schema.py``'s want set).
RECORD_FAMILIES = {
    "agreement_round": _family(
        ("round", "n", "leader_id", "order", "decision")
    ),
    "pipeline_dispatch": _family(
        ("dispatch", "round_base", "n", "order"), ci=False
    ),
    "agreement_rounds_pipelined": _family(
        ("rounds", "dispatches", "depth", "decision_counts"), ci=False
    ),
    "scenario_campaign": _family(("name", "rounds", "dispatches"), ci=False),
    "search_campaign": _family(
        ("objective", "generations", "campaigns", "found"), ci=False
    ),
    "metrics_snapshot": _family(("metrics",)),
    "compiled_artifact": _family(("fn", "axes", "flops", "bytes_accessed")),
    "recompile": _family(("fn", "axes", "changed", "cross_process")),
    "scenario_checkpoint": _family(
        ("scenario", "round", "rounds", "path", "bytes"), run_scoped=True
    ),
    "recovery": _family(
        ("action", "attempt", "fault", "error", "from_round", "lost_rounds"),
        run_scoped=True,
    ),
    "fault_injected": _family(
        ("kind", "phase", "round", "plan"), run_scoped=True
    ),
    "flight_span": _family(
        ("dispatch", "phase", "lo", "hi", "latency_s", "lag_s"),
        run_scoped=True,
    ),
    "health_snapshot": _family((), run_scoped=True),
    "flight_summary": _family(
        ("run_id", "rounds", "windows", "timeline"), run_scoped=True
    ),
    "request": _family(
        ("id", "kind", "status", "cohort", "tenant", "wall_s")
    ),
    "admission": _family(("decision", "tier", "queue_depth")),
    "shed": _family(("tier", "prev_tier", "queue_depth")),
    "warmup": _family(("phase", "run_id")),
    "sign_ahead": _family(("lo", "hi", "batch", "wall_s")),
    "sign_pool": _family(
        ("run_id", "workers", "requested", "degraded", "rounds"),
        run_scoped=True,
    ),
    "search_generation": _family(
        ("generation", "campaigns", "new_found", "found_total",
         "best_score", "objective"),
        run_scoped=True,
    ),
    "search_found": _family(
        ("generation", "uid", "name", "score", "objective"), run_scoped=True
    ),
    "search_minimized": _family(
        ("generation", "uid", "name", "bit_exact"), run_scoped=True
    ),
    "search_checkpoint": _family(
        ("generation", "path", "found"), run_scoped=True
    ),
    "slo_report": _family(
        ("run_id", "groups", "objectives", "worst_burn"), run_scoped=True
    ),
    "slo_alert": _family(
        ("run_id", "objective", "state", "burn_fast", "burn_slow"),
        run_scoped=True,
    ),
    "autoscale_signal": _family(
        ("run_id", "recommended", "replicas", "burn", "queue_frac"),
        run_scoped=True,
    ),
    # ISSUE 19 fleet-tracing families.  None are ``ci`` (CI_REQUIRED
    # drives the MAIN single-file schema session, where these never
    # appear: clock_anchor/pool_task exist only in sink-DIRECTORY
    # shards, request_trace/fleet_summary are ASSEMBLED offline by
    # ``obs/fleet.py``) — the dedicated sink-dir stage in
    # ``scripts/check_metrics_schema.py`` validates them instead.
    # None are ``run_scoped``: clock_anchor is written before any run
    # exists, pool_task is emitted by a worker process with no run
    # scope, and the assembled families carry ``run_id`` as data
    # copied from the request record, not a sink stamp.
    "clock_anchor": _family(("pid", "shard", "perf_t", "ts"), ci=False),
    "trace_span": _family(
        ("name", "trace_id", "span_id", "parent_id", "t_perf", "dur_s"),
        ci=False,
    ),
    "pool_task": _family(("kind", "rows", "wall_s", "t_perf"), ci=False),
    "request_trace": _family(
        ("trace_id", "request_id", "root_span", "spans", "span_count",
         "processes", "unparented", "critical_path", "attribution_s",
         "wall_s", "within_tol"),
        ci=False,
    ),
    "fleet_summary": _family(
        ("replicas", "cohorts", "requests", "pool_tasks", "traces",
         "worst_burn", "slo_alerts", "autoscale_last"),
        ci=False,
    ),
    # ISSUE 20 fleet-tier families (ba_tpu/fleet/).  ``ci=False`` like
    # the ISSUE 19 set: the MAIN schema session runs one service, no
    # fleet — the dedicated 2-replica router stage in
    # ``scripts/check_metrics_schema.py`` validates these end-to-end.
    # Not ``run_scoped``: the emitters stamp ``run_id`` explicitly as
    # DATA (the manager's fleet id / the campaign's id) wherever it is
    # known, not via a sink run scope.
    "router_route": _family(
        ("request_id", "cohort", "replica", "hops", "rerouted"),
        ci=False,
    ),
    "replica_state": _family(("replica", "state", "prev"), ci=False),
    "migration": _family(("phase", "campaign", "from_replica"), ci=False),
}

# Families that by construction always carry ``run_id`` (must equal
# ``ba_tpu.obs.flight.RUN_SCOPED_EVENTS`` — pinned by a test AND
# asserted at import by scripts/check_metrics_schema.py).
RUN_SCOPED_EVENTS = frozenset(
    name for name, spec in RECORD_FAMILIES.items() if spec["run_scoped"]
)

# Families the end-to-end CI schema session must observe.
CI_REQUIRED_EVENTS = frozenset(
    name for name, spec in RECORD_FAMILIES.items() if spec["ci"]
)


def metric_name_violation(name: str):
    """The instrument-naming contract (DESIGN §8), as one predicate.

    Returns a human-readable reason string, or ``None`` when the name
    conforms.  Mirrored from the runtime assertions in
    ``obs/registry.MetricsRegistry._get`` (which stay, as
    defense-in-depth); BA602 applies this statically at construction
    sites, the dynamic schema checker re-applies it to snapshots.
    """
    if "per_shard" in name and not name.endswith("_per_shard"):
        return (
            f"per-shard metric {name!r} must end with '_per_shard' "
            f"(the suffix is the shard-denominator marker dashboards "
            f"key on)"
        )
    if "serve" in name.split("_") and not name.startswith("serve_"):
        return (
            f"service metric {name!r} must start with 'serve_' "
            f"(the prefix rule groups the serving family in "
            f"dashboards and the schema checker)"
        )
    return None


# The documented environment surface: every ``BA_TPU_*`` variable the
# README "Environment knobs" table names in full.  BA603 flags a
# ``BA_TPU_*`` read absent from this set (used-but-undocumented) and —
# when the analyzed set spans the whole repo — a row here that nothing
# reads (documented-but-unused).  ``ENV_WILDCARDS`` are documented
# name PREFIXES (the ``BA_TPU_BENCH_*`` row).
ENV_DOCUMENTED = frozenset(
    {
        "BA_TPU_PALLAS",
        "BA_TPU_NATIVE",
        "BA_TPU_VERIFY_CHUNK",
        "BA_TPU_METRICS",
        "BA_TPU_TRACE",
        "BA_TPU_TRACE_CONTEXT",
        "BA_TPU_HLO",
        "BA_TPU_XPROF",
        "BA_TPU_RNG",
        "BA_TPU_FUSED_SWEEP",
        "BA_TPU_FUSED_TILE",
        "BA_TPU_FUSED_ROUNDS",
        "BA_TPU_FUSED_UNROLL",
        "BA_TPU_SIGN_DEVICE",
        "BA_TPU_EIG_FUSED",
        "BA_TPU_PIPELINE_DEPTH",
        "BA_TPU_SIGN_POOL",
        "BA_TPU_SIGN_POOL_TIMEOUT_S",
        "BA_TPU_SIGN_CACHE",
        "BA_TPU_SIGN_CACHE_BYTES",
        "BA_TPU_SIGN_COALESCE",
        "BA_TPU_ENGINE",
        "BA_TPU_PIPELINE_ROUNDS",
        "BA_TPU_COMPILE_CACHE",
        "BA_TPU_COMPILE_LEDGER",
        "BA_TPU_RUN_ID",
        "BA_TPU_SUPERVISE_TIMEOUT_S",
        "BA_TPU_MAX_RETRIES",
        "BA_TPU_SERVE_BATCH",
        "BA_TPU_SERVE_QUEUE",
        "BA_TPU_SERVE_WINDOW_S",
        "BA_TPU_SERVE_DEADLINE_S",
        "BA_TPU_SERVE_RETRIES",
        "BA_TPU_SLO",
        "BA_TPU_WARM",
        "BA_TPU_AOT_CACHE",
        "BA_TPU_TESTS_ON_TPU",
        "BA_TPU_EXAMPLE_PLATFORM",
        "BA_TPU_VERIFY_NATIVE",
        "BA_TPU_VERIFY_RLC",
        # Multi-host launch coordinates (examples/multihost_cluster.py).
        "BA_TPU_COORD",
        "BA_TPU_NPROCS",
        "BA_TPU_PROCID",
        # Fused-kernel strategy-chain A/B dial (scenario/strategies.py).
        "BA_TPU_STRATEGY_CHAIN",
        # Bench calibration knobs (bench.py).
        "BA_TPU_HBM_PEAK_GBPS",
        "BA_TPU_FMUL_PROBE_VARIANTS",
        # Span-budget A/B harness (scripts/span_budget_ab.py).
        "BA_TPU_SPAN_AB_ROUNDS",
        "BA_TPU_SPAN_AB_REPS",
        "BA_TPU_SPAN_AB_PLATFORM",
        # Fleet tier (ba_tpu/fleet/replica.py — ISSUE 20).
        "BA_TPU_FLEET_REPLICAS",
        "BA_TPU_FLEET_HOPS",
        "BA_TPU_FLEET_VNODES",
        "BA_TPU_FLEET_ROOT",
    }
)

ENV_WILDCARDS = ("BA_TPU_BENCH_",)


def env_documented(name: str) -> bool:
    """True when ``name`` is covered by the README env table (an exact
    row or a documented wildcard prefix)."""
    return name in ENV_DOCUMENTED or any(
        name.startswith(w) for w in ENV_WILDCARDS
    )
