"""Rule plugin base: findings, severities, and the rule registry.

A rule is a class with a ``code`` (``BAxxx``), a short ``name``, a
``severity`` (``error`` fails the run, ``warning`` reports only), and a
``check_module(mod, project)`` generator yielding :class:`Finding`s.
Registration is a decorator side effect at import time — the driver
imports ``ba_tpu.analysis.rules`` once and every rule module registers
itself, so adding a rule is: drop a module in ``rules/``, decorate the
class, import it from ``rules/__init__``.
"""

from __future__ import annotations

import dataclasses

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit, pinned to a source location.

    ``line``/``col`` are 1-based line and 0-based column, matching both
    ``ast`` node coordinates and the ``path:line:col`` convention
    editors parse.
    """

    code: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.code} [{self.severity}] {self.message}"
        )


class Rule:
    """Base class; subclasses set the class attributes and implement
    ``check_module``."""

    code = "BA000"
    name = "abstract"
    severity = ERROR

    def check_module(self, mod, project):
        """Yield :class:`Finding`s for one parsed module.

        ``mod`` is a :class:`ba_tpu.analysis.project.ModuleInfo`;
        ``project`` is the whole-run :class:`ba_tpu.analysis.project.Project`
        (import graph, donation registry, every other module).
        """
        raise NotImplementedError
        yield  # pragma: no cover

    def finding(self, mod, node, message: str) -> Finding:
        """A :class:`Finding` at ``node``'s location in ``mod``."""
        return Finding(
            code=self.code,
            severity=self.severity,
            path=mod.display_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index the rule by its code."""
    if cls.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _REGISTRY[cls.code] = cls()
    return cls


def all_rules() -> list[Rule]:
    """Every registered rule, code order (loads the plugins on first use)."""
    from ba_tpu.analysis import rules

    rules.load_all()
    return [_REGISTRY[c] for c in sorted(_REGISTRY)]
