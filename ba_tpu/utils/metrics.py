"""Structured metrics: versioned JSON-lines observability for agreement
rounds — the bottom layer of ``ba_tpu.obs``.

The reference's only observability is bare ``print()`` to stdout with
exceptions swallowed (/root/reference/ba.py:255,389; SURVEY.md section 6
rules the new framework must do far better).  Here every agreement round
can emit one machine-readable JSON line — decision, vote counts, quorum
threshold, fault count, wall time — without touching the REPL's
byte-identical stdout contract (metrics go to a file or stderr).

Schema contract: every record carries ``"v": 1`` (the JSONL schema
version — consumers gate on it; ``scripts/ci.sh`` checks every emitted
line parses and carries ``event`` + ``v``) and a ``ts`` wall-clock
timestamp.  ``ts`` is for correlation across processes ONLY: durations
are never derived from it — every ``*_s``/``*elapsed*`` field is
measured with ``time.perf_counter`` (monotonic) at its call site, and
span timing (``obs.trace``) uses ``perf_counter_ns``.

Enable with ``BA_TPU_METRICS=<path>`` (append) or ``BA_TPU_METRICS=-``
(stderr); disabled (zero overhead beyond one dict build, zero file
writes) otherwise.  The file handle opens lazily on first emit, is held
for the sink's lifetime (the first cut reopened the file on EVERY
record — an open/close syscall pair per line, which the pipelined
engine's ``host_work`` lane paid per dispatch), flushes per line so
tail-readers and crashes lose nothing, and closes atexit.  Emission is
thread-safe: the pipelined driver's host lane and the main thread may
interleave emits.

Aggregation (counters/histograms) lives one layer up in
``obs.registry``, which snapshots into this sink as
``{"event": "metrics_snapshot", "v": 1, ...}`` records; device-side
sweeps keep their metrics as tensors (``failover_sweep`` /
``sharded_sweep`` return per-round decision histograms).  ``bench.py
--profile DIR`` adds the jax.profiler device trace and ``--obs DIR`` the
host span trace (``obs.trace``) for timeline-level timing.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import sys
import threading
import time

SCHEMA_VERSION = 1

# -- run correlation (ISSUE 9) ------------------------------------------------
#
# One campaign run = one run_id.  The flight recorder (``obs/flight.py``)
# owns derivation and scoping; the primitive lives HERE — the bottom of
# the obs stack — so the sink can stamp every record emitted while a run
# scope is active and the tracer can ride the same state without a
# layering inversion (obs.trace must never import obs.flight, which
# imports the registry, which imports this module).  A single module
# global, written only by the scope owner on the driving thread; reader
# threads (the retire watchdog timer, host_work lanes) see either the
# current id or None, both correct.

_run_id: str | None = None


def set_run_id(run_id: str | None) -> str | None:
    """Install ``run_id`` as the active run (None clears).  Returns the
    PREVIOUS value so scopes can nest/restore — use
    ``obs.flight.run_scope`` rather than calling this directly."""
    global _run_id
    prev = _run_id
    _run_id = run_id
    return prev


def active_run_id() -> str | None:
    return _run_id


# -- trace context (ISSUE 19) -------------------------------------------------
#
# One causal position = one (trace_id, span_id, parent_id) tuple.  The
# fleet tracer (``obs/trace.py``) owns creation and scoping; the
# primitive lives HERE — the bottom of the obs stack — so the sink can
# stamp every record emitted while a context is active, mirroring the
# run_id placement above.  Unlike ``_run_id`` (a module global with one
# scope owner), contexts are PER-THREAD state: the serve dispatcher, its
# client threads, and the sign-pool staging path each sit at a different
# position in the causal tree at the same instant, so a global would
# cross-stamp them.  Threads do NOT inherit a parent thread's context —
# propagation is always explicit (that is the contract that makes the
# assembled span tree trustworthy).

_trace_local = threading.local()


def set_trace_context(ctx: tuple | None) -> tuple | None:
    """Install ``(trace_id, span_id, parent_id)`` as the calling
    thread's active trace context (None clears).  Returns the PREVIOUS
    value so scopes can nest/restore — use ``obs.trace.scope`` rather
    than calling this directly."""
    prev = getattr(_trace_local, "ctx", None)
    _trace_local.ctx = ctx
    return prev


def active_trace_context() -> tuple | None:
    return getattr(_trace_local, "ctx", None)


# W3C traceparent codec (version 00, sampled flag always 01).  Lives
# here — not in obs/trace — because the sign-pool WORKER processes must
# decode the context that rode the pickle pipe while importing exactly
# the host-tier modules they already import (crypto/pool pulls this
# module; pulling the obs package into a worker would widen its jax-free
# import closure for no reason).
_TRACEPARENT_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value) -> tuple | None:
    """``(trace_id, span_id)`` from a W3C traceparent string, or None
    for anything malformed (a bad external header must degrade to
    "untraced", never raise into the request path)."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None or m.group(1) == "0" * 32 or m.group(2) == "0" * 16:
        return None
    return (m.group(1), m.group(2))


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


# -- sharded directory mode (ISSUE 19) ----------------------------------------


def is_dir_target(target) -> bool:
    """True when ``target`` selects the sharded sink-directory mode: a
    trailing separator always does (the caller's declared intent, even
    before the directory exists); an existing directory does too."""
    if not target or target == "-":
        return False
    return target.endswith(("/", os.sep)) or os.path.isdir(target)


# One shard token per process, chosen at first shard open: the active
# run id when one is pinned, else a random process token.  Module-level
# so a process that reconfigures its sink keeps appending to ONE shard
# (the shard file is the process's stream identity; merging is always
# by the run_id/trace_id FIELDS, never by filename).
_shard_token: str | None = None


def _process_shard_name() -> str:
    global _shard_token
    if _shard_token is None:
        _shard_token = _run_id or f"proc-{os.urandom(4).hex()}"
    return f"{os.getpid()}.{_shard_token}.jsonl"


class MetricsSink:
    """Append-mode JSON-lines emitter; a falsy target disables it."""

    def __init__(self, target: str | None = None):
        self.target = (
            target if target is not None else os.environ.get("BA_TPU_METRICS")
        )
        self._fh = None
        self._lock = threading.Lock()
        self._atexit_registered = False
        # Resolved shard path when the target is a directory (ISSUE 19);
        # None until the lazy open, and for plain file/stderr targets.
        self.shard_path: str | None = None

    @property
    def enabled(self) -> bool:
        return bool(self.target)

    def file_path(self) -> str | None:
        """The actual JSONL file this sink appends to: the shard inside
        a directory target (once opened), the file itself otherwise;
        None for stderr/disabled sinks and unopened directory targets."""
        if not self.target or self.target == "-":
            return None
        if is_dir_target(self.target):
            return self.shard_path
        return self.target

    def emit(self, record: dict) -> None:
        if not self.target:
            return
        record.setdefault("v", SCHEMA_VERSION)
        record.setdefault("ts", round(time.time(), 3))
        if _run_id is not None:
            # Run correlation (ISSUE 9): every record emitted while a
            # flight-recorder run scope is active carries the run_id, so
            # the FlightLog assembler can join span/checkpoint/recovery/
            # recompile records of ONE campaign out of a shared stream.
            record.setdefault("run_id", _run_id)
        ctx = getattr(_trace_local, "ctx", None)
        if ctx is not None:
            # Causal correlation (ISSUE 19): records emitted inside an
            # active trace scope carry the thread's causal position, so
            # obs/fleet can assemble one cross-process span tree.  The
            # context RIDES the emit — no record is ever added just to
            # carry it (the zero-added-sync contract).
            record.setdefault("trace_id", ctx[0])
            record.setdefault("span_id", ctx[1])
            if ctx[2] is not None:
                record.setdefault("parent_id", ctx[2])
        line = json.dumps(record)
        # Telemetry must never kill the agreement path: ANY OSError —
        # failed open, ENOSPC mid-write, EPIPE on a closed stderr —
        # warns once, disables the sink, and lets the protocol continue.
        # (The reference's sin was the inverse, swallowing everything
        # silently, so the single warning is loud.)
        with self._lock:
            if not self.target:  # _disable() raced us; re-check held
                return
            if self._fh is None:
                if self.target == "-":
                    self._fh = sys.stderr  # borrowed: close() skips it
                else:
                    anchor = None
                    try:
                        path = self.target
                        if is_dir_target(path):
                            # Sharded directory mode (ISSUE 19): one
                            # shard per process, named by the grammar
                            # <pid>.<token>.jsonl, opened with a clock
                            # anchor as its first line of this session —
                            # the perf_counter<->unix pair obs/fleet
                            # uses to align per-process monotonic
                            # clocks at merge time.
                            os.makedirs(path, exist_ok=True)
                            shard = _process_shard_name()
                            self.shard_path = os.path.join(path, shard)
                            path = self.shard_path
                            anchor = {
                                "event": "clock_anchor",
                                "v": SCHEMA_VERSION,
                                "pid": os.getpid(),
                                "shard": shard,
                                "perf_t": time.perf_counter(),
                                # 6 dp: alignment precision is the point
                                # of this record (ordinary records round
                                # ts to 3 dp for size).
                                "ts": round(time.time(), 6),
                            }
                        else:
                            parent = os.path.dirname(path)
                            if parent:
                                os.makedirs(parent, exist_ok=True)
                        self._fh = open(path, "a")
                        if anchor is not None:
                            self._fh.write(json.dumps(anchor) + "\n")
                    except OSError as e:
                        self._disable(e)
                        return
                    if not self._atexit_registered:
                        atexit.register(self.close)
                        self._atexit_registered = True
            try:
                # One write call per record (line + newline together):
                # concurrent emitters must not interleave mid-line.
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                self._disable(e)

    def _owns_fh(self) -> bool:
        return self._fh is not None and self.target != "-"

    def _disable(self, err: OSError) -> None:
        """Warn once and turn the sink off (called under ``_lock``)."""
        owned = self._owns_fh()
        target, self.target = self.target, None
        if self._fh is not None:
            if owned:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = None
        try:
            print(
                f"ba_tpu.utils.metrics: sink {target!r} failed ({err}); "
                f"metrics disabled",
                file=sys.stderr,
            )
        except OSError:  # stderr itself is gone — nothing left to say
            pass

    def close(self) -> None:
        """Close the held handle (idempotent; emit lazily reopens).

        The ``-`` target's handle is BORROWED stderr — dropped from the
        sink but never actually closed."""
        with self._lock:
            if self._owns_fh():
                try:
                    self._fh.close()
                except OSError:  # pragma: no cover - target fs went away
                    pass
            self._fh = None


_default: MetricsSink | None = None


def default_sink() -> MetricsSink:
    """Process-wide sink configured from the environment (lazily)."""
    global _default
    if _default is None:
        _default = MetricsSink()
    return _default


def configure(target: str | None) -> MetricsSink:
    """Point the process-wide sink at ``target`` (closing any old handle).

    The programmatic counterpart of ``BA_TPU_METRICS`` — ``bench.py
    --obs DIR`` routes the sink to ``DIR/metrics.jsonl`` with this.
    """
    global _default
    if _default is not None:
        _default.close()
    _default = MetricsSink(target)
    return _default


def emit(record: dict) -> None:
    default_sink().emit(record)
