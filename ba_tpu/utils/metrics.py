"""Structured metrics: JSON-lines observability for agreement rounds.

The reference's only observability is bare ``print()`` to stdout with
exceptions swallowed (/root/reference/ba.py:255,389; SURVEY.md section 6
rules the new framework must do far better).  Here every agreement round
can emit one machine-readable JSON line — decision, vote counts, quorum
threshold, fault count, wall time — without touching the REPL's
byte-identical stdout contract (metrics go to a file or stderr).

Enable with ``BA_TPU_METRICS=<path>`` (append) or ``BA_TPU_METRICS=-``
(stderr); disabled (zero overhead beyond one dict build) otherwise.
Device-side sweeps keep their metrics as tensors (``failover_sweep`` /
``sharded_sweep`` return per-round decision histograms); this sink is the
host-side shell's counterpart.  ``bench.py --profile DIR`` adds the
jax.profiler trace for kernel-level timing.
"""

from __future__ import annotations

import json
import os
import sys
import time


class MetricsSink:
    """Append-mode JSON-lines emitter; a falsy target disables it."""

    def __init__(self, target: str | None = None):
        self.target = (
            target if target is not None else os.environ.get("BA_TPU_METRICS")
        )

    @property
    def enabled(self) -> bool:
        return bool(self.target)

    def emit(self, record: dict) -> None:
        if not self.target:
            return
        record.setdefault("ts", round(time.time(), 3))
        line = json.dumps(record)
        if self.target == "-":
            print(line, file=sys.stderr, flush=True)
        else:
            with open(self.target, "a") as fh:
                fh.write(line + "\n")


_default: MetricsSink | None = None


def default_sink() -> MetricsSink:
    """Process-wide sink configured from the environment (lazily)."""
    global _default
    if _default is None:
        _default = MetricsSink()
    return _default


def emit(record: dict) -> None:
    default_sink().emit(record)
