"""Structured metrics: versioned JSON-lines observability for agreement
rounds — the bottom layer of ``ba_tpu.obs``.

The reference's only observability is bare ``print()`` to stdout with
exceptions swallowed (/root/reference/ba.py:255,389; SURVEY.md section 6
rules the new framework must do far better).  Here every agreement round
can emit one machine-readable JSON line — decision, vote counts, quorum
threshold, fault count, wall time — without touching the REPL's
byte-identical stdout contract (metrics go to a file or stderr).

Schema contract: every record carries ``"v": 1`` (the JSONL schema
version — consumers gate on it; ``scripts/ci.sh`` checks every emitted
line parses and carries ``event`` + ``v``) and a ``ts`` wall-clock
timestamp.  ``ts`` is for correlation across processes ONLY: durations
are never derived from it — every ``*_s``/``*elapsed*`` field is
measured with ``time.perf_counter`` (monotonic) at its call site, and
span timing (``obs.trace``) uses ``perf_counter_ns``.

Enable with ``BA_TPU_METRICS=<path>`` (append) or ``BA_TPU_METRICS=-``
(stderr); disabled (zero overhead beyond one dict build, zero file
writes) otherwise.  The file handle opens lazily on first emit, is held
for the sink's lifetime (the first cut reopened the file on EVERY
record — an open/close syscall pair per line, which the pipelined
engine's ``host_work`` lane paid per dispatch), flushes per line so
tail-readers and crashes lose nothing, and closes atexit.  Emission is
thread-safe: the pipelined driver's host lane and the main thread may
interleave emits.

Aggregation (counters/histograms) lives one layer up in
``obs.registry``, which snapshots into this sink as
``{"event": "metrics_snapshot", "v": 1, ...}`` records; device-side
sweeps keep their metrics as tensors (``failover_sweep`` /
``sharded_sweep`` return per-round decision histograms).  ``bench.py
--profile DIR`` adds the jax.profiler device trace and ``--obs DIR`` the
host span trace (``obs.trace``) for timeline-level timing.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time

SCHEMA_VERSION = 1

# -- run correlation (ISSUE 9) ------------------------------------------------
#
# One campaign run = one run_id.  The flight recorder (``obs/flight.py``)
# owns derivation and scoping; the primitive lives HERE — the bottom of
# the obs stack — so the sink can stamp every record emitted while a run
# scope is active and the tracer can ride the same state without a
# layering inversion (obs.trace must never import obs.flight, which
# imports the registry, which imports this module).  A single module
# global, written only by the scope owner on the driving thread; reader
# threads (the retire watchdog timer, host_work lanes) see either the
# current id or None, both correct.

_run_id: str | None = None


def set_run_id(run_id: str | None) -> str | None:
    """Install ``run_id`` as the active run (None clears).  Returns the
    PREVIOUS value so scopes can nest/restore — use
    ``obs.flight.run_scope`` rather than calling this directly."""
    global _run_id
    prev = _run_id
    _run_id = run_id
    return prev


def active_run_id() -> str | None:
    return _run_id


class MetricsSink:
    """Append-mode JSON-lines emitter; a falsy target disables it."""

    def __init__(self, target: str | None = None):
        self.target = (
            target if target is not None else os.environ.get("BA_TPU_METRICS")
        )
        self._fh = None
        self._lock = threading.Lock()
        self._atexit_registered = False

    @property
    def enabled(self) -> bool:
        return bool(self.target)

    def emit(self, record: dict) -> None:
        if not self.target:
            return
        record.setdefault("v", SCHEMA_VERSION)
        record.setdefault("ts", round(time.time(), 3))
        if _run_id is not None:
            # Run correlation (ISSUE 9): every record emitted while a
            # flight-recorder run scope is active carries the run_id, so
            # the FlightLog assembler can join span/checkpoint/recovery/
            # recompile records of ONE campaign out of a shared stream.
            record.setdefault("run_id", _run_id)
        line = json.dumps(record)
        # Telemetry must never kill the agreement path: ANY OSError —
        # failed open, ENOSPC mid-write, EPIPE on a closed stderr —
        # warns once, disables the sink, and lets the protocol continue.
        # (The reference's sin was the inverse, swallowing everything
        # silently, so the single warning is loud.)
        with self._lock:
            if not self.target:  # _disable() raced us; re-check held
                return
            if self._fh is None:
                if self.target == "-":
                    self._fh = sys.stderr  # borrowed: close() skips it
                else:
                    try:
                        parent = os.path.dirname(self.target)
                        if parent:
                            os.makedirs(parent, exist_ok=True)
                        self._fh = open(self.target, "a")
                    except OSError as e:
                        self._disable(e)
                        return
                    if not self._atexit_registered:
                        atexit.register(self.close)
                        self._atexit_registered = True
            try:
                # One write call per record (line + newline together):
                # concurrent emitters must not interleave mid-line.
                self._fh.write(line + "\n")
                self._fh.flush()
            except OSError as e:
                self._disable(e)

    def _owns_fh(self) -> bool:
        return self._fh is not None and self.target != "-"

    def _disable(self, err: OSError) -> None:
        """Warn once and turn the sink off (called under ``_lock``)."""
        owned = self._owns_fh()
        target, self.target = self.target, None
        if self._fh is not None:
            if owned:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = None
        try:
            print(
                f"ba_tpu.utils.metrics: sink {target!r} failed ({err}); "
                f"metrics disabled",
                file=sys.stderr,
            )
        except OSError:  # stderr itself is gone — nothing left to say
            pass

    def close(self) -> None:
        """Close the held handle (idempotent; emit lazily reopens).

        The ``-`` target's handle is BORROWED stderr — dropped from the
        sink but never actually closed."""
        with self._lock:
            if self._owns_fh():
                try:
                    self._fh.close()
                except OSError:  # pragma: no cover - target fs went away
                    pass
            self._fh = None


_default: MetricsSink | None = None


def default_sink() -> MetricsSink:
    """Process-wide sink configured from the environment (lazily)."""
    global _default
    if _default is None:
        _default = MetricsSink()
    return _default


def configure(target: str | None) -> MetricsSink:
    """Point the process-wide sink at ``target`` (closing any old handle).

    The programmatic counterpart of ``BA_TPU_METRICS`` — ``bench.py
    --obs DIR`` routes the sink to ``DIR/metrics.jsonl`` with this.
    """
    global _default
    if _default is not None:
        _default.close()
    _default = MetricsSink(target)
    return _default


def emit(record: dict) -> None:
    default_sink().emit(record)
