"""Checkpoint / resume: durable snapshots of cluster and sweep state.

The reference keeps everything in memory and loses it on exit
(SURVEY.md section 6: checkpoint/resume "Absent"; state is cleared between
rounds, ba.py:291-293).  This framework makes both of its state shapes
durable:

- the interactive cluster (roster ids/ports/fault flags, leader, round
  counter) serializes to JSON — ``python -m ba_tpu.runtime.main N
  --state FILE`` restores it at startup and saves on ``Exit``;
- batched ``SimState`` tensors (and any dict of arrays a sweep produces)
  serialize to ``.npz`` for long sweep campaigns.

Plain JSON/NPZ rather than orbax: the state is kilobytes of host-side
metadata plus dense arrays with no sharding to preserve (re-sharding on
load is one device_put), so the dependency would buy nothing.

All writes are atomic (temp file + ``os.replace``): a crash mid-save — the
exact event checkpointing exists to survive — must never corrupt the only
good copy.  Cluster snapshots also record the backend configuration
(protocol / m / signed / backend class) and ``restore_cluster`` refuses a
mismatch, so a resumed campaign cannot silently continue under different
protocol semantics.

**Carry checkpoints** (ISSUE 6): the third durable shape is the
pipelined engine's donated carry — SimState + KeySchedule (key data and
round counter) + the scenario counter block + the live strategy plane +
the round cursor — serialized as ONE versioned ``.npz`` whose
``__meta__`` entry holds a JSON header.  This is the repo's single
checkpoint format: ``parallel/pipeline.py`` writes it at its retire
points (zero added sync) and resumes from it bit-exactly,
``examples/sweep_campaign.py`` chunks long campaigns over it, and
``python -m ba_tpu.scenario`` validates the schema jax-free (this
module's reader is numpy + stdlib only — jax appears only inside
``load_sim_state``).
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile

import numpy as np

CARRY_CHECKPOINT_FORMAT = "ba_tpu.carry_checkpoint"
CARRY_CHECKPOINT_VERSION = 1

# SimState fields in carry order, then the KeySchedule pair; `counters`
# and `strategy` ride only on scenario / with_counters carries (the
# meta header says which).
CARRY_STATE_FIELDS = ("order", "leader", "faulty", "alive", "ids")
CARRY_SCHED_FIELDS = ("key_data", "counter")


def _atomic_write(path: str, write_fn, durable: bool = True) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        # fsync BEFORE the rename: os.replace is atomic against other
        # processes (a reader sees old-or-new, never a torn file — the
        # mid-write SIGKILL test pins it), but only the fsync makes the
        # rename crash-durable against a whole-SYSTEM crash: without it
        # the journal may commit the rename before the data blocks, and
        # the "complete" file after power loss reads as garbage.
        # ``durable=False`` skips it for DERIVED data (the supervisor's
        # rows sidecars): a reader still never sees a torn file, and a
        # power-loss-garbled sidecar is detected by its own schema check
        # and costs only assembled history, never the resume.
        if durable:
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        os.replace(tmp, path)
        if durable:
            # The rename itself lives in the DIRECTORY: without fsyncing
            # the parent, power loss can forget the new name even though
            # the data blocks are safe — and a just-pruned older
            # checkpoint may be the one that survived.  Best-effort:
            # platforms that refuse directory fds degrade to the
            # pre-fsync guarantee instead of failing the write.
            try:
                dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
            except OSError:
                pass
            else:
                try:
                    os.fsync(dfd)
                except OSError:
                    pass
                finally:
                    os.close(dfd)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save_sim_state(path: str, state, **extra_arrays) -> None:
    """SimState (+ any extra named arrays) -> one .npz file."""

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                order=np.asarray(state.order),
                leader=np.asarray(state.leader),
                faulty=np.asarray(state.faulty),
                alive=np.asarray(state.alive),
                ids=np.asarray(state.ids),
                **{k: np.asarray(v) for k, v in extra_arrays.items()},
            )

    _atomic_write(path, write)


def load_sim_state(path: str):
    """.npz -> (SimState, dict of extra arrays) on the default device."""
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState

    with np.load(path) as data:
        fields = {k: data[k] for k in data.files}
    state = SimState(
        order=jnp.asarray(fields.pop("order")),
        leader=jnp.asarray(fields.pop("leader")),
        faulty=jnp.asarray(fields.pop("faulty")),
        alive=jnp.asarray(fields.pop("alive")),
        ids=jnp.asarray(fields.pop("ids")),
    )
    return state, fields


def _backend_config(cluster) -> dict:
    """Protocol-defining backend attributes (class + flags when present)."""
    b = cluster.backend
    return {
        "backend": type(b).__name__,
        "protocol": getattr(b, "protocol", "om"),
        "m": getattr(b, "m", 1),
        "signed": getattr(b, "signed", False),
    }


def save_cluster(path: str, cluster) -> None:
    """Interactive Cluster -> JSON (roster, leader, round counter, seed,
    backend configuration)."""
    doc = {
        "version": 1,
        "seed": cluster.seed,
        "round": cluster._round,
        "next_id": cluster._next_id,
        "leader_id": cluster.leader_id,
        "config": _backend_config(cluster),
        "generals": [
            {"id": g.id, "port": g.port, "faulty": g.faulty, "alive": g.alive}
            for g in cluster.generals
        ],
    }
    def write(tmp):
        with open(tmp, "w") as fh:
            json.dump(doc, fh)

    _atomic_write(path, write)


def restore_cluster(path: str, cluster) -> None:
    """Load a JSON snapshot into an existing Cluster (backend unchanged).

    Refuses a snapshot whose recorded backend configuration differs from
    the running cluster's — a resumed campaign must not silently switch
    protocol, recursion depth, signing, or engine.
    """
    from ba_tpu.runtime.cluster import General

    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != 1:
        raise ValueError(f"unknown cluster snapshot version in {path!r}")
    want = doc.get("config")
    have = _backend_config(cluster)
    if want is not None and want != have:
        raise ValueError(
            f"snapshot {path!r} was taken with backend config {want}, "
            f"but this run uses {have}; relaunch with matching flags"
        )
    cluster.seed = doc["seed"]
    cluster._round = doc["round"]
    cluster._next_id = doc["next_id"]
    cluster.leader_id = doc["leader_id"]
    cluster.generals = [General(**g) for g in doc["generals"]]


# -- carry checkpoints (the pipelined engine's donated carry, durable) --------


SEARCH_STATE_FORMAT = "ba_tpu.search_state"
SEARCH_STATE_VERSION = 1


def _search_state_digest(state: dict) -> str:
    """sha256 over the canonical JSON of a search-state payload — the
    pure-JSON twin of :func:`content_digest` (search state is plain
    data, no arrays to hash)."""
    return hashlib.sha256(
        json.dumps(state, sort_keys=True).encode()
    ).hexdigest()


def write_search_checkpoint(path: str, state: dict, **meta) -> None:
    """Search-state payload + meta -> one atomic versioned JSON file.

    The adversary search engine's checkpoint (ISSUE 15): the hunt's
    resumable state is plain JSON-able data (seed, generation cursor,
    uid counter, elites, findings), so the repo's checkpoint discipline
    — versioned format header, computed content digest, atomic write —
    applies without the ``.npz`` array machinery.  ``meta`` keys ride
    the header next to the engine's own (``run_id`` in particular).
    """
    doc = {
        "format": SEARCH_STATE_FORMAT,
        "v": SEARCH_STATE_VERSION,
        **meta,
        # Last so caller meta can never mask them: the digest is
        # computed, not declared (the write_carry_checkpoint rule).
        "sha256": _search_state_digest(state),
        "state": state,
    }

    def write(tmp):
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")

    _atomic_write(path, write)


def read_search_checkpoint(path: str):
    """JSON file -> ``(meta, state dict)`` after schema checks.

    Raises ``ValueError`` on anything that could silently resume the
    wrong hunt: unknown format/version, a missing/non-object payload,
    or a content-digest mismatch.  stdlib-only — the jax-free search
    CLI validates checkpoints through this reader.
    """
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except json.JSONDecodeError as e:
        raise ValueError(f"{path!r}: not valid JSON ({e})") from None
    if not isinstance(doc, dict) or doc.get("format") != SEARCH_STATE_FORMAT:
        raise ValueError(
            f"{path!r}: format "
            f"{doc.get('format') if isinstance(doc, dict) else doc!r} "
            f"is not {SEARCH_STATE_FORMAT!r}"
        )
    if doc.get("v") != SEARCH_STATE_VERSION:
        raise ValueError(
            f"{path!r}: search state version {doc.get('v')!r} "
            f"(this build reads v{SEARCH_STATE_VERSION})"
        )
    state = doc.get("state")
    if not isinstance(state, dict):
        raise ValueError(f"{path!r}: search state payload missing")
    want = doc.get("sha256")
    got = _search_state_digest(state)
    if want != got:
        raise ValueError(
            f"{path!r}: content digest mismatch (stored "
            f"{str(want)[:12]}..., recomputed {got[:12]}...) — the "
            f"search checkpoint is corrupt; refusing to resume from it"
        )
    meta = {k: v for k, v in doc.items() if k not in ("state", "sha256")}
    return meta, state


def content_digest(arrays: dict) -> str:
    """sha256 over every array's name, dtype, shape and raw bytes.

    The end-to-end integrity check for carry checkpoints (ISSUE 7): zip
    CRCs only cover what the zip reader happens to decompress, while
    this digest is recomputed by ``read_carry_checkpoint`` over the
    arrays as loaded — any silent corruption between writer and reader
    (bit rot, a chaos-injected flip, a buggy transfer) fails validation
    instead of resuming a subtly wrong campaign.
    """
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def write_carry_checkpoint(path: str, arrays: dict, meta: dict) -> None:
    """Host arrays + JSON-able meta -> one atomic versioned ``.npz``.

    ``arrays`` must already be host numpy (the engine fetches the carry
    copy inside its existing retire sync — no device handles reach this
    layer).  ``meta`` is stamped with the format/version keys plus the
    ``sha256`` content digest (:func:`content_digest`) and stored as the
    ``__meta__`` entry (a unicode scalar: loads without pickle).
    """
    meta = {
        "format": CARRY_CHECKPOINT_FORMAT,
        "v": CARRY_CHECKPOINT_VERSION,
        **meta,
        # Last so caller meta can never mask it: the digest is computed,
        # not declared.
        "sha256": content_digest(arrays),
    }

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                __meta__=np.asarray(json.dumps(meta)),
                **{k: np.asarray(v) for k, v in arrays.items()},
            )

    _atomic_write(path, write)


def read_carry_checkpoint(path: str):
    """``.npz`` -> ``(meta, {name: numpy array})`` after schema checks.

    Raises ``ValueError`` on anything that could silently resume the
    wrong campaign: unknown format/version, missing carry arrays, a
    round cursor that disagrees with the stored KeySchedule counter, or
    counters/strategy shapes inconsistent with the state.  Numpy +
    stdlib only — ``python -m ba_tpu.scenario`` runs this jax-free.
    """
    try:
        with np.load(path, allow_pickle=False) as data:
            fields = {k: data[k] for k in data.files}
    except zipfile.BadZipFile as e:
        # np.load raises BadZipFile (not OSError/ValueError) on a
        # truncated/half-written file — normalize it so callers keeping
        # this function's documented ValueError contract (the jax-free
        # CLI validator, resume= error paths) see every corruption the
        # same way.
        raise ValueError(f"{path!r}: not a readable .npz ({e})") from None
    raw = fields.pop("__meta__", None)
    if raw is None:
        raise ValueError(f"{path!r}: no __meta__ entry — not a carry checkpoint")
    try:
        meta = json.loads(str(raw))
    except ValueError as e:
        raise ValueError(f"{path!r}: unparseable __meta__ ({e})") from None
    if meta.get("format") != CARRY_CHECKPOINT_FORMAT:
        raise ValueError(
            f"{path!r}: format {meta.get('format')!r} is not "
            f"{CARRY_CHECKPOINT_FORMAT!r}"
        )
    if meta.get("v") != CARRY_CHECKPOINT_VERSION:
        raise ValueError(
            f"{path!r}: carry checkpoint version {meta.get('v')!r} "
            f"(this build reads v{CARRY_CHECKPOINT_VERSION})"
        )
    want_digest = meta.get("sha256")
    if want_digest is not None:
        # End-to-end integrity (ISSUE 7): recompute the content digest
        # over the arrays as LOADED.  Verified when present so pre-digest
        # checkpoints still read; every checkpoint this build writes
        # carries one.
        got = content_digest(fields)
        if got != want_digest:
            raise ValueError(
                f"{path!r}: content digest mismatch (stored "
                f"{want_digest[:12]}..., recomputed {got[:12]}...) — the "
                f"checkpoint is corrupt; refusing to resume from it"
            )
    missing = [
        k for k in CARRY_STATE_FIELDS + CARRY_SCHED_FIELDS if k not in fields
    ]
    if missing:
        raise ValueError(f"{path!r}: missing carry arrays {missing}")
    rnd = meta.get("round")
    if not isinstance(rnd, int) or rnd < 0:
        raise ValueError(f"{path!r}: bad round cursor {rnd!r}")
    if int(fields["counter"]) != rnd:
        raise ValueError(
            f"{path!r}: round cursor {rnd} disagrees with the KeySchedule "
            f"counter {int(fields['counter'])} — the carry would replay "
            f"the wrong key stream"
        )
    if fields["faulty"].shape != fields["alive"].shape or fields[
        "faulty"
    ].ndim != 2:
        raise ValueError(
            f"{path!r}: state planes malformed "
            f"(faulty {fields['faulty'].shape}, alive {fields['alive'].shape})"
        )
    layout = meta.get("shard_layout")
    if layout is not None and (
        not isinstance(layout, dict)
        or not layout
        or not all(
            isinstance(k, str) and isinstance(v, int) and v >= 1
            for k, v in layout.items()
        )
    ):
        # Provenance only (the arrays are canonical / device-count-free,
        # ISSUE 8), but a malformed layout means a corrupted or
        # hand-edited header — refuse like any other schema break.
        raise ValueError(
            f"{path!r}: malformed shard_layout {layout!r} (want "
            f"{{axis: devices >= 1}})"
        )
    names = meta.get("counter_names")
    if "counters" in fields:
        if not isinstance(names, list) or len(names) != fields[
            "counters"
        ].shape[-1]:
            raise ValueError(
                f"{path!r}: counters block has {fields['counters'].shape} "
                f"entries but counter_names is {names!r}"
            )
    if "strategy" in fields and fields["strategy"].shape != fields[
        "faulty"
    ].shape:
        raise ValueError(
            f"{path!r}: strategy plane {fields['strategy'].shape} does not "
            f"match the state {fields['faulty'].shape}"
        )
    if meta.get("scenario") and (
        "counters" not in fields or "strategy" not in fields
    ):
        raise ValueError(
            f"{path!r}: scenario carry without counters/strategy planes"
        )
    return meta, fields


def validate_carry_checkpoint(path: str) -> dict:
    """Schema-check a carry checkpoint; returns its meta header.

    The jax-free CI entry (``python -m ba_tpu.scenario <ckpt.npz>``)
    and anything else that wants to vet a checkpoint without paying a
    backend init.
    """
    meta, _ = read_carry_checkpoint(path)
    return meta


# -- checkpoint retention + recovery scanning (ISSUE 7) -----------------------
#
# A ``{round}``-templated checkpoint path names a FAMILY of files; the
# helpers below are the numpy/stdlib-only machinery the engine's
# ``checkpoint_keep_last=`` retention and the execution supervisor's
# automatic recovery share: enumerate the family, prune it, and find the
# newest member that still validates — quarantining corrupt ones to
# ``<path>.corrupt`` so a damaged file is diagnosed once instead of
# blocking every future resume scan.

# Sidecar suffixes that travel with a checkpoint (the supervisor's
# campaign-history rows ride next to the carry): retention and
# quarantine move/remove them together with their checkpoint.
CHECKPOINT_COMPANION_SUFFIXES = (".rows.npz",)


def checkpoint_paths(template: str) -> list:
    """All on-disk checkpoints of a ``{round}``-templated path, as
    ``[(round, path)]`` sorted oldest-first by round cursor.

    Matching is purely lexical (the basename's ``{round}`` slot must be
    digits), so ``.tmp.<pid>`` strays from killed writers and
    ``.corrupt`` quarantines never appear in the family.
    """
    if "{round}" not in template:
        raise ValueError(f"checkpoint template {template!r} has no {{round}}")
    dirname, base = os.path.split(template)
    if "{round}" in dirname:
        raise ValueError(
            f"{{round}} must be in the filename, not the directory "
            f"({template!r})"
        )
    prefix, suffix = base.split("{round}", 1)
    out = []
    try:
        names = os.listdir(dirname or ".")
    except OSError:
        return []
    for name in names:
        if not (name.startswith(prefix) and name.endswith(suffix)):
            continue
        mid = name[len(prefix):len(name) - len(suffix)]
        if mid.isdigit():
            out.append((int(mid), os.path.join(dirname, name)))
    out.sort()
    return out


def _remove_companions(path: str) -> None:
    for suffix in CHECKPOINT_COMPANION_SUFFIXES:
        side = path + suffix
        if os.path.exists(side):
            try:
                os.remove(side)
            except OSError:
                pass


def prune_checkpoints(
    template: str, keep_last: int, companions: bool = True
) -> list:
    """Delete all but the ``keep_last`` newest checkpoints of a
    ``{round}``-templated family (companion sidecars go with them unless
    ``companions=False`` — the execution supervisor keeps its rows
    sidecars: they ARE the campaign history, O(R) total by design,
    while the carry checkpoints they ride beside are point-in-time and
    safely bounded).  Returns the removed paths.  Never raises on a
    racing writer/reader — retention is hygiene, not correctness.
    """
    if keep_last < 1:
        raise ValueError(f"keep_last={keep_last} must be >= 1")
    removed = []
    for _, path in checkpoint_paths(template)[:-keep_last]:
        try:
            os.remove(path)
        except OSError:
            continue
        if companions:
            _remove_companions(path)
        removed.append(path)
    return removed


def quarantine_checkpoint(path: str) -> str:
    """Move a corrupt checkpoint (and companions) to ``<path>.corrupt``.

    ``os.replace`` so a half-quarantined state cannot exist; the renamed
    file keeps its bytes for post-mortem.  Returns the quarantine path.
    """
    target = path + ".corrupt"
    os.replace(path, target)
    for suffix in CHECKPOINT_COMPANION_SUFFIXES:
        side = path + suffix
        if os.path.exists(side):
            try:
                os.replace(side, side + ".corrupt")
            except OSError:
                pass
    return target


def newest_valid_checkpoint(
    path_or_template: str,
    quarantine: bool = True,
    below: int | None = None,
    accept=None,
):
    """The newest checkpoint that passes full schema+digest validation.

    Scans a ``{round}``-templated family newest-first (a plain path is a
    family of one); each member that fails :func:`read_carry_checkpoint`
    is quarantined to ``<path>.corrupt`` (when ``quarantine``) and the
    scan FALLS BACK to the next-newest instead of failing — the recovery
    contract: one torn or rotten file costs one checkpoint interval, not
    the campaign.  ``below`` skips members at round cursors >= it
    WITHOUT quarantining (they are valid, just not resumable — the
    engine refuses a cursor at the campaign end, and a completed
    campaign's final checkpoint must not poison its own rerun).
    ``accept(meta) -> bool`` skips non-matching members the same way —
    valid-but-not-ours (the supervisor's campaign-fingerprint filter),
    so a foreign family sharing the path is stepped over, never
    quarantined.  Returns ``(path, meta)`` or ``None`` when nothing
    valid remains.
    """
    if "{round}" in path_or_template:
        members = checkpoint_paths(path_or_template)
        if below is not None:
            members = [(r, p) for r, p in members if r < below]
        candidates = [p for _, p in reversed(members)]
    else:
        candidates = [path_or_template] if os.path.exists(path_or_template) else []
    for path in candidates:
        try:
            meta = validate_carry_checkpoint(path)
        except (OSError, ValueError):
            if quarantine:
                try:
                    quarantine_checkpoint(path)
                except OSError:
                    pass
            continue
        if below is not None and meta.get("round", 0) >= below:
            # Valid but at/after the cut (a plain path, or a templated
            # member whose filename lied about its cursor): skip, never
            # quarantine.
            continue
        if accept is not None and not accept(meta):
            continue
        return path, meta
    return None
