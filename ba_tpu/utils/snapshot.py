"""Checkpoint / resume: durable snapshots of cluster and sweep state.

The reference keeps everything in memory and loses it on exit
(SURVEY.md section 6: checkpoint/resume "Absent"; state is cleared between
rounds, ba.py:291-293).  This framework makes both of its state shapes
durable:

- the interactive cluster (roster ids/ports/fault flags, leader, round
  counter) serializes to JSON — ``python -m ba_tpu.runtime.main N
  --state FILE`` restores it at startup and saves on ``Exit``;
- batched ``SimState`` tensors (and any dict of arrays a sweep produces)
  serialize to ``.npz`` for long sweep campaigns.

Plain JSON/NPZ rather than orbax: the state is kilobytes of host-side
metadata plus dense arrays with no sharding to preserve (re-sharding on
load is one device_put), so the dependency would buy nothing.

All writes are atomic (temp file + ``os.replace``): a crash mid-save — the
exact event checkpointing exists to survive — must never corrupt the only
good copy.  Cluster snapshots also record the backend configuration
(protocol / m / signed / backend class) and ``restore_cluster`` refuses a
mismatch, so a resumed campaign cannot silently continue under different
protocol semantics.
"""

from __future__ import annotations

import json
import os

import numpy as np


def _atomic_write(path: str, write_fn) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save_sim_state(path: str, state, **extra_arrays) -> None:
    """SimState (+ any extra named arrays) -> one .npz file."""

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                order=np.asarray(state.order),
                leader=np.asarray(state.leader),
                faulty=np.asarray(state.faulty),
                alive=np.asarray(state.alive),
                ids=np.asarray(state.ids),
                **{k: np.asarray(v) for k, v in extra_arrays.items()},
            )

    _atomic_write(path, write)


def load_sim_state(path: str):
    """.npz -> (SimState, dict of extra arrays) on the default device."""
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState

    with np.load(path) as data:
        fields = {k: data[k] for k in data.files}
    state = SimState(
        order=jnp.asarray(fields.pop("order")),
        leader=jnp.asarray(fields.pop("leader")),
        faulty=jnp.asarray(fields.pop("faulty")),
        alive=jnp.asarray(fields.pop("alive")),
        ids=jnp.asarray(fields.pop("ids")),
    )
    return state, fields


def _backend_config(cluster) -> dict:
    """Protocol-defining backend attributes (class + flags when present)."""
    b = cluster.backend
    return {
        "backend": type(b).__name__,
        "protocol": getattr(b, "protocol", "om"),
        "m": getattr(b, "m", 1),
        "signed": getattr(b, "signed", False),
    }


def save_cluster(path: str, cluster) -> None:
    """Interactive Cluster -> JSON (roster, leader, round counter, seed,
    backend configuration)."""
    doc = {
        "version": 1,
        "seed": cluster.seed,
        "round": cluster._round,
        "next_id": cluster._next_id,
        "leader_id": cluster.leader_id,
        "config": _backend_config(cluster),
        "generals": [
            {"id": g.id, "port": g.port, "faulty": g.faulty, "alive": g.alive}
            for g in cluster.generals
        ],
    }
    def write(tmp):
        with open(tmp, "w") as fh:
            json.dump(doc, fh)

    _atomic_write(path, write)


def restore_cluster(path: str, cluster) -> None:
    """Load a JSON snapshot into an existing Cluster (backend unchanged).

    Refuses a snapshot whose recorded backend configuration differs from
    the running cluster's — a resumed campaign must not silently switch
    protocol, recursion depth, signing, or engine.
    """
    from ba_tpu.runtime.cluster import General

    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != 1:
        raise ValueError(f"unknown cluster snapshot version in {path!r}")
    want = doc.get("config")
    have = _backend_config(cluster)
    if want is not None and want != have:
        raise ValueError(
            f"snapshot {path!r} was taken with backend config {want}, "
            f"but this run uses {have}; relaunch with matching flags"
        )
    cluster.seed = doc["seed"]
    cluster._round = doc["round"]
    cluster._next_id = doc["next_id"]
    cluster.leader_id = doc["leader_id"]
    cluster.generals = [General(**g) for g in doc["generals"]]
