"""Checkpoint / resume: durable snapshots of cluster and sweep state.

The reference keeps everything in memory and loses it on exit
(SURVEY.md section 6: checkpoint/resume "Absent"; state is cleared between
rounds, ba.py:291-293).  This framework makes both of its state shapes
durable:

- the interactive cluster (roster ids/ports/fault flags, leader, round
  counter) serializes to JSON — ``python -m ba_tpu.runtime.main N
  --state FILE`` restores it at startup and saves on ``Exit``;
- batched ``SimState`` tensors (and any dict of arrays a sweep produces)
  serialize to ``.npz`` for long sweep campaigns.

Plain JSON/NPZ rather than orbax: the state is kilobytes of host-side
metadata plus dense arrays with no sharding to preserve (re-sharding on
load is one device_put), so the dependency would buy nothing.

All writes are atomic (temp file + ``os.replace``): a crash mid-save — the
exact event checkpointing exists to survive — must never corrupt the only
good copy.  Cluster snapshots also record the backend configuration
(protocol / m / signed / backend class) and ``restore_cluster`` refuses a
mismatch, so a resumed campaign cannot silently continue under different
protocol semantics.

**Carry checkpoints** (ISSUE 6): the third durable shape is the
pipelined engine's donated carry — SimState + KeySchedule (key data and
round counter) + the scenario counter block + the live strategy plane +
the round cursor — serialized as ONE versioned ``.npz`` whose
``__meta__`` entry holds a JSON header.  This is the repo's single
checkpoint format: ``parallel/pipeline.py`` writes it at its retire
points (zero added sync) and resumes from it bit-exactly,
``examples/sweep_campaign.py`` chunks long campaigns over it, and
``python -m ba_tpu.scenario`` validates the schema jax-free (this
module's reader is numpy + stdlib only — jax appears only inside
``load_sim_state``).
"""

from __future__ import annotations

import json
import os
import zipfile

import numpy as np

CARRY_CHECKPOINT_FORMAT = "ba_tpu.carry_checkpoint"
CARRY_CHECKPOINT_VERSION = 1

# SimState fields in carry order, then the KeySchedule pair; `counters`
# and `strategy` ride only on scenario / with_counters carries (the
# meta header says which).
CARRY_STATE_FIELDS = ("order", "leader", "faulty", "alive", "ids")
CARRY_SCHED_FIELDS = ("key_data", "counter")


def _atomic_write(path: str, write_fn) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save_sim_state(path: str, state, **extra_arrays) -> None:
    """SimState (+ any extra named arrays) -> one .npz file."""

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                order=np.asarray(state.order),
                leader=np.asarray(state.leader),
                faulty=np.asarray(state.faulty),
                alive=np.asarray(state.alive),
                ids=np.asarray(state.ids),
                **{k: np.asarray(v) for k, v in extra_arrays.items()},
            )

    _atomic_write(path, write)


def load_sim_state(path: str):
    """.npz -> (SimState, dict of extra arrays) on the default device."""
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState

    with np.load(path) as data:
        fields = {k: data[k] for k in data.files}
    state = SimState(
        order=jnp.asarray(fields.pop("order")),
        leader=jnp.asarray(fields.pop("leader")),
        faulty=jnp.asarray(fields.pop("faulty")),
        alive=jnp.asarray(fields.pop("alive")),
        ids=jnp.asarray(fields.pop("ids")),
    )
    return state, fields


def _backend_config(cluster) -> dict:
    """Protocol-defining backend attributes (class + flags when present)."""
    b = cluster.backend
    return {
        "backend": type(b).__name__,
        "protocol": getattr(b, "protocol", "om"),
        "m": getattr(b, "m", 1),
        "signed": getattr(b, "signed", False),
    }


def save_cluster(path: str, cluster) -> None:
    """Interactive Cluster -> JSON (roster, leader, round counter, seed,
    backend configuration)."""
    doc = {
        "version": 1,
        "seed": cluster.seed,
        "round": cluster._round,
        "next_id": cluster._next_id,
        "leader_id": cluster.leader_id,
        "config": _backend_config(cluster),
        "generals": [
            {"id": g.id, "port": g.port, "faulty": g.faulty, "alive": g.alive}
            for g in cluster.generals
        ],
    }
    def write(tmp):
        with open(tmp, "w") as fh:
            json.dump(doc, fh)

    _atomic_write(path, write)


def restore_cluster(path: str, cluster) -> None:
    """Load a JSON snapshot into an existing Cluster (backend unchanged).

    Refuses a snapshot whose recorded backend configuration differs from
    the running cluster's — a resumed campaign must not silently switch
    protocol, recursion depth, signing, or engine.
    """
    from ba_tpu.runtime.cluster import General

    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != 1:
        raise ValueError(f"unknown cluster snapshot version in {path!r}")
    want = doc.get("config")
    have = _backend_config(cluster)
    if want is not None and want != have:
        raise ValueError(
            f"snapshot {path!r} was taken with backend config {want}, "
            f"but this run uses {have}; relaunch with matching flags"
        )
    cluster.seed = doc["seed"]
    cluster._round = doc["round"]
    cluster._next_id = doc["next_id"]
    cluster.leader_id = doc["leader_id"]
    cluster.generals = [General(**g) for g in doc["generals"]]


# -- carry checkpoints (the pipelined engine's donated carry, durable) --------


def write_carry_checkpoint(path: str, arrays: dict, meta: dict) -> None:
    """Host arrays + JSON-able meta -> one atomic versioned ``.npz``.

    ``arrays`` must already be host numpy (the engine fetches the carry
    copy inside its existing retire sync — no device handles reach this
    layer).  ``meta`` is stamped with the format/version keys and stored
    as the ``__meta__`` entry (a unicode scalar: loads without pickle).
    """
    meta = {
        "format": CARRY_CHECKPOINT_FORMAT,
        "v": CARRY_CHECKPOINT_VERSION,
        **meta,
    }

    def write(tmp):
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                __meta__=np.asarray(json.dumps(meta)),
                **{k: np.asarray(v) for k, v in arrays.items()},
            )

    _atomic_write(path, write)


def read_carry_checkpoint(path: str):
    """``.npz`` -> ``(meta, {name: numpy array})`` after schema checks.

    Raises ``ValueError`` on anything that could silently resume the
    wrong campaign: unknown format/version, missing carry arrays, a
    round cursor that disagrees with the stored KeySchedule counter, or
    counters/strategy shapes inconsistent with the state.  Numpy +
    stdlib only — ``python -m ba_tpu.scenario`` runs this jax-free.
    """
    try:
        with np.load(path, allow_pickle=False) as data:
            fields = {k: data[k] for k in data.files}
    except zipfile.BadZipFile as e:
        # np.load raises BadZipFile (not OSError/ValueError) on a
        # truncated/half-written file — normalize it so callers keeping
        # this function's documented ValueError contract (the jax-free
        # CLI validator, resume= error paths) see every corruption the
        # same way.
        raise ValueError(f"{path!r}: not a readable .npz ({e})") from None
    raw = fields.pop("__meta__", None)
    if raw is None:
        raise ValueError(f"{path!r}: no __meta__ entry — not a carry checkpoint")
    try:
        meta = json.loads(str(raw))
    except ValueError as e:
        raise ValueError(f"{path!r}: unparseable __meta__ ({e})") from None
    if meta.get("format") != CARRY_CHECKPOINT_FORMAT:
        raise ValueError(
            f"{path!r}: format {meta.get('format')!r} is not "
            f"{CARRY_CHECKPOINT_FORMAT!r}"
        )
    if meta.get("v") != CARRY_CHECKPOINT_VERSION:
        raise ValueError(
            f"{path!r}: carry checkpoint version {meta.get('v')!r} "
            f"(this build reads v{CARRY_CHECKPOINT_VERSION})"
        )
    missing = [
        k for k in CARRY_STATE_FIELDS + CARRY_SCHED_FIELDS if k not in fields
    ]
    if missing:
        raise ValueError(f"{path!r}: missing carry arrays {missing}")
    rnd = meta.get("round")
    if not isinstance(rnd, int) or rnd < 0:
        raise ValueError(f"{path!r}: bad round cursor {rnd!r}")
    if int(fields["counter"]) != rnd:
        raise ValueError(
            f"{path!r}: round cursor {rnd} disagrees with the KeySchedule "
            f"counter {int(fields['counter'])} — the carry would replay "
            f"the wrong key stream"
        )
    if fields["faulty"].shape != fields["alive"].shape or fields[
        "faulty"
    ].ndim != 2:
        raise ValueError(
            f"{path!r}: state planes malformed "
            f"(faulty {fields['faulty'].shape}, alive {fields['alive'].shape})"
        )
    names = meta.get("counter_names")
    if "counters" in fields:
        if not isinstance(names, list) or len(names) != fields[
            "counters"
        ].shape[-1]:
            raise ValueError(
                f"{path!r}: counters block has {fields['counters'].shape} "
                f"entries but counter_names is {names!r}"
            )
    if "strategy" in fields and fields["strategy"].shape != fields[
        "faulty"
    ].shape:
        raise ValueError(
            f"{path!r}: strategy plane {fields['strategy'].shape} does not "
            f"match the state {fields['faulty'].shape}"
        )
    if meta.get("scenario") and (
        "counters" not in fields or "strategy" not in fields
    ):
        raise ValueError(
            f"{path!r}: scenario carry without counters/strategy planes"
        )
    return meta, fields


def validate_carry_checkpoint(path: str) -> dict:
    """Schema-check a carry checkpoint; returns its meta header.

    The jax-free CI entry (``python -m ba_tpu.scenario <ckpt.npz>``)
    and anything else that wants to vet a checkpoint without paying a
    backend init.
    """
    meta, _ = read_carry_checkpoint(path)
    return meta
