"""Virtual-device platform setup shared by tests and the driver entry.

This image's ``sitecustomize`` imports jax at interpreter startup and latches
``JAX_PLATFORMS`` from the environment (a TPU tunnel backend that deadlocks if
re-selected under a CPU-only env var), so switching to the virtual CPU mesh
must happen via ``jax.config.update`` in-process.  ``XLA_FLAGS`` is read
lazily at first backend init, so mutating ``os.environ`` is early enough as
long as it happens before the first ``jax.devices()`` call.

Mirrors the reference's trick of simulating a multi-node cluster inside one
process (thread-per-general with real sockets, ba.py:79-80,344-351): here the
"cluster" is n virtual XLA CPU devices, so every sharding/collective path is
exercised without multi-chip TPU hardware (SURVEY.md section 5).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def use_pallas() -> bool:
    """Route hot crypto ops through the Pallas kernels (ba_tpu.ops)?

    BA_TPU_PALLAS=1 forces them, =0 disables, default ("auto") enables on
    real TPU only — the kernels are TPU-codegen (Mosaic); CPU tests
    exercise them explicitly via interpret mode.  Read at trace time, so
    flip it before the first jit of the caller.
    """
    v = os.environ.get("BA_TPU_PALLAS", "auto")
    if v in ("0", "1"):
        return v == "1"
    import jax

    return jax.devices()[0].platform == "tpu"


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Turn on JAX's persistent XLA compilation cache (idempotent).

    The interactive paths (REPL/cluster via ``JaxBackend``) and the bench
    driver re-pay every jit compile each process start — through the TPU
    tunnel a single Mosaic compile costs seconds to minutes, so a fresh
    REPL session used to burn its first ``actual-order`` on a compile the
    previous session already did.  The persistent cache keys on (HLO,
    compile options, backend), so re-compiles of unchanged programs become
    disk reads.

    ``BA_TPU_COMPILE_CACHE`` controls it: ``0`` disables, a path overrides
    the location, unset/``1`` uses ``path`` or ``~/.cache/ba_tpu/xla``.
    Thresholds are zeroed so even the small interactive B=1 programs are
    cached (the default min-compile-time gate would skip exactly the
    programs the REPL re-pays most often).  Returns the cache dir in use,
    or None when disabled or unsupported by the installed jax.
    """
    # Deferred import: obs is jax-free, but platform must stay importable
    # before ba_tpu.utils finishes initializing (utils/__init__ imports
    # this module first).
    from ba_tpu.obs.instrument import (
        configure_compile_ledger,
        report_compile_cache,
    )

    env = os.environ.get("BA_TPU_COMPILE_CACHE", "")
    if env == "0":
        report_compile_cache(None)
        configure_compile_ledger(None)
        return None
    if env not in ("", "1"):
        path = env
    if path is None:
        path = os.path.join(
            os.path.expanduser("~"), ".cache", "ba_tpu", "xla"
        )
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except (AttributeError, OSError):
        report_compile_cache(None)
        # No cache, no ledger: a previously configured ledger must not
        # keep explaining compiles against a cache dir we just failed
        # to (re)establish.
        configure_compile_ledger(None)
        return None  # jax without the cache, or unwritable cache dir
    # Threshold knobs are best-effort AFTER the dir is live: a jax that has
    # the cache but not a threshold knob keeps its default gate (some small
    # programs skip the cache) — the cache is still correctly reported as
    # enabled, never half-configured-but-claimed-off.
    for knob, val in (
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ):
        try:
            jax.config.update(knob, val)
        except AttributeError:
            pass
    # Observable cache state: gauge compile_cache_enabled + an instant
    # trace marker, so first-call "compile" spans (obs.instrument) can be
    # read as cache loads vs real compiles.
    report_compile_cache(path)
    # Cross-run recompile ledger (ISSUE 6): persist each jitted fn's
    # compile signature NEXT TO the persistent cache, so a
    # first-compile-of-the-session can be diffed against the previous
    # process ("recompiled because jaxlib_version changed" becomes a
    # row).  jax/jaxlib versions ride as process-constant axes — read
    # without a backend query, since enable_compilation_cache runs
    # before platform selection in some callers.  BA_TPU_COMPILE_LEDGER=0
    # opts out (the test suite does: shared ledger state would make
    # recompile-record tests order-dependent across processes).
    if os.environ.get("BA_TPU_COMPILE_LEDGER", "") == "0":
        configure_compile_ledger(None)
    else:
        try:
            import jaxlib

            jaxlib_version = getattr(jaxlib, "__version__", "unknown")
        except ImportError:  # pragma: no cover - jax without jaxlib
            jaxlib_version = "unknown"
        configure_compile_ledger(
            os.path.join(path, "ba_tpu_axes_ledger.json"),
            env_axes={
                "jax_version": jax.__version__,
                "jaxlib_version": jaxlib_version,
            },
        )
    return path


def force_virtual_cpu_devices(n: int = 8, *, override_tpu_guard: bool = False) -> None:
    """Ensure >= n virtual CPU devices and select the CPU platform.

    Must run before the first ``jax.devices()``/backend query in the process.
    Honors ``BA_TPU_TESTS_ON_TPU=1``: then it is a no-op so the caller runs
    against whatever real hardware the environment provides — unless
    ``override_tpu_guard`` is set, for callers relaying an *explicit* user
    request for CPU that must win over an inherited test-env var (ADVICE
    r2: ``BA_TPU_EXAMPLE_PLATFORM=cpu`` silently landing on the real chip).

    An existing ``--xla_force_host_platform_device_count`` smaller than n is
    upgraded in place; an equal-or-larger one is preserved.
    """
    if os.environ.get("BA_TPU_TESTS_ON_TPU") == "1" and not override_tpu_guard:
        return
    _provision_virtual_cpu_flag(n)

    import jax

    jax.config.update("jax_platforms", "cpu")


def _provision_virtual_cpu_flag(n: int) -> None:
    """Append/upgrade the host-device-count XLA flag (no platform switch).

    Safe to run unconditionally before backend init: the flag only affects
    the CPU platform, so a process that ends up on TPU ignores it.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    pat = re.escape(_COUNT_FLAG) + r"=(\d+)"
    m = re.search(pat, flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = re.sub(pat, f"{_COUNT_FLAG}={n}", flags)
    os.environ["XLA_FLAGS"] = flags


def select_example_platform(n: int = 8) -> None:
    """The examples' platform policy (shared so init order lives here once).

    ``BA_TPU_EXAMPLE_PLATFORM=cpu`` forces the n-device virtual CPU mesh;
    ``=tpu`` (or anything else explicit) leaves the default backend alone.
    Unset ("auto"): provision the virtual-CPU device-count flag BEFORE the
    first backend query — it must precede XLA init to take effect — then
    keep a real TPU if that is the default backend, else the process lands
    on the (now n-device) CPU backend with no further switching needed.
    """
    mode = os.environ.get("BA_TPU_EXAMPLE_PLATFORM", "auto")
    if mode == "cpu":
        # Explicit user request: wins even over an inherited
        # BA_TPU_TESTS_ON_TPU=1 (ADVICE r2).
        force_virtual_cpu_devices(n, override_tpu_guard=True)
        return
    if mode == "auto":
        _provision_virtual_cpu_flag(n)
        import jax

        jax.default_backend()  # first init happens with the flag in place
