"""Host-side utilities that must not depend on the rest of ba_tpu."""

from ba_tpu.utils.platform import force_virtual_cpu_devices
from ba_tpu.utils.metrics import MetricsSink

__all__ = ["force_virtual_cpu_devices", "MetricsSink"]
