"""Scaling past one host: the join protocol and the global (data, node) mesh.

The reference joins a new general by dialing every known peer for the
leader's port (discover_leader, ba.py:86-102); its transport tops out at
one OS process of threads.  This framework's join is
``init_distributed()`` (every process dials the coordinator) followed by
``make_global_mesh()`` — "data" (independent instances) spans hosts over
DCN, "node" (generals of one big cluster) stays inside a slice on ICI —
and the SAME shard_map programs run unchanged on the bigger mesh.

Single-process this degenerates to the local-device mesh, so the example
runs anywhere; launch it once per process with BA_TPU_COORD/NPROCS/PROCID
set to see the true multi-process path (tests/test_multihost.py drives
that form with two OS processes over gloo and checks bit-identical
decisions).

    python examples/multihost_cluster.py
"""

import os
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax
    import jax.random as jr

    from ba_tpu.parallel import (
        init_distributed,
        make_global_mesh,
        sm_node_sharded,
        sharded_sweep,
        make_sweep_state,
    )

    # The join: a no-op single-process, jax.distributed across hosts.
    nproc = init_distributed(
        os.environ.get("BA_TPU_COORD"),
        int(os.environ.get("BA_TPU_NPROCS", "1")),
        int(os.environ.get("BA_TPU_PROCID", "0")),
    )
    n_dev = len(jax.devices())
    node = 2 if n_dev % 2 == 0 else 1
    mesh = make_global_mesh(node_devices_per_host=node)
    print(f"processes={nproc} devices={n_dev} "
          f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}")

    # One big signed cluster, generals sharded over "node".
    from ba_tpu.core import ATTACK, make_state

    B, n = 64, 128
    state = make_state(B, n, order=ATTACK)
    out = sm_node_sharded(mesh, jr.key(0), state, m=2)
    maj = np.asarray(out["majorities"])
    assert (maj == ATTACK).all()
    print(f"node-sharded SM(2): n={n} generals agree on attack "
          f"(needed {int(np.asarray(out['needed'])[0])} of "
          f"{int(np.asarray(out['total'])[0])})")

    # A fault-pattern sweep, instances sharded over "data".
    sweep = make_sweep_state(jr.key(1), 4096, 32)
    res = sharded_sweep(mesh, jr.key(2), sweep)
    hist = np.asarray(res["histogram"])
    assert hist.sum() == 4096
    print(f"sharded sweep: 4096 instances -> "
          f"retreat/attack/undefined = {hist.tolist()}")


if __name__ == "__main__":
    main()
