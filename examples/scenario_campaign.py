"""Declarative adversary campaigns at sweep scale: the scenario engine.

Runs BOTH committed scenario specs (examples/scenarios/) over thousands
of independent clusters through the pipelined mutating megastep — the
whole ``g-kill``/``g-add``/``g-state`` REPL session each spec encodes,
plus coordinated adversary strategies the reference's coin-flipping
traitors could never express, as ceil(R/K) donated device dispatches:

- ``cascading_failover.json``: leaders die round after round
  (``g-kill`` at batch scale), a successor revives — every cluster
  re-elects on device by lowest alive id, election-for-life semantics.
- ``colluding_coalition.json``: the COMMANDER and two lieutenants turn
  traitor, then walk the strategy table — collusion deterministically
  FLIPS every cluster's decision to the coalition value, vote-splitting
  breaks Interactive Consistency (the on-device IC1/IC2 verdict
  counters record exactly when), and a silent commander deterministically
  destroys the quorum.  (A lieutenant-only coalition cannot flip the
  quorum no matter its size: traitors tally honestly — SURVEY Q3 — so
  they out-vote their own lies at the quorum layer.  Decision capture
  requires the commander; this spec is that attack.)

    python examples/scenario_campaign.py

Env: SCENARIO_BATCH (default 2048) scales the per-spec cluster count.
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

SCENARIO_DIR = pathlib.Path(__file__).resolve().parent / "scenarios"


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax.random as jr

    from ba_tpu.core import ATTACK, command_from_name, make_state
    from ba_tpu.parallel import SCENARIO_COUNTER_NAMES, scenario_sweep
    from ba_tpu.scenario import compile_scenario, load

    # SCENARIO_BATCH overrides; falls back to the smoke harness's
    # SWEEP_BATCH so the examples smoke test stays fast.
    batch = int(
        os.environ.get("SCENARIO_BATCH")
        or os.environ.get("SWEEP_BATCH")
        or 2048
    )
    n = 8

    # -- cascading failover ---------------------------------------------------
    spec = load(str(SCENARIO_DIR / "cascading_failover.json"))
    block = compile_scenario(spec, batch, n)
    state = make_state(batch, n, order=ATTACK)
    out = scenario_sweep(jr.key(0), state, block, rounds_per_dispatch=2)
    leaders = out["leaders"]
    print(f"{spec.name}: {batch} clusters x {spec.rounds} rounds")
    for r in range(spec.rounds):
        lead = int(leaders[r, 0]) + 1  # ids are 1-based in the REPL
        agree = int(out["histograms"][r, 1])
        print(f"  round {r}: leader G{lead}, attack-decisions {agree}/{batch}")
    # Kills at rounds 1/2/4 cascade the leadership 1 -> 2 -> 3 -> 4; the
    # round-5 revival of G2 does NOT displace G4 (election is for life).
    assert [int(v) + 1 for v in leaders[:, 0]] == [1, 2, 3, 3, 4, 4]
    assert (leaders == leaders[:, :1]).all()  # every cluster agrees
    assert (out["histograms"][:, 1] == batch).all(), "honest clusters decide"
    assert out["counters"]["ic1_violations"] == 0

    # -- colluding coalition --------------------------------------------------
    spec = load(str(SCENARIO_DIR / "colluding_coalition.json"))
    block = compile_scenario(spec, batch, n)
    state = make_state(batch, n, order=command_from_name(spec.order))
    out = scenario_sweep(jr.key(1), state, block, rounds_per_dispatch=2)
    print(f"{spec.name}: {batch} clusters x {spec.rounds} rounds")
    names = ["retreat", "attack", "undefined"]
    for r in range(spec.rounds):
        counts = " ".join(
            f"{nm}={int(c)}" for nm, c in zip(names, out["histograms"][r])
        )
        print(f"  round {r}: {counts}")
    print(
        "  counters: "
        + ", ".join(
            f"{k}={out['counters'][k]}" for k in SCENARIO_COUNTER_NAMES
        )
    )
    # Deterministic phase outcomes (no coin survives a coordinated
    # coalition): rounds 0 and 7 are fault-free -> unanimous retreat;
    # the colluding rounds (2-3) flip EVERY cluster to the coalition's
    # attack (the commander pushes it consistently, the colluders
    # reinforce it); the split rounds (4-5) keep the retreat quorum but
    # break IC1 (honest lieutenants disagree by asker parity); the
    # silent-commander rounds (6) destroy the quorum outright.
    assert int(out["histograms"][0, 0]) == batch
    assert int(out["histograms"][2, 1]) == batch  # collusion captures
    assert int(out["histograms"][3, 1]) == batch
    assert int(out["histograms"][4, 0]) == batch  # split: quorum holds...
    assert out["counters"]["ic1_violations"] >= 2 * batch  # ...IC1 doesn't
    assert int(out["histograms"][6, 2]) == batch  # silent commander
    assert out["counters"]["quorum_failures"] >= batch
    assert int(out["histograms"][-1, 0]) == batch
    assert out["counters"]["equivocation_observed"] > 0
    print("scenario campaigns: OK")


if __name__ == "__main__":
    sys.exit(main())
