"""Signed SM(m) agreement end-to-end: sign, verify on device, relay, decide.

The trust upgrade the reference lacks (its oral messages are plain strings
any general can lie about, ba.py:39-57): commanders Ed25519-sign their
orders (C++ batch signer when a compiler is present), every copy is
verified in one batched device call, and only validly-signed values enter
any general's V-set.  A corrupted signature is shown being rejected.

    python examples/signed_cluster.py
"""

import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax.random as jr

    from ba_tpu.core import ATTACK, make_state
    from ba_tpu.crypto.signed import signed_sm_agreement

    B, n, m = 4, 16, 2
    state = make_state(B, n, order=ATTACK)

    out = signed_sm_agreement(jr.key(0), state, m)
    assert bool(np.asarray(out["sig_valid"]).all())
    assert (np.asarray(out["decision"]) == ATTACK).all()
    print(f"{B} clusters x {n} generals, SM({m}) signed: all decided attack")

    # Corrupt general 3's copy in every instance: the device verifier must
    # reject exactly those signatures, and honest agreement must survive.
    corrupt = np.zeros((B, n), bool)
    corrupt[:, 3] = True
    out = signed_sm_agreement(jr.key(1), state, m, corrupt=corrupt)
    sig_valid = np.asarray(out["sig_valid"])
    assert (~sig_valid[:, 3]).all() and sig_valid[:, :3].all()
    assert (np.asarray(out["decision"]) == ATTACK).all()
    print("corrupted signature rejected; agreement unaffected: OK")


if __name__ == "__main__":
    sys.exit(main())
