"""Leader failover under fire: kill leaders mid-campaign, re-elect on device.

The reference's failure story is its 0.1 s ping loop + lowest-id
re-election (ba.py:306-314, 126-157), one cluster at a time.  Here the
same detect -> elect -> continue loop runs for 10,000 clusters at once,
entirely on device: a kill schedule marks who dies before each round,
``failover_sweep`` re-elects per instance (batched argmin over alive ids)
and keeps agreeing.

    python examples/failover_study.py
"""

import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.core import ATTACK, make_state
    from ba_tpu.parallel import failover_sweep

    B, n, rounds = 10_000, 8, 4
    state = make_state(B, n, order=ATTACK)
    # Round 2 kills every cluster's leader (id 1); round 3 kills its
    # successor (id 2).  Everyone else keeps agreeing.
    kills = jnp.zeros((rounds, B, n), bool)
    kills = kills.at[1, :, 0].set(True).at[2, :, 1].set(True)
    out = jax.jit(failover_sweep)(jr.key(0), state, kills)
    leaders = np.asarray(out["leaders"])
    decisions = np.asarray(out["decisions"])
    for r in range(rounds):
        lead = int(leaders[r, 0]) + 1  # ids are 1-based in the REPL
        agree = float((decisions[r] == ATTACK).mean())
        print(f"round {r}: leader G{lead}, attack-decisions {agree:.1%}")
    assert (leaders[0] == 0).all() and (leaders[1] == 1).all()
    assert (leaders[2] == 2).all() and (decisions == ATTACK).all()
    print("all clusters re-elected and kept deciding: OK")


if __name__ == "__main__":
    sys.exit(main())
