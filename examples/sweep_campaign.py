"""10k-instance fault-sweep campaign with checkpointing.

The batched equivalent of running the reference's REPL thousands of times
with different ``g-state``/``g-kill`` configurations (ba.py:401-437): one
device program agrees 10,240 independent clusters with random sizes and
traitor sets, reports the decision histogram, and checkpoints the final
state (something the reference cannot do at all — its state dies with the
process).

Runs anywhere: real TPU if available, else an 8-device virtual CPU mesh.

    python examples/sweep_campaign.py
"""

import os
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax.random as jr

    from ba_tpu.parallel import make_mesh, make_sweep_state, sharded_sweep
    from ba_tpu.utils.snapshot import save_sim_state

    batch = int(os.environ.get("SWEEP_BATCH", 10_240))
    cap = int(os.environ.get("SWEEP_CAP", 64))
    state = make_sweep_state(jr.key(0), batch, cap)
    mesh = make_mesh()
    out = sharded_sweep(mesh, jr.key(1), state, m=2)
    hist = np.asarray(out["histogram"])
    names = ["retreat", "attack", "undefined"]
    print(f"{batch} clusters (n <= {cap}, OM(2)):")
    for name, count in zip(names, hist):
        print(f"  {name:10s} {int(count):6d}")
    assert hist.sum() == batch
    path = os.environ.get("SWEEP_CKPT", "/tmp/sweep_campaign.npz")
    save_sim_state(path, state, decisions=np.asarray(out["decision"]))
    print(f"checkpoint -> {path}")


if __name__ == "__main__":
    sys.exit(main())
