"""Checkpointed fault-sweep campaign with per-checkpoint metrics.

The batched equivalent of running the reference's REPL thousands of
times with different ``g-state``/``g-kill`` configurations
(ba.py:401-437): each checkpoint agrees ``SWEEP_BATCH`` independent
clusters with random sizes and traitor sets under a fresh fold of the
campaign key, reports the decision histogram, snapshots the campaign's
metrics into the obs registry (ROADMAP: mid-campaign dashboards for
free), and checkpoints the final state — something the reference cannot
do at all, since its state dies with the process.

Observability wiring (PR 2's registry, PR 3's ROADMAP item): counters
for instances/decisions, a log-bucketed histogram of per-checkpoint
wall time, and one versioned ``{"event": "metrics_snapshot", "v": 1}``
record per checkpoint.  Point ``BA_TPU_METRICS`` at a path (or ``-``
for stderr) to capture the JSONL stream; unset, the snapshots are
returned in-memory only and the example stays file-silent.

Runs anywhere: real TPU if available, else an 8-device virtual CPU mesh.

    SWEEP_CHECKPOINTS=3 BA_TPU_METRICS=/tmp/campaign.jsonl \\
        python examples/sweep_campaign.py
"""

import os
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax.random as jr

    from ba_tpu.obs import default_registry
    from ba_tpu.parallel import make_mesh, make_sweep_state, sharded_sweep
    from ba_tpu.utils.snapshot import save_sim_state

    batch = int(os.environ.get("SWEEP_BATCH", 10_240))
    cap = int(os.environ.get("SWEEP_CAP", 64))
    checkpoints = int(os.environ.get("SWEEP_CHECKPOINTS", 3))
    ckpt_path = os.environ.get("SWEEP_CKPT", "/tmp/sweep_campaign.npz")

    reg = default_registry()
    ck_c = reg.counter("sweep_campaign_checkpoints_total")
    inst_c = reg.counter("sweep_campaign_instances_total")
    wall_h = reg.histogram("sweep_campaign_checkpoint_s")
    decision_c = {
        name: reg.counter(f"sweep_campaign_{name}_total")
        for name in ("retreat", "attack", "undefined")
    }

    mesh = make_mesh()
    campaign_key = jr.key(1)
    total = np.zeros(3, dtype=np.int64)
    names = ["retreat", "attack", "undefined"]
    print(
        f"campaign: {checkpoints} checkpoint(s) x {batch} clusters "
        f"(n <= {cap}, OM(2))"
    )
    for ck in range(checkpoints):
        t0 = time.perf_counter()
        state = make_sweep_state(jr.fold_in(jr.key(0), ck), batch, cap)
        out = sharded_sweep(
            mesh, jr.fold_in(campaign_key, ck), state, m=2
        )
        hist = np.asarray(out["histogram"])
        assert hist.sum() == batch
        total += hist
        wall_h.record(time.perf_counter() - t0)
        ck_c.inc()
        inst_c.inc(batch)
        for name, count in zip(names, hist):
            decision_c[name].inc(int(count))
        save_sim_state(
            ckpt_path, state, decisions=np.asarray(out["decision"])
        )
        # One versioned metrics_snapshot per checkpoint: the JSONL sink
        # (BA_TPU_METRICS) gets a {"event": "metrics_snapshot", "v": 1}
        # record a dashboard can tail mid-campaign.
        record = reg.emit_snapshot(checkpoint=ck, batch=batch)
        counts = " ".join(
            f"{name}={int(count)}" for name, count in zip(names, hist)
        )
        print(
            f"  checkpoint {ck}: {counts} "
            f"(snapshot: {len(record['metrics'])} metrics)"
        )
    print(f"{checkpoints * batch} clusters total:")
    for name, count in zip(names, total):
        print(f"  {name:10s} {int(count):7d}")
    assert total.sum() == checkpoints * batch
    sink_target = os.environ.get("BA_TPU_METRICS")
    where = sink_target or "in-memory only (set BA_TPU_METRICS to capture)"
    print(f"checkpoint -> {ckpt_path}")
    print(f"metrics_snapshot x{checkpoints} -> {where}")


if __name__ == "__main__":
    sys.exit(main())
