"""Checkpointed fault-sweep campaign with per-checkpoint metrics.

The batched equivalent of running the reference's REPL thousands of
times with different fault configurations (ba.py:401-437): ONE
continuous pipelined campaign agrees ``SWEEP_BATCH`` independent
clusters over ``SWEEP_CHECKPOINTS x SWEEP_ROUNDS_PER_CKPT`` rounds,
reporting the per-checkpoint decision histogram and snapshotting the
campaign's metrics into the obs registry — something the reference
cannot do at all, since its state dies with the process.

Checkpoint format (ISSUE 6): this example used to roll its own
chunking (fresh state + one ``save_sim_state`` per checkpoint); it now
rides the engine's CARRY checkpoints — ``pipeline_sweep(
checkpoint_every=..., checkpoint_path=...)`` serializes the donated
carry (SimState + KeySchedule + counter block + round cursor) inside
the engine's existing retire fetch, in the repo's single checkpoint
format (``utils/snapshot.py``).  The finale proves the point of the
format: the campaign RESUMES from its mid-point checkpoint and the
replayed tail bit-matches the original run.

Observability wiring (PR 2's registry): counters for
instances/decisions, a log-bucketed histogram of per-checkpoint wall
time, and one versioned ``{"event": "metrics_snapshot", "v": 1}``
record per checkpoint (the JSONL stream also carries the engine's
``scenario_checkpoint`` records now).  Point ``BA_TPU_METRICS`` at a
path (or ``-`` for stderr) to capture the stream; unset, the snapshots
are returned in-memory only and the example stays file-silent.

Runs anywhere: real TPU if available, else virtual CPU devices (the
campaign runs the single-device engine; see ``parallel/mesh.py`` for
the sharded sweeps).

    SWEEP_CHECKPOINTS=3 BA_TPU_METRICS=/tmp/campaign.jsonl \\
        python examples/sweep_campaign.py
"""

import os
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    from ba_tpu.utils.platform import select_example_platform

    select_example_platform(8)
    import jax.random as jr

    from ba_tpu.obs import default_registry
    from ba_tpu.parallel import (
        fresh_copy,
        make_sweep_state,
        pipeline_sweep,
    )

    batch = int(os.environ.get("SWEEP_BATCH", 10_240))
    cap = int(os.environ.get("SWEEP_CAP", 64))
    checkpoints = int(os.environ.get("SWEEP_CHECKPOINTS", 3))
    per_ckpt = int(os.environ.get("SWEEP_ROUNDS_PER_CKPT", 2))
    ckpt_path = os.environ.get("SWEEP_CKPT", "/tmp/sweep_campaign_{round}.npz")

    reg = default_registry()
    ck_c = reg.counter("sweep_campaign_checkpoints_total")
    inst_c = reg.counter("sweep_campaign_instances_total")
    wall_h = reg.histogram("sweep_campaign_checkpoint_s")
    decision_c = {
        name: reg.counter(f"sweep_campaign_{name}_total")
        for name in ("retreat", "attack", "undefined")
    }

    rounds = checkpoints * per_ckpt
    state = make_sweep_state(jr.key(0), batch, cap)
    names = ["retreat", "attack", "undefined"]
    print(
        f"campaign: {checkpoints} checkpoint(s) x {per_ckpt} round(s) "
        f"x {batch} clusters (n <= {cap}, OM(2))"
    )

    # One metrics_snapshot + wall/decision bookkeeping per checkpoint,
    # fired from the engine's on_checkpoint hook — the carry serialized
    # inside the retire fetch, the dashboard record right after it.
    t_last = time.perf_counter()
    snapshots = []
    written = []

    def on_checkpoint(round_cursor, path):
        nonlocal t_last
        wall_h.record(time.perf_counter() - t_last)
        t_last = time.perf_counter()
        ck_c.inc()
        inst_c.inc(batch * per_ckpt)
        written.append((round_cursor, path))
        record = reg.emit_snapshot(checkpoint=len(written) - 1,
                                   round=round_cursor, batch=batch)
        snapshots.append(record)

    out = pipeline_sweep(
        jr.key(1),
        fresh_copy(state),
        rounds,
        m=2,
        rounds_per_dispatch=per_ckpt,
        with_counters=True,
        collect_decisions=True,
        checkpoint_every=per_ckpt,
        checkpoint_path=ckpt_path,
        on_checkpoint=on_checkpoint,
    )

    total = np.zeros(3, dtype=np.int64)
    for ck in range(checkpoints):
        hist = out["histograms"][ck * per_ckpt:(ck + 1) * per_ckpt].sum(0)
        assert hist.sum() == batch * per_ckpt
        total += hist
        for name, count in zip(names, hist):
            decision_c[name].inc(int(count))
        counts = " ".join(
            f"{name}={int(count)}" for name, count in zip(names, hist)
        )
        n_metrics = len(snapshots[ck]["metrics"]) if ck < len(snapshots) else 0
        print(f"  checkpoint {ck}: {counts} (snapshot: {n_metrics} metrics)")
    print(f"{checkpoints * per_ckpt * batch} cluster-rounds total:")
    for name, count in zip(names, total):
        print(f"  {name:10s} {int(count):7d}")
    assert total.sum() == rounds * batch
    assert len(written) == checkpoints, written

    # Resume proof: replay the tail from a mid-campaign checkpoint and
    # bit-match the original run — the property that makes the carry
    # format worth committing to (deterministic replay-from-checkpoint,
    # elastic migration for the serving layer).  Without a {round}
    # placeholder every checkpoint overwrote the same file, so the only
    # carry on disk is the final one (cursor == rounds — nothing left to
    # replay); same story with a single checkpoint.  Skip the proof
    # rather than resume a finished campaign.
    resumable = [
        (r, p) for r, p in written
        if r < rounds and "{round}" in ckpt_path
    ]
    if not resumable:
        print(
            "resume proof skipped: no mid-campaign checkpoint on disk "
            "(SWEEP_CKPT needs a {round} placeholder and "
            "SWEEP_CHECKPOINTS >= 2)"
        )
    else:
        mid_round, mid_path = resumable[len(resumable) // 2]
        resumed = pipeline_sweep(
            None,
            None,
            rounds,
            m=2,
            rounds_per_dispatch=per_ckpt,
            with_counters=True,
            collect_decisions=True,
            resume=mid_path,
        )
        np.testing.assert_array_equal(
            resumed["decisions"], out["decisions"][mid_round:]
        )
        assert resumed["counters"] == out["counters"]
        print(
            f"resume from round {mid_round} ({mid_path}): "
            f"{rounds - mid_round} replayed round(s) bit-exact"
        )

    sink_target = os.environ.get("BA_TPU_METRICS")
    where = sink_target or "in-memory only (set BA_TPU_METRICS to capture)"
    print(f"carry checkpoints -> {ckpt_path}")
    print(f"metrics_snapshot x{len(snapshots)} -> {where}")


if __name__ == "__main__":
    sys.exit(main())
