"""Resilient execution supervisor tests (ISSUE 7: runtime/supervisor.py,
runtime/chaos.py, the pipeline's resilience seams, and the checkpoint
integrity/retention machinery in utils/snapshot.py).

The contracts, each pinned independently:

1. **Supervised parity** — a supervised campaign, with or without
   injected faults (transient retries, a fatal mid-campaign fault, an
   OOM degrade, a corrupt checkpoint, a process kill), produces
   decisions, leaders and every counter block bit-identical to the
   uninterrupted unsupervised run.
2. **Zero added sync** — the no-blocking dispatch-count proof re-runs
   under FULL supervision (watchdog armed, seam installed, rows
   collection + checkpointing live) with an unchanged schedule and
   ``jax.block_until_ready`` monkeypatched to raise.
3. **Checkpoint integrity** — the sha256 content digest rejects silent
   corruption, ``keep_last`` retention prunes families, corrupt files
   quarantine to ``.corrupt`` and recovery falls back to the next-newest
   valid checkpoint, and a REAL mid-write ``SIGKILL`` never leaves a
   half-written file a reader can see.
4. **FaultPlan** — JSON round-trip exactness, eager validation, and the
   jax-free ``python -m ba_tpu.runtime.chaos`` CLI.
"""

import dataclasses
import json
import os
import pathlib
import signal
import subprocess
import sys

import jax
import jax.random as jr
import numpy as np
import pytest

from ba_tpu.core.types import ATTACK
from ba_tpu.parallel import make_sweep_state, pipeline_sweep
from ba_tpu.parallel.pipeline import fresh_copy as _fresh
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import (
    PoisonousWindow,
    SupervisorConfig,
    backoff_s,
    classify_fault,
    derive_timeout_s,
    supervised_sweep,
)
from ba_tpu.scenario import compile_scenario, from_dict
from ba_tpu.utils import snapshot

REPO = pathlib.Path(__file__).resolve().parent.parent


def _campaign_setup(R=12):
    """A churny scenario campaign: kills, fault flips, a strategy, a
    revive — every counter has something to count."""
    B, cap = 16, 8
    key = jr.key(91)
    state = make_sweep_state(jr.key(90), B, cap, order=ATTACK)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    spec = from_dict(
        {
            "name": "supervised-campaign",
            "rounds": R,
            "order": "attack",
            "events": [
                e
                for e in [
                    {"round": 2, "kill": [1]},
                    {"round": 5, "set_faulty": [3], "value": True},
                    {"round": 6, "set_strategy": [3],
                     "value": "adaptive_split"},
                    {"round": 9, "revive": [1]},
                ]
                if e["round"] < R
            ],
        }
    )
    return key, state, compile_scenario(spec, B, cap, sparse=True)


def _baseline(key, state, block, R, **kw):
    return pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, **kw,
    )


def _assert_bit_identical(got, want):
    np.testing.assert_array_equal(got["decisions"], want["decisions"])
    np.testing.assert_array_equal(got["leaders"], want["leaders"])
    np.testing.assert_array_equal(
        got["counters_per_round"], want["counters_per_round"]
    )
    np.testing.assert_array_equal(got["histograms"], want["histograms"])
    assert got["counters"] == want["counters"]


# -- fault classification + backoff + timeout ---------------------------------


def test_classify_fault_duck_marker_wins():
    assert classify_fault(chaos.InjectedTransient("x")) == "transient"
    assert classify_fault(chaos.InjectedFatal("x")) == "fatal"
    assert classify_fault(chaos.InjectedOOM("x")) == "oom"


def test_classify_fault_message_markers():
    assert classify_fault(RuntimeError("UNAVAILABLE: socket closed")) == (
        "transient"
    )
    assert classify_fault(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 1GB")
    ) == "oom"
    # OOM beats the transient envelope it often travels in.
    assert classify_fault(
        RuntimeError("ABORTED: Allocation failure on device")
    ) == "oom"
    assert classify_fault(RuntimeError("something else broke")) == "fatal"
    assert classify_fault(ValueError("bad shape")) == "fatal"


def test_backoff_deterministic_and_bounded():
    cfg = SupervisorConfig(
        backoff_base_s=0.1, backoff_factor=2.0, backoff_max_s=1.0,
        jitter_frac=0.25, seed=3,
    )
    a = backoff_s(cfg, 1, "dispatch:4")
    assert a == backoff_s(cfg, 1, "dispatch:4")  # same site: same delay
    assert a != backoff_s(cfg, 1, "retire:4")    # different site: different
    assert a != backoff_s(cfg, 2, "dispatch:4")  # different attempt too
    for attempt in range(1, 8):
        for token in ("dispatch:0", "retire:6", "recover:12"):
            d = backoff_s(cfg, attempt, token)
            raw = min(0.1 * 2.0 ** (attempt - 1), 1.0)
            assert 0.0 <= d <= raw * 1.25
    with pytest.raises(ValueError):
        backoff_s(cfg, 0, "x")


def test_derive_timeout_pins_and_floor(monkeypatch):
    monkeypatch.delenv("BA_TPU_SUPERVISE_TIMEOUT_S", raising=False)
    assert derive_timeout_s(SupervisorConfig(timeout_s=7.5)) == 7.5
    monkeypatch.setenv("BA_TPU_SUPERVISE_TIMEOUT_S", "12.5")
    assert derive_timeout_s(SupervisorConfig()) == 12.5
    monkeypatch.delenv("BA_TPU_SUPERVISE_TIMEOUT_S")
    # Empty registry histogram: the floor.
    from ba_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    cfg = SupervisorConfig(timeout_floor_s=30.0, timeout_multiplier=16.0)
    assert derive_timeout_s(cfg, registry=reg) == 30.0
    # A populated histogram: multiplier x the worst observed latency.
    reg.histogram("pipeline_dispatch_latency_s").record(4.0)
    assert derive_timeout_s(cfg, registry=reg) == 64.0


# -- FaultPlan grammar + CLI --------------------------------------------------


def test_fault_plan_round_trip_exact():
    for path in sorted((REPO / "examples" / "faults").glob("*.json")):
        doc = json.loads(path.read_text())
        assert chaos.to_dict(chaos.from_dict(doc)) == doc, path


def test_fault_plan_validation_errors():
    bad = [
        {"faults": []},                                      # no name
        {"name": "x", "faults": [{"round": 0, "kind": "nope"}]},
        {"name": "x", "faults": [{"round": -1, "kind": "fatal"}]},
        {"name": "x", "faults": [{"round": 0, "kind": "fatal",
                                  "phase": "checkpoint"}]},
        {"name": "x", "faults": [{"round": 0, "kind": "corrupt",
                                  "phase": "dispatch"}]},
        {"name": "x", "faults": [{"round": 0, "kind": "stall"}]},  # no secs
        {"name": "x", "faults": [{"round": 0, "kind": "fatal",
                                  "seconds": 1.0}]},
        {"name": "x", "faults": [{"round": 0, "kind": "fatal",
                                  "times": 0}]},
        {"name": "x", "faults": [{"round": 0, "kind": "fatal",
                                  "bogus": 1}]},
        {"name": "x", "extra": 1, "faults": []},
    ]
    for doc in bad:
        with pytest.raises(chaos.FaultPlanError):
            chaos.from_dict(doc)


def test_chaos_cli_jax_free_subprocess():
    # The chaos smoke stage ci.sh runs: validate every committed fault
    # plan WITHOUT jax ever being imported.
    code = (
        "import sys\n"
        "from ba_tpu.runtime.chaos import main\n"
        "rc = main(sys.argv[1:])\n"
        "banned = {m for m in sys.modules if m.split('.')[0] in"
        " ('jax', 'jaxlib')}\n"
        "assert not banned, banned\n"
        "sys.exit(rc)\n"
    )
    plans = sorted(str(p) for p in (REPO / "examples" / "faults").glob("*.json"))
    assert plans
    proc = subprocess.run(
        [sys.executable, "-c", code, *plans],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count(": OK") == len(plans)
    # And a malformed plan fails with a one-line diagnosis, not a traceback.
    proc = subprocess.run(
        [sys.executable, "-c", code, os.devnull],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert proc.returncode == 1
    assert "FAIL" in proc.stderr and "Traceback" not in proc.stderr


# -- checkpoint integrity + retention (utils/snapshot.py) ---------------------


def _toy_checkpoint(path, round_=4, R=8):
    """A real carry checkpoint via the engine: 4 rounds in, 8 total."""
    key, state, block = _campaign_setup(R)
    pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        checkpoint_every=round_, checkpoint_path=str(path),
    )


def test_content_digest_rejects_silent_flip(tmp_path):
    path = tmp_path / "ck_{round}.npz"
    _toy_checkpoint(path)
    ck = tmp_path / "ck_4.npz"
    assert ck.exists()
    meta = snapshot.validate_carry_checkpoint(str(ck))
    assert len(meta["sha256"]) == 64
    chaos.corrupt_file(str(ck), "flip")
    with pytest.raises(ValueError, match="digest|corrupt|bad|invalid"):
        snapshot.read_carry_checkpoint(str(ck))


def test_checkpoint_family_scan_ignores_strays(tmp_path):
    tmpl = str(tmp_path / "ck_{round}.npz")
    for r in (2, 4, 10):
        (tmp_path / f"ck_{r}.npz").write_bytes(b"x")
    (tmp_path / "ck_4.npz.tmp.123").write_bytes(b"x")
    (tmp_path / "ck_2.npz.corrupt").write_bytes(b"x")
    (tmp_path / "ck_nope.npz").write_bytes(b"x")
    assert snapshot.checkpoint_paths(tmpl) == [
        (2, str(tmp_path / "ck_2.npz")),
        (4, str(tmp_path / "ck_4.npz")),
        (10, str(tmp_path / "ck_10.npz")),
    ]
    with pytest.raises(ValueError):
        snapshot.checkpoint_paths(str(tmp_path / "ck.npz"))


def test_prune_keep_last_removes_sidecars_too(tmp_path):
    tmpl = str(tmp_path / "ck_{round}.npz")
    for r in (2, 4, 6, 8):
        (tmp_path / f"ck_{r}.npz").write_bytes(b"x")
        (tmp_path / f"ck_{r}.npz.rows.npz").write_bytes(b"y")
    removed = snapshot.prune_checkpoints(tmpl, keep_last=2)
    assert sorted(removed) == [
        str(tmp_path / "ck_2.npz"), str(tmp_path / "ck_4.npz")
    ]
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == [
        "ck_6.npz", "ck_6.npz.rows.npz", "ck_8.npz", "ck_8.npz.rows.npz"
    ]


def test_engine_checkpoint_keep_last_retention(tmp_path):
    R = 12
    key, state, block = _campaign_setup(R)
    path = str(tmp_path / "ck_{round}.npz")
    out = pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        checkpoint_every=2, checkpoint_path=path, checkpoint_keep_last=2,
    )
    assert out["stats"]["checkpoints"] == 6
    kept = [r for r, _ in snapshot.checkpoint_paths(path)]
    assert kept == [10, 12]
    # Validation: retention needs a templated path + checkpointing on.
    with pytest.raises(ValueError):
        pipeline_sweep(
            key, None, R, checkpoint_keep_last=2,
            checkpoint_every=2, checkpoint_path=str(tmp_path / "flat.npz"),
        )
    with pytest.raises(ValueError):
        pipeline_sweep(key, None, R, checkpoint_keep_last=2)


def test_newest_valid_checkpoint_quarantines_and_falls_back(tmp_path):
    path = tmp_path / "ck_{round}.npz"
    _toy_checkpoint(path, round_=4, R=8)  # writes ck_4 and ck_8
    assert (tmp_path / "ck_8.npz").exists()
    chaos.corrupt_file(str(tmp_path / "ck_8.npz"), "truncate")
    found = snapshot.newest_valid_checkpoint(str(path))
    assert found is not None
    got_path, meta = found
    assert got_path == str(tmp_path / "ck_4.npz")
    assert meta["round"] == 4
    # The corrupt newest was quarantined, bytes preserved for post-mortem.
    assert not (tmp_path / "ck_8.npz").exists()
    assert (tmp_path / "ck_8.npz.corrupt").exists()
    # Nothing valid at all -> None.
    chaos.corrupt_file(str(tmp_path / "ck_4.npz"), "flip")
    assert snapshot.newest_valid_checkpoint(str(path)) is None


def test_torn_write_sigkill_never_exposes_half_file(tmp_path):
    # The atomic-write claim under a REAL mid-write SIGKILL: the child
    # dies with half the npz bytes written to the .tmp staging file; the
    # final path must never exist half-written, and the stray .tmp must
    # not break the next write to the same path.
    ck = tmp_path / "torn.npz"
    child = f'''
import io, os, signal
import numpy as np
from ba_tpu.utils import snapshot

real_savez = np.savez
def savez_half_then_die(fh, **kw):
    buf = io.BytesIO()
    real_savez(buf, **kw)
    data = buf.getvalue()
    fh.write(data[: len(data) // 2])
    fh.flush()
    os.fsync(fh.fileno())
    os.kill(os.getpid(), signal.SIGKILL)

np.savez = savez_half_then_die
snapshot.write_carry_checkpoint(
    {str(ck)!r},
    {{"alive": np.ones((2, 4), bool)}},
    {{"round": 3}},
)
raise SystemExit("unreachable: the writer must have died mid-write")
'''
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    # The reader can never see a torn file: the final path simply does
    # not exist (the rename never happened).
    assert not ck.exists()
    strays = list(tmp_path.glob("torn.npz.tmp.*"))
    assert strays, "the killed writer should have left its staging file"
    # A stray .tmp from the killed writer must not break the next write.
    arrays = {"alive": np.ones((2, 4), bool)}
    snapshot.write_carry_checkpoint(str(ck), arrays, {"round": 3})
    with np.load(ck, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
    assert meta["round"] == 3
    assert meta["sha256"] == snapshot.content_digest(arrays)


# -- pipeline resilience seams ------------------------------------------------


def test_retire_watchdog_fires_on_injected_stall():
    R = 6
    key, state, block = _campaign_setup(R)
    plan = chaos.from_dict(
        {"name": "stall", "faults": [
            {"round": 2, "kind": "stall", "phase": "retire",
             "seconds": 0.3},
        ]}
    )
    inj = chaos.ChaosInjector(plan)
    stalls = []
    out = pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        exec_seam=lambda call, phase, d, lo, hi: inj.fire(
            call, phase, lo, hi
        ),
        retire_timeout_s=0.05,
        on_stall=lambda d, t: stalls.append((d, t)),
    )
    assert out["stats"]["stalls"] == 1
    assert stalls == [(1, 0.05)]  # rounds [2,4) = dispatch 1
    assert [f["kind"] for f in inj.fired] == ["stall"]
    # Validation: a watchdog callback needs a timeout to arm.
    with pytest.raises(ValueError):
        pipeline_sweep(key, None, R, on_stall=lambda d, t: None)
    with pytest.raises(ValueError):
        pipeline_sweep(key, None, R, retire_timeout_s=0.0)


def test_supervised_no_blocking_schedule_unchanged(monkeypatch, tmp_path):
    # ISSUE 7 acceptance: the engine's only sync stays the depth-delayed
    # retire fetch even under FULL supervision — watchdog armed, seam
    # installed, rows collection and checkpointing live.
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    R, depth = 7, 3
    state = make_sweep_state(jr.key(5), 8, 8)
    events = []
    out = supervised_sweep(
        jr.key(23), state, R,
        config=SupervisorConfig(timeout_s=60.0),
        depth=depth, rounds_per_dispatch=1, with_counters=True,
        checkpoint_every=3, checkpoint_path=str(tmp_path / "nb_{round}.npz"),
        on_event=lambda kind, i: events.append((kind, i)),
    )
    dispatches = [i for kind, i in events if kind == "dispatch"]
    retires = [i for kind, i in events if kind == "retire"]
    assert dispatches == list(range(R))
    assert retires == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [
        ("dispatch", i) for i in range(depth + 1)
    ]
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["stalls"] == 0
    assert out["supervisor"]["attempts"] == 1
    assert out["supervisor"]["retries"] == 0


# -- supervised parity --------------------------------------------------------


def test_supervised_clean_run_matches_unsupervised():
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["attempts"] == 1 and sup["recoveries"] == 0
    assert sup["history_rounds"] == R


def test_supervised_transient_storm_parity():
    # Transient faults at both seam phases retry in place; a retire
    # stall trips the watchdog; everything stays bit-identical.
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    plan = chaos.from_dict(
        {"name": "storm", "faults": [
            {"round": 2, "kind": "transient"},
            {"round": 6, "kind": "transient", "phase": "retire",
             "times": 2},
            {"round": 8, "kind": "stall", "phase": "retire",
             "seconds": 0.2},
        ]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan,
        config=SupervisorConfig(timeout_s=0.05, backoff_base_s=0.01),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["attempts"] == 1 and sup["retries"] == 3
    assert sup["stalls"] == 1 and sup["injected"] == 4


def test_supervised_fatal_recovers_from_checkpoint_bit_exact(tmp_path):
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    plan = chaos.from_dict(
        {"name": "fatal", "faults": [
            {"round": 8, "kind": "fatal"},
        ]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan,
        checkpoint_every=4, checkpoint_path=str(tmp_path / "f_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["attempts"] == 2 and sup["recoveries"] == 1
    # Resumed from the round-4 checkpoint; "lost" counts only rounds
    # whose rows had already retired past the resume point (the fault
    # fired at the round-8 dispatch, before those retires caught up).
    assert sup["lost_rounds"] <= 4
    # stats["checkpoints"] spans EVERY attempt (a failed attempt's
    # engine stats die with its exception): all three family members
    # on disk were written by this one supervised call.
    assert got["stats"]["checkpoints"] == len(
        snapshot.checkpoint_paths(str(tmp_path / "f_{round}.npz"))
    )


def test_supervised_fatal_recovery_on_mesh_bit_exact(tmp_path):
    # ISSUE 8: the supervisor's auto-resume works UNCHANGED on a mesh —
    # a fatal mid-campaign fault on an 8x1 sharded campaign recovers
    # from the (canonical, gather-on-write) checkpoint, re-splits the
    # carry on read, and completes bit-identical to the uninterrupted
    # SINGLE-DEVICE run (the strongest form: recovery + resharding +
    # sharded re-execution, one assertion).
    import jax

    from ba_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    plan = chaos.from_dict(
        {"name": "mesh-fatal", "faults": [
            {"round": 8, "kind": "fatal"},
        ]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan,
        mesh=make_mesh((8, 1), ("data", "node")),
        checkpoint_every=4,
        checkpoint_path=str(tmp_path / "mf_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["attempts"] == 2 and sup["recoveries"] == 1
    assert got["stats"]["shards"] == 8


def test_supervised_corrupt_checkpoint_falls_back(tmp_path):
    # The round-4 checkpoint is chaos-corrupted as it is written; the
    # round-8 fatal then forces recovery: the scan quarantines the
    # rotten file (nothing older survives, so the campaign restarts
    # from round 0) and still completes bit-identically — one rotten
    # file costs a replay, never the campaign.
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    plan = chaos.from_dict(
        {"name": "rot", "faults": [
            {"round": 4, "kind": "corrupt", "mode": "flip"},
            {"round": 8, "kind": "fatal"},
        ]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan,
        checkpoint_every=4, checkpoint_path=str(tmp_path / "c_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["recoveries"] == 1
    # The corrupt newest (and only) checkpoint was quarantined for
    # post-mortem and attempt 2 rewrote a fresh, valid one in its place.
    assert (tmp_path / "c_4.npz.corrupt").exists()
    snapshot.validate_carry_checkpoint(str(tmp_path / "c_4.npz"))


def test_supervised_oom_degrades_depth_and_completes(tmp_path):
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    plan = chaos.from_dict(
        {"name": "oom", "faults": [{"round": 6, "kind": "oom"}]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan, depth=2,
        checkpoint_every=4, checkpoint_path=str(tmp_path / "o_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0, backoff_base_s=0.01),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["degrades"] == 1
    assert sup["depth"] == 1  # halved from 2 — a scheduling dial only
    assert sup["recoveries"] == 0  # degrade is not a recovery


def test_poison_window_quarantines_with_reproducer(tmp_path):
    R = 12
    key, state, block = _campaign_setup(R)
    plan = chaos.from_dict(
        {"name": "poison", "faults": [
            {"round": 6, "kind": "fatal", "times": -1},
        ]}
    )
    with pytest.raises(PoisonousWindow) as exc:
        supervised_sweep(
            key, _fresh(state), scenario=block, rounds_per_dispatch=2,
            collect_decisions=True, chaos=plan,
            checkpoint_every=2,
            checkpoint_path=str(tmp_path / "p_{round}.npz"),
            config=SupervisorConfig(
                timeout_s=60.0, poison_threshold=3, backoff_base_s=0.01,
            ),
        )
    rep = exc.value.reproducer
    assert rep["failures"] == 3 and rep["fault"] == "fatal"
    # The window keys off the campaign's completed-rows high-water mark
    # — STABLE across attempts because replay is bit-exact (rounds [0,2)
    # retired before the depth-delayed schedule reached the fault).
    assert rep["window"] == [2, 4]
    assert rep["resume"] is not None and rep["resume"].endswith("p_2.npz")
    on_disk = json.loads((tmp_path / "poison_2.json").read_text())
    assert on_disk["window"] == rep["window"]
    assert on_disk["hint"]


def test_supervised_kill_and_rerun_completes_bit_exact(tmp_path):
    # THE acceptance criterion: a mid-campaign SIGKILL (the real
    # preemption, injected by the chaos plan) kills the child process;
    # rerunning the SAME supervised call picks the campaign up from the
    # newest checkpoint (resume="auto") and the assembled result —
    # decisions, leaders, every counter block — is bit-identical to the
    # uninterrupted run.
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    ck = tmp_path / "kill_{round}.npz"
    child = f'''
import dataclasses, jax.random as jr
from ba_tpu.parallel import make_sweep_state
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
from ba_tpu.scenario import compile_scenario, from_dict

key = jr.key(91)
state = make_sweep_state(jr.key(90), 16, 8, order=1)
state = dataclasses.replace(
    state, faulty=state.faulty.at[:8, 0].set(True)
)
spec = from_dict({{
    "name": "supervised-campaign", "rounds": {R}, "order": "attack",
    "events": [
        {{"round": 2, "kill": [1]}},
        {{"round": 5, "set_faulty": [3], "value": True}},
        {{"round": 6, "set_strategy": [3], "value": "adaptive_split"}},
        {{"round": 9, "revive": [1]}},
    ],
}})
block = compile_scenario(spec, 16, 8, sparse=True)
plan = chaos.from_dict({{
    "name": "mid-kill",
    "faults": [{{"round": 10, "kind": "kill"}}],
}})
supervised_sweep(
    key, state, scenario=block, rounds_per_dispatch=2,
    collect_decisions=True, chaos=plan,
    checkpoint_every=4, checkpoint_path={str(ck)!r},
    checkpoint_keep_last=1,
    config=SupervisorConfig(timeout_s=60.0),
)
raise SystemExit("unreachable: the kill fault must have fired")
'''
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(REPO), timeout=600, env=env,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    # The child got the round-4 checkpoint out before dying (the kill
    # fires at the [10, 12) dispatch, BEFORE the depth-delayed retire
    # that would have written the round-8 checkpoint), and
    # checkpoint_keep_last=1 retention kept only the newest CARRY —
    # but every rows sidecar survives (supervisor-owned retention is
    # sidecar-preserving: the sidecars ARE the campaign history).
    assert (tmp_path / "kill_4.npz").exists()
    assert (tmp_path / "kill_4.npz.rows.npz").exists()
    # The successor: the SAME call, no chaos — resume="auto" finds the
    # newest valid checkpoint and merges the sidecar chain, including
    # ORPHAN sidecars whose carry was pruned.
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True,
        checkpoint_every=4, checkpoint_path=str(ck),
        checkpoint_keep_last=1,
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    sup = got["supervisor"]
    assert sup["history_start"] == 0  # the rows sidecar restored [0, 8)
    assert sup["attempts"] == 1
    # Retention end-state: one carry (the final), every sidecar.
    carries = [r for r, _ in snapshot.checkpoint_paths(str(ck))]
    assert carries == [R]
    side_rounds = sorted(
        int(p.name.split("_")[1].split(".")[0])
        for p in tmp_path.glob("kill_*.npz.rows.npz")
    )
    assert side_rounds == [4, 8, 12]


def test_supervised_plain_sweep_parity_and_donation_guard():
    # The non-scenario path: plain pipeline_sweep under supervision,
    # with the supervisor's own engine-kwarg guard.
    R = 6
    key = jr.key(7)
    state = make_sweep_state(jr.key(0), 16, 8, order=ATTACK)
    want = pipeline_sweep(
        key, _fresh(state), R, rounds_per_dispatch=2, collect_decisions=True
    )
    got = supervised_sweep(
        key, _fresh(state), R, rounds_per_dispatch=2,
        collect_decisions=True, config=SupervisorConfig(timeout_s=60.0),
    )
    np.testing.assert_array_equal(got["decisions"], want["decisions"])
    np.testing.assert_array_equal(got["histograms"], want["histograms"])
    with pytest.raises(ValueError, match="owned by the supervisor"):
        supervised_sweep(key, None, R, exec_seam=lambda *a: None)
    with pytest.raises(ValueError, match="rounds"):
        supervised_sweep(key, None)


def test_rerun_after_completion_replays_last_window(tmp_path):
    # A COMPLETED campaign's final checkpoint (round == rounds) is valid
    # but not resumable; rerunning the same supervised call must pick
    # the previous checkpoint (below=rounds), replay the last window and
    # return the full bit-identical result — NOT poison itself retrying
    # the final checkpoint the engine refuses.
    R = 8
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    ck = str(tmp_path / "done_{round}.npz")
    kw = dict(
        scenario=block, rounds_per_dispatch=2, collect_decisions=True,
        checkpoint_every=4, checkpoint_path=ck,
        config=SupervisorConfig(timeout_s=60.0),
    )
    first = supervised_sweep(key, _fresh(state), **kw)
    _assert_bit_identical(first, want)
    assert (tmp_path / "done_8.npz").exists()
    again = supervised_sweep(key, _fresh(state), **kw)
    _assert_bit_identical(again, want)
    assert again["supervisor"]["attempts"] == 1
    assert again["supervisor"]["history_start"] == 0
    # The final checkpoint was skipped, never quarantined.
    assert (tmp_path / "done_8.npz").exists()
    assert not (tmp_path / "done_8.npz.corrupt").exists()


def test_auto_resume_refuses_foreign_campaign(tmp_path):
    # A checkpoint family left behind by a DIFFERENT campaign at the
    # same path must refuse loudly (campaign_sha256 fingerprint), not
    # silently splice its carry into this run.
    R = 12
    key, state, block = _campaign_setup(R)
    ck = str(tmp_path / "own_{round}.npz")
    kw = dict(
        scenario=block, rounds_per_dispatch=2, collect_decisions=True,
        checkpoint_every=4, checkpoint_path=ck,
        config=SupervisorConfig(timeout_s=60.0),
    )
    supervised_sweep(key, _fresh(state), **kw)
    meta = snapshot.validate_carry_checkpoint(str(tmp_path / "own_4.npz"))
    assert len(meta["campaign_sha256"]) == 64
    from ba_tpu.runtime.supervisor import SupervisorError

    with pytest.raises(SupervisorError, match="DIFFERENT campaign"):
        supervised_sweep(jr.key(12345), _fresh(state), **kw)


def test_recovery_skips_foreign_family_resumes_own(tmp_path):
    # A stale FOREIGN campaign's newer checkpoints share the template
    # (the operator overrode the entry guard with resume=None): fault
    # recovery must step over them (campaign_sha256 filter) and resume
    # this campaign's own newest checkpoint — never splice the foreign
    # carry in.
    R = 12
    key, state, block = _campaign_setup(R)
    want = _baseline(key, state, block, R)
    ck = str(tmp_path / "shared_{round}.npz")
    # Campaign A (different key): leaves ck_4/8/12 with A's fingerprint.
    supervised_sweep(
        jr.key(777), _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, checkpoint_every=4, checkpoint_path=ck,
        config=SupervisorConfig(timeout_s=60.0),
    )
    # Campaign B: fresh start (resume=None), fatal at round 8 — by then
    # B has overwritten ck_4 with its own; recovery must pick B's ck_4,
    # skipping A's newer ck_8/ck_12.
    plan = chaos.from_dict(
        {"name": "f", "faults": [{"round": 8, "kind": "fatal"}]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan, resume=None,
        checkpoint_every=4, checkpoint_path=ck,
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    assert got["supervisor"]["recoveries"] == 1
    # A's checkpoints were stepped over, not quarantined.
    assert not (tmp_path / "shared_12.npz.corrupt").exists()


def test_initial_strategy_campaign_recovers(tmp_path):
    # The engine rejects initial_strategy alongside resume= (the carry
    # supplies the live plane); the supervisor must drop it on resumed
    # attempts — otherwise the first recovery of any initial_strategy
    # campaign dies in a bogus PoisonousWindow.
    import numpy as np

    R = 12
    key, state, block = _campaign_setup(R)
    plane = np.zeros((16, 8), np.int8)
    want = pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, initial_strategy=plane,
    )
    plan = chaos.from_dict(
        {"name": "f", "faults": [{"round": 8, "kind": "fatal"}]}
    )
    got = supervised_sweep(
        key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, chaos=plan, initial_strategy=plane,
        checkpoint_every=4, checkpoint_path=str(tmp_path / "is_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0),
    )
    _assert_bit_identical(got, want)
    assert got["supervisor"]["recoveries"] == 1


def test_unrecoverable_explicit_resume_raises_cleanly(tmp_path):
    # Entered via explicit resume= (key/state None) with no
    # checkpoint_path: a fatal fault has nothing to restart from, and
    # must surface a clear SupervisorError chaining the real fault —
    # not a TypeError from the engine consuming state=None.
    from ba_tpu.runtime.supervisor import SupervisorError

    R = 8
    path = tmp_path / "seed_{round}.npz"
    _toy_checkpoint(path, round_=4, R=R)
    _, _, block = _campaign_setup(R)
    plan = chaos.from_dict(
        {"name": "dead-end", "faults": [
            {"round": 6, "kind": "fatal", "times": -1},
        ]}
    )
    with pytest.raises(SupervisorError, match="cannot recover") as exc:
        supervised_sweep(
            None, None, scenario=block, rounds_per_dispatch=2,
            collect_decisions=True, chaos=plan,
            resume=str(tmp_path / "seed_4.npz"),
            config=SupervisorConfig(timeout_s=60.0),
        )
    assert isinstance(exc.value.__cause__, chaos.InjectedFatal)


def test_prune_companions_false_keeps_sidecars(tmp_path):
    tmpl = str(tmp_path / "ck_{round}.npz")
    for r in (2, 4, 6):
        (tmp_path / f"ck_{r}.npz").write_bytes(b"x")
        (tmp_path / f"ck_{r}.npz.rows.npz").write_bytes(b"y")
    removed = snapshot.prune_checkpoints(tmpl, keep_last=1, companions=False)
    assert sorted(removed) == [
        str(tmp_path / "ck_2.npz"), str(tmp_path / "ck_4.npz")
    ]
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == [
        "ck_2.npz.rows.npz", "ck_4.npz.rows.npz",
        "ck_6.npz", "ck_6.npz.rows.npz",
    ]


def test_checkpoint_meta_reserved_keys_rejected_eagerly():
    key = jr.key(0)
    with pytest.raises(ValueError, match="reserved"):
        pipeline_sweep(
            key, make_sweep_state(jr.key(1), 4, 4), 4,
            checkpoint_every=2, checkpoint_path="x_{round}.npz",
            checkpoint_meta={"round": 5},
        )
    with pytest.raises(ValueError, match="checkpoint_every"):
        pipeline_sweep(
            key, make_sweep_state(jr.key(1), 4, 4), 4,
            checkpoint_meta={"campaign_sha256": "x"},
        )


def test_config_errors_bypass_recovery(monkeypatch, tmp_path):
    # Deterministic engine/parameter validation errors must surface
    # IMMEDIATELY — not burn the poison budget re-running the campaign
    # and then masquerade as a PoisonousWindow.
    R = 8
    key, state, block = _campaign_setup(R)
    # rounds disagrees with the scenario block: the engine's eager
    # ValueError propagates on attempt 1, no recovery records emitted.
    with pytest.raises(ValueError, match="scenario block covers"):
        supervised_sweep(
            key, _fresh(state), R + 4, scenario=block,
            rounds_per_dispatch=2,
            config=SupervisorConfig(timeout_s=60.0),
        )
    # A zero watchdog timeout is a config error naming the knob, caught
    # before any attempt runs.
    monkeypatch.setenv("BA_TPU_SUPERVISE_TIMEOUT_S", "0")
    with pytest.raises(ValueError, match="BA_TPU_SUPERVISE_TIMEOUT_S"):
        supervised_sweep(
            key, _fresh(state), scenario=block, rounds_per_dispatch=2,
        )
    # keep_last with the {round} slot in the DIRECTORY component is
    # rejected eagerly, not at the first mid-campaign prune.
    monkeypatch.delenv("BA_TPU_SUPERVISE_TIMEOUT_S")
    with pytest.raises(ValueError, match="directory component"):
        supervised_sweep(
            key, _fresh(state), scenario=block, rounds_per_dispatch=2,
            checkpoint_every=2,
            checkpoint_path=str(tmp_path / "d_{round}" / "carry.npz"),
            checkpoint_keep_last=2,
            config=SupervisorConfig(timeout_s=60.0),
        )


def test_cluster_supervised_refuses_partial_history(tmp_path, monkeypatch):
    # Checkpoints written UNSUPERVISED carry no rows sidecars; a
    # supervised rerun over them can only assemble the tail — the
    # cluster's per-round decision tally would silently cover a
    # fraction of the campaign, so the backend refuses loudly.
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    spec = _wiring_spec()
    ck = str(tmp_path / "un_{round}.npz")
    # 2 rounds/dispatch so a MID-campaign checkpoint exists (the final
    # one is excluded from resume by the below=rounds cut).
    monkeypatch.setenv("BA_TPU_PIPELINE_ROUNDS", "2")
    Cluster(4, JaxBackend(platform="cpu", m=1), seed=7).run_scenario(
        spec, checkpoint_every=4, checkpoint_path=ck
    )
    with pytest.raises(ValueError, match="sidecars"):
        Cluster(4, JaxBackend(platform="cpu", m=1), seed=7).run_scenario(
            spec, checkpoint_every=4, checkpoint_path=ck, supervise=True
        )


def test_newest_valid_checkpoint_below_cut(tmp_path):
    path = tmp_path / "cut_{round}.npz"
    _toy_checkpoint(path, round_=4, R=8)  # writes cut_4 and cut_8
    found = snapshot.newest_valid_checkpoint(str(path), below=8)
    assert found is not None and found[1]["round"] == 4
    # below respects the meta cursor too, and never quarantines.
    assert snapshot.newest_valid_checkpoint(str(path), below=4) is None
    assert (tmp_path / "cut_4.npz").exists()
    assert (tmp_path / "cut_8.npz").exists()


# -- runtime wiring (backend / cluster / REPL) --------------------------------


def _wiring_spec():
    return from_dict(
        {"name": "wire", "order": "attack", "rounds": 8,
         "events": [{"round": 2, "kill": [2]},
                    {"round": 5, "revive": [2]}]}
    )


def test_cluster_supervised_scenario_parity(tmp_path):
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    spec = _wiring_spec()
    ref = Cluster(4, JaxBackend(platform="cpu", m=1), seed=7).run_scenario(
        spec
    )
    plan = chaos.from_dict(
        {"name": "t", "faults": [{"round": 3, "kind": "transient"}]}
    )
    sup = Cluster(4, JaxBackend(platform="cpu", m=1), seed=7).run_scenario(
        spec, checkpoint_every=4,
        checkpoint_path=str(tmp_path / "cl_{round}.npz"),
        supervise=True, fault_plan=plan,
    )
    (rc, rres), (sc, sres) = ref, sup
    assert rc == sc
    assert rres["decisions"] == sres["decisions"]
    assert rres["leaders"] == sres["leaders"]
    assert rres["counters"] == sres["counters"]
    assert rres["alive"] == sres["alive"]
    assert sres["stats"]["supervisor"]["retries"] == 1


def test_backend_fault_plan_requires_supervise():
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    plan = chaos.from_dict({"name": "t", "faults": []})
    cluster = Cluster(4, JaxBackend(platform="cpu", m=1), seed=0)
    with pytest.raises(ValueError, match="supervise"):
        cluster.run_scenario(_wiring_spec(), fault_plan=plan)


def test_repl_scenario_supervise_flag(tmp_path):
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    spec_path = tmp_path / "s.json"
    spec_path.write_text(json.dumps(
        {"name": "s", "order": "attack", "rounds": 4,
         "events": [{"round": 1, "kill": [2]}]}
    ))
    cluster = Cluster(4, JaxBackend(platform="cpu", m=1), seed=3)
    lines = []
    handle_command(cluster, f"scenario {spec_path} supervise", lines.append)
    assert any(l.startswith("Scenario supervisor: attempts=1") for l in lines)
    # Unsupervised output stays supervisor-line-free.
    cluster2 = Cluster(4, JaxBackend(platform="cpu", m=1), seed=3)
    lines2 = []
    handle_command(cluster2, f"scenario {spec_path}", lines2.append)
    assert not any("supervisor" in l for l in lines2)
    # A bare `scenario supervise` has no file: ignored like `scenario`.
    lines3 = []
    assert handle_command(cluster2, "scenario supervise", lines3.append)
    assert lines3 == []


# -- observability records ----------------------------------------------------


def test_recovery_and_fault_records_schema(tmp_path):
    # The supervised run's JSONL stream carries versioned recovery +
    # fault_injected records (the shapes check_metrics_schema.py
    # type-checks in CI).
    from ba_tpu.utils import metrics

    R = 12
    key, state, block = _campaign_setup(R)
    plan = chaos.from_dict(
        {"name": "rec", "faults": [
            {"round": 2, "kind": "transient"},
            {"round": 8, "kind": "fatal"},
        ]}
    )
    sink = tmp_path / "metrics.jsonl"
    old = metrics._default
    metrics._default = metrics.MetricsSink(str(sink))
    try:
        supervised_sweep(
            key, _fresh(state), scenario=block, rounds_per_dispatch=2,
            collect_decisions=True, chaos=plan,
            checkpoint_every=4,
            checkpoint_path=str(tmp_path / "r_{round}.npz"),
            config=SupervisorConfig(timeout_s=60.0, backoff_base_s=0.01),
        )
    finally:
        metrics._default.close()
        metrics._default = old
    recs = [json.loads(l) for l in sink.read_text().splitlines()]
    inj = [r for r in recs if r["event"] == "fault_injected"]
    assert [r["kind"] for r in inj] == ["transient", "fatal"]
    for r in inj:
        assert r["v"] == 1 and r["plan"] == "rec"
        assert isinstance(r["round"], int) and r["phase"] in (
            "dispatch", "retire", "checkpoint"
        )
    rec = [r for r in recs if r["event"] == "recovery"]
    assert len(rec) == 1
    r = rec[0]
    assert r["v"] == 1 and r["fault"] == "fatal" and r["action"] == "resume"
    assert isinstance(r["from_round"], int)
    assert isinstance(r["lost_rounds"], int) and r["lost_rounds"] >= 0
    assert r["error"].startswith("InjectedFatal")
