"""Serving front-end tests (ISSUE 10).

Two layers, mirroring the module's own split:

- the jax-free admission layer (request validation, shed-tier ladder,
  bounded-queue rejection, deadline bookkeeping, client-tier fault
  plans, the host-tier import contract) — these are the
  ``scripts/ci.sh`` serve-smoke subset (``-k "tier or admission or
  validate or plan or ticket or jax_free"``) and never touch jax;
- the engine-backed serving layer: the COALESCED-BATCH PARITY pin (the
  acceptance criterion — any request served in a coalesced batch is
  bit-identical to the same request run alone at equal padded
  capacity), overload determinism, deadline expiry before-dispatch vs
  in-flight, and per-cohort fault isolation.
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np
import pytest

from ba_tpu.obs.registry import MetricsRegistry
from ba_tpu.runtime import chaos
from ba_tpu.runtime.serve import (
    AgreementRequest,
    AgreementService,
    DeadlineExceeded,
    Overloaded,
    RequestFailed,
    ServeConfig,
    ServeError,
    Ticket,
    cohort_key,
    shed_tier,
    validate_request,
)


# -- jax-free admission layer -------------------------------------------------


def test_serve_import_is_jax_free():
    # The BA301 host-tier contract, proven at runtime: importing the
    # service must not pull jax (admission control and plan validation
    # run on hosts without it).
    code = (
        "import sys; import ba_tpu.runtime.serve; "
        "assert 'jax' not in sys.modules, 'serve import pulled jax'; "
        "print('ok')"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "ok"


def test_shed_tier_ladder():
    cfg = ServeConfig()
    # Healthy; absent signals never raise the tier.
    assert shed_tier(0.0, None, None, cfg) == 0
    assert shed_tier(0.0, 0.1, 1.0, cfg) == 0
    # Tier 1: queue soft, lag soft, or device saturation.
    assert shed_tier(cfg.queue_soft_frac, None, None, cfg) == 1
    assert shed_tier(0.0, cfg.lag_soft_s, None, cfg) == 1
    assert shed_tier(0.0, None, float(cfg.depth), cfg) == 1
    # Tier 2: queue hard or lag hard (inf — the overflow bucket —
    # counts as hard).
    assert shed_tier(cfg.queue_hard_frac, None, None, cfg) == 2
    assert shed_tier(0.0, cfg.lag_hard_s, None, cfg) == 2
    assert shed_tier(0.0, float("inf"), None, cfg) == 2
    # Tier 3: queue full beats everything.
    assert shed_tier(1.0, None, None, cfg) == 3


def test_serve_config_validate_and_env(monkeypatch):
    with pytest.raises(ValueError):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError):
        ServeConfig(max_queue=0)
    with pytest.raises(ValueError):
        ServeConfig(coalesce_window_s=-1.0)
    with pytest.raises(ValueError):
        ServeConfig(queue_soft_frac=0.9, queue_hard_frac=0.5)
    with pytest.raises(ValueError):
        ServeConfig(lag_soft_s=9.0, lag_hard_s=1.0)
    monkeypatch.setenv("BA_TPU_SERVE_BATCH", "16")
    monkeypatch.setenv("BA_TPU_SERVE_QUEUE", "5")
    monkeypatch.setenv("BA_TPU_SERVE_WINDOW_S", "0.25")
    monkeypatch.setenv("BA_TPU_SERVE_DEADLINE_S", "")
    cfg = ServeConfig.from_env()
    assert (cfg.max_batch, cfg.max_queue) == (16, 5)
    assert cfg.coalesce_window_s == 0.25
    assert cfg.default_deadline_s is None  # "" = no deadline
    monkeypatch.setenv("BA_TPU_SERVE_RETRIES", "7")
    assert cfg.resolved_max_retries() == 7


def test_validate_request_errors():
    validate_request(AgreementRequest())  # the default is valid
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(kind="nope"))
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(order="surrender"))
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(n=0))
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(faulty=(4,)))  # outside n=4
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(faulty=(True,)))
    with pytest.raises(ValueError):  # actual-order is one round
        validate_request(AgreementRequest(kind="actual-order", rounds=3))
    with pytest.raises(ValueError):
        validate_request(AgreementRequest(kind="run-rounds", rounds=0))
    with pytest.raises(ValueError):  # scenario needs a spec
        validate_request(AgreementRequest(kind="scenario"))
    with pytest.raises(ValueError):  # ...and only scenario takes one
        validate_request(AgreementRequest(kind="run-rounds", spec=object()))
    # Cohorts: same (scenario-ness, rounds, padded capacity) coalesce;
    # an actual-order and a 1-round run-rounds share a batch.
    a = AgreementRequest(kind="actual-order", n=3, seed=1)
    b = AgreementRequest(kind="run-rounds", n=4, seed=2, rounds=1)
    c = AgreementRequest(kind="run-rounds", n=5, seed=3, rounds=1)
    assert cohort_key(a) == cohort_key(b)
    assert cohort_key(c) != cohort_key(b)  # capacity 8 vs 4


def test_admission_closed_service_rejects():
    svc = AgreementService(ServeConfig(max_queue=2), registry=MetricsRegistry())
    with pytest.raises(ServeError):
        svc.submit(AgreementRequest())
    svc.open()
    t = svc.submit(AgreementRequest())
    assert isinstance(t, Ticket) and not t.done()
    svc.stop()  # never started: queued ticket fails loudly
    with pytest.raises(ServeError):
        t.result(timeout=1)
    with pytest.raises(ServeError):
        svc.submit(AgreementRequest())  # closed again


def test_admission_queue_full_is_bounded_rejection():
    cfg = ServeConfig(max_queue=3)
    svc = AgreementService(cfg, registry=MetricsRegistry())
    svc.open()  # admission without the dispatcher: deterministic fill
    for i in range(cfg.max_queue):
        svc.submit(AgreementRequest(kind="run-rounds", seed=i, rounds=2))
    with pytest.raises(Overloaded) as exc:
        svc.submit(AgreementRequest(kind="run-rounds", seed=99, rounds=2))
    assert exc.value.reason == "queue_full"
    assert exc.value.retry_after_s > 0
    # The queue NEVER grew past its bound (the overload acceptance
    # criterion's memory half): depth stays max_queue however many
    # submissions storm in.
    for i in range(10):
        with pytest.raises(Overloaded):
            svc.submit(AgreementRequest(kind="run-rounds", rounds=2))
    assert svc.stats()["queue_depth"] == cfg.max_queue
    assert svc.stats()["rejected"] == 11
    svc.stop()


def test_admission_sheds_interactive_before_campaigns():
    from ba_tpu.scenario import from_dict

    svc = AgreementService(ServeConfig(max_queue=100), registry=MetricsRegistry())
    svc.open()
    spec = from_dict({"name": "t", "rounds": 2, "events": []})
    # Tier 2 (set directly — the ladder itself is unit-tested above,
    # and the live transition is driven end-to-end by
    # scripts/check_metrics_schema.py): interactive sheds, campaigns
    # still admit.
    svc._tier = 2
    with pytest.raises(Overloaded) as exc:
        svc.submit(AgreementRequest(kind="run-rounds", rounds=2))
    assert exc.value.reason == "shed_interactive"
    with pytest.raises(Overloaded):
        svc.submit(AgreementRequest(kind="actual-order"))
    svc.submit(AgreementRequest(kind="scenario", spec=spec))  # admitted
    # Tier 3: everything rejects.
    svc._tier = 3
    with pytest.raises(Overloaded) as exc:
        svc.submit(AgreementRequest(kind="scenario", spec=spec))
    assert exc.value.reason == "shed_all"
    svc.stop()


def test_ticket_result_timeout():
    t = Ticket(AgreementRequest(), 1, None)
    with pytest.raises(TimeoutError):
        t.result(timeout=0.01)
    t._resolve({"x": 1})
    assert t.result(timeout=1) == {"x": 1}


def test_client_fault_plan_round_trip_and_validation():
    doc = {
        "name": "clients",
        "faults": [
            {"round": 2, "kind": "slow_client", "seconds": 0.5,
             "times": 3},
            {"round": 4, "kind": "abandon"},
            {"round": 6, "kind": "deadline_storm"},
        ],
    }
    plan = chaos.from_dict(doc)
    assert [f.phase for f in plan.faults] == ["client"] * 3
    assert chaos.to_dict(plan) == doc  # defaults omitted, byte-stable
    with pytest.raises(chaos.FaultPlanError):  # needs seconds
        chaos.from_dict({"name": "x", "faults": [
            {"round": 0, "kind": "slow_client"}]})
    with pytest.raises(chaos.FaultPlanError):  # seconds meaningless
        chaos.from_dict({"name": "x", "faults": [
            {"round": 0, "kind": "abandon", "seconds": 1.0}]})
    with pytest.raises(chaos.FaultPlanError):  # client kind, engine phase
        chaos.from_dict({"name": "x", "faults": [
            {"round": 0, "kind": "abandon", "phase": "dispatch"}]})
    with pytest.raises(chaos.FaultPlanError):  # engine kind, client phase
        chaos.from_dict({"name": "x", "faults": [
            {"round": 0, "kind": "transient", "phase": "client"}]})


def test_client_fault_plan_ordinal_consumption():
    plan = chaos.from_dict({
        "name": "t",
        "faults": [
            {"round": 1, "kind": "slow_client", "seconds": 0.1,
             "times": 2},
            {"round": 1, "kind": "abandon"},
        ],
    })
    inj = chaos.ChaosInjector(plan)
    assert inj.client_faults(0) == []
    fired = inj.client_faults(1)
    assert sorted(f.kind for f in fired) == ["abandon", "slow_client"]
    # times respected; the slow_client entry has one firing left but
    # only matches its own ordinal.
    assert [f.kind for f in inj.client_faults(1)] == ["slow_client"]
    assert inj.client_faults(1) == []
    assert len(inj.fired) == 3
    assert all(f["phase"] == "client" for f in inj.fired)


def test_committed_deadline_storm_plan_is_valid():
    plan = chaos.load("examples/faults/deadline_storm.json")
    kinds = {f.kind for f in plan.faults}
    assert kinds == {"slow_client", "abandon", "deadline_storm"}
    assert all(f.phase == "client" for f in plan.faults)


# -- engine-backed serving layer ----------------------------------------------


def _alone_state(n, faulty, order, cap):
    """The B=1 padded state a request run ALONE would use (exactly the
    service's staging at batch slot 0)."""
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState
    from ba_tpu.core.types import COMMAND_DTYPE, command_from_name
    from ba_tpu.parallel.pipeline import fresh_copy

    f = np.zeros((1, cap), bool)
    a = np.zeros((1, cap), bool)
    a[0, :n] = True
    for i in faulty:
        f[0, i] = True
    return fresh_copy(
        SimState(
            order=jnp.full((1,), command_from_name(order), COMMAND_DTYPE),
            leader=jnp.zeros((1,), jnp.int32),
            faulty=jnp.asarray(f),
            alive=jnp.asarray(a),
            ids=jnp.asarray(
                np.arange(1, cap + 1, dtype=np.int32)[None, :]
            ),
        )
    )


def _alone_run(req, rounds=None, scenario_block=None):
    """The reference the parity pin compares against: the same request
    run ALONE through the standard engine at equal padded capacity."""
    import jax.random as jr

    from ba_tpu.parallel.pipeline import pipeline_sweep, scenario_sweep

    cap = 4
    state = _alone_state(req.n, req.faulty, req.order, cap)
    if scenario_block is not None:
        return scenario_sweep(
            jr.key(req.seed), state, scenario_block,
            collect_decisions=True, rounds_per_dispatch=2,
        )
    return pipeline_sweep(
        jr.key(req.seed), state, rounds, collect_decisions=True,
        with_counters=True, rounds_per_dispatch=2,
    )


def test_coalesced_parity_plain():
    # THE acceptance pin (heart of ISSUE 10): every slot of a coalesced
    # batch is bit-identical to its own run alone at equal padded
    # capacity — decisions, per-slot counters, final majorities.
    import jax.random as jr

    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy

    reqs = [
        AgreementRequest(kind="run-rounds", order="attack", n=4,
                         faulty=(2,), seed=11, rounds=4),
        AgreementRequest(kind="run-rounds", order="retreat", n=3,
                         faulty=(), seed=12, rounds=4),
        AgreementRequest(kind="run-rounds", order="attack", n=4,
                         faulty=(1, 3), seed=13, rounds=4),
    ]
    import jax.numpy as jnp

    from ba_tpu.core.state import SimState

    rows = [_alone_state(r.n, r.faulty, r.order, 4) for r in reqs]
    batched = fresh_copy(
        SimState(*[
            jnp.concatenate([getattr(s, f) for s in rows])
            for f in ("order", "leader", "faulty", "alive", "ids")
        ])
    )
    co = coalesced_sweep(
        [jr.key(r.seed) for r in reqs], batched, 4,
        rounds_per_dispatch=2,
    )
    retire_windows = []
    co2 = coalesced_sweep(
        [jr.key(r.seed) for r in reqs],
        fresh_copy(SimState(*[
            jnp.concatenate([getattr(s, f) for s in
                             [_alone_state(r.n, r.faulty, r.order, 4)
                              for r in reqs]])
            for f in ("order", "leader", "faulty", "alive", "ids")
        ])),
        4, rounds_per_dispatch=2,
        on_retire=lambda d, lo, hi, ys: retire_windows.append((lo, hi)),
    )
    # The slot→request mapping hook saw every round window, in order.
    assert retire_windows == [(0, 2), (2, 4)]
    np.testing.assert_array_equal(co2["decisions"], co["decisions"])
    for i, req in enumerate(reqs):
        alone = _alone_run(req, rounds=4)
        np.testing.assert_array_equal(
            co["decisions"][:, i], alone["decisions"][:, 0]
        )
        got = dict(zip(co["counter_names"], (int(v) for v in
                                             co["counters"][i])))
        assert got == alone["counters"]
    # Majorities: alone at B=1 through the same coalesced entry.
    for i, req in enumerate(reqs):
        solo = coalesced_sweep(
            [jr.key(req.seed)],
            _alone_state(req.n, req.faulty, req.order, 4),
            4, rounds_per_dispatch=2,
        )
        np.testing.assert_array_equal(
            co["majorities"][i], solo["majorities"][0]
        )


def test_coalesced_parity_scenario():
    import jax.numpy as jnp
    import jax.random as jr

    from ba_tpu.core.state import SimState
    from ba_tpu.parallel.pipeline import coalesced_sweep, fresh_copy
    from ba_tpu.scenario import compile_scenario, from_dict

    spec_a = from_dict({"name": "a", "rounds": 4, "events": [
        {"round": 1, "kill": [1]},
    ]})
    spec_b = from_dict({"name": "b", "rounds": 4, "events": [
        {"round": 2, "set_faulty": [2], "value": True},
        {"round": 3, "set_strategy": [2], "value": "collude_attack"},
    ]})
    ids = np.arange(1, 5, dtype=np.int64)
    blocks = [
        compile_scenario(s, 1, 4, ids=ids) for s in (spec_a, spec_b)
    ]
    reqs = [
        AgreementRequest(kind="scenario", n=4, seed=21, spec=spec_a),
        AgreementRequest(kind="scenario", n=4, faulty=(3,), seed=22,
                         spec=spec_b),
    ]
    rows = [_alone_state(r.n, r.faulty, r.order, 4) for r in reqs]
    batched = fresh_copy(
        SimState(*[
            jnp.concatenate([getattr(s, f) for s in rows])
            for f in ("order", "leader", "faulty", "alive", "ids")
        ])
    )
    planes = {
        name: np.concatenate(
            [getattr(b, name) for b in blocks], axis=1
        )
        for name in ("kill", "revive", "set_faulty", "set_strategy")
    }
    co = coalesced_sweep(
        [jr.key(r.seed) for r in reqs], batched, 4,
        rounds_per_dispatch=2, scenario=planes,
    )
    for i, (req, block) in enumerate(zip(reqs, blocks)):
        alone = _alone_run(req, scenario_block=block)
        np.testing.assert_array_equal(
            co["decisions"][:, i], alone["decisions"][:, 0]
        )
        np.testing.assert_array_equal(
            co["leaders"][:, i], alone["leaders"][:, 0]
        )
        got = dict(zip(co["counter_names"], (int(v) for v in
                                             co["counters"][i])))
        assert got == alone["counters"]


def test_serve_batched_requests_bit_exact_and_coalesced():
    # The service path end-to-end: concurrent submissions coalesce into
    # ONE batch and every result matches its alone run.
    svc = AgreementService(
        ServeConfig(max_batch=4, max_queue=16, coalesce_window_s=0.25,
                    rounds_per_dispatch=2),
        registry=MetricsRegistry(),
    )
    svc.start()
    reqs = [
        AgreementRequest(kind="run-rounds", order=("attack", "retreat")[i % 2],
                         n=(4, 3, 4, 2)[i], faulty=((2,), (), (1,), ())[i],
                         seed=30 + i, rounds=4)
        for i in range(4)
    ]
    tickets = [svc.submit(r) for r in reqs]
    outs = [t.result(timeout=300) for t in tickets]
    try:
        assert [o["batch"] for o in outs] == [4, 4, 4, 4]
        assert sorted(o["slot"] for o in outs) == [0, 1, 2, 3]
        for req, out in zip(reqs, outs):
            alone = _alone_run(req, rounds=4)
            assert out["decisions"] == [
                int(v) for v in alone["decisions"][:, 0]
            ]
            assert out["counters"] == alone["counters"]
            assert out["run_id"].startswith("run-")
    finally:
        svc.stop()


def test_overload_path_deterministic_no_deadlock():
    # Fill the bounded queue with the dispatcher parked, overflow
    # rejects explicitly, then the dispatcher drains EVERYTHING — no
    # deadlock, every ticket terminal.
    cfg = ServeConfig(max_batch=4, max_queue=4, coalesce_window_s=0.05,
                      rounds_per_dispatch=2)
    svc = AgreementService(cfg, registry=MetricsRegistry())
    svc.open()
    tickets = [
        svc.submit(AgreementRequest(kind="run-rounds", seed=40 + i,
                                    rounds=2))
        for i in range(cfg.max_queue)
    ]
    with pytest.raises(Overloaded):
        svc.submit(AgreementRequest(kind="run-rounds", rounds=2))
    svc.start()
    outs = [t.result(timeout=300) for t in tickets]
    assert all(o["counts"]["attack"] + o["counts"]["retreat"]
               + o["counts"]["undefined"] == 2 for o in outs)
    st = svc.stats()
    assert st["completed"] == 4 and st["rejected"] == 1
    assert st["queue_depth"] == 0
    svc.stop()
    assert not svc.running()


def test_deadline_expiry_before_dispatch_vs_in_flight():
    # Before-dispatch: an expired budget cancels the request with
    # DeadlineExceeded.  In-flight: a deadline passing AFTER dispatch
    # never cancels — the donated cohort completes and the (late)
    # result is still delivered.
    plan = chaos.from_dict({"name": "slow", "faults": [
        {"round": 0, "kind": "stall", "phase": "dispatch",
         "seconds": 0.4},
    ]})
    svc = AgreementService(
        ServeConfig(max_batch=2, max_queue=8, coalesce_window_s=0.001,
                    rounds_per_dispatch=2),
        fault_plan=plan,
        registry=MetricsRegistry(),
    )
    svc.open()
    dead = svc.submit(
        AgreementRequest(kind="run-rounds", seed=50, rounds=2),
        deadline_s=0.0,
    )
    svc.start()
    with pytest.raises(DeadlineExceeded):
        dead.result(timeout=60)
    # In-flight: dispatch starts immediately (empty queue, ~zero
    # window) and the injected 0.4 s stall pushes completion past the
    # 0.15 s budget — the result must still arrive.
    t0 = time.perf_counter()
    late = svc.submit(
        AgreementRequest(kind="run-rounds", seed=51, rounds=2),
        deadline_s=0.15,
    )
    out = late.result(timeout=60)
    assert time.perf_counter() - t0 >= 0.35
    assert out["counts"]
    assert svc.stats()["expired"] == 1
    svc.stop()


def test_cohort_fatal_fails_only_its_cohort():
    # A mid-request injected fatal exhausts nothing but its own
    # cohort: those tickets fail with the classified fault while a
    # concurrent request (different cohort) completes bit-exactly and
    # the service keeps serving afterwards.
    plan = chaos.from_dict({"name": "one-fatal", "faults": [
        {"round": 0, "kind": "fatal"},
    ]})
    svc = AgreementService(
        ServeConfig(max_batch=2, max_queue=8, coalesce_window_s=0.02,
                    rounds_per_dispatch=2),
        fault_plan=plan,
        registry=MetricsRegistry(),
    )
    svc.open()
    doomed_req = AgreementRequest(kind="run-rounds", seed=60, rounds=4)
    doomed = svc.submit(doomed_req)
    bystander_req = AgreementRequest(kind="run-rounds", seed=61, rounds=2)
    bystander = svc.submit(bystander_req)  # different cohort (rounds)
    svc.start()
    with pytest.raises(RequestFailed) as exc:
        doomed.result(timeout=300)
    assert exc.value.fault == "fatal"
    out = bystander.result(timeout=300)
    alone = _alone_run(bystander_req, rounds=2)
    assert out["decisions"] == [int(v) for v in alone["decisions"][:, 0]]
    # The service survived: the SAME request re-submitted (fault
    # consumed, times=1) now completes bit-exactly.
    retry = svc.submit(doomed_req).result(timeout=300)
    alone2 = _alone_run(doomed_req, rounds=4)
    assert retry["decisions"] == [int(v) for v in alone2["decisions"][:, 0]]
    st = svc.stats()
    assert st["failed"] == 1 and st["completed"] == 2
    assert st["injected"] == 1
    svc.stop()


def test_serve_transient_retry_in_place():
    # Transient faults retry inside the seam (supervisor backoff +
    # classification) without failing the cohort — and the retried
    # result is bit-exact (injection fires before the donated carry is
    # consumed).
    plan = chaos.from_dict({"name": "flaky", "faults": [
        {"round": 0, "kind": "transient", "times": 2},
    ]})
    svc = AgreementService(
        ServeConfig(max_batch=2, max_queue=8, coalesce_window_s=0.001,
                    rounds_per_dispatch=2),
        fault_plan=plan,
        registry=MetricsRegistry(),
    )
    svc.start()
    req = AgreementRequest(kind="run-rounds", seed=70, rounds=2)
    out = svc.submit(req).result(timeout=300)
    alone = _alone_run(req, rounds=2)
    assert out["decisions"] == [int(v) for v in alone["decisions"][:, 0]]
    st = svc.stats()
    assert st["retries"] == 2 and st["failed"] == 0
    svc.stop()


def test_dispatch_watchdog_wedge_applies_backpressure():
    # A dispatch running past dispatch_timeout_s cannot be interrupted
    # (PR 7 semantics) — the watchdog observes and applies explicit
    # backpressure: tier 3 while wedged (submissions reject with the
    # wedge named in the shed record), the late result still delivers,
    # and the tier decays once the dispatch returns.
    plan = chaos.from_dict({"name": "wedge", "faults": [
        {"round": 0, "kind": "stall", "phase": "dispatch",
         "seconds": 1.0},
    ]})
    svc = AgreementService(
        ServeConfig(max_batch=2, max_queue=8, coalesce_window_s=0.001,
                    rounds_per_dispatch=2, dispatch_timeout_s=0.2),
        fault_plan=plan,
        registry=MetricsRegistry(),
    )
    svc.open()
    req = AgreementRequest(kind="run-rounds", seed=80, rounds=2)
    wedged = svc.submit(req)
    svc.start()
    time.sleep(0.6)  # stall 1.0 s in flight; watchdog fired at ~0.2 s
    assert svc.stats()["tier"] == 3
    with pytest.raises(Overloaded) as exc:
        svc.submit(AgreementRequest(kind="run-rounds", seed=81, rounds=2))
    assert exc.value.reason == "shed_all"
    out = wedged.result(timeout=60)  # the wedge clears, result delivers
    alone = _alone_run(req, rounds=2)
    assert out["decisions"] == [int(v) for v in alone["decisions"][:, 0]]
    # Recovery: tier decays on the dispatcher's next refresh ticks.
    later = None
    for _ in range(200):
        try:
            later = svc.submit(
                AgreementRequest(kind="run-rounds", seed=82, rounds=2)
            )
            break
        except Overloaded:
            time.sleep(0.05)
    assert later is not None, "tier never decayed after the wedge"
    later.result(timeout=60)
    assert svc.stats()["stalls"] == 1
    svc.stop()


def test_repl_serve_command():
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, PyBackend(), seed=0)
    lines = []
    handle_command(cluster, "serve", lines.append)
    assert lines and lines[0].startswith("serve error: usage")
    lines.clear()
    handle_command(cluster, "serve stat", lines.append)
    assert lines == ["serve error: not running (serve start first)"]
    lines.clear()
    handle_command(
        cluster, "serve start queue=4 window=0.01 batch=2", lines.append
    )
    assert lines == ["serve: started (queue=4, window=0.01s, batch=2)"]
    lines.clear()
    handle_command(cluster, "serve start", lines.append)
    assert lines == ["serve error: already running (serve stop first)"]
    lines.clear()
    handle_command(cluster, "serve stat", lines.append)
    assert any(ln.startswith("serve_queue_depth ") for ln in lines)
    assert any(ln.startswith("serve_tier ") for ln in lines)
    lines.clear()
    handle_command(cluster, "serve start queue=x", lines.append)
    assert lines == ["serve error: already running (serve stop first)"]
    lines.clear()
    handle_command(cluster, "serve stop", lines.append)
    assert lines[0].startswith("serve: stopped — admitted=0")
    lines.clear()
    handle_command(cluster, "serve bogus", lines.append)
    assert lines[0].startswith("serve error: usage")
