"""SLO engine tests (ISSUE 17).

Two layers, mirroring tests/test_serve.py's split:

- the jax-free streaming layer: policy grammar + round-trip, the
  promoted ``registry.delta_quantile`` helper, window rings, the
  replica-recommendation ladder, deterministic burn-alert fire/clear on
  a synthetic record stream with an injected clock, and the
  ``python -m ba_tpu.obs.slo`` CLI subprocess pin;
- the engine-backed serving layer: the ATTRIBUTION-SUM invariant
  (``sum(phases) ≈ wall_s`` on every ok record, pinned under a chaos
  retire stall that inflates exactly one phase), per-tenant accounting
  inside ONE coalesced batch, and the no-blocking proof with a live
  installed engine (reports ride the health sampler's host_work slot —
  zero added syncs).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

from ba_tpu.obs import health, slo
from ba_tpu.obs.registry import Histogram, MetricsRegistry, delta_quantile
from ba_tpu.runtime import chaos
from ba_tpu.runtime.serve import (
    COLD_RETRY_AFTER_S,
    AgreementRequest,
    AgreementService,
    Overloaded,
    ServeConfig,
    cohort_key,
    cohort_label,
    shed_tier,
)
from ba_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- jax-free streaming layer -------------------------------------------------


def test_delta_quantile_promoted_and_shared():
    # ISSUE 17 satellite: the windowed-quantile helper is PUBLIC on the
    # registry module (the repo's one implementation) and the health
    # sampler's old private name delegates to it bit-for-bit.
    hist = Histogram(threading.Lock())
    for v in (0.001, 0.002, 0.004, 0.1):
        hist.record(v)
    base = hist.peek()["counts"]
    for v in (0.01, 0.02, 0.03):
        hist.record(v)
    now = hist.peek()["counts"]
    # Windowed: only the 3 post-baseline values count; the p50 upper
    # edge must cover 0.02 but not the baseline's 0.1.
    p50 = delta_quantile(hist, base, now, 0.5)
    assert p50 is not None and 0.02 <= p50 < 0.1
    assert delta_quantile(hist, base, base, 0.5) is None  # empty window
    # Full-history (no baseline) agrees between public and health alias.
    assert health._delta_quantile(hist, None, now, 0.99) == delta_quantile(
        hist, None, now, 0.99
    )
    # Overflow bucket reads as +inf (callers null it for strict JSON).
    hist.record(1e9)
    assert delta_quantile(hist, now, hist.peek()["counts"], 0.99) == float(
        "inf"
    )


def test_policy_validation_and_round_trip():
    with pytest.raises(slo.SLOPolicyError):
        slo.SLOPolicy(objectives=())
    with pytest.raises(slo.SLOPolicyError):  # duplicate names
        slo.SLOPolicy(
            objectives=(
                slo.SLOObjective(name="a", latency_s=0.1),
                slo.SLOObjective(name="a", latency_s=0.2),
            )
        )
    with pytest.raises(slo.SLOPolicyError):  # target must be in (0, 1)
        slo.SLOObjective(name="a", latency_s=0.1, target=1.0)
    with pytest.raises(slo.SLOPolicyError):  # window nesting
        slo.SLOObjective(
            name="a", latency_s=0.1, fast_window_s=60.0, slow_window_s=30.0
        )
    with pytest.raises(slo.SLOPolicyError):
        slo.SLOObjective(name="a", latency_s=0.0)
    # to_doc -> from_doc is a fixed point (the CLI's validate pin).
    pol = slo.default_policy()
    doc = pol.to_doc()
    assert slo.SLOPolicy.from_doc(doc).to_doc() == doc
    assert doc["format"] == slo.POLICY_FORMAT and doc["v"] == 1
    with pytest.raises(slo.SLOPolicyError):  # unknown keys rejected
        slo.SLOPolicy.from_doc({**doc, "surprise": 1})
    bad_obj = {**doc, "objectives": [{**doc["objectives"][0], "oops": 2}]}
    with pytest.raises(slo.SLOPolicyError):
        slo.SLOPolicy.from_doc(bad_obj)
    # The committed example policy loads and round-trips too.
    committed = slo.SLOPolicy.load(
        os.path.join(REPO, "examples", "slo", "serving.json")
    )
    assert slo.SLOPolicy.from_doc(committed.to_doc()) == committed


def test_window_ring_slides_and_resets():
    ring = slo._WindowRing(12.0, n_slots=12)  # 1 s buckets
    ring.add(0.5, good=2)
    ring.add(5.5, bad=3)
    assert ring.totals(5.9) == (2, 3)
    # 12 s later the first bucket's epoch has fallen out of the window.
    assert ring.totals(12.5) == (0, 3)
    assert ring.totals(30.0) == (0, 0)
    # Epoch reuse: a new event in a recycled slot resets it lazily.
    ring.add(24.5, good=1)  # same slot index as t=0.5
    assert ring.totals(24.9) == (1, 0)


def test_recommend_replicas_ladder():
    assert slo.recommend_replicas(0.0, None) == (1, "steady")
    assert slo.recommend_replicas(0.0, 2.0, replicas=2) == (4, "burn_hard")
    assert slo.recommend_replicas(0.9, 0.0, replicas=3) == (6, "queue_hard")
    assert slo.recommend_replicas(0.0, 1.0) == (2, "burn_soft")
    assert slo.recommend_replicas(0.5, 0.0) == (2, "queue_soft")
    assert slo.recommend_replicas(0.0, 0.0, replicas=2) == (1, "decay")
    assert slo.recommend_replicas(0.3, 0.6, replicas=2) == (2, "steady")
    # The cap binds the doubling branch.
    assert slo.recommend_replicas(1.0, 9.0, replicas=6, max_replicas=8) == (
        8,
        "burn_hard",
    )


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, rec):
        self.records.append(rec)


def _req(status="ok", wall=0.01, cohort="plain.r2.c4.xla.m1", tenant="t0"):
    phases = {
        "queue_s": wall * 0.2,
        "coalesce_s": wall * 0.1,
        "compile_s": 0.0,
        "dispatch_s": wall * 0.5,
        "retire_lag_s": wall * 0.2,
    }
    return {
        "event": "request",
        "v": 1,
        "status": status,
        "kind": "run-rounds",
        "cohort": cohort,
        "tenant": tenant,
        "wall_s": wall,
        **phases,
    }


def test_burn_alert_fire_and_clear_deterministic():
    # Synthetic stream + injected clock: the alert must FIRE only once
    # both windows burn past threshold, and CLEAR only once the fast
    # window recovers — exact transition records, no flapping.
    t = [0.0]
    pol = slo.SLOPolicy(
        objectives=(
            slo.SLOObjective(
                name="wall",
                latency_s=0.05,
                target=0.5,  # burn = 2 * bad_frac
                window_s=120.0,
                fast_window_s=10.0,
                slow_window_s=40.0,
                burn_threshold=1.5,
            ),
        ),
        report_every_s=0.001,
    )
    eng = slo.SLOEngine(pol, registry=MetricsRegistry(), clock=lambda: t[0])
    sink = _ListSink()

    def alerts():
        return [r for r in sink.records if r["event"] == "slo_alert"]

    # Healthy traffic: slow window fills with good events.
    for i in range(40):
        t[0] = i * 1.0
        eng.fold(_req(wall=0.01))
    eng.maybe_report(force=True, sink=sink)
    assert alerts() == []
    # Short burst of SLO misses: the fast window saturates immediately
    # but the slow window still remembers the healthy traffic — NO fire
    # yet (fast alone is noise; this is the multi-window point).
    for i in range(10):
        t[0] = 40.0 + i
        eng.fold(_req(wall=0.5))
    eng.maybe_report(force=True, sink=sink)
    assert alerts() == []
    # Sustained burn: the slow window turns over too -> exactly one
    # fire transition.
    for i in range(26):
        t[0] = 50.0 + i
        eng.fold(_req(wall=0.5))
    eng.maybe_report(force=True, sink=sink)
    fired = alerts()
    assert [a["state"] for a in fired] == ["fire"]
    assert fired[0]["objective"] == "wall"
    assert fired[0]["burn_fast"] >= 1.5 and fired[0]["burn_slow"] >= 1.5
    assert slo._burn(0, 10, 0.5) == 2.0  # the arithmetic the gate used
    # Still burning: no duplicate fire records (transitions only).
    t[0] = 76.0
    eng.fold(_req(wall=0.5))
    eng.maybe_report(force=True, sink=sink)
    assert [a["state"] for a in alerts()] == ["fire"]
    # Recovery: good traffic refills the fast window -> clear.
    for i in range(10):
        t[0] = 77.0 + i
        eng.fold(_req(wall=0.01))
    eng.maybe_report(force=True, sink=sink)
    assert [a["state"] for a in alerts()] == ["fire", "clear"]
    # The gate gauge tracked the transitions (worst burn, 0 when the
    # window empties).
    reports = [r for r in sink.records if r["event"] == "slo_report"]
    assert all(slo._flight.valid_run_id(r["run_id"]) for r in reports)
    assert reports[-1]["objectives"][0]["alerting"] is False


def test_engine_folds_rejects_and_autoscale_signal():
    t = [100.0]
    reg = MetricsRegistry()
    eng = slo.SLOEngine(
        slo.SLOPolicy(
            objectives=(
                slo.SLOObjective(
                    name="wall", latency_s=0.05, target=0.5,
                    window_s=120.0, fast_window_s=10.0, slow_window_s=20.0,
                    burn_threshold=1.5,
                ),
            ),
            report_every_s=0.001,
        ),
        registry=reg,
        clock=lambda: t[0],
    )
    eng.fold(
        # Hand-built partial record: fold() only reads the keys the
        # engine groups on, so the full admission schema is not needed.
        {  # ba-lint: disable=BA601
            "event": "admission",
            "v": 1,
            "decision": "reject",
            "reason": "queue_full",
            "kind": "run-rounds",
            "cohort": "plain.r2.c4.xla.m1",
            "tenant": "t9",
        }
    )
    eng.queue_frac = 0.9
    sink = _ListSink()
    eng.maybe_report(force=True, sink=sink)
    (report,) = [r for r in sink.records if r["event"] == "slo_report"]
    (g,) = report["groups"]
    assert g["tenant"] == "t9" and g["counts"]["rejected"] == 1
    assert g["reject_reasons"] == {"queue_full": 1}
    # Rejected work burns budget: one bad event, burn = 2.0.
    assert report["objectives"][0]["burn"] == 2.0
    (sig,) = [r for r in sink.records if r["event"] == "autoscale_signal"]
    assert sig["queue_frac"] == 0.9
    assert sig["recommended"] == 2 and sig["reason"] == "burn_hard"
    assert reg.get("health_slo_burn").value == 2.0


def test_slo_cli_jax_free_subprocess():
    # The BA301 obs-tier contract, proven at runtime: validating the
    # committed policy through the CLI must never import jax (the CI
    # round-trip stage depends on it).
    code = (
        "import sys; from ba_tpu.obs import slo; "
        "rc = slo.main(['validate', 'examples/slo/serving.json']); "
        "assert 'jax' not in sys.modules, 'slo CLI pulled jax'; "
        "sys.exit(rc)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def test_shed_tier_burn_signal():
    cfg = ServeConfig()
    # None (no engine installed / no data) never raises the tier.
    assert shed_tier(0.0, None, None, cfg, burn=None) == 0
    assert shed_tier(0.0, None, None, cfg, burn=cfg.burn_soft - 0.01) == 0
    assert shed_tier(0.0, None, None, cfg, burn=cfg.burn_soft) == 1
    assert shed_tier(0.0, None, None, cfg, burn=cfg.burn_hard) == 2
    # Queue-full still beats everything.
    assert shed_tier(1.0, None, None, cfg, burn=cfg.burn_hard) == 3
    with pytest.raises(ValueError):
        ServeConfig(burn_soft=9.0, burn_hard=1.0)


def test_cold_retry_after_and_cohort_label_and_tenant_validation():
    assert COLD_RETRY_AFTER_S == 0.1  # documented cold-start default
    req = AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=2)
    assert cohort_label(cohort_key(req)) == "plain.r2.c4.xla.m1"
    signed = AgreementRequest(
        kind="run-rounds", n=4, seed=1, rounds=2, signed=True
    )
    assert cohort_label(cohort_key(signed)).endswith(".signed")
    scen = AgreementRequest(kind="scenario", n=4, seed=2, spec=None)
    # tenant is NOT part of the cohort key: accounting, not isolation.
    a = AgreementRequest(kind="run-rounds", n=4, rounds=2, tenant="a")
    b = AgreementRequest(kind="run-rounds", n=4, rounds=2, tenant="b")
    assert cohort_key(a) == cohort_key(b)
    del scen
    from ba_tpu.runtime.serve import validate_request

    with pytest.raises(ValueError):
        validate_request(
            AgreementRequest(kind="run-rounds", rounds=2, tenant="")
        )
    with pytest.raises(ValueError):
        validate_request(
            AgreementRequest(kind="run-rounds", rounds=2, tenant=7)
        )


def test_router_reject_propagates_origin_retry_after():
    # The fleet-router half of the retry-after contract (ISSUE 20
    # satellite, pinned next to the COLD_RETRY_AFTER_S pin above): when
    # EVERY hop sheds, the router re-raises with the ORIGIN replica's
    # retry_after_s — the hash home's queue depth is the real
    # backpressure signal — never a recomputed cold default and never a
    # later hop's smaller hint.
    from ba_tpu.fleet import FleetConfig, FleetRouter, ReplicaManager

    mgr = ReplicaManager(
        FleetConfig(replicas=2),
        serve_config=ServeConfig(max_queue=8, max_batch=2, warm=False),
    )
    for _ in range(2):
        rep = mgr._new_replica()
        rep.service.open()  # admission only: queues fill, nothing runs
        rep.set_state("ready")
    router = FleetRouter(mgr)
    router._sync_ring()
    req = AgreementRequest(kind="run-rounds", n=4, seed=1, rounds=2)
    home = router._ring.prefer(cohort_label(cohort_key(req)))[0]
    other = next(r.name for r in mgr.all() if r.name != home)
    for i in range(8):  # fill the home's queue to the brim
        mgr.get(home).submit(
            AgreementRequest(kind="run-rounds", n=4, seed=i, rounds=2),
            deadline_s=None,
        )
    mgr.get(other).service._tier = 3  # the hop sheds with a COLD hint
    with pytest.raises(Overloaded) as origin_info:
        mgr.get(home).submit(req, deadline_s=None)
    origin = origin_info.value
    # Cold queue-full hint: ceil(8 deep / max_batch 2) cold batches.
    assert origin.retry_after_s == 4 * COLD_RETRY_AFTER_S
    with pytest.raises(Overloaded) as routed_info:
        router.submit(req, deadline_s=None)
    routed = routed_info.value
    assert routed.retry_after_s == origin.retry_after_s
    assert routed.retry_after_s != COLD_RETRY_AFTER_S
    assert (routed.reason, routed.tier) == (origin.reason, origin.tier)


# -- engine-backed serving layer ---------------------------------------------


def _drain_requests(path):
    recs = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("event") == "request":
                recs.append(rec)
    return recs


def test_attribution_sum_under_retire_stall(tmp_path):
    # The acceptance invariant: sum(phases) ≈ wall_s on EVERY ok
    # record — pinned where it is hardest, under a chaos retire stall
    # that inflates the retire-fetch leg by ~0.3 s.  The stall must
    # land in retire_lag_s (the fetch is part of delivered latency),
    # not smear into dispatch_s.
    plan = chaos.from_dict(
        {
            "name": "slow-retire",
            "faults": [
                {
                    "round": 0,
                    "kind": "stall",
                    "phase": "retire",
                    "seconds": 0.3,
                }
            ],
        }
    )
    sink_path = tmp_path / "slo_stall.jsonl"
    metrics.configure(str(sink_path))
    try:
        svc = AgreementService(
            ServeConfig(
                max_batch=2,
                max_queue=8,
                coalesce_window_s=0.001,
                rounds_per_dispatch=2,
                slo=True,
            ),
            fault_plan=plan,
            registry=MetricsRegistry(),
        )
        svc.start()
        out = svc.submit(
            AgreementRequest(
                kind="run-rounds", n=4, seed=90, rounds=2, tenant="stall"
            )
        ).result(timeout=300)
        assert out["counts"]
        stats = svc.stats()
        svc.stop()
    finally:
        metrics.configure(None)
    assert stats["slo"] and stats["slo_reports"] >= 1
    recs = [r for r in _drain_requests(sink_path) if r["status"] == "ok"]
    assert recs, "no ok request records emitted"
    for rec in recs:
        phases = [rec[k] for k in slo.PHASES]
        assert all(isinstance(p, (int, float)) for p in phases)
        assert abs(sum(phases) - rec["wall_s"]) <= slo.ATTRIB_TOL_S
        assert rec["tenant"] == "stall"
    # The 0.3 s stall is attributed to the retire leg.
    assert max(r["retire_lag_s"] for r in recs) >= 0.25


def test_per_tenant_accounting_single_coalesced_batch(tmp_path):
    # Two tenants coalesced into ONE batch (same cohort) must land in
    # two distinct SLO groups with one ok each — per-tenant accounting
    # is row-level, not batch-level.
    sink_path = tmp_path / "slo_tenants.jsonl"
    metrics.configure(str(sink_path))
    try:
        svc = AgreementService(
            ServeConfig(
                max_batch=2,
                max_queue=8,
                coalesce_window_s=0.2,
                rounds_per_dispatch=2,
                slo=slo.SLOPolicy(
                    objectives=(
                        slo.SLOObjective(name="wall", latency_s=30.0),
                    ),
                    report_every_s=0.001,
                ),
            ),
            registry=MetricsRegistry(),
        )
        svc.open()
        ta = svc.submit(
            AgreementRequest(
                kind="run-rounds", n=4, seed=91, rounds=2, tenant="alpha"
            )
        )
        tb = svc.submit(
            AgreementRequest(
                kind="run-rounds", n=4, seed=92, rounds=2, tenant="beta"
            )
        )
        svc.start()
        ta.result(timeout=300)
        tb.result(timeout=300)
        svc.stop()
    finally:
        metrics.configure(None)
    recs = _drain_requests(sink_path)
    ok = [r for r in recs if r["status"] == "ok"]
    assert len(ok) == 2
    # ONE coalesced batch: same cohort run_id and batch counter,
    # different slots, different tenants.
    assert ok[0]["run_id"] == ok[1]["run_id"]
    assert ok[0]["batch"] == ok[1]["batch"]
    assert {r["slot"] for r in ok} == {0, 1}
    assert {r["tenant"] for r in ok} == {"alpha", "beta"}
    # And the engine's final forced report (stop()) split the groups.
    reports = []
    with open(sink_path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("event") == "slo_report":
                reports.append(rec)
    assert reports
    tallies = {}
    for rep in reports:
        for g in rep["groups"]:
            tallies[g["tenant"]] = (
                tallies.get(g["tenant"], 0) + g["counts"]["ok"]
            )
    # counts are cumulative per group; the LAST report has the totals.
    last = {g["tenant"]: g["counts"]["ok"] for g in reports[-1]["groups"]}
    assert last == {"alpha": 1, "beta": 1}
    for g in reports[-1]["groups"]:
        assert g["attribution_checked"] == 1 and g["attribution_bad"] == 0
        assert g["cohort"] == "plain.r2.c4.xla.m1"


def test_no_blocking_with_slo_engine_installed(monkeypatch):
    # Zero added syncs: with a live installed SLO engine riding the
    # health sampler's cadence (health_every=1 — every window), the
    # engine still never calls block_until_ready and the depth-k
    # dispatch/retire schedule is unchanged.
    import jax
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state, pipeline_sweep

    eng = slo.SLOEngine(
        slo.default_policy(), registry=MetricsRegistry()
    )
    slo.install(eng)
    try:

        def _forbidden(*a, **k):
            raise AssertionError(
                "block_until_ready called with SLO engine installed"
            )

        monkeypatch.setattr(jax, "block_until_ready", _forbidden)
        B, cap, R, depth = 8, 8, 7, 3
        state = make_sweep_state(jr.key(5), B, cap)
        events = []
        out = pipeline_sweep(
            jr.key(23),
            state,
            R,
            depth=depth,
            rounds_per_dispatch=1,
            health_every=1,
            on_event=lambda kind, i: events.append((kind, i)),
        )
        dispatches = [i for kind, i in events if kind == "dispatch"]
        retires = [i for kind, i in events if kind == "retire"]
        assert dispatches == list(range(R))
        assert retires == list(range(R))
        first_retire = events.index(("retire", 0))
        assert events[:first_retire] == [
            ("dispatch", i) for i in range(depth + 1)
        ]
        assert out["stats"]["max_in_flight"] == depth + 1
    finally:
        slo.install(None)
    assert slo.installed() is None


def test_repl_stats_live_slo_line():
    # REPL satellite: one lock-free SLO line when an engine with a
    # report exists; nothing (and no error) when none is installed.
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, PyBackend(), seed=0)
    lines = []
    handle_command(cluster, "stats --live", lines.append)
    assert not any("slo_worst" in ln for ln in lines)
    # A real fold -> the sampler's own maybe_report (stats --live
    # samples, which invokes the installed engine) computes last_worst.
    eng = slo.SLOEngine(slo.default_policy(), registry=MetricsRegistry())
    rec = _req(wall=2.0, tenant="alpha")  # misses the 0.5 s objective
    rec.update(
        queue_s=1.9, coalesce_s=0.025, compile_s=0.0,
        dispatch_s=0.05, retire_lag_s=0.025,
    )
    eng.fold(rec)
    slo.install(eng)
    try:
        lines.clear()
        handle_command(cluster, "stats --live", lines.append)
        (slo_line,) = [ln for ln in lines if ln.startswith("slo_worst")]
        assert "tenant=alpha" in slo_line and "phase=queue_s" in slo_line
        # One all-bad event against target 0.99: burn = 1/0.01 = 100.
        assert "burn=100.0" in slo_line
    finally:
        slo.install(None)
