"""Quorum math vs. the reference's exact rule (ba.py:225-255)."""

import jax.numpy as jnp
import pytest

from ba_tpu.core import (
    ATTACK,
    RETREAT,
    UNDEFINED,
    majority_counts,
    quorum_decision,
    quorum_threshold,
    quorum_threshold_py,
)


def ref_threshold(total: int) -> int:
    # Transcription of ba.py:228-235 for cross-checking.
    k = (total - 1) // 3
    needed = 2 * k + 1
    if total <= 3:
        needed = total - 1
    if total == 1:
        needed = 1
    return needed


@pytest.mark.parametrize("total", range(1, 50))
def test_threshold_matches_reference(total):
    assert quorum_threshold_py(total) == ref_threshold(total)
    assert int(quorum_threshold(jnp.asarray(total))) == ref_threshold(total)


def test_threshold_examples():
    # 3k+1 nodes tolerate k traitors with needed = 2k+1 (ba.py:229).
    assert quorum_threshold_py(4) == 3
    assert quorum_threshold_py(7) == 5
    assert quorum_threshold_py(10) == 7
    # Overrides (ba.py:231-235, SURVEY.md Q7).
    assert quorum_threshold_py(1) == 1
    assert quorum_threshold_py(2) == 1
    assert quorum_threshold_py(3) == 2


def test_retreat_checked_first():
    # With needed <= both counts, retreat wins (ba.py:246-250, Q7).
    d, needed, total = quorum_decision(
        jnp.asarray([2]), jnp.asarray([2]), jnp.asarray([0])
    )
    assert int(total[0]) == 4 and int(needed[0]) == 3
    # needed=3 > both -> undefined here; build a real tie at total=2:
    d2, n2, t2 = quorum_decision(jnp.asarray([1]), jnp.asarray([1]), jnp.asarray([0]))
    assert int(n2[0]) == 1
    assert int(d2[0]) == RETREAT


def test_decision_attack():
    d, needed, total = quorum_decision(
        jnp.asarray([3]), jnp.asarray([0]), jnp.asarray([1])
    )
    assert int(total[0]) == 4 and int(needed[0]) == 3
    assert int(d[0]) == ATTACK


def test_decision_undefined():
    d, needed, total = quorum_decision(
        jnp.asarray([2]), jnp.asarray([2]), jnp.asarray([3])
    )
    # total=7, needed=5, neither side reaches it.
    assert int(d[0]) == UNDEFINED


def test_majority_counts_masks_dead():
    majorities = jnp.asarray([[ATTACK, RETREAT, UNDEFINED, ATTACK]], jnp.int8)
    alive = jnp.asarray([[True, True, True, False]])
    a, r, u = majority_counts(majorities, alive)
    assert (int(a[0]), int(r[0]), int(u[0])) == (1, 1, 1)
