"""Crypto subsystem tests: oracle vs RFC 8032, field vs bigints, batched
SHA-512 vs hashlib, batched Ed25519 verify vs the oracle.

The reference has no crypto (SURVEY.md section 2: ba.py is unsigned oral
messages only); these tests cover the BASELINE.json north-star addition.
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ba_tpu.crypto import ed25519, field as F, oracle
from ba_tpu.crypto.scalar import reduce_mod_l
from ba_tpu.crypto.sha512 import sha512

P = F.P_INT


# -- oracle vs RFC 8032 -------------------------------------------------------

RFC8032_VECTORS = [
    # (secret key, public key, message, signature) — RFC 8032 section 7.1
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
]


@pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
def test_oracle_rfc8032(sk, pk, msg, sig):
    sk_b, msg_b = bytes.fromhex(sk), bytes.fromhex(msg)
    assert oracle.publickey(sk_b).hex() == pk
    assert oracle.sign(sk_b, bytes.fromhex(pk), msg_b).hex() == sig
    assert oracle.verify(bytes.fromhex(pk), msg_b, bytes.fromhex(sig))
    assert not oracle.verify(bytes.fromhex(pk), msg_b + b"x", bytes.fromhex(sig))


# -- field arithmetic vs Python bigints --------------------------------------


def _to_limbs(vals):
    out = np.zeros((len(vals), F.LIMBS), np.int32)
    for b, v in enumerate(vals):
        for i in range(F.LIMBS):
            out[b, i] = v & F.MASK
            v >>= F.BITS
    return jnp.asarray(out)


def _from_canon(x):
    x = np.asarray(F.canonical(x))
    assert x.min() >= 0 and x.max() <= F.MASK, "canonical limbs out of range"
    vals = []
    for row in x:
        v = 0
        for i in reversed(range(F.LIMBS)):
            v = (v << F.BITS) | int(row[i])
        vals.append(v)
    return vals


@pytest.fixture(scope="module")
def field_values():
    rng = np.random.default_rng(7)
    vals = [int.from_bytes(rng.bytes(32), "little") % P for _ in range(24)]
    vals[:4] = [0, 1, P - 1, (P - 1) // 2]
    return vals


def test_field_ops(field_values):
    a_i = field_values
    b_i = list(reversed(field_values))
    a, b = _to_limbs(a_i), _to_limbs(b_i)
    assert _from_canon(F.mul(a, b)) == [x * y % P for x, y in zip(a_i, b_i)]
    assert _from_canon(F.add(a, b)) == [(x + y) % P for x, y in zip(a_i, b_i)]
    assert _from_canon(F.sub(a, b)) == [(x - y) % P for x, y in zip(a_i, b_i)]
    # Negative-valued lazy operands through a multiply.
    assert _from_canon(F.mul(F.sub(a, b), F.sub(b, a))) == [
        (x - y) * (y - x) % P for x, y in zip(a_i, b_i)
    ]


def test_field_deep_chain_stays_in_bounds(field_values):
    """Stress the carried-limb contract: long mul/sub/add chains must keep
    every limb inside the int32-safe envelope and the value exact."""
    a_i = field_values
    b_i = list(reversed(field_values))
    a, b = _to_limbs(a_i), _to_limbs(b_i)
    x = F.mul(F.sub(a, b), F.sub(b, a))
    exp = [(p - q) * (q - p) % P for p, q in zip(a_i, b_i)]
    for _ in range(20):
        x = F.mul(F.sub(x, a), F.add(x, b))
        exp = [(e - p) * (e + q) % P for e, p, q in zip(exp, a_i, b_i)]
        arr = np.asarray(x)
        assert abs(arr[..., 0]).max() < 13824
        assert arr[..., 1:].min() > -16 and arr[..., 1:].max() <= 4096
    assert _from_canon(x) == exp


def test_field_inv_pow_bytes(field_values):
    a_i = field_values
    a = _to_limbs(a_i)
    assert _from_canon(F.inv(a)) == [pow(v, P - 2, P) if v else 0 for v in a_i]
    e = (P + 3) // 8
    assert _from_canon(F.pow_const(a, e)) == [pow(v, e, P) for v in a_i]
    by = jnp.asarray(
        np.stack([np.frombuffer(v.to_bytes(32, "little"), np.uint8) for v in a_i])
    )
    assert _from_canon(F.from_bytes(by)) == a_i
    assert (np.asarray(F.to_bytes(F.from_bytes(by))) == np.asarray(by)).all()
    assert np.asarray(F.eq(a, a)).all()
    assert np.asarray(F.is_zero(F.sub(a, a))).all()


# -- batched SHA-512 vs hashlib ----------------------------------------------


@pytest.mark.parametrize("length", [0, 32, 96, 111, 112, 127, 128, 200])
def test_sha512_matches_hashlib(length):
    rng = np.random.default_rng(length)
    msgs = rng.integers(0, 256, size=(4, length), dtype=np.uint8)
    got = np.asarray(sha512(jnp.asarray(msgs)))
    for b in range(4):
        exp = np.frombuffer(hashlib.sha512(msgs[b].tobytes()).digest(), np.uint8)
        assert (got[b] == exp).all()


# -- mod-L scalar reduction vs Python bigints ---------------------------------


def test_reduce_mod_l_matches_bigints():
    L = oracle.L
    q = 2**512 // L
    rng = np.random.default_rng(11)
    vals = [0, 1, L - 1, L, L + 1, 2**252, 2**256, q * L - 1, q * L, 2**512 - 1]
    vals += [int.from_bytes(rng.bytes(64), "little") for _ in range(64)]
    by = jnp.asarray(
        np.stack([np.frombuffer(v.to_bytes(64, "little"), np.uint8) for v in vals])
    )
    out = np.asarray(jax.jit(reduce_mod_l)(by))
    got = [int.from_bytes(out[i].tobytes(), "little") for i in range(len(vals))]
    assert got == [v % L for v in vals]


# -- fixed-base window table vs ladder and oracle -----------------------------


def test_fixed_base_matches_ladder_and_oracle():
    L = oracle.L
    rng = np.random.default_rng(12)
    ss = [0, 1, 2, 15, 16, L - 1]
    ss += [int.from_bytes(rng.bytes(32), "little") % L for _ in range(6)]
    enc = jnp.asarray(
        np.stack([np.frombuffer(s.to_bytes(32, "little"), np.uint8) for s in ss])
    )
    got = ed25519.fixed_base_mult(enc)
    exp = ed25519.scalar_mult_base(F.bytes_to_bits(enc))
    assert np.asarray(ed25519.point_eq(got, exp)).all()
    comp = np.asarray(ed25519.compress(got))
    for i, s in enumerate(ss):
        assert comp[i].tobytes() == oracle.encode_point(
            oracle.scalarmult(oracle.BASE, s)
        )


# -- batched Ed25519 verify vs oracle ----------------------------------------


@pytest.fixture(scope="module")
def sig_batch():
    """8 lanes: 4 valid, then corrupted sig / corrupted msg / wrong key /
    valid — exercising every rejection path next to accept paths."""
    msgs, pks, sigs, expect = [], [], [], []
    for i in range(4):
        sk, pk = oracle.keypair(bytes([i]))
        m = bytes([i]) * 32
        sig = oracle.sign(sk, pk, m)
        msgs.append(m)
        pks.append(pk)
        sigs.append(sig)
        expect.append(True)
    m = bytes(32)
    sk, pk = oracle.keypair(b"x")
    sig = oracle.sign(sk, pk, m)
    bad_sig = bytearray(sig)
    bad_sig[0] ^= 1
    msgs.append(m), pks.append(pk), sigs.append(bytes(bad_sig)), expect.append(False)
    bad_msg = bytearray(m)
    bad_msg[5] ^= 0xFF
    msgs.append(bytes(bad_msg)), pks.append(pk), sigs.append(sig), expect.append(False)
    _, pk2 = oracle.keypair(b"y")
    msgs.append(m), pks.append(pk2), sigs.append(sig), expect.append(False)
    msgs.append(m), pks.append(pk), sigs.append(sig), expect.append(True)
    to_arr = lambda rows: jnp.asarray(np.stack([np.frombuffer(r, np.uint8) for r in rows]))
    return to_arr(pks), to_arr(msgs), to_arr(sigs), expect


def test_verify_matches_oracle(sig_batch):
    pk, msg, sig, expect = sig_batch
    got = np.asarray(jax.jit(ed25519.verify)(pk, msg, sig))
    assert got.tolist() == expect
    # Cross-check every lane against the oracle too.
    for b in range(pk.shape[0]):
        assert expect[b] == oracle.verify(
            bytes(np.asarray(pk[b])), bytes(np.asarray(msg[b])), bytes(np.asarray(sig[b]))
        )


def test_compress_decompress_roundtrip():
    enc = []
    for i in range(4):
        _, pk = oracle.keypair(bytes([40 + i]))
        enc.append(np.frombuffer(pk, np.uint8))
    by = jnp.asarray(np.stack(enc))
    pts, ok = ed25519.decompress(by)
    assert np.asarray(ok).all()
    back = np.asarray(ed25519.compress(pts))
    assert (back == np.asarray(by)).all()


def test_oracle_rejects_noncanonical_x_zero():
    """RFC 8032 5.1.3 step 4: y=1 with sign bit 1 encodes x=0 non-canonically;
    accepting it lets [h]A collapse to the identity — a forgery vector.  The
    oracle and the device kernel must both reject it."""
    bad_pk = bytes([1] + [0] * 30 + [0x80])
    sk, pk = oracle.keypair(b"canon")
    s = 5
    r_enc = oracle.encode_point(oracle.scalarmult(oracle.BASE, s))
    forged = r_enc + s.to_bytes(32, "little")
    assert not oracle.verify(bad_pk, b"m" * 32, forged)
    got = np.asarray(
        ed25519.verify(
            jnp.asarray(np.frombuffer(bad_pk, np.uint8)[None]),
            jnp.asarray(np.frombuffer(b"m" * 32, np.uint8)[None]),
            jnp.asarray(np.frombuffer(forged, np.uint8)[None]),
        )
    )
    assert not got[0]


def test_decompress_rejects_junk():
    # y >= p is an invalid encoding (RFC 8032 5.1.3 step 1).
    bad = np.zeros((2, 32), np.uint8)
    bad[0] = 0xFF  # y = 2^255-1 with sign bit -> y >= p after masking
    bad[0, 31] = 0x7F
    # A y whose x^2 has no square root: y=2 works for ed25519.
    bad[1, 0] = 2
    _, ok = ed25519.decompress(jnp.asarray(bad))
    assert not np.asarray(ok)[0]
    assert not np.asarray(ok)[1]


# -- mod-L products / sums + RLC batch verification ---------------------------


def test_mul_mod_l_matches_bigints():
    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.scalar import mul_mod_l

    rng = np.random.default_rng(21)
    a = rng.integers(0, 256, (64, 32)).astype(np.uint8)
    z = rng.integers(0, 256, (64, 16)).astype(np.uint8)
    # Edge rows: zero, max, L-1 * max.
    a[0] = 0
    a[1] = 255
    a[2] = np.frombuffer(int(L - 1).to_bytes(32, "little"), np.uint8)
    z[1] = 255
    z[2] = 255
    got = np.asarray(jax.jit(mul_mod_l)(jnp.asarray(a), jnp.asarray(z)))
    for i in range(64):
        want = (
            int.from_bytes(a[i].tobytes(), "little")
            * int.from_bytes(z[i].tobytes(), "little")
        ) % L
        assert int.from_bytes(got[i].tobytes(), "little") == want, i


def test_sum_mod_l_matches_bigints():
    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.scalar import sum_mod_l

    rng = np.random.default_rng(22)
    v = rng.integers(0, 256, (3, 4097, 32)).astype(np.uint8)  # odd G
    got = np.asarray(jax.jit(sum_mod_l)(jnp.asarray(v)))
    for i in range(3):
        want = sum(
            int.from_bytes(v[i, g].tobytes(), "little") for g in range(4097)
        ) % L
        assert int.from_bytes(got[i].tobytes(), "little") == want, i


def test_muladd_bytes_matches_bigints():
    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.scalar import muladd_bytes

    rng = np.random.default_rng(23)
    k = rng.integers(0, 256, (16, 32)).astype(np.uint8)
    a = rng.integers(0, 256, (16, 32)).astype(np.uint8)
    r = rng.integers(0, 256, (16, 32)).astype(np.uint8)
    # Edge rows: zeros, all-0xFF (the 2^508-scale worst case), L-1 pairs.
    k[0] = a[0] = r[0] = 0
    k[1] = a[1] = r[1] = 255
    k[2] = a[2] = np.frombuffer(int(L - 1).to_bytes(32, "little"), np.uint8)
    got = np.asarray(
        jax.jit(muladd_bytes)(jnp.asarray(k), jnp.asarray(a), jnp.asarray(r))
    )
    for i in range(16):
        want = int.from_bytes(k[i].tobytes(), "little") * int.from_bytes(
            a[i].tobytes(), "little"
        ) + int.from_bytes(r[i].tobytes(), "little")
        assert int.from_bytes(got[i].tobytes(), "little") == want, i


def test_sign_device_matches_oracle():
    """The device signer's differential contract: byte-identical to
    oracle.sign (RFC 8032 determinism) for every lane, including the
    degenerate all-zero seed.  Runs the jnp path on CPU; the same test
    under BA_TPU_TESTS_ON_TPU=1 pins the full Pallas pipeline (sha512 +
    mod-L + fixed-base + inv-chain compress kernels)."""
    from ba_tpu.crypto import ed25519
    from ba_tpu.crypto import oracle
    from ba_tpu.crypto.signed import MSG_LEN, order_message

    B = 8
    sks = [oracle.secret_from_seed(f"signdev:{i}".encode()) for i in range(B)]
    sks[0] = b"\0" * 32
    pks = [oracle.publickey(sk) for sk in sks]
    msgs = [order_message(i, i & 1) for i in range(B)]
    want = np.stack(
        [
            np.frombuffer(oracle.sign(sk, pk, m), np.uint8)
            for sk, pk, m in zip(sks, pks, msgs)
        ]
    )
    sk_arr = jnp.asarray(np.stack([np.frombuffer(s, np.uint8) for s in sks]))
    pk_arr = jnp.asarray(np.stack([np.frombuffer(p, np.uint8) for p in pks]))
    msg_arr = jnp.asarray(
        np.stack([np.frombuffer(m, np.uint8) for m in msgs])
    )
    assert msg_arr.shape == (B, MSG_LEN)
    got = np.asarray(jax.jit(ed25519.sign)(sk_arr, pk_arr, msg_arr))
    np.testing.assert_array_equal(got, want)
    # And the signatures verify on the device verifier.
    ok = np.asarray(jax.jit(ed25519.verify)(pk_arr, msg_arr, jnp.asarray(got)))
    assert ok.all()


def test_sum_mod_l_above_default_headroom():
    """G above ~1.05M: the sum exceeds the 34-byte capacity that a fixed
    2-extra-limb settle gives, so this pins the static extra sizing
    (ADVICE r4 medium — a dropped top carry would be silently wrong)."""
    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.scalar import sum_mod_l

    G = 1_200_000
    lm1 = np.frombuffer(int(L - 1).to_bytes(32, "little"), np.uint8)
    v = np.broadcast_to(lm1, (G, 32))
    got = np.asarray(jax.jit(sum_mod_l)(jnp.asarray(v)))
    want = (G * (L - 1)) % L
    assert int.from_bytes(got.tobytes(), "little") == want


def test_batch_point_sum_matches_sequential():
    rng = np.random.default_rng(23)
    for B in (1, 2, 5, 8):  # covers pad and no-pad tree shapes
        bits = jnp.asarray(rng.integers(0, 2, (B, 16)), jnp.int32)
        pts = ed25519.scalar_mult(ed25519.base_point((B,)), bits)
        acc = ed25519.identity((1,))
        for i in range(B):
            acc = ed25519.point_add(acc, tuple(c[i : i + 1] for c in pts))
        got = ed25519.batch_point_sum(pts)
        assert bool(ed25519.point_eq(got, acc)[0]), B


def _rlc_fixture(rng, B=4, n=4):
    from ba_tpu.crypto.signed import commander_keys, sign_received

    sks, pks = commander_keys(B)
    received = rng.integers(0, 2, (B, n))
    msgs, sigs = sign_received(sks, pks, received)
    pk_l = jnp.asarray(np.repeat(pks, n, axis=0))
    return (
        pks, msgs, sigs, pk_l,
        jnp.asarray(msgs.reshape(B * n, -1)),
        jnp.asarray(sigs.reshape(B * n, 64)),
    )


def test_verify_rlc_accepts_valid_batch_and_rejects_corrupt():
    rng = np.random.default_rng(24)
    B, n = 4, 4
    pks, msgs, sigs, pk_l, msg_l, sig_l = _rlc_fixture(rng, B, n)
    z = jnp.asarray(rng.integers(0, 256, (B * n, 16)), jnp.uint8)
    ok, enc = ed25519.verify_rlc(pk_l, msg_l, sig_l, z, pk_group=n)
    assert bool(ok) and bool(jnp.all(enc))
    # grouped and ungrouped paths agree
    ok_u, _ = ed25519.verify_rlc(pk_l, msg_l, sig_l, z, pk_group=1)
    assert bool(ok_u)
    # a single flipped signature byte (valid encodings) must reject
    s2 = np.array(sigs)
    s2[1, 2, 40] ^= 0x01
    ok2, enc2 = ed25519.verify_rlc(
        pk_l, msg_l, jnp.asarray(s2.reshape(B * n, 64)), z, pk_group=n
    )
    assert not bool(ok2) and bool(jnp.all(enc2))
    # an out-of-range S is flagged per-lane (exact check) and rejects
    s3 = np.array(sigs)
    s3[2, 1, 32:] = 0xFF
    ok3, enc3 = ed25519.verify_rlc(
        pk_l, msg_l, jnp.asarray(s3.reshape(B * n, 64)), z, pk_group=n
    )
    enc3 = np.asarray(enc3)
    assert not bool(ok3) and not enc3[2 * n + 1] and enc3.sum() == B * n - 1


def test_verify_received_rlc_matches_exact_mask():
    from ba_tpu.crypto.signed import verify_received, verify_received_rlc

    rng = np.random.default_rng(25)
    B, n = 4, 4
    pks, msgs, sigs, *_ = _rlc_fixture(rng, B, n)
    # all-valid: the RLC fast path must return the all-true mask
    got = np.asarray(verify_received_rlc(pks, msgs, sigs))
    assert got.all() and got.shape == (B, n)
    # corrupt one copy: the fallback must reproduce the exact mask
    s2 = np.array(sigs)
    s2[3, 0, 0] ^= 0xFF
    want = np.asarray(verify_received(pks, msgs, s2))
    got2 = np.asarray(verify_received_rlc(pks, msgs, s2))
    np.testing.assert_array_equal(got2, want)
    assert not got2[3, 0] and got2.sum() == B * n - 1


def test_rlc_batch_ok_chunked_padding(monkeypatch):
    # The chunked RLC dispatch (ADVICE r4: fixed compiled shapes instead
    # of one monolithic program per (B, n)): force a tiny chunk so the
    # pad-by-whole-pk-groups path executes, and pin both verdicts.
    from ba_tpu.crypto.signed import rlc_batch_ok

    rng = np.random.default_rng(26)
    B, n = 5, 4  # total 20, chunk 8 -> pad 4 (one replicated group)
    pks, msgs, sigs, *_ = _rlc_fixture(rng, B, n)
    monkeypatch.setenv("BA_TPU_VERIFY_CHUNK", "8")
    assert bool(rlc_batch_ok(pks, msgs, sigs))
    s2 = np.array(sigs)
    s2[4, 3, 40] ^= 0x01  # corrupt a lane in the padded tail chunk
    assert not bool(rlc_batch_ok(pks, msgs, s2))


def test_setup_rlc_deferred_fetch_matches_exact(monkeypatch):
    # BA_TPU_VERIFY_RLC=1 in the overlapped setup: table verify becomes
    # per-chunk deferred-fetch RLC dispatches drained in one fetch
    # (VERDICT r4 item 3a).  Self-signed tables always accept, so the ok
    # mask must be all-true with the same tables as the exact path.
    from ba_tpu.crypto.signed import (
        setup_signed_tables_overlapped,
        sign_value_tables,
        commander_keys,
    )

    B = 13  # uneven: padded tail chunk through the RLC route
    sks, pks = commander_keys(B)
    want_msgs, want_sigs = sign_value_tables(sks, pks)
    monkeypatch.setenv("BA_TPU_VERIFY_RLC", "1")
    _, _, got_msgs, got_sigs, ok, _ = setup_signed_tables_overlapped(
        B, chunks=3
    )
    np.testing.assert_array_equal(got_msgs, want_msgs)
    np.testing.assert_array_equal(got_sigs, want_sigs)
    ok = np.asarray(ok)
    assert ok.shape == (B, 2) and ok.all()


def test_verify_rlc_cofactored_accepts_torsion_malleated_sig():
    # The documented one-sided divergence between the RLC batch check and
    # the cofactorless per-signature path: a signer offsets its own R by a
    # small-order point T (R' = rB + T) and recomputes S for the new hash.
    # Per-signature verify (oracle, jnp) must REJECT — the defect -T is a
    # torsion component.  verify_rlc with z = 8u (fresh_rlc_coeffs's
    # contract) runs the standard COFACTORED batch equation, which
    # annihilates T and must ACCEPT, deterministically.  If this test
    # ever starts failing on the accept side, the cofactored contract in
    # verify_rlc's docstring is stale.
    import hashlib

    from ba_tpu.crypto import oracle
    from ba_tpu.crypto.signed import (
        commander_keys,
        fresh_rlc_coeffs,
        order_message,
    )

    # A small-order point: scan y, keep curve-valid points whose [L]Q is
    # not the identity.
    T = None
    for y in range(2, 200):
        try:
            q = oracle.decode_point(int(y).to_bytes(32, "little"))
        except ValueError:
            continue
        x, yy = q
        if (-x * x + yy * yy - 1 - oracle.D * x * x * yy * yy) % oracle.P:
            continue  # not on the curve
        cand = oracle.scalarmult(q, oracle.L)
        if cand != (0, 1):
            T = cand
            break
    assert T is not None, "no small-order point found in scan range"

    sks, pks = commander_keys(2, seed=7)
    msg0 = order_message(0, 1)
    sig0 = np.frombuffer(
        oracle.sign(sks[0], pks[0].tobytes(), msg0), np.uint8
    )
    # Malleate lane 1's signature: same RFC nonce r, R' = rB + T.
    msg1 = order_message(1, 0)
    h = hashlib.sha512(sks[1]).digest()
    a = oracle._clamp(h[:32])
    r = oracle._hint(h[32:] + msg1) % oracle.L
    r_pt = oracle.edwards_add(oracle.scalarmult(oracle.BASE, r), T)
    r_enc = oracle.encode_point(r_pt)
    pk1 = pks[1].tobytes()
    hp = oracle._hint(r_enc + pk1 + msg1) % oracle.L
    s = (r + hp * a) % oracle.L
    sig1 = np.frombuffer(r_enc + s.to_bytes(32, "little"), np.uint8)

    assert not oracle.verify(pk1, msg1, bytes(sig1))  # cofactorless: reject
    pk_l = jnp.asarray(pks)
    msg_l = jnp.asarray(
        np.stack([np.frombuffer(msg0, np.uint8),
                  np.frombuffer(msg1, np.uint8)])
    )
    sig_l = jnp.asarray(np.stack([sig0, sig1]))
    per_sig = np.asarray(ed25519.verify(pk_l, msg_l, sig_l))
    np.testing.assert_array_equal(per_sig, [True, False])

    z = jnp.asarray(fresh_rlc_coeffs(2))
    ok, enc = ed25519.verify_rlc(pk_l, msg_l, sig_l, z, pk_group=1)
    assert bool(jnp.all(enc))  # encodings are valid either way
    assert bool(ok)  # cofactored comparison: the torsion defect annihilates

    # The clearing happens at the COMPARISON (both sides x8), so it is
    # z-independent: odd coefficients accept identically...
    z_odd = np.asarray(z).copy()
    z_odd[:, 0] |= 1
    ok_odd, _ = ed25519.verify_rlc(
        pk_l, msg_l, sig_l, jnp.asarray(z_odd), pk_group=1
    )
    assert bool(ok_odd)
    # ...while a PRIME-ORDER defect on the same malleated lane (S bumped
    # by 1) must still reject — cofactoring hides torsion only.
    s_bad = (s + 1) % oracle.L
    sig_bad = np.frombuffer(r_enc + s_bad.to_bytes(32, "little"), np.uint8)
    ok_bad, _ = ed25519.verify_rlc(
        pk_l, msg_l, jnp.asarray(np.stack([sig0, sig_bad])), z, pk_group=1
    )
    assert not bool(ok_bad)


def test_verify_received_rlc_env_knob(monkeypatch):
    # BA_TPU_VERIFY_RLC=1 must be observably identical to the exact path
    # on both all-valid and mixed batches (reject -> exact fallback).
    from ba_tpu.crypto.signed import verify_received

    rng = np.random.default_rng(26)
    B, n = 4, 4
    pks, msgs, sigs, *_ = _rlc_fixture(rng, B, n)
    monkeypatch.setenv("BA_TPU_VERIFY_RLC", "1")
    got = np.asarray(verify_received(pks, msgs, sigs))
    assert got.all() and got.shape == (B, n)
    s2 = np.array(sigs)
    s2[0, 3, 10] ^= 0x04
    got2 = np.asarray(verify_received(pks, msgs, s2))
    monkeypatch.setenv("BA_TPU_VERIFY_RLC", "0")
    want2 = np.asarray(verify_received(pks, msgs, s2))
    np.testing.assert_array_equal(got2, want2)
    assert not got2[0, 3] and got2.sum() == B * n - 1


def test_sign_on_device_auto_gates_on_real_tpu(monkeypatch):
    # ADVICE r5 (signed.py:465): auto mode must NOT flip the signing
    # default to the emulated device path just because BA_TPU_PALLAS=1 is
    # forced on a CPU backend — the platform itself has to be TPU.  The
    # explicit knob still overrides in both directions.
    from ba_tpu.crypto.signed import sign_on_device

    if jax.devices()[0].platform == "tpu":
        pytest.skip("CPU-platform gating test")
    monkeypatch.delenv("BA_TPU_SIGN_DEVICE", raising=False)
    monkeypatch.setenv("BA_TPU_PALLAS", "1")  # the silent-flip case
    assert sign_on_device() is False
    monkeypatch.setenv("BA_TPU_PALLAS", "0")
    assert sign_on_device() is False
    monkeypatch.setenv("BA_TPU_SIGN_DEVICE", "1")  # deliberate override
    assert sign_on_device() is True
    monkeypatch.setenv("BA_TPU_SIGN_DEVICE", "0")
    monkeypatch.setenv("BA_TPU_PALLAS", "1")
    assert sign_on_device() is False
