"""Differential tests: JAX tensor core vs the sequential Python oracle.

The PyBackend is a loop-for-loop transcription of the reference's semantics
(SURVEY.md section 3.2); agreement between the two engines on every
deterministic case is the parity argument for the tensorised core.
"""

import pytest

from ba_tpu.core.types import ATTACK
from ba_tpu.runtime.backends import JaxBackend, PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.runtime.repl import handle_command


def drive(cluster, lines):
    out = []
    for line in lines:
        if not handle_command(cluster, line, out.append):
            break
    return out


SCRIPTS = [
    ["actual-order attack"],
    ["actual-order retreat"],
    ["g-state 3 faulty", "actual-order attack"],
    ["g-kill 2", "actual-order retreat"],
    ["g-kill 1", "g-add 1", "actual-order attack", "List"],
    # Two traitors need n=7 to be outcome-deterministic: each honest
    # lieutenant then tallies 4 fixed votes vs 2 coins.  (At n=5 the 2-2
    # tie is reachable, so 5-general 2-traitor scripts are coin-sensitive
    # — they only ever passed by RNG-stream luck.)
    ["g-add 2", "g-state 2 faulty", "g-state 4 faulty", "actual-order retreat"],
    ["actual-order charge"],
]


@pytest.mark.parametrize("script", SCRIPTS, ids=[" ".join(s)[:40] for s in SCRIPTS])
def test_backends_agree_deterministic(script):
    # Every script here has deterministic output (enough honest generals
    # that traitor coins cannot flip any majority).
    out_py = drive(Cluster(5, PyBackend(), seed=7), script)
    out_jax = drive(Cluster(5, JaxBackend(platform="cpu"), seed=7), script)
    assert out_py == out_jax


def test_backends_agree_om3():
    # OM(3) via the EIG tree vs OM(1): identical on fault-free clusters.
    script = ["actual-order attack", "g-kill 3", "actual-order retreat"]
    out_m1 = drive(Cluster(6, JaxBackend(platform="cpu", m=1), seed=1), script)
    out_m3 = drive(Cluster(6, JaxBackend(platform="cpu", m=3), seed=1), script)
    out_py = drive(Cluster(6, PyBackend(), seed=1), script)
    assert out_m1 == out_m3 == out_py


def test_faulty_leader_agreement_property():
    # With a faulty leader both engines must keep all honest lieutenants in
    # agreement with each other (IC1), though the agreed value is random.
    for seed in range(6):
        for backend in (PyBackend(), JaxBackend(platform="cpu")):
            cluster = Cluster(5, backend, seed=seed)
            drive(cluster, ["g-state 1 faulty"])
            res = cluster.actual_order("attack")
            lieutenant_majorities = {
                maj for (_, is_primary, maj, _) in res.per_general if not is_primary
            }
            assert len(lieutenant_majorities) == 1


def test_jax_backend_capacity_reuse():
    # g-add within the padded capacity must not recompile; crossing a
    # power-of-two boundary compiles exactly one new program.
    # Padding is what prevents recompiles: jax.jit re-traces only on new
    # shapes (its public contract), so equal padded state shapes across
    # g-add within a power-of-two boundary mean one compiled program.
    backend = JaxBackend(platform="cpu")
    cluster = Cluster(3, backend, seed=0)
    drive(cluster, ["actual-order attack"])
    assert backend._capacity(3) == 4
    shape3 = backend._make_state(cluster.generals, 0, ATTACK).faulty.shape
    drive(cluster, ["g-add 1", "actual-order attack"])
    shape4 = backend._make_state(cluster.generals, 0, ATTACK).faulty.shape
    assert shape3 == shape4 == (1, 4)  # same program serves both rosters
    drive(cluster, ["g-add 1", "actual-order attack"])
    shape5 = backend._make_state(cluster.generals, 0, ATTACK).faulty.shape
    assert shape5 == (1, 8)  # crossing the boundary pads to the next pow2


# -- SM / signed protocols through the full REPL shell ------------------------


def test_sm_backend_repl_honest():
    # --protocol sm: honest commander -> signatures make agreement exact,
    # REPL output must match the OM backend on deterministic scripts.
    script = ["actual-order attack", "g-kill 2", "actual-order retreat"]
    out_sm = drive(Cluster(5, JaxBackend(platform="cpu", protocol="sm", m=1), seed=7), script)
    out_om = drive(Cluster(5, JaxBackend(platform="cpu"), seed=7), script)
    assert out_sm == out_om


def test_sm_backend_repl_faulty_commander():
    # Faulty commander with t = m = 1: honest lieutenants agree (IC1), so
    # the quorum line reports a decisive 3-of-4... or undefined if the
    # coalition equivocated; either way all lieutenant rows must agree.
    cluster = Cluster(4, JaxBackend(platform="cpu", protocol="sm", m=1), seed=3)
    out = drive(cluster, ["g-state 1 faulty", "actual-order attack"])
    rows = [l for l in out if l.startswith("G") and "majority" in l]
    lieutenant_maj = {r.split("majority=")[1].split(",")[0] for r in rows[1:]}
    assert len(lieutenant_maj) == 1  # IC1 at the REPL surface


def test_signed_backend_repl_end_to_end():
    # --protocol sm --signed: full host-sign -> device-verify round from
    # the REPL shell (n=4 keeps the CPU jnp verify affordable).
    cluster = Cluster(
        4, JaxBackend(platform="cpu", protocol="sm", m=1, signed=True), seed=1
    )
    out = drive(cluster, ["actual-order retreat"])
    assert out[-1] == (
        "Execute order: retreat! Non-faulty nodes in the system - "
        "3 out of 4 quorum suggests retreat"
    )
