"""Smoke tests: every examples/ script runs green as a subprocess.

Each example asserts its own invariants; here we only require exit 0 on
the virtual-CPU path with small sizes, so the examples can never rot.
"""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = sorted(
    (pathlib.Path(__file__).parent.parent / "examples").glob("*.py")
)


@pytest.mark.parametrize("script", EXAMPLES, ids=[p.stem for p in EXAMPLES])
def test_example_runs(script):
    # Force the CPU path regardless of a present TPU: examples must be
    # runnable on any machine, and the smoke test must not contend for
    # the chip.
    # BA_TPU_TESTS_ON_TPU=1 is set explicitly (not just inherited) so every
    # run pins the precedence rule: an explicit BA_TPU_EXAMPLE_PLATFORM=cpu
    # must override the TPU-tests guard inside select_example_platform, or
    # the subprocess would land on (and race for) the real chip.
    env = dict(
        os.environ,
        BA_TPU_EXAMPLE_PLATFORM="cpu",
        BA_TPU_TESTS_ON_TPU="1",
        SWEEP_BATCH="256",
        SWEEP_CAP="16",
    )
    proc = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=str(script.parent.parent),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
