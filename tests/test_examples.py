"""Smoke tests: every examples/ script runs green as a subprocess.

Each example asserts its own invariants; here we only require exit 0 on
the virtual-CPU path with small sizes, so the examples can never rot.
"""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = sorted(
    (pathlib.Path(__file__).parent.parent / "examples").glob("*.py")
)


@pytest.mark.parametrize("script", EXAMPLES, ids=[p.stem for p in EXAMPLES])
def test_example_runs(script):
    # Force the CPU path regardless of a present TPU: examples must be
    # runnable on any machine, and the smoke test must not contend for
    # the chip.
    env = dict(
        os.environ,
        BA_TPU_EXAMPLE_PLATFORM="cpu",
        SWEEP_BATCH="256",
        SWEEP_CAP="16",
    )
    # An inherited BA_TPU_TESTS_ON_TPU=1 would make force_virtual_cpu_devices
    # a no-op and put the example subprocesses on the real chip, racing the
    # main pytest process for it — the explicit cpu request must win here.
    env.pop("BA_TPU_TESTS_ON_TPU", None)
    proc = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=str(script.parent.parent),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
