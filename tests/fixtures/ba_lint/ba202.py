"""BA202 rng-key-reuse fixture (never imported; parsed by ba-lint)."""

import jax.random as jr
import jax.random as aliased_random
from jax.random import normal as nrm


def positive_plain_reuse(key):
    a = jr.normal(key, (2,))
    b = jr.uniform(key, (2,))  # expect: BA202
    return a + b


def positive_through_aliases(key):
    a = aliased_random.bernoulli(key, 0.5, (4,))
    b = nrm(key, (4,))  # expect: BA202
    return a, b


def positive_loop_invariant(key):
    acc = 0.0
    for _ in range(8):
        acc += jr.normal(key, ())  # expect: BA202
    return acc


def positive_after_derive_then_double(key):
    k = jr.fold_in(key, 7)
    a = jr.randint(k, (3,), 0, 10)
    b = jr.permutation(k, 16)  # expect: BA202
    return a, b


def positive_derive_does_not_decorrelate(key):
    # Keys are immutable: splitting `key` does not change what
    # jr.normal(key) returns, so the second sampling still repeats the
    # first — deriving must NOT clear the consumed mark.
    a = jr.normal(key, (4,))
    k1, k2 = jr.split(key)
    b = jr.normal(key, (4,))  # expect: BA202
    return a, b, k1, k2


def negative_split_between(key):
    a = jr.normal(key, (2,))
    k1, k2 = jr.split(key)
    b = jr.uniform(k1, (2,))
    c = jr.uniform(k2, (2,))
    return a, b, c


def negative_fold_in_between(key):
    a = jr.normal(key, (2,))
    k2 = jr.fold_in(key, 1)
    b = jr.uniform(k2, (2,))
    return a, b


def negative_inline_derivation(key):
    a = jr.normal(jr.fold_in(key, 0), (2,))
    b = jr.normal(jr.fold_in(key, 1), (2,))
    return a, b


def negative_rebound(key):
    a = jr.normal(key, (2,))
    key = jr.fold_in(key, 1)
    b = jr.normal(key, (2,))
    return a, b


def negative_branches(key, flag):
    if flag:
        a = jr.normal(key, (2,))
    else:
        a = jr.uniform(key, (2,))
    return a


def negative_loop_derives(key):
    acc = 0.0
    for i in range(8):
        acc += jr.normal(jr.fold_in(key, i), ())
    return acc


def negative_lambda_is_opaque(key):
    fns = [lambda k=key: jr.normal(k, ())]
    a = jr.normal(key, (2,))
    return fns, a


def suppressed_ab_replay(key):
    a = jr.normal(key, (4,))
    b = jr.normal(key, (4,))  # ba-lint: disable=BA202
    return a, b
