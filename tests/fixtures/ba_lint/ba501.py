"""BA501 unsynchronized-shared-mutation fixture (parsed, never run).

Covers: Thread-target entry discovery through an import ALIAS
(``import threading as th``), the ``# ba-lint: thread-entry``
annotation for indirect dispatch, guarded-vs-unguarded mixes, the
clean common-lock negative, and the suppression demo.
"""

import threading
import threading as th


class Racy:
    """Dispatcher-loop pattern: `_loop` runs on its own thread, the
    public API mutates the same attributes from caller threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counter = 0
        self.mode = "idle"

    def start(self):
        worker = th.Thread(target=self._loop, daemon=True)
        worker.start()

    def _loop(self):
        while True:
            self.counter = self.counter + 1  # expect: BA501
            with self._lock:
                self.mode = "busy"

    def bump(self):
        self.counter = 0
        self.mode = "idle"  # expect: BA501


class Dispatched:
    """No Thread() call names `on_tick` — an external registry fires
    it — so the annotation supplies the entry fact."""

    def __init__(self):
        self.jobs = 0

    def on_tick(self):  # ba-lint: thread-entry
        self.jobs = self.jobs + 1  # expect: BA501

    def reset(self):
        self.jobs = 0


class Disciplined:
    """Negative: every cross-context write holds the same lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.total = self.total + 1

    def reset(self):
        with self._lock:
            self.total = 0


class Waived:
    """Suppression demo: a deliberate GIL-atomic single-store pattern
    carries the named waiver on the anchored line."""

    def __init__(self):
        self.beat = 0.0

    def arm(self):
        th.Thread(target=self._tick, daemon=True).start()

    def _tick(self):
        self.beat = 1.0  # ba-lint: disable=BA501

    def poke(self):
        self.beat = 2.0
