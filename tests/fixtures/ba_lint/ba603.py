"""BA603 env-registry fixture (parsed, never run).

Every ``BA_TPU_*`` READ must have a README "Environment knobs" row
(the ``analysis/contracts.ENV_DOCUMENTED`` mirror).  Reads through
module-level name constants resolve; writes/clears never flag (tests
legitimately set synthetic names); wildcard-documented prefixes pass.
"""

import os

FIXTURE_ENV = "BA_TPU_FIXTURE_ONLY_KNOB"


def undocumented_read():
    return os.environ.get("BA_TPU_NOT_A_DOCUMENTED_KNOB", "")  # expect: BA603


def constant_indirection():
    return os.environ.get(FIXTURE_ENV, "")  # expect: BA603


def subscript_read():
    return os.environ["BA_TPU_ALSO_UNDOCUMENTED"]  # expect: BA603


def membership_read():
    return "BA_TPU_THIRD_UNDOCUMENTED" in os.environ  # expect: BA603


def getenv_read():
    return os.getenv("BA_TPU_FOURTH_UNDOCUMENTED")  # expect: BA603


def documented_read():
    return os.environ.get("BA_TPU_WARM", "")  # negative: README row exists


def wildcard_read():
    return os.getenv("BA_TPU_BENCH_ANYTHING")  # negative: wildcard row


def write_only():
    os.environ["BA_TPU_SCRATCH_SET_ONLY"] = "1"  # negative: a write
    os.environ.pop("BA_TPU_SCRATCH_SET_ONLY", None)  # negative: a clear
