"""BA602 metric-naming fixture (parsed, never run).

The ``serve_`` prefix and ``_per_shard`` suffix rules, applied at
counter/gauge/histogram construction sites with literal names — the
static mirror of the runtime asserts in ``obs/registry``.
"""


class _Reg:
    def counter(self, name):
        return name

    def gauge(self, name):
        return name

    def histogram(self, name):
        return name


def build(reg):
    reg.counter("requests_serve_total")  # expect: BA602
    reg.gauge("per_shard_bytes")  # expect: BA602
    reg.histogram("wait_serve_s")  # expect: BA602
    reg.histogram("serve_wait_s")  # negative: canonical prefix
    reg.gauge("plane_bytes_per_shard")  # negative: canonical suffix
    reg.counter("observed_metric")  # negative: 'serve' only as substring
    name = "dyn_serve_gauge"
    reg.gauge(name)  # negative: dynamic name, runtime assert covers it
