"""BA504 leaked-timer/daemon-lifecycle fixture (parsed, never run).

Covers: local Timer armed without a finally-cancel, the unbindable
``Timer(...).start()`` chain, the compliant try/finally pattern,
self-stored Timers with and without a class-side cancel, and non-daemon
threads with and without a join.
"""

import threading


def orphan_timer():
    t = threading.Timer(1.0, print)  # expect: BA504
    t.start()


def chained_start():
    threading.Timer(0.5, print).start()  # expect: BA504


def clean_timer():
    t = threading.Timer(1.0, print)
    t.start()
    try:
        return 1
    finally:
        t.cancel()


def unarmed_timer():
    t = threading.Timer(1.0, print)  # negative: never started
    return t


class KeepsTimer:
    def arm(self):
        self._t = threading.Timer(1.0, print)  # expect: BA504
        self._t.start()


class CancelsTimer:
    def arm(self):
        self._t = threading.Timer(1.0, print)
        self._t.start()

    def close(self):
        self._t.cancel()


def unjoined_thread():
    t = threading.Thread(target=print)  # expect: BA504
    t.start()


def joined_thread():
    t = threading.Thread(target=print)
    t.start()
    t.join()


def daemon_thread():
    t = threading.Thread(target=print, daemon=True)
    t.start()


def daemon_after_construction():
    t = threading.Thread(target=print)
    t.daemon = True
    t.start()


class KeepsThread:
    def start(self):
        self._thr = threading.Thread(target=self._idle)  # expect: BA504
        self._thr.start()

    def _idle(self):
        pass


class JoinsThread:
    def start(self):
        self._thr = threading.Thread(target=self._idle)
        self._thr.start()

    def _idle(self):
        pass

    def stop(self):
        self._thr.join()
