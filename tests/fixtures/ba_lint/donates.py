"""BA201 via the `# ba-lint: donates(...)` ANNOTATION (ISSUE 5): a
wrapper with no visible donate_argnums declares its consuming contract
on its own def line, and use-after-donate at its call sites flags
exactly like the jit-decorated registry entries.  Also pins that a
mis-declared annotation (a name that is not a parameter) is itself a
finding rather than silent dead protection.
"""


def consume_state(key, state):  # ba-lint: donates(state)
    # Stand-in for a pipeline_sweep-style wrapper: `state` is consumed
    # by a donating dispatch inside; `key` survives.
    return state


def positional_call_site(key, state):
    out = consume_state(key, state)
    bad = state  # expect: BA201
    return out, bad


def keyword_call_site(key, state):
    out = consume_state(key, state=state)
    return out, state  # expect: BA201


def key_survives(key, state):
    out = consume_state(key, state)
    return out, key  # the annotation names only `state`


def rebinding_is_clean(key, state):
    state = consume_state(key, state)
    return state


def annotated_with_typo(key, state):  # ba-lint: donates(sate)  # expect: BA201
    return state
