"""BA401 dead-import fixture (never imported; parsed by ba-lint)."""

from __future__ import annotations

import json
import os as operating_system  # expect: BA401
from datetime import datetime  # expect: BA401
from functools import wraps  # expect: BA401

import collections
import collections.abc as cabc  # expect: BA401

from json import JSONDecodeError as ReExported
from json import dumps as _

__all__ = ["ReExported", "used_everywhere"]


def used_everywhere(blob):
    # `json` used via attribute chain (base name counts); `collections`
    # via a nested attribute.
    payload = json.loads(blob)
    return collections.OrderedDict(sorted(payload.items()))
