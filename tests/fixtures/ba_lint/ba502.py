"""BA502 lock-free-read-discipline fixture (parsed, never run).

The own-line declaration below puts the whole module under the
discipline: attribute RMW, shared-container iteration, and ANY lock
acquisition flag; single-opcode reads, local snapshots, and literal
iteration stay legal.
"""

# ba-lint: lockfree

import threading

_LOCK = threading.Lock()
SHARED = {"a": 1}


class Sampler:
    def __init__(self):
        self.count = 0
        self.table = {}

    def sample(self):
        self.count += 1  # expect: BA502
        with _LOCK:  # expect: BA502
            pass
        _LOCK.acquire()  # expect: BA502
        for k in self.table:  # expect: BA502
            _ = k
        for _k, _v in SHARED.items():  # expect: BA502
            pass
        snapshot = dict(SHARED)  # a single-opcode-ish copy is the fix
        for k in snapshot:  # negative: local
            _ = k
        for i in (1, 2, 3):  # negative: literal
            _ = i
        value = self.count  # negative: GIL-atomic attribute load
        return value
