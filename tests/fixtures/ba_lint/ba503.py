"""BA503 lock-order-cycle fixture (parsed, never run).

Covers: the two-lock AB/BA cycle (both acquisition sites flag), the
one-hop cycle through a method call made under a lock, non-reentrant
re-acquire (self-deadlock), and the RLock re-entry negative.
"""

import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # expect: BA503
                pass

    def backward(self):
        with self._b:
            with self._a:  # expect: BA503
                pass


class Hop:
    """The second edge of the cycle is indirect: `top` calls `_low`
    while holding `_x`, and `_low` acquires `_y` at its top level."""

    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def top(self):
        with self._x:
            self._low()  # expect: BA503

    def _low(self):
        with self._y:
            pass

    def rev(self):
        with self._y:
            with self._x:  # expect: BA503
                pass


class Reacquire:
    def __init__(self):
        self._m = threading.Lock()

    def outer(self):
        with self._m:
            with self._m:  # expect: BA503
                pass


class Reentrant:
    """Negative: RLock re-entry is what RLock is FOR."""

    def __init__(self):
        self._m = threading.RLock()

    def outer(self):
        with self._m:
            with self._m:
                pass


class Ordered:
    """Negative: both paths take the locks in the same order."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a, self._b:
            pass
