"""BA601 record-schema fixture (parsed, never run).

Emit sites are dict literals with a constant ``"event"`` key that
either spell ``"v"`` literally or flow directly into ``.emit(...)``.
Unknown families and missing required keys flag; ``**spread`` sites and
plain payload/filter dicts do not.
"""

SCHEMA_VERSION = 1


class _Sink:
    def emit(self, rec):
        return rec


def unknown_family(sink):
    sink.emit({"event": "mystery_signal", "v": 1})  # expect: BA601


def missing_required_keys(sink):
    sink.emit(
        {  # expect: BA601
            "event": "admission",
            "v": SCHEMA_VERSION,
            "decision": "admit",
        }
    )


def complete_site(sink):
    sink.emit(
        {
            "event": "admission",
            "v": SCHEMA_VERSION,
            "decision": "admit",
            "tier": 0,
            "queue_depth": 3,
        }
    )


def spread_site(sink, extra):
    # Negative: required keys may arrive through the **spread — only
    # the dynamic checker can judge this site.
    sink.emit({"event": "shed", "v": SCHEMA_VERSION, **extra})


# Negative: names an event but neither versions itself nor reaches an
# emit() — a filter/payload dict, not an emit site.
ADMISSION_FILTER = {"event": "admission"}
