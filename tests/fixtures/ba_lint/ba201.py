"""BA201 use-after-donate fixture (never imported; parsed by ba-lint)."""

import functools

import jax

import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnames=("n",))
def megastep(state, sched, *, n=1):
    return state + 1, sched + n


@functools.partial(jax.jit, donate_argnames=("buf",))
def named_donate(x, buf):
    return x + buf


def _plain(state):
    return state * 2


consuming = jax.jit(_plain, donate_argnums=(0,))


def positive_read_after_donate(state, sched):
    out = megastep(state, sched)
    return state.sum()  # expect: BA201


def positive_second_arg(state, sched):
    out = megastep(state, sched)
    hist = jnp.sum(sched)  # expect: BA201
    return out, hist


def positive_assigned_jit(state):
    out = consuming(state)
    return out + state  # expect: BA201


def positive_kwarg_by_name(x, buf):
    y = named_donate(x, buf=buf)
    return y, buf  # expect: BA201


def positive_loop_carried(state, sched):
    outs = []
    for _ in range(4):
        out = megastep(state, sched)  # expect: BA201
        outs.append(out)
        # `state` is donated above and never rebound: the second
        # iteration's call reads a deleted buffer.
    return outs


def negative_rethread(state, sched):
    state, sched = megastep(state, sched)
    return state.sum() + sched.sum()


def negative_copy_before(state, sched):
    keep = jax.tree.map(lambda x: x.copy(), state)
    state, sched = megastep(state, sched)
    return keep, state, sched


def negative_branch_isolated(state, sched, flag):
    if flag:
        state, sched = megastep(state, sched)
    return state.sum()  # donate happened only on the taken branch


def negative_boolop_short_circuit(state, sched, flag):
    # `and` may never evaluate its right side: the conditional donate
    # must not poison the fall-through read, same as an `if` branch.
    _ = flag and megastep(state, sched)
    return state.sum()


def negative_read_before(state, sched):
    shape = state.shape
    out = megastep(state, sched)
    return shape, out


def suppressed_deliberate(state, sched):
    out = megastep(state, sched)
    return state.is_deleted(), out  # ba-lint: disable=BA201
