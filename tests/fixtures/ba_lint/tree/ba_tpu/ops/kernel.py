"""BA301 fixture: transitive contamination through the jitted tree.

This module never names obs — but it imports ``ba_tpu.core.impure``,
a jitted-tree module whose closure reaches ``ba_tpu.obs``.  The grep
this rule replaced could not see this at all.
"""

from ba_tpu.core.impure import positive_emit_through_alias  # expect: BA301

from ba_tpu.core.pure import quorum_threshold


def body(x):
    return positive_emit_through_alias(x) + quorum_threshold(x)
