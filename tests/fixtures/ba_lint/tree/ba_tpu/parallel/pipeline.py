"""BA101/BA102 fixture: scoped as ba_tpu.parallel.pipeline (never run).

The alias tricks here are the whole point: the old greps matched
``\\bnp\\.asarray`` and ``jr\\.split`` as TEXT, so ``import numpy as
jnp_like`` slipped through and ``import jax.numpy as np`` false-
positived.  ba-lint resolves both.
"""

import os

import functools

import jax
import jax.numpy as np
import jax.random as jr
import numpy as jnp_like
from jax.random import split as sp


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, keys):
    return state


def positive_host_sync(x, state):
    jax.block_until_ready(x)  # expect: BA101
    y = x.block_until_ready()  # expect: BA101
    h = jnp_like.asarray(x)  # expect: BA101
    v = x.item()  # expect: BA101
    t = x.tolist()  # expect: BA101
    n = int(np.sum(x))  # expect: BA101
    return y, h, v, t, n


def positive_host_keys(key, xs):
    k1, k2 = jr.split(key)  # expect: BA102
    k3 = sp(k2, 3)  # expect: BA102
    out = []
    for i, x in enumerate(xs):
        out.append(jr.fold_in(key, i))  # expect: BA102
    return k1, k3, out


def negative_device_side(x, key, sched_counter):
    # jax.numpy is device-side whatever it is locally named; fold_in
    # OUTSIDE a host loop is the sanctioned round_keys-style derivation.
    a = np.asarray(x)
    b = np.array([1, 2, 3])
    kr = jr.fold_in(key, sched_counter)
    n = int(os.environ.get("DEPTH", 2))
    return a, b, kr, n


def suppressed_sanctioned_drain(x):
    return x.item()  # ba-lint: disable=BA101
