"""Cross-module BA201 + parallel-wide BA101 fixture (never run).

``step`` donates in ``pipeline.py``; the call sites here prove the
donation registry resolves through import aliases across modules, and
that the ``pipeline_sweep`` CONVENTION entry (donates ``state``, arg 1)
applies to importers by qualified name.  ``block_until_ready`` is
banned across ALL of ``ba_tpu.parallel``, not just the two
conversion-scoped modules.
"""

from ba_tpu.parallel.pipeline import pipeline_sweep, step as megastep


def positive_cross_module_donate(state, keys):
    out = megastep(state, keys)
    return state  # expect: BA201


def positive_convention_donate(key, state):
    out = pipeline_sweep(key, state, 64)
    hist = out["histograms"]
    return hist, state.shape  # expect: BA201


def positive_sync_outside_conversion_scope(x):
    return x.block_until_ready()  # expect: BA101


def negative_key_survives(key, state):
    state2 = pipeline_sweep(key, state, 64)["final_state"]
    probe = pipeline_sweep(key, state2, 1)
    return probe
