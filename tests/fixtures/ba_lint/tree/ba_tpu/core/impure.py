"""BA301 fixture: direct obs reference from a jitted-tree module."""

from ba_tpu import obs as quietly_renamed  # expect: BA301 BA401
from ba_tpu.obs.trace import span as sp  # expect: BA301 BA401
from ba_tpu.utils import metrics as m

from ba_tpu.core.pure import quorum_threshold


def positive_emit_through_alias(decision):
    m.emit({"event": "round", "decision": decision})  # expect: BA301 BA601
    return quorum_threshold(decision)
