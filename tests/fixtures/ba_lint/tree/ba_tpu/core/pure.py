"""BA301 fixture: a clean jitted-tree module (all negatives)."""

import jax.numpy as jnp

from ba_tpu.utils.helpers import clamp


def quorum_threshold(n):
    return clamp(jnp.asarray(n) // 3 + 1)
