"""BA301 fixture: a HOST-layer module may reach obs (boundary case).

``core.pure`` imports this module; this module references obs.  That
must NOT contaminate ``core.pure`` — the closure follows edges only
through jitted-tree (core/ops) modules, because host-layer utilities
legitimately instrument their own host paths (the real
``utils/platform.py`` -> ``obs.instrument`` chain).
"""

from ba_tpu.obs import default_registry


def clamp(x):
    default_registry().counter("clamp_calls_total").inc()
    return x
