"""Test harness config: force an 8-device virtual CPU mesh before jax runs.

The platform quirk and the virtual-mesh rationale live in
``ba_tpu.utils.platform`` (shared with ``__graft_entry__.dryrun_multichip``).
Set ``BA_TPU_TESTS_ON_TPU=1`` to run the suite on real TPU hardware instead.
"""

import os

import pytest

from ba_tpu.utils.platform import force_virtual_cpu_devices

force_virtual_cpu_devices(8)

# Compilation-cache hygiene (ROADMAP decision): the suite SHARES the
# persistent XLA cache, enabled here EXPLICITLY rather than as a side
# effect of whichever test constructs a JaxBackend first (the pre-PR-2
# accident: files sorted before test_backends ran cold, everything after
# ran warm).  Measured on this 2-vCPU CI host: tests/test_crypto.py
# ALONE takes 8m19s cold vs the ENTIRE warm suite at ~10m, against
# tier-1's fixed 870 s budget — cold-by-default is not a choice this
# suite can afford.  Compile-regression hunts opt OUT explicitly:
# BA_TPU_COMPILE_CACHE=0 in the invoking env keeps every compile real
# (tests/test_platform.py covers the knob; scripts/ci.sh documents the
# decision).
# Cross-run recompile ledger hygiene (ISSUE 6): the ledger persists
# compile signatures in the SHARED cache dir, so with it on, whichever
# axes the previous test process happened to compile last would make
# this process's first compiles emit cross_process recompile records —
# order-dependent test noise.  Tests that cover the ledger configure it
# explicitly at tmp paths (tests/test_obs_xla.py).
os.environ.setdefault("BA_TPU_COMPILE_LEDGER", "0")

if os.environ.get("BA_TPU_COMPILE_CACHE") != "0":
    from ba_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
