"""Test harness config: force an 8-device virtual CPU mesh before jax runs.

Mirrors the reference's trick of simulating a multi-node cluster inside one
process (thread-per-general with real sockets, ba.py:79-80,344-351): here the
"cluster" is 8 virtual XLA CPU devices, so every sharding/collective path is
exercised without TPU hardware (SURVEY.md section 5).

Environment quirk: this image's ``sitecustomize`` imports jax at interpreter
startup and latches ``JAX_PLATFORMS`` from the environment (a TPU tunnel
backend that deadlocks if re-selected under a CPU-only env), so we must
switch platforms via ``jax.config.update`` rather than env vars.  XLA_FLAGS
is still read lazily at first backend init, so setting it here (before any
``jax.devices()`` call) is early enough.  Set ``BA_TPU_TESTS_ON_TPU=1`` to
run the suite on real TPU hardware instead.
"""

import os

import pytest

if os.environ.get("BA_TPU_TESTS_ON_TPU") != "1":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
