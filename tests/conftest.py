"""Test harness config: force an 8-device virtual CPU mesh before jax runs.

The platform quirk and the virtual-mesh rationale live in
``ba_tpu.utils.platform`` (shared with ``__graft_entry__.dryrun_multichip``).
Set ``BA_TPU_TESTS_ON_TPU=1`` to run the suite on real TPU hardware instead.
"""

import pytest

from ba_tpu.utils.platform import force_virtual_cpu_devices

force_virtual_cpu_devices(8)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]
