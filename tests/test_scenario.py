"""Scenario engine tests (ISSUE 5 tentpole, ba_tpu/scenario + the
mutating megastep in parallel/pipeline.py).

The load-bearing contracts, each pinned independently:

1. **Spec/compiler hygiene** — eager host validation, JSON round-trip,
   dense-plane lowering (the CI CLI exercises the same path jax-free).
2. **Parity, bit-exact** (the ISSUE's three): the EMPTY scenario vs
   ``pipeline_sweep``, the KILL-ONLY scenario vs ``failover_sweep``
   (decisions, leaders, histograms), and the RANDOM strategy vs the
   historical coin paths under the same keys.
3. **Strategy semantics** — coordinated adversaries behave as specified
   (deterministic mini-cases: collusion forces quorum loss, silence is
   harmless withholding, ADAPTIVE_SPLIT responders break IC1/IC2) in
   both the oral and signed protocols.
4. **Counters** — the scenario counter block (PR 4 names + IC1/IC2
   verdicts) folded on device bit-matches a host derivation from the
   blocking reference driver across a kill-mid-campaign.
5. **Engine invariants** — donation consumes exactly (state, sched,
   strategy); the depth-k no-blocking schedule holds with a LIVE
   scenario block (dispatch-count proof, no new host sync).
6. **Runtime wiring** — backend/cluster/REPL scenario runs mutate the
   roster like the equivalent ``g-kill``/``g-state`` session would.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from ba_tpu.core import ATTACK, RETREAT, UNDEFINED, make_state
from ba_tpu.core.om import om1_round
from ba_tpu.core.eig import eig_round
from ba_tpu.core.sm import sm_round
from ba_tpu.parallel import (
    SCENARIO_COUNTER_NAMES,
    failover_sweep,
    fresh_copy as _fresh,
    load_carry_checkpoint,
    make_mesh,
    make_sweep_state,
    pipeline_sweep,
    save_carry_checkpoint,
    scenario_megastep,
    scenario_counters_init,
    scenario_sweep,
)
from ba_tpu.parallel.pipeline import make_key_schedule, round_keys
from ba_tpu.parallel.sweep import agreement_step
from ba_tpu.scenario import (
    ScenarioError,
    SparseScenarioBlock,
    block_from_kills,
    compile_scenario,
    empty_block,
    from_dict,
    to_dict,
    zero_chunk,
)
from ba_tpu.scenario import spec as spec_mod
from ba_tpu.scenario import strategies as strat_mod


# -- spec + compiler ----------------------------------------------------------


def test_strategy_ids_and_command_codes_pinned():
    # strategies.py keeps its constants local (import-cycle discipline);
    # they MUST track spec.STRATEGY_NAMES positions and core.types codes.
    for i, name in enumerate(spec_mod.STRATEGY_NAMES):
        assert getattr(strat_mod, name.upper()) == i == spec_mod.strategy_id(name)
    assert (strat_mod._RETREAT, strat_mod._ATTACK, strat_mod._UNDEFINED) == (
        RETREAT,
        ATTACK,
        UNDEFINED,
    )


def test_spec_round_trip_and_validation():
    doc = {
        "name": "demo",
        "rounds": 4,
        "order": "retreat",
        "events": [
            {"round": 1, "kill": [1, 2]},
            {"round": 2, "set_faulty": [3], "value": True},
            {"round": 3, "set_strategy": [3], "value": "silent",
             "instances": [0]},
            {"round": 3, "revive": [2]},
        ],
    }
    spec = from_dict(doc)
    assert to_dict(spec) == doc
    assert to_dict(from_dict(to_dict(spec))) == doc  # fixed point

    bad = [
        dict(doc, rounds=0),
        dict(doc, order="charge"),
        dict(doc, extra_key=1),
        dict(doc, events=[{"round": 9, "kill": [1]}]),       # round range
        dict(doc, events=[{"round": 0, "kill": []}]),        # empty ids
        dict(doc, events=[{"round": 0, "kill": [1, 1]}]),    # dup ids
        dict(doc, events=[{"round": 0, "kill": [0]}]),       # 1-based ids
        dict(doc, events=[{"round": 0, "kill": [1], "value": True}]),
        dict(doc, events=[{"round": 0, "set_faulty": [1]}]),  # no value
        dict(doc, events=[{"round": 0, "set_strategy": [1],
                           "value": "nope"}]),
        dict(doc, events=[{"round": 0, "boom": [1]}]),       # unknown kind
        dict(doc, events=[{"round": 0, "kill": [1], "revive": [2]}]),
        dict(doc, events=[{"round": 0, "kill": [1]},
                          {"round": 0, "revive": [1]}]),     # kill+revive
        dict(doc, events=[{"round": 0, "kill": [1],
                           "instances": []}]),
    ]
    for b in bad:
        with pytest.raises(ScenarioError):
            from_dict(b)


def test_spec_file_round_trip(tmp_path):
    spec = from_dict(
        {"name": "f", "rounds": 2,
         "events": [{"round": 1, "kill": [2]}]}
    )
    path = tmp_path / "s.json"
    spec_mod.save(str(path), spec)
    again = spec_mod.load(str(path))
    assert to_dict(again) == to_dict(spec)
    (tmp_path / "broken.json").write_text("{nope")
    with pytest.raises(ScenarioError, match="not valid JSON"):
        spec_mod.load(str(tmp_path / "broken.json"))


def test_compile_lowers_events_to_planes():
    spec = from_dict(
        {
            "name": "lower",
            "rounds": 3,
            "events": [
                {"round": 0, "kill": [2]},
                {"round": 1, "set_faulty": [1, 3], "value": True,
                 "instances": [1]},
                {"round": 2, "set_strategy": [3], "value": "collude_attack"},
                {"round": 2, "revive": [2]},
            ],
        }
    )
    block = compile_scenario(spec, batch=2, capacity=4)
    assert (block.rounds, block.batch, block.n) == (3, 2, 4)
    kill = np.zeros((3, 2, 4), bool)
    kill[0, :, 1] = True  # id 2 -> slot 1
    np.testing.assert_array_equal(block.kill, kill)
    revive = np.zeros((3, 2, 4), bool)
    revive[2, :, 1] = True
    np.testing.assert_array_equal(block.revive, revive)
    fset = np.full((3, 2, 4), -1, np.int8)
    fset[1, 1, 0] = 1
    fset[1, 1, 2] = 1  # instance-masked: only batch row 1
    np.testing.assert_array_equal(block.set_faulty, fset)
    sset = np.full((3, 2, 4), -1, np.int8)
    sset[2, :, 2] = spec_mod.strategy_id("collude_attack")
    np.testing.assert_array_equal(block.set_strategy, sset)
    # chunk() slices rounds for one dispatch.
    ck = block.chunk(1, 3)
    assert ck["kill"].shape == (2, 2, 4)
    np.testing.assert_array_equal(ck["set_faulty"], fset[1:])


def test_compile_rejects_unknown_ids_and_instances():
    spec = from_dict(
        {"name": "x", "rounds": 1, "events": [{"round": 0, "kill": [9]}]}
    )
    with pytest.raises(ScenarioError, match="not in the roster"):
        compile_scenario(spec, batch=2, capacity=4)
    spec2 = from_dict(
        {"name": "x", "rounds": 1,
         "events": [{"round": 0, "kill": [1], "instances": [5]}]}
    )
    with pytest.raises(ScenarioError, match="outside batch"):
        compile_scenario(spec2, batch=2, capacity=4)
    # Roster-id mapping: the backend's padded roster addresses by id.
    spec3 = from_dict(
        {"name": "x", "rounds": 1, "events": [{"round": 0, "kill": [7]}]}
    )
    block = compile_scenario(spec3, batch=1, capacity=4, ids=[3, 7, 9, 0])
    assert block.kill[0, 0].tolist() == [False, True, False, False]


def test_scenario_cli_round_trips_committed_specs(tmp_path):
    # The exact stage scripts/ci.sh gates on — and it must stay jax-free
    # (spec+compile are the analyzer-grade import-light path).
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    specs = sorted((repo / "examples" / "scenarios").glob("*.json"))
    assert len(specs) >= 2, "committed scenario specs missing"
    code = (
        "import sys\n"
        "from ba_tpu.scenario.__main__ import main\n"
        "rc = main(sys.argv[1:])\n"
        "banned = {m for m in sys.modules if m.split('.')[0] in"
        " ('jax', 'jaxlib')}\n"
        "assert not banned, banned\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, *map(str, specs)],
        capture_output=True, text=True, cwd=str(repo), timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count(": OK") == len(specs)
    # And a malformed file fails loudly.
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"name": "b", "rounds": 0, "events": []}))
    proc = subprocess.run(
        [sys.executable, "-m", "ba_tpu.scenario", str(bad)],
        capture_output=True, text=True, cwd=str(repo), timeout=120,
    )
    assert proc.returncode == 1 and "FAIL" in proc.stderr


# -- sparse lowering (ISSUE 6 tentpole piece 1) -------------------------------


def _churn_doc(rounds):
    return {
        "name": "sparse-demo",
        "rounds": rounds,
        "order": "attack",
        "events": [
            {"round": 1, "kill": [2]},
            {"round": 4, "set_faulty": [3], "value": True,
             "instances": [0]},
            {"round": 5, "set_strategy": [3], "value": "collude_attack"},
            {"round": rounds - 1, "revive": [2]},
        ],
    }


def test_sparse_vs_dense_lowering_parity_per_chunk():
    # Every chunk window the engine could request — ragged tails, event
    # windows, pure-agreement stretches — must materialize bit-identical
    # to the dense lowering's slice of the same rounds.
    R = 20
    spec = from_dict(_churn_doc(R))
    dense = compile_scenario(spec, batch=3, capacity=4)
    sparse = compile_scenario(spec, batch=3, capacity=4, sparse=True)
    assert (sparse.rounds, sparse.batch, sparse.n) == (R, 3, 4)
    for step in (1, 3, 7, R):
        for lo in range(0, R, step):
            hi = min(lo + step, R)
            d, s = dense.chunk(lo, hi), sparse.chunk(lo, hi)
            for name in d:
                np.testing.assert_array_equal(
                    d[name], s[name], err_msg=f"window [{lo}, {hi})"
                )
    # Emptiness agrees between the lowerings (bisect vs array scan).
    for lo, hi in [(0, 2), (2, 4), (6, R - 1), (R - 1, R)]:
        assert sparse.chunk_is_empty(lo, hi) == dense.chunk_is_empty(lo, hi)


def test_sparse_empty_chunk_fast_path_is_shared_and_readonly():
    spec = from_dict(
        {"name": "mostly-empty", "rounds": 1000,
         "events": [{"round": 2, "kill": [1]}]}
    )
    sparse = compile_scenario(spec, batch=2, capacity=4, sparse=True)
    assert sparse.event_rounds == (2,)
    # Two different empty windows of equal length: the SAME arrays.
    a, b = sparse.chunk(100, 200), sparse.chunk(500, 600)
    assert a["kill"] is b["kill"]
    assert a["kill"] is zero_chunk(100, 2, 4)["kill"]
    # Shared planes are read-only: scribbling fails loudly.
    with pytest.raises(ValueError):
        a["kill"][0, 0, 0] = True
    # Event windows allocate fresh, writable planes.
    ev = sparse.chunk(0, 10)
    assert ev["kill"] is not zero_chunk(10, 2, 4)["kill"]
    assert ev["kill"][2, :, 0].all()


def test_sparse_block_is_o_events_not_o_rounds():
    # A million-round campaign compiles instantly and holds no [R, ...]
    # arrays — only the resolved event tuples (the memory contract that
    # makes campaign length unbounded).
    R = 1_000_000
    spec = from_dict(
        {"name": "long", "rounds": R,
         "events": [{"round": R // 2, "kill": [1]}]}
    )
    sparse = compile_scenario(spec, batch=8, capacity=8, sparse=True)
    assert len(sparse.events) == 1
    assert sparse.chunk_nbytes(0, 64) == 64 * 8 * 8 * 4
    # Only the requested window materializes.
    ck = sparse.chunk(R // 2 - 1, R // 2 + 1)
    assert ck["kill"].shape == (2, 8, 8)
    assert ck["kill"][1, :, 0].all() and not ck["kill"][0].any()


def test_sparse_doc_round_trip_exact():
    spec = from_dict(_churn_doc(12))
    sparse = compile_scenario(spec, batch=3, capacity=4, sparse=True)
    doc = sparse.to_doc()
    again = SparseScenarioBlock.from_doc(json.loads(json.dumps(doc)))
    assert again == sparse
    assert again.to_doc() == doc
    for bad in (
        {"format": "nope"},
        dict(doc, v=99),
        dict(doc, events=[{"round": 0}]),
        # Hand-edited docs with JSON-plausible but unindexable types
        # must fail HERE (ScenarioError at construction), not as an
        # IndexError/TypeError mid-campaign inside chunk staging.
        dict(doc, rounds=float(sparse.rounds)),
        dict(doc, rounds=str(sparse.rounds)),
        dict(doc, events=[dict(doc["events"][0], round=1.0)]),
        dict(doc, events=[dict(doc["events"][0], slots=[0.0])]),
        # Values too: the resolved contract is None for kill/revive,
        # 0/1 for set_faulty, a strategy-table id for set_strategy — a
        # hand-edited doc carrying the SPEC grammar's string form, an
        # out-of-table id, or a stray tri-state value must fail here,
        # not inside _apply_event's plane write mid-campaign.
        dict(doc, events=[dict(doc["events"][0], value=1)]),  # kill
        dict(doc, events=[dict(doc["events"][1], value=3)]),  # set_faulty
        dict(doc, events=[dict(doc["events"][1], value=True)]),
        dict(doc, events=[dict(doc["events"][2], value="silent")]),
        dict(doc, events=[dict(doc["events"][2], value=200)]),
        dict(doc, events=[dict(doc["events"][2], value=None)]),
    ):
        with pytest.raises(ScenarioError):
            SparseScenarioBlock.from_doc(bad)
    with pytest.raises(ScenarioError):  # events validate on construction
        SparseScenarioBlock(rounds=2, batch=1, capacity=4,
                            events=((5, "kill", None, (0,), None),))


def test_sparse_scenario_engine_bit_exact_vs_dense():
    # The whole campaign through the engine under both lowerings:
    # decisions, leaders, histograms, counters — and the staging stats
    # prove the sparse side stayed O(chunk).
    B, cap, R = 16, 8, 12
    key = jr.key(47)
    state = make_sweep_state(jr.key(46), B, cap, order=ATTACK)
    spec = from_dict(_churn_doc(R))
    dense = compile_scenario(spec, B, cap)
    sparse = compile_scenario(spec, B, cap, sparse=True)
    out_d = scenario_sweep(
        key, _fresh(state), dense, rounds_per_dispatch=3,
        collect_decisions=True,
    )
    out_s = scenario_sweep(
        key, _fresh(state), sparse, rounds_per_dispatch=3,
        collect_decisions=True,
    )
    for k in ("decisions", "leaders", "histograms", "counters_per_round"):
        np.testing.assert_array_equal(out_d[k], out_s[k])
    assert out_d["counters"] == out_s["counters"]
    # Peak staged bytes bounded by ONE chunk, not the campaign.
    assert out_s["stats"]["plane_peak_bytes"] <= 3 * B * cap * 4
    assert out_s["stats"]["plane_peak_bytes"] > 0


def test_sparse_staging_reuses_zero_chunk_and_reports_gauges():
    from ba_tpu import obs
    from ba_tpu.obs.registry import MetricsRegistry

    # Events only in the FIRST chunk: every later chunk is the shared
    # zero chunk — peak bytes stay at exactly one chunk's planes even
    # though the campaign is 100x that, and the gauges expose it.
    B, cap, R, kpd = 8, 8, 200, 2
    spec = from_dict(
        {"name": "front-loaded", "rounds": R,
         "events": [{"round": 0, "kill": [2]}]}
    )
    sparse = compile_scenario(spec, B, cap, sparse=True)
    reg = MetricsRegistry()
    old = obs.registry._default
    obs.registry._default = reg
    try:
        out = scenario_sweep(
            jr.key(48), make_sweep_state(jr.key(49), B, cap), sparse,
            rounds_per_dispatch=kpd,
        )
    finally:
        obs.registry._default = old
    chunk_bytes = kpd * B * cap * 4
    assert out["stats"]["plane_peak_bytes"] == chunk_bytes
    snap = reg.snapshot()
    assert snap["scenario_plane_bytes"]["value"] == chunk_bytes
    assert snap["scenario_stage_overlap_s"]["value"] >= 0
    assert snap["scenario_stage_overlap_s"]["value"] == pytest.approx(
        out["stats"]["stage_s"]
    )


# -- parity (the ISSUE's three, all bit-exact) --------------------------------


def test_empty_scenario_bit_exact_vs_pipeline_sweep():
    B, cap, R = 32, 8, 6
    key = jr.key(11)
    state = make_sweep_state(jr.key(1), B, cap, order=ATTACK)
    plain = pipeline_sweep(
        key, _fresh(state), R, depth=2, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    scen = scenario_sweep(
        key, state, empty_block(R, B, cap),
        depth=2, rounds_per_dispatch=2, collect_decisions=True,
    )
    np.testing.assert_array_equal(scen["decisions"], plain["decisions"])
    np.testing.assert_array_equal(scen["histograms"], plain["histograms"])
    # Nothing mutated: leaders stay slot 0, strategies stay RANDOM.
    assert (scen["leaders"] == 0).all()
    assert (np.asarray(scen["final_strategy"]) == 0).all()


def test_kill_only_scenario_bit_exact_vs_failover_sweep():
    B, n, R = 24, 8, 7
    key = jr.key(13)
    faulty = jnp.zeros((B, n), bool).at[:, 4].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    rng = np.random.default_rng(3)
    kills = rng.random((R, B, n)) < 0.05
    kills[1, :, 0] = True  # every leader dies before round 1
    want = jax.jit(lambda k, s, ks: failover_sweep(k, s, ks))(
        key, _fresh(state), jnp.asarray(kills)
    )
    got = scenario_sweep(
        key, state, block_from_kills(kills),
        depth=2, rounds_per_dispatch=3, collect_decisions=True,
    )
    np.testing.assert_array_equal(got["decisions"], np.asarray(want["decisions"]))
    np.testing.assert_array_equal(got["leaders"], np.asarray(want["leaders"]))
    np.testing.assert_array_equal(
        got["histograms"], np.asarray(want["histograms"])
    )
    np.testing.assert_array_equal(
        np.asarray(got["final_state"].alive),
        np.asarray(want["final_state"].alive),
    )


def test_random_strategy_bit_exact_vs_coin_paths(monkeypatch):
    # The all-RANDOM strategy plane must reproduce the historical coin
    # streams bit-for-bit under the same keys: OM(1), the dense EIG
    # path, SM's exact relay, and the whole vmapped agreement_step.
    B, n = 16, 8
    faulty = jnp.zeros((B, n), bool).at[:, [0, 3]].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    zeros = jnp.zeros((B, n), jnp.int8)
    k = jr.key(17)
    np.testing.assert_array_equal(
        np.asarray(om1_round(k, state)),
        np.asarray(om1_round(k, state, zeros)),
    )
    monkeypatch.setenv("BA_TPU_EIG_FUSED", "0")  # strategies force dense
    np.testing.assert_array_equal(
        np.asarray(eig_round(k, state, 2)),
        np.asarray(eig_round(k, state, 2, None, zeros)),
    )
    np.testing.assert_array_equal(
        np.asarray(sm_round(k, state, 2)),
        np.asarray(sm_round(k, state, 2, strategies=zeros)),
    )
    keys = jr.split(jr.key(19), B)
    a = agreement_step(keys, state, m=1)
    b = agreement_step(keys, state, m=1, strategies=zeros)
    for field in ("majorities", "decision", "histogram"):
        np.testing.assert_array_equal(np.asarray(a[field]), np.asarray(b[field]))


# -- strategy semantics (deterministic mini-cases) ----------------------------


def _one_round(state, strategies, key=None):
    out = agreement_step(
        jr.split(key if key is not None else jr.key(0), state.batch),
        state,
        strategies=strategies,
    )
    return (
        np.asarray(out["majorities"]),
        np.asarray(out["decision"]),
    )


def test_colluding_coalition_forces_quorum_loss():
    # n=7, honest leader orders RETREAT, traitors {slots 4,5,6} collude
    # on ATTACK: each HONEST lieutenant tallies 3 retreat (self + two
    # honest peers) vs 3 attack (the coalition) -> tie -> UNDEFINED; the
    # traitors themselves still tally honestly (SURVEY Q3) and each
    # hears only the OTHER two traitors' lies (4R-2A -> RETREAT).  That
    # leaves 4 retreat votes against needed=5 (3f+1 at total 7): quorum
    # lost.  Fully deterministic (no coins survive the collusion) —
    # exactly the coordinated adversary the random-coin fault model
    # could never express.
    n = 7
    faulty = jnp.zeros((1, n), bool).at[:, [4, 5, 6]].set(True)
    state = make_state(1, n, order=RETREAT, faulty=faulty)
    strategies = jnp.zeros((1, n), jnp.int8).at[:, [4, 5, 6]].set(
        strat_mod.COLLUDE_ATTACK
    )
    maj, dec = _one_round(state, strategies)
    assert maj[0, 0] == RETREAT  # the commander keeps its order
    assert (maj[0, [1, 2, 3]] == UNDEFINED).all()  # honest: split 3-3
    assert (maj[0, [4, 5, 6]] == RETREAT).all()  # Q3: traitors tally honestly
    assert dec[0] == UNDEFINED


def test_silent_traitors_are_harmless_withholders():
    # The same coalition gone SILENT contributes nothing: every
    # lieutenant sees 2 retreat vs 0 -> the order stands.  Deterministic.
    n = 5
    faulty = jnp.zeros((1, n), bool).at[:, [3, 4]].set(True)
    state = make_state(1, n, order=RETREAT, faulty=faulty)
    strategies = jnp.zeros((1, n), jnp.int8).at[:, [3, 4]].set(
        strat_mod.SILENT
    )
    maj, dec = _one_round(state, strategies)
    assert (maj[0] == RETREAT).all()
    assert dec[0] == RETREAT


def test_adaptive_split_responders_break_ic1_and_ic2():
    # ADAPTIVE_SPLIT traitors answer by ASKER parity: with n=5, honest
    # leader ordering ATTACK and traitors {3,4}, odd asker 1 tallies
    # 2A/2R -> UNDEFINED while even asker 2 tallies 4A -> ATTACK: the
    # honest lieutenants disagree (IC1 broken) and one of them disobeys
    # an honest commander (IC2 broken).  Deterministic.
    n = 5
    faulty = jnp.zeros((1, n), bool).at[:, [3, 4]].set(True)
    state = make_state(1, n, order=ATTACK, faulty=faulty)
    strategies = jnp.zeros((1, n), jnp.int8).at[:, [3, 4]].set(
        strat_mod.ADAPTIVE_SPLIT
    )
    maj, _dec = _one_round(state, strategies)
    assert maj[0, 1] == UNDEFINED and maj[0, 2] == ATTACK
    # The scenario counters see exactly this as IC1+IC2 violations.
    spec = from_dict(
        {
            "name": "split",
            "rounds": 2,
            "order": "attack",
            "events": [
                {"round": 0, "set_faulty": [4, 5], "value": True},
                {"round": 0, "set_strategy": [4, 5],
                 "value": "adaptive_split"},
            ],
        }
    )
    out = scenario_sweep(
        jr.key(23), make_state(1, n, order=ATTACK),
        compile_scenario(spec, 1, n),
    )
    assert out["counters"]["ic1_violations"] == 2  # every round, B=1
    assert out["counters"]["ic2_violations"] == 2
    assert out["counters"]["equivocation_observed"] == 2


def test_sm_strategies_withhold_and_collude():
    n = 6
    # SILENT lieutenants with an honest commander: withholding cannot
    # stop the honest relay -> everyone decides the order.
    faulty = jnp.zeros((1, n), bool).at[:, [3, 4]].set(True)
    state = make_state(1, n, order=ATTACK, faulty=faulty)
    strategies = jnp.zeros((1, n), jnp.int8).at[:, [3, 4]].set(
        strat_mod.SILENT
    )
    choices = np.asarray(sm_round(jr.key(29), state, 2, strategies=strategies))
    assert (choices == ATTACK).all()
    # A COLLUDE_ATTACK commander stops equivocating: everyone receives
    # (and therefore sees exactly) {ATTACK} -> unanimous agreement even
    # under a faulty commander.  Deterministic.
    faulty_c = jnp.zeros((1, n), bool).at[:, 0].set(True)
    state_c = make_state(1, n, order=RETREAT, faulty=faulty_c)
    strategies_c = jnp.zeros((1, n), jnp.int8).at[:, 0].set(
        strat_mod.COLLUDE_ATTACK
    )
    choices_c = np.asarray(
        sm_round(jr.key(31), state_c, 2, strategies=strategies_c)
    )
    assert (choices_c[0, 1:] == ATTACK).all()


def test_sm_strategies_incompatible_modes_raise():
    state = make_state(1, 4, order=ATTACK)
    strategies = jnp.zeros((1, 4), jnp.int8)
    with pytest.raises(ValueError, match="collapsed"):
        sm_round(jr.key(0), state, 1, collapsed=True, strategies=strategies)
    withhold = jnp.zeros((1, 1, 4, 4, 2), bool)
    with pytest.raises(ValueError, match="withhold"):
        sm_round(jr.key(0), state, 1, withhold=withhold,
                 strategies=strategies)


# -- counters: device fold bit-matches host derivation ------------------------


@pytest.mark.parametrize("shards", [1, 8])
def test_scenario_counters_bit_match_host_derivation_kill_mid_campaign(
    shards,
):
    # ISSUE 5 satellite (extends PR 4's bit-match): the 5-entry scenario
    # block folded in-scan — agreement counters AND IC1/IC2 verdicts —
    # must bit-match the same counts derived on host from the blocking
    # reference driver, across a campaign that kills a leader and flips
    # strategies mid-flight.  The first three entries ARE the PR 4
    # block (protocol-agnostic: everything reads step outputs + state).
    # shards=8 (ISSUE 8) re-runs the proof through the mesh scan core:
    # the per-shard blocks tree-reduced at retire must bit-match the
    # same host derivation.
    if shards > 1 and len(jax.devices()) < shards:
        pytest.skip(f"needs {shards} virtual devices")
    B, cap, R = 16, 8, 6
    key = jr.key(37)
    state = make_sweep_state(jr.key(36), B, cap, order=ATTACK)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    spec = from_dict(
        {
            "name": "mid-campaign",
            "rounds": R,
            "order": "attack",
            "events": [
                {"round": 2, "kill": [1]},               # leaders die
                {"round": 3, "set_faulty": [3], "value": True},
                {"round": 3, "set_strategy": [3],
                 "value": "adaptive_split"},
                {"round": 4, "set_strategy": [3], "value": "silent",
                 "instances": list(range(B // 2))},
            ],
        }
    )
    block = compile_scenario(spec, B, cap)

    # Host derivation: replay the campaign with the blocking driver —
    # numpy membership bookkeeping + one jitted agreement_step per
    # round under the SAME key schedule and strategy planes.
    step = jax.jit(agreement_step, static_argnames=("m", "max_liars"))
    keys_fn = jax.jit(round_keys, static_argnums=1)
    alive = np.asarray(state.alive).copy()
    faulty = np.asarray(state.faulty).copy()
    leader = np.asarray(state.leader).copy()
    ids = np.asarray(state.ids)
    strat = np.zeros((B, cap), np.int8)
    want = np.zeros(len(SCENARIO_COUNTER_NAMES), np.int64)
    ref_decisions, ref_leaders = [], []
    for r in range(R):
        alive = (alive & ~block.kill[r]) | block.revive[r]
        faulty = np.where(block.set_faulty[r] >= 0,
                          block.set_faulty[r] > 0, faulty)
        strat = np.where(block.set_strategy[r] >= 0,
                         block.set_strategy[r], strat).astype(np.int8)
        dead = ~alive[np.arange(B), leader]
        lowest = np.where(alive, ids, np.iinfo(np.int32).max).argmin(1)
        leader = np.where(dead, lowest, leader).astype(np.int32)
        st = dataclasses.replace(
            state,
            leader=jnp.asarray(leader),
            faulty=jnp.asarray(faulty),
            alive=jnp.asarray(alive),
        )
        out = step(
            keys_fn(make_key_schedule(key, r), B), st,
            strategies=jnp.asarray(strat),
        )
        dec = np.asarray(out["decision"])
        maj = np.asarray(out["majorities"])
        ref_decisions.append(dec)
        ref_leaders.append(leader.copy())
        idx = np.arange(cap)[None, :]
        lieutenants = alive & (idx != leader[:, None])
        want[0] += (dec == UNDEFINED).sum()
        want[1] += int((dec == dec[0]).all())
        mmax = np.where(lieutenants, maj, -127).max(1)
        mmin = np.where(lieutenants, maj, 127).min(1)
        traitor_present = (faulty & alive).any(1)
        want[2] += (((mmax != mmin) & lieutenants.any(1))
                    & traitor_present).sum()
        honest_lt = lieutenants & ~faulty
        hmax = np.where(honest_lt, maj, -127).max(1)
        hmin = np.where(honest_lt, maj, 127).min(1)
        want[3] += ((hmax != hmin) & honest_lt.any(1)).sum()
        leader_faulty = faulty[np.arange(B), leader]
        disobey = (honest_lt & (maj != np.asarray(state.order)[:, None])).any(1)
        want[4] += (~leader_faulty & disobey).sum()

    got = scenario_sweep(
        key, _fresh(state), block,
        depth=2, rounds_per_dispatch=2, collect_decisions=True,
        mesh=(
            make_mesh((shards, 1), ("data", "node")) if shards > 1 else None
        ),
    )
    np.testing.assert_array_equal(got["decisions"], np.stack(ref_decisions))
    np.testing.assert_array_equal(got["leaders"], np.stack(ref_leaders))
    got_ctr = np.array(
        [got["counters"][name] for name in SCENARIO_COUNTER_NAMES]
    )
    np.testing.assert_array_equal(got_ctr, want)
    rows = got["counters_per_round"]
    assert rows.shape == (R, len(SCENARIO_COUNTER_NAMES))
    assert (np.diff(rows, axis=0) >= 0).all()
    np.testing.assert_array_equal(rows[-1], want)
    # The campaign actually exercised the verdicts.
    assert want[3] > 0 and want[4] > 0, want


def test_scenario_counters_continue_across_engine_runs():
    B, cap, R = 8, 8, 6
    key = jr.key(41)
    state = make_sweep_state(jr.key(40), B, cap, order=ATTACK)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    block = empty_block(R, B, cap)
    full = scenario_sweep(key, _fresh(state), block)
    head_block = block_from_kills(np.zeros((R // 2, B, cap), bool))
    head = scenario_sweep(key, _fresh(state), head_block)
    tail = scenario_megastep(
        head["final_state"],
        head["final_schedule"],
        head["final_strategy"],
        head["final_counters"],
        {k: jnp.asarray(v) for k, v in block.chunk(R // 2, R).items()},
        rounds=R // 2,
    )
    np.testing.assert_array_equal(
        np.asarray(tail[5])[-1],
        np.array([full["counters"][n] for n in SCENARIO_COUNTER_NAMES]),
    )


# -- engine invariants --------------------------------------------------------


def test_scenario_megastep_donation_contract():
    B, cap, R = 8, 8, 3
    state = make_sweep_state(jr.key(50), B, cap)
    sched = make_key_schedule(jr.key(51))
    strategy = jnp.zeros((B, cap), jnp.int8)
    counters = scenario_counters_init()
    ev = {k: jnp.asarray(v) for k, v in empty_block(R, B, cap).chunk(0, R).items()}
    out = scenario_megastep(state, sched, strategy, counters, ev, rounds=R)
    # The mutating carry (state, sched, strategy) is consumed...
    assert state.faulty.is_deleted()  # ba-lint: disable=BA201
    assert sched.key_data.is_deleted()  # ba-lint: disable=BA201
    assert strategy.is_deleted()  # ba-lint: disable=BA201
    # ...while the counter block and event planes are plain inputs (no
    # output aliases their shapes — the thread continues via the rows).
    assert not counters.is_deleted()
    assert not ev["kill"].is_deleted()
    # The returned carry is live and continues the campaign.
    assert int(jax.device_get(out[1].counter)) == R
    out2 = scenario_megastep(
        out[0], out[1], out[2], out[5][-1], ev, rounds=R
    )
    assert int(jax.device_get(out2[1].counter)) == 2 * R


def test_scenario_depth_k_no_blocking_with_live_block(monkeypatch):
    # ISSUE 5 acceptance: the dispatch-count proof holds with a LIVE
    # scenario block — kills mid-campaign, counters folding, event-chunk
    # staging — and the engine still never calls block_until_ready.
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 8, 8, 7, 3
    state = make_sweep_state(jr.key(55), B, cap)
    kills = np.zeros((R, B, cap), bool)
    kills[2, :, 0] = True
    kills[4, :, 1] = True
    events = []
    out = scenario_sweep(
        jr.key(56), state, block_from_kills(kills),
        depth=depth, rounds_per_dispatch=1,
        on_event=lambda kind, i: events.append((kind, i)),
    )
    assert [i for kind, i in events if kind == "dispatch"] == list(range(R))
    assert [i for kind, i in events if kind == "retire"] == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(("dispatch", r + depth))
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["retires_before_drain"] == R - depth
    # And the campaign genuinely mutated: leaders moved 0 -> 1 -> 2.
    assert out["leaders"][0, 0] == 0
    assert out["leaders"][2, 0] == 1
    assert out["leaders"][4, 0] == 2


def test_scenario_mesh_composes_bit_exact(eight_devices):
    mesh = make_mesh((8, 1), ("data", "node"))
    key = jr.key(61)
    state = make_sweep_state(jr.key(60), 32, 8, order=ATTACK)
    kills = np.zeros((4, 32, 8), bool)
    kills[1, :, 0] = True
    block = block_from_kills(kills)
    plain = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    sharded = scenario_sweep(
        key, state, block, rounds_per_dispatch=2, collect_decisions=True,
        mesh=mesh,
    )
    np.testing.assert_array_equal(plain["decisions"], sharded["decisions"])
    np.testing.assert_array_equal(plain["leaders"], sharded["leaders"])
    assert plain["counters"] == sharded["counters"]


def test_scenario_argument_validation():
    state = make_sweep_state(jr.key(70), 8, 8)
    with pytest.raises(ValueError, match="covers 3"):
        pipeline_sweep(jr.key(0), state, 4, scenario=empty_block(3, 8, 8))
    with pytest.raises(ValueError, match=r"\[8, 4\]"):
        pipeline_sweep(jr.key(0), state, 2, scenario=empty_block(2, 8, 4))
    with pytest.raises(ValueError, match="initial_strategy"):
        pipeline_sweep(
            jr.key(0), state, 2,
            initial_strategy=jnp.zeros((8, 8), jnp.int8),
        )
    with pytest.raises(ValueError, match="initial_strategy shape"):
        pipeline_sweep(
            jr.key(0), state, 2, scenario=empty_block(2, 8, 8),
            initial_strategy=jnp.zeros((4, 8), jnp.int8),
        )


def test_initial_strategy_is_not_consumed():
    # Only `state` is in scenario_sweep's donation contract: a caller's
    # strategy plane must survive the run (the engine copies it before
    # it joins the donated carry — jnp.asarray would otherwise zero-copy
    # a device array straight into the donation thread).
    B, cap = 8, 8
    plane = jnp.zeros((B, cap), jnp.int8).at[:, 3].set(
        strat_mod.COLLUDE_ATTACK
    )
    out1 = scenario_sweep(
        jr.key(90), make_sweep_state(jr.key(91), B, cap),
        empty_block(2, B, cap), initial_strategy=plane,
    )
    # Same plane reused for a second campaign: must not raise.
    out2 = scenario_sweep(
        jr.key(90), make_sweep_state(jr.key(91), B, cap),
        empty_block(2, B, cap), initial_strategy=plane,
    )
    assert not plane.is_deleted()
    np.testing.assert_array_equal(
        np.asarray(out1["final_strategy"]), np.asarray(plane)
    )
    np.testing.assert_array_equal(out1["histograms"], out2["histograms"])


def test_scenario_registry_counters_and_gauges():
    from ba_tpu import obs
    from ba_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    old = obs.registry._default
    obs.registry._default = reg
    try:
        state = make_sweep_state(jr.key(80), 8, 8)
        out = scenario_sweep(jr.key(81), state, empty_block(3, 8, 8))
    finally:
        obs.registry._default = old
    snap = reg.snapshot()
    assert snap["scenario_campaigns_total"]["value"] == 1
    assert snap["scenario_rounds_total"]["value"] == 3
    for name in SCENARIO_COUNTER_NAMES:
        assert snap[f"scenario_{name}"]["value"] == out["counters"][name]


def test_sparse_depth_k_no_blocking_with_staging_and_checkpoints(
    monkeypatch, tmp_path
):
    # ISSUE 6 acceptance: the dispatch-count proof holds with a SPARSE
    # block — double-buffered staging live, zero-chunk reuse live, carry
    # checkpoints live — and the engine still never calls
    # block_until_ready (checkpoint serialization rides the existing
    # retire fetch, staging is an async upload).
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 8, 8, 7, 3
    state = make_sweep_state(jr.key(55), B, cap)
    spec = from_dict(
        {
            "name": "sparse-noblock",
            "rounds": R,
            "events": [
                {"round": 2, "kill": [1]},
                {"round": 4, "kill": [2]},
            ],
        }
    )
    sparse = compile_scenario(spec, B, cap, sparse=True)
    events = []
    ckpts = []
    out = scenario_sweep(
        jr.key(56), state, sparse,
        depth=depth, rounds_per_dispatch=1,
        on_event=lambda kind, i: events.append((kind, i)),
        checkpoint_every=3,
        checkpoint_path=str(tmp_path / "nb_{round}.npz"),
        on_checkpoint=lambda r, p: ckpts.append((r, p)),
    )
    assert [i for kind, i in events if kind == "dispatch"] == list(range(R))
    assert [i for kind, i in events if kind == "retire"] == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(("dispatch", r + depth))
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["retires_before_drain"] == R - depth
    # The campaign mutated (leaders moved) and the checkpoints landed.
    assert out["leaders"][0, 0] == 0
    assert out["leaders"][2, 0] == 1
    assert out["leaders"][4, 0] == 2
    assert [r for r, _ in ckpts] == [3, 6]
    assert out["stats"]["checkpoints"] == 2
    assert (tmp_path / "nb_3.npz").exists()
    assert (tmp_path / "nb_6.npz").exists()


def test_mesh_depth_k_no_blocking_with_staging_and_checkpoints(
    monkeypatch, tmp_path
):
    # ISSUE 8: the dispatch-count proof on a LIVE 8x1 MESH with the full
    # streaming stack armed — sparse block, per-shard double-buffered
    # staging, carry checkpoints (gather-on-write) — and still no host
    # sync beyond the depth-delayed retires: the per-shard counter/
    # histogram reduction is host arithmetic inside the existing fetch.
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    mesh = make_mesh((8, 1), ("data", "node"))
    B, cap, R, depth = 16, 8, 7, 3
    state = make_sweep_state(jr.key(55), B, cap)
    spec = from_dict(
        {
            "name": "mesh-noblock",
            "rounds": R,
            "events": [
                {"round": 2, "kill": [1]},
                {"round": 4, "kill": [2]},
            ],
        }
    )
    sparse = compile_scenario(spec, B, cap, sparse=True)
    events = []
    out = scenario_sweep(
        jr.key(56), state, sparse,
        depth=depth, rounds_per_dispatch=1, mesh=mesh,
        on_event=lambda kind, i: events.append((kind, i)),
        checkpoint_every=3,
        checkpoint_path=str(tmp_path / "mnb_{round}.npz"),
    )
    assert [i for kind, i in events if kind == "dispatch"] == list(range(R))
    assert [i for kind, i in events if kind == "retire"] == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(("dispatch", r + depth))
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["shards"] == 8
    # Per-shard staging: one device holds 1/8 of each staged chunk.
    assert out["stats"]["plane_peak_bytes_per_shard"] == (
        out["stats"]["plane_peak_bytes"] // 8
    )
    assert (tmp_path / "mnb_3.npz").exists()
    # Gather-on-write: the checkpoint's counter block is canonical (1-D)
    # and the layout header records the writing mesh.
    ck = load_carry_checkpoint(str(tmp_path / "mnb_3.npz"))
    assert ck.counters.ndim == 1
    assert ck.shard_layout == {"data": 8, "node": 1}


def test_checkpoint_reshard_d8_to_d2_subprocess_bit_exact(tmp_path):
    # ISSUE 8 acceptance: a campaign checkpointed on EIGHT devices in a
    # separate process resumes HERE on a 2x1 mesh (and the same carry on
    # a single device), every tail bit-identical to the uninterrupted
    # run — gather-on-write / reshard-on-read, across a process
    # boundary.
    import os
    import pathlib
    import subprocess
    import sys

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    R = 8
    key, state, block = _mid_campaign_setup(R)
    full = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    ck_path = tmp_path / "reshard_{round}.npz"
    child = f'''
import dataclasses, jax.random as jr
from ba_tpu.parallel import make_mesh, make_sweep_state, pipeline_sweep
from ba_tpu.scenario import compile_scenario, from_dict

key = jr.key(91)
state = make_sweep_state(jr.key(90), 16, 8, order=1)
state = dataclasses.replace(
    state, faulty=state.faulty.at[:8, 0].set(True)
)
spec = from_dict({{
    "name": "ckpt-campaign", "rounds": {R}, "order": "attack",
    "events": [
        {{"round": 2, "kill": [1]}},
        {{"round": 5, "set_faulty": [3], "value": True}},
        {{"round": 6, "set_strategy": [3], "value": "adaptive_split"}},
    ],
}})
block = compile_scenario(spec, 16, 8, sparse=True)
mesh = make_mesh((8, 1), ("data", "node"))
out = pipeline_sweep(
    key, state, {R}, scenario=block, rounds_per_dispatch=2, mesh=mesh,
    checkpoint_every=4, checkpoint_path={str(ck_path)!r},
)
assert out["stats"]["shards"] == 8
'''
    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(repo), timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    mid = tmp_path / "reshard_4.npz"
    assert mid.exists()
    ck = load_carry_checkpoint(str(mid))
    assert ck.shard_layout == {"data": 8, "node": 1}
    for mesh in (make_mesh((2, 1), ("data", "node")), None):
        tail = pipeline_sweep(
            None, None, R, scenario=block, rounds_per_dispatch=2,
            collect_decisions=True, resume=str(mid), mesh=mesh,
        )
        np.testing.assert_array_equal(
            tail["decisions"], full["decisions"][4:]
        )
        np.testing.assert_array_equal(tail["leaders"], full["leaders"][4:])
        np.testing.assert_array_equal(
            tail["counters_per_round"], full["counters_per_round"][4:]
        )
        assert tail["counters"] == full["counters"]


def test_mesh_resume_of_in_memory_per_shard_carry(tmp_path):
    # The in-memory path of the same invariant: final_counters from a
    # mesh run is per-shard [d, C]; resuming it — via a saved
    # checkpoint on a DIFFERENT mesh size, or collapsing to a single
    # device — keeps totals bit-exact (the sum is the invariant).
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    R = 8
    key, state, block = _mid_campaign_setup(R)
    full = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    mesh8 = make_mesh((8, 1), ("data", "node"))
    head_ck = str(tmp_path / "head_{round}.npz")
    scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2, mesh=mesh8,
        checkpoint_every=4, checkpoint_path=head_ck,
    )
    ck = load_carry_checkpoint(str(tmp_path / "head_4.npz"))
    # save_carry_checkpoint round-trips a carry whose counters were
    # expanded per-shard in memory: seed one by hand.
    from ba_tpu.parallel import CarryCheckpoint
    from ba_tpu.parallel.shard import expand_counters

    per_shard = CarryCheckpoint(
        state=ck.state, schedule=ck.schedule,
        counters=expand_counters(mesh8, ck.counters),
        strategy=ck.strategy, round=ck.round,
        shard_layout={"data": 8, "node": 1},
    )
    path2 = str(tmp_path / "pershard.npz")
    save_carry_checkpoint(path2, per_shard)
    re = load_carry_checkpoint(path2)
    assert re.counters.ndim == 1
    np.testing.assert_array_equal(
        np.asarray(re.counters), np.asarray(ck.counters)
    )
    tail = scenario_sweep(
        None, None, block, rounds_per_dispatch=2,
        collect_decisions=True, resume=per_shard,
        mesh=make_mesh((4, 1), ("data", "node")),
    )
    np.testing.assert_array_equal(tail["decisions"], full["decisions"][4:])
    assert tail["counters"] == full["counters"]


# -- checkpointed carries (ISSUE 6 tentpole piece 3) --------------------------


def _mid_campaign_setup(R=12):
    B, cap = 16, 8
    key = jr.key(91)
    state = make_sweep_state(jr.key(90), B, cap, order=ATTACK)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    events = [
        e
        for e in [
            {"round": 2, "kill": [1]},
            {"round": 5, "set_faulty": [3], "value": True},
            {"round": 6, "set_strategy": [3], "value": "adaptive_split"},
            {"round": 9, "revive": [1]},
        ]
        if e["round"] < R
    ]
    spec = from_dict(
        {
            "name": "ckpt-campaign",
            "rounds": R,
            "order": "attack",
            "events": events,
        }
    )
    return key, state, compile_scenario(spec, B, cap, sparse=True)


def test_resume_from_checkpoint_bit_exact_mid_campaign(tmp_path):
    # The headline contract: interrupt nowhere, checkpoint mid-flight,
    # resume in a FRESH engine run — decisions, leaders, every counter,
    # the final strategy plane, alive masks and the schedule cursor all
    # bit-match the uninterrupted campaign's tail.
    R = 12
    key, state, block = _mid_campaign_setup(R)
    full = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    ckpts = []
    path = str(tmp_path / "carry_{round}.npz")
    pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, checkpoint_every=4, checkpoint_path=path,
        on_checkpoint=lambda r, p: ckpts.append((r, p)),
    )
    assert [r for r, _ in ckpts] == [4, 8, 12]
    for r0, p0 in ckpts[:-1]:
        tail = pipeline_sweep(
            None, None, R, scenario=block, rounds_per_dispatch=2,
            collect_decisions=True, resume=p0,
        )
        assert tail["stats"]["start_round"] == r0
        assert tail["stats"]["rounds"] == R - r0
        np.testing.assert_array_equal(
            tail["decisions"], full["decisions"][r0:]
        )
        np.testing.assert_array_equal(tail["leaders"], full["leaders"][r0:])
        np.testing.assert_array_equal(
            tail["histograms"], full["histograms"][r0:]
        )
        np.testing.assert_array_equal(
            tail["counters_per_round"], full["counters_per_round"][r0:]
        )
        assert tail["counters"] == full["counters"]
        np.testing.assert_array_equal(
            np.asarray(tail["final_strategy"]),
            np.asarray(full["final_strategy"]),
        )
        np.testing.assert_array_equal(
            np.asarray(tail["final_state"].alive),
            np.asarray(full["final_state"].alive),
        )
        assert int(jax.device_get(tail["final_schedule"].counter)) == R


def test_save_load_carry_checkpoint_public_api(tmp_path):
    from ba_tpu.parallel import CarryCheckpoint

    # A caller can checkpoint a finished run's live carry directly and
    # continue it later — the library form of the engine's in-retire
    # writer (same format, same loader).
    R = 6
    key, state, block = _mid_campaign_setup(R)
    head = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=3,
    )
    path = str(tmp_path / "manual.npz")
    save_carry_checkpoint(
        path,
        CarryCheckpoint(
            state=head["final_state"],
            schedule=head["final_schedule"],
            counters=head["final_counters"],
            strategy=head["final_strategy"],
            round=R,
        ),
        rounds_total=R,
    )
    ck = load_carry_checkpoint(path)
    assert ck.round == R
    np.testing.assert_array_equal(
        np.asarray(ck.state.alive), np.asarray(head["final_state"].alive)
    )
    assert int(jax.device_get(ck.schedule.counter)) == R
    # The loaded carry is donation-safe: run it straight into the engine.
    cont = pipeline_sweep(
        None, None, 2 * R,
        scenario=compile_scenario(
            from_dict({"name": "tail", "rounds": 2 * R, "events": []}),
            16, 8, sparse=True,
        ),
        rounds_per_dispatch=3, resume=ck,
    )
    assert cont["stats"]["rounds"] == R


def test_resume_validation_errors(tmp_path):
    R = 6
    key, state, block = _mid_campaign_setup(R)
    path = str(tmp_path / "ck_{round}.npz")
    pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=3,
        checkpoint_every=3, checkpoint_path=path,
    )
    ck = load_carry_checkpoint(str(tmp_path / "ck_3.npz"))
    with pytest.raises(ValueError, match="key=None"):
        pipeline_sweep(jr.key(0), _fresh(state), R, scenario=block,
                       resume=ck)
    with pytest.raises(ValueError, match="initial_strategy"):
        pipeline_sweep(
            None, None, R, scenario=block, resume=ck,
            initial_strategy=jnp.zeros((16, 8), jnp.int8),
        )
    with pytest.raises(ValueError, match="outside campaign"):
        short_block = compile_scenario(
            from_dict({"name": "short", "rounds": ck.round, "events": []}),
            16, 8, sparse=True,
        )
        pipeline_sweep(None, None, ck.round, scenario=short_block,
                       resume=ck)
    with pytest.raises(ValueError, match="strategy plane"):
        pipeline_sweep(None, None, R, resume=ck)  # scenario ckpt, no block
    with pytest.raises(ValueError, match="checkpoint_every"):
        pipeline_sweep(jr.key(0), _fresh(state), R,
                       checkpoint_path=str(tmp_path / "x.npz"))
    with pytest.raises(ValueError, match="checkpoint_every"):
        pipeline_sweep(jr.key(0), _fresh(state), R, checkpoint_every=0)


def test_checkpoint_schema_rejects_corruption(tmp_path):
    from ba_tpu.utils.snapshot import (
        read_carry_checkpoint,
        write_carry_checkpoint,
    )

    R = 6
    key, state, block = _mid_campaign_setup(R)
    path = str(tmp_path / "ck.npz")
    pipeline_sweep(
        key, _fresh(state), R, scenario=block, rounds_per_dispatch=3,
        checkpoint_every=3, checkpoint_path=path,
    )
    meta, arrays = read_carry_checkpoint(path)
    assert meta["scenario"] is True and meta["rounds_total"] == R
    # Cursor/counter disagreement is the resume-wrong-keys hazard.
    bad = str(tmp_path / "bad.npz")
    write_carry_checkpoint(bad, arrays, dict(meta, round=meta["round"] + 1))
    with pytest.raises(ValueError, match="disagrees"):
        read_carry_checkpoint(bad)
    # Missing carry arrays.
    broken = dict(arrays)
    del broken["key_data"]
    write_carry_checkpoint(bad, broken, meta)
    with pytest.raises(ValueError, match="missing carry arrays"):
        read_carry_checkpoint(bad)
    # Scenario carry without its planes.
    no_strat = {k: v for k, v in arrays.items() if k != "strategy"}
    write_carry_checkpoint(bad, no_strat, meta)
    with pytest.raises(ValueError, match="without counters/strategy"):
        read_carry_checkpoint(bad)
    # A truncated/half-written file raises ValueError like every other
    # corruption (np.load's BadZipFile is normalized), so the jax-free
    # CLI validator and resume= callers catching the documented
    # ValueError see it instead of a raw zipfile traceback.
    with open(path, "rb") as fh:
        head = fh.read(40)
    with open(bad, "wb") as fh:
        fh.write(head)
    with pytest.raises(ValueError, match="not a readable"):
        read_carry_checkpoint(bad)
    # A malformed shard-layout header (ISSUE 8) is a schema break like
    # any other; absence stays tolerated (pre-mesh checkpoints).
    assert meta["shard_layout"] == {"data": 1}
    write_carry_checkpoint(
        bad, arrays, dict(meta, shard_layout={"data": 0})
    )
    with pytest.raises(ValueError, match="shard_layout"):
        read_carry_checkpoint(bad)
    write_carry_checkpoint(bad, arrays, dict(meta, shard_layout="8x1"))
    with pytest.raises(ValueError, match="shard_layout"):
        read_carry_checkpoint(bad)
    legacy = {k: v for k, v in meta.items() if k != "shard_layout"}
    write_carry_checkpoint(bad, arrays, legacy)
    read_carry_checkpoint(bad)  # no layout: reads fine


def test_checkpoint_emits_jsonl_record(tmp_path):
    from ba_tpu.utils import metrics

    R = 6
    key, state, block = _mid_campaign_setup(R)
    sink = tmp_path / "metrics.jsonl"
    path = str(tmp_path / "ck_{round}.npz")
    old = metrics._default
    metrics._default = metrics.MetricsSink(str(sink))
    try:
        pipeline_sweep(
            key, _fresh(state), R, scenario=block, rounds_per_dispatch=3,
            checkpoint_every=3, checkpoint_path=path,
        )
    finally:
        metrics._default.close()
        metrics._default = old
    # Filter by the parsed event field, not a substring: the flight
    # recorder's end-of-run flight_summary (ISSUE 9) counts every event
    # family by name, so the literal string rides other records too.
    recs = [
        r
        for r in map(json.loads, sink.read_text().splitlines())
        if r.get("event") == "scenario_checkpoint"
    ]
    assert [r["round"] for r in recs] == [3, 6]
    for r in recs:
        assert r["v"] == 1 and r["rounds"] == R and r["scenario"] is True
        assert r["bytes"] > 0 and r["path"].endswith(f"ck_{r['round']}.npz")


def test_resume_across_process_boundary_bit_exact(tmp_path):
    # The carry crosses a PROCESS boundary: a subprocess runs the head
    # of the campaign and checkpoints; this process resumes from the
    # file and must bit-match its own uninterrupted run (threefry
    # derivation is process-independent — the checkpoint carries
    # everything else).  The written file is also vetted by the jax-free
    # CLI, proving ops can sanity-check checkpoints without a backend.
    import os
    import pathlib
    import subprocess
    import sys

    R = 8
    key, state, block = _mid_campaign_setup(R)
    full = scenario_sweep(
        key, _fresh(state), block, rounds_per_dispatch=2,
        collect_decisions=True,
    )
    ck_path = tmp_path / "boundary_{round}.npz"
    child = f'''
import dataclasses, jax.random as jr
from ba_tpu.parallel import make_sweep_state, pipeline_sweep, fresh_copy
from ba_tpu.scenario import compile_scenario, from_dict

key = jr.key(91)
state = make_sweep_state(jr.key(90), 16, 8, order=1)
state = dataclasses.replace(
    state, faulty=state.faulty.at[:8, 0].set(True)
)
spec = from_dict({{
    "name": "ckpt-campaign", "rounds": {R}, "order": "attack",
    "events": [
        {{"round": 2, "kill": [1]}},
        {{"round": 5, "set_faulty": [3], "value": True}},
        {{"round": 6, "set_strategy": [3], "value": "adaptive_split"}},
    ],
}})
block = compile_scenario(spec, 16, 8, sparse=True)
pipeline_sweep(
    key, state, {R}, scenario=block, rounds_per_dispatch=2,
    checkpoint_every=4, checkpoint_path={str(ck_path)!r},
)
'''
    repo = pathlib.Path(__file__).resolve().parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(repo), timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    mid = tmp_path / "boundary_4.npz"
    assert mid.exists()
    # Jax-free CLI validation of the child's checkpoint.
    code = (
        "import sys\n"
        "from ba_tpu.scenario.__main__ import main\n"
        "rc = main(sys.argv[1:])\n"
        "banned = {m for m in sys.modules if m.split('.')[0] in"
        " ('jax', 'jaxlib')}\n"
        "assert not banned, banned\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, str(mid)],
        capture_output=True, text=True, cwd=str(repo), timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "carry checkpoint v1 (scenario), round 4 of 8" in proc.stdout
    # Resume the child's carry HERE, against this process's compile of
    # the same 8-round spec (events 2/5/6 — the round-9 revive is past
    # R, filtered identically in both processes).
    tail = pipeline_sweep(
        None, None, R, scenario=block, rounds_per_dispatch=2,
        collect_decisions=True, resume=str(mid),
    )
    np.testing.assert_array_equal(tail["decisions"], full["decisions"][4:])
    np.testing.assert_array_equal(tail["leaders"], full["leaders"][4:])
    assert tail["counters"] == full["counters"]


def test_cluster_scenario_checkpoint_every(tmp_path):
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    cluster = Cluster(4, JaxBackend(platform="cpu"), seed=0)
    spec = from_dict(
        {"name": "ck", "rounds": 4, "order": "attack",
         "events": [{"round": 1, "kill": [1]}]}
    )
    path = str(tmp_path / "cluster_{round}.npz")
    counts, res = cluster.run_scenario(
        spec, checkpoint_every=2, checkpoint_path=path
    )
    assert sum(counts.values()) == 4
    assert res["stats"]["checkpoints"] >= 1
    written = sorted(tmp_path.glob("cluster_*.npz"))
    assert written
    from ba_tpu.utils.snapshot import validate_carry_checkpoint

    meta = validate_carry_checkpoint(str(written[-1]))
    assert meta["scenario"] is True and meta["rounds_total"] == 4


# -- runtime wiring -----------------------------------------------------------


def _write_spec(tmp_path, doc):
    path = tmp_path / "spec.json"
    path.write_text(json.dumps(doc))
    return str(path)


def test_repl_scenario_command_mutates_roster(tmp_path):
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    path = _write_spec(
        tmp_path,
        {
            "name": "repl",
            "rounds": 4,
            "order": "attack",
            "events": [
                {"round": 1, "kill": [1]},
                {"round": 2, "set_faulty": [3], "value": True},
                {"round": 3, "kill": [2]},
            ],
        },
    )
    cluster = Cluster(5, JaxBackend(platform="cpu"), seed=0)
    out = []
    assert handle_command(cluster, f"scenario {path}", out.append)
    assert out[0].startswith("Scenario repl: 4 rounds - ")
    assert out[1].startswith("Scenario counters: quorum_failures=")
    assert "ic1_violations=" in out[1]
    # The roster adopted the campaign's final state: G1/G2 dead, G3
    # faulty and (lowest alive id) the leader — election for life.
    assert [g.id for g in cluster.generals] == [3, 4, 5]
    assert cluster.leader_id == 3
    assert cluster.find(3).faulty
    assert cluster._round == 4  # future seeds advance past the campaign
    # The same session's g-state output reflects it (byte format).
    out2 = []
    handle_command(cluster, "g-state", out2.append)
    assert out2[0] == "G3, primary, state=F"


def test_repl_scenario_command_guards(tmp_path):
    from ba_tpu.runtime.backends import JaxBackend, PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    # PyBackend has no scenario support: silently ignored (guarded
    # divergence convention, like unknown ids).
    path = _write_spec(
        tmp_path, {"name": "s", "rounds": 1, "events": []}
    )
    py = Cluster(4, PyBackend(), seed=0)
    out = []
    assert handle_command(py, f"scenario {path}", out.append)
    assert out == []
    # Bad files and specs naming unknown generals print one error line.
    jx = Cluster(4, JaxBackend(platform="cpu"), seed=0)
    out = []
    handle_command(jx, "scenario /definitely/not/there.json", out.append)
    assert len(out) == 1 and out[0].startswith("scenario error:")
    bad = _write_spec(
        tmp_path,
        {"name": "s", "rounds": 1,
         "events": [{"round": 0, "kill": [99]}]},
    )
    out = []
    handle_command(jx, f"scenario {bad}", out.append)
    assert len(out) == 1 and "not in the roster" in out[0]
    assert len(jx.generals) == 4  # roster untouched on error
    # A trailing space (trivial to type interactively) must not read as
    # an empty checkpoint path — the campaign just runs.  (_write_spec
    # reuses one filename; restore the good spec the bad one clobbered.)
    path = _write_spec(tmp_path, {"name": "s", "rounds": 1, "events": []})
    out = []
    assert handle_command(jx, f"scenario {path} ", out.append)
    assert out and out[0].startswith("Scenario s:")
    # An unwritable checkpoint path is one error line mid-campaign, not
    # a dead REPL (checkpoint writes surface OSError, not ValueError).
    out = []
    assert handle_command(
        jx, f"scenario {path} {tmp_path}/no/such/dir/ck.npz 1", out.append
    )
    assert len(out) == 1 and out[0].startswith("scenario error:")
    # Extra tokens refuse loudly (same class as path-without-<every>).
    out = []
    assert handle_command(
        jx, f"scenario {path} ck.npz 1 500", out.append
    )
    assert out == ["scenario error: too many arguments "
                   "(usage: scenario <file> [<ckpt-path> <every>] "
                   "[supervise] [mesh=N] [engine=...])"]
    # mesh=1 (ISSUE 8) routes the B=1 campaign through the sharded scan
    # core and still prints the normal result lines.
    out = []
    assert handle_command(jx, f"scenario {path} mesh=1", out.append)
    assert out and out[0].startswith("Scenario s:")
    # Oversized meshes surface the engine's/make_mesh's clear message as
    # ONE line — the interactive batch is 1, so mesh=8 cannot split it
    # (and mesh=9999 cannot even build on this host).
    for bad_tok in ("mesh=8", "mesh=9999"):
        out = []
        assert handle_command(jx, f"scenario {path} {bad_tok}", out.append)
        assert len(out) == 1 and out[0].startswith("scenario error:")
    out = []
    assert handle_command(jx, f"scenario {path} mesh=zero", out.append)
    assert out == ["scenario error: mesh= wants a device count, "
                   "got 'zero'"]


def test_cluster_scenario_emits_campaign_record(tmp_path):
    from ba_tpu.utils import metrics
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    sink = tmp_path / "metrics.jsonl"
    old = metrics._default
    metrics._default = metrics.MetricsSink(str(sink))
    try:
        cluster = Cluster(4, JaxBackend(platform="cpu"), seed=0)
        spec = from_dict(
            {"name": "obs", "rounds": 3, "order": "attack",
             "events": [{"round": 1, "kill": [1]}]}
        )
        counts, res = cluster.run_scenario(spec)
    finally:
        metrics._default = old
    assert sum(counts.values()) == 3
    records = [json.loads(l) for l in sink.read_text().splitlines()]
    camp = [r for r in records if r["event"] == "scenario_campaign"]
    assert len(camp) == 1
    assert camp[0]["killed"] == [1]
    assert camp[0]["decision_counts"] == counts
    assert camp[0]["counters"] == res["counters"]
    assert camp[0]["leader_id"] == 2
    assert camp[0]["v"] == 1


def test_backend_scenario_unsupported_paths_return_none():
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    spec = from_dict({"name": "s", "rounds": 1, "events": []})
    sm = Cluster(4, JaxBackend(platform="cpu", protocol="sm"), seed=0)
    assert sm.run_scenario(spec) is None
