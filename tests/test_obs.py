"""Observability layer tests (ISSUE 2 tentpole, ba_tpu/obs/).

Contracts pinned here:

1. **Disabled = free**: with BA_TPU_METRICS/BA_TPU_TRACE unset, spans
   record nothing (no buffer growth) and no file is ever written — the
   overhead-guard the hot paths rely on.
2. **Tracer**: spans/instants land in the ring buffer with monotonic
   timestamps, the Chrome export validates against the trace-event
   schema (``ph``, ``ts``, ``dur``, ``pid``, ``tid``), and the ring
   capacity bounds memory.
3. **Registry**: typed counters/gauges/log-bucketed histograms snapshot
   to a versioned ``metrics_snapshot`` JSONL record and dump Prometheus
   text with cumulative buckets.
4. **Thread safety**: sink + tracer survive concurrent emission (the
   pipelined driver's host_work lane vs. the main thread).
5. **Pipeline wiring**: a pipeline_sweep run with instrumentation on
   produces compile/dispatch/retire spans and occupancy/latency
   histograms — and `bench.py --obs DIR` pins the end-to-end acceptance
   artifact pair on the CPU backend.
"""

import json
import math
import os
import subprocess
import sys
import threading

import pytest

from ba_tpu import obs
from ba_tpu.obs.registry import MetricsRegistry
from ba_tpu.obs.trace import Tracer
from ba_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh(monkeypatch, *, trace_enabled):
    """Swap in a fresh default tracer + registry (and return them)."""
    tracer = Tracer(enabled=trace_enabled)
    reg = MetricsRegistry()
    monkeypatch.setattr(obs.trace, "_default", tracer)
    monkeypatch.setattr(obs.registry, "_default", reg)
    return tracer, reg


# -- 1. disabled path ---------------------------------------------------------


def test_disabled_tracer_records_nothing(monkeypatch):
    monkeypatch.delenv("BA_TPU_TRACE", raising=False)
    tracer = Tracer()
    assert not tracer.enabled
    with tracer.span("x", a=1):
        pass
    tracer.instant("y")
    assert len(tracer) == 0


def test_env_zero_disables_tracer(monkeypatch):
    monkeypatch.setenv("BA_TPU_TRACE", "0")
    assert not Tracer().enabled


def test_disabled_obs_zero_writes_and_growth(monkeypatch, tmp_path):
    # The overhead guard: a full pipelined run with every obs env var
    # unset must write no files and grow no span buffer.
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state, pipeline_sweep

    monkeypatch.delenv("BA_TPU_TRACE", raising=False)
    monkeypatch.delenv("BA_TPU_METRICS", raising=False)
    tracer, reg = _fresh(monkeypatch, trace_enabled=False)
    monkeypatch.setattr(metrics, "_default", metrics.MetricsSink())
    monkeypatch.chdir(tmp_path)
    state = make_sweep_state(jr.key(41), 8, 8)
    out = pipeline_sweep(jr.key(42), state, 4, depth=2, host_work=lambda d: None)
    assert out["stats"]["dispatches"] == 4
    assert len(tracer) == 0  # no span-buffer growth
    assert not metrics.default_sink().enabled
    assert list(tmp_path.iterdir()) == []  # zero file writes
    # emit_snapshot with a disabled sink builds the dict but writes nothing.
    rec = reg.emit_snapshot()
    assert rec["event"] == "metrics_snapshot" and rec["v"] == 1
    assert list(tmp_path.iterdir()) == []


# -- 2. tracer ----------------------------------------------------------------


def test_span_records_and_chrome_schema(tmp_path):
    tracer = Tracer(enabled=True)
    with tracer.span("outer", kind="test"):
        with tracer.span("inner"):
            pass
    tracer.instant("marker", gid=3)
    assert len(tracer) == 3
    path = tmp_path / "trace.json"
    tracer.export_chrome(str(path))
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert len(events) == 3
    complete = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert len(complete) == 2 and len(instants) == 1
    for ev in complete:
        # Trace-event schema: name, ph, ts (us), dur (us), pid, tid.
        assert isinstance(ev["ts"], float) and ev["ts"] > 0
        assert isinstance(ev["dur"], float) and ev["dur"] >= 0
        assert ev["pid"] == os.getpid()
        assert isinstance(ev["tid"], int)
        assert ev["name"] in ("outer", "inner")
    # inner nests within outer on the monotonic timeline.
    outer = next(e for e in complete if e["name"] == "outer")
    inner = next(e for e in complete if e["name"] == "inner")
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"kind": "test"}
    assert instants[0]["args"] == {"gid": 3}


def test_ring_buffer_caps_memory():
    tracer = Tracer(capacity=4, enabled=True)
    for i in range(10):
        with tracer.span("s", i=i):
            pass
    assert len(tracer) == 4
    names = [e["args"]["i"] for e in tracer.chrome_events()]
    assert names == [6, 7, 8, 9]  # oldest dropped first


def test_span_survives_exceptions():
    tracer = Tracer(enabled=True)
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    assert len(tracer) == 1  # the span still closed and recorded


# -- 3. registry --------------------------------------------------------------


def test_counter_gauge_histogram_snapshot():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    h = reg.histogram("lat_s")
    for v in (1e-7, 3e-6, 3e-6, 0.5):
        h.record(v)
    snap = reg.snapshot()
    assert snap["c"] == {"type": "counter", "value": 5}
    assert snap["g"] == {"type": "gauge", "value": 2.5}
    hs = snap["lat_s"]
    assert hs["count"] == 4 and hs["min"] == 1e-7 and hs["max"] == 0.5
    assert math.isclose(hs["sum"], 1e-7 + 6e-6 + 0.5)
    assert sum(c for _, c in hs["buckets"]) == 4
    # Log-bucket shape: every value is <= its bucket's upper edge and
    # (except bucket 0) > the previous edge.
    for le, _ in hs["buckets"]:
        assert le > 0


def test_histogram_bucket_edges_and_overflow():
    reg = MetricsRegistry()
    h = reg.histogram("occ", base=1.0, factor=2.0, n_buckets=3)
    for v in (1, 2, 3, 100):  # edges: 1, 2, 4; 100 -> +Inf overflow
        h.record(v)
    snap = h.snapshot()
    buckets = dict((le, c) for le, c in snap["buckets"])
    assert buckets[1.0] == 1
    assert buckets[2.0] == 1
    assert buckets[4.0] == 1
    # The overflow edge serializes as the STRING "+Inf" so the snapshot
    # stays strict JSON (a float('inf') would dump as bare `Infinity`).
    assert buckets["+Inf"] == 1
    json.loads(json.dumps(snap, allow_nan=False))  # strict round-trip


def test_registry_type_clash_raises():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    with pytest.raises(TypeError):
        reg.histogram("x")


def test_prometheus_text_cumulative_buckets():
    reg = MetricsRegistry()
    reg.counter("events_total").inc(3)
    h = reg.histogram("lat_s", base=1e-3, factor=2.0, n_buckets=4)
    h.record(0.0005)
    h.record(0.003)
    text = reg.prometheus_text()
    assert "# TYPE events_total counter\nevents_total 3" in text
    assert "# TYPE lat_s histogram" in text
    assert 'lat_s_bucket{le="0.001"} 1' in text
    assert 'lat_s_bucket{le="0.004"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 2' in text
    assert "lat_s_count 2" in text


def test_emit_snapshot_versioned_record(tmp_path):
    sink = metrics.MetricsSink(str(tmp_path / "m.jsonl"))
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.emit_snapshot(sink=sink, platform="cpu")
    sink.close()
    rec = json.loads((tmp_path / "m.jsonl").read_text())
    assert rec["event"] == "metrics_snapshot"
    assert rec["v"] == 1 and rec["platform"] == "cpu"
    assert rec["metrics"]["c"]["value"] == 1


# -- 4. thread safety ---------------------------------------------------------


def test_sink_and_tracer_thread_safety(tmp_path):
    # The pipelined driver's host_work lane can emit/span concurrently
    # with the main thread: every line must stay intact JSON and every
    # span must be recorded.
    sink = metrics.MetricsSink(str(tmp_path / "t.jsonl"))
    tracer = Tracer(capacity=1 << 16, enabled=True)
    threads, per = 8, 50

    # All workers stay alive together (barrier) so their thread idents
    # are necessarily distinct: on a loaded host, threads that finish
    # before later ones start get their idents RECYCLED, and the
    # tid-identity assertion below would flake on scheduler luck.
    gate = threading.Barrier(threads)

    def work(t):
        gate.wait()
        for i in range(per):
            with tracer.span("w", t=t, i=i):
                sink.emit(  # synthetic sink-mechanics family:
                    {"event": "thread_test", "t": t, "i": i}  # ba-lint: disable=BA601
                )

    ts = [threading.Thread(target=work, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    sink.close()
    lines = (tmp_path / "t.jsonl").read_text().splitlines()
    assert len(lines) == threads * per
    for line in lines:
        rec = json.loads(line)  # interleaved writes would break parsing
        assert rec["event"] == "thread_test" and rec["v"] == 1
    assert len(tracer) == threads * per
    tids = {e["tid"] for e in tracer.chrome_events()}
    assert len(tids) == threads  # each thread's spans keep its identity


# -- 5. pipeline + REPL + bench wiring ---------------------------------------


def test_pipeline_emits_spans_and_histograms(monkeypatch):
    import jax.random as jr

    from ba_tpu.parallel import make_sweep_state, pipeline_sweep

    tracer, reg = _fresh(monkeypatch, trace_enabled=True)
    obs.reset_first_calls()  # force the first dispatch to classify as compile
    state = make_sweep_state(jr.key(43), 12, 8)
    out = pipeline_sweep(
        jr.key(44), state, 6,
        depth=2, rounds_per_dispatch=2, host_work=lambda d: None,
    )
    assert out["stats"]["dispatches"] == 3
    names = [e["name"] for e in tracer.chrome_events()]
    assert names.count("compile") == 1  # one fresh specialization
    assert names.count("dispatch") == 2  # the cached re-dispatches
    assert names.count("retire") == 3
    assert names.count("host_work") == 3
    snap = reg.snapshot()
    assert snap["pipeline_dispatches_total"]["value"] == 3
    assert snap["pipeline_retires_total"]["value"] == 3
    assert snap["pipeline_dispatch_latency_s"]["count"] == 3
    assert snap["pipeline_retire_lag_s"]["count"] == 3
    assert snap["compile_time_s"]["count"] == 1
    occ = snap["pipeline_depth_occupancy"]
    assert occ["count"] == 3 and occ["max"] <= 3  # depth+1 momentary cap


def test_repl_stats_command_additive(monkeypatch):
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    _fresh(monkeypatch, trace_enabled=False)
    cluster = Cluster(3, PyBackend(), seed=5)
    lines = []
    assert handle_command(cluster, "actual-order attack", lines.append)
    before = list(lines)
    assert handle_command(cluster, "stats", lines.append)
    stats_lines = lines[len(before):]
    text = "\n".join(stats_lines)
    assert "# TYPE round_wall_s histogram" in text
    assert "round_wall_s_count 1" in text
    assert "# TYPE elections_total counter" in text  # init elected G1
    # Reference commands' output is untouched by the new command.
    assert before[0].startswith("G1, primary")


def test_cluster_election_failover_counters(monkeypatch):
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster

    tracer, reg = _fresh(monkeypatch, trace_enabled=True)
    cluster = Cluster(4, PyBackend(), seed=9)
    assert cluster.leader_id == 1
    cluster.kill(1)  # leader dies -> failover + re-election
    assert cluster.leader_id == 2
    snap = reg.snapshot()
    assert snap["elections_total"]["value"] == 2  # init + re-election
    assert snap["failover_kills_total"]["value"] == 1
    names = [e["name"] for e in tracer.chrome_events()]
    assert "election" in names and "failover_kill" in names


def test_bench_obs_acceptance_cpu(tmp_path):
    """The ISSUE 2 acceptance pin: ``bench.py --obs DIR`` on the CPU
    backend produces (a) a Chrome trace with compile/dispatch/retire
    spans for a pipeline_sweep run and (b) a metrics_snapshot JSONL
    record with depth-occupancy and dispatch-latency histogram buckets —
    and scripts/obs_report.py renders the pair."""
    obs_dir = tmp_path / "obs"
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "BA_TPU_BENCH_PLATFORM": "cpu",
            "BA_TPU_COMPILE_CACHE": "0",
            "BA_TPU_BENCH_PIPE_BATCH": "8",
            "BA_TPU_BENCH_PIPE_CAP": "8",
            "BA_TPU_BENCH_PIPE_ROUNDS": "8",
            "BA_TPU_BENCH_PIPE_KPD": "2",
            "BA_TPU_BENCH_PIPE_UNROLL": "1",
            "BA_TPU_BENCH_DETAIL": str(tmp_path / "detail.json"),
        }
    )
    p = subprocess.run(
        [sys.executable, "bench.py", "--obs", str(obs_dir),
         "--configs", "pipeline_sweep"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=540,
    )
    assert p.returncode == 0, p.stderr[-2000:]

    # (a) the Chrome trace parses and carries the pipeline's span kinds.
    doc = json.loads((obs_dir / "trace.json").read_text())
    complete = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in complete}
    assert {"compile", "dispatch", "retire"} <= names
    for ev in complete:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)

    # (b) the JSONL stream: every record versioned, snapshot present
    # with depth-occupancy + dispatch-latency buckets populated.
    recs = [
        json.loads(l)
        for l in (obs_dir / "metrics.jsonl").read_text().splitlines()
    ]
    assert recs and all(r["v"] == 1 and "event" in r for r in recs)
    snaps = [r for r in recs if r["event"] == "metrics_snapshot"]
    assert len(snaps) == 1
    m = snaps[0]["metrics"]
    assert m["pipeline_depth_occupancy"]["count"] > 0
    assert m["pipeline_depth_occupancy"]["buckets"]
    assert m["pipeline_dispatch_latency_s"]["count"] > 0
    assert m["pipeline_dispatch_latency_s"]["buckets"]
    assert m["compile_time_s"]["count"] > 0

    # (b2) ISSUE 4 acceptance: the device tier rode along — at least
    # one compiled_artifact record for the megastep with nonzero
    # flops/bytes and alias bytes proving the donate_argnums contract.
    arts = [r for r in recs if r["event"] == "compiled_artifact"]
    mega = [a for a in arts if a["fn"] == "pipeline_megastep"]
    assert mega, arts
    assert all(a["flops"] > 0 and a["bytes_accessed"] > 0 for a in mega)
    assert all(a["alias_bytes"] > 0 and a["donation_aliased"] for a in mega)
    # ... and the config artifact surfaces the same numbers.
    detail = json.loads((tmp_path / "detail.json").read_text())
    xla_cost = detail["configs"]["pipeline_sweep"]["xla_cost"]
    assert xla_cost["flops"] > 0 and xla_cost["alias_bytes"] > 0

    # Prometheus text exposition rides along.
    prom = (obs_dir / "metrics.prom").read_text()
    assert "# TYPE pipeline_dispatch_latency_s histogram" in prom

    # The report renderer digests the pair without ba_tpu on its path.
    r = subprocess.run(
        [sys.executable, "scripts/obs_report.py", str(obs_dir)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "dispatch" in r.stdout and "pipeline_dispatch_latency_s" in r.stdout
    # The device section renders the artifact + donation verification.
    assert "compiled artifacts (device tier)" in r.stdout
    assert "donation held" in r.stdout
