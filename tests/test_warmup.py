"""Warm-serving stack tests (ISSUE 11): the persistent executable cache
(``obs/aotcache.py``), the background warmup pass
(``runtime/warmup.py``), and the dispatcher integration.

The contract under test, layer by layer:

- warm-vs-cold BIT-EXACTNESS: a dispatch served from a precompiled
  (or deserialized) executable is bit-identical to the jit path —
  through the engine directly and through the service;
- every degradation path reaches a fresh compile: cold miss (counted),
  signature/version mismatch (eager invalidation, never a stale load),
  corrupt entry (``.corrupt`` quarantine, the snapshot.py discipline);
- the warmup thread is background + health-gated: it never sheds or
  delays live traffic, and a gate reading pressure pauses it;
- the new host-tier modules import jax-free (the BA301 contract,
  runtime-proven).
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from ba_tpu import obs  # noqa: E402
from ba_tpu.core.state import SimState  # noqa: E402
from ba_tpu.core.types import COMMAND_DTYPE  # noqa: E402
from ba_tpu.obs import aotcache  # noqa: E402
from ba_tpu.obs.registry import MetricsRegistry  # noqa: E402
from ba_tpu.parallel.pipeline import (  # noqa: E402
    AOT_SPECS,
    coalesced_sweep,
    fresh_copy,
    pipeline_sweep,
)
from ba_tpu.runtime import warmup  # noqa: E402
from ba_tpu.runtime.serve import (  # noqa: E402
    AgreementRequest,
    AgreementService,
    ServeConfig,
)

B, CAP, ROUNDS, RPD = 2, 4, 8, 4

COALESCED_AXES = {
    "batch": B, "capacity": CAP, "rounds": RPD, "m": 1,
    "max_liars": None, "unroll": 1, "scenario": False,
    # ISSUE 14: the protocol axes joined the coalesced signature —
    # signed/oral cohorts never share an executable, and a protocol
    # flip is an explained recompile.
    "signed": False, "collapsed": False,
    # ISSUE 13: the engine joined the compile signature — warm lookups
    # without it can never match the dispatch loop's axes.
    "engine": "xla",
}


def mkstate(batch=B, cap=CAP):
    faulty = np.zeros((batch, cap), np.bool_)
    alive = np.ones((batch, cap), np.bool_)
    faulty[0, 2] = True
    return fresh_copy(
        SimState(
            order=jnp.asarray(
                (np.arange(batch) % 2).astype(COMMAND_DTYPE)
            ),
            leader=jnp.zeros((batch,), jnp.int32),
            faulty=jnp.asarray(faulty),
            alive=jnp.asarray(alive),
            ids=jnp.asarray(
                np.tile(np.arange(1, cap + 1, dtype=np.int32), (batch, 1))
            ),
        )
    )


def slot_keys(batch=B):
    return [jr.key(100 + i) for i in range(batch)]


@pytest.fixture(scope="module")
def warm_dir(tmp_path_factory):
    """One ensured coalesced entry, shared by the read-path tests (a
    fresh AOT compile per test would dominate the suite's budget)."""
    d = str(tmp_path_factory.mktemp("aot"))
    cache = aotcache.ExecutableCache(d)
    info = cache.ensure(
        "coalesced_megastep", COALESCED_AXES,
        AOT_SPECS["coalesced_megastep"],
    )
    assert info["status"] == "compiled"
    # Donation-alias evidence harvested at compile time (the loaded
    # executable's own memory stats are empty — the documented trap).
    assert info["alias_bytes"] > 0
    return d


# -- bit-exactness through the engine ----------------------------------------


def test_warm_vs_cold_bit_exact(warm_dir):
    ref = coalesced_sweep(
        slot_keys(), mkstate(), ROUNDS, rounds_per_dispatch=RPD
    )
    cache = aotcache.ExecutableCache(warm_dir)
    warm = coalesced_sweep(
        slot_keys(), mkstate(), ROUNDS, rounds_per_dispatch=RPD,
        executables=cache,
    )
    np.testing.assert_array_equal(warm["decisions"], ref["decisions"])
    np.testing.assert_array_equal(warm["majorities"], ref["majorities"])
    np.testing.assert_array_equal(warm["counters"], ref["counters"])
    assert warm["stats"]["warm_dispatches"] == warm["stats"]["dispatches"]
    assert warm["stats"]["request_path_compiles"] == 0
    # The entry came off DISK in this cache instance — the persistence
    # leg of the bit-exactness pin, not just the in-process memo.
    assert cache.counts["loads"] == 1


def test_cold_miss_falls_back_and_counts(tmp_path):
    cache = aotcache.ExecutableCache(str(tmp_path / "empty"))
    obs.reset_first_calls()
    ref = coalesced_sweep(
        slot_keys(), mkstate(), ROUNDS, rounds_per_dispatch=RPD
    )
    obs.reset_first_calls()
    out = coalesced_sweep(
        slot_keys(), mkstate(), ROUNDS, rounds_per_dispatch=RPD,
        executables=cache,
    )
    # Served correctly through the jit fallback...
    np.testing.assert_array_equal(out["decisions"], ref["decisions"])
    np.testing.assert_array_equal(out["counters"], ref["counters"])
    # ...and the misses/compiles are COUNTED, not silent.
    assert out["stats"]["warm_dispatches"] == 0
    assert out["stats"]["request_path_compiles"] >= 1
    assert cache.counts["misses"] >= 1


def test_signature_mismatch_invalidates_and_recompiles(tmp_path):
    d = str(tmp_path)
    cache = aotcache.ExecutableCache(d)
    cache.ensure(
        "coalesced_megastep", COALESCED_AXES,
        AOT_SPECS["coalesced_megastep"],
    )
    path = aotcache.entry_path(d, "coalesced_megastep", COALESCED_AXES)
    # Tamper the stored jaxlib version — the stale-toolchain scenario.
    with open(path, "rb") as fh:
        data = fh.read()
    off = len(aotcache._MAGIC)
    (hlen,) = struct.unpack(">I", data[off:off + 4])
    header = json.loads(data[off + 4:off + 4 + hlen])
    header["signature"]["jaxlib_version"] = "0.0.0-stale"
    new_head = json.dumps(header, sort_keys=True, default=str).encode()
    with open(path, "wb") as fh:
        fh.write(aotcache._MAGIC)
        fh.write(struct.pack(">I", len(new_head)))
        fh.write(new_head)
        fh.write(data[off + 4 + hlen:])
    fresh = aotcache.ExecutableCache(d)
    # Eager invalidation: never loaded, stale entry removed.
    assert fresh.get("coalesced_megastep", COALESCED_AXES) is None
    assert fresh.counts["invalidated"] == 1
    assert not os.path.exists(path)
    # The fallback is a fresh compile that re-persists the entry.
    info = fresh.ensure(
        "coalesced_megastep", COALESCED_AXES,
        AOT_SPECS["coalesced_megastep"],
    )
    assert info["status"] == "compiled"
    assert os.path.exists(path)


def test_corrupt_entry_quarantines_and_recompiles(tmp_path):
    d = str(tmp_path)
    cache = aotcache.ExecutableCache(d)
    cache.ensure(
        "coalesced_megastep", COALESCED_AXES,
        AOT_SPECS["coalesced_megastep"],
    )
    path = aotcache.entry_path(d, "coalesced_megastep", COALESCED_AXES)
    with open(path, "rb") as fh:
        data = fh.read()
    with open(path, "wb") as fh:
        # Valid magic + header, garbled payload: the deserialize (not
        # the parse) is what must fail safely.
        fh.write(data[: len(data) // 2])
        fh.write(b"\x00garbage\x00" * 16)
    fresh = aotcache.ExecutableCache(d)
    assert fresh.get("coalesced_megastep", COALESCED_AXES) is None
    assert fresh.counts["corrupt"] == 1
    # The snapshot.py discipline: bytes kept for post-mortem at
    # <entry>.corrupt, the family never trips on them twice.
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    info = fresh.ensure(
        "coalesced_megastep", COALESCED_AXES,
        AOT_SPECS["coalesced_megastep"],
    )
    assert info["status"] == "compiled"
    assert (
        fresh.get("coalesced_megastep", COALESCED_AXES) is not None
    )


def test_call_time_failure_evicts_and_falls_back(warm_dir, tmp_path):
    # An entry that LOADS but cannot RUN (stale-structure drift the
    # load-time ladder cannot see) must cost one fallback, never a
    # bricked signature: evicted from the memo, disk bytes quarantined,
    # the jit path serves, and the event counts as a request-path
    # compile rather than a warm dispatch.
    import shutil

    d = str(tmp_path)
    src = aotcache.entry_path(warm_dir, "coalesced_megastep", COALESCED_AXES)
    dst = aotcache.entry_path(d, "coalesced_megastep", COALESCED_AXES)
    os.makedirs(d, exist_ok=True)
    shutil.copy(src, dst)
    cache = aotcache.ExecutableCache(d)

    calls = {"n": 0}

    def broken(*args, **kwargs):
        calls["n"] += 1
        raise RuntimeError("stale executable")

    cache._mem[cache._key("coalesced_megastep", COALESCED_AXES)] = broken
    ref = coalesced_sweep(
        slot_keys(), mkstate(), RPD, rounds_per_dispatch=RPD
    )
    out = coalesced_sweep(
        slot_keys(), mkstate(), RPD, rounds_per_dispatch=RPD,
        executables=cache,
    )
    np.testing.assert_array_equal(out["decisions"], ref["decisions"])
    np.testing.assert_array_equal(out["counters"], ref["counters"])
    assert calls["n"] == 1
    assert out["stats"]["warm_dispatches"] == 0
    assert out["stats"]["request_path_compiles"] == 1
    assert cache.counts["evicted"] == 1
    assert os.path.exists(dst + ".corrupt") and not os.path.exists(dst)
    # The signature is negative-marked: the next dispatch goes straight
    # to the jit path without re-probing the quarantined entry.
    out2 = coalesced_sweep(
        slot_keys(), mkstate(), RPD, rounds_per_dispatch=RPD,
        executables=cache,
    )
    np.testing.assert_array_equal(out2["decisions"], ref["decisions"])
    assert out2["stats"]["warm_dispatches"] == 0


def test_aot_warm_does_not_mask_jit_cold_accounting(tmp_path):
    # ensure() stores a LEDGER row but must NOT mark the jit first-call
    # classifier: an AOT compile never populates jit's cache, so a
    # later cache-LESS dispatch of the same signature pays a real
    # compile — and it must still COUNT as one.
    axes = dict(COALESCED_AXES, batch=1)
    cache = aotcache.ExecutableCache(str(tmp_path))
    obs.reset_first_calls()
    cache.ensure(
        "coalesced_megastep", axes, AOT_SPECS["coalesced_megastep"]
    )
    out = coalesced_sweep(
        slot_keys(1), mkstate(1), RPD, rounds_per_dispatch=RPD
    )
    assert out["stats"]["request_path_compiles"] == 1


def test_pipeline_sweep_warm_opt_in(tmp_path):
    axes = {
        "batch": B, "capacity": CAP, "rounds": RPD, "m": 1,
        "max_liars": None, "unroll": 1, "collect_decisions": True,
        "counters": True, "data": 1, "scenario": False,
        "signed": False, "engine": "xla",
    }
    cache = aotcache.ExecutableCache(str(tmp_path))
    cache.ensure("pipeline_megastep", axes, AOT_SPECS["pipeline_megastep"])
    ref = pipeline_sweep(
        jr.key(5), mkstate(), ROUNDS, rounds_per_dispatch=RPD,
        collect_decisions=True, with_counters=True,
    )
    warm = pipeline_sweep(
        jr.key(5), mkstate(), ROUNDS, rounds_per_dispatch=RPD,
        collect_decisions=True, with_counters=True, executables=cache,
    )
    np.testing.assert_array_equal(warm["decisions"], ref["decisions"])
    np.testing.assert_array_equal(warm["histograms"], ref["histograms"])
    assert warm["counters"] == ref["counters"]
    assert warm["stats"]["warm_dispatches"] == warm["stats"]["dispatches"]


# -- the warmup runner --------------------------------------------------------


def test_bucket_lattice_covers_cohort_space():
    plan = warmup.bucket_lattice(8, 8, capacities=(4,), rounds=20)
    axes = [a for fn, a in plan]
    assert all(fn == "coalesced_megastep" for fn, _ in plan)
    assert {a["batch"] for a in axes} == {1, 2, 4, 8}
    # Windows: the steady-state dispatch plus the ragged remainder
    # (20 % 8 == 4) — the exact chunking coalesced_sweep performs.
    assert {a["rounds"] for a in axes} == {4, 8}
    # Dedup + determinism: same config, same plan.
    assert plan == warmup.bucket_lattice(8, 8, capacities=(4,), rounds=20)
    with pytest.raises(ValueError):
        warmup.bucket_lattice(0, 8)
    with pytest.raises(ValueError):
        warmup.builder_for("not_a_megastep")


def test_ledger_replay_set_filters_toolchain(tmp_path):
    from ba_tpu.obs import instrument

    ledger = str(tmp_path / "ledger.json")
    env = {"jax_version": jax.__version__, "jaxlib_version": "test-jl"}
    try:
        instrument.configure_compile_ledger(ledger, env_axes=env)
        obs.reset_first_calls()
        instrument.classify_compile(
            "coalesced_megastep", dict(COALESCED_AXES)
        )
        # A row from a DIFFERENT toolchain, written straight into the
        # file the way a previous process would have left it.
        doc = json.load(open(ledger))
        doc["fns"]["coalesced_megastep"].append(
            {**COALESCED_AXES, "batch": 64,
             "jax_version": "0.0.0", "jaxlib_version": "other"}
        )
        doc["fns"]["not_a_megastep"] = [
            {**env, "batch": 1}
        ]
        json.dump(doc, open(ledger, "w"))
        instrument.configure_compile_ledger(ledger, env_axes=env)
        replay = warmup.ledger_replay_set()
        # Exactly the reproducible row of a known fn survives, with the
        # env axes (and run_id rider) stripped back off.
        assert replay == [("coalesced_megastep", dict(COALESCED_AXES))]
    finally:
        instrument.configure_compile_ledger(None)
        obs.reset_first_calls()


def test_warmup_gate_pauses_until_healthy(warm_dir):
    cache = aotcache.ExecutableCache(warm_dir)
    healthy = {"v": False}
    runner = warmup.WarmupRunner(
        cache,
        [("coalesced_megastep", dict(COALESCED_AXES))],
        gate=lambda: healthy["v"],
        registry=MetricsRegistry(),
        pause_s=0.01,
    )
    runner.start()
    # The gate reads pressure: the runner must PAUSE, not proceed.
    assert not runner.wait(0.3)
    assert runner.warmed == 0
    healthy["v"] = True
    assert runner.wait(60.0)
    assert runner.progress()["warmed"] == 1
    assert runner.progress()["pending"] == 0


def test_warmup_runner_counts_errors_and_finishes(tmp_path):
    cache = aotcache.ExecutableCache(str(tmp_path))
    runner = warmup.WarmupRunner(
        cache,
        # A signature no builder can lower (capacity 0 state) — the
        # runner must count it and keep going, never raise.
        [("pipeline_megastep", {"batch": 1, "capacity": 4, "rounds": 2,
                                "m": 1, "max_liars": None, "unroll": 1,
                                "collect_decisions": False,
                                "counters": False, "data": 8,
                                "scenario": False})],
        registry=MetricsRegistry(),
    )
    runner.start()
    assert runner.wait(60.0)
    assert runner.progress()["errors"] == 1
    assert runner.progress()["warmed"] == 0


# -- the warm service ---------------------------------------------------------


def _alone(req):
    cap = 4
    faulty = np.zeros((1, cap), np.bool_)
    alive = np.zeros((1, cap), np.bool_)
    alive[0, : req.n] = True
    for i in req.faulty:
        faulty[0, i] = True
    state = fresh_copy(
        SimState(
            order=jnp.full(
                (1,), 1 if req.order == "attack" else 0, COMMAND_DTYPE
            ),
            leader=jnp.zeros((1,), jnp.int32),
            faulty=jnp.asarray(faulty),
            alive=jnp.asarray(alive),
            ids=jnp.asarray(np.arange(1, cap + 1, dtype=np.int32)[None, :]),
        )
    )
    return coalesced_sweep(
        [jr.key(req.seed)], state, req.rounds, rounds_per_dispatch=RPD
    )


def test_service_warm_zero_request_path_compiles(warm_dir):
    obs.reset_first_calls()
    svc = AgreementService(
        ServeConfig(
            max_batch=2, max_queue=8, coalesce_window_s=0.002,
            rounds_per_dispatch=RPD, warm=True, warm_rounds=ROUNDS,
            aot_cache=warm_dir,
        ),
        registry=MetricsRegistry(),
    )
    svc.open()
    assert svc.warm_barrier(timeout=300)
    svc.start()
    reqs = [
        AgreementRequest(kind="run-rounds", n=4, seed=41, rounds=ROUNDS),
        AgreementRequest(
            kind="run-rounds", n=4, faulty=(2,), seed=43, rounds=ROUNDS
        ),
    ]
    tickets = [svc.submit(r) for r in reqs]
    outs = [t.result(timeout=300) for t in tickets]
    # Scenario cohorts are first-class warm traffic too (the default
    # lattice covers scenario=True): a post-barrier scenario request
    # must also dispatch without a request-path compile.
    from ba_tpu.scenario import from_dict

    spec = from_dict(
        {"name": "warmtest", "rounds": ROUNDS,
         "events": [{"round": 2, "kill": [3]}]}
    )
    scn = svc.submit(
        AgreementRequest(kind="scenario", n=4, seed=49, spec=spec)
    ).result(timeout=300)
    stats = svc.stats()
    svc.stop()
    # Warm-vs-cold bit-exactness through the SERVICE.
    for req, out in zip(reqs, outs):
        ref = _alone(req)
        assert out["decisions"] == [int(v) for v in ref["decisions"][:, 0]]
        assert out["counters"] == {
            n: int(v)
            for n, v in zip(ref["counter_names"], ref["counters"][0])
        }
    assert len(scn["decisions"]) == ROUNDS and "leaders" in scn
    # The acceptance boolean, measured: a warm service never compiled
    # on the request path — interactive OR scenario.
    assert stats["compiles_on_request_path"] == 0
    assert stats["warmup_done"] and stats["warmup_errors"] == 0
    assert stats["warmup_warmed"] == stats["warmup_planned"]


def test_service_unwarmed_window_counts_miss(warm_dir):
    # rounds=6 dispatches as windows 4+2; window 2 is NOT in the warm
    # plan — the cohort must still serve (compile-on-miss) and the miss
    # must be counted.
    obs.reset_first_calls()
    svc = AgreementService(
        ServeConfig(
            max_batch=2, max_queue=8, coalesce_window_s=0.002,
            rounds_per_dispatch=RPD, warm=True, warm_rounds=ROUNDS,
            aot_cache=warm_dir,
        ),
        registry=MetricsRegistry(),
    )
    svc.open()
    assert svc.warm_barrier(timeout=300)
    svc.start()
    req = AgreementRequest(kind="run-rounds", n=4, seed=47, rounds=6)
    out = svc.submit(req).result(timeout=300)
    stats = svc.stats()
    svc.stop()
    ref = _alone(req)
    assert out["decisions"] == [int(v) for v in ref["decisions"][:, 0]]
    assert stats["compiles_on_request_path"] >= 1
    assert stats["warmup_misses"] >= 1


def test_warmup_never_sheds_live_traffic(tmp_path):
    # A FRESH cache dir: the warmup thread pays real AOT compiles while
    # live traffic flows.  The pin: no request is shed, the tier never
    # leaves 0, and every result stays bit-exact.
    reg = MetricsRegistry()
    svc = AgreementService(
        ServeConfig(
            max_batch=2, max_queue=8, coalesce_window_s=0.002,
            rounds_per_dispatch=RPD, warm=True, warm_rounds=ROUNDS,
            aot_cache=str(tmp_path),
        ),
        registry=reg,
    )
    svc.start()  # warmup launches with the dispatcher already live
    tiers = []
    outs = []
    reqs = []
    for i in range(6):
        req = AgreementRequest(
            kind="run-rounds", n=4, seed=60 + i, rounds=ROUNDS
        )
        reqs.append(req)
        outs.append(svc.submit(req).result(timeout=300))
        tiers.append(svc.stats()["tier"])
    assert svc.warm_barrier(timeout=300)
    stats = svc.stats()
    svc.stop()
    assert stats["rejected"] == 0 and stats["failed"] == 0
    assert tiers == [0] * len(tiers)
    assert reg.get("serve_shed_tier").value == 0
    for req, out in zip(reqs, outs):
        ref = _alone(req)
        assert out["decisions"] == [int(v) for v in ref["decisions"][:, 0]]


# -- host-tier / REPL ---------------------------------------------------------


def test_warmup_and_aotcache_import_jax_free():
    # The BA301 host-tier contract, runtime-proven (the lint direction
    # is mutation-checked in ci.sh): importing the warmup pass and the
    # executable cache must not pull jax — plan construction runs on
    # hosts without it.
    code = (
        "import sys; import ba_tpu.runtime.warmup; "
        "import ba_tpu.obs.aotcache; "
        "assert 'jax' not in sys.modules, 'warm stack import pulled jax'; "
        "print('ok')"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True
    )
    assert out.returncode == 0, out.stderr


def test_repl_serve_start_warm(monkeypatch, warm_dir):
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command
    from ba_tpu.runtime.backends import PyBackend

    monkeypatch.setenv("BA_TPU_AOT_CACHE", warm_dir)
    cluster = Cluster(4, PyBackend(), seed=0)
    lines: list = []
    out = lines.append
    # batch=1 keeps the warmup plan at two signatures (one bucket x one
    # window x scenario {off, on}) — the command surface is under test,
    # not warmup breadth.
    assert handle_command(cluster, "serve start warm=1 batch=1", out)
    assert lines and lines[-1].startswith("serve: started") \
        and "warm" in lines[-1]
    svc = cluster._serve_service
    assert svc.warm_barrier(timeout=300)
    lines.clear()
    assert handle_command(cluster, "serve stat", out)
    stat = "\n".join(lines)
    assert "serve_warmup_planned" in stat
    assert "serve_warmup_pending 0" in stat
    lines.clear()
    assert handle_command(cluster, "serve warm=nonsense", out)
    assert lines[-1].startswith("serve error:")
    lines.clear()
    assert handle_command(cluster, "serve start warm=oops", out)
    assert lines[-1].startswith("serve error: already running") or (
        "wants a int" in lines[-1]
    )
    lines.clear()
    assert handle_command(cluster, "serve stop", out)
    assert lines[-1].startswith("serve: stopped")
