"""Election-by-lowest-id as a masked argmin (ba.py:126-157)."""

import jax.numpy as jnp
import numpy as np

from ba_tpu.core import elect_lowest_id


def test_lowest_alive_wins():
    ids = jnp.asarray([[1, 2, 3, 4], [1, 2, 3, 4]], jnp.int32)
    alive = jnp.asarray([[True, True, True, True], [False, True, True, True]])
    leader = np.asarray(elect_lowest_id(ids, alive))
    assert leader.tolist() == [0, 1]


def test_reelection_after_kills():
    # Kill G1 then G2: leadership passes 0 -> 1 -> 2, deterministically —
    # the convergence argument of SURVEY.md section 4.3.
    ids = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    for killed, expect in [([0], 1), ([0, 1], 2), ([0, 1, 2], 3)]:
        alive = jnp.ones((1, 4), bool).at[0, jnp.asarray(killed)].set(False)
        assert int(elect_lowest_id(ids, alive)[0]) == expect


def test_unordered_ids():
    # Ids need not be sorted by index (elastic g-add keeps them ascending in
    # the reference, ba.py:344-351, but the core must not rely on that).
    ids = jnp.asarray([[7, 3, 9, 5]], jnp.int32)
    alive = jnp.ones((1, 4), bool)
    assert int(elect_lowest_id(ids, alive)[0]) == 1
