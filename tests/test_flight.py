"""Flight recorder, live health view and perf sentinel tests (ISSUE 9:
obs/flight.py, obs/health.py, scripts/bench_sentinel.py, and the
run-id threading through the engine/supervisor/sink/checkpoints).

The contracts, each pinned independently:

1. **Run-id threading** — ``BA_TPU_RUN_ID`` pins, derivation is
   deterministic, scopes nest with one owner, checkpoints carry the id
   and resumes adopt it, and every JSONL record emitted inside a scope
   is stamped.
2. **Zero added sync** — the no-blocking dispatch-count proof re-runs
   with the flight recorder AND the health sampler live, on an
   8-device forced-host mesh, under full supervision, with
   ``jax.block_until_ready`` monkeypatched to raise (the ISSUE 9
   acceptance schedule proof).
3. **Crash-consistent flight logs** — a recorded campaign SIGKILLed
   mid-retire (subprocess, real signal) auto-resumes in a successor,
   and the assembled timeline is contiguous across the process
   boundary with ONE recovery edge, no duplicated dispatch windows,
   and every checkpoint/recovery event exactly once under one run_id.
4. **Sentinel flips** — green against the committed baselines, red on
   a synthetically >=2x-degraded artifact for an existing config
   (jax-free subprocess).
"""

import dataclasses
import json
import os
import pathlib
import signal
import subprocess
import sys

import jax
import jax.random as jr
import pytest

from ba_tpu import obs
from ba_tpu.obs import flight, health
from ba_tpu.parallel import make_mesh, make_sweep_state, pipeline_sweep
from ba_tpu.parallel.pipeline import (
    fresh_copy as _fresh,
    load_carry_checkpoint,
)
from ba_tpu.runtime.backends import PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.runtime.repl import handle_command
from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
from ba_tpu.scenario import compile_scenario, from_dict
from ba_tpu.utils import metrics

REPO = pathlib.Path(__file__).resolve().parent.parent


def _campaign(R=12, B=16, cap=8):
    key = jr.key(91)
    state = make_sweep_state(jr.key(90), B, cap, order=1)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    spec = from_dict(
        {
            "name": "flight-campaign",
            "rounds": R,
            "order": "attack",
            "events": [
                e
                for e in [
                    {"round": 2, "kill": [1]},
                    {"round": 5, "set_faulty": [3], "value": True},
                    {"round": 9, "revive": [1]},
                ]
                if e["round"] < R
            ],
        }
    )
    return key, state, compile_scenario(spec, B, cap, sparse=True)


@pytest.fixture
def sink_path(tmp_path):
    """Route the process-wide JSONL sink to a temp file for one test,
    restoring the (disabled-in-tests) default afterwards."""
    path = tmp_path / "metrics.jsonl"
    metrics.configure(str(path))
    try:
        yield path
    finally:
        metrics.configure(None)
        metrics.set_run_id(None)


def _records(path):
    return [json.loads(l) for l in open(path) if l.strip()]


# -- run-id derivation + scoping ----------------------------------------------


def test_run_id_env_pins_and_validates(monkeypatch):
    monkeypatch.setenv("BA_TPU_RUN_ID", "drill-42")
    assert flight.resolve_run_id("anything") == "drill-42"
    monkeypatch.setenv("BA_TPU_RUN_ID", "bad id with spaces")
    with pytest.raises(ValueError, match="BA_TPU_RUN_ID"):
        flight.resolve_run_id("anything")


def test_derive_run_id_deterministic():
    a = flight.derive_run_id(b"key", 64, "scenario")
    assert a == flight.derive_run_id(b"key", 64, "scenario")
    assert a != flight.derive_run_id(b"key", 65, "scenario")
    assert flight.valid_run_id(a) and a.startswith("run-")
    # Material boundaries matter: ("ab", "c") != ("a", "bc").
    assert flight.derive_run_id("ab", "c") != flight.derive_run_id("a", "bc")


def test_run_scope_nests_with_one_owner():
    with flight.run_scope("outer-1") as outer:
        assert outer.owner and outer.run_id == "outer-1"
        assert metrics.active_run_id() == "outer-1"
        with flight.run_scope("inner-2") as inner:
            # The outer id wins; the inner scope is not the owner.
            assert not inner.owner and inner.run_id == "outer-1"
            assert metrics.active_run_id() == "outer-1"
        assert metrics.active_run_id() == "outer-1"
    assert metrics.active_run_id() is None
    # Exception-safe restore.
    with pytest.raises(RuntimeError):
        with flight.run_scope("boom"):
            raise RuntimeError("x")
    assert metrics.active_run_id() is None


# -- engine recording ---------------------------------------------------------


def test_engine_records_one_correlated_run(sink_path, tmp_path):
    R = 8
    key, state, block = _campaign(R)
    ck = tmp_path / "fl_{round}.npz"
    out = pipeline_sweep(
        key, state, R, scenario=block, rounds_per_dispatch=2,
        checkpoint_every=4, checkpoint_path=str(ck), health_every=1,
    )
    rid = out["stats"]["run_id"]
    assert flight.valid_run_id(rid)
    metrics.default_sink().close()
    recs = _records(sink_path)
    # Every record of the run carries the one id.
    assert {r.get("run_id") for r in recs} == {rid}
    spans = [r for r in recs if r["event"] == "flight_span"]
    assert [(s["lo"], s["hi"]) for s in spans] == [
        (lo, lo + 2) for lo in range(0, R, 2)
    ]
    assert sum(r["event"] == "health_snapshot" for r in recs) == R // 2
    assert out["stats"]["health_samples"] == R // 2
    # The checkpoint header carries the id; a resume adopts it.
    ckpt = load_carry_checkpoint(str(tmp_path / "fl_4.npz"))
    assert ckpt.run_id == rid
    resumed = pipeline_sweep(
        None, None, R, scenario=block, resume=str(tmp_path / "fl_4.npz"),
        rounds_per_dispatch=2,
    )
    assert resumed["stats"]["run_id"] == rid
    # The owner appended one assembled summary per run (initial +
    # resumed), both contiguous under the same id.
    metrics.default_sink().close()
    summaries = [
        r for r in _records(sink_path) if r["event"] == "flight_summary"
    ]
    assert len(summaries) == 2
    assert all(s["run_id"] == rid for s in summaries)
    final = summaries[-1]
    assert final["contiguous"] and final["rounds"] == [0, R]
    assert [c["round"] for c in final["checkpoints"]] == [4, 8]
    assert final["shard_layout"] == {"data": 1}


def test_supervised_mesh_no_blocking_with_recorder_and_sampler(
    eight_devices, monkeypatch, sink_path, tmp_path
):
    # THE ISSUE 9 schedule acceptance: recorder + sampler live, on an
    # 8-device forced-host mesh, under full supervision — and the
    # engine's only sync stays the depth-delayed retire fetch.
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    R, depth = 8, 3
    key, state, block = _campaign(R)
    mesh = make_mesh((8, 1), ("data", "node"))
    events = []
    out = supervised_sweep(
        key, state, scenario=block, mesh=mesh,
        depth=depth, rounds_per_dispatch=1, health_every=2,
        checkpoint_every=4,
        checkpoint_path=str(tmp_path / "mesh_{round}.npz"),
        config=SupervisorConfig(timeout_s=60.0),
        on_event=lambda kind, i: events.append((kind, i)),
    )
    dispatches = [i for kind, i in events if kind == "dispatch"]
    retires = [i for kind, i in events if kind == "retire"]
    assert dispatches == list(range(R))
    assert retires == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [
        ("dispatch", i) for i in range(depth + 1)
    ]
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["health_samples"] == R // 2
    rid = out["supervisor"]["run_id"]
    metrics.default_sink().close()
    recs = _records(sink_path)
    assert {r.get("run_id") for r in recs} == {rid}
    summary = [r for r in recs if r["event"] == "flight_summary"][-1]
    assert summary["contiguous"] and summary["rounds"] == [0, R]
    assert summary["shard_layout"] == {"data": 8, "node": 1}
    healths = [r for r in recs if r["event"] == "health_snapshot"]
    assert healths and healths[-1]["shards"] == 8
    # Watchdog margin is live: timeout was pinned at 60 s.
    assert 0 < healths[-1]["watchdog_margin_s"] < 60.0
    # Imbalance gauges are MEASURED per-device shares, live: an even
    # 16/8 split reads 1.0 on both the carry and the staged planes.
    assert healths[-1]["carry_imbalance"] == pytest.approx(1.0)
    assert healths[-1]["plane_imbalance"] == pytest.approx(1.0)
    assert healths[-1]["plane_bytes_per_shard"] > 0


def test_kill_mid_retire_then_resume_assembles_contiguous_flight(tmp_path):
    # ISSUE 9 satellite: SIGKILL a RECORDED campaign mid-retire (real
    # signal, subprocess), auto-resume the same call, and the assembled
    # flight log is contiguous across the process boundary — one
    # recovery edge, no duplicated dispatch windows, every checkpoint
    # exactly once, one run_id.
    R = 12
    jsonl = tmp_path / "flight.jsonl"
    ck = tmp_path / "kill_{round}.npz"
    child = f'''
import dataclasses, jax.random as jr
from ba_tpu.parallel import make_sweep_state
from ba_tpu.runtime import chaos
from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep
from ba_tpu.scenario import compile_scenario, from_dict

key = jr.key(91)
state = make_sweep_state(jr.key(90), 16, 8, order=1)
state = dataclasses.replace(
    state, faulty=state.faulty.at[:8, 0].set(True)
)
spec = from_dict({{
    "name": "flight-campaign", "rounds": {R}, "order": "attack",
    "events": [
        {{"round": 2, "kill": [1]}},
        {{"round": 5, "set_faulty": [3], "value": True}},
        {{"round": 9, "revive": [1]}},
    ],
}})
block = compile_scenario(spec, 16, 8, sparse=True)
plan = chaos.from_dict({{
    "name": "mid-retire-kill",
    "faults": [{{"round": 10, "kind": "kill", "phase": "retire"}}],
}})
supervised_sweep(
    key, state, scenario=block, rounds_per_dispatch=2,
    checkpoint_every=4, checkpoint_path={str(ck)!r},
    health_every=2, chaos=plan,
    config=SupervisorConfig(timeout_s=60.0),
)
raise SystemExit("unreachable: the kill fault must have fired")
'''
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", BA_TPU_METRICS=str(jsonl),
        BA_TPU_COMPILE_LEDGER="0",
    )
    proc = subprocess.run(
        [sys.executable, "-c", child],
        capture_output=True, text=True, cwd=str(REPO), timeout=600, env=env,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout + proc.stderr
    # The successor: the SAME call (fingerprint-derived run id — no
    # env, no handshake), recording into the SAME stream.
    key, state, block = _campaign(R)
    metrics.configure(str(jsonl))
    try:
        got = supervised_sweep(
            key, _fresh(state), scenario=block, rounds_per_dispatch=2,
            checkpoint_every=4, checkpoint_path=str(ck), health_every=2,
            config=SupervisorConfig(timeout_s=60.0),
        )
        rid = got["supervisor"]["run_id"]
        metrics.default_sink().close()
    finally:
        metrics.configure(None)
        metrics.set_run_id(None)
    recs = _records(jsonl)
    # One run id across BOTH processes' records (the successor
    # re-derived it from the campaign identity).
    assert {r.get("run_id") for r in recs} == {rid}
    summary = [r for r in recs if r["event"] == "flight_summary"][-1]
    # Contiguous across the process boundary...
    assert summary["contiguous"] and summary["rounds"] == [0, R]
    # ...no duplicated dispatch windows (replayed windows dedup on the
    # round grid)...
    los = [w["lo"] for w in (
        e for e in summary["timeline"] if e["kind"] == "dispatch_window"
    )]
    assert los == list(range(0, R, 2))
    # ...exactly ONE recovery edge (the successor's auto-resume)...
    assert len(summary["recoveries"]) == 1
    assert summary["recoveries"][0]["action"] == "resume"
    assert sum(r["event"] == "recovery" for r in recs) == 1
    # ...and every checkpoint exactly once, bit-consistent with the
    # raw records (the child got round 4 and 8 out; the successor
    # re-wrote from its resume point onward).
    ck_rounds = [c["round"] for c in summary["checkpoints"]]
    assert ck_rounds == sorted(set(ck_rounds))
    assert ck_rounds[-1] == R
    raw_rounds = {
        r["round"] for r in recs if r["event"] == "scenario_checkpoint"
    }
    assert set(ck_rounds) == raw_rounds
    # The surviving checkpoint headers carry the same run id.
    for rnd in ck_rounds:
        assert load_carry_checkpoint(
            str(tmp_path / f"kill_{rnd}.npz")
        ).run_id == rid


# -- health sampler -----------------------------------------------------------


def test_health_sampler_windows_and_gauges():
    reg = obs.registry.MetricsRegistry()
    sampler = health.HealthSampler(reg, timeout_s=30.0)
    rounds_c = reg.counter("pipeline_rounds_total")
    reg.counter("pipeline_retires_total")
    occ = reg.histogram(
        "pipeline_depth_occupancy", base=1.0, n_buckets=16
    )
    lag = reg.histogram("pipeline_retire_lag_s")
    lat = reg.histogram("pipeline_dispatch_latency_s")
    # Pre-window sample: every windowed field is None — never a fake
    # zero or a lifetime blend.
    first = sampler.sample()
    assert first["rounds_per_s"] is None
    assert first["depth_occupancy"] is None
    assert first["retire_lag_p50_s"] is None
    assert first["watchdog_margin_s"] is None
    rounds_c.inc(100)
    reg.counter("pipeline_retires_total").inc(10)
    for _ in range(10):
        occ.record(3)
    for _ in range(9):
        lag.record(0.001)
    lag.record(0.5)
    lat.record(0.25)
    snap = sampler.sample()
    assert snap["rounds_per_s"] > 0
    assert snap["rounds_total"] == 100
    assert snap["depth_occupancy"] == 3.0
    # p50 sits in the ~1ms bucket, p99 reaches the 0.5 s outlier.
    assert snap["retire_lag_p50_s"] < 0.01
    assert snap["retire_lag_p99_s"] >= 0.5
    # The window's worst latency reads as its bucket's UPPER edge (the
    # histogram's .max is lifetime-scoped — deliberately unused), so
    # the margin errs conservative by at most one bucket factor.
    assert 0.25 <= snap["dispatch_latency_max_s"] <= 0.5
    assert snap["watchdog_margin_s"] == pytest.approx(
        30.0 - snap["dispatch_latency_max_s"]
    )
    # The gauge family landed in the registry.
    text = reg.prometheus_text()
    assert "health_rounds_per_s" in text
    assert "health_watchdog_margin_s" in text
    # Second window with no new rounds: rate drops to 0 — and the
    # latency window is EMPTY, so the margin reports None instead of
    # replaying the last window's (or a lifetime) max forever.
    snap2 = sampler.sample()
    assert snap2["rounds_per_s"] == 0.0
    assert snap2["watchdog_margin_s"] is None


def test_health_sampler_prime_isolates_prior_campaigns():
    # The registry outlives campaigns: a primed sampler must not read
    # an earlier sweep's totals as its first window (the engine primes
    # its per-sweep sampler before the first dispatch).
    reg = obs.registry.MetricsRegistry()
    occ = reg.histogram(
        "pipeline_depth_occupancy", base=1.0, n_buckets=16
    )
    for _ in range(10):
        occ.record(4)  # a previous depth-4 campaign's lifetime record
    reg.counter("pipeline_rounds_total").inc(1000)
    sampler = health.HealthSampler(reg)
    sampler.prime()
    occ.record(1)
    occ.record(1)
    reg.counter("pipeline_rounds_total").inc(2)
    snap = sampler.sample()
    assert snap["depth_occupancy"] == 1.0  # not (40 + 2) / 12
    assert snap["rounds_total"] == 1002
    assert snap["rounds_per_s"] is not None  # prime opened the window


def test_health_snapshot_record_carries_run_id(sink_path):
    reg = obs.registry.MetricsRegistry()
    sampler = health.HealthSampler(reg)
    with flight.run_scope("health-run-1"):
        sampler.sample(emit=True, dispatch=3)
    metrics.default_sink().close()
    recs = _records(sink_path)
    assert recs and recs[-1]["event"] == "health_snapshot"
    assert recs[-1]["run_id"] == "health-run-1"
    assert recs[-1]["dispatch"] == 3


def test_registry_per_shard_naming_rule():
    reg = obs.registry.MetricsRegistry()
    reg.gauge("scenario_plane_bytes_per_shard")  # canonical spelling
    # The misspellings are the POINT here (the runtime assert under
    # test must reject them) — waive the static mirror per line.
    with pytest.raises(ValueError, match="_per_shard"):
        reg.gauge("per_shard_plane_bytes")  # ba-lint: disable=BA602
    with pytest.raises(ValueError, match="_per_shard"):
        reg.counter("plane_per_shard_bytes")  # ba-lint: disable=BA602
    with pytest.raises(ValueError, match="_per_shard"):
        reg.histogram("plane_bytes_per_shard_s")  # ba-lint: disable=BA602
    # Plain 'shards' (no per-device-share claim) stays legal.
    reg.gauge("pipeline_shards")


def test_repl_stats_live(monkeypatch):
    cluster = Cluster(4, PyBackend(), seed=0)
    lines = []
    assert handle_command(cluster, "stats --live", lines.append)
    keys = {l.split(" ")[0] for l in lines}
    assert "rounds_total" in keys and "stalls_total" in keys
    # The plain exposition path is untouched.
    lines2 = []
    assert handle_command(cluster, "stats", lines2.append)


# -- ledger run-id riders -----------------------------------------------------


def test_compile_ledger_rows_ride_run_id(tmp_path):
    ledger = tmp_path / "ledger.json"
    obs.reset_first_calls()
    obs.configure_compile_ledger(str(ledger), {"jax": "x"})
    try:
        with flight.run_scope("ledger-run"):
            first, changed, cross = obs.classify_compile(
                "fn_a", {"capacity": 4}
            )
        assert first and changed is None
        doc = json.loads(ledger.read_text())
        assert doc["fns"]["fn_a"][0]["run_id"] == "ledger-run"
        # A NEW process (fresh session state) compiling the same axes
        # under a different run must NOT read as a cross-process change
        # — the rider is provenance, not identity.
        obs.reset_first_calls()
        obs.configure_compile_ledger(str(ledger), {"jax": "x"})
        with flight.run_scope("ledger-run-2"):
            first, changed, cross = obs.classify_compile(
                "fn_a", {"capacity": 4}
            )
        assert first and changed is None and not cross
    finally:
        obs.configure_compile_ledger(None)
        obs.reset_first_calls()


# -- bench sentinel -----------------------------------------------------------


def _sentinel(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_sentinel.py"),
         *args],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )


def test_sentinel_index_only_green():
    proc = _sentinel("--index-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "trajectory rows" in proc.stdout


def test_sentinel_green_against_committed_baseline():
    proc = _sentinel("--fresh", str(REPO / "BENCH_resilience_r10.json"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "green" in proc.stdout


def test_sentinel_red_on_degraded_artifact(tmp_path):
    doc = json.load(open(REPO / "BENCH_resilience_r10.json"))
    doc["configs"]["resilience"]["rounds_per_sec"] /= 2.5  # >= 2x slower
    degraded = tmp_path / "degraded.json"
    degraded.write_text(json.dumps(doc))
    proc = _sentinel("--fresh", str(degraded))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "RED" in proc.stdout and "regression" in proc.stderr


def test_sentinel_red_on_false_acceptance_flag(tmp_path):
    doc = json.load(open(REPO / "BENCH_resilience_r10.json"))
    doc["configs"]["resilience"]["recovery_within_15pct"] = False
    bad = tmp_path / "accept.json"
    bad.write_text(json.dumps(doc))
    proc = _sentinel("--fresh", str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr


def test_sentinel_refuses_vacuous_comparison(tmp_path):
    # Comparing NOTHING must not be green: a fresh doc whose platform
    # matches no committed baseline key (the silent-gate-off drift)
    # exits 2, distinct from both green (0) and regression (1).
    doc = json.load(open(REPO / "BENCH_resilience_r10.json"))
    doc["platform"] = "made-up-platform"
    drifted = tmp_path / "drifted.json"
    drifted.write_text(json.dumps(doc))
    proc = _sentinel("--fresh", str(drifted))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "compared nothing" in proc.stderr


def test_committed_trajectory_artifact_is_current():
    # BENCH_trajectory.json is the sentinel's own index, committed: it
    # must stay regenerable byte-for-byte from the committed artifacts
    # (a drifted table would silently mis-baseline future PRs).
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        import bench_sentinel
        index = bench_sentinel.build_index(
            bench_sentinel.committed_artifacts(str(REPO))
        )
    finally:
        sys.path.pop(0)
    committed = json.load(open(REPO / "BENCH_trajectory.json"))
    assert committed == json.loads(json.dumps(index))
