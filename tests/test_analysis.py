"""ba-lint (ba_tpu/analysis) tests: fixtures, self-lint, CLI contract.

Three layers, mirroring what CI relies on:

- **Fixture exactness**: every ``# expect: BAxxx`` marker in
  ``tests/fixtures/ba_lint/`` must be matched by a finding at that
  (file, line) and vice versa — a missed positive and a false positive
  fail the same assertion.  The fixtures cover the alias tricks the old
  greps could not see (``import numpy as jnp_like``, ``from jax.random
  import split as sp``), both suppression forms, and the module-scoped
  rules through a miniature package tree.
- **Self-lint**: the shipped tree is finding-free — the CI lint set
  (``ba_tpu/ examples/ bench.py``) has ZERO findings of any severity,
  and the whole repo (tests + scripts included) has zero errors.
- **CLI/JSON contract**: exit codes, the version-1 findings schema
  (checked like the metrics JSONL), ``--rules`` filtering, and the
  no-jax-import guarantee, all through real subprocesses.

None of these tests import jax; the whole module runs in milliseconds,
which is the point of a pure-ast analyzer.
"""

import json
import pathlib
import re
import subprocess
import sys
import textwrap

import pytest

from ba_tpu.analysis import run_paths
from ba_tpu.analysis.base import all_rules
from ba_tpu.analysis.resolver import module_name

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "ba_lint"
_EXPECT_RE = re.compile(r"#\s*expect:\s*((?:BA\d+\s*)+)")


def _expected_markers():
    """{(relative path, line, code)} parsed from fixture ``# expect:``s."""
    expected = set()
    for path in sorted(FIXTURES.rglob("*.py")):
        rel = str(path.relative_to(REPO))
        for lineno, text in enumerate(
            path.read_text().splitlines(), start=1
        ):
            m = _EXPECT_RE.search(text)
            if m:
                for code in m.group(1).split():
                    expected.add((rel, lineno, code))
    return expected


def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "ba_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=str(cwd),
        timeout=120,
    )


def test_fixture_findings_exact():
    expected = _expected_markers()
    assert expected, "fixture markers vanished — fixtures moved?"
    active, suppressed, files = run_paths([str(FIXTURES)])
    actual = {
        (str(pathlib.Path(f.path)), f.line, f.code) for f in active
    }
    # Normalize to repo-relative (run_paths reports cwd-relative).
    actual = {
        (str((pathlib.Path.cwd() / p).resolve().relative_to(REPO)), l, c)
        for p, l, c in actual
    }
    missed = expected - actual
    false_pos = actual - expected
    assert not missed, f"fixture positives MISSED: {sorted(missed)}"
    assert not false_pos, f"FALSE positives: {sorted(false_pos)}"
    # The deliberate `# ba-lint: disable=` demo lines land in the
    # suppressed bucket (one per scope-free fixture + one in the tree).
    assert len(suppressed) >= 3
    assert files >= 10


def test_self_lint_shipped_tree_is_finding_free():
    # The CI lint set: zero findings of ANY severity (BA401 included —
    # the ISSUE 3 dead-import sweep fixed what it found).
    active, _suppressed, files = run_paths(
        [str(REPO / "ba_tpu"), str(REPO / "examples"), str(REPO / "bench.py")]
    )
    assert files > 50
    assert not active, "shipped tree has findings:\n" + "\n".join(
        f.render() for f in active
    )


def test_self_lint_tests_and_scripts_error_free():
    # tests/ and scripts/ ride along at error level (the four deliberate
    # use-after-donate reads in test_pipeline.py are suppressed inline).
    # Top-level test files only: tests/fixtures/ba_lint/ is deliberately
    # full of violations — that's what test_fixture_findings_exact pins.
    # ba_tpu/ rides in the analyzed set so the cross-module donation
    # registry knows pipeline_megastep; its own findings are covered by
    # the test above.
    active, suppressed, _files = run_paths(
        [str(REPO / "ba_tpu")]
        + sorted(str(p) for p in (REPO / "tests").glob("*.py"))
        + [str(REPO / "scripts")]
    )
    errors = [f for f in active if f.severity == "error"]
    assert not errors, "\n".join(f.render() for f in errors)
    assert any(
        f.code == "BA201" and f.path.endswith("test_pipeline.py")
        for f in suppressed
    ), "the donation-safety test's inline BA201 waivers disappeared"


def test_module_name_scoping_survives_tree_copies(tmp_path):
    # The CI mutation check analyzes a tempdir copy; scoping must come
    # from __init__.py ancestry, not the absolute path.
    pkg = tmp_path / "ba_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    target = pkg / "pipeline.py"
    target.write_text("def f(x):\n    return x.block_until_ready()\n")
    assert module_name(str(target)) == "ba_tpu.parallel.pipeline"
    active, _, _ = run_paths([str(tmp_path)])
    assert [f.code for f in active] == ["BA101"]


def test_file_wide_suppression(tmp_path):
    src = textwrap.dedent(
        """
        # ba-lint: disable-file=BA202
        import jax.random as jr

        def f(key):
            a = jr.normal(key, (2,))
            return a + jr.uniform(key, (2,))
        """
    )
    f = tmp_path / "mod.py"
    f.write_text(src)
    active, suppressed, _ = run_paths([str(tmp_path)])
    assert not active
    assert [s.code for s in suppressed] == ["BA202"]


def test_exclude_prunes_paths(tmp_path):
    # --exclude (ISSUE 4 satellite): a path prefix keeps its subtree out
    # of discovery — the CI spelling for linting tests/ without the
    # deliberately-violating tests/fixtures/ba_lint/ fixtures.
    pkg = tmp_path / "ba_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "pipeline.py").write_text(
        "def f(x):\n    return x.block_until_ready()\n"
    )
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    active, _, files = run_paths([str(tmp_path)])
    assert [f.code for f in active] == ["BA101"] and files == 4
    active, _, files = run_paths(
        [str(tmp_path)], exclude=[str(tmp_path / "ba_tpu")]
    )
    assert active == [] and files == 1  # only clean.py survives
    # The CLI spelling agrees (and the excluded tree never parses).
    proc = _run_cli(
        [str(tmp_path), "--format", "json",
         "--exclude", str(tmp_path / "ba_tpu")]
    )
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == [] and doc["files_scanned"] == 1


def test_ci_lint_set_with_exclude_is_error_free():
    # The exact invocation scripts/ci.sh gates on: the full repo lint
    # set with the fixtures excluded exits 0.
    proc = _run_cli(
        ["ba_tpu/", "examples/", "bench.py", "tests/", "scripts/",
         "--exclude", "tests/fixtures/ba_lint", "--format", "json"]
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["counts"]["error"] == 0
    assert not any(
        "fixtures/ba_lint" in f["path"]
        for f in doc["findings"] + doc["suppressed"]
    )


def test_syntax_error_is_fatal_finding(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    active, _, _ = run_paths([str(tmp_path)])
    assert [f.code for f in active] == ["BA900"]
    assert active[0].severity == "error"


def test_cli_json_schema_and_exit_codes(tmp_path):
    bad = tmp_path / "ba_tpu" / "parallel"
    bad.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (bad / "__init__.py").write_text("")
    (bad / "pipeline.py").write_text(
        "import jax.random as jr\n\n"
        "def f(key):\n    return jr.split(key)\n"
    )
    proc = _run_cli([str(tmp_path), "--format", "json"])
    assert proc.returncode == 1, proc.stderr
    doc = json.loads(proc.stdout)
    # The findings JSON is schema-checked like the metrics JSONL.
    for field in (
        "version", "tool", "files_scanned", "rules", "findings",
        "suppressed", "counts", "exit",
    ):
        assert field in doc, field
    assert doc["version"] == 1
    assert doc["tool"] == "ba-lint"
    assert doc["exit"] == 1
    assert [f["code"] for f in doc["findings"]] == ["BA102"]
    for f in doc["findings"]:
        assert {"code", "severity", "path", "line", "col", "message"} <= set(f)

    # Rule filtering: excluding BA102 turns the same tree green.
    proc = _run_cli(
        [str(tmp_path), "--format", "json", "--rules", "BA101,BA301"]
    )
    assert proc.returncode == 0
    doc = json.loads(proc.stdout)
    assert doc["findings"] == [] and doc["rules"] == ["BA101", "BA301"]

    # Unknown rule codes are a usage error (argparse exit 2).
    proc = _run_cli([str(tmp_path), "--rules", "BA999"])
    assert proc.returncode == 2
    assert "unknown rule code" in proc.stderr


def test_cli_never_imports_jax():
    # The acceptance contract: analyzing the real tree must not import
    # jax (or even numpy) — ba-lint runs on hosts with no accelerator
    # stack.  sys.modules is inspected in-process after a full run.
    code = (
        "import sys\n"
        "from ba_tpu.analysis import run_paths\n"
        "active, _, files = run_paths(['ba_tpu', 'examples', 'bench.py'])\n"
        "assert files > 50, files\n"
        "banned = {m for m in sys.modules if m.split('.')[0] in"
        " ('jax', 'jaxlib', 'numpy')}\n"
        "assert not banned, banned\n"
        "print('clean', files)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=str(REPO),
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.startswith("clean")


def test_list_rules_covers_the_documented_set():
    proc = _run_cli(["--list-rules"])
    assert proc.returncode == 0
    listed = {line.split()[0] for line in proc.stdout.splitlines() if line}
    assert listed == {
        "BA101", "BA102", "BA201", "BA202", "BA301", "BA401",
        "BA501", "BA502", "BA503", "BA504",
        "BA601", "BA602", "BA603",
    }
    # Severity contract: BA401 is the one warning-level rule.
    severities = {r.code: r.severity for r in all_rules()}
    assert severities["BA401"] == "warning"
    assert all(
        sev == "error"
        for code, sev in severities.items()
        if code != "BA401"
    )


def test_warnings_do_not_fail_the_run(tmp_path):
    (tmp_path / "mod.py").write_text("import os\n\nX = 1\n")
    proc = _run_cli([str(tmp_path), "--format", "json"])
    assert proc.returncode == 0
    doc = json.loads(proc.stdout)
    assert [f["code"] for f in doc["findings"]] == ["BA401"]
    assert doc["counts"] == {"error": 0, "warning": 1, "suppressed": 0}


def test_relative_import_anchoring_in_package_init(tmp_path):
    # `from . import x` in pkg/__init__.py anchors at the package
    # ITSELF (a naive parts[:-level] lands on the parent and BA301's
    # closure silently misses the edge).
    core = tmp_path / "ba_tpu" / "core"
    core.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (core / "__init__.py").write_text("from . import impure\n")
    (core / "impure.py").write_text("from ba_tpu import obs as _o\n")
    active, _, _ = run_paths([str(tmp_path)], rule_codes={"BA301"})
    hits = {(pathlib.Path(f.path).name, f.code) for f in active}
    assert ("impure.py", "BA301") in hits, hits
    assert ("__init__.py", "BA301") in hits, (
        "transitive edge from the package __init__ was mis-anchored: "
        f"{hits}"
    )


def test_match_statement_arms_are_flow_branches(tmp_path):
    # Rebinds inside `case` arms clear BA202 marks (no false positive);
    # a double-consume INSIDE one arm still flags.
    (tmp_path / "m.py").write_text(textwrap.dedent(
        """
        import jax.random as jr

        def rebound_in_every_arm(key, mode):
            a = jr.normal(key, (2,))
            match mode:
                case 1:
                    key = jr.split(key)[0]
                case _:
                    key = jr.split(key)[1]
            return a, jr.uniform(key, (2,))

        def double_consume_in_arm(key, mode):
            match mode:
                case 1:
                    a = jr.normal(key, (2,))
                    b = jr.uniform(key, (2,))
                    return a, b
            return None
        """
    ))
    active, _, _ = run_paths([str(tmp_path)])
    # One finding: the SECOND consume inside the arm (line 17); the
    # rebound-in-every-arm function stays clean.
    assert [(f.code, f.line) for f in active] == [("BA202", 17)], active


def test_docstring_directives_and_trailing_disable_file_inert(tmp_path):
    # Suppressions parse from COMMENT tokens: syntax examples inside a
    # docstring are inert (suppress.py documents its own syntax without
    # self-suppressing), and a TRAILING disable-file never goes
    # file-wide.
    (tmp_path / "m.py").write_text(textwrap.dedent(
        '''
        """Docs: write `# ba-lint: disable-file=BA202` to waive a file."""
        import jax.random as jr

        def f(key):
            a = jr.normal(key, (2,))  # ba-lint: disable-file=BA202
            b = jr.uniform(key, (2,))
            return a, b
        '''
    ))
    active, suppressed, _ = run_paths([str(tmp_path)])
    assert [f.code for f in active] == ["BA202"] and not suppressed


def test_donates_annotation_cross_module(tmp_path):
    # ISSUE 5 satellite (ROADMAP PR 3 item): a donates annotation on
    # a def line registers the wrapper project-wide — a use-after-donate
    # at an ALIASED call site in another module flags, docstring
    # mentions of the syntax stay inert, and the hand table
    # (KNOWN_DONATING) still backs the un-annotated legacy names.
    pkg = tmp_path / "ba_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "engine.py").write_text(textwrap.dedent(
        '''
        """Docs may say `# ba-lint: donates(state)` without registering."""

        def run(  # ba-lint: donates(state)
            key, state, rounds,
        ):
            return state
        '''
    ))
    (pkg / "caller.py").write_text(textwrap.dedent(
        """
        from ba_tpu.parallel.engine import run as launch

        def bad(key, state):
            out = launch(key, state, 4)
            return out, state

        def key_is_fine(key, state):
            out = launch(key, state, 4)
            return out, key
        """
    ))
    active, _, _ = run_paths([str(tmp_path)], rule_codes={"BA201"})
    assert [(pathlib.Path(f.path).name, f.code, f.line) for f in active] == [
        ("caller.py", "BA201", 6)
    ], active


def test_donates_annotation_typo_is_a_finding(tmp_path):
    # A donated-name typo must surface, not silently protect nothing.
    (tmp_path / "m.py").write_text(
        "def run(key, state):  # ba-lint: donates(stat)\n"
        "    return state\n"
    )
    active, _, _ = run_paths([str(tmp_path)], rule_codes={"BA201"})
    assert [(f.code, f.line) for f in active] == [("BA201", 1)]
    assert "not positional parameters" in active[0].message


@pytest.mark.parametrize("seed,code", [
    ("def _m(x):\n    return x.block_until_ready()\n", "BA101"),
    ("import jax.random as _j\n\ndef _m(k):\n    return _j.split(k)\n",
     "BA102"),
    (
        "import threading\n\n\n"
        "class _M:\n"
        "    def start(self):\n"
        "        threading.Thread(\n"
        "            target=self._loop, daemon=True\n"
        "        ).start()\n\n"
        "    def _loop(self):\n"
        "        self.n = 1\n\n"
        "    def poke(self):\n"
        "        self.n = 2\n",
        "BA501",
    ),
    (
        "def _m(sink):\n"
        "    sink.emit({'event': 'mystery_event', 'v': 1})\n",
        "BA601",
    ),
    (
        "def _m(reg):\n"
        "    return reg.gauge('depth_serve_live')\n",
        "BA602",
    ),
    (
        "import os\n\n\n"
        "def _m():\n"
        "    return os.environ.get('BA_TPU_TOTALLY_UNDOCUMENTED', '')\n",
        "BA603",
    ),
])
def test_mutation_flips_red(tmp_path, seed, code):
    # The in-process twin of scripts/ci.sh's mutation check.
    pkg = tmp_path / "ba_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (tmp_path / "ba_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "pipeline.py").write_text(seed)
    active, _, _ = run_paths([str(tmp_path)])
    assert code in {f.code for f in active}


def test_sarif_output_structure(tmp_path):
    # --sarif composes with either --format and carries suppressed
    # findings marked inSource; structure is the SARIF 2.1.0 minimum
    # code-scanning ingestion needs.
    out = tmp_path / "lint.sarif"
    proc = _run_cli(
        ["tests/fixtures/ba_lint/ba501.py",
         "tests/fixtures/ba_lint/ba601.py",
         "--sarif", str(out), "--format", "json"]
    )
    assert proc.returncode == 1  # fixtures are deliberately violating
    json.loads(proc.stdout)  # --format json still prints on stdout
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "ba-lint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert {"BA501", "BA601"} <= rule_ids
    results = run["results"]
    assert results, "fixture findings must appear as SARIF results"
    for r in results:
        assert r["ruleId"] in rule_ids
        assert r["level"] in ("error", "warning")
        assert r["message"]["text"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1  # SARIF is 1-based
    # ba501.py's Waived class demo is in-source suppressed.
    assert any(
        r.get("suppressions") == [{"kind": "inSource"}] for r in results
    )
    assert any("suppressions" not in r for r in results)


def test_readme_env_table_matches_contracts_registry():
    # The BA603 registry IS the README "Environment knobs" table: every
    # name in the section must be covered by contracts.ENV_DOCUMENTED/
    # ENV_WILDCARDS and vice versa — a row added to one without the
    # other fails here before the lint rule can drift.
    from ba_tpu.analysis import contracts

    readme = (REPO / "README.md").read_text()
    start = readme.index("## Environment knobs")
    section = readme[start:]
    end = section.find("\n## ", 1)
    if end != -1:
        section = section[:end]
    tokens = set(re.findall(r"BA_TPU_[A-Z0-9_]+", section))
    # A trailing underscore is the wildcard-row spelling
    # (`BA_TPU_BENCH_*` tokenizes to `BA_TPU_BENCH_`).
    wildcards = {t for t in tokens if t.endswith("_")}
    names = tokens - wildcards
    assert wildcards == set(contracts.ENV_WILDCARDS)
    undocumented = {n for n in names if not contracts.env_documented(n)}
    assert not undocumented, (
        f"README names missing from contracts.ENV_DOCUMENTED: "
        f"{sorted(undocumented)}"
    )
    missing_rows = {
        n for n in contracts.ENV_DOCUMENTED if n not in section
    }
    assert not missing_rows, (
        f"contracts.ENV_DOCUMENTED entries with no README row: "
        f"{sorted(missing_rows)}"
    )


def test_contracts_registry_pins_runtime_tables():
    # One schema table in the repo: the static registry must equal the
    # runtime source-of-truth sets it mirrors.  obs/flight and
    # utils/metrics are host-tier (BA301-pinned), so importing them
    # here stays jax-free.
    from ba_tpu.analysis import contracts
    from ba_tpu.obs import flight
    from ba_tpu.utils import metrics

    assert contracts.RUN_SCOPED_EVENTS == flight.RUN_SCOPED_EVENTS
    assert contracts.SCHEMA_VERSION == metrics.SCHEMA_VERSION
    # Registry invariants: run-scoped/ci flags only on known families,
    # and the metric predicate accepts the canonical spellings the
    # runtime registry asserts on.
    assert contracts.CI_REQUIRED_EVENTS <= set(contracts.RECORD_FAMILIES)
    assert contracts.metric_name_violation("serve_queue_depth") is None
    assert contracts.metric_name_violation("plane_bytes_per_shard") is None
    assert contracts.metric_name_violation("queue_serve_depth")
    assert contracts.metric_name_violation("per_shard_bytes")


def test_ba603_unused_check_gated_on_full_repo_span(tmp_path):
    # documented-but-unused only fires when the analyzed set spans the
    # whole repo (ba_tpu/ tests/ scripts/ examples/ bench.py) — a
    # partial run cannot see every reader, so absence there is not
    # evidence of a stale row.
    pkg = tmp_path / "ba_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("X = 1\n")
    active, _, _ = run_paths([str(tmp_path)], rule_codes={"BA603"})
    assert active == [], [f.render() for f in active]
