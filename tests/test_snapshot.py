"""Checkpoint/resume: the durability the reference lacks (SURVEY.md §6).

Pins both snapshot shapes — batched SimState tensors to .npz, the
interactive cluster to JSON — and the CLI ``--state FILE`` contract:
restore at startup, save on Exit, REPL semantics (ids, leadership,
fault flags, per-round seeds) indistinguishable from a never-stopped run.
"""

import io

import numpy as np

import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core import ATTACK, make_state, om1_agreement
from ba_tpu.runtime.backends import PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.utils.snapshot import (
    load_sim_state,
    restore_cluster,
    save_cluster,
    save_sim_state,
)


def test_sim_state_npz_roundtrip(tmp_path):
    faulty = jnp.zeros((8, 6), bool).at[:, 2].set(True)
    state = make_state(8, 6, order=ATTACK, faulty=faulty)
    decisions = np.arange(8, dtype=np.int8)
    path = str(tmp_path / "sweep.npz")
    save_sim_state(path, state, decisions=decisions)
    back, extra = load_sim_state(path)
    for field in ("order", "leader", "faulty", "alive", "ids"):
        np.testing.assert_array_equal(
            np.asarray(getattr(back, field)), np.asarray(getattr(state, field))
        )
    np.testing.assert_array_equal(extra["decisions"], decisions)
    # The restored state is live: a round runs on it unchanged.
    out = om1_agreement(jr.key(0), back)
    assert np.all(np.asarray(out["decision"]) == ATTACK)


def test_cluster_json_roundtrip(tmp_path):
    c1 = Cluster(5, PyBackend(), seed=3)
    c1.set_faulty(2, True)
    c1.kill(1)  # leadership moves to 2
    c1.actual_order("attack")  # advances the round counter
    path = str(tmp_path / "cluster.json")
    save_cluster(path, c1)

    c2 = Cluster(1, PyBackend(), seed=0)
    restore_cluster(path, c2)
    assert [g.id for g in c2.generals] == [g.id for g in c1.generals]
    assert [g.faulty for g in c2.generals] == [g.faulty for g in c1.generals]
    assert c2.leader_id == c1.leader_id == 2
    assert c2._round == c1._round == 1
    assert c2._next_id == c1._next_id
    # Resumed run behaves exactly like the uninterrupted one: same seeds,
    # same roster -> byte-identical round results.
    r1 = c1.actual_order("retreat")
    r2 = c2.actual_order("retreat")
    assert r1 == r2


def test_restore_refuses_backend_config_mismatch(tmp_path):
    import pytest

    from ba_tpu.runtime.backends import JaxBackend

    c1 = Cluster(4, JaxBackend(platform="cpu", protocol="sm", m=2), seed=0)
    path = str(tmp_path / "sm.json")
    save_cluster(path, c1)
    c2 = Cluster(4, PyBackend(), seed=0)
    with pytest.raises(ValueError, match="backend config"):
        restore_cluster(path, c2)


def test_save_is_atomic_no_tmp_left(tmp_path):
    c = Cluster(3, PyBackend(), seed=0)
    path = tmp_path / "c.json"
    save_cluster(str(path), c)
    save_cluster(str(path), c)  # overwrite goes through os.replace
    assert [p.name for p in tmp_path.iterdir()] == ["c.json"]


def test_cli_state_flag_restores_roster(tmp_path):
    from ba_tpu.runtime.main import build_cluster, main
    import sys

    path = str(tmp_path / "state.json")
    stdin = sys.stdin
    try:
        sys.stdin = io.StringIO("g-kill 1\ng-add 1\nExit\n")
        main(["3", "--backend", "py", "--state", path])
    finally:
        sys.stdin = stdin
    # Fresh process: restored roster is G2, G3, G4 with leader 2 and the
    # next id continuing from 5, not a fresh 3-general cluster.
    cluster, state_path = build_cluster(["3", "--backend", "py", "--state", path])
    assert state_path == path
    assert [g.id for g in cluster.generals] == [2, 3, 4]
    assert cluster.leader_id == 2
    assert cluster._next_id == 5
