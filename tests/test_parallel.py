"""Sharding tests on the 8-device virtual CPU mesh (SURVEY.md section 5)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from ba_tpu.core import ATTACK, RETREAT, make_state
from ba_tpu.parallel import (
    bucketed_sweep_states,
    make_mesh,
    make_sweep_state,
    om1_node_sharded,
    sharded_sweep,
)


@pytest.fixture(scope="module")
def mesh42(eight_devices):
    return make_mesh((4, 2), ("data", "node"))


@pytest.fixture(scope="module")
def mesh8(eight_devices):
    return make_mesh((8, 1), ("data", "node"))


def test_sweep_state_shapes():
    state = make_sweep_state(jr.key(0), 64, 16)
    assert state.faulty.shape == (64, 16)
    n_alive = np.asarray(state.alive).sum(-1)
    assert (n_alive >= 4).all() and (n_alive <= 16).all()
    # Leader honest, traitors only among alive lieutenants, <= n/3.
    f = np.asarray(state.faulty)
    assert not f[:, 0].any()
    assert (f & ~np.asarray(state.alive)).sum() == 0
    assert (f.sum(-1) <= n_alive // 3).all()


def test_bucketed_sweep_states_partition():
    # 2 buckets over capacity 1024: sizes in [4,512] pad to 512, sizes in
    # [513,1024] pad to 1024; instance counts split evenly; remainder goes
    # to the last (widest) bucket.
    states = bucketed_sweep_states(jr.key(1), 1001, 1024, 2)
    assert [s.faulty.shape for s in states] == [(500, 512), (501, 1024)]
    n0 = np.asarray(states[0].alive).sum(-1)
    n1 = np.asarray(states[1].alive).sum(-1)
    assert (n0 >= 4).all() and (n0 <= 512).all()
    assert (n1 >= 513).all() and (n1 <= 1024).all()
    # Sweep-state invariants hold per bucket (honest leader, traitor cap).
    for s, n in ((states[0], n0), (states[1], n1)):
        f = np.asarray(s.faulty)
        assert not f[:, 0].any()
        assert (f.sum(-1) <= n // 3).all()


def test_bucketed_sweep_one_bucket_matches_flat():
    # n_buckets=1 degenerates to a single make_sweep_state-shaped batch.
    (state,) = bucketed_sweep_states(jr.key(2), 64, 16, 1)
    assert state.faulty.shape == (64, 16)
    n_alive = np.asarray(state.alive).sum(-1)
    assert (n_alive >= 4).all() and (n_alive <= 16).all()


def test_make_sweep_state_max_n_bounds():
    # max_n narrows the size range without touching the padded capacity.
    state = make_sweep_state(jr.key(9), 64, 32, min_n=6, max_n=9)
    assert state.faulty.shape == (64, 32)
    n_alive = np.asarray(state.alive).sum(-1)
    assert (n_alive >= 6).all() and (n_alive <= 9).all()
    with pytest.raises(ValueError):
        make_sweep_state(jr.key(9), 4, 32, min_n=10, max_n=9)
    with pytest.raises(ValueError):
        make_sweep_state(jr.key(9), 4, 32, max_n=33)


def test_bucketed_sweep_custom_min_n_and_guard():
    # Custom min_n threads through to the first bucket; the bucket-width
    # guard names the real constraint.
    states = bucketed_sweep_states(jr.key(10), 64, 256, 2, min_n=100)
    n0 = np.asarray(states[0].alive).sum(-1)
    assert (n0 >= 100).all() and (n0 <= 128).all()
    with pytest.raises(ValueError, match="upper edge below min_n"):
        bucketed_sweep_states(jr.key(10), 64, 256, 2, min_n=200)


def test_bucketed_sweep_decisions_compose():
    # Each bucket is an independent sweep: with an honest leader every
    # instance must decide the ordered value regardless of padding width.
    from ba_tpu.core import sm_agreement

    states = bucketed_sweep_states(jr.key(3), 96, 256, 2, order=ATTACK)
    for i, st in enumerate(states):
        out = jax.jit(
            lambda k, s: sm_agreement(k, s, 3, collapsed=True)
        )(jr.fold_in(jr.key(4), i), st)
        assert (np.asarray(out["decision"]) == ATTACK).all()


def test_sharded_sweep_all_decide_order(mesh8):
    # Honest leader + traitors <= (n-1)/3 per instance: every instance's
    # quorum must decide the ordered command (IC1+IC2 at sweep scale).
    state = make_sweep_state(jr.key(1), 256, 16, order=ATTACK)
    out = sharded_sweep(mesh8, jr.key(2), state, m=1)
    hist = np.asarray(out["histogram"])
    assert hist.tolist() == [0, 256, 0]
    assert (np.asarray(out["decision"]) == ATTACK).all()


def test_sharded_sweep_om2(mesh8):
    # OM(m) validity needs n > 2t + m (majority of honest eligible relays
    # at every resolve level), so cap traitors at n/4 for m=2, n=8.
    state = make_sweep_state(
        jr.key(3), 64, 8, min_n=8, max_traitor_frac=0.25, order=RETREAT
    )
    out = sharded_sweep(mesh8, jr.key(4), state, m=2)
    assert np.asarray(out["histogram"]).tolist() == [64, 0, 0]


def test_node_sharded_matches_dense(mesh42):
    # No faults: node-sharded OM(1) must agree exactly with the dense core.
    from ba_tpu.core import om1_agreement

    state = make_state(8, 16, order=ATTACK)
    sharded = om1_node_sharded(mesh42, jr.key(5), state)
    dense = jax.jit(om1_agreement)(jr.key(5), state)
    assert (np.asarray(sharded["majorities"]) == ATTACK).all()
    assert np.array_equal(
        np.asarray(sharded["decision"]), np.asarray(dense["decision"])
    )
    assert np.array_equal(np.asarray(sharded["total"]), np.asarray(dense["total"]))


def test_node_sharded_dead_and_faulty(mesh42):
    # 1 traitor + 1 dead out of 16: validity still deterministic.
    faulty = jnp.zeros((4, 16), bool).at[:, 5].set(True)
    alive = jnp.ones((4, 16), bool).at[:, 9].set(False)
    state = make_state(4, 16, order=RETREAT, faulty=faulty, alive=alive)
    out = om1_node_sharded(mesh42, jr.key(6), state)
    maj = np.asarray(out["majorities"])
    honest = [i for i in range(16) if i not in (5, 9)]
    assert (maj[:, honest] == RETREAT).all()
    assert (np.asarray(out["total"]) == 15).all()
    assert (np.asarray(out["decision"]) == RETREAT).all()


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out["majorities"].shape == (256, 16)


def test_graft_entry_dryrun(eight_devices):
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


# -- on-device leader failover (ba.py:306-314 at tensor scale) ----------------


def test_failover_sweep_reelects_per_instance():
    from ba_tpu.parallel import failover_sweep

    B, n, R = 4, 6, 3
    state = make_state(B, n, order=ATTACK)
    kills = jnp.zeros((R, B, n), bool)
    # Round 1: kill the leader (idx 0) in instances 0 and 2 only.
    kills = kills.at[1, [0, 2], 0].set(True)
    # Round 2: kill general 1 in instance 0 -> its leadership moves on;
    # instance 2 keeps leader 1 ("election is for life", ba.py:124-125).
    kills = kills.at[2, 0, 1].set(True)
    out = jax.jit(lambda k, s, ks: failover_sweep(k, s, ks))(
        jr.key(0), state, kills
    )
    leaders = np.asarray(out["leaders"])  # [R, B]
    assert leaders[0].tolist() == [0, 0, 0, 0]
    assert leaders[1].tolist() == [1, 0, 1, 0]
    assert leaders[2].tolist() == [2, 0, 1, 0]
    # Honest clusters keep deciding the order; totals track the kills.
    decisions = np.asarray(out["decisions"])
    assert (decisions == ATTACK).all()
    final_alive = np.asarray(out["final_state"].alive)
    assert final_alive.sum(axis=1).tolist() == [4, 6, 5, 6]


def test_failover_sweep_om2_and_faulty():
    from ba_tpu.parallel import failover_sweep

    B, n, R = 8, 7, 2
    faulty = jnp.zeros((B, n), bool).at[:, 3].set(True)
    state = make_state(B, n, order=RETREAT, faulty=faulty)
    kills = jnp.zeros((R, B, n), bool).at[1, :, 0].set(True)
    out = failover_sweep(jr.key(1), state, kills, m=2)
    leaders = np.asarray(out["leaders"])
    assert (leaders[0] == 0).all() and (leaders[1] == 1).all()
    # OM(2) with 1 traitor among 6 alive: validity holds post-failover.
    assert (np.asarray(out["decisions"])[1] == RETREAT).all()
    hists = np.asarray(out["histograms"])
    assert hists.shape == (R, 3) and (hists.sum(axis=1) == B).all()


def test_failover_sweep_sharded(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ba_tpu.parallel import failover_sweep

    B, n, R = 16, 8, 2
    state = make_state(B, n, order=ATTACK)
    state = jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh8, P("data", *([None] * (x.ndim - 1))))
        ),
        state,
    )
    kills = jnp.zeros((R, B, n), bool).at[1, :, 0].set(True)
    out = jax.jit(lambda k, s, ks: failover_sweep(k, s, ks))(
        jr.key(2), state, kills
    )
    assert (np.asarray(out["leaders"])[1] == 1).all()
    assert (np.asarray(out["decisions"]) == ATTACK).all()


# -- node-sharded OM(m)/EIG ----------------------------------------------------


def test_eig_node_sharded_honest_matches_unsharded(mesh42):
    from ba_tpu.core import eig_agreement
    from ba_tpu.parallel import eig_node_sharded

    # Honest cluster: OM(2) is deterministic, sharded == unsharded exactly.
    state = make_state(8, 8, order=ATTACK)
    want = eig_agreement(jr.key(0), state, 2)
    got = eig_node_sharded(mesh42, jr.key(0), state, 2)
    for k in ("majorities", "decision", "needed", "total"):
        np.testing.assert_array_equal(np.asarray(want[k]), np.asarray(got[k]))


def test_eig_node_sharded_ic_with_traitors(mesh42):
    from ba_tpu.parallel import eig_node_sharded

    # OM(2), t=2 (commander + one lieutenant), n=8 > 3m+... honest
    # lieutenants must agree (IC1) and quorum counts must be consistent.
    B = 256
    faulty = jnp.zeros((B, 8), bool).at[:, [0, 3]].set(True)
    state = make_state(B, 8, order=RETREAT, faulty=faulty)
    out = eig_node_sharded(mesh42, jr.key(1), state, 2)
    maj = np.asarray(out["majorities"])
    honest = np.ones((B, 8), bool)
    honest[:, [0, 3]] = False
    lo = np.where(honest, maj, 127).min(axis=1)
    hi = np.where(honest, maj, -1).max(axis=1)
    assert (lo == hi).all(), "IC1 violated on the sharded EIG path"
    for k, code in (("n_attack", ATTACK), ("n_retreat", RETREAT)):
        assert np.array_equal(np.asarray(out[k]), (maj == code).sum(axis=1))


def test_eig_node_sharded_dead_general(mesh42):
    from ba_tpu.parallel import eig_node_sharded

    alive = jnp.ones((4, 8), bool).at[:, 5].set(False)
    state = make_state(4, 8, order=ATTACK, alive=alive)
    out = eig_node_sharded(mesh42, jr.key(2), state, 2)
    maj = np.asarray(out["majorities"])
    live = [i for i in range(8) if i != 5]
    assert (maj[:, live] == ATTACK).all()
    assert (np.asarray(out["total"]) == 7).all()
    assert (np.asarray(out["decision"]) == ATTACK).all()


def test_make_mesh_oversized_request_names_counts(eight_devices):
    # ISSUE 8 satellite: an oversized mesh request used to die inside
    # jax.sharding.Mesh with an opaque reshape error; now the error
    # names available vs requested so REPL/bench can print one line.
    import jax

    n_avail = len(jax.devices())
    with pytest.raises(ValueError, match=rf"needs 999 .* {n_avail}"):
        make_mesh((999, 1), ("data", "node"))
    with pytest.raises(ValueError, match="all-positive"):
        make_mesh((0, 1), ("data", "node"))
    with pytest.raises(ValueError, match="axis"):
        make_mesh((2, 2, 2), ("data", "node"))


# -- multi-host mesh helpers (single-process degenerate form) -----------------


def test_init_distributed_noop_single_process():
    from ba_tpu.parallel.multihost import init_distributed

    assert init_distributed() == 1


def test_global_mesh_runs_sweeps(eight_devices):
    from ba_tpu.parallel.multihost import make_global_mesh

    mesh = make_global_mesh(node_devices_per_host=2)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("data", "node")
    # The mesh must be usable by both parallelism families unchanged.
    state = make_sweep_state(jr.key(0), 16, 8)
    out = sharded_sweep(mesh, jr.key(1), state, m=1)
    assert int(np.asarray(out["histogram"]).sum()) == 16
    big = make_state(8, 8, order=ATTACK)
    out2 = om1_node_sharded(mesh, jr.key(2), big)
    assert (np.asarray(out2["majorities"]) == ATTACK).all()
    with pytest.raises(ValueError):
        make_global_mesh(node_devices_per_host=3)
