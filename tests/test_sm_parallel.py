"""Node-sharded + collapsed SM(m): the large-n (n=1024) execution path.

Pins the three claims sm_parallel.py / sm_relay_rounds_collapsed make:

- the collapsed O(n)-per-round relay is *distributionally* identical to the
  exact per-(receiver, sender)-coin cube (deterministic equality when no
  traitor holds a coin, statistical equality of outcome frequencies
  otherwise) and preserves IC1/IC2 at the t = m boundary;
- the node-sharded round (both modes) computes the same protocol as the
  unsharded reference implementation on an 8-virtual-device mesh;
- BASELINE config #4's scale point — n=1024, m=32 signed — actually runs,
  sharded and single-device, which the dense EIG tree (O(n^m)) cannot do.
"""

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core import (
    ATTACK,
    RETREAT,
    UNDEFINED,
    make_state,
    sm_agreement,
    sm_round,
)
from ba_tpu.crypto.signed import signed_sm_agreement_sharded
from ba_tpu.parallel import make_mesh, sm_node_sharded

from tests.test_sm import assert_ic1, honest_lieutenants


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh((4, 2), ("data", "node"))


# -- collapsed relay: equivalence with the exact cube -------------------------


def test_collapsed_equals_exact_when_deterministic():
    # No faulty general ever holds an unrevealed value -> both models are
    # coin-free and must agree bit-for-bit.
    state = make_state(32, 8, order=ATTACK)
    exact = np.asarray(sm_round(jr.key(0), state, 3))
    fast = np.asarray(sm_round(jr.key(0), state, 3, collapsed=True))
    np.testing.assert_array_equal(exact, fast)


@pytest.mark.parametrize(
    "traitors,order,m,keys",
    [
        # Faulty commander alone (k=1 traitor-holder counts).
        ([0], ATTACK, 1, (1, 2)),
        # Three traitors incl. the commander, m=2: k reaches 3, exercising
        # the packed 8-bit threshold sampler beyond k=1 (exact in 256ths
        # for k <= 8).
        ([0, 2, 4], RETREAT, 2, (21, 22)),
    ],
)
def test_collapsed_matches_exact_distribution(traitors, order, m, keys):
    # Receivers' outcomes are random in both models; per-general outcome
    # frequencies must match within binomial noise.  The difference of two
    # independent estimates at B=16384 has sigma <= sqrt(2*.25/B) ~ 0.0055;
    # 0.022 is the 4-sigma band.
    B, n = 16384, 6
    faulty = jnp.zeros((B, n), bool).at[:, traitors].set(True)
    state = make_state(B, n, order=order, faulty=faulty)
    exact = np.asarray(sm_round(jr.key(keys[0]), state, m))
    fast = np.asarray(sm_round(jr.key(keys[1]), state, m, collapsed=True))
    for code in (ATTACK, RETREAT, UNDEFINED):
        f_exact = (exact == code).mean(axis=0)  # [n]
        f_fast = (fast == code).mean(axis=0)
        np.testing.assert_allclose(f_exact, f_fast, atol=0.022)


@pytest.mark.parametrize("m,traitors", [(1, [0]), (2, [0, 2])])
def test_collapsed_ic1_at_boundary(m, traitors):
    # IC1 must hold at t = m with a faulty commander — the chain-length
    # boundary the exact model protects (ADVICE.md round 1); the collapsed
    # sampler must inherit the same bound.
    B = 8192
    faulty = jnp.zeros((B, 5), bool).at[:, traitors].set(True)
    state = make_state(B, 5, order=ATTACK, faulty=faulty)
    choices = np.asarray(sm_round(jr.key(3), state, m, collapsed=True))
    assert_ic1(choices, honest_lieutenants(state))


def test_collapsed_ic2_honest_commander():
    B = 1024
    faulty = jr.bernoulli(jr.key(9), 0.4, (B, 6)).at[:, 0].set(False)
    state = make_state(B, 6, order=RETREAT, faulty=faulty)
    choices = np.asarray(sm_round(jr.key(4), state, 2, collapsed=True))
    honest = honest_lieutenants(state)
    assert np.all(choices[honest] == RETREAT)


# -- node-sharded SM ----------------------------------------------------------


@pytest.mark.parametrize("collapsed", [True, False])
def test_sharded_matches_unsharded_deterministic(mesh, collapsed):
    # Honest commander: the whole exchange is deterministic, so the sharded
    # round must equal the unsharded one exactly, mode-independently.
    state = make_state(8, 8, order=ATTACK)
    want = sm_agreement(jr.key(5), state, 2)
    got = sm_node_sharded(mesh, jr.key(5), state, 2, collapsed=collapsed)
    for k in ("majorities", "decision", "needed", "total"):
        np.testing.assert_array_equal(np.asarray(want[k]), np.asarray(got[k]))


@pytest.mark.parametrize("collapsed", [True, False])
def test_sharded_ic1_faulty_commander(mesh, collapsed):
    # t = m = 1 with a faulty commander: agreement must survive sharding
    # (the chain bound is enforced from psum'd global counts).
    B = 512
    faulty = jnp.zeros((B, 8), bool).at[:, 0].set(True)
    state = make_state(B, 8, order=ATTACK, faulty=faulty)
    out = sm_node_sharded(mesh, jr.key(6), state, 1, collapsed=collapsed)
    maj = np.asarray(out["majorities"])
    assert_ic1(maj, honest_lieutenants(state))
    # Quorum counts must be consistent with the sharded majorities.
    for k, code in (("n_attack", ATTACK), ("n_retreat", RETREAT),
                    ("n_undefined", UNDEFINED)):
        assert np.array_equal(np.asarray(out[k]), (maj == code).sum(axis=1))


def test_sharded_sig_valid_gates_vsets(mesh):
    # m=0, one corrupted signature -> that general's V is empty -> UNDEFINED;
    # everyone else follows the order.  Exercises the received/sig_valid
    # plumbing of the sharded path end-to-end.
    B, n = 4, 8
    state = make_state(B, n, order=RETREAT)
    received = jnp.full((B, n), RETREAT, jnp.int8)
    sig_valid = jnp.ones((B, n), bool).at[:, 3].set(False)
    out = sm_node_sharded(
        mesh, jr.key(7), state, 0, received=received, sig_valid=sig_valid
    )
    maj = np.asarray(out["majorities"])
    assert np.all(maj[:, 3] == UNDEFINED)
    keep = np.ones(n, bool)
    keep[[0, 3]] = False
    assert np.all(maj[:, keep] == RETREAT)


def test_sharded_sig_valid_recovered_by_relay(mesh):
    # Same corruption with m=1: honest relays re-deliver the signed value.
    B, n = 4, 8
    state = make_state(B, n, order=RETREAT)
    received = jnp.full((B, n), RETREAT, jnp.int8)
    sig_valid = jnp.ones((B, n), bool).at[:, 3].set(False)
    out = sm_node_sharded(
        mesh, jr.key(8), state, 1, received=received, sig_valid=sig_valid
    )
    assert np.all(np.asarray(out["majorities"]) == RETREAT)


def test_sharded_withhold_matches_unsharded_exactly(mesh):
    # A pinned adversary schedule removes all randomness from the relay,
    # so the sharded exact mode must reproduce the unsharded sm_round
    # bit-for-bit under the same (received, withhold).
    B, n, m = 8, 8, 2
    faulty = jnp.zeros((B, n), bool).at[:, [0, 3]].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    rng = np.random.default_rng(12)
    received = jnp.asarray(rng.integers(0, 2, (B, n)), jnp.int8)
    withhold = jnp.asarray(rng.random((m, B, n, n, 2)) < 0.5)
    want = sm_round(jr.key(0), state, m, withhold=withhold, received=received)
    got = sm_node_sharded(
        mesh, jr.key(0), state, m,
        received=received, withhold=withhold, collapsed=False,
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got["majorities"]))


def test_sharded_chain_bound_blocks_late_reveal(mesh):
    # The coalition-only late-reveal guard (sm.py chain bound) must survive
    # sharding: a faulty commander's unrevealed signed ATTACK stays
    # unrevealable when t = 1 (mirrors the unsharded test in test_sm.py).
    B, n, m = 4, 8, 2
    received = jnp.full((B, n), RETREAT, jnp.int8).at[:, 0].set(ATTACK)
    faulty = jnp.zeros((B, n), bool).at[:, 0].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    withhold = jnp.zeros((m, B, n, n, 2), bool)  # coalition sends eagerly
    out = sm_node_sharded(
        mesh, jr.key(0), state, m,
        received=received, withhold=withhold, collapsed=False,
    )
    assert np.all(np.asarray(out["majorities"])[:, 1:] == RETREAT)


def test_signed_sharded_end_to_end(mesh):
    # The full signed pipeline (host sign -> device Ed25519 verify -> node-
    # sharded relay) with one corrupted signature: the victim recovers via
    # honest relay (m=1), and the decision is unanimous.
    B, n = 4, 8  # B must divide the mesh's data axis
    corrupt = np.zeros((B, n), bool)
    corrupt[:, 5] = True
    state = make_state(B, n, order=ATTACK)
    out = signed_sm_agreement_sharded(mesh, jr.key(9), state, 1, corrupt=corrupt)
    assert np.all(~np.asarray(out["sig_valid"])[:, 5])
    assert np.all(np.asarray(out["majorities"]) == ATTACK)
    assert np.all(np.asarray(out["decision"]) == ATTACK)


# -- the n=1024 scale point ---------------------------------------------------


def test_n1024_m32_sharded(mesh):
    # BASELINE config #4: n=1024 generals, m=32, on the 8-device mesh.
    # 32 traitors (m = t), faulty commander included — the hardest
    # guaranteed-agreement point.  EIG at this n/m would need n^32 cells.
    B, n, m = 4, 1024, 32
    traitors = np.arange(32)
    faulty = jnp.zeros((B, n), bool).at[:, traitors].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    out = sm_node_sharded(mesh, jr.key(10), state, m, collapsed=True)
    maj = np.asarray(out["majorities"])
    assert_ic1(maj, honest_lieutenants(state))
    assert np.asarray(out["total"]).tolist() == [n] * B


def test_n1024_m32_single_device():
    # The same scale point unsharded (one chip): the collapsed relay keeps
    # it O(B * n * m) so a single device handles it comfortably.
    B, n, m = 4, 1024, 32
    faulty = jnp.zeros((B, n), bool).at[:, :32].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    choices = np.asarray(sm_round(jr.key(11), state, m, collapsed=True))
    assert_ic1(choices, honest_lieutenants(state))
