"""Pipelined sweep engine tests (ISSUE 1 tentpole, parallel/pipeline.py).

Three contracts, each pinned independently:

1. **Bit-exact equivalence** — under the engine's own key schedule, the
   pipelined multi-round run produces byte-identical decisions and
   histograms to the round-by-round ``agreement_step`` driver (and the
   megastep/unroll/depth dials must not change results, only scheduling).
2. **Donation safety** — the input state and schedule are consumed
   (deleted) by dispatch, the engine never touches a donated buffer
   afterwards, and the returned final state/schedule are live and
   continue the sweep.
3. **Depth-k overlap** — the engine keeps up to ``depth`` dispatches in
   flight and performs NO host sync between dispatches: the first retire
   happens only after the in-flight window fills, and
   ``jax.block_until_ready`` is never called (it is monkeypatched to
   raise for the duration).
"""

import dataclasses

import jax
import jax.random as jr
import numpy as np
import pytest

from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.parallel import make_mesh, make_sweep_state, pipeline_sweep
from ba_tpu.parallel.pipeline import (
    COUNTER_NAMES,
    fresh_copy as _fresh,
    make_key_schedule,
    pipeline_megastep,
    round_keys,
)
from ba_tpu.parallel.sweep import agreement_step


def _reference_rounds(key, state, rounds, batch, m=1):
    """The blocking round-by-round driver under the SAME key schedule."""
    step = jax.jit(agreement_step, static_argnames=("m", "max_liars"))
    keys_fn = jax.jit(round_keys, static_argnums=1)
    decisions, hists = [], []
    for r in range(rounds):
        keys = keys_fn(make_key_schedule(key, r), batch)
        out = step(keys, state, m=m)
        decisions.append(np.asarray(out["decision"]))
        hists.append(np.asarray(out["histogram"]))
    return np.stack(decisions), np.stack(hists)


def test_pipeline_matches_blocking_driver_bit_exact():
    B, cap, R = 48, 16, 9
    key = jr.key(7)
    state = make_sweep_state(jr.key(0), B, cap, order=ATTACK)
    want_dec, want_hist = _reference_rounds(key, _fresh(state), R, B)
    out = pipeline_sweep(
        key, state, R, depth=2, rounds_per_dispatch=1,
        collect_decisions=True,
    )
    np.testing.assert_array_equal(out["decisions"], want_dec)
    np.testing.assert_array_equal(out["histograms"], want_hist)
    # Honest-leader sweep sanity: every round's histogram covers the batch.
    assert (out["histograms"].sum(axis=1) == B).all()


def test_megastep_and_unroll_do_not_change_results():
    # K rounds per dispatch (lax.scan megastep) with unroll, plus a ragged
    # remainder dispatch: pure scheduling — results stay bit-identical.
    B, cap, R = 32, 8, 10
    key = jr.key(11)
    state = make_sweep_state(jr.key(1), B, cap, order=RETREAT)
    want_dec, want_hist = _reference_rounds(key, _fresh(state), R, B)
    for kpd, unroll, depth in ((4, 2, 1), (3, 3, 2), (10, 1, 3)):
        out = pipeline_sweep(
            key, _fresh(state), R,
            depth=depth, rounds_per_dispatch=kpd, unroll=unroll,
            collect_decisions=True,
        )
        np.testing.assert_array_equal(out["decisions"], want_dec)
        np.testing.assert_array_equal(out["histograms"], want_hist)
        assert out["stats"]["dispatches"] == -(-R // kpd)


def test_pipeline_eig_m2():
    # The m>1 EIG path threads through the same engine.
    B, cap, R = 16, 8, 4
    key = jr.key(13)
    state = make_sweep_state(
        jr.key(2), B, cap, min_n=8, max_traitor_frac=0.25, order=ATTACK
    )
    want_dec, _ = _reference_rounds(key, _fresh(state), R, B, m=2)
    out = pipeline_sweep(key, state, R, m=2, collect_decisions=True)
    np.testing.assert_array_equal(out["decisions"], want_dec)
    # OM(2) validity: honest leader + t <= n/4 decides the order every round.
    assert (out["histograms"][:, 1] == B).all()


def test_donation_consumes_inputs_and_returns_live_state():
    B, cap, R = 16, 8, 5
    key = jr.key(17)
    state = make_sweep_state(jr.key(3), B, cap, order=ATTACK)
    sched = make_key_schedule(key)
    out_state, out_sched, hists = pipeline_megastep(state, sched, rounds=R)
    # Donated inputs are deleted: any further use must raise.  (The
    # reads below are the POINT of the test — the same defect class
    # ba-lint's BA201 proves statically — hence the suppressions.)
    assert state.faulty.is_deleted()  # ba-lint: disable=BA201
    assert sched.key_data.is_deleted()  # ba-lint: disable=BA201
    # The exception TYPE depends on jit-cache temperature (a cold
    # jnp.add raises RuntimeError at trace time; a warmed one surfaces
    # the runtime's deleted-buffer ValueError) — the contract under
    # test is only that use-after-donate RAISES.
    with pytest.raises((RuntimeError, ValueError)):
        _ = state.faulty + 0  # ba-lint: disable=BA201
    with pytest.raises((RuntimeError, ValueError)):
        _ = sched.counter + 0  # ba-lint: disable=BA201
    # The returned pair is live and carries the thread forward.
    assert int(out_sched.counter) == R
    assert hists.shape == (R, 3)
    out2 = pipeline_sweep(key, out_state, 2)
    assert out2["histograms"].shape == (2, 3)


def test_caller_key_survives_donation():
    # make_key_schedule copies the key data: the caller's key must stay
    # usable even though the schedule it seeded was donated.
    key = jr.key(19)
    state = make_sweep_state(jr.key(4), 8, 8)
    pipeline_sweep(key, state, 3)
    jr.fold_in(key, 0)  # would raise RuntimeError if donated


def test_depth_k_inflight_no_intermediate_blocking(monkeypatch):
    # The engine must never call block_until_ready (its only sync is the
    # depth-delayed retire fetch), and the retire schedule must show k
    # dispatches genuinely in flight before the first fetch.
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 8, 8, 7, 3
    state = make_sweep_state(jr.key(5), B, cap)
    events = []
    out = pipeline_sweep(
        jr.key(23), state, R,
        depth=depth, rounds_per_dispatch=1,
        on_event=lambda kind, i: events.append((kind, i)),
    )
    dispatches = [i for kind, i in events if kind == "dispatch"]
    retires = [i for kind, i in events if kind == "retire"]
    assert dispatches == list(range(R))
    assert retires == list(range(R))  # FIFO, all retired by return
    # Steady state: retire r happens only after dispatch r + depth — the
    # in-flight window is full before the engine ever blocks.
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(("dispatch", r + depth))
    assert out["stats"]["dispatches"] == R
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["retires_before_drain"] == R - depth


def test_depth_k_no_blocking_with_instrumentation_enabled(monkeypatch):
    # ISSUE 2 acceptance (extended by ISSUE 4): the observability
    # layer's only added work is clock reads + in-memory appends — with
    # tracing, the registry, AND the on-device agreement counters all
    # live, the engine still never calls block_until_ready and the
    # dispatch/retire schedule is unchanged (depth dispatches genuinely
    # in flight before the first retire fetch; counter rows piggyback
    # the existing retire fetch).
    from ba_tpu import obs
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.obs.trace import Tracer

    monkeypatch.setattr(obs.trace, "_default", Tracer(enabled=True))
    monkeypatch.setattr(obs.registry, "_default", MetricsRegistry())

    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 8, 8, 7, 3
    state = make_sweep_state(jr.key(55), B, cap)
    events = []
    out = pipeline_sweep(
        jr.key(56), state, R,
        depth=depth, rounds_per_dispatch=1, with_counters=True,
        on_event=lambda kind, i: events.append((kind, i)),
    )
    assert [i for kind, i in events if kind == "dispatch"] == list(range(R))
    assert [i for kind, i in events if kind == "retire"] == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    assert out["stats"]["max_in_flight"] == depth + 1
    # And the instrumentation actually observed the run.
    tracer = obs.default_tracer()
    names = [e["name"] for e in tracer.chrome_events()]
    assert names.count("retire") == R
    assert names.count("compile") + names.count("dispatch") == R
    snap = obs.default_registry().snapshot()
    assert snap["pipeline_dispatch_latency_s"]["count"] == R
    assert snap["pipeline_depth_occupancy"]["count"] == R


def test_on_device_counters_bit_match_host_derivation():
    # ISSUE 4: the counter block folded inside the compiled scan must
    # bit-match the same counts derived ON THE HOST from the blocking
    # reference driver's decisions/majorities streams — and enabling it
    # must not change a single decision bit.
    B, cap, R = 32, 8, 7
    key = jr.key(71)
    state = make_sweep_state(jr.key(70), B, cap, order=ATTACK)
    # Flip half the leaders faulty so equivocation and quorum failures
    # actually occur (make_sweep_state keeps leaders honest by default).
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )

    # Host derivation from the round-by-round reference driver.
    step = jax.jit(agreement_step, static_argnames=("m", "max_liars"))
    keys_fn = jax.jit(round_keys, static_argnums=1)
    alive = np.asarray(state.alive)
    faulty = np.asarray(state.faulty)
    leader = np.asarray(state.leader)
    lieutenants = alive & (np.arange(cap)[None, :] != leader[:, None])
    traitor_present = (faulty & alive).any(axis=1)
    want = np.zeros(len(COUNTER_NAMES), np.int64)
    ref_decisions = []
    for r in range(R):
        out = step(keys_fn(make_key_schedule(key, r), B), state, m=1)
        dec = np.asarray(out["decision"])
        maj = np.asarray(out["majorities"])
        ref_decisions.append(dec)
        want[0] += (dec == UNDEFINED).sum()
        want[1] += int((dec == dec[0]).all())
        mmax = np.where(lieutenants, maj, -127).max(axis=1)
        mmin = np.where(lieutenants, maj, 127).min(axis=1)
        disagree = (mmax != mmin) & lieutenants.any(axis=1)
        want[2] += (disagree & traitor_present).sum()

    out = pipeline_sweep(
        key, _fresh(state), R,
        depth=2, rounds_per_dispatch=3,
        collect_decisions=True, with_counters=True,
    )
    np.testing.assert_array_equal(out["decisions"], np.stack(ref_decisions))
    got = np.array([out["counters"][name] for name in COUNTER_NAMES])
    np.testing.assert_array_equal(got, want)
    # The per-round rows are cumulative and end at the final block.
    rows = out["counters_per_round"]
    assert rows.shape == (R, len(COUNTER_NAMES))
    assert (np.diff(rows, axis=0) >= 0).all()
    np.testing.assert_array_equal(rows[-1], want)
    # Sanity: faulty leaders actually exercised the failure counters
    # (no batch-unanimous rounds under this split, by construction).
    assert want[0] > 0 and want[2] > 0, want
    assert want[1] == 0

    # An honest OM(1) sweep with t <= n/4 decides the order everywhere:
    # every round is batch-unanimous, nothing fails quorum.
    honest = make_sweep_state(
        jr.key(74), B, cap, min_n=8, max_traitor_frac=0.25, order=ATTACK
    )
    out_h = pipeline_sweep(jr.key(75), honest, 4, with_counters=True)
    assert out_h["counters"]["unanimous_rounds"] == 4
    assert out_h["counters"]["quorum_failures"] == 0


def test_counters_continue_across_engine_runs():
    # final_counters continues the thread: head + tail == full run.
    B, cap = 16, 8
    key = jr.key(73)
    state = make_sweep_state(jr.key(72), B, cap, order=ATTACK)
    state = dataclasses.replace(
        state, faulty=state.faulty.at[: B // 2, 0].set(True)
    )
    full = pipeline_sweep(key, _fresh(state), 6, with_counters=True)
    head = pipeline_sweep(key, _fresh(state), 3, with_counters=True)
    tail = pipeline_megastep(
        head["final_state"],
        head["final_schedule"],
        rounds=3,
        counters=head["final_counters"],
    )
    np.testing.assert_array_equal(
        np.asarray(tail[-1])[-1],
        np.array([full["counters"][n] for n in COUNTER_NAMES]),
    )


def test_pipeline_host_work_overlaps_dispatches():
    # host_work runs once per dispatch, after it is queued and before the
    # engine may block on a retire — the metrics-emission overlap hook.
    state = make_sweep_state(jr.key(6), 8, 8)
    order = []
    out = pipeline_sweep(
        jr.key(29), state, 4,
        depth=2, rounds_per_dispatch=2,
        host_work=lambda d: order.append(("work", d)),
        on_event=lambda kind, i: order.append((kind, i)),
    )
    assert [e for e in order if e[0] == "work"] == [("work", 0), ("work", 1)]
    # Each dispatch's host work precedes any retire the same iteration does.
    assert order.index(("work", 0)) < order.index(("retire", 0))
    assert out["stats"]["dispatches"] == 2


def test_pipeline_mesh_composes_bit_exact(eight_devices):
    # ISSUE 8: the shard_map scan core must not change a single bit of
    # the results at equal shapes — decisions, histograms, AND the
    # counter block (per-shard on device, tree-reduced at retire; the
    # unanimity verdict crosses shards via the in-scan psum).
    mesh = make_mesh((8, 1), ("data", "node"))
    key = jr.key(31)
    state = make_sweep_state(jr.key(7), 64, 16, order=ATTACK)
    plain = pipeline_sweep(
        key, _fresh(state), 6, rounds_per_dispatch=3,
        collect_decisions=True, with_counters=True,
    )
    sharded = pipeline_sweep(
        key, state, 6, rounds_per_dispatch=3, collect_decisions=True,
        with_counters=True, mesh=mesh,
    )
    np.testing.assert_array_equal(plain["decisions"], sharded["decisions"])
    np.testing.assert_array_equal(plain["histograms"], sharded["histograms"])
    np.testing.assert_array_equal(
        plain["counters_per_round"], sharded["counters_per_round"]
    )
    assert plain["counters"] == sharded["counters"]
    assert sharded["stats"]["shards"] == 8
    # The live continuation block is per-shard [d, C]; its shard sum is
    # the canonical block.
    assert sharded["final_counters"].shape == (8, len(COUNTER_NAMES))
    np.testing.assert_array_equal(
        np.asarray(sharded["final_counters"]).sum(axis=0),
        np.array([plain["counters"][n] for n in COUNTER_NAMES]),
    )
    # Per-device carry bytes genuinely shrink: the sharded carry's
    # per-device share is well under the whole single-device carry.
    assert (
        sharded["stats"]["carry_bytes_per_shard"]
        < plain["stats"]["carry_bytes_per_shard"]
    )


def test_pipeline_mesh_no_blocking_dispatch_count(eight_devices, monkeypatch):
    # ISSUE 8: the no-blocking dispatch-count proof re-run on a LIVE
    # 8x1 mesh with counters on — sharding must not introduce a host
    # sync anywhere (the per-shard blocks reduce inside the existing
    # retire fetch; the only in-scan collective is the device-side
    # histogram psum, invisible to the host schedule).
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    mesh = make_mesh((8, 1), ("data", "node"))
    B, cap, R, depth = 16, 8, 7, 3
    state = make_sweep_state(jr.key(5), B, cap)
    events = []
    out = pipeline_sweep(
        jr.key(23), state, R,
        depth=depth, rounds_per_dispatch=1, with_counters=True, mesh=mesh,
        on_event=lambda kind, i: events.append((kind, i)),
    )
    assert [i for kind, i in events if kind == "dispatch"] == list(range(R))
    assert [i for kind, i in events if kind == "retire"] == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(("dispatch", r + depth))
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["retires_before_drain"] == R - depth
    assert out["stats"]["shards"] == 8


def test_pipeline_mesh_validation_errors(eight_devices):
    mesh = make_mesh((8, 1), ("data", "node"))
    # Batch 12 cannot split 8 ways: eager, named error — never an XLA
    # shape failure after the carry entered the donation thread.
    state = make_sweep_state(jr.key(9), 12, 8)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_sweep(jr.key(0), state, 2, mesh=mesh)
    # A mesh without the engine's "data" axis names the problem too.
    odd = make_mesh((8,), ("model",))
    state = make_sweep_state(jr.key(9), 16, 8)
    with pytest.raises(ValueError, match="no 'data' axis"):
        pipeline_sweep(jr.key(0), state, 2, mesh=odd)


def test_pipeline_validates_arguments():
    state = make_sweep_state(jr.key(8), 8, 8)
    with pytest.raises(ValueError):
        pipeline_sweep(jr.key(0), state, 0)
    with pytest.raises(ValueError):
        pipeline_sweep(jr.key(0), state, 4, depth=0)
    with pytest.raises(ValueError):
        pipeline_sweep(jr.key(0), state, 4, rounds_per_dispatch=0)
    with pytest.raises(ValueError):
        pipeline_sweep(jr.key(0), state, 4, unroll=0)


def test_key_schedule_resume_midstream():
    # A schedule resumed at counter=r reproduces the tail of a full run:
    # the continuation contract behind final_schedule.
    B, cap = 24, 8
    key = jr.key(37)
    state = make_sweep_state(jr.key(9), B, cap, order=ATTACK)
    full = pipeline_sweep(key, _fresh(state), 6, collect_decisions=True)
    head = pipeline_sweep(key, _fresh(state), 3, collect_decisions=True)
    sched = head["final_schedule"]
    assert int(jax.device_get(sched.counter)) == 3
    tail_state, tail_sched, hists, decs = pipeline_megastep(
        head["final_state"], sched, rounds=3, collect_decisions=True
    )
    np.testing.assert_array_equal(
        np.asarray(decs), full["decisions"][3:]
    )
    np.testing.assert_array_equal(np.asarray(hists), full["histograms"][3:])


# -- runtime wiring (cluster/repl use the engine for multi-round runs) --------


def test_cluster_run_rounds_pipelined_matches_repl_format():
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, JaxBackend(platform="cpu"), seed=0)
    out = []
    assert handle_command(cluster, "run-rounds attack 5", out.append)
    assert out[:4] == [
        "G1, primary, majority=attack, state=NF",
        "G2, secondary, majority=attack, state=NF",
        "G3, secondary, majority=attack, state=NF",
        "G4, secondary, majority=attack, state=NF",
    ]
    assert out[4] == (
        "Execute order: attack! Non-faulty nodes in the system"
        " - 3 out of 4 quorum suggests attack"
    )
    assert out[5] == "Rounds: 5 - attack=5, retreat=0, undefined=0"
    assert cluster._round == 5  # future seeds advance past the whole run


def test_cluster_run_rounds_fallback_py_backend():
    from ba_tpu.runtime.backends import PyBackend
    from ba_tpu.runtime.cluster import Cluster

    cluster = Cluster(4, PyBackend(), seed=0)
    res, counts, stats = cluster.actual_order_rounds("retreat", 3)
    assert res.decision == "retreat"
    assert counts == {"attack": 0, "retreat": 3, "undefined": 0}
    assert stats is None  # sequential fallback, no pipeline stats
    assert cluster._round == 3


def test_cluster_run_rounds_noncanonical_command_takes_quirk_path():
    # A non-attack/retreat order hits the leader raw-string parity quirk
    # (ba.py:284-285), which the device quorum cannot represent — the
    # cluster must take the sequential path so the per-general block and
    # the decision tally stay quirk-exact (and mutually consistent).
    from ba_tpu.runtime.backends import JaxBackend, PyBackend
    from ba_tpu.runtime.cluster import Cluster

    jx = Cluster(4, JaxBackend(platform="cpu"), seed=0)
    res, counts, stats = jx.actual_order_rounds("charge", 2)
    assert stats is None  # sequential fallback, not the pipeline
    py = Cluster(4, PyBackend(), seed=0)
    want, want_counts, _ = py.actual_order_rounds("charge", 2)
    assert counts == want_counts
    assert res.decision == want.decision
    # The leader's printed majority is the raw string in both.
    assert res.per_general[0][2] == "charge" == want.per_general[0][2]


def test_cluster_run_rounds_emits_overlapped_metrics(tmp_path):
    import json

    from ba_tpu.utils import metrics
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster

    sink = tmp_path / "metrics.jsonl"
    old = metrics._default
    metrics._default = metrics.MetricsSink(str(sink))
    try:
        cluster = Cluster(4, JaxBackend(platform="cpu"), seed=0)
        res, counts, stats = cluster.actual_order_rounds("attack", 20)
    finally:
        metrics._default = old
    assert stats is not None and stats["dispatches"] >= 2
    records = [json.loads(l) for l in sink.read_text().splitlines()]
    per_dispatch = [r for r in records if r["event"] == "pipeline_dispatch"]
    summary = [r for r in records if r["event"] == "agreement_rounds_pipelined"]
    assert len(per_dispatch) == stats["dispatches"]
    assert len(summary) == 1 and summary[0]["rounds"] == 20
    assert summary[0]["decision_counts"] == counts
