"""OM(m)/EIG properties: reduction to OM(1), IC1/IC2 guarantees."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from ba_tpu.core import ATTACK, RETREAT, make_state, eig_agreement
from ba_tpu.core.eig import eig_round


def test_m0_trusts_leader():
    state = make_state(4, 4, order=ATTACK)
    maj = np.asarray(eig_round(jr.key(0), state, 0))
    assert np.all(maj == ATTACK)


def test_m1_matches_om1_no_faults():
    from ba_tpu.core import om1_round

    state = make_state(8, 5, order=RETREAT, leader=1)
    a = np.asarray(eig_round(jr.key(0), state, 1))
    b = np.asarray(om1_round(jr.key(0), state))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("seed", range(4))
def test_m1_one_traitor_matches_om1_properties(seed):
    # Same guarantees as OM(1): validity with 1 faulty lieutenant, n=4.
    faulty = jnp.zeros((32, 4), bool).at[:, 3].set(True)
    state = make_state(32, 4, order=ATTACK, faulty=faulty)
    maj = np.asarray(eig_round(jr.key(seed), state, 1))
    assert np.all(maj[:, :3] == ATTACK)


@pytest.mark.parametrize("seed", range(3))
def test_om3_n10_validity(seed):
    # BASELINE config #2: OM(3), n=10, 3 traitor lieutenants, honest leader.
    # IC2 validity: every honest lieutenant decides the leader's order.
    faulty = jnp.zeros((8, 10), bool).at[:, [3, 6, 9]].set(True)
    state = make_state(8, 10, order=ATTACK, faulty=faulty)
    out = eig_agreement(jr.key(seed), state, 3)
    maj = np.asarray(out["majorities"])
    honest = [0, 1, 2, 4, 5, 7, 8]
    assert np.all(maj[:, honest] == ATTACK)
    # Quorum: 7 honest ATTACK majorities out of 10 voters, needed = 7.
    assert np.all(np.asarray(out["needed"]) == 7)
    assert np.all(np.asarray(out["decision"]) == ATTACK)


@pytest.mark.parametrize("seed", range(3))
def test_om2_faulty_leader_agreement(seed):
    # IC1 with a faulty *leader* and one faulty lieutenant, n=7, m=2:
    # n > 3m so all honest lieutenants must agree on some common value.
    faulty = jnp.zeros((16, 7), bool).at[:, [0, 4]].set(True)
    state = make_state(16, 7, order=ATTACK, faulty=faulty)
    maj = np.asarray(eig_round(jr.key(seed), state, 2))
    honest = [1, 2, 3, 5, 6]
    assert np.all(maj[:, honest] == maj[:, honest][:, :1])


@pytest.mark.parametrize("n", [2, 3])
@pytest.mark.parametrize("m", [2, 3])
def test_deep_recursion_tiny_cluster_matches_om1(n, m):
    # n < m+2 runs out of relays: the resolve must fall back to the OM(0)
    # base case, not report a spurious tie (matches OM(1) on honest nodes).
    from ba_tpu.core import om1_round

    state = make_state(4, n, order=ATTACK)
    deep = np.asarray(eig_round(jr.key(0), state, m))
    om1 = np.asarray(om1_round(jr.key(0), state))
    assert np.array_equal(deep, om1)


def test_dead_relays_excluded():
    alive = jnp.ones((4, 6), bool).at[:, 5].set(False)
    state = make_state(4, 6, order=RETREAT, alive=alive)
    out = eig_agreement(jr.key(2), state, 2)
    assert np.all(np.asarray(out["total"]) == 5)
    assert np.all(np.asarray(out["decision"]) == RETREAT)


# -- fused deepest level vs the dense path ------------------------------------


def _with_env(key, val):
    import os

    class _Ctx:
        def __enter__(self):
            self.old = os.environ.get(key)
            os.environ[key] = val

        def __exit__(self, *a):
            if self.old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = self.old

    return _Ctx()


def test_fused_deepest_no_traitors_bit_exact():
    # Zero traitors => no coins anywhere => the fused einsum/Binomial path
    # must equal the dense path bit-for-bit despite different key splits.
    for m, n in ((2, 12), (3, 7)):
        state = make_state(32, n, order=ATTACK)
        with _with_env("BA_TPU_EIG_FUSED", "0"):
            want = np.asarray(eig_round(jr.key(3), state, m))
        with _with_env("BA_TPU_EIG_FUSED", "1"):
            got = np.asarray(eig_round(jr.key(3), state, m))
        np.testing.assert_array_equal(got, want)


def test_fused_deepest_equivocating_leader_histograms():
    # The genuinely stochastic regime: a faulty LEADER equivocates, faulty
    # lieutenants lie per path — per-general majority histograms from the
    # fused path must sit in the dense path's 6-sigma band (the tallies
    # have identical joint law: Binomial(k, 1/2) == sum of k fair coins).
    B, n, m = 4096, 9, 2
    faulty = np.zeros((B, n), bool)
    faulty[:, 0] = True  # the leader equivocates
    faulty[:, 4] = True
    state = make_state(B, n, order=ATTACK, faulty=jnp.asarray(faulty))
    with _with_env("BA_TPU_EIG_FUSED", "0"):
        want = np.asarray(eig_round(jr.key(4), state, m))
    with _with_env("BA_TPU_EIG_FUSED", "1"):
        got = np.asarray(eig_round(jr.key(5), state, m, 2))
    band = 6 * np.sqrt(B * n)
    h_want = np.bincount(want.ravel(), minlength=3)
    h_got = np.bincount(got.ravel(), minlength=3)
    assert (np.abs(h_want - h_got) < band).all(), (h_want, h_got)
    # repeated-digit degenerate paths exist at m=2 depth-1? depth m-1=1 has
    # none; exercise m=3 (depth-2 paths include (j,j)) the same way.
    B3, n3 = 2048, 6
    faulty = np.zeros((B3, n3), bool)
    faulty[:, 0] = True
    faulty[:, 3] = True
    state = make_state(B3, n3, order=ATTACK, faulty=jnp.asarray(faulty))
    with _with_env("BA_TPU_EIG_FUSED", "0"):
        want = np.asarray(eig_round(jr.key(6), state, 3))
    with _with_env("BA_TPU_EIG_FUSED", "1"):
        got = np.asarray(eig_round(jr.key(7), state, 3, 2))
    band = 6 * np.sqrt(B3 * n3)
    h_want = np.bincount(want.ravel(), minlength=3)
    h_got = np.bincount(got.ravel(), minlength=3)
    assert (np.abs(h_want - h_got) < band).all(), (h_want, h_got)


def test_binomial_half_exact_moments_and_bounds():
    from ba_tpu.core.eig import _binomial_half

    k = jnp.asarray([0, 1, 31, 32, 33, 64])
    for t in range(3):
        d = np.asarray(_binomial_half(jr.key(t), k, 64))
        assert d[0] == 0 and (d >= 0).all() and (d <= np.asarray(k)).all()
    ks = jnp.full((20000,), 8)
    draws = np.asarray(_binomial_half(jr.key(9), ks, 8))
    assert abs(draws.mean() - 4) < 0.06 and abs(draws.var() - 2) < 0.12
