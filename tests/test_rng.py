"""Packed-coin RNG helpers (ba_tpu/core/rng.py).

These back every fault coin in the framework (the vectorised analogue of
the reference's per-call ``random.randint``, ba.py:44-49) and the collapsed
relay's Bernoulli thresholds, so their distributional claims are pinned
here exactly where the docstrings make them.
"""

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core.rng import coin_bits, make_key, or_coin_threshold8, uniform_u8


def test_coin_bits_shape_dtype_determinism():
    a = coin_bits(jr.key(0), (7, 13), bool)
    assert a.shape == (7, 13) and a.dtype == jnp.bool_
    b = coin_bits(jr.key(0), (7, 13), bool)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = coin_bits(jr.key(1), (7, 13), bool)
    assert (np.asarray(a) != np.asarray(c)).any()


def test_coin_bits_fair():
    coins = np.asarray(coin_bits(jr.key(2), (1 << 20,), jnp.int32))
    assert set(np.unique(coins)) <= {0, 1}
    # 4-sigma band for a fair coin at 2^20 draws: 0.5 +- 0.002.
    assert abs(coins.mean() - 0.5) < 0.002


def test_uniform_u8_range_and_uniformity():
    u = np.asarray(uniform_u8(jr.key(3), (1 << 20,)))
    assert u.dtype == np.int32
    assert u.min() >= 0 and u.max() <= 255
    counts = np.bincount(u, minlength=256)
    # Each byte value: n*p = 4096 expected, sigma ~ 64; allow 6 sigma.
    assert (np.abs(counts - 4096) < 6 * 64).all()


def test_or_threshold8_exact_small_k():
    k = jnp.arange(0, 12)
    t = np.asarray(or_coin_threshold8(k, jnp.ones_like(k, bool)))
    for kk in range(9):  # exact in 256ths for k <= 8
        assert t[kk] == 256 - (256 >> kk), (kk, t[kk])
        assert t[kk] / 256 == 1.0 - 2.0 ** -kk
    assert (t[9:] == 256).all()  # saturation: fire always, error 2^-k


def test_or_threshold8_gate_and_large_k():
    k = jnp.asarray([0, 1, 5, 40, 1000])  # large k must not hit shift UB
    gated = np.asarray(or_coin_threshold8(k, jnp.zeros_like(k, bool)))
    assert (gated == 0).all()
    open_ = np.asarray(or_coin_threshold8(k, jnp.ones_like(k, bool)))
    assert open_[0] == 0 and (open_[3:] == 256).all()


def test_make_key_default_is_threefry(monkeypatch):
    # Default impl must stay threefry2x32: recorded artifacts and the
    # differential tests depend on cross-backend-deterministic streams.
    monkeypatch.delenv("BA_TPU_RNG", raising=False)
    a = np.asarray(jr.key_data(make_key(7)))
    b = np.asarray(jr.key_data(jr.key(7)))
    np.testing.assert_array_equal(a, b)


def test_make_key_rbg_draws_are_uniform(monkeypatch):
    # The BA_TPU_RNG=rbg bench knob: every packed-draw helper must keep its
    # distributional contract on the RngBitGenerator substrate too.
    monkeypatch.setenv("BA_TPU_RNG", "rbg")
    key = make_key(11)
    coins = np.asarray(coin_bits(key, (1 << 20,), jnp.int32))
    assert set(np.unique(coins)) <= {0, 1}
    assert abs(coins.mean() - 0.5) < 0.002  # 4 sigma at 2^20
    u = np.asarray(uniform_u8(jr.fold_in(key, 1), (1 << 20,)))
    assert u.min() >= 0 and u.max() <= 255
    counts = np.bincount(u, minlength=256)
    assert (np.abs(counts - 4096) < 6 * 64).all()
    # fold_in/split derivation stays usable (and distinct) on rbg keys.
    k1, k2 = jr.split(key)
    assert (
        np.asarray(coin_bits(k1, (128,), jnp.int32))
        != np.asarray(coin_bits(k2, (128,), jnp.int32))
    ).any()


def test_make_key_rejects_unknown_impl(monkeypatch):
    monkeypatch.setenv("BA_TPU_RNG", "definitely-not-an-impl")
    with pytest.raises(ValueError):
        make_key(0)
    # unsafe_rbg weakens split/fold_in derivation; the allowlist keeps the
    # docstring's "deliberately not offered" contract honest.
    monkeypatch.setenv("BA_TPU_RNG", "unsafe_rbg")
    with pytest.raises(ValueError):
        make_key(0)


def test_threshold_draw_realizes_bernoulli():
    # End-to-end: P(uniform_u8 < T(k)) ~ 1 - 2^-k within binomial noise.
    n = 1 << 18
    for kk in (1, 3, 8):
        t = int(or_coin_threshold8(jnp.asarray(kk), jnp.asarray(True)))
        u = np.asarray(uniform_u8(jr.fold_in(jr.key(4), kk), (n,)))
        p_hat = (u < t).mean()
        p = 1 - 2.0 ** -kk
        sigma = (p * (1 - p) / n) ** 0.5
        assert abs(p_hat - p) < 6 * max(sigma, 1e-4), (kk, p_hat, p)
