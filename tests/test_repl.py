"""Golden-output REPL tests: byte-identical strings vs SURVEY.md section 3.1."""

import subprocess
import sys

import pytest

from ba_tpu.runtime.backends import PyBackend
from ba_tpu.runtime.cluster import Cluster
from ba_tpu.runtime.repl import handle_command


def drive(cluster, lines):
    out = []
    for line in lines:
        if not handle_command(cluster, line, out.append):
            break
    return out


@pytest.fixture()
def cluster4():
    return Cluster(4, PyBackend(), seed=0)


def test_g_state_initial(cluster4):
    assert drive(cluster4, ["g-state"]) == [
        "G1, primary, state=NF",
        "G2, secondary, state=NF",
        "G3, secondary, state=NF",
        "G4, secondary, state=NF",
    ]


def test_actual_order_all_honest(cluster4):
    assert drive(cluster4, ["actual-order attack"]) == [
        "G1, primary, majority=attack, state=NF",
        "G2, secondary, majority=attack, state=NF",
        "G3, secondary, majority=attack, state=NF",
        "G4, secondary, majority=attack, state=NF",
        "Execute order: attack! Non-faulty nodes in the system"
        " - 3 out of 4 quorum suggests attack",
    ]


def test_g_state_set_faulty_drops_role_column(cluster4):
    assert drive(cluster4, ["g-state 2 faulty"]) == [
        "G1, state=NF",
        "G2, state=F",
        "G3, state=NF",
        "G4, state=NF",
    ]


def test_actual_order_one_faulty_lieutenant(cluster4):
    # Deterministic regardless of the traitor's coins: every lieutenant
    # tallies its own true order plus at least one honest peer.
    out = drive(cluster4, ["g-state 2 faulty", "actual-order retreat"])
    assert out[4:] == [
        "G1, primary, majority=retreat, state=NF",
        "G2, secondary, majority=retreat, state=F",
        "G3, secondary, majority=retreat, state=NF",
        "G4, secondary, majority=retreat, state=NF",
        "Execute order: retreat! 1 faulty node(s) in the system"
        " - 3 out of 4 quorum suggests retreat",
    ]


def test_kill_add_list_and_reelection(cluster4):
    out = drive(
        cluster4,
        ["g-kill 1", "List", "g-add 2", "List", "actual-order attack"],
    )
    assert out == [
        "P2, True",
        "P3, False",
        "P4, False",
        "P2, True",
        "P3, False",
        "P4, False",
        "P5, False",
        "P6, False",
        "G2, primary, majority=attack, state=NF",
        "G3, secondary, majority=attack, state=NF",
        "G4, secondary, majority=attack, state=NF",
        "G5, secondary, majority=attack, state=NF",
        "G6, secondary, majority=attack, state=NF",
        "Execute order: attack! Non-faulty nodes in the system"
        " - 3 out of 5 quorum suggests attack",
    ]


def test_raw_command_string_passthrough(cluster4):
    # The leader reports the raw string as its majority (ba.py:284-285);
    # lieutenants tally non-"attack" as retreat (ba.py:163-167).
    out = drive(cluster4, ["actual-order foo"])
    assert out == [
        "G1, primary, majority=foo, state=NF",
        "G2, secondary, majority=retreat, state=NF",
        "G3, secondary, majority=retreat, state=NF",
        "G4, secondary, majority=retreat, state=NF",
        "Execute order: retreat! Non-faulty nodes in the system"
        " - 3 out of 4 quorum suggests retreat",
    ]


def test_single_general_undefined_quorum():
    # n=1 with a non-attack/retreat order: the leader's raw majority buckets
    # as undefined, total=1, needed=1 -> "cannot be determined" line
    # (ba.py:225-255 with the total==1 override).
    cluster = Cluster(1, PyBackend(), seed=0)
    out = drive(cluster, ["actual-order foo"])
    assert out == [
        "G1, primary, majority=foo, state=NF",
        "Execute order: cannot be determined - not enough generals in the"
        " system! Non-faulty nodes in the system - 1 out of 1 quorum not"
        " consistent",
    ]


def test_guarded_edges_do_not_crash(cluster4):
    # Unknown ids, empty args, unknown commands, empty cluster (reference
    # crashes on some of these: SURVEY.md Q4).
    out = drive(
        cluster4,
        [
            "g-kill",
            "g-kill 99",
            "g-state 99 faulty",
            "g-add",
            "nonsense",
            "",
            "actual-order",
            "g-kill 1",
            "g-kill 2",
            "g-kill 3",
            "g-kill 4",
            "List",
            "actual-order attack",
            "g-state",
        ],
    )
    assert out == []


def test_exit_stops_loop(cluster4):
    out = drive(cluster4, ["Exit", "g-state"])
    assert out == []


def test_faulty_leader_election_not_disturbed(cluster4):
    # Fault injection never triggers re-election (election is for life,
    # ba.py:124-125); only death does.
    drive(cluster4, ["g-state 1 faulty"])
    assert cluster4.leader_id == 1
    drive(cluster4, ["g-kill 1"])
    assert cluster4.leader_id == 2


def test_cli_subprocess_py_backend():
    """End-to-end through the real launcher contract (stdin -> stdout)."""
    script = "g-state\nactual-order attack\nExit\n"
    proc = subprocess.run(
        [sys.executable, "-m", "ba_tpu.runtime.main", "3", "--backend", "py"],
        input=script,
        capture_output=True,
        text=True,
        timeout=120,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.splitlines() == [
        "G1, primary, state=NF",
        "G2, secondary, state=NF",
        "G3, secondary, state=NF",
        "G1, primary, majority=attack, state=NF",
        "G2, secondary, majority=attack, state=NF",
        "G3, secondary, majority=attack, state=NF",
        "Execute order: attack! Non-faulty nodes in the system"
        " - 2 out of 3 quorum suggests attack",
    ]
