"""Real multi-process ``jax.distributed`` integration (VERDICT r2 missing #2).

The reference joins processes over TCP (discover_leader, ba.py:86-102);
this framework's join is ``jax.distributed.initialize`` + a global mesh.
Until now ``make_global_mesh``'s multi-host branch only ever ran in its
single-process degenerate form; here two OS processes with 4 virtual CPU
devices each form a global (4, 2) mesh over gloo and run the node-sharded
SM round and the sharded sweep.  The (4, 2) mesh shape matches the
single-process 8-device run exactly, so every per-(data, node)-shard PRNG
fold is identical and the decisions must agree bit-for-bit.
"""

import json
import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = pathlib.Path(__file__).parent / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_results(tmp_path_factory):
    out = tmp_path_factory.mktemp("multihost") / "out.json"
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker provisions its own 4-device flag
    # Script-by-path puts tests/ on sys.path, not the repo root.
    repo_root = str(WORKER.parent.parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(i), "2", str(port), str(out)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(WORKER.parent.parent),
        )
        for i in range(2)
    ]
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out (distributed join hung?)")
        logs.append(stdout)
    for p, log in zip(procs, logs):
        if p.returncode != 0 and (
            "Multiprocess computations aren't implemented" in log
        ):
            # jaxlib builds without multiprocess CPU collectives (e.g.
            # 0.4.x) cannot run the two-process gloo mesh at all — an
            # environment incapability, not a framework regression (the
            # single-process 8-device mesh tests still cover the sharded
            # code paths bit-for-bit).
            pytest.skip("jaxlib lacks multiprocess CPU collectives")
        assert p.returncode == 0, f"worker failed:\n{log}"
    with open(out) as f:
        return json.load(f)


def test_two_process_mesh_matches_single_process(worker_results, eight_devices):
    import jax.random as jr
    from jax.sharding import PartitionSpec as P

    from ba_tpu.core import ATTACK, make_state
    from ba_tpu.parallel import (
        eig_node_sharded,
        make_mesh,
        om1_node_sharded,
        put_global,
        sm_node_sharded,
    )
    from ba_tpu.parallel.sweep import make_sweep_state, sharded_sweep

    mesh = make_mesh((4, 2), ("data", "node"))

    B, n = 16, 8
    faulty = np.zeros((B, n), bool)
    faulty[:, 3] = True
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    received = np.full((B, n), int(ATTACK), np.int8)
    out_sm = sm_node_sharded(
        mesh,
        jr.key(7),
        state,
        2,
        received=put_global(mesh, received, P("data", None)),
        collapsed=True,
    )
    np.testing.assert_array_equal(
        np.asarray(out_sm["decision"]), np.asarray(worker_results["sm_decision"])
    )

    out_sm2 = sm_node_sharded(mesh, jr.key(10), state, 2, collapsed=True)
    np.testing.assert_array_equal(
        np.asarray(out_sm2["decision"]),
        np.asarray(worker_results["sm_default_r1_decision"]),
    )

    out_om = om1_node_sharded(mesh, jr.key(11), state)
    np.testing.assert_array_equal(
        np.asarray(out_om["decision"]),
        np.asarray(worker_results["om1_decision"]),
    )
    out_eig = eig_node_sharded(mesh, jr.key(12), state, 2)
    np.testing.assert_array_equal(
        np.asarray(out_eig["decision"]),
        np.asarray(worker_results["eig_decision"]),
    )

    sweep_state = make_sweep_state(jr.key(8), 32, 16)
    out_sw = sharded_sweep(mesh, jr.key(9), sweep_state)
    np.testing.assert_array_equal(
        np.asarray(out_sw["decision"]),
        np.asarray(worker_results["sweep_decision"]),
    )
    np.testing.assert_array_equal(
        np.asarray(out_sw["histogram"]),
        np.asarray(worker_results["sweep_histogram"]),
    )
