"""Sign-ahead lane tests (ISSUE 14): the pipelined signed SM(m)
protocol.

The contract under test, layer by layer:

- the pipelined signed sweep is BIT-EXACT with the blocking sequential
  signed driver under the same key schedule and round tables
  (decisions / histograms / counters — the counters cross-checked
  against an independent host numpy derivation);
- the no-blocking dispatch-count proof holds with the sign-ahead lane
  live (host signing + verify dispatch in the overlap slot add no
  synchronization);
- signed carries checkpoint and resume bit-exactly, and a carry never
  crosses protocols;
- signed cohorts serve coalesced with per-slot parity (batched ≡
  alone, bit-identical), and the serving cohort key separates signed
  and m>=2 traffic while one service front-end serves them
  concurrently;
- engine selection: explicit kernel requests on signed raise eagerly,
  env/auto preferences fall back counted;
- the warmup lattice covers the signed axis.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from ba_tpu.core.state import SimState  # noqa: E402
from ba_tpu.core.types import COMMAND_DTYPE  # noqa: E402
from ba_tpu.parallel.pipeline import (  # noqa: E402
    SIGNED_COUNTER_NAMES,
    coalesced_sweep,
    fresh_copy,
    load_carry_checkpoint,
    pipeline_sweep,
)
from ba_tpu.parallel.signing import sequential_signed_sweep  # noqa: E402
from ba_tpu.parallel.sweep import make_sweep_state  # noqa: E402


def churn_state(batch, cap, *, faulty_leaders=True, seed=3):
    """A sweep state with (optionally) half the leaders faulty, so the
    commander-equivocation verdicts actually fire."""
    state = make_sweep_state(jr.key(seed), batch, cap)
    if faulty_leaders:
        faulty = np.asarray(state.faulty).copy()
        leader = np.asarray(state.leader)
        for b in range(0, batch, 2):
            faulty[b, leader[b]] = True
        state = SimState(
            state.order, state.leader, jnp.asarray(faulty),
            state.alive, state.ids,
        )
    return state


def alone_state(n, faulty, order, cap):
    f = np.zeros((1, cap), bool)
    a = np.zeros((1, cap), bool)
    a[0, :n] = True
    for i in faulty:
        f[0, i] = True
    return fresh_copy(
        SimState(
            order=jnp.asarray(np.full(1, order, np.int8).astype(COMMAND_DTYPE)),
            leader=jnp.zeros(1, jnp.int32),
            faulty=jnp.asarray(f),
            alive=jnp.asarray(a),
            ids=jnp.asarray(
                np.tile(np.arange(1, cap + 1, dtype=np.int32), (1, 1))
            ),
        )
    )


# -- encoders -----------------------------------------------------------------


def test_round_table_msgs_match_per_call_encoder():
    from ba_tpu.crypto import signed as cs

    msgs = cs._round_table_msgs(5, 7, 2, base=3)
    for b in range(5):
        for v in range(2):
            assert msgs[b, v].tobytes() == cs.round_message(3 + b, 7, v)
    # Distinct domain separator: a round-bound message can never equal
    # a round-free table message, whatever the ids.
    assert cs.round_message(0, 0, 0)[:4] != cs.order_message(0, 0)[:4]


def test_sign_round_tables_round_binding():
    from ba_tpu.crypto.signed import commander_keys, sign_round_tables

    sks, pks = commander_keys(2, seed=1)
    m0, s0 = sign_round_tables(sks, pks, 0)
    m1, s1 = sign_round_tables(sks, pks, 1)
    # The round is bound INTO the message, so both bytes differ — a
    # round-free table would make per-round signing a no-op recompute.
    assert not np.array_equal(m0, m1)
    assert not np.array_equal(s0, s1)


# -- bit-exactness vs the sequential driver -----------------------------------


@pytest.mark.parametrize("collapsed", [False, True])
def test_signed_pipeline_bit_exact_vs_sequential(collapsed):
    state = churn_state(8, 8)
    key = jr.key(11)
    ref = sequential_signed_sweep(key, state, 9, m=2, collapsed=collapsed)
    out = pipeline_sweep(
        key, fresh_copy(state), 9, signed=True, m=2, collapsed=collapsed,
        depth=2, rounds_per_dispatch=4, collect_decisions=True,
    )
    np.testing.assert_array_equal(out["histograms"], ref["histograms"])
    np.testing.assert_array_equal(out["decisions"], ref["decisions"])
    # The sequential driver derives its counters INDEPENDENTLY on host
    # (numpy over the fetched streams) — this cross-checks the in-scan
    # verdict formulas, not just the schedule.
    assert out["counters"] == ref["counters"]
    # The campaign actually exercised the signed verdicts.
    assert out["counters"]["commander_equivocations"] > 0
    assert out["stats"]["signed"] is True
    assert out["stats"]["sign_ahead_s"] > 0
    assert list(out["counters"]) == list(SIGNED_COUNTER_NAMES)


def test_signed_counters_continue_across_dispatches():
    state = churn_state(6, 8)
    key = jr.key(21)
    one = pipeline_sweep(
        key, fresh_copy(state), 8, signed=True, rounds_per_dispatch=8,
    )
    many = pipeline_sweep(
        key, fresh_copy(state), 8, signed=True, rounds_per_dispatch=3,
    )
    # Chunking is invisible: cumulative counter rows and totals match.
    assert one["counters"] == many["counters"]
    np.testing.assert_array_equal(
        one["counters_per_round"], many["counters_per_round"]
    )


# -- no-blocking proof with the lane live -------------------------------------


def test_signed_no_blocking_dispatch_count(monkeypatch):
    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    B, cap, R, depth = 4, 8, 7, 3
    state = churn_state(B, cap)
    events = []
    out = pipeline_sweep(
        jr.key(23), state, R, signed=True,
        depth=depth, rounds_per_dispatch=1,
        on_event=lambda kind, i: events.append((kind, i)),
    )
    dispatches = [i for kind, i in events if kind == "dispatch"]
    retires = [i for kind, i in events if kind == "retire"]
    assert dispatches == list(range(R))
    assert retires == list(range(R))
    # The in-flight window fills before the engine ever blocks — with
    # the sign-ahead lane staging every window in between.
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [("dispatch", i) for i in range(depth + 1)]
    for r in range(R - depth):
        assert events.index(("retire", r)) > events.index(
            ("dispatch", r + depth)
        )
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["stats"]["sign_ahead_s"] > 0


# -- checkpoint / resume ------------------------------------------------------


def test_signed_checkpoint_resume_bit_exact(tmp_path):
    p = str(tmp_path / "ck_{round}.npz")
    state = churn_state(6, 8)
    key = jr.key(9)
    full = pipeline_sweep(
        key, fresh_copy(state), 12, signed=True, m=2,
        rounds_per_dispatch=3, collect_decisions=True,
        checkpoint_every=6, checkpoint_path=p,
    )
    ck = load_carry_checkpoint(p.replace("{round}", "6"))
    assert ck.signed is True and ck.round == 6
    res = pipeline_sweep(
        None, None, 12, signed=True, m=2,
        rounds_per_dispatch=3, collect_decisions=True, resume=ck,
    )
    np.testing.assert_array_equal(res["histograms"], full["histograms"][6:])
    np.testing.assert_array_equal(res["decisions"], full["decisions"][6:])
    assert res["counters"] == full["counters"]
    # Resume from the PATH form too (the load-in-wrapper route).
    res2 = pipeline_sweep(
        None, None, 12, signed=True, m=2,
        rounds_per_dispatch=3, collect_decisions=True,
        resume=p.replace("{round}", "6"),
    )
    np.testing.assert_array_equal(res2["histograms"], full["histograms"][6:])


def test_signed_checkpoint_never_crosses_protocols(tmp_path):
    p = str(tmp_path / "ck_{round}.npz")
    pipeline_sweep(
        jr.key(5), churn_state(4, 8), 6, signed=True,
        rounds_per_dispatch=3, checkpoint_every=3, checkpoint_path=p,
    )
    ck = load_carry_checkpoint(p.replace("{round}", "3"))
    with pytest.raises(ValueError, match="protocol"):
        pipeline_sweep(
            None, None, 6, rounds_per_dispatch=3, with_counters=True,
            resume=ck,
        )
    # ...and the other direction: an oral carry never enters the lane.
    p2 = str(tmp_path / "oral_{round}.npz")
    pipeline_sweep(
        jr.key(6), make_sweep_state(jr.key(7), 4, 8), 6,
        with_counters=True, rounds_per_dispatch=3,
        checkpoint_every=3, checkpoint_path=p2,
    )
    ck2 = load_carry_checkpoint(p2.replace("{round}", "3"))
    with pytest.raises(ValueError, match="protocol"):
        pipeline_sweep(
            None, None, 6, signed=True, rounds_per_dispatch=3, resume=ck2,
        )


# -- serving: coalesced parity + cohort separation ----------------------------


def test_signed_coalesced_parity():
    cap = 4
    reqs = [(4, (2,), 1, 11), (3, (), 0, 12), (4, (0, 3), 1, 13)]
    rows = [alone_state(n, f, o, cap) for n, f, o, s in reqs]
    batched = fresh_copy(
        SimState(*[
            jnp.concatenate([getattr(s, fld) for s in rows])
            for fld in ("order", "leader", "faulty", "alive", "ids")
        ])
    )
    co = coalesced_sweep(
        [jr.key(s) for n, f, o, s in reqs], batched, 5,
        rounds_per_dispatch=2, signed=True, m=2,
    )
    assert co["counter_names"] == list(SIGNED_COUNTER_NAMES)
    for i, (n, f, o, s) in enumerate(reqs):
        alone = pipeline_sweep(
            jr.key(s), alone_state(n, f, o, cap), 5,
            signed=True, m=2, rounds_per_dispatch=2,
            collect_decisions=True,
        )
        np.testing.assert_array_equal(
            co["decisions"][:, i], alone["decisions"][:, 0]
        )
        got = dict(
            zip(co["counter_names"], (int(v) for v in co["counters"][i]))
        )
        assert got == alone["counters"]
        solo = coalesced_sweep(
            [jr.key(s)], alone_state(n, f, o, cap), 5,
            rounds_per_dispatch=2, signed=True, m=2,
        )
        np.testing.assert_array_equal(
            co["majorities"][i], solo["majorities"][0]
        )


def test_signed_cohort_key_separation():
    from ba_tpu.runtime.serve import AgreementRequest, cohort_key

    a = AgreementRequest(kind="run-rounds", n=4, rounds=4, seed=1)
    b = AgreementRequest(kind="run-rounds", n=4, rounds=4, seed=2, m=2)
    c = AgreementRequest(
        kind="run-rounds", n=4, rounds=4, seed=3, signed=True
    )
    d = AgreementRequest(
        kind="run-rounds", n=4, rounds=4, seed=4, signed=True, m=2
    )
    keys = [cohort_key(r) for r in (a, b, c, d)]
    assert len(set(keys)) == 4  # m and signed separate INDEPENDENTLY
    # The m dial defaults through the service's config, so an explicit
    # m equal to the default coalesces with the default.
    assert cohort_key(a, "xla", 2) == cohort_key(b)
    # Signed scenario requests are invalid eagerly.
    from ba_tpu.runtime.serve import validate_request
    from ba_tpu.scenario import from_dict

    spec = from_dict({"name": "s", "rounds": 2, "events": []})
    with pytest.raises(ValueError, match="signed"):
        validate_request(
            AgreementRequest(kind="scenario", n=4, spec=spec, signed=True)
        )
    with pytest.raises(ValueError, match="m="):
        validate_request(
            AgreementRequest(kind="run-rounds", n=4, rounds=2, m=0)
        )


def test_service_serves_mixed_protocol_cohorts():
    from ba_tpu.obs.registry import MetricsRegistry
    from ba_tpu.runtime.serve import (
        AgreementRequest,
        AgreementService,
        ServeConfig,
    )

    svc = AgreementService(
        ServeConfig(
            max_batch=4, max_queue=16, coalesce_window_s=0.2,
            rounds_per_dispatch=2,
        ),
        registry=MetricsRegistry(),
    )
    svc.start()
    reqs = [
        AgreementRequest(kind="run-rounds", n=4, faulty=(2,), seed=31,
                         rounds=4),
        AgreementRequest(kind="run-rounds", n=4, faulty=(2,), seed=31,
                         rounds=4, signed=True),
        AgreementRequest(kind="run-rounds", n=4, faulty=(1,), seed=32,
                         rounds=4, signed=True),
        AgreementRequest(kind="run-rounds", n=4, faulty=(), seed=33,
                         rounds=4, m=2),
    ]
    tickets = [svc.submit(r) for r in reqs]
    outs = [t.result(timeout=600) for t in tickets]
    try:
        # The two signed requests coalesced into ONE batch; the oral and
        # the m=2 request each dispatched alone — protocols never share
        # a batch, yet one front-end served all three cohorts.
        assert outs[1]["batch"] == 2 and outs[2]["batch"] == 2
        assert outs[0]["batch"] == 1 and outs[3]["batch"] == 1
        assert "sig_rejections" in outs[1]["counters"]
        # Per-request parity through the service: each signed result is
        # bit-identical to its own alone run at equal padded capacity.
        for req, out in zip(reqs[1:3], outs[1:3]):
            alone = pipeline_sweep(
                jr.key(req.seed),
                alone_state(req.n, req.faulty, 1, 4), 4,
                signed=True, rounds_per_dispatch=2,
                collect_decisions=True,
            )
            assert out["decisions"] == [
                int(v) for v in alone["decisions"][:, 0]
            ]
            assert out["counters"] == alone["counters"]
    finally:
        svc.stop()


# -- engine selection ---------------------------------------------------------


def test_signed_engine_rules():
    state = churn_state(4, 8)
    with pytest.raises(ValueError, match="signed"):
        pipeline_sweep(
            jr.key(1), fresh_copy(state), 2, signed=True, engine="pallas"
        )
    with pytest.raises(ValueError, match="signed"):
        coalesced_sweep(
            [jr.key(1)], alone_state(4, (), 1, 4), 2, signed=True,
            engine="interpret",
        )
    # auto prefers the kernel but falls back COUNTED for signed.
    out = pipeline_sweep(
        jr.key(2), fresh_copy(state), 2, signed=True, engine="auto",
    )
    assert out["stats"]["engine"] == "xla"
    assert "signed" in out["stats"]["engine_fallback"]
    # The signed/scenario/mesh combos error eagerly.
    with pytest.raises(ValueError, match="scenario"):
        from ba_tpu.scenario import compile_scenario, from_dict

        spec = from_dict({"name": "x", "rounds": 2, "events": []})
        pipeline_sweep(
            jr.key(3), fresh_copy(state), 2, signed=True,
            scenario=compile_scenario(spec, 4, 8),
        )
    with pytest.raises(ValueError, match="collapsed"):
        pipeline_sweep(jr.key(4), fresh_copy(state), 2, collapsed=True)
    with pytest.raises(ValueError, match="collapsed"):
        coalesced_sweep(
            [jr.key(5)], alone_state(4, (), 1, 4), 2, collapsed=True
        )


# -- the interactive backend --------------------------------------------------


def test_backend_signed_run_rounds_matches_sequential_driver():
    from ba_tpu.runtime.backends import JaxBackend

    class G:
        def __init__(self, i, faulty=False):
            self.id = i
            self.faulty = faulty
            self.alive = True

    gens = [G(1), G(2, True), G(3), G(4)]
    be = JaxBackend(protocol="sm", m=1, signed=True)
    majorities, decisions, stats = be.run_rounds(gens, 0, 1, 42, 6)
    assert stats["signed"] is True
    assert list(stats["counters"]) == list(SIGNED_COUNTER_NAMES)
    # The backend's padded B=1 state under the same key/sign-seed: the
    # sequential driver's last-round majorities must match the
    # backend's recompute (schedule + lane determinism, end to end).
    state = be._make_state(gens, 0, 1)
    ref = sequential_signed_sweep(jr.key(42), state, 6, m=1)
    assert majorities == [int(v) for v in ref["majorities"][0, :4]]
    assert decisions == [int(v) for v in ref["decisions"][:, 0]]
    assert stats["counters"] == ref["counters"]


def test_repl_signed_run_rounds_prints_lane_line():
    from ba_tpu.runtime.backends import JaxBackend
    from ba_tpu.runtime.cluster import Cluster
    from ba_tpu.runtime.repl import handle_command

    cluster = Cluster(4, JaxBackend(protocol="sm", m=1, signed=True), seed=0)
    lines = []
    handle_command(cluster, "run-rounds attack 4", lines.append)
    assert any(l.startswith("Rounds: 4") for l in lines)
    # The signed lane evidence line (additive; oral sessions never
    # print it).
    assert any(l.startswith("Signed lane:") for l in lines)
    oral = Cluster(4, JaxBackend(), seed=0)
    lines2 = []
    handle_command(oral, "run-rounds attack 2", lines2.append)
    assert not any(l.startswith("Signed lane:") for l in lines2)


# -- observability ------------------------------------------------------------


def test_sign_ahead_records_and_gauges(tmp_path):
    from ba_tpu import obs
    from ba_tpu.utils import metrics as _metrics

    path = str(tmp_path / "m.jsonl")
    sink = _metrics.configure(path)
    try:
        pipeline_sweep(
            jr.key(30), churn_state(4, 8), 6, signed=True,
            rounds_per_dispatch=2,
        )
        sink.close()
        import json

        recs = [
            json.loads(line)
            for line in open(path).read().splitlines()
            if line.strip()
        ]
        sa = [r for r in recs if r.get("event") == "sign_ahead"]
        assert len(sa) == 3  # one per staged window
        assert [(r["lo"], r["hi"]) for r in sa] == [(0, 2), (2, 4), (4, 6)]
        for r in sa:
            assert r["batch"] == 4 and r["values"] == 2
            assert r["table_bytes"] > 0 and r["wall_s"] >= 0
        reg = obs.default_registry()
        assert reg.get("host_sign_ahead_s").value > 0
        assert reg.get("pipeline_sign_ahead_windows_total").value >= 3
    finally:
        _metrics.configure(None)


# -- warmup covers the signed axis --------------------------------------------


def test_warmup_lattice_covers_signed_axis():
    from ba_tpu.runtime import warmup
    from ba_tpu.runtime.serve import ServeConfig

    plan = warmup.bucket_lattice(2, 4, signeds=(False, True))
    signed_rows = [a for fn, a in plan if a["signed"]]
    oral_rows = [a for fn, a in plan if not a["signed"]]
    assert signed_rows and oral_rows
    # Signed entries mirror the dispatch loop's reachable combinations:
    # XLA core only, never scenario.
    assert all(a["engine"] == "xla" for a in signed_rows)
    assert all(a["scenario"] is False for a in signed_rows)
    assert all("collapsed" in a for a in signed_rows + oral_rows)
    # The service plan covers the axis by default and trims on request.
    cfg = ServeConfig(max_batch=1, rounds_per_dispatch=2, warm=True)
    assert any(a["signed"] for _, a in warmup.service_plan(cfg))
    # The per-request m dial (cohort-key member) warms through warm_ms
    # — the config's own m always included, the overrides added.
    cfg_m = ServeConfig(
        max_batch=1, rounds_per_dispatch=2, warm=True, warm_ms=(2,)
    )
    ms = {a["m"] for _, a in warmup.service_plan(cfg_m)}
    assert ms == {1, 2}
    with pytest.raises(ValueError, match="warm_ms"):
        ServeConfig(max_batch=1, warm_ms=(0,))
    cfg_off = ServeConfig(
        max_batch=1, rounds_per_dispatch=2, warm=True, warm_signed=False
    )
    assert not any(a["signed"] for _, a in warmup.service_plan(cfg_off))
    # The signed megastep has a registered AOT builder.
    assert "signed_megastep" in warmup.WARM_FNS
    from ba_tpu.parallel.pipeline import AOT_SPECS

    assert "signed_megastep" in AOT_SPECS


def test_signed_aot_warm_dispatch_bit_exact(tmp_path):
    from ba_tpu.obs import aotcache
    from ba_tpu.parallel.pipeline import AOT_SPECS

    axes = {
        "batch": 4, "capacity": 8, "rounds": 3, "m": 2,
        "collapsed": False, "unroll": 1, "collect_decisions": True,
        "signed": True, "engine": "xla",
    }
    cache = aotcache.ExecutableCache(str(tmp_path))
    cache.ensure("signed_megastep", axes, AOT_SPECS["signed_megastep"])
    state = churn_state(4, 8)
    ref = pipeline_sweep(
        jr.key(6), fresh_copy(state), 6, signed=True, m=2,
        rounds_per_dispatch=3, collect_decisions=True,
    )
    warm = pipeline_sweep(
        jr.key(6), fresh_copy(state), 6, signed=True, m=2,
        rounds_per_dispatch=3, collect_decisions=True, executables=cache,
    )
    np.testing.assert_array_equal(warm["decisions"], ref["decisions"])
    np.testing.assert_array_equal(warm["histograms"], ref["histograms"])
    assert warm["counters"] == ref["counters"]
    assert warm["stats"]["warm_dispatches"] == warm["stats"]["dispatches"]
    assert warm["stats"]["request_path_compiles"] == 0
