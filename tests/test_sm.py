"""SM(m) signed-message properties: IC1/IC2, adversary schedules, Ed25519.

The reference has only unsigned oral messages (ba.py:258-285); SM(m) is the
signed north-star upgrade.  These tests pin:

- IC2 validity: an honest commander's order is chosen by every honest
  lieutenant, regardless of traitor count.
- IC1 agreement: honest lieutenants agree whenever t <= m — including the
  boundary t = m with a faulty commander, the case the chain-length bound
  (sm.py) must get right.
- Beyond the guarantee (t = m + 1) a violating adversary schedule is
  *reachable* — the simulation is not secretly stronger than real SM(m).
- The Ed25519 integration: device-verified signature masks gate the V-sets
  (bad signatures are dropped; honest relay recovers the value when m >= 1).
"""

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from ba_tpu.core import ATTACK, RETREAT, UNDEFINED, make_state, sm_agreement, sm_round
from ba_tpu.crypto import oracle
from ba_tpu.crypto.signed import (
    commander_keys,
    host_publickey,
    host_sign,
    order_message,
    sign_received,
    sign_round1,
    signed_sm_agreement,
    verify_received,
)


def honest_lieutenants(state) -> np.ndarray:
    """[B, n] bool: alive, non-faulty, non-leader."""
    leader = np.asarray(state.leader)
    n = state.n
    is_leader = np.eye(n, dtype=bool)[leader]
    return np.asarray(state.alive) & ~np.asarray(state.faulty) & ~is_leader


def assert_ic1(choices: np.ndarray, honest: np.ndarray):
    """All honest lieutenants of each instance chose the same value."""
    big = np.where(honest, choices, 127)
    small = np.where(honest, choices, -1)
    lo = big.min(axis=1)
    hi = small.max(axis=1)
    has = honest.any(axis=1)
    bad = has & (lo != hi)
    assert not bad.any(), f"IC1 violated in instances {np.where(bad)[0][:10]}"


# -- IC2: honest commander ----------------------------------------------------


@pytest.mark.parametrize("m", [0, 1, 2])
def test_ic2_honest_commander_any_traitor_count(m):
    # Signatures make IC2 unconditional: the commander's signed order
    # reaches every general in round 1 and traitors cannot forge another.
    key = jr.key(10 + m)
    faulty = jr.bernoulli(jr.key(99), 0.4, (64, 6)).at[:, 0].set(False)
    state = make_state(64, 6, order=ATTACK, faulty=faulty)
    choices = np.asarray(sm_round(key, state, m))
    honest = honest_lieutenants(state)
    assert np.all(choices[honest] == ATTACK)


# -- IC1: agreement up to t = m ----------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_ic1_faulty_commander_t_eq_m1(seed):
    # m = 1, t = 1 (the commander): the exact case the chain-length bound
    # r < t protects — with the off-by-one (r <= t) this fails ~1.7% of
    # instances (ADVICE.md round 1).
    B = 4096
    faulty = jnp.zeros((B, 4), bool).at[:, 0].set(True)
    state = make_state(B, 4, order=ATTACK, faulty=faulty)
    choices = np.asarray(sm_round(jr.key(seed), state, 1))
    assert_ic1(choices, honest_lieutenants(state))


@pytest.mark.parametrize("seed", range(3))
def test_ic1_t_eq_m2_commander_plus_lieutenant(seed):
    B = 2048
    faulty = jnp.zeros((B, 5), bool).at[:, [0, 2]].set(True)
    state = make_state(B, 5, order=RETREAT, faulty=faulty)
    choices = np.asarray(sm_round(jr.key(seed), state, 2))
    assert_ic1(choices, honest_lieutenants(state))


@pytest.mark.parametrize("p", [0.1, 0.5, 0.9])
def test_ic1_adversarial_withhold_schedules(p):
    # Biased withholding coins across the full schedule space: IC1 must
    # hold for every schedule when t <= m, not just fair coins.
    B, n, m = 1024, 6, 2
    faulty = jnp.zeros((B, n), bool).at[:, [0, 3]].set(True)
    state = make_state(B, n, order=ATTACK, faulty=faulty)
    withhold = jr.bernoulli(jr.key(7), p, (m, B, n, n, 2))
    choices = np.asarray(sm_round(jr.key(3), state, m, withhold=withhold))
    assert_ic1(choices, honest_lieutenants(state))


def test_ic1_lieutenant_traitors_only(seed=0):
    # Honest commander with t = m faulty lieutenants is IC2 territory, but
    # check IC1 formally too on mixed faulty/alive masks.
    B = 1024
    faulty = jnp.zeros((B, 6), bool).at[:, [1, 4]].set(True)
    alive = jnp.ones((B, 6), bool).at[:, 5].set(False)
    state = make_state(B, 6, order=ATTACK, faulty=faulty, alive=alive)
    choices = np.asarray(sm_round(jr.key(seed), state, 2))
    honest = honest_lieutenants(state)
    assert_ic1(choices, honest)
    assert np.all(choices[honest] == ATTACK)


# -- beyond the guarantee: t = m + 1 violations are reachable -----------------


def test_ic1_violation_reachable_at_t_eq_m_plus_1():
    # m = 1, t = 2 (commander 0 + lieutenant 1), n = 4.  Crafted run:
    # commander utters RETREAT to honest 2 only (its signed send to 3 is
    # dropped via sig_valid — withholding); traitor 1 holds a signed ATTACK
    # and reveals it to general 3 only, in the single relay round (legal
    # chain: r = 1 < t = 2).  General 2 ends with {RETREAT} -> RETREAT;
    # general 3 ends with {RETREAT (from 2), ATTACK (from 1)} -> UNDEFINED.
    received = jnp.asarray([[RETREAT, ATTACK, RETREAT, RETREAT]], jnp.int8)
    sig_valid = jnp.asarray([[True, True, True, False]])
    faulty = jnp.asarray([[True, True, False, False]])
    state = make_state(1, 4, order=RETREAT, faulty=faulty)
    withhold = jnp.ones((1, 1, 4, 4, 2), bool)  # traitors send nothing...
    withhold = withhold.at[0, 0, 3, 1, ATTACK].set(False)  # ...except 1->3
    choices = np.asarray(
        sm_round(
            jr.key(0), state, 1,
            withhold=withhold, sig_valid=sig_valid, received=received,
        )
    )[0]
    assert choices[2] == RETREAT
    assert choices[3] == UNDEFINED  # two contradictory signed values


def test_chain_bound_blocks_coalition_late_reveal():
    # t = 1 (commander only), m = 2: the commander holds a signed ATTACK it
    # never uttered in round 1 — the chain bound (r < t = 1 never holds)
    # must keep it unrevealable in *any* relay round, so every lieutenant
    # sticks with RETREAT.
    received = jnp.asarray([[ATTACK, RETREAT, RETREAT, RETREAT]], jnp.int8)
    faulty = jnp.asarray([[True, False, False, False]])
    state = make_state(1, 4, order=ATTACK, faulty=faulty)
    withhold = jnp.zeros((2, 1, 4, 4, 2), bool)  # coalition sends eagerly
    choices = np.asarray(
        sm_round(jr.key(0), state, 2, withhold=withhold, received=received)
    )[0]
    # The commander's own seen-set contains ATTACK (its received slot) but
    # honest lieutenants never accept it: chains would need 2 traitors.
    assert np.all(choices[1:] == RETREAT)


# -- quorum layer -------------------------------------------------------------


def test_sm_agreement_quorum_outputs():
    B = 16
    faulty = jnp.zeros((B, 7), bool).at[:, 0].set(True)
    state = make_state(B, 7, order=ATTACK, faulty=faulty)
    out = sm_agreement(jr.key(1), state, 1)
    maj = np.asarray(out["majorities"])
    assert_ic1(maj, honest_lieutenants(state))
    total = np.asarray(out["total"])
    assert np.all(total == 7)
    # Honest lieutenants agree; whichever common value won, the quorum
    # counts must be consistent with the per-general majorities.
    for k, code in (("n_attack", ATTACK), ("n_retreat", RETREAT),
                    ("n_undefined", UNDEFINED)):
        assert np.array_equal(np.asarray(out[k]), (maj == code).sum(axis=1))


# -- Ed25519 integration ------------------------------------------------------

SIG_B, SIG_N = 2, 4  # one shape for every signed test -> one jit compile


def test_host_signer_matches_oracle():
    # The native (cryptography-wheel) host signer and the pure-Python
    # oracle must be byte-identical — Ed25519 is deterministic.
    sk, pk = oracle.keypair(b"host-signer")
    msg = order_message(3, 1)
    assert host_publickey(sk) == pk
    assert host_sign(sk, pk, msg) == oracle.sign(sk, pk, msg)


def test_dedup_verify_matches_full():
    # Verifying the per-(instance, value) tables once and gathering must
    # yield the same mask as verifying every general's copy, including
    # under commander equivocation (both values uttered).
    faulty = jnp.zeros((SIG_B, SIG_N), bool).at[:, 0].set(True)
    state = make_state(SIG_B, SIG_N, order=ATTACK, faulty=faulty)
    k2a, rec_a, sv_a = sign_round1(jr.key(6), state)
    k2b, rec_b, sv_b = sign_round1(jr.key(6), state, dedup_verify=True)
    np.testing.assert_array_equal(np.asarray(rec_a), np.asarray(rec_b))
    np.testing.assert_array_equal(np.asarray(sv_a), np.asarray(sv_b))
    assert np.all(np.asarray(sv_a))  # honestly-signed values all verify


def test_verify_received_matches_oracle():
    rng = np.random.default_rng(0)
    received = rng.integers(0, 2, (SIG_B, SIG_N))
    sks, pks = commander_keys(SIG_B, seed=5)
    corrupt = np.zeros((SIG_B, SIG_N), bool)
    corrupt[0, 1] = corrupt[1, 3] = True
    msgs, sigs = sign_received(sks, pks, received, corrupt)
    got = np.asarray(verify_received(pks, msgs, sigs))
    for b in range(SIG_B):
        for i in range(SIG_N):
            want = oracle.verify(
                pks[b].tobytes(), msgs[b, i].tobytes(), sigs[b, i].tobytes()
            )
            assert got[b, i] == want == (not corrupt[b, i])


def test_signed_agreement_honest_end_to_end():
    state = make_state(SIG_B, SIG_N, order=ATTACK)
    out = signed_sm_agreement(jr.key(2), state, 1)
    assert np.all(np.asarray(out["sig_valid"]))
    assert np.all(np.asarray(out["majorities"]) == ATTACK)
    assert np.all(np.asarray(out["decision"]) == ATTACK)


def test_corrupt_signature_dropped_no_relay():
    # m = 0: no relay rounds, so a recipient whose signature check fails
    # has an empty V -> UNDEFINED, everyone else follows the order.
    corrupt = np.zeros((SIG_B, SIG_N), bool)
    corrupt[:, 2] = True
    state = make_state(SIG_B, SIG_N, order=RETREAT)
    out = signed_sm_agreement(jr.key(3), state, 0, corrupt=corrupt)
    maj = np.asarray(out["majorities"])
    assert np.all(~np.asarray(out["sig_valid"])[:, 2])
    assert np.all(maj[:, 2] == UNDEFINED)
    assert np.all(maj[:, [1, 3]] == RETREAT)


def test_corrupt_signature_recovered_by_relay():
    # m = 1: honest peers relay the commander-signed value, so the victim
    # of the corrupted round-1 signature still decides correctly.
    corrupt = np.zeros((SIG_B, SIG_N), bool)
    corrupt[:, 2] = True
    state = make_state(SIG_B, SIG_N, order=RETREAT)
    out = signed_sm_agreement(jr.key(4), state, 1, corrupt=corrupt)
    assert np.all(np.asarray(out["majorities"]) == RETREAT)
