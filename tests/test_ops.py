"""Pallas kernels: differential tests against the jnp reference paths.

The plane-arithmetic bodies (ops/planes.py) are pure shape-agnostic jnp, so
they are tested directly on CPU against ba_tpu.crypto.field / ed25519, and
the ladder's pallas-specific plumbing (bit packing, tile layout) has CPU
unit tests; the assembled 512-step kernel is TPU-gated (run with
BA_TPU_TESTS_ON_TPU=1) because neither interpret mode (~5M interpreted
vector ops per tile) nor an XLA-CPU jit of the 2-point-add body (>9 min
compile; Mosaic does it in ~15 s) is practical on CPU.  The majority
kernel is one fused pass, cheap enough for interpret mode everywhere.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ba_tpu.crypto.ed25519 as E
import ba_tpu.crypto.field as F
from ba_tpu.core.quorum import strict_majority
from ba_tpu.core.types import ATTACK, RETREAT, UNDEFINED
from ba_tpu.ops import ladder, planes
from ba_tpu.ops.majority import masked_majority_rows


def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def _stack(plane_list):
    return jnp.stack(plane_list, axis=-1)


def _unstack(coord):
    return [coord[..., i] for i in range(F.LIMBS)]


# -- plane arithmetic vs field.py --------------------------------------------


def test_plane_mul_matches_field_mul():
    rng = np.random.default_rng(0)
    # Lazy operand range: one add/sub of carried values (field.py contract).
    a = rng.integers(-8000, 8000, (128, F.LIMBS)).astype(np.int32)
    b = rng.integers(-8000, 8000, (128, F.LIMBS)).astype(np.int32)
    got = _stack(planes.p_mul(_unstack(jnp.asarray(a)), _unstack(jnp.asarray(b))))
    ref = F.mul(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
    )


def test_plane_point_add_matches_ed25519():
    B = 32
    rng = np.random.default_rng(1)
    bits = jnp.asarray(rng.integers(0, 2, (B, 16)), jnp.int32)
    p = E.scalar_mult(E.base_point((B,)), bits)  # varied valid points
    q = E.point_add(p, p)
    ref = E.point_add(p, q)
    got = planes.p_point_add(
        tuple(_unstack(c) for c in p), tuple(_unstack(c) for c in q)
    )
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(
            np.asarray(F.canonical(_stack(g))), np.asarray(F.canonical(r))
        )


def test_plane_point_dbl_matches_point_add():
    # The dedicated doubling must equal add(P, P) as a group element (the
    # projective representation differs by design), with and without the
    # T coordinate; the with_t=False T planes must be exactly zero.
    B = 32
    rng = np.random.default_rng(7)
    bits = jnp.asarray(rng.integers(0, 2, (B, 16)), jnp.int32)
    p = E.scalar_mult(E.base_point((B,)), bits)
    ref = E.point_add(p, p)
    got = planes.p_point_dbl(tuple(_unstack(c) for c in p))
    got_pt = tuple(_stack(c) for c in got)
    assert bool(jnp.all(E.point_eq(got_pt, ref)))
    # T consistency: T == XY/Z  <=>  T * Z == X * Y.
    x, y, z, t = got_pt
    assert bool(jnp.all(F.eq(F.mul(t, z), F.mul(x, y))))
    got_not = planes.p_point_dbl(tuple(_unstack(c) for c in p), with_t=False)
    for g, g_t in zip(got[:3], got_not[:3]):
        np.testing.assert_array_equal(
            np.asarray(_stack(g)), np.asarray(_stack(g_t))
        )
    np.testing.assert_array_equal(np.asarray(_stack(got_not[3])), 0)


def test_plane_canonical_and_eq_match_field():
    # p_canonical/p_eq back the fused verify epilogue's projective
    # equality (ops/ladder._window_verify_kernel); pin them limb for limb
    # against field.canonical/eq on lazy/negative/edge inputs.
    rng = np.random.default_rng(9)
    a = rng.integers(-8000, 8000, (64, F.LIMBS)).astype(np.int32)
    a[0] = 0
    a[1] = F._np_limbs(F.P_INT - 1)
    a[2] = F._np_limbs(F.P_INT - 1) * 2  # 2p - 2: needs full reduction
    aj = jnp.asarray(a)
    got = _stack(planes.p_canonical(_unstack(aj)))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(F.canonical(aj)))
    b = np.array(a)
    b[4] += 7  # differ in one limb
    bj = jnp.asarray(b)
    got_eq = planes.p_eq(_unstack(aj), _unstack(bj))
    np.testing.assert_array_equal(np.asarray(got_eq), np.asarray(F.eq(aj, bj)))
    # same value, different lazy encodings: must compare equal
    shifted = _unstack(aj + jnp.asarray(F._np_limbs(F.P_INT)))
    assert bool(jnp.all(planes.p_eq(_unstack(aj), shifted)))


def test_sha512_mod_l_jnp_matches_bigints():
    # The fallback composition (the fused kernel's accept-set anchor):
    # digest-as-little-endian-int mod L, vs hashlib + Python bigints.
    import hashlib

    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.sha512 import sha512_mod_l

    rng = np.random.default_rng(19)
    msgs = rng.integers(0, 256, (8, 80)).astype(np.uint8)
    got = np.asarray(jax.jit(sha512_mod_l)(jnp.asarray(msgs)))
    for i in range(8):
        want = (
            int.from_bytes(hashlib.sha512(msgs[i].tobytes()).digest(), "little")
            % L
        )
        assert int.from_bytes(got[i].tobytes(), "little") == want, i


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_sha512_mod_l_fused_kernel_tpu():
    # On TPU sha512_mod_l routes through the FUSED sha+modl kernel; same
    # differential as the jnp test (interpret mode would run the 80
    # unrolled rounds under Python, like the plain sha kernel's policy).
    import hashlib

    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.sha512 import sha512_mod_l

    rng = np.random.default_rng(20)
    for B, ln in ((64, 80), (16, 200)):  # 1- and 2-block messages
        msgs = rng.integers(0, 256, (B, ln)).astype(np.uint8)
        got = np.asarray(jax.jit(sha512_mod_l)(jnp.asarray(msgs)))
        for i in range(B):
            want = (
                int.from_bytes(
                    hashlib.sha512(msgs[i].tobytes()).digest(), "little"
                )
                % L
            )
            assert int.from_bytes(got[i].tobytes(), "little") == want, (B, i)


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_window_verify_fused_matches_parts_tpu():
    # The fused verify tail (window mult + completion add + projective
    # eq in one kernel) against its composed parts, on valid AND
    # deliberately-failing lanes.
    from ba_tpu.ops.ladder import window_mult, window_verify

    rng = np.random.default_rng(21)
    B = 8
    bits = jnp.asarray(rng.integers(0, 2, (B, 256)), jnp.int32)
    a_pt = E.scalar_mult(E.base_point((B,)), jnp.asarray(
        rng.integers(0, 2, (B, 16)), jnp.int32))
    r_pt = E.scalar_mult(E.base_point((B,)), jnp.asarray(
        rng.integers(0, 2, (B, 16)), jnp.int32))
    ha = window_mult(a_pt, bits)
    right = E.point_add(r_pt, ha)
    want = np.asarray(E.point_eq(right, right))
    # left == the true sum on even lanes; a perturbed point on odd ones.
    wrong = E.point_add(right, E.base_point((B,)))
    odd = (np.arange(B) % 2) == 1
    left = tuple(
        jnp.where(jnp.asarray(odd)[:, None], w, r)
        for w, r in zip(wrong, right)
    )
    got = np.asarray(window_verify(a_pt, bits, r_pt, left))
    np.testing.assert_array_equal(got, ~odd & want)


# -- the ladder ---------------------------------------------------------------


def test_pack_bits_roundtrip():
    # The kernel's bit extraction is word = packed[t>>5]; bit = (word >>
    # (t & 31)) & 1 — replay it on the packed words and require the
    # original bit matrix back.
    B, nbits = 256, 512
    rng = np.random.default_rng(2)
    bits = rng.integers(0, 2, (B, nbits)).astype(np.int32)
    words = np.asarray(ladder._pack_bits(jnp.asarray(bits), B))
    words = words.reshape(nbits // 32, B).T  # [B, nw]
    for t in (0, 1, 31, 32, 63, 255, 511):
        got = (words[:, t >> 5] >> (t & 31)) & 1
        np.testing.assert_array_equal(got, bits[:, t])


def test_tile_layout_roundtrip():
    B = 1000  # deliberately not a multiple of the 1024-lane tile
    rng = np.random.default_rng(3)
    coord = jnp.asarray(rng.integers(-8000, 8000, (B, F.LIMBS)), jnp.int32)
    pad = -(-B // ladder.TILE) * ladder.TILE
    tiles = ladder._to_tiles(coord, pad)
    assert tiles.shape == (F.LIMBS, pad // ladder.LANES, ladder.LANES)
    back = ladder._from_tiles(tiles, B)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(coord))


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_window_mult_matches_scalar_mult_tpu():
    # The 4-bit-window kernel returns the same group element as the plain
    # ladder (different projective representation -> point_eq).
    B = 1024
    rng = np.random.default_rng(13)
    pbits = jnp.asarray(rng.integers(0, 2, (B, 16)), jnp.int32)
    pt = E.scalar_mult(E.base_point((B,)), pbits)
    kbits = jnp.asarray(rng.integers(0, 2, (B, 256)), jnp.int32)
    ref = ladder.scalar_mult(pt, kbits)
    got = ladder.window_mult(pt, kbits)
    assert np.asarray(E.point_eq(got, ref)).all()


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_ladder_pallas_matches_scalar_mult_tpu():
    B = 1024
    rng = np.random.default_rng(3)
    bits = jnp.asarray(rng.integers(0, 2, (B, 512)), jnp.int32)
    pt = E.base_point((B,))
    ref = jax.jit(E.scalar_mult)(pt, bits)
    got = ladder.scalar_mult(pt, bits)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(
            np.asarray(F.canonical(g)), np.asarray(F.canonical(r))
        )


# -- fixed-exponent pow chain -------------------------------------------------


def test_pow_planes_small_exponent_interpret():
    # Small exponent keeps interpret mode tractable on CPU (6 steps); the
    # packing/SMEM-word/select plumbing is identical at any size.
    from ba_tpu.ops.powchain import pow_planes

    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.integers(-8000, 8000, (8, F.LIMBS)), jnp.int32)
    for e in (1, 2, 37):
        got = pow_planes(a, e, interpret=not _on_tpu())
        ref = F.pow_const(a, e)
        np.testing.assert_array_equal(
            np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
        )


def test_sqrt_chain_algebra_matches_pow_const():
    # The addition-chain tower, instantiated with plain field ops on CPU:
    # pins the chain's algebra (z^(2^252-3)) without Mosaic.
    from ba_tpu.crypto.oracle import P
    from ba_tpu.ops.powchain import sqrt_chain

    rng = np.random.default_rng(15)
    a = jnp.asarray(rng.integers(0, 4096, (4, F.LIMBS)), jnp.int32)

    def sq_n(x, n):
        for _ in range(n):
            x = F.square(x)
        return x

    got = sqrt_chain(a, F.mul, sq_n)
    ref = F.pow_const(a, (P - 5) // 8)
    np.testing.assert_array_equal(
        np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
    )


def test_inv_chain_algebra_matches_pow_const():
    # The p-2 inversion chain (device signer's compress), instantiated
    # with plain field ops on CPU: pins the tower + z^11 epilogue algebra
    # without Mosaic.  The kernel plumbing it shares with the sqrt chain
    # (p_sq_n runs, limb writeback) is covered by the interpret test
    # above; the fused routing is pinned on hardware by the sign
    # differential in test_crypto.py running under BA_TPU_TESTS_ON_TPU.
    from ba_tpu.crypto.oracle import P
    from ba_tpu.ops.powchain import inv_chain

    rng = np.random.default_rng(16)
    a = jnp.asarray(rng.integers(0, 4096, (4, F.LIMBS)), jnp.int32)

    def sq_n(x, n):
        for _ in range(n):
            x = F.square(x)
        return x

    got = inv_chain(a, F.mul, sq_n)
    ref = F.pow_const(a, P - 2)
    np.testing.assert_array_equal(
        np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
    )


def test_pow_planes_sqrt_chain_kernel_interpret():
    # The production (p-5)/8 routing swaps in the addition-chain kernel;
    # cover the kernel plumbing (fori_loop squaring runs, limb writeback)
    # off-TPU via interpret mode — ~90 s, the price of not shipping a
    # TPU-only path untested (the algebra twin above is instant but does
    # not execute the kernel).
    from ba_tpu.crypto.oracle import P
    from ba_tpu.ops.powchain import pow_planes

    rng = np.random.default_rng(16)
    a = jnp.asarray(rng.integers(0, 4096, (8, F.LIMBS)), jnp.int32)
    got = pow_planes(a, (P - 5) // 8, interpret=not _on_tpu())
    ref = F.pow_const(a, (P - 5) // 8)
    np.testing.assert_array_equal(
        np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
    )


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_pow_planes_sqrt_exponent_tpu():
    from ba_tpu.crypto.oracle import P
    from ba_tpu.ops.powchain import pow_planes

    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.integers(0, 4096, (1024, F.LIMBS)), jnp.int32)
    e = (P - 5) // 8
    got = pow_planes(a, e)
    ref = jax.jit(lambda x: F.pow_const(x, e))(a)
    np.testing.assert_array_equal(
        np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
    )


# -- fixed-base point-add tree ------------------------------------------------


def _random_entries(B, seed):
    """[B, 64, 4, 22] of varied valid curve points (multiples of the base)."""
    rng = np.random.default_rng(seed)
    bits = jnp.asarray(rng.integers(0, 2, (B * 64, 16)), jnp.int32)
    pts = E.scalar_mult(E.base_point((B * 64,)), bits)
    return jnp.stack([c.reshape(B, 64, F.LIMBS) for c in pts], axis=2)


def _fold_ref(entries):
    acc = E.identity((entries.shape[0],))
    for w in range(64):
        acc = E.point_add(acc, tuple(entries[:, w, c] for c in range(4)))
    return acc


def test_treeadd_entries_layout_roundtrip():
    from ba_tpu.ops import treeadd

    B = 1000  # non-multiple of the 1024-lane tile: exercises pad + unpad
    entries = _random_entries(B, 9)
    pad = -(-B // ladder.TILE) * ladder.TILE
    coords = treeadd.entries_to_planes(entries, pad)
    for c in range(4):
        assert coords[c].shape == (64, F.LIMBS, pad // ladder.LANES, ladder.LANES)
        for w in (0, 13, 63):
            back = ladder._from_tiles(coords[c][w], B)
            np.testing.assert_array_equal(
                np.asarray(back), np.asarray(entries[:, w, c])
            )


def test_treeadd_pairing_order_matches_left_fold():
    # The kernel folds ((p0+p1)+(p2+p3))+... — same group element as the
    # left fold; pinned here at the jnp level with the tested point_add so
    # the TPU run only has to vouch for the Mosaic lowering.
    B = 16
    entries = _random_entries(B, 11)
    pts = [tuple(entries[:, w, c] for c in range(4)) for w in range(64)]
    while len(pts) > 1:
        pts = [E.point_add(pts[k], pts[k + 1]) for k in range(0, len(pts), 2)]
    assert np.asarray(E.point_eq(pts[0], _fold_ref(entries))).all()


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_treeadd_pallas_tpu_multi_tile():
    from ba_tpu.ops.treeadd import tree_point_add

    B = 1100  # non-multiple of the tile: padding + 2 grid tiles
    entries = _random_entries(B, 10)
    got = tree_point_add(entries)
    ref = _fold_ref(entries)
    assert np.asarray(E.point_eq(got, ref)).all()


# -- decompression core kernel ------------------------------------------------


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_decompress_core_matches_jnp():
    # The fused chain is too large for interpret-under-jit on CPU (the
    # XLA-CPU compile blows past 9 min); its pieces are CPU-covered
    # separately (plane ops, sqrt_chain algebra + interpret), and this
    # pins the fused kernel against the jnp formulation on hardware.
    from ba_tpu.crypto.oracle import P
    from ba_tpu.ops.decompress import decompress_core

    rng = np.random.default_rng(18)
    vals = [int.from_bytes(rng.bytes(32), "little") % P for _ in range(6)]
    ylimbs = jnp.asarray(
        np.stack([
            [(v >> (12 * i)) & 0xFFF for i in range(F.LIMBS)] for v in vals
        ]).astype(np.int32)
    )
    x, x_alt, vxx, u = decompress_core(ylimbs)
    one = jnp.broadcast_to(F.constant(1), ylimbs.shape)
    yy = F.square(ylimbs)
    u_ref = F.sub(yy, one)
    d = F.constant((-121665 * pow(121666, P - 2, P)) % P)
    v = F.carry(F.add(F.mul(yy, d), one))
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    t = F.pow_const(F.mul(u_ref, v7), (P - 5) // 8)
    x_ref = F.mul(F.mul(u_ref, v3), t)
    vxx_ref = F.mul(v, F.square(x_ref))
    for got, ref in ((x, x_ref), (vxx, vxx_ref), (u, u_ref)):
        np.testing.assert_array_equal(
            np.asarray(F.canonical(got)), np.asarray(F.canonical(ref))
        )
    sqrt_m1 = F.constant(pow(2, (P - 1) // 4, P))
    np.testing.assert_array_equal(
        np.asarray(F.canonical(x_alt)),
        np.asarray(F.canonical(F.mul(x_ref, sqrt_m1))),
    )


# -- mod-L reduction kernel ---------------------------------------------------


def test_modl_kernel_matches_jnp():
    # Interpret mode is cheap here (~2k vector ops); edges + random vs the
    # bigint-pinned jnp reduction.
    from ba_tpu.crypto.oracle import L
    from ba_tpu.crypto.scalar import reduce_mod_l
    from ba_tpu.ops.modl import reduce_mod_l_planes

    rng = np.random.default_rng(17)
    q = 2**512 // L
    vals = [0, 1, L - 1, L, L + 1, 2**252, 2**256, q * L - 1, q * L, 2**512 - 1]
    vals += [int.from_bytes(rng.bytes(64), "little") for _ in range(54)]
    by = jnp.asarray(
        np.stack([np.frombuffer(v.to_bytes(64, "little"), np.uint8) for v in vals])
    )
    a = np.asarray(jax.jit(reduce_mod_l)(by))
    b = np.asarray(reduce_mod_l_planes(by, interpret=not _on_tpu()))
    np.testing.assert_array_equal(a, b)


# -- sha512 kernel ------------------------------------------------------------


def test_sha512_word_tile_roundtrip():
    # The sha kernel reuses ladder's tile layout on a 32-plane word axis.
    rng = np.random.default_rng(8)
    B, nb = 1000, 2  # non-multiple of the tile to exercise the unpad
    w = jnp.asarray(
        rng.integers(0, 2**32, (B, nb * 16), dtype=np.uint64).astype(np.uint32)
    )
    pad = -(-B // ladder.TILE) * ladder.TILE
    tiles = ladder._to_tiles(w, pad)
    assert tiles.shape == (nb * 16, pad // 128, 128)
    back = ladder._from_tiles(tiles, B)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(w))


@pytest.mark.skipif(not _on_tpu(), reason="Mosaic kernel needs real TPU")
def test_sha512_kernel_matches_hashlib_tpu():
    # On TPU, sha512() routes through the unrolled Mosaic kernel
    # (use_pallas auto); differential vs hashlib, incl. a 2-block message.
    # (Interpret mode would execute ~10k ops per lane under Python; the
    # kernel's round functions are the jnp path's own, tested on CPU.)
    import hashlib

    from ba_tpu.crypto.sha512 import sha512

    rng = np.random.default_rng(7)
    for B, L in ((64, 80), (16, 200)):
        msgs = rng.integers(0, 256, (B, L)).astype(np.uint8)
        got = np.asarray(jax.jit(sha512)(jnp.asarray(msgs)))
        for i in range(B):
            assert got[i].tobytes() == hashlib.sha512(msgs[i].tobytes()).digest()


# -- masked majority reduce ---------------------------------------------------


def _majority_ref(answers, valid, fallback):
    att = ((answers == ATTACK) & valid).sum(axis=1)
    ret = ((answers == RETREAT) & valid).sum(axis=1)
    maj = strict_majority(jnp.asarray(att), jnp.asarray(ret))
    return np.where(valid.sum(axis=1) > 0, np.asarray(maj), fallback)


@pytest.mark.parametrize("R,K", [(64, 7), (300, 33), (256, 128)])
def test_masked_majority_matches_jnp(R, K):
    rng = np.random.default_rng(4)
    answers = rng.integers(0, 3, (R, K)).astype(np.int8)
    valid = rng.random((R, K)) < 0.6
    valid[:5] = False  # zero-eligible rows exercise the fallback
    fallback = rng.integers(0, 3, (R,)).astype(np.int8)
    got = masked_majority_rows(
        jnp.asarray(answers), jnp.asarray(valid), jnp.asarray(fallback),
        interpret=not _on_tpu(),
    )
    np.testing.assert_array_equal(
        np.asarray(got), _majority_ref(answers, valid, fallback)
    )


def test_masked_majority_ties_and_unanimity():
    answers = np.asarray(
        [[ATTACK, RETREAT, UNDEFINED, UNDEFINED],  # tie 1-1 -> UNDEFINED
         [ATTACK, ATTACK, RETREAT, ATTACK],        # attack
         [RETREAT, RETREAT, RETREAT, ATTACK]],     # retreat
        np.int8,
    )
    valid = np.ones_like(answers, bool)
    fallback = np.full((3,), ATTACK, np.int8)
    got = masked_majority_rows(
        jnp.asarray(answers), jnp.asarray(valid), jnp.asarray(fallback),
        interpret=not _on_tpu(),
    )
    assert got.tolist() == [UNDEFINED, ATTACK, RETREAT]


# -- fused signed-sweep step kernel ------------------------------------------


def _xla_sweep_step(key, state, ok, m):
    """The reference composition the kernel fuses (bench's one_bucket)."""
    import jax.random as jr

    from ba_tpu.core import sm_agreement
    from ba_tpu.core.om import round1_broadcast
    from ba_tpu.crypto.signed import sig_valid_from_tables

    k1, k2 = jr.split(key)
    received = round1_broadcast(k1, state)
    sig_valid = sig_valid_from_tables(ok, received)
    out = sm_agreement(k2, state, m, None, sig_valid, received, True)
    return out["decision"]


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sweep_step_matches_xla_no_traitors():
    # Zero traitors => no draw influences any value (thresholds are 0 and
    # honest-held flags drive everything), so the fused kernel must match
    # the XLA composition bit-for-bit despite different PRNG substrates.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 512, 256, 3
    state = make_sweep_state(jr.key(0), B, cap, max_traitor_frac=0.0)
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(1), state, ok, m))
    got = np.asarray(fused_signed_sweep_step(
        jnp.asarray([3], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sweep_step_invalid_signatures_undefined():
    # Both table signatures invalid => no value ever enters any V-set =>
    # every lieutenant chooses UNDEFINED; the (honest) leader still reports
    # its own order, so n_attack = 1 < needed and the quorum cannot decide.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 256, 128, 2
    state = make_sweep_state(jr.key(2), B, cap)
    ok = jnp.zeros((B, 2), bool)
    got = np.asarray(fused_signed_sweep_step(
        jnp.asarray([5], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    assert (got == UNDEFINED).all()


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sweep_step_honest_leader_validity():
    # SM validity with an honest signed commander is absolute: only the
    # one signed value can ever enter a V-set, so BOTH paths must decide
    # the ordered value on every instance regardless of traitor count —
    # deterministic despite the live relay draws (which run but cannot
    # change saturated V-sets).  Exact equality, no statistics needed.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 1024, 64, 3
    state = make_sweep_state(jr.key(4), B, cap, max_traitor_frac=0.2)
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(5), state, ok, m))
    got = np.asarray(fused_signed_sweep_step(
        jnp.asarray([6], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    np.testing.assert_array_equal(got, want)
    assert (got == ATTACK).all()


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sweep_step_faulty_leader_equivocates():
    # A faulty leader's equivocation coins come from the in-kernel PRNG:
    # with all-faulty leaders and no relay (m such that chains die), both
    # decisions and per-seed variability must behave.  t >= 1 instances
    # with a faulty leader can produce mixed decisions; assert the fused
    # kernel produces BOTH orders across instances (equivocation visible)
    # and decisions vary with the seed (live randomness).
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 2048, 32, 1
    state = make_sweep_state(jr.key(6), B, cap)
    faulty = np.array(state.faulty)  # np.asarray of a device array is read-only
    faulty[:, 0] = True  # leader lies per recipient (ba.py:268-273)
    state = type(state)(
        state.order, state.leader, jnp.asarray(faulty), state.alive, state.ids
    )
    ok = jnp.ones((B, 2), bool)
    d1 = np.asarray(fused_signed_sweep_step(
        jnp.asarray([7], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    d2 = np.asarray(fused_signed_sweep_step(
        jnp.asarray([8], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    assert len(np.unique(d1)) > 1  # equivocation produced mixed outcomes
    assert (d1 != d2).any()  # seed changes the coins


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sweep_step_histogram_matches_xla():
    # The genuinely stochastic regime: faulty leaders make outcomes
    # random, so compare DECISION HISTOGRAMS between the fused kernel and
    # the XLA composition over a large iid instance population.  Per-bin
    # counts are sums of B independent Bernoulli-ish indicators; a 6*sqrt(B)
    # band is > 6 sigma for any bin probability, so a pass is meaningful
    # and a distributional bug (wrong threshold, wrong chain gate, biased
    # draws) shows up as a multi-sigma bin shift.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 8192, 16, 2
    state = make_sweep_state(jr.key(8), B, cap)
    faulty = np.array(state.faulty)  # writable copy
    faulty[:, 0] = True  # every leader equivocates
    state = type(state)(
        state.order, state.leader, jnp.asarray(faulty), state.alive, state.ids
    )
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(9), state, ok, m))
    got = np.asarray(fused_signed_sweep_step(
        jnp.asarray([10], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m,
    ))
    h_want = np.bincount(want, minlength=3)
    h_got = np.bincount(got, minlength=3)
    band = 6 * np.sqrt(B)
    assert (np.abs(h_want - h_got) < band).all(), (h_want, h_got)


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_sharded_sweep_matches_unsharded():
    # The multi-chip composition on its 1-device degenerate mesh: axis
    # index 0 folds to the same seed, so the shard_map form must be
    # bit-identical to the plain kernel call.  (The >1-device case runs in
    # the same code path with disjoint shards + per-shard seeds; instances
    # are independent, so correctness does not couple across shards.)
    import jax.random as jr
    from jax.sharding import Mesh

    from ba_tpu.ops.sweep_step import (
        fused_sharded_sweep_step,
        fused_signed_sweep_step,
    )
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 512, 128, 3
    state = make_sweep_state(jr.key(11), B, cap)
    ok = jnp.ones((B, 2), bool)
    seed = jnp.asarray([21], jnp.int32)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    want = np.asarray(fused_signed_sweep_step(
        seed, state.order, state.leader, state.faulty, state.alive, ok, m,
    ))
    got = np.asarray(fused_sharded_sweep_step(
        mesh, seed, state.order, state.leader, state.faulty, state.alive,
        ok, m,
    ))
    np.testing.assert_array_equal(got, want)


def test_fused_multi_round_bounds():
    # 15 rounds pack per int32 column, one 128-lane column register caps
    # rounds at 1920; the wrapper must reject out-of-range values loudly
    # at trace time (CPU-safe: the check runs before the pallas_call is
    # built).
    from ba_tpu.ops.sweep_step import fused_signed_sweep_step

    o = jnp.zeros((8,), jnp.int8)
    ldr = jnp.zeros((8,), jnp.int32)
    f = jnp.zeros((8, 16), bool)
    ok = jnp.ones((8, 2), bool)
    for bad in (0, 1921):
        with pytest.raises(ValueError, match="rounds"):
            fused_signed_sweep_step(
                jnp.asarray([1], jnp.int32), o, ldr, f, f, ok, 1, bad
            )


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_multi_round_first_round_bit_compatible():
    # Round 0 of a rounds=K dispatch consumes the PRNG stream in exactly
    # the order the single-round kernel does, so column 0 must equal the
    # rounds=1 output bit-for-bit under the same seed.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 1024, 64, 3
    state = make_sweep_state(jr.key(30), B, cap)
    ok = jnp.ones((B, 2), bool)
    seed = jnp.asarray([31], jnp.int32)
    single = np.asarray(fused_signed_sweep_step(
        seed, state.order, state.leader, state.faulty, state.alive, ok, m,
    ))
    multi = np.asarray(fused_signed_sweep_step(
        seed, state.order, state.leader, state.faulty, state.alive, ok, m, 8,
    ))
    assert multi.shape == (B, 8)
    np.testing.assert_array_equal(multi[:, 0], single)


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_multi_round_matches_xla_no_traitors():
    # Zero traitors => draw-independent => EVERY round's column must match
    # the XLA composition bit-for-bit (the multi-round generalisation of
    # test_fused_sweep_step_matches_xla_no_traitors).
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m = 512, 256, 3
    state = make_sweep_state(jr.key(32), B, cap, max_traitor_frac=0.0)
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(33), state, ok, m))
    multi = np.asarray(fused_signed_sweep_step(
        jnp.asarray([34], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m, 6,
    ))
    for r in range(6):
        np.testing.assert_array_equal(multi[:, r], want)


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_multi_round_rounds_are_independent():
    # With equivocating leaders each round draws fresh coins, so columns
    # must differ across rounds (live per-round randomness, not a copied
    # round-0 result) while every column's histogram stays in the same
    # 6-sigma band as the XLA composition's.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m, R = 8192, 16, 2, 4
    state = make_sweep_state(jr.key(35), B, cap)
    faulty = np.array(state.faulty)
    faulty[:, 0] = True  # every leader equivocates
    state = type(state)(
        state.order, state.leader, jnp.asarray(faulty), state.alive, state.ids
    )
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(36), state, ok, m))
    multi = np.asarray(fused_signed_sweep_step(
        jnp.asarray([37], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m, R,
    ))
    h_want = np.bincount(want, minlength=3)
    band = 6 * np.sqrt(B)
    for r in range(R):
        h_got = np.bincount(multi[:, r], minlength=3)
        assert (np.abs(h_want - h_got) < band).all(), (r, h_want, h_got)
    assert any(
        (multi[:, r] != multi[:, 0]).any() for r in range(1, R)
    )  # fresh coins per round


@pytest.mark.skipif(not _on_tpu(), reason="in-kernel PRNG needs real TPU")
def test_fused_multi_round_multi_column():
    # rounds > 15 spill into additional packed output columns; with zero
    # traitors every one of the 35 columns (15+15+5 split) must match the
    # XLA composition bit-for-bit, which pins both the per-column packing
    # width and the cross-column round order.
    import jax.random as jr

    from ba_tpu.ops.sweep_step import fused_signed_sweep_step
    from ba_tpu.parallel import make_sweep_state

    B, cap, m, R = 512, 128, 3, 35
    state = make_sweep_state(jr.key(40), B, cap, max_traitor_frac=0.0)
    ok = jnp.ones((B, 2), bool)
    want = np.asarray(_xla_sweep_step(jr.key(41), state, ok, m))
    multi = np.asarray(fused_signed_sweep_step(
        jnp.asarray([42], jnp.int32), state.order, state.leader,
        state.faulty, state.alive, ok, m, R,
    ))
    assert multi.shape == (B, R)
    for r in range(R):
        np.testing.assert_array_equal(multi[:, r], want)
