"""Platform knobs: the persistent-compilation-cache decision.

ROADMAP cache-hygiene decision (ISSUE 2 satellite): the suite SHARES the
persistent cache, enabled explicitly by tests/conftest.py (measured on
the CI host: test_crypto.py alone is 8m19s cold vs ~10m for the entire
warm suite against tier-1's 870 s budget — cold-by-default cannot fit),
with ``BA_TPU_COMPILE_CACHE=0`` as the documented cold opt-out for
compile-regression hunts.  The knob's three behaviors (disable, path
override, caller-path default) are covered here so the machinery
interactive sessions, bench, and conftest rely on cannot rot.
"""

import contextlib

import pytest

from ba_tpu.utils.platform import enable_compilation_cache


@contextlib.contextmanager
def _restore_cache_dir():
    """Restore jax_compilation_cache_dir after the test: later tests in
    the process must keep whatever cache state conftest established
    (the suite's shared warm cache, or cold when the invoker opted out)."""
    import jax

    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        with contextlib.suppress(Exception):
            jax.config.update("jax_compilation_cache_dir", prev)


def test_cache_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("BA_TPU_COMPILE_CACHE", "0")
    assert enable_compilation_cache() is None
    # The decision is observable: the obs gauge reports disabled.
    from ba_tpu import obs

    assert obs.default_registry().gauge("compile_cache_enabled").value == 0


def test_cache_opt_in_env_path(monkeypatch, tmp_path):
    target = tmp_path / "xla-cache"
    monkeypatch.setenv("BA_TPU_COMPILE_CACHE", str(target))
    with _restore_cache_dir():
        got = enable_compilation_cache()
        if got is None:
            pytest.skip("this jax build has no persistent compilation cache")
        assert got == str(target)
        assert target.is_dir()  # created on enable
        import jax

        assert getattr(jax.config, "jax_compilation_cache_dir", got) == str(
            target
        )
        from ba_tpu import obs

        assert (
            obs.default_registry().gauge("compile_cache_enabled").value == 1
        )


def test_cache_opt_in_uses_caller_path(monkeypatch, tmp_path):
    # env "1" = enabled at the caller-supplied (or default) location.
    monkeypatch.setenv("BA_TPU_COMPILE_CACHE", "1")
    want = str(tmp_path / "caller-cache")
    with _restore_cache_dir():
        got = enable_compilation_cache(want)
        if got is None:
            pytest.skip("this jax build has no persistent compilation cache")
        assert got == want


def test_conftest_cache_decision_applied():
    # The suite-level decision this file's docstring promises: conftest
    # explicitly enabled the shared persistent cache (so the whole suite
    # runs warm deterministically) — unless the invoking environment
    # opted out with BA_TPU_COMPILE_CACHE=0, in which case every compile
    # must be real.
    import os

    import jax

    if not hasattr(jax.config, "jax_compilation_cache_dir"):
        pytest.skip("this jax build has no persistent compilation cache")
    configured = jax.config.jax_compilation_cache_dir
    if os.environ.get("BA_TPU_COMPILE_CACHE") == "0":
        assert configured is None
    else:
        assert configured  # conftest enabled it before any test ran
