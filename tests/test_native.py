"""Native C++ Ed25519/SHA-512 (ba_tpu.native) vs the Python oracle.

The reference has no native code (SURVEY.md section 2); this is the
framework's CPU native path — the host-side batch signer for signed SM(m)
(ba_tpu/crypto/signed.py) and a third independent verifier.  Ed25519 is
deterministic, so byte equality with the RFC-8032-pinned oracle is the
whole contract; rejection paths are exercised next to accept paths.

Skipped wholesale when no compiler is available (``native.available()``).
"""

import hashlib

import numpy as np
import pytest

from ba_tpu import native
from ba_tpu.crypto import oracle

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native build unavailable (no g++?)"
)


def test_sha512_matches_hashlib_boundaries():
    rng = np.random.default_rng(0)
    for n in (0, 1, 111, 112, 127, 128, 129, 300):
        m = rng.bytes(n)
        assert native.sha512(m) == hashlib.sha512(m).digest()


def test_rfc8032_vector():
    sk = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    pk = bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert native.publickey(sk) == pk
    assert native.sign(sk, pk, b"") == sig
    assert native.verify(pk, b"", sig)


def test_sign_verify_matches_oracle():
    rng = np.random.default_rng(1)
    for i in range(8):
        sk, pk = oracle.keypair(bytes([i]))
        msg = rng.bytes(int(rng.integers(0, 120)))
        sig = native.sign(sk, pk, msg)
        assert sig == oracle.sign(sk, pk, msg)
        assert native.verify(pk, msg, sig)
        assert oracle.verify(pk, msg, sig)
        assert not native.verify(pk, msg + b"x", sig)
        bad = bytearray(sig)
        bad[5] ^= 1
        assert not native.verify(pk, msg, bytes(bad))


def test_batch_apis_match_scalar():
    B = 64
    rng = np.random.default_rng(2)
    sks = np.stack(
        [
            np.frombuffer(oracle.secret_from_seed(f"n:{i}".encode()), np.uint8)
            for i in range(B)
        ]
    )
    pks = native.publickey_batch(sks)
    msgs = rng.integers(0, 256, (B, 16), dtype=np.uint8)
    sigs = native.sign_batch(sks, pks, msgs)
    for i in (0, 7, 63):
        assert pks[i].tobytes() == native.publickey(sks[i].tobytes())
        assert sigs[i].tobytes() == native.sign(
            sks[i].tobytes(), pks[i].tobytes(), msgs[i].tobytes()
        )
    oks = native.verify_batch(pks, msgs, sigs)
    assert oks.all()
    bad = sigs.copy()
    bad[:, 40] ^= 1
    assert not native.verify_batch(pks, msgs, bad).any()


def test_batch_apis_multi_chunk():
    # B=300 spans one full 256-point shared-inversion chunk plus a 44-point
    # tail (ge_tobytes_batch's TOBYTES_CHUNK boundary — the index arithmetic
    # most worth pinning); spot-check scalar equality on both sides of the
    # boundary and at the tail end.
    B = 300
    sks = np.stack(
        [
            np.frombuffer(oracle.secret_from_seed(f"c:{i}".encode()), np.uint8)
            for i in range(B)
        ]
    )
    pks = native.publickey_batch(sks)
    msgs = np.tile(np.arange(16, dtype=np.uint8), (B, 1))
    msgs[:, 0] = np.arange(B) % 256
    sigs = native.sign_batch(sks, pks, msgs)
    for i in (0, 255, 256, 299):
        assert pks[i].tobytes() == native.publickey(sks[i].tobytes())
        assert sigs[i].tobytes() == native.sign(
            sks[i].tobytes(), pks[i].tobytes(), msgs[i].tobytes()
        )
    assert native.verify_batch(pks, msgs, sigs).all()


def test_rejection_edges():
    sk, pk = oracle.keypair(b"edge")
    msg = b"m" * 16
    sig = native.sign(sk, pk, msg)
    # s >= L is non-canonical (RFC 8032 5.1.7 / oracle parity).
    forged = bytearray(sig)
    forged[32:] = oracle.L.to_bytes(32, "little")
    assert not native.verify(pk, msg, bytes(forged))
    assert not oracle.verify(pk, msg, bytes(forged))
    # Non-canonical x=0 encoding with sign bit set (forgery vector).
    bad_pk = bytes([1] + [0] * 30 + [0x80])
    assert not native.verify(bad_pk, msg, sig)
    # y >= p encodings are invalid.
    big_y = bytearray([0xFF] * 32)
    big_y[31] = 0x7F
    assert not native.verify(bytes(big_y), msg, sig)
    assert not oracle.verify(bytes(big_y), msg, sig)


def test_scalar_reduce_via_sign_diversity():
    # sc_reduce64 / sc_muladd are driven by sign's nonce and hram scalars;
    # byte equality with the oracle across many 64-byte messages sweeps
    # random 512-bit reduction inputs through both (the jnp twin of the
    # same fold plan has direct bigint edge tests in test_crypto.py).
    rng = np.random.default_rng(3)
    sk, pk = oracle.keypair(b"edge2")
    for _ in range(6):
        msg = rng.bytes(64)
        assert native.sign(sk, pk, msg) == oracle.sign(sk, pk, msg)


def test_verify_received_native_matches_jnp(monkeypatch):
    # The CPU fast path and the jnp kernel path must produce the same
    # [B, n] mask, incl. rejected corruptions.
    from ba_tpu.crypto.signed import (
        commander_keys,
        sign_received,
        verify_received,
    )

    rng = np.random.default_rng(4)
    B, n = 4, 6
    sks, pks = commander_keys(B, seed=9)
    received = rng.integers(0, 2, (B, n))
    corrupt = rng.random((B, n)) < 0.3
    msgs, sigs = sign_received(sks, pks, received, corrupt)
    monkeypatch.setenv("BA_TPU_VERIFY_NATIVE", "1")
    got_native = np.asarray(verify_received(pks, msgs, sigs))
    monkeypatch.setenv("BA_TPU_VERIFY_NATIVE", "0")
    got_jnp = np.asarray(verify_received(pks, msgs, sigs))
    np.testing.assert_array_equal(got_native, got_jnp)
    np.testing.assert_array_equal(got_native, ~corrupt)


def test_sign_value_tables_match_order_message():
    # The vectorized message-table encoder must stay byte-identical to the
    # per-call order_message() contract (magic || u32 instance || value).
    from ba_tpu.crypto.signed import (
        commander_keys,
        order_message,
        sign_value_tables,
    )

    sks, pks = commander_keys(7, seed=1)
    msgs, _ = sign_value_tables(sks, pks)
    for b in (0, 3, 6):
        for v in (0, 1):
            assert msgs[b, v].tobytes() == order_message(b, v)


def test_signed_host_paths_agree():
    # commander_keys / sign_value_tables must produce identical bytes
    # whichever host signer (native / cryptography / oracle) is active.
    from ba_tpu.crypto.signed import commander_keys, sign_value_tables

    sks, pks = commander_keys(6, seed=3)
    for b in (0, 5):
        assert pks[b].tobytes() == oracle.publickey(sks[b])
    msgs, sigs = sign_value_tables(sks, pks)
    for b in (0, 5):
        for v in (0, 1):
            assert sigs[b, v].tobytes() == oracle.sign(
                sks[b], pks[b].tobytes(), msgs[b, v].tobytes()
            )


def test_overlapped_setup_matches_sequential_tables():
    # The chunked, sign/verify-overlapped setup must produce BYTE-identical
    # tables to one sequential sign_value_tables call: in particular every
    # chunk's messages must bind the GLOBAL instance id (a chunk signed
    # with local ids would re-bind instances 0..chunk-1 — the replay
    # protection the message format exists for).
    from ba_tpu.crypto.signed import (
        commander_keys,
        order_message,
        setup_signed_tables_overlapped,
        sign_value_tables,
    )

    B = 37  # uneven: exercises the padded tail chunk too
    sks, pks = commander_keys(B)
    want_msgs, want_sigs = sign_value_tables(sks, pks)
    _, pks2, got_msgs, got_sigs, ok, _ = setup_signed_tables_overlapped(
        B, chunks=4
    )
    np.testing.assert_array_equal(pks2, pks)
    np.testing.assert_array_equal(got_msgs, want_msgs)
    np.testing.assert_array_equal(got_sigs, want_sigs)
    assert np.asarray(ok).all()
    assert got_msgs[B - 1, 1].tobytes() == order_message(B - 1, 1)


def test_setup_device_sign_matches_host(monkeypatch):
    # BA_TPU_SIGN_DEVICE=1 routes table signing through the on-device
    # Ed25519 signer (ed25519.sign); Ed25519 determinism means the
    # resulting tables must be BYTE-identical to the host path, verified
    # mask included — incl. the padded tail chunk (jnp concat branch) and
    # the global instance-id binding.
    from ba_tpu.crypto.signed import (
        commander_keys,
        setup_signed_tables_overlapped,
        sign_value_tables,
    )

    B = 21  # uneven: exercises the device-array tail-pad branch
    sks, pks = commander_keys(B)
    want_msgs, want_sigs = sign_value_tables(sks, pks)
    monkeypatch.setenv("BA_TPU_SIGN_DEVICE", "1")
    _, pks2, got_msgs, got_sigs, ok, timings = setup_signed_tables_overlapped(
        B, chunks=2
    )
    np.testing.assert_array_equal(pks2, pks)
    np.testing.assert_array_equal(got_msgs, want_msgs)
    np.testing.assert_array_equal(got_sigs, want_sigs)
    assert isinstance(got_sigs, np.ndarray)  # fetched to host at drain
    assert np.asarray(ok).all()
    assert timings["device_sign"] is True
