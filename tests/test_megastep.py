"""Pallas scenario megastep + engine selection (ISSUE 13 tentpole,
ba_tpu/ops/scenario_step.py + the engine seam in parallel/pipeline.py).

The load-bearing contracts, each pinned independently:

1. **In-kernel threefry** — the kernel's int32 threefry2x32 reproduces
   jax.random's ``fold_in``/``split``/``bits`` word-for-word (the
   derivation chain the bit-exactness contract stands on).
2. **Parity, bit-exact** — a fuzz sweep of random strategy mixes (all
   five strategies) with kills/revives/fault-flips mid-campaign pins
   decisions, leaders, histograms, every counter row, the final
   strategy plane and the schedule cursor BIT-IDENTICAL across engines
   (xla vs the kernel in interpret mode), for the campaign, plain, and
   coalesced (per-slot key) paths — including RANDOM coins under the
   same keys.
3. **Branch-free strategy table** — the lie-table rewrite is
   bit-identical to the legacy select chains, at the function level and
   through a whole campaign re-traced under ``chain_impl()``.
4. **Engine selection** — explicit unsupported combinations error
   eagerly (mesh, m >= 2, signed via the backend); ``auto`` falls back
   silently-but-counted; the resolved engine rides compile-signature
   axes (a flip is an explained recompile) and the pipeline_engine
   gauge; serving cohorts never coalesce across engines and the warmup
   lattice covers both engines when a kernel engine is configured.
5. **Engine invariants survive** — the depth-k no-blocking
   dispatch-count proof re-runs with ``engine="interpret"`` under full
   supervision, and a campaign checkpointed under one engine resumes
   bit-exactly under the other.
"""

import numpy as np
import jax
import jax.numpy as jnp
import jax.random as jr
import pytest

from ba_tpu import obs
from ba_tpu.parallel import (
    ENGINES,
    SCENARIO_COUNTER_NAMES,
    engine_support,
    fresh_copy as _fresh,
    make_mesh,
    make_sweep_state,
    pipeline_sweep,
    resolve_engine,
    scenario_sweep,
)
from ba_tpu.parallel.pipeline import (
    ENGINE_IDS,
    _ENGINE_REQUESTS,
    coalesced_aot_spec,
    coalesced_sweep,
    scenario_aot_spec,
)
from ba_tpu.ops import scenario_step as ss
from ba_tpu.scenario import strategies as strat_mod
from ba_tpu.scenario.compile import ScenarioBlock, block_from_kills


def _u32(x):
    return np.asarray(x).astype(np.uint32)


def _tf_np(kernel_out):
    return _u32(np.asarray(kernel_out))


# -- 1. in-kernel threefry ----------------------------------------------------


def test_kernel_threefry_matches_jax_fold_in_split_bits():
    key = jr.key(1234)
    kd = np.asarray(jr.key_data(key)).view(np.int32)
    k0 = jnp.asarray(kd[0])
    k1 = jnp.asarray(kd[1])
    for d in (0, 1, 7, 512, 2**31 - 1):
        want = _u32(jr.key_data(jr.fold_in(key, d)))
        g0, g1 = ss._fold_in(k0, k1, jnp.int32(np.int64(d) & 0x7FFFFFFF))
        got = np.array([_tf_np(g0), _tf_np(g1)])
        if d < 2**31:  # int32-representable data words
            np.testing.assert_array_equal(got, want)
    ka, kb = jr.split(key)
    (a0, b0), (a1, b1) = ss._split2(k0, k1)
    np.testing.assert_array_equal(
        np.array([_tf_np(a0), _tf_np(b0)]), _u32(jr.key_data(ka))
    )
    np.testing.assert_array_equal(
        np.array([_tf_np(a1), _tf_np(b1)]), _u32(jr.key_data(kb))
    )
    # Counter-mode WORDS through the static maps, odd and even word
    # counts: a draw of 32*s coins uses exactly s words, and coins
    # 0..s-1 unpack bit 0 of words 0..s-1 — so the map slice [:, :s]
    # is the word schedule itself.
    for s in (1, 2, 3, 5, 31, 32, 33, 81):
        # Deliberate same-key redraws: each size's words must come from
        # the SAME stream the kernel maps reproduce.
        want = np.asarray(jr.bits(key, (s,), jnp.uint32))  # ba-lint: disable=BA202
        maps = jnp.asarray(ss._word_maps(32 * s, (32 * s,))[:, :s])
        y0, y1 = ss.tf2x32(k0, k1, maps[0], maps[1])
        words = _u32(jnp.where(maps[2] == 1, y0, y1))
        np.testing.assert_array_equal(words, want)


# -- 2. parity fuzz across engines --------------------------------------------


def _random_campaign(rng, B, n, R):
    """A strategy-mixed campaign: all five strategies present, kills,
    revives and fault flips mid-campaign."""
    strat0 = rng.integers(0, 5, (B, n)).astype(np.int8)
    events = {
        "kill": jnp.asarray(rng.random((R, B, n)) < 0.08),
        "revive": jnp.asarray(rng.random((R, B, n)) < 0.05),
        "set_faulty": jnp.asarray(
            np.where(rng.random((R, B, n)) < 0.1,
                     rng.integers(0, 2, (R, B, n)), -1).astype(np.int8)
        ),
        "set_strategy": jnp.asarray(
            np.where(rng.random((R, B, n)) < 0.15,
                     rng.integers(0, 5, (R, B, n)), -1).astype(np.int8)
        ),
    }
    block = ScenarioBlock(**events)
    return jnp.asarray(strat0), block


def _assert_campaign_identical(a, b):
    np.testing.assert_array_equal(a["decisions"], b["decisions"])
    np.testing.assert_array_equal(a["leaders"], b["leaders"])
    np.testing.assert_array_equal(a["histograms"], b["histograms"])
    np.testing.assert_array_equal(
        a["counters_per_round"], b["counters_per_round"]
    )
    assert a["counters"] == b["counters"]
    assert set(a["counters"]) == set(SCENARIO_COUNTER_NAMES)
    np.testing.assert_array_equal(
        np.asarray(a["final_strategy"]), np.asarray(b["final_strategy"])
    )
    for f in ("order", "leader", "faulty", "alive", "ids"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a["final_state"], f)),
            np.asarray(getattr(b["final_state"], f)),
        )
    assert int(a["final_schedule"].counter) == int(b["final_schedule"].counter)
    np.testing.assert_array_equal(
        _u32(a["final_schedule"].key_data), _u32(b["final_schedule"].key_data)
    )


@pytest.mark.parametrize("seed,B,n,R,kpd", [
    (0, 4, 5, 6, 2),
    (1, 8, 9, 7, 3),
    (2, 3, 33, 5, 5),   # multi-word round-1 coins
    (3, 9, 16, 9, 4),   # padding on both axes
])
def test_scenario_parity_fuzz_xla_vs_interpret(seed, B, n, R, kpd):
    rng = np.random.default_rng(seed)
    state = make_sweep_state(jr.key(100 + seed), B, n)
    strat0, block = _random_campaign(rng, B, n, R)
    key = jr.key(200 + seed)
    a = scenario_sweep(
        key, _fresh(state), block, initial_strategy=strat0,
        rounds_per_dispatch=kpd, collect_decisions=True, engine="xla",
    )
    b = scenario_sweep(
        key, _fresh(state), block, initial_strategy=strat0,
        rounds_per_dispatch=kpd, collect_decisions=True,
        engine="interpret",
    )
    assert a["stats"]["engine"] == "xla"
    assert b["stats"]["engine"] == "interpret"
    _assert_campaign_identical(a, b)


def test_plain_pipeline_parity_xla_vs_interpret():
    state = make_sweep_state(jr.key(7), 10, 12)
    kw = dict(
        with_counters=True, collect_decisions=True, rounds_per_dispatch=3
    )
    a = pipeline_sweep(jr.key(8), _fresh(state), 8, engine="xla", **kw)
    b = pipeline_sweep(jr.key(8), _fresh(state), 8, engine="interpret", **kw)
    np.testing.assert_array_equal(a["decisions"], b["decisions"])
    np.testing.assert_array_equal(a["histograms"], b["histograms"])
    np.testing.assert_array_equal(
        a["counters_per_round"], b["counters_per_round"]
    )
    assert a["counters"] == b["counters"]


def test_coalesced_parity_xla_vs_interpret_plain_and_scenario():
    rng = np.random.default_rng(5)
    B, n, R = 4, 6, 6
    keys = [jr.key(40 + i) for i in range(B)]
    state = make_sweep_state(jr.key(41), B, n)
    a = coalesced_sweep(keys, _fresh(state), R, rounds_per_dispatch=2,
                        engine="xla")
    b = coalesced_sweep(keys, _fresh(state), R, rounds_per_dispatch=2,
                        engine="interpret")
    for f in ("decisions", "counters", "majorities"):
        np.testing.assert_array_equal(a[f], b[f])
    strat0, block = _random_campaign(rng, B, n, R)
    sa = coalesced_sweep(keys, _fresh(state), R, rounds_per_dispatch=3,
                         scenario=block, initial_strategy=strat0,
                         engine="xla")
    sb = coalesced_sweep(keys, _fresh(state), R, rounds_per_dispatch=3,
                         scenario=block, initial_strategy=strat0,
                         engine="interpret")
    for f in ("decisions", "counters", "majorities", "leaders"):
        np.testing.assert_array_equal(sa[f], sb[f])


# -- 3. branch-free strategy table --------------------------------------------


def test_lie_table_bit_identical_to_select_chain():
    rng = np.random.default_rng(11)
    strat = jnp.asarray(rng.integers(-3, 8, (4, 1, 7)), jnp.int8)
    coins = jnp.asarray(rng.integers(0, 2, (4, 7, 7)), jnp.int8)
    ridx = jnp.arange(7)[None, :, None]
    new = strat_mod.lie_values(strat, coins, ridx)
    old = strat_mod.lie_values_chain(strat, coins, ridx)
    assert new.dtype == old.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(new), np.asarray(old))
    gc = jnp.asarray(rng.integers(0, 2, (4, 7, 7, 2)), bool)
    vidx = jnp.arange(2)[None, None, None, :]
    sg = strat_mod.send_gate(strat[..., None], gc, ridx[..., None], vidx)
    sgc = strat_mod.send_gate_chain(strat[..., None], gc, ridx[..., None], vidx)
    assert sg.dtype == sgc.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(sg), np.asarray(sgc))


def test_chain_impl_retrace_matches_branch_free_campaign():
    # The megastep_ab bench's mechanism: re-tracing a fresh jit closure
    # under chain_impl() runs the legacy formulation — results must be
    # bit-identical (the A/B measures speed, never semantics).
    from ba_tpu.parallel.sweep import agreement_step

    B, n = 6, 8
    state = make_sweep_state(jr.key(60), B, n)
    strat = jnp.asarray(
        np.random.default_rng(6).integers(0, 5, (B, n)), jnp.int8
    )
    keys = jr.split(jr.key(61), B)
    new = jax.jit(
        lambda k, st, s: agreement_step(k, st, strategies=s)
    )(keys, state, strat)
    with strat_mod.chain_impl():
        old = jax.jit(
            lambda k, st, s: agreement_step(k, st, strategies=s)
        )(keys, state, strat)
    for f in ("majorities", "decision", "histogram"):
        np.testing.assert_array_equal(np.asarray(new[f]), np.asarray(old[f]))


# -- 4. engine selection ------------------------------------------------------


def test_resolve_engine_table():
    assert resolve_engine("xla") == ("xla", None)
    assert resolve_engine(None) == ("xla", None)  # env default
    assert resolve_engine("interpret") == ("interpret", None)
    # pallas off-TPU resolves to the interpreter (house pattern); the
    # recorded engine always names what ran.
    resolved, fb = resolve_engine("pallas")
    assert resolved == ("pallas" if jax.devices()[0].platform == "tpu"
                        else "interpret")
    assert fb is None
    with pytest.raises(ValueError, match="bogus"):
        resolve_engine("bogus")
    with pytest.raises(ValueError, match="m=2"):
        resolve_engine("pallas", m=2)
    with pytest.raises(ValueError, match="data=4"):
        resolve_engine("interpret", n_shards=4)
    with pytest.raises(ValueError, match="signed"):
        resolve_engine("pallas", signed=True)
    assert resolve_engine("auto", m=3)[0] == "xla"
    assert "m=3" in resolve_engine("auto", m=3)[1]
    assert engine_support() is None
    assert "signed" in engine_support(signed=True)
    assert "mesh" in engine_support(meshed=True)
    # An ENV-sourced kernel preference on an unsupported combination is
    # a counted fallback, never a hard failure (only a CALL-SITE
    # engine= demand raises) — exporting BA_TPU_ENGINE must not break
    # the paths the kernel never covered.
    import os

    os.environ["BA_TPU_ENGINE"] = "interpret"
    try:
        resolved, why = resolve_engine(None, m=2)
        assert resolved == "xla" and "m=2" in why
        assert resolve_engine(None) == ("interpret", None)
    finally:
        del os.environ["BA_TPU_ENGINE"]


def test_engine_eager_errors_and_counted_fallback():
    state = make_sweep_state(jr.key(70), 8, 8)
    with pytest.raises(ValueError, match="m=2"):
        pipeline_sweep(jr.key(0), _fresh(state), 4, m=2, engine="pallas")
    # ANY mesh excludes the kernel — even data=1 routes every dispatch
    # through the shard_map-wrapped XLA core, and a kernel request that
    # silently ran XLA would record an engine that never executed.
    mesh = make_mesh((1, 1), ("data", "node"))
    with pytest.raises(ValueError, match="mesh"):
        scenario_sweep(
            jr.key(0), _fresh(state),
            block_from_kills(np.zeros((2, 8, 8), bool)),
            mesh=mesh, engine="interpret",
        )
    with pytest.raises(ValueError, match="m=2"):
        scenario_sweep(
            jr.key(0), _fresh(state),
            block_from_kills(np.zeros((2, 8, 8), bool)),
            m=2, engine="interpret",
        )
    # auto + mesh: counted fallback, XLA actually runs and is recorded.
    mout = scenario_sweep(
        jr.key(1), _fresh(state),
        block_from_kills(np.zeros((2, 8, 8), bool)),
        mesh=mesh, engine="auto",
    )
    assert mout["stats"]["engine"] == "xla"
    assert "mesh" in mout["stats"]["engine_fallback"]
    del mesh
    reg = obs.default_registry()
    out = pipeline_sweep(jr.key(1), _fresh(state), 2, m=2, engine="auto")
    assert out["stats"]["engine"] == "xla"
    assert "m=2" in out["stats"]["engine_fallback"]
    assert reg.get("pipeline_engine").value == ENGINE_IDS["xla"]
    assert reg.get("pipeline_engine_fallback_total").value >= 1
    out2 = pipeline_sweep(jr.key(1), _fresh(state), 2, engine="interpret")
    assert out2["stats"]["engine_fallback"] is None
    assert reg.get("pipeline_engine").value == ENGINE_IDS["interpret"]


def test_backend_run_rounds_signed_engine_errors_eagerly():
    from ba_tpu.runtime.backends import JaxBackend

    class _G:
        def __init__(self, i):
            self.id = i
            self.faulty = False
            self.alive = True

    gens = [_G(i + 1) for i in range(4)]
    # UNSIGNED sm still has no pipelined path: silent None by default,
    # loud error on an explicit kernel-engine request.
    be_plain = JaxBackend(protocol="sm", m=1, signed=False)
    assert be_plain.run_rounds(gens, 0, 1, 0, 2) is None
    with pytest.raises(ValueError, match="pipelined"):
        be_plain.run_rounds(gens, 0, 1, 0, 2, engine="pallas")
    # SIGNED sm rides the sign-ahead lane (ISSUE 14) — but an explicit
    # kernel-engine request must still error eagerly: the kernel never
    # covered the SM relay.
    be = JaxBackend(protocol="sm", m=1, signed=True)
    with pytest.raises(ValueError, match="signed"):
        be.run_rounds(gens, 0, 1, 0, 2, engine="pallas")
    out = be.run_rounds(gens, 0, 1, 0, 2)
    assert out is not None
    majorities, decisions, stats = out
    assert stats["signed"] is True and len(decisions) == 2


def test_engine_axis_is_an_explained_recompile():
    obs.reset_first_calls()
    axes = {"batch": 4, "capacity": 8, "rounds": 2, "engine": "xla"}
    first, changed, cross = obs.classify_compile("megastep_test_fn", axes)
    assert first and changed is None
    first, changed, cross = obs.classify_compile(
        "megastep_test_fn", {**axes, "engine": "interpret"}
    )
    assert first
    assert changed == {"engine": ["xla", "interpret"]}


def test_aot_specs_build_kernel_engines():
    from ba_tpu.ops.scenario_step import (
        pallas_coalesced_megastep, pallas_scenario_megastep,
    )

    axes = {"batch": 2, "capacity": 4, "rounds": 3, "m": 1,
            "max_liars": None, "unroll": 1, "scenario": True,
            "engine": "interpret"}
    fn, args, kwargs = coalesced_aot_spec(axes)
    assert fn is pallas_coalesced_megastep
    assert kwargs["interpret"] is True
    sx = {**axes, "engine": "xla", "collect_decisions": True, "data": 1}
    fn2, _, kwargs2 = scenario_aot_spec(sx)
    assert fn2 is not pallas_scenario_megastep  # xla rows keep the scan core
    assert "interpret" not in kwargs2
    si = {**sx, "engine": "interpret"}
    fn3, _, kwargs3 = scenario_aot_spec(si)
    assert fn3 is pallas_scenario_megastep and kwargs3["interpret"] is True
    with pytest.raises(ValueError, match="unknown engine"):
        coalesced_aot_spec({**axes, "engine": "mosaic2"})


def test_serve_engine_tokens_and_cohort_separation():
    from ba_tpu.runtime.serve import (
        ENGINE_TOKENS, AgreementRequest, ServeConfig, cohort_key,
        validate_request,
    )

    # serve.py's jax-free spelling must track the engine seam's.
    assert ENGINE_TOKENS == _ENGINE_REQUESTS
    assert set(ENGINES) <= set(ENGINE_TOKENS)
    r1 = AgreementRequest(kind="run-rounds", n=4, rounds=4, seed=1)
    r2 = AgreementRequest(
        kind="run-rounds", n=4, rounds=4, seed=1, engine="interpret"
    )
    assert cohort_key(r1) != cohort_key(r2)
    assert cohort_key(r1, "interpret") == cohort_key(r2)
    with pytest.raises(ValueError, match="engine"):
        validate_request(AgreementRequest(engine="mosaic2"))
    with pytest.raises(ValueError, match="engine"):
        ServeConfig(engine="mosaic2")
    assert ServeConfig(engine="interpret").engine == "interpret"


def test_warmup_plan_covers_both_engines():
    from ba_tpu.runtime.serve import ServeConfig
    from ba_tpu.runtime.warmup import bucket_lattice, plan_engines

    assert plan_engines(ServeConfig()) == ("xla",)
    got = plan_engines(ServeConfig(engine="interpret"))
    assert got == ("xla", "interpret")
    # pallas resolves per-platform; both engines always present.
    got = plan_engines(ServeConfig(engine="pallas"))
    assert got[0] == "xla" and len(got) == 2 and got[1] in ENGINES
    plan = bucket_lattice(2, 4, engines=got)
    assert {a["engine"] for _, a in plan} == set(got)


# -- 5. engine invariants -----------------------------------------------------


def test_interpret_engine_no_blocking_dispatch_count_supervised(
    monkeypatch, tmp_path
):
    # ISSUE 13 acceptance: the depth-k dispatch schedule is untouched by
    # the kernel engine — re-run the no-blocking proof with
    # engine="interpret" under FULL supervision.
    from ba_tpu.runtime.supervisor import SupervisorConfig, supervised_sweep

    def _forbidden(*a, **k):
        raise AssertionError("block_until_ready called inside the engine")

    monkeypatch.setattr(jax, "block_until_ready", _forbidden)
    R, depth = 7, 3
    state = make_sweep_state(jr.key(90), 8, 8)
    events = []
    out = supervised_sweep(
        jr.key(91), state, R,
        config=SupervisorConfig(timeout_s=60.0),
        depth=depth, rounds_per_dispatch=1, with_counters=True,
        checkpoint_every=3,
        checkpoint_path=str(tmp_path / "nb_{round}.npz"),
        on_event=lambda kind, i: events.append((kind, i)),
        engine="interpret",
    )
    assert out["stats"]["engine"] == "interpret"
    dispatches = [i for kind, i in events if kind == "dispatch"]
    retires = [i for kind, i in events if kind == "retire"]
    assert dispatches == list(range(R))
    assert retires == list(range(R))
    first_retire = events.index(("retire", 0))
    assert events[:first_retire] == [
        ("dispatch", i) for i in range(depth + 1)
    ]
    assert out["stats"]["max_in_flight"] == depth + 1
    assert out["supervisor"]["attempts"] == 1


def test_checkpoint_crosses_engines_bit_exact(tmp_path):
    # A campaign checkpointed under the XLA core resumes under the
    # kernel engine (and vice versa) bit-exactly: the carry format and
    # the key schedule are engine-free, and the coins are bit-equal.
    rng = np.random.default_rng(21)
    B, n, R = 6, 7, 8
    state = make_sweep_state(jr.key(95), B, n)
    strat0, block = _random_campaign(rng, B, n, R)
    key = jr.key(96)
    kw = dict(initial_strategy=strat0, rounds_per_dispatch=2,
              collect_decisions=True)
    want = scenario_sweep(key, _fresh(state), block, engine="xla", **kw)
    ck = str(tmp_path / "cross_{round}.npz")
    scenario_sweep(
        key, _fresh(state), block, engine="xla",
        checkpoint_every=4, checkpoint_path=ck, **kw,
    )
    resumed = scenario_sweep(
        None, None, block, resume=ck.replace("{round}", "4"),
        engine="interpret", rounds_per_dispatch=2,
        collect_decisions=True,
    )
    np.testing.assert_array_equal(
        want["decisions"][4:], resumed["decisions"]
    )
    np.testing.assert_array_equal(want["leaders"][4:], resumed["leaders"])
    assert want["counters"] == resumed["counters"]
    np.testing.assert_array_equal(
        np.asarray(want["final_strategy"]),
        np.asarray(resumed["final_strategy"]),
    )
